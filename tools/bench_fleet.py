"""Fleet training bench: vmapped multi-forest vs sequential solo runs.

Run: python tools/bench_fleet.py [n_rows] [rounds] [sizes]

  sizes   comma list of fleet widths, default ``1,4,8,16``

For each N the sweep times ONE warm ``fleet_train`` run of N members
(a feature_fraction-seed roster — every member is a distinct forest
but all share one super-epoch program shape) against N warm sequential
solo ``lgb.train`` runs of the same member configs, and reports the
AGGREGATE iters/s of each side (``N * rounds / seconds``).  A warmup
run of the same shape precedes every timed run so compile cost is
excluded: the fleet's claim is steady-state sweep throughput — the
vmapped program amortizes the per-epoch host round-trip (one ``_eget``
for all N members) and batches N small member programs into one, which
is where small-data hyperparameter sweeps spend their time.  Solo runs
share one compiled program across members (per-member seeds are scan
operands, not trace constants), so the baseline is also warm after one
member — the comparison is dispatch-for-dispatch fair.

``run_bench()`` is importable: bench.py folds the returned dict into
its extras as ``fleet_<key>`` (tools/perf_budget.txt pins the headline
``fleet_agg_iters_per_s`` — the N=8 vmapped aggregate — and the
``fleet_speedup_x8`` ratio against 8 sequential solos).
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")


def _make_data(n, f=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float32)
    logit = (1.1 * x[:, 0] - 0.7 * x[:, 1] + 0.5 * x[:, 2] * x[:, 3]
             + 0.4 * rng.randn(n))
    y = (logit > 0).astype(np.float32)
    return x, y


def _base_params(num_leaves=15):
    return {"objective": "binary", "num_leaves": num_leaves,
            "learning_rate": 0.1, "min_data_in_leaf": 5,
            "verbosity": -1, "deterministic": True,
            "tpu_learner": "masked", "superepoch": 8,
            "fused_eval": True, "fused_chunk": 8,
            "metric": ["binary_logloss"], "padded_leaves": True,
            "split_batch": 1, "feature_fraction": 0.9}


def _members(n):
    # distinct forests, one shared program shape: only the per-member
    # RNG stream differs, and seeds ride the scan as operands
    return [{"feature_fraction_seed": 100 + j} for j in range(n)]


def _mk_dataset(lgb, x, y, params):
    ds = lgb.Dataset(x, label=y, params=dict(params))
    ds.construct()
    return ds


def run_bench(n_rows=500, rounds=32, sizes=(1, 4, 8, 16), n_feat=10,
              num_leaves=15, log=None):
    """{key: value} over fleet widths; see module docstring."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.fleet import fleet_train

    x, y = _make_data(n_rows, n_feat)
    base = _base_params(num_leaves)
    out = {"n_rows": n_rows, "rounds": rounds}

    # solo baseline: ONE warmup train compiles the shared program, then
    # each width's baseline is the sum of N warm sequential runs
    def solo(mj):
        p = dict(base)
        p.update(mj)
        return lgb.train(p, _mk_dataset(lgb, x, y, base),
                         num_boost_round=rounds)

    solo(_members(1)[0])                                 # warm/compile
    solo_dt = {}
    for n in sorted(sizes):
        t0 = time.perf_counter()
        for mj in _members(n):
            bst = solo(mj)
        solo_dt[n] = time.perf_counter() - t0
        assert len(bst.trees) == rounds
        out[f"solo{n}_agg_iters_per_s"] = round(
            n * rounds / solo_dt[n], 3)

    for n in sorted(sizes):
        if n < 2:
            # fleet_train requires >= 2 members; N=1 IS the solo run
            out["n1_agg_iters_per_s"] = out.get("solo1_agg_iters_per_s")
            continue
        mem = _members(n)
        try:
            fleet_train(dict(base), _mk_dataset(lgb, x, y, base),
                        num_boost_round=rounds, members=mem)  # warm
            t0 = time.perf_counter()
            fr = fleet_train(dict(base), _mk_dataset(lgb, x, y, base),
                             num_boost_round=rounds, members=mem)
            dt = time.perf_counter() - t0
        except Exception as e:                          # noqa: BLE001
            out[f"n{n}_error"] = f"{type(e).__name__}: {e}"[:120]
            continue
        assert all(len(b.trees) == rounds for b in fr.boosters)
        agg = n * rounds / dt
        out[f"n{n}_agg_iters_per_s"] = round(agg, 3)
        out[f"n{n}_speedup"] = round(agg * solo_dt[n] / (n * rounds), 3)
        if log:
            log(f"N={n}: fleet {dt:.2f}s ({agg:.2f} agg iters/s), "
                f"solo {solo_dt[n]:.2f}s -> {out[f'n{n}_speedup']:.2f}x")

    # headline keys (tools/perf_budget.txt pins): the acceptance shape
    # is N=8 vmapped vs 8 sequential solos, both warm
    if "n8_agg_iters_per_s" in out:
        out["agg_iters_per_s"] = out["n8_agg_iters_per_s"]
        out["speedup_x8"] = out["n8_speedup"]
    return out


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 500
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 32
    sizes = tuple(int(s) for s in sys.argv[3].split(",")) \
        if len(sys.argv) > 3 else (1, 4, 8, 16)

    import jax
    print(f"devices={jax.devices()}", file=sys.stderr, flush=True)
    res = run_bench(n, rounds, sizes,
                    log=lambda m: print(m, file=sys.stderr, flush=True))
    import json
    print(json.dumps(res, sort_keys=True))


if __name__ == "__main__":
    main()
