"""Leaf-wise tree grower: a fully device-resident JAX program.

TPU-native re-design of the reference's device learner
(/root/reference/src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp:108-232
and serial_tree_learner.cpp:159-210): the whole tree build is ONE jitted
``lax.fori_loop`` with ``num_leaves-1`` trip count (static shapes — SURVEY.md
§7 "hard parts").  Design translations:

- ``DataPartition``'s permuted index array (data_partition.hpp:161) becomes a
  row->leaf index vector (``leaf_of_row``), exactly like the CUDA learner's
  ``data_index_to_leaf_index`` (cuda_data_partition.cu:111) — no reordering,
  per-leaf work masks by leaf id.
- Histogram **subtraction trick** (serial_tree_learner.cpp:423-425): only the
  smaller child's histogram is constructed (masked MXU pass); the sibling is
  parent - smaller.
- Split search: vectorized scans over ``[2, F, B]`` (ops/split.py).
- Distributed: a ``hist_reduce`` hook (identity | ``lax.psum`` over the mesh
  row axis) makes the same program the data-parallel learner
  (data_parallel_tree_learner.cpp:174-186's ReduceScatter collapses onto an
  XLA collective; split decisions are then replicated).
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .ops.histogram import compute_histogram
from . import sparse_data as _spd
from .ops.split import (SplitParams, SplitResult, find_best_split,
                        leaf_output, monotone_penalty_factor)
from .utils.compile_cache import trace_event


def grower_trace_count() -> int:
    """Number of times a grower program has been traced (== compiled,
    modulo persistent-cache hits) in this process — the ``grower``
    entry of ``utils/compile_cache.trace_counts()``, counted by the
    ``trace_event`` call inside the traced function bodies (a Python
    side effect: once per new jit cache entry, never per execution).
    tests/test_compile_cache.py and tools/check_retraces.py read this
    to prove the leaf-budget bucketing bounds XLA compiles (one L=64
    trace covers num_leaves 31/40/63)."""
    from .utils.compile_cache import trace_counts
    return trace_counts().get("grower", 0)


# process-level grower sharing: two Boosters whose grower CONFIG matches
# (after leaf-budget bucketing the common num_leaves sweep collapses
# onto one config) reuse the same jitted callable — and therefore the
# same trace.  Keyed on every closure input of make_grower; skipped
# whenever a distribution hook (an unkeyable callable) is present.
# Bounded LRU: evicting an entry only drops the SHARED handle — live
# Boosters keep their reference, exactly like the pre-memo behavior.
_SHARED_GROWERS: "OrderedDict[tuple, Callable]" = OrderedDict()
_SHARED_GROWERS_MAX = 64
_SHARED_GROWERS_LOCK = threading.Lock()


class _Unkeyable(Exception):
    pass


def _key_part(x):
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    if isinstance(x, (tuple, list)):
        return tuple(_key_part(v) for v in x)
    try:
        a = np.asarray(x)
    except Exception:
        raise _Unkeyable
    if a.dtype == object:
        # np.asarray(<arbitrary object>).tobytes() is the raw CPython
        # POINTER — address reuse after GC would alias two different
        # configs onto one cached grower.  Unkeyable -> private jit.
        raise _Unkeyable
    return (str(a.dtype), a.shape, a.tobytes())


def _grower_key(kw: dict):
    try:
        return tuple((k, _key_part(v)) for k, v in sorted(kw.items()))
    except _Unkeyable:
        return None


class TreeArrays(NamedTuple):
    """Array-encoded tree (include/LightGBM/tree.h:25 analog).

    Internal nodes are 0..num_leaves-2; a child pointer < 0 encodes leaf
    ``~child`` (tree.h leaf encoding).
    """
    num_leaves: jax.Array        # scalar int32, actual number of leaves
    split_feature: jax.Array     # [L-1] int32 (used-feature slot)
    threshold_bin: jax.Array     # [L-1] int32
    default_left: jax.Array      # [L-1] bool
    left_child: jax.Array        # [L-1] int32
    right_child: jax.Array       # [L-1] int32
    split_gain: jax.Array        # [L-1] f32
    leaf_value: jax.Array        # [L] f32
    leaf_weight: jax.Array       # [L] f32 (sum hessian)
    leaf_count: jax.Array        # [L] f32
    internal_value: jax.Array    # [L-1] f32
    internal_weight: jax.Array   # [L-1] f32
    internal_count: jax.Array    # [L-1] f32
    leaf_depth: jax.Array        # [L] int32
    leaf_of_row: jax.Array       # [N] int32 — final row -> leaf assignment
    is_cat_node: jax.Array       # [L-1] bool — categorical split flags
    cat_rank: jax.Array          # [L-1, B] int32 — per-node bin decision rank
    n_steps: jax.Array           # scalar int32 — grower loop steps taken
    #                              (== splits for strict leaf-wise; < splits
    #                              for split_batch>1 super-steps) — perf
    #                              observability, not part of the model


class _GrowState(NamedTuple):
    leaf_of_row: jax.Array
    hist: jax.Array              # [L, F, B, 3]
    # per-leaf allowed output range (monotone 'basic' method; ±inf w/o)
    olo: jax.Array               # [L] f32
    ohi: jax.Array               # [L] f32
    # per-leaf BRANCH feature sets (interaction constraints; [1,1] w/o) —
    # the allowed mask is derived per step by subset containment against
    # the constraint groups (col_sampler.hpp:91-111 GetByNode)
    fallow: jax.Array            # [L, F] bool (or [L, 1] placeholder)
    # features already split on (CEGB coupled penalties; [1] w/o)
    cuse: jax.Array              # [F] bool (or [1] placeholder)
    # per-leaf best-split candidates
    bg: jax.Array                # [L] gain
    bf: jax.Array                # [L] feature
    bt: jax.Array                # [L] threshold
    bdl: jax.Array               # [L] default_left
    bls: jax.Array               # [L, 3] left sums
    brs: jax.Array               # [L, 3] right sums
    blo: jax.Array               # [L] left output
    bro: jax.Array               # [L] right output
    bic: jax.Array               # [L] bool is-categorical
    brank: jax.Array             # [L, B] decision rank vector
    # tree arrays under construction
    split_feature: jax.Array
    threshold_bin: jax.Array
    default_left: jax.Array
    left_child: jax.Array
    right_child: jax.Array
    split_gain: jax.Array
    leaf_value: jax.Array
    leaf_weight: jax.Array
    leaf_count: jax.Array
    internal_value: jax.Array
    internal_weight: jax.Array
    internal_count: jax.Array
    leaf_depth: jax.Array
    leaf_parent: jax.Array       # [L] int32
    num_leaves: jax.Array        # scalar int32
    done: jax.Array              # scalar bool
    is_cat_node: jax.Array       # [L-1] bool
    cat_rank: jax.Array          # [L-1, B] int32


def make_grower(*, num_leaves: int, num_bins: int, params: SplitParams,
                max_depth: int = -1, block_rows: int = 0,
                hist_reduce: Optional[Callable] = None,
                hist_view: Optional[Callable] = None,
                hist_expand: Optional[Callable] = None,
                select_best: Optional[Callable] = None,
                mono_view: Optional[Callable] = None,
                subtract: bool = True,
                gather: bool = False, min_gather_rows: int = 4096,
                count_reduce: Optional[Callable] = None,
                sum_reduce: Optional[Callable] = None,
                efb=None,
                gain_scale=None,
                extra_trees: bool = False, extra_seed: int = 6,
                split_batch: int = 1,
                hist_overlap: bool = False,
                mono=None, mono_penalty: float = 0.0,
                interaction_groups=None,
                bynode_frac: float = 1.0, bynode_seed: int = 0,
                cegb=None,
                padded_leaves: Optional[int] = None,
                quant=None,
                scale_reduce: Optional[Callable] = None,
                row_offset: Optional[Callable] = None,
                jit: bool = True):
    """Build a jitted ``grow_tree(binned, vals, feature_mask, num_bin, na_bin,
    na_bin_part=None)``.

    ``padded_leaves``: leaf-budget bucketing (utils/shapes.bucket_leaves)
    — state arrays are sized to this PADDED budget while the grow loop
    exits on the ACTUAL budget, which the caller must then pass per call
    as the traced ``max_leaves`` scalar.  One padded trace covers every
    ``num_leaves`` in its bucket (31/40/63 share L=64) with
    bit-identical trees: padded leaf slots start at -inf cached gain so
    argmax/top_k never select them, and the host side slices all tree
    arrays by the returned ``num_leaves``.

    vals: [N, 3] f32 = (grad, hess, in-bag weight); out-of-bag rows zeroed.

    Parallelism hooks (SURVEY.md §2.6 strategies map onto one program):
    - hist_reduce: reduce local histograms across the mesh row axis
      (data-parallel psum; identity for serial).  The hook may SHRINK the
      feature axis: the owner-shard data-parallel learner reduce-scatters
      a feature-chunked layout (``lax.psum_scatter``) so each shard's
      histogram carry holds only its owned chunk of the GLOBAL
      histograms — the carry and every child histogram follow the
      reduced shape, never the local-view width.
    - hist_expand: maps the (possibly owner-chunked) reduced histogram
      plus the leaf totals into the SPLIT-SCAN feature space — replaces
      the built-in EFB group->feature expansion when the scan space is a
      per-shard slice (owner-shard dp; identity slicing without EFB).
      ``num_bin``/``na_bin``/``feature_mask``/``is_cat`` must then be the
      scan-space slices, while ``na_bin_part``/``num_bin_part`` carry the
      global arrays for row partitioning.
    - mono_view: maps the global [F] monotone-constraint vector into the
      split-scan feature space (owner-shard dp); partitioning keeps the
      global vector (the winning feature id is global).
    - hist_view:   restrict the binned matrix to this shard's feature slice
      before histogram work (feature-parallel; identity for serial).
      ``feature_mask``/``num_bin``/``na_bin`` must then be the local slices,
      while ``na_bin_part`` carries the global array for row partitioning.
    - select_best: cross-shard reduction of a SplitResult (feature-parallel
      argmax + feature-index globalization; identity for serial).
    - gather/min_gather_rows: child histograms are built from a COMPACTED
      row gather into the smallest power-of-2 capacity tier that fits the
      child (``lax.switch`` over tiers), so per-split matmul work is
      ∝ rows-in-smaller-child like the reference
      (serial_tree_learner.cpp:283-323 smaller-leaf discipline;
      cuda_histogram_constructor's leaf-indexed construction) instead of a
      full-N masked pass.  Below ``min_gather_rows`` tiers stop (compile
      cost isn't worth it).  DEFAULT OFF: measured on TPU v5e (PROFILE.md)
      XLA's row gather costs ~22 ns/row and ``nonzero`` ~3 ms/1M rows, so
      the tiered path is ~2.4x SLOWER than the masked full pass it tries
      to avoid; it also multiplies compile time by the tier count.
    - count_reduce: makes the tier choice uniform across shards (pmax over
      the mesh axis) so collectives inside the switch stay congruent; must
      be set whenever hist_reduce crosses shards.
    - efb: an ``EFBDevice`` — ``binned`` is then the BUNDLED group matrix
      [N, G] (dataset.cpp:239 FastFeatureBundling); histograms are built
      and subtracted in the narrow group space (the HBM-bandwidth win) and
      expanded to feature space only for split search, with the leaf's
      totals reconstructing the shared default bin (FixHistogram,
      dataset.cpp:1292).  Row partitioning decodes the winning feature's
      bins from its group column.
    - interaction_groups: [G, F] bool constraint-group matrix (ColSampler
      / col_sampler.hpp:91-111 GetByNode): per-leaf BRANCH feature sets
      are tracked on device ([L, F] state); a leaf may split on its
      branch features plus the union of the groups that contain the
      whole branch set (subset containment — progressive intersection
      diverges for overlapping groups), and the root is restricted to
      the union of all groups.
    - bynode_frac/bynode_seed: feature_fraction_bynode — every candidate
      leaf evaluation draws its own random feature subset in-graph
      (keyed by iteration/step/child so the fused scan reproduces the
      per-iteration stream).
    - cegb: a ``CEGBState`` (cost_effective_gradient_boosting.hpp):
      per-candidate acquisition penalties subtracted from gains in-graph;
      within-tree feature usage is tracked as an [F] bool state vector,
      and cross-tree usage comes in through ``grow(..., cegb_used=...)``
      (the caller derives the update from the returned split features).
    - mono/mono_penalty: [F] -1/0/+1 monotone constraints, 'basic' method
      (monotone_constraints.hpp BasicLeafConstraints): per-leaf allowed
      output ranges tracked ON DEVICE ([L] lo/hi vectors in the grow
      state), split candidates clamped+filtered in the split scan, child
      ranges bounded by the split midpoint.  Works under hist_reduce
      (data-parallel monotone, which the reference supports in all
      parallel learners) because ranges derive from replicated split
      decisions.  mono_penalty applies the depth-based gain de-rating
      (ComputeMonotoneSplitGainPenalty, monotone_constraints.hpp:355).
    - quant: a ``QuantSpec`` (ops/quantize.py) — quantized training:
      the (grad, hess, weight) stack is packed to int8/int16 with one
      shared per-channel scale per call (= per boosting iteration) and
      iteration-keyed stochastic rounding (``rng_iter`` keys the
      counter-based stream, so resume stays byte-identical), histograms
      accumulate exact int32 through the same one-hot contraction (the
      carry, the subtraction trick and any ``hist_reduce`` collective
      all run on int32), and dequantization happens only at split-scan
      time (ops/split.py ``dequantize_hist``).  Hooks for the sharded
      learners: ``scale_reduce`` maxes the [3] scale vector across the
      mesh so every shard quantizes with the GLOBAL scale, and
      ``row_offset(n_local)`` returns this shard's global row offset so
      the rounding stream is keyed by GLOBAL row ids — together they
      make the int32 reduce bitwise dp==serial.
    - split_batch=K>1: grow K leaves per super-step instead of strictly
      one.  Each step picks the top-K leaves by cached best gain, applies
      all K splits in one row-partition pass, and builds all K smaller
      children's histograms in ONE one-hot contraction with C=3K channels.
      PROFILE.md §2-6: the histogram matmul is sublane-bound at M=3 (3 of
      8 sublanes, ~4.6 TFLOP/s ceiling), so batching K leaves raises the
      ceiling ~K× while amortizing the one-hot generation — per-split cost
      drops toward 1/K.  Trees differ slightly from strict leaf-wise
      growth (between LightGBM's leaf-wise and XGBoost's depth-wise);
      K=1 keeps exact reference semantics and is the default.  Widths
      are snapped into ``utils/shapes.SPLIT_BATCH_SET`` (and fitted
      under the leaf budget) by the driver; the wide widths (32/64)
      lane-pad their C=3K channel axis to MXU 128-multiples inside the
      contraction (ops/histogram.py) — exact zeros, sliced off,
      excluded from MFU accounting (obs/flops.py ``hist_pad``).
    - hist_overlap: route the STRICT (K=1) grower's masked smaller-child
      pass through the same per-row slot mechanism the batched grower
      uses (``slot = 0 if in_child else -1``, num_slots=1) instead of
      materializing a fresh ``vals * mask`` [N, 3] scan operand per
      split.  The slot one-hot multiplies the identical 0/1 factors
      inside the row-block scan, so the histogram — and the trained
      model — is BYTE-IDENTICAL to the serialized masked baseline
      (tests/test_hist_width.py pins it), while the per-split scan
      operand shrinks to one [N] int32 slot vector and the strict path
      shares the contraction form (and the autotuner's block_rows
      choice, ops/hist_tune.py) with the batched super-step.
      Sparse-binned data keeps the masked form (its per-slot total
      reduction has a different summation order).
    """
    L_req = int(num_leaves)
    L = int(padded_leaves) if padded_leaves and int(padded_leaves) > L_req \
        else L_req
    padded = L != L_req
    B = int(num_bins)
    use_quant = quant is not None
    if use_quant:
        from .ops.quantize import quant_scales, quantize_stack
        from .ops.split import dequantize_hist
    reduce_fn = hist_reduce or (lambda h, scales=None: h)
    view_fn = hist_view or (lambda b: b)
    select_fn = select_best or (lambda r: r)
    use_subtraction = subtract
    Bh = int(efb.group_bins) if efb is not None else B   # histogram bin axis
    if efb is not None:
        efb_off_dev = jnp.asarray(efb.off_host)
    if hist_expand is not None:
        # owner-shard distribution: the reduced histogram is this shard's
        # chunk of the global one; the hook views it in scan space
        # (including the EFB group->feature expansion, done per shard)
        _expand = hist_expand
    elif efb is not None:
        from .efb import expand_group_hist

        def _expand(gh, total):
            return expand_group_hist(gh, total, efb.group_of_feat,
                                     efb.col_idx, efb.fix0)
    else:
        def _expand(gh, total):
            return gh

    def _hist(binned_view, vals, slot=None, nslots=1, scales=None):
        """Reduced histogram; with ``slot`` a per-slot multi-histogram
        (split_batch) whose vals ⊗ onehot(slot) expansion happens inside
        the scan (ops/histogram.py), never as an [N, 3*K] HBM buffer.
        Sparse-binned data takes the O(nnz) segment-sum formulation
        (sparse_data.py) instead of the one-hot contraction.  Under
        quantized training the hook receives the iteration's scales as
        a second argument (voting's gain-statistic vote needs real
        values; the reduce itself stays int32)."""
        if isinstance(binned_view, _spd.SparseBinned):
            h = _spd.histogram(binned_view, vals, num_bins=Bh, slot=slot,
                               num_slots=nslots)
        else:
            h = compute_histogram(binned_view, vals, num_bins=Bh,
                                  block_rows=block_rows, slot=slot,
                                  num_slots=nslots)
        return reduce_fn(h, scales) if use_quant else reduce_fn(h)

    def _quant_prepare(n, vals, feature_mask, rng_iter, n_leaves,
                       quant_seed=None):
        """Shared quantized-training entry for the strict and batched
        growers: trace-time flop/byte notes, the per-iteration GLOBAL
        scales, and the iteration-keyed stochastic quantization of the
        grad/hess/weight stack (ops/quantize.py).  One definition so
        the rounding key and scale reduction can never diverge between
        the two paths — the fused==per-iter and dp==serial bitwise
        contracts hang off them.  Returns (vals, scales, scan_expand);
        ``n_leaves`` sizes the dequant ledger note (2 children per
        split, 2K under a K-way super-step)."""
        from .obs.flops import (dequant_flops_bytes, note_traced,
                                quantize_flops_bytes)
        note_traced("quantize", *quantize_flops_bytes(
            n, quant.itemsize), phase="grow", cadence="iter")
        note_traced("dequant", *dequant_flops_bytes(
            feature_mask.shape[0], B, n_leaves=n_leaves), phase="grow")
        scales = quant_scales(vals, quant.qmax)
        if scale_reduce is not None:
            scales = scale_reduce(scales)
        off = row_offset(n) if row_offset is not None else 0
        ikey = jnp.int32(0) if rng_iter is None \
            else jnp.asarray(rng_iter, jnp.int32)
        vals = quantize_stack(vals, scales, quant, ikey, off,
                              seed=quant_seed)

        def scan_expand(h, t):
            return _expand(dequantize_hist(h, scales), t)
        return vals, scales, scan_expand

    def _make_child_hist(n: int, scales=None):
        """Child-histogram builder: tiered gather (see ``gather`` above)
        with a masked full-N pass as the top tier / fallback."""
        caps = []
        if gather:
            c = int(min_gather_rows)
            while c < n:
                caps.append(c)
                c *= 2

        def child_hist(binned_view, vals, leaf_of_row, child_id):
            in_child = leaf_of_row == child_id

            def full_pass(_):
                if hist_overlap \
                        and not isinstance(binned_view, _spd.SparseBinned):
                    # overlap path: the mask rides as a 1-slot id so the
                    # 0/1 multiply happens INSIDE the row-block scan —
                    # byte-identical products, but the per-split scan
                    # operand is one [N] int32 vector instead of a
                    # fresh [N, 3] masked temp (see make_grower doc)
                    sl = jnp.where(in_child, jnp.int32(0), jnp.int32(-1))
                    return _hist(binned_view, vals, slot=sl, nslots=1,
                                 scales=scales)
                mask = in_child.astype(vals.dtype)[:, None]
                return _hist(binned_view, vals * mask, scales=scales)

            if not caps:
                return full_pass(None)
            count = jnp.sum(in_child.astype(jnp.int32))
            if count_reduce is not None:
                count = count_reduce(count)
            tier = jnp.searchsorted(jnp.asarray(caps, jnp.int32), count,
                                    side="left")

            def gather_tier(cap):
                def f(_):
                    idx = jnp.nonzero(in_child, size=cap, fill_value=n)[0]
                    safe = jnp.minimum(idx, n - 1)
                    if isinstance(binned_view, _spd.SparseBinned):
                        b_g = binned_view.take_rows(safe)
                    else:
                        b_g = jnp.take(binned_view, safe, axis=0)
                    v_g = jnp.take(vals, safe, axis=0) \
                        * (idx < n)[:, None].astype(vals.dtype)
                    return _hist(b_g, v_g, scales=scales)
                return f

            return lax.switch(tier, [gather_tier(c) for c in caps]
                              + [full_pass], None)

        return child_hist

    gscale = None if gain_scale is None else jnp.asarray(gain_scale,
                                                         jnp.float32)
    mono_dev = None if mono is None else jnp.asarray(mono, jnp.int32)
    use_mono = mono_dev is not None

    def _scan_mono():
        """Monotone vector in SPLIT-SCAN feature space: owner-shard
        learners scan only their owned feature chunk (mono_view gathers
        the slice in-graph); identity otherwise.  Partitioning and child
        range propagation keep indexing the GLOBAL ``mono_dev`` — the
        winning feature id is global after select_best."""
        return mono_dev if mono_view is None else mono_view(mono_dev)
    inter_dev = None if interaction_groups is None \
        else jnp.asarray(interaction_groups, bool)     # [G, F]
    use_inter = inter_dev is not None

    def _inter_allowed(branch):
        """GetByNode: branch ∪ (∪ groups that contain the whole branch).
        ``branch`` [F] bool -> allowed [F] bool.  An empty branch is a
        subset of every group -> union of all groups (root case)."""
        contains = (inter_dev | ~branch[None, :]).all(axis=1)      # [G]
        return (inter_dev & contains[:, None]).any(axis=0) | branch
    use_bynode = 0.0 < float(bynode_frac) < 1.0
    use_cegb = cegb is not None and cegb.active
    if use_cegb:
        nf_c = len(cegb.used)
        lazy = cegb.lazy if cegb.lazy is not None else np.zeros(nf_c)
        # per-count slope and coupled once-per-model components of
        # CEGBState.penalty_vector, as device constants
        cegb_slope = jnp.asarray(
            cegb.tradeoff * (cegb.penalty_split + lazy), jnp.float32)
        cegb_coupled = None if cegb.coupled is None else \
            jnp.asarray(cegb.tradeoff * cegb.coupled, jnp.float32)

    def _cegb_penalty(count, cuse):
        pen = cegb_slope * count
        if cegb_coupled is not None:
            pen = pen + cegb_coupled * (~cuse)
        return pen
    # per-leaf feature masks are threaded through _best2 whenever EITHER
    # mechanism is active (they compose by &)
    per_leaf_mask = use_inter or use_bynode

    def _bynode_mask(key, base):
        """One random feature subset (ColSampler bynode): keep
        ceil(frac * |valid|) features sampled FROM the valid set ``base``
        (reference semantics, col_sampler.hpp — sampling from the full
        axis and intersecting could leave a constrained branch with an
        empty candidate set).  Always keeps >= 1 valid feature."""
        nf = base.shape[0]
        nvalid = base.sum()
        k = jnp.maximum(1, jnp.ceil(
            nvalid.astype(jnp.float32) * bynode_frac)).astype(jnp.int32)
        u = jnp.where(base, jax.random.uniform(key, (nf,)), jnp.inf)
        rank = jnp.argsort(jnp.argsort(u))
        return base & (rank < k)

    def _rand_bins(key, shape, num_bin):
        """extra_trees (feature_histogram.hpp:116): one random threshold
        bin per feature, uniform over the feature's valid range."""
        u = jax.random.uniform(key, shape)
        span = jnp.maximum(num_bin - 1, 1).astype(jnp.float32)
        return jnp.minimum((u * span).astype(jnp.int32), num_bin - 2)

    def _mono_gain_scale(depth):
        """Per-feature [F] penalty scale on monotone features, composed
        with ``gain_scale`` (shared formula: ops/split.py
        monotone_penalty_factor); scan-space under owner sharding."""
        factor = monotone_penalty_factor(mono_penalty, depth)
        gs = jnp.where(_scan_mono() != 0, factor, 1.0).astype(jnp.float32)
        return gs if gscale is None else gs * gscale

    def _best2(hist2, totals2, num_bin, na_bin, fmask, parent_out2, is_cat,
               rand2=None, lo2=None, hi2=None, depth2=None, fmask2=None,
               cuse_cur=None):
        """Vmapped best-split over a batch of candidate leaves; optional
        per-leaf extra_trees random bins, monotone output ranges, and
        per-leaf feature masks (interaction constraints / bynode)."""
        extras, axes = [], []
        if rand2 is not None:
            extras.append(rand2)
            axes.append(0)
        if use_mono:
            extras += [lo2, hi2, depth2]
            axes += [0, 0, 0]
        if fmask2 is not None:
            extras.append(fmask2)
            axes.append(0)

        def one(h, t, po, *rest):
            i = 0
            kw = {}
            if rand2 is not None:
                kw["rand_bin"] = rest[i]
                i += 1
            if use_mono:
                lo, hi, d = rest[i], rest[i + 1], rest[i + 2]
                i += 3
                kw.update(mono=_scan_mono(), out_lo=lo, out_hi=hi)
                kw["gain_scale"] = _mono_gain_scale(d) \
                    if mono_penalty > 0.0 else gscale
            else:
                kw["gain_scale"] = gscale
            fm = rest[i] if fmask2 is not None else fmask
            if use_cegb:
                # cuse_cur is shared by all children of this step (the
                # vmap closes over it); the penalty's count term is the
                # candidate leaf's own row count
                kw["gain_penalty"] = _cegb_penalty(t[2], cuse_cur)
            return select_fn(find_best_split(h, t, num_bin, na_bin, fm,
                                             params, po, is_cat, **kw))

        return jax.vmap(one, in_axes=(0, 0, 0) + tuple(axes))(
            hist2, totals2, parent_out2, *extras)

    def _child_ranges(lo_p, hi_p, mc, icat, mid):
        """BasicLeafConstraints child range propagation: a +1 split caps
        the left child at the midpoint and floors the right child (and
        mirrored for -1); categorical or unconstrained splits inherit."""
        apply = (mc != 0) & (~icat)
        up = mc > 0
        l_lo = jnp.where(apply & (~up), jnp.maximum(lo_p, mid), lo_p)
        l_hi = jnp.where(apply & up, jnp.minimum(hi_p, mid), hi_p)
        r_lo = jnp.where(apply & up, jnp.maximum(lo_p, mid), lo_p)
        r_hi = jnp.where(apply & (~up), jnp.minimum(hi_p, mid), hi_p)
        return l_lo, l_hi, r_lo, r_hi

    def _root_eval(binned_view, vals, feature_mask, num_bin, na_bin,
                   is_cat, rng_iter, cuse0=None, expand=None,
                   scales=None):
        """Root histogram + aggregates + best split; shared by the strict
        and batched growers.  ``expand``/``scales``: quantized training
        — ``vals`` is already the int stack, ``expand`` dequantizes
        before the scan-space view, and the root aggregates come from
        exact int32 sums dequantized by the shared scales."""
        expand = _expand if expand is None else expand
        hist0 = _hist(binned_view, vals, scales=scales)  # [F|G, B|Bg, 3]
        # root aggregates from vals directly, NOT from hist0[0]: a filtering
        # hist_reduce (voting's top-k zeroing) may have dropped feature 0's
        # histogram, and this is also one less reduction of a big tensor
        if scales is not None:
            # int32 sums are exact; cross-shard sum_reduce (psum) runs
            # on the integers so the dequantized totals are bitwise
            # identical between serial and every sharded learner
            if sum_reduce is not None:
                ti = sum_reduce(vals.astype(jnp.int32).sum(axis=0))
            elif hist_reduce is not None:
                ti = hist0[0].sum(axis=0)
            else:
                ti = vals.astype(jnp.int32).sum(axis=0)
            total0 = dequantize_hist(ti, scales)
        elif sum_reduce is not None:
            total0 = sum_reduce(vals.sum(axis=0))
        elif hist_reduce is not None:
            # caller-supplied reduce hook without a sum_reduce: derive the
            # totals from the reduced histogram so cross-shard hooks keep
            # seeing globally-reduced root aggregates
            total0 = hist0[0].sum(axis=0)
        else:
            total0 = vals.sum(axis=0)
        root_out = leaf_output(total0[0], total0[1], params)
        rb0 = None
        et_key = None
        if extra_trees:
            # key = (extra_seed, iteration, split index): without the
            # iteration fold every TREE would redraw identical thresholds
            # and the ExtraTrees decorrelation would be lost entirely
            et_key = jax.random.PRNGKey(extra_seed)
            if rng_iter is not None:
                et_key = jax.random.fold_in(et_key, rng_iter)
            # the split search runs in (possibly EFB-expanded) feature
            # space = feature_mask's axis, not binned_view's column count
            rb0 = _rand_bins(jax.random.fold_in(et_key, 0),
                             (feature_mask.shape[0],), num_bin)
        bn_key = None
        fmask_root = feature_mask
        if use_inter:
            # root branch is empty -> only the union of all groups is
            # splittable (col_sampler.hpp:99-100)
            fmask_root = fmask_root & _inter_allowed(
                jnp.zeros(feature_mask.shape[0], bool))
        if use_bynode:
            bn_key = jax.random.PRNGKey(bynode_seed)
            if rng_iter is not None:
                bn_key = jax.random.fold_in(bn_key, rng_iter)
            fmask_root = _bynode_mask(jax.random.fold_in(bn_key, 0),
                                      fmask_root)
        kw = {"gain_scale": gscale, "rand_bin": rb0}
        if use_mono:
            kw.update(mono=_scan_mono(), out_lo=jnp.float32(-jnp.inf),
                      out_hi=jnp.float32(jnp.inf))
            if mono_penalty > 0.0:
                kw["gain_scale"] = _mono_gain_scale(jnp.int32(0))
        if use_cegb:
            kw["gain_penalty"] = _cegb_penalty(total0[2], cuse0)
        res0 = select_fn(find_best_split(expand(hist0, total0), total0,
                                         num_bin, na_bin, fmask_root,
                                         params, root_out, is_cat, **kw))
        return hist0, total0, root_out, res0, et_key, bn_key

    def _init_state(n, nleaf, nnode, fv, nf, hist0, total0, root_out,
                    res0, cuse0=None) -> _GrowState:
        """Fresh grow state with ``nleaf`` leaf slots / ``nnode`` node
        slots (== L/L-1 strict; +K scratch slots batched)."""
        neg_inf = jnp.float32(-jnp.inf)
        return _GrowState(
            leaf_of_row=jnp.zeros(n, jnp.int32),
            # quantized training carries the histogram state as exact
            # int32 (dtype follows the root pass); subtraction and the
            # reduce collectives stay integer, dequantized only at scan
            hist=jnp.zeros((nleaf, fv, Bh, 3),
                           hist0.dtype).at[0].set(hist0),
            olo=jnp.full(nleaf, neg_inf),
            ohi=jnp.full(nleaf, jnp.inf),
            # branch sets start empty (root has no ancestors)
            fallow=jnp.zeros((nleaf, nf if use_inter else 1), bool),
            cuse=cuse0 if cuse0 is not None else jnp.zeros(1, bool),
            bg=jnp.full(nleaf, neg_inf).at[0].set(res0.gain),
            bf=jnp.zeros(nleaf, jnp.int32).at[0].set(res0.feature),
            bt=jnp.zeros(nleaf, jnp.int32).at[0].set(res0.threshold),
            bdl=jnp.zeros(nleaf, bool).at[0].set(res0.default_left),
            bls=jnp.zeros((nleaf, 3)).at[0].set(res0.left_sum),
            brs=jnp.zeros((nleaf, 3)).at[0].set(res0.right_sum),
            blo=jnp.zeros(nleaf).at[0].set(res0.left_output),
            bro=jnp.zeros(nleaf).at[0].set(res0.right_output),
            bic=jnp.zeros(nleaf, bool).at[0].set(res0.is_cat),
            brank=jnp.zeros((nleaf, B), jnp.int32).at[0].set(res0.bin_rank),
            split_feature=jnp.zeros(nnode, jnp.int32),
            threshold_bin=jnp.zeros(nnode, jnp.int32),
            default_left=jnp.zeros(nnode, bool),
            left_child=jnp.zeros(nnode, jnp.int32),
            right_child=jnp.zeros(nnode, jnp.int32),
            split_gain=jnp.zeros(nnode, jnp.float32),
            leaf_value=jnp.zeros(nleaf, jnp.float32).at[0].set(root_out),
            leaf_weight=jnp.zeros(nleaf, jnp.float32).at[0].set(total0[1]),
            leaf_count=jnp.zeros(nleaf, jnp.float32).at[0].set(total0[2]),
            internal_value=jnp.zeros(nnode, jnp.float32),
            internal_weight=jnp.zeros(nnode, jnp.float32),
            internal_count=jnp.zeros(nnode, jnp.float32),
            leaf_depth=jnp.zeros(nleaf, jnp.int32),
            leaf_parent=jnp.full(nleaf, -1, jnp.int32),
            num_leaves=jnp.int32(1),
            done=jnp.bool_(False),
            is_cat_node=jnp.zeros(nnode, bool),
            cat_rank=jnp.broadcast_to(
                jnp.arange(B, dtype=jnp.int32)[None], (nnode, B)) + 0,
        )

    def grow_tree(binned, vals, feature_mask, num_bin, na_bin,
                  na_bin_part=None, is_cat=None,
                  rng_iter=None, cegb_used=None,
                  num_bin_part=None, max_leaves=None,
                  quant_seed=None) -> TreeArrays:
        trace_event("grower")
        if max_leaves is None:
            if padded:
                raise ValueError(
                    "a leaf-padded grower needs the actual budget per "
                    "call: pass max_leaves=<num_leaves>")
            limit = jnp.int32(L)
        else:
            limit = jnp.asarray(max_leaves, jnp.int32)
        n, _f_global = binned.shape
        binned_view = view_fn(binned)
        scales = None
        scan_expand = _expand
        if use_quant:
            vals, scales, scan_expand = _quant_prepare(
                n, vals, feature_mask, rng_iter, n_leaves=2,
                quant_seed=quant_seed)
        child_hist = _make_child_hist(n, scales)
        if na_bin_part is None:
            na_bin_part = na_bin
        if num_bin_part is None:
            num_bin_part = num_bin
        cuse0 = None
        if use_cegb:
            cuse0 = cegb_used if cegb_used is not None \
                else jnp.zeros(feature_mask.shape[0], bool)

        hist0, total0, root_out, res0, et_key, bn_key = _root_eval(
            binned_view, vals, feature_mask, num_bin, na_bin, is_cat,
            rng_iter, cuse0, expand=scan_expand, scales=scales)
        # the carry follows the REDUCED histogram's feature axis, not the
        # binned view's: an owner-shard hist_reduce leaves each shard with
        # only its chunk of the global histograms ([L, F/n, B, 3])
        st = _init_state(n, L, L - 1, hist0.shape[0],
                         feature_mask.shape[0], hist0, total0,
                         root_out, res0, cuse0)

        def split_step(st: _GrowState) -> _GrowState:
            # one split per step, so the node id IS the split count so far
            i = st.num_leaves - 1
            leaf = jnp.argmax(st.bg).astype(jnp.int32)
            can_split = (st.bg[leaf] > 0.0) & (~st.done)

            def do_split(st: _GrowState) -> _GrowState:
                # partition-site static accounting (obs/flops.py): a
                # trace-time Python side effect, zero runtime cost
                from .obs.flops import note_traced, partition_flops_bytes
                note_traced("partition", *partition_flops_bytes(n),
                            phase="grow")
                new_leaf = (i + 1).astype(jnp.int32)
                feat, thr = st.bf[leaf], st.bt[leaf]
                dleft = st.bdl[leaf]
                lsum, rsum = st.bls[leaf], st.brs[leaf]
                icat, rank_vec = st.bic[leaf], st.brank[leaf]

                # --- tree bookkeeping (Tree::Split, src/io/tree.cpp) ------
                parent = st.leaf_parent[leaf]
                node_ids = jnp.arange(L - 1, dtype=jnp.int32)
                fix_l = (node_ids == parent) & (st.left_child == ~leaf)
                fix_r = (node_ids == parent) & (st.right_child == ~leaf)
                lc = jnp.where(fix_l, i, st.left_child).at[i].set(~leaf)
                rc = jnp.where(fix_r, i, st.right_child).at[i].set(~new_leaf)

                # --- partition rows (CUDADataPartition::Split analog) -----
                # decision rank unifies numerical (iota rank) and
                # categorical (ratio-order rank) predicates
                if efb is None:
                    if isinstance(binned, _spd.SparseBinned):
                        fcol = _spd.column(binned, feat)
                    else:
                        fcol = jnp.take(binned, feat, axis=1) \
                            .astype(jnp.int32)
                else:
                    # decode the feature's bins from its bundle column
                    # (SubFeatureIterator analog, feature_group.h)
                    gcol = jnp.take(binned, efb.group_of_feat[feat],
                                    axis=1).astype(jnp.int32)
                    off = efb_off_dev[feat]
                    in_range = (gcol >= off) \
                        & (gcol < off + num_bin_part[feat] - 1)
                    fcol = jnp.where(off < 0, gcol,
                                     jnp.where(in_range, gcol - off + 1, 0))
                nb = na_bin_part[feat]
                is_na = (nb >= 0) & (fcol == nb) & (~icat)
                go_left = jnp.where(is_na, dleft, rank_vec[fcol] <= thr)
                in_leaf = st.leaf_of_row == leaf
                leaf_of_row = jnp.where(in_leaf & (~go_left), new_leaf,
                                        st.leaf_of_row)

                # --- histograms: smaller child + subtraction --------------
                smaller_left = lsum[2] <= rsum[2]
                smaller_id = jnp.where(smaller_left, leaf, new_leaf)
                hist_small = child_hist(binned_view, vals, leaf_of_row,
                                        smaller_id)
                if use_subtraction:
                    hist_large = st.hist[leaf] - hist_small
                else:
                    # voting-parallel: per-split feature votes make the
                    # reduced hist feature sets differ between parent and
                    # children, so the larger child is constructed too
                    larger_id = jnp.where(smaller_left, new_leaf, leaf)
                    hist_large = child_hist(binned_view, vals, leaf_of_row,
                                            larger_id)
                hl_leaf = jnp.where(smaller_left, hist_small, hist_large)
                hl_new = jnp.where(smaller_left, hist_large, hist_small)
                hist = st.hist.at[leaf].set(hl_leaf).at[new_leaf].set(hl_new)

                # --- leaf stats -------------------------------------------
                d = st.leaf_depth[leaf] + 1
                lv = st.leaf_value.at[leaf].set(st.blo[leaf]) \
                                  .at[new_leaf].set(st.bro[leaf])
                lw = st.leaf_weight.at[leaf].set(lsum[1]).at[new_leaf].set(rsum[1])
                lcnt = st.leaf_count.at[leaf].set(lsum[2]).at[new_leaf].set(rsum[2])
                ld = st.leaf_depth.at[leaf].set(d).at[new_leaf].set(d)

                # --- monotone range propagation (basic) -------------------
                lo2 = hi2 = depth2 = None
                olo, ohi = st.olo, st.ohi
                if use_mono:
                    mid = 0.5 * (st.blo[leaf] + st.bro[leaf])
                    l_lo, l_hi, r_lo, r_hi = _child_ranges(
                        st.olo[leaf], st.ohi[leaf], mono_dev[feat], icat,
                        mid)
                    olo = st.olo.at[leaf].set(l_lo).at[new_leaf].set(r_lo)
                    ohi = st.ohi.at[leaf].set(l_hi).at[new_leaf].set(r_hi)
                    lo2 = jnp.stack([l_lo, r_lo])
                    hi2 = jnp.stack([l_hi, r_hi])
                    depth2 = jnp.stack([d, d])

                # --- per-leaf feature masks (interaction / bynode) --------
                fmask2 = None
                fallow = st.fallow
                if per_leaf_mask:
                    nf = feature_mask.shape[0]
                    if use_inter:
                        child_branch = st.fallow[leaf] | (
                            jnp.arange(nf, dtype=jnp.int32) == feat)
                        fallow = st.fallow.at[leaf].set(child_branch) \
                                          .at[new_leaf].set(child_branch)
                        base = _inter_allowed(child_branch) & feature_mask
                    else:
                        base = feature_mask
                    if use_bynode:
                        kL = jax.random.fold_in(bn_key, 2 * (i + 1))
                        kR = jax.random.fold_in(bn_key, 2 * (i + 1) + 1)
                        m_l = _bynode_mask(kL, base)
                        m_r = _bynode_mask(kR, base)
                    else:
                        m_l = m_r = base
                    fmask2 = jnp.stack([m_l, m_r])

                # --- new best splits for both children (batched) ----------
                hist2 = jnp.stack([hl_leaf, hl_new])
                tot2 = jnp.stack([lsum, rsum])
                po2 = jnp.stack([st.blo[leaf], st.bro[leaf]])
                rand2 = None
                if extra_trees:
                    rand2 = _rand_bins(jax.random.fold_in(et_key, i + 1),
                                       (2, feature_mask.shape[0]), num_bin)
                cuse = st.cuse
                if use_cegb:
                    cuse = st.cuse | (
                        jnp.arange(st.cuse.shape[0], dtype=jnp.int32)
                        == feat)
                r2 = _best2(jax.vmap(scan_expand)(hist2, tot2), tot2,
                            num_bin, na_bin, feature_mask, po2, is_cat,
                            rand2, lo2, hi2, depth2, fmask2, cuse)
                depth_ok = (max_depth <= 0) | (d < max_depth)
                g2 = jnp.where(depth_ok, r2.gain, -jnp.inf)

                return st._replace(
                    leaf_of_row=leaf_of_row,
                    hist=hist,
                    olo=olo, ohi=ohi, fallow=fallow, cuse=cuse,
                    bg=st.bg.at[leaf].set(g2[0]).at[new_leaf].set(g2[1]),
                    bf=st.bf.at[leaf].set(r2.feature[0]).at[new_leaf].set(r2.feature[1]),
                    bt=st.bt.at[leaf].set(r2.threshold[0]).at[new_leaf].set(r2.threshold[1]),
                    bdl=st.bdl.at[leaf].set(r2.default_left[0]).at[new_leaf].set(r2.default_left[1]),
                    bls=st.bls.at[leaf].set(r2.left_sum[0]).at[new_leaf].set(r2.left_sum[1]),
                    brs=st.brs.at[leaf].set(r2.right_sum[0]).at[new_leaf].set(r2.right_sum[1]),
                    blo=st.blo.at[leaf].set(r2.left_output[0]).at[new_leaf].set(r2.left_output[1]),
                    bro=st.bro.at[leaf].set(r2.right_output[0]).at[new_leaf].set(r2.right_output[1]),
                    bic=st.bic.at[leaf].set(r2.is_cat[0]).at[new_leaf].set(r2.is_cat[1]),
                    brank=st.brank.at[leaf].set(r2.bin_rank[0]).at[new_leaf].set(r2.bin_rank[1]),
                    split_feature=st.split_feature.at[i].set(feat),
                    threshold_bin=st.threshold_bin.at[i].set(thr),
                    default_left=st.default_left.at[i].set(dleft),
                    left_child=lc,
                    right_child=rc,
                    split_gain=st.split_gain.at[i].set(st.bg[leaf]),
                    leaf_value=lv, leaf_weight=lw, leaf_count=lcnt,
                    internal_value=st.internal_value.at[i].set(st.leaf_value[leaf]),
                    internal_weight=st.internal_weight.at[i].set(st.leaf_weight[leaf]),
                    internal_count=st.internal_count.at[i].set(st.leaf_count[leaf]),
                    leaf_depth=ld,
                    leaf_parent=st.leaf_parent.at[leaf].set(i).at[new_leaf].set(i),
                    num_leaves=new_leaf + 1,
                    done=st.done,
                    is_cat_node=st.is_cat_node.at[i].set(icat),
                    cat_rank=st.cat_rank.at[i].set(rank_vec),
                )

            return lax.cond(can_split, do_split,
                            lambda s: s._replace(done=jnp.bool_(True)), st)

        # while_loop, not a fixed L-1 fori_loop: a tree that stops early
        # (no positive gain) exits instead of running no-op tail steps —
        # with 255-leaf budgets those dead steps used to dominate small
        # trees' device time (each one still copies the multi-MB carried
        # state through the cond).  The exit bound is the TRACED actual
        # budget ``limit`` (== L unless leaf-padded), which is what lets
        # one padded trace serve a whole num_leaves bucket.
        st = lax.while_loop(
            lambda s: (~s.done) & (s.num_leaves < limit), split_step, st)
        return TreeArrays(
            num_leaves=st.num_leaves,
            split_feature=st.split_feature,
            threshold_bin=st.threshold_bin,
            default_left=st.default_left,
            left_child=st.left_child,
            right_child=st.right_child,
            split_gain=st.split_gain,
            leaf_value=st.leaf_value,
            leaf_weight=st.leaf_weight,
            leaf_count=st.leaf_count,
            internal_value=st.internal_value,
            internal_weight=st.internal_weight,
            internal_count=st.internal_count,
            leaf_depth=st.leaf_depth,
            leaf_of_row=st.leaf_of_row,
            is_cat_node=st.is_cat_node,
            cat_rank=st.cat_rank,
            n_steps=st.num_leaves - 1,
        )

    # K clamps against the ACTUAL budget, not the padded one: the
    # super-step width is baked into RNG streams (bynode/extra_trees key
    # schedules) and tree shape, so padding must never change it
    K = max(1, min(int(split_batch), L_req - 1)) if L_req > 1 else 1

    def grow_tree_batched(binned, vals, feature_mask, num_bin, na_bin,
                          na_bin_part=None, is_cat=None,
                          rng_iter=None, cegb_used=None,
                          num_bin_part=None, max_leaves=None,
                          quant_seed=None) -> TreeArrays:
        """K-splits-per-super-step grower (split_batch above).

        Per-leaf state arrays carry K scratch slots past the real range
        (leaves ``L..L+K-1``, nodes ``L-1..L-2+K``): slots of the top-K
        batch whose cached gain is non-positive (or past the leaf budget)
        are redirected there, so every step runs the same fixed-shape
        program and the scratch writes are sliced off at the end."""
        trace_event("grower")
        if max_leaves is None:
            if padded:
                raise ValueError(
                    "a leaf-padded grower needs the actual budget per "
                    "call: pass max_leaves=<num_leaves>")
            limit = jnp.int32(L)
        else:
            limit = jnp.asarray(max_leaves, jnp.int32)
        n, _f_global = binned.shape
        binned_view = view_fn(binned)
        scales = None
        scan_expand = _expand
        if use_quant:
            vals, scales, scan_expand = _quant_prepare(
                n, vals, feature_mask, rng_iter, n_leaves=2 * K,
                quant_seed=quant_seed)
        if na_bin_part is None:
            na_bin_part = na_bin
        if num_bin_part is None:
            num_bin_part = num_bin
        LP, NP = L + K, (L - 1) + K
        cuse0 = None
        if use_cegb:
            cuse0 = cegb_used if cegb_used is not None \
                else jnp.zeros(feature_mask.shape[0], bool)

        hist0, total0, root_out, res0, et_key, bn_key = _root_eval(
            binned_view, vals, feature_mask, num_bin, na_bin, is_cat,
            rng_iter, cuse0, expand=scan_expand, scales=scales)
        # carry feature axis = the REDUCED histogram's (owner-shard chunk
        # under the scatter-reducing dp learner; the view width otherwise)
        fh = hist0.shape[0]
        st = _init_state(n, LP, NP, fh, feature_mask.shape[0], hist0,
                         total0, root_out, res0, cuse0)

        neg_inf = jnp.float32(-jnp.inf)
        kidx = jnp.arange(K, dtype=jnp.int32)
        nC = K if use_subtraction else 2 * K

        def super_step(carry):
            s, st = carry
            gains, leaves = lax.top_k(lax.slice_in_dim(st.bg, 0, L), K)
            num_nodes = st.num_leaves - 1
            budget = (limit - 1) - num_nodes
            # gains sorted desc and budget a prefix: valid slots are a
            # prefix, so node/leaf id assignment below stays contiguous
            valid = (gains > 0.0) & (kidx < budget) & (~st.done)
            can_split = valid[0]

            def do_split(st: _GrowState) -> _GrowState:
                # one partition pass serves all K splits of the super-
                # step (trace-time note; obs/flops.py)
                from .obs.flops import note_traced, partition_flops_bytes
                note_traced("partition", *partition_flops_bytes(n),
                            phase="grow")
                leaf_sel = jnp.where(valid, leaves, L + kidx)
                node_sel = jnp.where(valid, num_nodes + kidx,
                                     jnp.int32(L - 1) + kidx)
                new_leaf_sel = jnp.where(valid, st.num_leaves + kidx,
                                         L + kidx)

                feat_k = st.bf[leaf_sel]
                thr_k = st.bt[leaf_sel]
                dleft_k = st.bdl[leaf_sel]
                icat_k = st.bic[leaf_sel]
                lsum_k, rsum_k = st.bls[leaf_sel], st.brs[leaf_sel]
                rank_k = st.brank[leaf_sel]          # [K, B]
                blo_k, bro_k = st.blo[leaf_sel], st.bro[leaf_sel]
                parent_k = st.leaf_parent[leaf_sel]

                # --- partition rows: ONE pass for all K splits ------------
                slot_of_leaf = jnp.full(LP, -1, jnp.int32) \
                    .at[leaf_sel].set(kidx)
                slot = slot_of_leaf[st.leaf_of_row]          # [N]
                active = slot >= 0
                sl = jnp.maximum(slot, 0)
                feat_r = feat_k[sl]                          # [N]
                if efb is None:
                    if isinstance(binned, _spd.SparseBinned):
                        fcol = _spd.column_per_row(binned, feat_r)
                    else:
                        fcol = jnp.take_along_axis(
                            binned, feat_r[:, None], axis=1)[:, 0] \
                            .astype(jnp.int32)
                else:
                    grp_r = efb.group_of_feat[feat_r]
                    gcol = jnp.take_along_axis(
                        binned, grp_r[:, None], axis=1)[:, 0] \
                        .astype(jnp.int32)
                    off = efb_off_dev[feat_r]
                    in_range = (gcol >= off) \
                        & (gcol < off + num_bin_part[feat_r] - 1)
                    fcol = jnp.where(off < 0, gcol,
                                     jnp.where(in_range, gcol - off + 1, 0))
                nb_r = na_bin_part[feat_r]
                icat_r = icat_k[sl]
                is_na = (nb_r >= 0) & (fcol == nb_r) & (~icat_r)
                rv = rank_k[sl, fcol]
                go_left = jnp.where(is_na, dleft_k[sl], rv <= thr_k[sl])
                leaf_of_row = jnp.where(active & (~go_left),
                                        new_leaf_sel[sl], st.leaf_of_row)

                # --- batched child histograms: one C=3K contraction -------
                smaller_left = lsum_k[:, 2] <= rsum_k[:, 2]  # [K]
                small_id = jnp.where(smaller_left, leaf_sel, new_leaf_sel)
                targets = small_id if use_subtraction \
                    else jnp.concatenate([leaf_sel, new_leaf_sel])
                tslot_of_leaf = jnp.full(LP, -1, jnp.int32) \
                    .at[targets].set(jnp.arange(nC, dtype=jnp.int32))
                tslot = tslot_of_leaf[leaf_of_row]           # [N]
                hist_c = _hist(binned_view, vals, tslot, nC,
                               scales=scales)                # [Fh, Bh, 3nC]
                hist_c = hist_c.reshape(fh, Bh, 3, nC) \
                    .transpose(3, 0, 1, 2)                   # [nC, Fh, Bh, 3]
                if use_subtraction:
                    hist_small = hist_c
                    hist_large = st.hist[leaf_sel] - hist_small
                    sel = smaller_left[:, None, None, None]
                    hl_leaf = jnp.where(sel, hist_small, hist_large)
                    hl_new = jnp.where(sel, hist_large, hist_small)
                else:
                    hl_leaf, hl_new = hist_c[:K], hist_c[K:]
                hist = st.hist.at[leaf_sel].set(hl_leaf) \
                              .at[new_leaf_sel].set(hl_new)

                # --- leaf stats -------------------------------------------
                d_k = st.leaf_depth[leaf_sel] + 1
                lv = st.leaf_value.at[leaf_sel].set(blo_k) \
                                  .at[new_leaf_sel].set(bro_k)
                lw = st.leaf_weight.at[leaf_sel].set(lsum_k[:, 1]) \
                                   .at[new_leaf_sel].set(rsum_k[:, 1])
                lcnt = st.leaf_count.at[leaf_sel].set(lsum_k[:, 2]) \
                                    .at[new_leaf_sel].set(rsum_k[:, 2])
                ld = st.leaf_depth.at[leaf_sel].set(d_k) \
                                  .at[new_leaf_sel].set(d_k)

                # --- monotone range propagation (basic, ×K) ---------------
                lo2 = hi2 = depth2 = None
                olo, ohi = st.olo, st.ohi
                if use_mono:
                    mid_k = 0.5 * (blo_k + bro_k)
                    l_lo, l_hi, r_lo, r_hi = _child_ranges(
                        st.olo[leaf_sel], st.ohi[leaf_sel],
                        mono_dev[feat_k], icat_k, mid_k)
                    olo = st.olo.at[leaf_sel].set(l_lo) \
                                .at[new_leaf_sel].set(r_lo)
                    ohi = st.ohi.at[leaf_sel].set(l_hi) \
                                .at[new_leaf_sel].set(r_hi)
                    lo2 = jnp.concatenate([l_lo, r_lo])
                    hi2 = jnp.concatenate([l_hi, r_hi])
                    depth2 = jnp.concatenate([d_k, d_k])

                # --- per-leaf feature masks (interaction / bynode, ×K) ----
                fmask2 = None
                fallow = st.fallow
                if per_leaf_mask:
                    nf = feature_mask.shape[0]
                    if use_inter:
                        child_branch = st.fallow[leaf_sel] | (
                            jnp.arange(nf, dtype=jnp.int32)[None]
                            == feat_k[:, None])              # [K, F]
                        fallow = st.fallow.at[leaf_sel].set(child_branch) \
                                          .at[new_leaf_sel].set(child_branch)
                        base = jax.vmap(_inter_allowed)(child_branch) \
                            & feature_mask[None]
                    else:
                        base = jnp.broadcast_to(feature_mask[None],
                                                (K, nf))
                    if use_bynode:
                        ids = (s + 1) * 2 * K \
                            + jnp.arange(2 * K, dtype=jnp.int32)
                        keys = jax.vmap(
                            lambda j: jax.random.fold_in(bn_key, j))(ids)
                        fmask2 = jax.vmap(_bynode_mask)(
                            keys, jnp.concatenate([base, base]))
                    else:
                        fmask2 = jnp.concatenate([base, base])

                # --- best splits for all 2K children (batched) ------------
                hist2 = jnp.concatenate([hl_leaf, hl_new])   # [2K, ...]
                tot2 = jnp.concatenate([lsum_k, rsum_k])
                po2 = jnp.concatenate([blo_k, bro_k])
                rand2 = None
                if extra_trees:
                    rand2 = _rand_bins(jax.random.fold_in(et_key, s + 1),
                                       (2 * K, feature_mask.shape[0]),
                                       num_bin)
                cuse = st.cuse
                if use_cegb:
                    marks = jnp.zeros(st.cuse.shape[0], jnp.int32) \
                        .at[feat_k].add(valid.astype(jnp.int32))
                    cuse = st.cuse | (marks > 0)
                r2 = _best2(jax.vmap(scan_expand)(hist2, tot2), tot2,
                            num_bin, na_bin, feature_mask, po2, is_cat,
                            rand2, lo2, hi2, depth2, fmask2, cuse)
                d2 = jnp.concatenate([d_k, d_k])
                depth_ok = (max_depth <= 0) | (d2 < max_depth)
                valid2 = jnp.concatenate([valid, valid])
                g2 = jnp.where(depth_ok & valid2, r2.gain, neg_inf)
                idx2 = jnp.concatenate([leaf_sel, new_leaf_sel])

                # --- tree bookkeeping (Tree::Split ×K) --------------------
                node_ids = jnp.arange(NP, dtype=jnp.int32)
                lc, rc = st.left_child, st.right_child
                for j in range(K):       # static unroll over tiny arrays
                    fix_l = (node_ids == parent_k[j]) \
                        & (lc == ~leaf_sel[j])
                    fix_r = (node_ids == parent_k[j]) \
                        & (rc == ~leaf_sel[j])
                    lc = jnp.where(fix_l, node_sel[j], lc)
                    rc = jnp.where(fix_r, node_sel[j], rc)
                lc = lc.at[node_sel].set(~leaf_sel)
                rc = rc.at[node_sel].set(~new_leaf_sel)

                return st._replace(
                    leaf_of_row=leaf_of_row,
                    hist=hist,
                    olo=olo, ohi=ohi, fallow=fallow, cuse=cuse,
                    bg=st.bg.at[idx2].set(g2),
                    bf=st.bf.at[idx2].set(r2.feature),
                    bt=st.bt.at[idx2].set(r2.threshold),
                    bdl=st.bdl.at[idx2].set(r2.default_left),
                    bls=st.bls.at[idx2].set(r2.left_sum),
                    brs=st.brs.at[idx2].set(r2.right_sum),
                    blo=st.blo.at[idx2].set(r2.left_output),
                    bro=st.bro.at[idx2].set(r2.right_output),
                    bic=st.bic.at[idx2].set(r2.is_cat),
                    brank=st.brank.at[idx2].set(r2.bin_rank),
                    split_feature=st.split_feature.at[node_sel].set(feat_k),
                    threshold_bin=st.threshold_bin.at[node_sel].set(thr_k),
                    default_left=st.default_left.at[node_sel].set(dleft_k),
                    left_child=lc,
                    right_child=rc,
                    split_gain=st.split_gain.at[node_sel].set(
                        jnp.where(valid, gains, 0.0)),
                    leaf_value=lv, leaf_weight=lw, leaf_count=lcnt,
                    internal_value=st.internal_value.at[node_sel].set(
                        st.leaf_value[leaf_sel]),
                    internal_weight=st.internal_weight.at[node_sel].set(
                        st.leaf_weight[leaf_sel]),
                    internal_count=st.internal_count.at[node_sel].set(
                        st.leaf_count[leaf_sel]),
                    leaf_depth=ld,
                    leaf_parent=st.leaf_parent.at[leaf_sel].set(node_sel)
                                              .at[new_leaf_sel].set(node_sel),
                    num_leaves=st.num_leaves
                    + valid.sum().astype(jnp.int32),
                    done=st.done,
                    is_cat_node=st.is_cat_node.at[node_sel].set(icat_k),
                    cat_rank=st.cat_rank.at[node_sel].set(rank_k),
                )

            return s + 1, lax.cond(can_split, do_split,
                                   lambda s: s._replace(done=jnp.bool_(True)),
                                   st)

        # while_loop, not a fixed trip count: a super-step splits only the
        # leaves that HAVE positive gain (chain-shaped trees take 1 split
        # per step, balanced trees ~K), so no static count below L-1 is
        # safe — and a fixed L-1 count makes balanced 255-leaf trees pay
        # ~(L-1)(1-1/K) dead steps, each copying the multi-MB carried
        # state through the cond's no-op branch.  The loop exits the
        # moment the budget is exhausted or no leaf can split; the step
        # counter ``s`` is carried for the bynode RNG stream.  As in the
        # strict grower, the bound is the TRACED actual budget.
        s_final, st = lax.while_loop(
            lambda c: (~c[1].done) & (c[1].num_leaves < limit), super_step,
            (jnp.int32(0), st))
        return TreeArrays(
            num_leaves=st.num_leaves,
            split_feature=st.split_feature[:L - 1],
            threshold_bin=st.threshold_bin[:L - 1],
            default_left=st.default_left[:L - 1],
            left_child=st.left_child[:L - 1],
            right_child=st.right_child[:L - 1],
            split_gain=st.split_gain[:L - 1],
            leaf_value=st.leaf_value[:L],
            leaf_weight=st.leaf_weight[:L],
            leaf_count=st.leaf_count[:L],
            internal_value=st.internal_value[:L - 1],
            internal_weight=st.internal_weight[:L - 1],
            internal_count=st.internal_count[:L - 1],
            leaf_depth=st.leaf_depth[:L],
            leaf_of_row=st.leaf_of_row,
            is_cat_node=st.is_cat_node[:L - 1],
            cat_rank=st.cat_rank[:L - 1],
            n_steps=s_final,
        )

    fn = grow_tree_batched if K > 1 else grow_tree
    if not jit:
        return fn
    # process-level sharing: identical configs (common after leaf-budget
    # bucketing) reuse ONE jitted callable, so a num_leaves sweep inside
    # a bucket traces the grower exactly once per process.  Distribution
    # hooks are callables (unkeyable) -> those growers jit privately.
    key = None
    if all(h is None for h in (hist_reduce, hist_view, hist_expand,
                               select_best, mono_view, count_reduce,
                               sum_reduce, scale_reduce, row_offset)):
        key = _grower_key(dict(
            L=L, B=B, K=K, padded=padded, params=params,
            hist_overlap=hist_overlap,
            max_depth=max_depth, block_rows=block_rows, subtract=subtract,
            gather=gather, min_gather_rows=min_gather_rows, efb=efb,
            gain_scale=gain_scale, extra_trees=extra_trees,
            extra_seed=extra_seed, mono=mono, mono_penalty=mono_penalty,
            interaction_groups=interaction_groups, bynode_frac=bynode_frac,
            bynode_seed=bynode_seed, cegb=cegb, quant=quant,
            # unpadded growers bake the budget as the default limit, so
            # the key must carry it; padded ones take it per call
            L_default=None if padded else L_req))
    if key is None:
        return jax.jit(fn)
    with _SHARED_GROWERS_LOCK:
        shared = _SHARED_GROWERS.get(key)
        if shared is None:
            shared = jax.jit(fn)
            _SHARED_GROWERS[key] = shared
            while len(_SHARED_GROWERS) > _SHARED_GROWERS_MAX:
                _SHARED_GROWERS.popitem(last=False)
        else:
            _SHARED_GROWERS.move_to_end(key)
    return shared


def make_shadow_grower(**kwargs):
    """An INDEPENDENTLY-jitted twin of ``make_grower(**kwargs)`` for the
    computation-integrity layer (lightgbm_tpu/integrity.py): same
    logical math, but a separate ``jax.jit`` wrapper that deliberately
    bypasses the ``_SHARED_GROWERS`` memo — so the shadow program is a
    second trace AND a second compiled executable, and a silently wrong
    answer must reproduce across two distinct programs to evade the
    compare.  The extra trace is intentional and accounted in
    tools/retrace_budget (sites fire only when integrity_check_freq>0).
    """
    return jax.jit(make_grower(**dict(kwargs, jit=False)))
