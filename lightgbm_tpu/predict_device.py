"""Device-side tree traversal over binned data.

Used for validation-set score updates each iteration (the reference's
``ScoreUpdater::AddScore(tree)`` path, score_updater.hpp:21-128) and for
batched leaf prediction.  The traversal is a fixed-depth ``fori_loop`` of
vectorized gathers: every row walks one level per step; finished rows carry
their (negative-encoded) leaf id unchanged — static shapes, no divergence.

Numerical and categorical decisions share one predicate: per-node
``cat_rank`` maps bin -> decision rank (identity for numerical nodes), go
left iff rank <= threshold (see ops/split.py SplitResult).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .utils.compile_cache import trace_event
from .utils.shapes import round_up_pow2  # noqa: F401  (shared policy;
#                                          re-exported for existing users)


@functools.partial(jax.jit, static_argnames=("steps",))
def traverse_tree_binned(binned, split_feature, threshold_bin, default_left,
                         left_child, right_child, na_bin, is_cat_node,
                         cat_rank, efb_maps=None, *, steps: int):
    """Return the leaf index for every row of ``binned`` [N, F].

    ``efb_maps``: optional (group_of_feat, off_of_feat, nbm1_of_feat) device
    arrays when ``binned`` is the EFB-grouped matrix [N, G] (efb.py) — the
    gathered group bin is unmapped to the feature's own bin space."""
    trace_event("traverse_tree")
    n = binned.shape[0]
    from .obs.flops import note_traced, traverse_flops_bytes
    note_traced("traverse_tree", *traverse_flops_bytes(
        n, 1, steps, binned.shape[1],
        binned_itemsize=getattr(binned.dtype, "itemsize", 1)),
        phase="score", cadence="iter")
    node = jnp.zeros(n, jnp.int32)

    def body(_, node):
        internal = node >= 0
        nid = jnp.maximum(node, 0)
        f = split_feature[nid]
        if efb_maps is None:
            col = f
        else:
            col = efb_maps[0][f]
        v = jnp.take_along_axis(binned, col[:, None].astype(jnp.int32),
                                axis=1)[:, 0].astype(jnp.int32)
        if efb_maps is not None:
            off, nbm1 = efb_maps[1][f], efb_maps[2][f]
            v = jnp.where(off < 0, v,
                          jnp.where((v >= off) & (v < off + nbm1),
                                    v - off + 1, 0))
        nb = na_bin[f]
        is_na = (nb >= 0) & (v == nb) & (~is_cat_node[nid])
        rank = cat_rank[nid, v]
        go_left = jnp.where(is_na, default_left[nid], rank <= threshold_bin[nid])
        nxt = jnp.where(go_left, left_child[nid], right_child[nid])
        return jnp.where(internal, nxt, node)

    node = lax.fori_loop(0, steps, body, node)
    return (~node).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("steps",))
def add_tree_score(score, binned, split_feature, threshold_bin, default_left,
                   left_child, right_child, na_bin, is_cat_node, cat_rank,
                   leaf_value, weight, efb_maps=None, *, steps: int):
    """score += weight * tree(binned) — incremental ScoreUpdater step."""
    trace_event("add_tree_score")
    leaf = traverse_tree_binned(binned, split_feature, threshold_bin,
                                default_left, left_child, right_child,
                                na_bin, is_cat_node, cat_rank, efb_maps,
                                steps=steps)
    return score + weight * jnp.take(leaf_value, leaf)


# (round_up_pow2 moved to utils/shapes.py — the ONE bucketing policy
# shared by serving batches, validation rows and the grower leaf budget
# — and re-imported above so existing callers keep working.)


# ---------------------------------------------------------------------------
# Whole-ensemble traversal (serving / bucketed Booster.predict)
# ---------------------------------------------------------------------------

# traces of the forest-traversal program, incremented while TRACING only
# (the increment is a Python side effect, so it runs once per new jit
# cache entry, never per execution).  tests/test_serve.py reads this to
# prove the bucketed compile cache bounds XLA compiles.
_FOREST_TRACES = [0]


def forest_trace_count() -> int:
    """Number of times ``traverse_forest_binned`` has been traced (==
    compiled) in this process."""
    return _FOREST_TRACES[0]


def _forest_walk(binned, split_feature, threshold_bin, default_left,
                 left_child, right_child, na_bin, is_cat_node, cat_index,
                 cat_table, steps: int):
    """Shared traced body of the whole-forest traversal (no counters —
    callers own trace accounting).  The node tables may arrive in
    PACKED narrow dtypes (serve/engine.py ``serve_packed_tables``:
    thresholds uint8/uint16 by bin count, children int8/int16 by node
    count); every gathered value is widened to int32 before compare /
    index use, so packing shrinks HBM traffic without touching the
    decision arithmetic."""
    n = binned.shape[0]
    t = split_feature.shape[0]
    node = jnp.zeros((n, t), jnp.int32)
    tree_ids = jnp.arange(t, dtype=jnp.int32)[None, :]

    def body(_, node):
        internal = node >= 0
        nid = jnp.maximum(node, 0)
        f = split_feature[tree_ids, nid].astype(jnp.int32)     # [N, T]
        v = jnp.take_along_axis(binned, f, axis=1) \
            .astype(jnp.int32)                                 # [N, T]
        cat = is_cat_node[tree_ids, nid]
        nb = na_bin[f]
        is_na = (nb >= 0) & (v == nb) & (~cat)
        ci = cat_index[tree_ids, nid].astype(jnp.int32)
        rank = jnp.where(cat, cat_table[ci, v].astype(jnp.int32), v)
        go_left = jnp.where(
            is_na, default_left[tree_ids, nid],
            rank <= threshold_bin[tree_ids, nid].astype(jnp.int32))
        nxt = jnp.where(go_left,
                        left_child[tree_ids, nid].astype(jnp.int32),
                        right_child[tree_ids, nid].astype(jnp.int32))
        return jnp.where(internal, nxt, node)

    node = lax.fori_loop(0, steps, body, node)
    return (~node).astype(jnp.int32)


def traverse_forest_binned(binned, split_feature, threshold_bin,
                           default_left, left_child, right_child, na_bin,
                           is_cat_node, cat_index, cat_table, *, steps: int):
    """Leaf index for every (row, tree) pair: ``binned`` [N, F] ->
    [N, T] int32.

    The whole-ensemble counterpart of :func:`traverse_tree_binned` used
    by ``serve/engine.py``: per-node arrays are stacked [T, M] (M = max
    nodes per tree, padded), every row walks all T trees one level per
    step, finished rows carry their ~leaf id unchanged.  Categorical
    decisions go through a compact rank table — ``cat_index`` maps a
    node to its row of ``cat_table`` [C, B] (0 = category in the node's
    left set, 1 = not), numerical nodes use the bin id itself as the
    rank (model-derived binning makes ``bin(x) <= threshold_bin`` exact,
    see serve/engine.py).  Call under ``jax.jit`` with ``steps`` static;
    a module-level trace counter records each compilation.
    """
    _FOREST_TRACES[0] += 1
    trace_event("forest")
    n = binned.shape[0]
    t = split_feature.shape[0]
    from .obs.flops import note_traced, traverse_flops_bytes
    note_traced("forest", *traverse_flops_bytes(
        n, t, steps, binned.shape[1],
        binned_itemsize=getattr(binned.dtype, "itemsize", 1)),
        phase="serve", cadence="iter")
    return _forest_walk(binned, split_feature, threshold_bin,
                        default_left, left_child, right_child, na_bin,
                        is_cat_node, cat_index, cat_table, steps)


def bin_rows_device(x, thresholds, na_bin, zero_bin):
    """On-device model-derived binning of raw NUMERICAL rows (f32).

    ``thresholds`` [F, B] is each feature's sorted split-threshold table
    padded with +inf; the bin id is the count of thresholds < x, i.e.
    ``searchsorted(T_f, x, 'left')`` as a comparison-sum.  NaNs map to
    ``na_bin[f]`` when the feature reserves one (missing-type NaN nodes)
    and to ``zero_bin[f]`` (the bin of 0.0) otherwise — the reference
    Predictor's NaN->0 conversion.  f32 comparisons: rows whose value
    ties a threshold within f32 rounding may bin differently from the
    exact host (f64) path — this feeds the opt-in approximate
    ``serve_device_binning`` mode only (docs/Serving.md)."""
    xf = x.astype(jnp.float32)
    isnan = jnp.isnan(xf)
    bins = jnp.sum(xf[:, :, None] > thresholds[None, :, :],
                   axis=-1).astype(jnp.int32)
    fallback = jnp.where(na_bin >= 0, na_bin, zero_bin)[None, :]
    return jnp.where(isnan, fallback, bins)


def bin_rows_device_full(x, thresholds, na_bin, zero_bin, cat_values,
                         cat_len):
    """On-device model-derived binning covering BOTH feature kinds.

    Numerical features bin exactly like :func:`bin_rows_device`.
    Categorical features (``cat_len[f] > 0``) reproduce the host
    ``engine.bin_rows`` mapping in integer-exact arithmetic:
    ``iv = trunc(x)`` (NaN/inf -> -1, the reference
    CategoricalDecision input mapping), position = count of known
    categories < iv, and the position is kept only when the category
    at it matches ``iv`` — otherwise the unseen-category sentinel bin
    ``cat_len[f]``.  ``cat_values`` [F, C] holds each categorical
    feature's sorted known categories as f32 (padded +inf; exact for
    |category| < 2^24 — the engine refuses device binning beyond
    that).  f32 rounding can only move a NUMERICAL threshold tie; the
    categorical compare is integer-exact."""
    xf = x.astype(jnp.float32)
    isnan = jnp.isnan(xf)
    bins = jnp.sum(xf[:, :, None] > thresholds[None, :, :],
                   axis=-1).astype(jnp.int32)
    fallback = jnp.where(na_bin >= 0, na_bin, zero_bin)[None, :]
    bins = jnp.where(isnan, fallback, bins)
    if cat_values.shape[1] > 0:
        iv = jnp.where(jnp.isfinite(xf), jnp.trunc(xf), -1.0)
        pos = jnp.sum(cat_values[None, :, :] < iv[:, :, None],
                      axis=-1).astype(jnp.int32)
        posc = jnp.clip(pos, 0, jnp.maximum(cat_len - 1, 0)[None, :])
        feat_ids = jnp.arange(xf.shape[1], dtype=jnp.int32)[None, :]
        hit = cat_values[feat_ids, posc]                    # [N, F]
        cat_bin = jnp.where(hit == iv, posc, cat_len[None, :])
        bins = jnp.where((cat_len > 0)[None, :], cat_bin, bins)
    return bins


# ---------------------------------------------------------------------------
# Fused device-resident serve path (one jit: bin -> traverse -> accumulate
# -> transform; serve/engine.py fused_predict)
# ---------------------------------------------------------------------------

# traces of the fused serve program, counted at trace time like
# _FOREST_TRACES — tests and tools/check_retraces.py pin the budget
_FUSED_TRACES = [0]


def fused_trace_count() -> int:
    """Number of times ``fused_forest_predict`` has been traced (==
    compiled) in this process."""
    return _FUSED_TRACES[0]


def fused_forest_predict(x, thresholds, na_bin, zero_bin, cat_values,
                         cat_len, split_feature, threshold_bin,
                         default_left, left_child, right_child,
                         is_cat_node, cat_index, cat_table, leaf_value,
                         tree_weight, avg_denom, *, steps: int,
                         num_class: int, transform):
    """The device-resident serve fast path: raw rows [N, F] -> final
    scores, ONE program.

    Bins on device (:func:`bin_rows_device_full`, f32), walks the whole
    forest (:func:`_forest_walk` over the packed SoA tables), gathers
    each tree's leaf value (``leaf_value`` [T, L] f32), multiplies by
    ``tree_weight`` [T] (DART/RF weights), and accumulates per class
    IN TREE ORDER with a sequential ``fori_loop`` — the accumulation
    order is part of the path's parity contract (serve/engine.py
    ``_fused_reference`` recomputes exactly these f32 ops on the host
    for the self-check).  ``avg_denom`` (f32 scalar, 1.0 when not
    averaging) applies RF output averaging; ``transform`` (static; a
    shared per-objective-config callable, None = raw) applies the
    objective's output conversion.  The caller fetches ONLY the
    returned [N] / [N, num_class] scores — the single host<->device
    sync of a fused serve batch (tools/sync_allowlist.txt)."""
    _FUSED_TRACES[0] += 1
    trace_event("serve_fused")
    n, f = x.shape
    t = split_feature.shape[0]
    from .obs.flops import fused_forest_flops_bytes, note_traced
    note_traced("serve_fused", *fused_forest_flops_bytes(
        n, t, steps, f, thresholds.shape[1], num_class,
        table_itemsize=getattr(threshold_bin.dtype, "itemsize", 4)),
        phase="serve", cadence="iter")
    binned = bin_rows_device_full(x, thresholds, na_bin, zero_bin,
                                  cat_values, cat_len)
    leaves = _forest_walk(binned, split_feature, threshold_bin,
                          default_left, left_child, right_child, na_bin,
                          is_cat_node, cat_index, cat_table, steps)
    tree_ids = jnp.arange(t, dtype=jnp.int32)[None, :]
    vals = leaf_value[tree_ids, leaves]                        # [N, T]
    # barrier: keep the weight multiply a distinct op from the loop's
    # adds so XLA cannot FMA-contract across them — the host oracle
    # recomputes mul-then-add as separate IEEE f32 ops
    prods = lax.optimization_barrier(vals * tree_weight[None, :])
    k = max(1, int(num_class))
    score = jnp.zeros((n, k), jnp.float32)

    def body(ti, s):
        return s.at[:, ti % k].add(prods[:, ti])

    score = lax.fori_loop(0, t, body, score)
    score = score / avg_denom
    out = score if k > 1 else score[:, 0]
    if transform is not None:
        out = transform(out)
    return out
