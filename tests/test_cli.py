"""CLI / data-io / consistency tests.

Mirrors the reference's CLI-vs-Python parity suite
(tests/python_package_test/test_consistency.py) and the cpp CLI conf runs
(tests/cpp_tests/test.py pattern): train via the config-file CLI, predict,
and compare against the Python API on the same data.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.cli import run as cli_run
from lightgbm_tpu.data_io import detect_format, load_text
from lightgbm_tpu.native import native_parse_csv


@pytest.fixture()
def csv_data(tmp_path):
    rs = np.random.RandomState(0)
    n = 1200
    x = rs.randn(n, 5)
    y = ((x[:, 0] + x[:, 1] > 0)).astype(np.float32)
    data = np.column_stack([y, x])
    path = str(tmp_path / "train.csv")
    np.savetxt(path, data, delimiter=",", fmt="%.6f")
    return path, x, y


class TestDataIO:
    def test_csv_roundtrip(self, csv_data):
        path, x, y = csv_data
        xl, yl = load_text(path)
        np.testing.assert_allclose(xl, x, atol=1e-5)
        np.testing.assert_allclose(yl, y, atol=1e-6)

    def test_native_parser_matches_numpy(self, csv_data):
        path, x, y = csv_data
        arr = native_parse_csv(path, ",", False)
        if arr is None:
            pytest.skip("native parser unavailable")
        ref = np.genfromtxt(path, delimiter=",")
        np.testing.assert_allclose(arr, ref, atol=1e-12)

    def test_native_parser_missing_values(self, tmp_path):
        p = str(tmp_path / "m.csv")
        with open(p, "w") as f:
            f.write("1.5,,3\n,2.5,na\n")
        arr = native_parse_csv(p, ",", False)
        if arr is None:
            pytest.skip("native parser unavailable")
        assert arr.shape == (2, 3)
        assert arr[0, 0] == 1.5 and np.isnan(arr[0, 1]) and arr[0, 2] == 3
        assert np.isnan(arr[1, 0]) and arr[1, 1] == 2.5 and np.isnan(arr[1, 2])

    def test_tsv_detect(self, tmp_path):
        p = str(tmp_path / "d.tsv")
        with open(p, "w") as f:
            f.write("1\t2\t3\n4\t5\t6\n")
        assert detect_format(p) == "tsv"

    def test_libsvm(self, tmp_path):
        p = str(tmp_path / "d.svm")
        with open(p, "w") as f:
            f.write("1 0:1.5 3:2.0\n0 1:0.5\n")
        x, y = load_text(p)
        assert x.shape == (2, 4)
        assert x[0, 0] == 1.5 and x[0, 3] == 2.0 and x[1, 1] == 0.5
        np.testing.assert_array_equal(y, [1, 0])


class TestCLI:
    def test_train_predict_consistency(self, csv_data, tmp_path):
        path, x, y = csv_data
        conf = str(tmp_path / "train.conf")
        model_path = str(tmp_path / "model.txt")
        with open(conf, "w") as f:
            f.write(f"""
task = train
objective = binary
data = {path}
num_trees = 10
num_leaves = 7
max_bin = 31
min_data_in_leaf = 5
output_model = {model_path}
verbosity = 0
""")
        assert cli_run([f"config={conf}"]) == 0
        assert os.path.exists(model_path)

        # predict task
        out_path = str(tmp_path / "preds.txt")
        assert cli_run([
            "task=predict", f"data={path}", f"input_model={model_path}",
            f"output_result={out_path}"]) == 0
        cli_preds = np.loadtxt(out_path)

        # python API on same data must match (consistency suite pattern)
        bst = lgb.Booster(model_file=model_path)
        py_preds = bst.predict(x)
        np.testing.assert_allclose(cli_preds, py_preds, rtol=1e-5, atol=1e-6)
        # and the model must actually be good
        acc = ((py_preds > 0.5) == y).mean()
        assert acc > 0.9

    def test_cli_overrides_config_file(self, csv_data, tmp_path):
        path, _, _ = csv_data
        conf = str(tmp_path / "c.conf")
        model_path = str(tmp_path / "m.txt")
        with open(conf, "w") as f:
            f.write(f"task = train\nobjective = binary\ndata = {path}\n"
                    f"num_trees = 3\noutput_model = {model_path}\n"
                    f"max_bin = 31\nverbosity = 0\n")
        assert cli_run([f"config={conf}", "num_trees=5"]) == 0
        bst = lgb.Booster(model_file=model_path)
        assert bst.num_trees() == 5

    def test_refit_task(self, csv_data, tmp_path):
        path, x, y = csv_data
        model_path = str(tmp_path / "m.txt")
        refit_path = str(tmp_path / "m2.txt")
        cli_run(["task=train", "objective=binary", f"data={path}",
                 "num_trees=5", "num_leaves=7", "max_bin=31",
                 f"output_model={model_path}", "verbosity=0"])
        assert cli_run(["task=refit", f"data={path}",
                        f"input_model={model_path}",
                        f"output_model={refit_path}"]) == 0
        bst = lgb.Booster(model_file=refit_path)
        p = bst.predict(x)
        assert ((p > 0.5) == y).mean() > 0.85

    def test_save_binary_task(self, csv_data, tmp_path):
        path, _, _ = csv_data
        assert cli_run(["task=save_binary", f"data={path}", "max_bin=31"]) == 0
        ds = lgb.Dataset.load_binary(path + ".bin.npz")
        assert ds.num_data == 1200
