"""Monotone + interaction constraint tests
(test_engine.py:1508-1670 monotone constraints analog, SURVEY.md §4)."""

import pytest

pytestmark = pytest.mark.slow   # exhaustive sweep tier (docs/Testing.md)


import numpy as np

import lightgbm_tpu as lgb


def _mono_data(n=4000, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.rand(n, 3)
    # y increasing in x0, decreasing in x1, free in x2
    y = (3.0 * x[:, 0] - 2.0 * x[:, 1] + np.sin(6.28 * x[:, 2])
         + 0.2 * rs.randn(n)).astype(np.float32)
    return x, y


def _check_monotone(bst, feature, sign, n_checks=50, seed=1):
    """Sweep the constrained feature on fixed rows; predictions must be
    monotone in the swept direction."""
    rs = np.random.RandomState(seed)
    base = rs.rand(n_checks, 3)
    grid = np.linspace(0.0, 1.0, 30)
    ok = True
    for i in range(n_checks):
        rows = np.repeat(base[i][None, :], len(grid), axis=0)
        rows[:, feature] = grid
        pred = bst.predict(rows)
        diffs = np.diff(pred)
        if sign > 0:
            ok &= bool((diffs >= -1e-9).all())
        else:
            ok &= bool((diffs <= 1e-9).all())
    return ok


class TestMonotone:
    def test_increasing_decreasing(self):
        x, y = _mono_data()
        p = {"objective": "regression", "num_leaves": 31, "max_bin": 63,
             "min_data_in_leaf": 10, "monotone_constraints": [1, -1, 0]}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=30)
        assert _check_monotone(bst, 0, +1), "predictions not increasing in x0"
        assert _check_monotone(bst, 1, -1), "predictions not decreasing in x1"
        # still a useful model
        mse = np.mean((bst.predict(x) - y) ** 2)
        assert mse < 0.5 * np.var(y)

    def test_intermediate_method(self):
        """'intermediate' (IntermediateLeafConstraints,
        monotone_constraints.hpp:514): still monotone, and at least as good
        a fit as 'basic' (it is strictly less conservative)."""
        x, y = _mono_data(seed=5)
        base = {"objective": "regression", "num_leaves": 31, "max_bin": 63,
                "min_data_in_leaf": 10, "monotone_constraints": [1, -1, 0]}
        bst_i = lgb.train({**base, "monotone_constraints_method": "intermediate"},
                          lgb.Dataset(x, label=y), num_boost_round=30)
        assert _check_monotone(bst_i, 0, +1)
        assert _check_monotone(bst_i, 1, -1)
        bst_b = lgb.train({**base, "monotone_constraints_method": "basic"},
                          lgb.Dataset(x, label=y), num_boost_round=30)
        mse_i = np.mean((bst_i.predict(x) - y) ** 2)
        mse_b = np.mean((bst_b.predict(x) - y) ** 2)
        assert mse_i <= mse_b * 1.05, (mse_i, mse_b)

    def test_monotone_penalty(self):
        """monotone_penalty discourages monotone-feature splits near the
        root (ComputeMonotoneSplitGainPenalty, monotone_constraints.hpp:355)."""
        x, y = _mono_data(seed=7)
        base = {"objective": "regression", "num_leaves": 15, "max_bin": 63,
                "min_data_in_leaf": 10, "monotone_constraints": [1, -1, 0]}
        bst = lgb.train({**base, "monotone_penalty": 2.0},
                        lgb.Dataset(x, label=y), num_boost_round=10)
        assert _check_monotone(bst, 0, +1)
        # with a large penalty, depth-0/1 splits should avoid monotone feats
        for t in bst.trees:
            if t.num_nodes() > 0:
                assert int(t.split_feature[0]) == 2, \
                    f"root split used monotone feature {t.split_feature[0]}"

    def test_unconstrained_violates(self):
        # sanity: without constraints the sweep check fails (data is noisy)
        x, y = _mono_data(seed=3)
        p = {"objective": "regression", "num_leaves": 31, "max_bin": 63,
             "min_data_in_leaf": 2}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=30)
        assert not _check_monotone(bst, 2, +1)


class TestInteraction:
    def test_constraint_respected(self):
        rs = np.random.RandomState(0)
        n = 3000
        x = rs.randn(n, 4)
        y = (x[:, 0] * x[:, 1] + x[:, 2] + 0.1 * rs.randn(n)).astype(np.float32)
        p = {"objective": "regression", "num_leaves": 15, "max_bin": 63,
             "min_data_in_leaf": 5,
             "interaction_constraints": "[0,1],[2,3]"}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=10)
        # every path may only mix features within one group
        for t in bst.trees:
            n_nodes = t.num_nodes()
            if n_nodes == 0:
                continue
            # walk all root->node paths and collect features
            def paths(node, feats):
                if node < 0:
                    yield feats
                    return
                nf = feats | {int(t.split_feature[node])}
                yield from paths(t.left_child[node], nf)
                yield from paths(t.right_child[node], nf)
            for feats in paths(0, set()):
                assert feats <= {0, 1} or feats <= {2, 3}, \
                    f"path mixes groups: {feats}"

    def test_feature_fraction_bynode(self, binary_data):
        x, y = binary_data
        p = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
             "feature_fraction_bynode": 0.5}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=10)
        from lightgbm_tpu.metrics import _auc
        assert _auc(y, bst.predict(x, raw_score=True), None) > 0.9


class TestMonotoneMethodSweep:
    """VERDICT r2 task 8: property test across every
    monotone_constraints_method — zero violations on random data.
    'advanced' is now a real implementation (per-threshold neighbor
    bounds from leaf boxes, grower_partitioned._advanced_bounds), not a
    fallback."""

    @pytest.mark.parametrize("method", ["basic", "intermediate", "advanced"])
    @pytest.mark.parametrize("seed", [0, 7])
    def test_zero_violations(self, method, seed):
        x, y = _mono_data(seed=seed)
        p = {"objective": "regression", "num_leaves": 31, "max_bin": 63,
             "min_data_in_leaf": 10, "monotone_constraints": [1, -1, 0],
             "monotone_constraints_method": method, "verbosity": -1}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=25)
        assert _check_monotone(bst, 0, +1), f"{method}: not increasing in x0"
        assert _check_monotone(bst, 1, -1), f"{method}: not decreasing in x1"

    def test_advanced_with_missing_values(self):
        """NA rows route by default_left regardless of the threshold, so
        the advanced leaf boxes widen over the NA bin — without that the
        overlap filter can DROP constraints and violate monotonicity."""
        x, y = _mono_data(seed=5)
        rs = np.random.RandomState(5)
        x = x.copy()
        x[rs.rand(*x.shape) < 0.1] = np.nan
        p = {"objective": "regression", "num_leaves": 31, "max_bin": 63,
             "min_data_in_leaf": 10, "monotone_constraints": [1, -1, 0],
             "monotone_constraints_method": "advanced", "verbosity": -1}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=25)
        assert _check_monotone(bst, 0, +1)
        assert _check_monotone(bst, 1, -1)

    def test_advanced_at_least_as_accurate(self):
        """The point of 'advanced' (monotone_constraints.hpp:856): only
        constrain where regions actually interact, recovering gain the
        midpoint method forfeits — train loss should not be worse than
        'basic' by more than noise."""
        x, y = _mono_data(seed=3)
        losses = {}
        for method in ("basic", "advanced"):
            p = {"objective": "regression", "num_leaves": 31, "max_bin": 63,
                 "min_data_in_leaf": 10, "monotone_constraints": [1, -1, 0],
                 "monotone_constraints_method": method, "verbosity": -1}
            bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=25)
            pred = bst.predict(x)
            losses[method] = float(np.mean((pred - y) ** 2))
        assert losses["advanced"] <= losses["basic"] * 1.05, losses


class TestMonotoneMasked:
    """Monotone 'basic' on the one-program masked grower (device-resident
    [L] lo/hi range vectors, grower.py) — the reference supports monotone
    in ALL parallel learners (monotone_constraints.hpp), so the masked /
    data-parallel paths must honor it too, not just the host-orchestrated
    partitioned learner."""

    P = {"objective": "regression", "num_leaves": 31, "max_bin": 63,
         "min_data_in_leaf": 10, "monotone_constraints": [1, -1, 0],
         "verbose": -1}

    def test_masked_zero_violations(self):
        x, y = _mono_data()
        bst = lgb.train({**self.P, "tpu_learner": "masked"},
                        lgb.Dataset(x, label=y), num_boost_round=30)
        assert bst._model._learner_kind == "masked"
        assert _check_monotone(bst, 0, +1)
        assert _check_monotone(bst, 1, -1)

    def test_masked_batched_zero_violations(self):
        x, y = _mono_data()
        bst = lgb.train({**self.P, "tpu_learner": "masked",
                         "split_batch": 4},
                        lgb.Dataset(x, label=y), num_boost_round=30)
        assert _check_monotone(bst, 0, +1)
        assert _check_monotone(bst, 1, -1)

    def test_masked_fused_zero_violations(self):
        x, y = _mono_data()
        bst = lgb.train({**self.P, "tpu_learner": "masked",
                         "fused_chunk": 10},
                        lgb.Dataset(x, label=y), num_boost_round=30)
        assert _check_monotone(bst, 0, +1)

    def test_masked_matches_partitioned(self):
        """Same 'basic' semantics on both learners -> identical trees."""
        x, y = _mono_data()
        b_m = lgb.train({**self.P, "tpu_learner": "masked"},
                        lgb.Dataset(x, label=y), num_boost_round=10)
        b_p = lgb.train({**self.P, "tpu_learner": "partitioned"},
                        lgb.Dataset(x, label=y), num_boost_round=10)
        assert len(b_m.trees) == len(b_p.trees)
        for tm, tp in zip(b_m.trees, b_p.trees):
            np.testing.assert_array_equal(tm.split_feature, tp.split_feature)
            np.testing.assert_allclose(tm.leaf_value, tp.leaf_value,
                                       rtol=1e-5, atol=1e-7)

    def test_masked_penalty(self):
        x, y = _mono_data()
        bst = lgb.train({**self.P, "tpu_learner": "masked",
                         "monotone_penalty": 2.0},
                        lgb.Dataset(x, label=y), num_boost_round=20)
        assert _check_monotone(bst, 0, +1)

    @pytest.mark.skipif(
        __import__("jax").device_count() < 8,
        reason="needs the 8-device virtual mesh")
    def test_data_parallel_monotone(self):
        x, y = _mono_data()
        b_s = lgb.train({**self.P, "tpu_learner": "masked"},
                        lgb.Dataset(x, label=y), num_boost_round=10)
        b_d = lgb.train({**self.P, "tree_learner": "data"},
                        lgb.Dataset(x, label=y), num_boost_round=10)
        assert b_d._model._dist == "data"
        assert _check_monotone(b_d, 0, +1)
        assert _check_monotone(b_d, 1, -1)
        for tm, tp in zip(b_s.trees, b_d.trees):
            np.testing.assert_array_equal(tm.split_feature, tp.split_feature)

    def test_feature_parallel_monotone_refused(self):
        x, y = _mono_data()
        with pytest.raises(ValueError, match="tree_learner=feature"):
            lgb.train({**self.P, "tree_learner": "feature"},
                      lgb.Dataset(x, label=y), num_boost_round=2)

    def test_intermediate_still_partitioned(self):
        """Non-basic methods keep the host-orchestrated learner."""
        x, y = _mono_data()
        bst = lgb.train({**self.P,
                         "monotone_constraints_method": "intermediate"},
                        lgb.Dataset(x, label=y), num_boost_round=5)
        assert bst._model._learner_kind == "partitioned"
        assert _check_monotone(bst, 0, +1)


class TestInteractionMasked:
    """Interaction constraints + feature_fraction_bynode on the masked
    grower (per-leaf [L, F] feature-mask state / in-graph subset draws,
    grower.py) — previously host-orchestrated only."""

    def _paths_ok(self, bst, groups):
        for t in bst.trees:
            if t.num_nodes() == 0:
                continue

            def paths(node, feats):
                if node < 0:
                    yield feats
                    return
                nf = feats | {int(t.split_feature[node])}
                yield from paths(t.left_child[node], nf)
                yield from paths(t.right_child[node], nf)
            for feats in paths(0, set()):
                assert any(feats <= g for g in groups), \
                    f"path mixes groups: {feats}"

    def test_masked_interaction_respected(self):
        rs = np.random.RandomState(0)
        n = 3000
        x = rs.randn(n, 4)
        y = (x[:, 0] * x[:, 1] + x[:, 2] + 0.1 * rs.randn(n)).astype(np.float32)
        p = {"objective": "regression", "num_leaves": 15, "max_bin": 63,
             "min_data_in_leaf": 5, "tpu_learner": "masked",
             "interaction_constraints": "[0,1],[2,3]", "verbose": -1}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=10)
        assert bst._model._learner_kind == "masked"
        self._paths_ok(bst, [{0, 1}, {2, 3}])

    def test_masked_interaction_batched_and_fused(self):
        rs = np.random.RandomState(1)
        n = 3000
        x = rs.randn(n, 4)
        y = (x[:, 0] * x[:, 1] + x[:, 2] + 0.1 * rs.randn(n)).astype(np.float32)
        p = {"objective": "regression", "num_leaves": 15, "max_bin": 63,
             "min_data_in_leaf": 5, "tpu_learner": "masked",
             "interaction_constraints": "[0,1],[2,3]", "verbose": -1,
             "split_batch": 4, "fused_chunk": 5}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=10)
        self._paths_ok(bst, [{0, 1}, {2, 3}])

    def test_overlapping_groups(self):
        # ADVICE r3 (medium): with overlapping groups [0,1],[1,2],[0,2] a
        # progressive intersection allow[0]&allow[1] = {0,1,2} would let a
        # path use all three features — a subset of NO group.  GetByNode
        # subset-containment semantics (col_sampler.hpp:91-111) forbid it.
        rs = np.random.RandomState(2)
        n = 4000
        x = rs.randn(n, 3)
        y = (x[:, 0] * x[:, 1] + x[:, 1] * x[:, 2] + x[:, 0] * x[:, 2]
             + 0.1 * rs.randn(n)).astype(np.float32)
        groups = [{0, 1}, {1, 2}, {0, 2}]
        for extra in ({}, {"tpu_learner": "masked"},
                      {"tpu_learner": "masked", "split_batch": 4,
                       "fused_chunk": 5}):
            p = {"objective": "regression", "num_leaves": 31, "max_bin": 63,
                 "min_data_in_leaf": 2, "verbose": -1,
                 "interaction_constraints": "[0,1],[1,2],[0,2]", **extra}
            bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=8)
            deep = max(len(f) for t in bst.trees
                       for f in self._iter_paths(t))
            assert deep >= 2, "test setup: trees should mix two features"
            self._paths_ok(bst, groups)

    def test_unlisted_feature_never_used(self):
        # a feature in no constraint group is unusable (root branch is
        # empty -> allowed = union of all groups, col_sampler.hpp:99-100)
        rs = np.random.RandomState(3)
        n = 3000
        x = rs.randn(n, 4)
        y = (2.0 * x[:, 3] + 0.5 * x[:, 0] + 0.1 * rs.randn(n)) \
            .astype(np.float32)   # the EXCLUDED feature is the strongest
        for extra in ({}, {"tpu_learner": "masked"}):
            p = {"objective": "regression", "num_leaves": 15, "max_bin": 63,
                 "min_data_in_leaf": 5, "verbose": -1,
                 "interaction_constraints": "[0,1],[1,2]", **extra}
            bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=5)
            used = {int(f) for t in bst.trees
                    for f in t.split_feature[:t.num_nodes()]}
            assert 3 not in used, f"unlisted feature used ({extra})"

    @staticmethod
    def _iter_paths(t):
        if t.num_nodes() == 0:
            return
        def paths(node, feats):
            if node < 0:
                yield feats
                return
            nf = feats | {int(t.split_feature[node])}
            yield from paths(t.left_child[node], nf)
            yield from paths(t.right_child[node], nf)
        yield from paths(0, set())

    def test_masked_bynode(self, binary_data):
        x, y = binary_data
        p = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
             "feature_fraction_bynode": 0.5, "tpu_learner": "masked",
             "verbose": -1}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=10)
        assert bst._model._learner_kind == "masked"
        from lightgbm_tpu.metrics import _auc
        assert _auc(y, bst.predict(x, raw_score=True), None) > 0.9
        # bynode actually varies the chosen features across nodes: with
        # frac=0.5 of 20 features, a single tree using only the global
        # best feature everywhere is the degenerate failure
        feats = {int(f) for t in bst.trees
                 for f in np.asarray(t.split_feature)[:t.num_leaves - 1]}
        assert len(feats) > 3

    def test_masked_bynode_fused_equals_per_iter(self, binary_data):
        """bynode keys are derived from (seed, iteration, step, child)
        in-graph, so the fused scan reproduces the per-iteration stream."""
        x, y = binary_data
        p = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
             "feature_fraction_bynode": 0.5, "tpu_learner": "masked",
             "verbose": -1}
        b_it = lgb.train(dict(p, fused_chunk=0), lgb.Dataset(x, label=y),
                         num_boost_round=8)
        b_fu = lgb.train(dict(p, fused_chunk=4), lgb.Dataset(x, label=y),
                         num_boost_round=8)
        np.testing.assert_array_equal(b_it.predict(x), b_fu.predict(x))

    def test_dist_interaction_refused(self):
        rs = np.random.RandomState(2)
        x = rs.randn(400, 4)
        y = (x[:, 0] > 0).astype(np.float32)
        with pytest.raises(ValueError, match="interaction"):
            lgb.train({"objective": "binary", "tree_learner": "data",
                       "interaction_constraints": "[0,1],[2,3]",
                       "verbose": -1},
                      lgb.Dataset(x, label=y), num_boost_round=2)
