"""Jit-purity lint: host side effects inside traced bodies.

A ``jax.jit``-traced function body runs ONCE per trace, not once per
call — host-side effects inside it are silent correctness bugs of two
shapes: (1) side effects that fire at trace time and then never again
(``print``, ``time.*``, RNG, mutation of module globals), so steady
state silently diverges from the first call; (2) host ops applied to
TRACED values (``np.*`` on a tracer, bare ``float()`` / ``bool()``
coercions), which either raise ``TracerConversionError`` on an
untested path or — worse — silently constant-fold a value that should
be data-dependent.  The sync lint (check_syncs) already polices
``device_get``-style transfers tree-wide; this pass complements it by
walking every function REACHABLE inside a traced body and flagging
host-effect constructs there specifically.

Mechanics (AST, best-effort by design — a discipline gate, not a
verifier):

1. **Roots.**  Every ``jax.jit`` site in the package: ``@jax.jit`` /
   ``@functools.partial(jax.jit, ...)`` decorators, and ``jax.jit(f)``
   call arguments resolved through ``functools.partial(g, ...)``,
   ``shard_map(g, ...)`` wrappers, local ``f = ...`` assignments,
   ``self._method`` references and cross-module imports.
2. **Reachability.**  From the roots, any name referenced in a
   reachable function that resolves to a package-internal function
   (direct call, ``lax.fori_loop``/``scan``/``cond`` callback, nested
   closure) is reachable too.
3. **Findings** inside reachable functions: ``np.*`` calls (dtype
   constructors and ``iinfo``/``finfo`` excepted), ``time.*`` /
   ``random.*`` / ``np.random.*`` / ``os.*`` / ``open`` / ``print``
   calls, bare ``float()`` / ``bool()`` on non-literals, ``.item()`` /
   ``jax.device_get`` / ``block_until_ready`` (a sync INSIDE a traced
   body escapes the tracer, strictly worse than the tree-wide sync
   lint's concern), and mutation of module-level state (``global``
   declarations, subscript/attribute stores to module globals).
4. **Sanctioned trace-time accounting** is never flagged:
   ``utils.compile_cache.trace_event`` and ``obs.flops.note_traced``
   are DESIGNED to fire once per fresh trace (idempotent on retrace;
   the retrace lint counts on the former).
5. **Allowlist** ``tools/purity_allowlist.txt``:
   ``path | function.qualname | token | rationale`` (rationale
   MANDATORY — e.g. the module-level trace counters that exist to be
   a once-per-trace side effect).  Stale entries are errors.

Run via ``python tools/lint.py`` (tier-1), or standalone
(``python tools/analyze/check_purity.py``; exit 1 on findings).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Set, Tuple

if __package__:
    from . import lintlib
else:                                        # standalone execution
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import lintlib

REPO = lintlib.REPO
PACKAGE = lintlib.PACKAGE
ALLOWLIST = os.path.join(REPO, "tools", "purity_allowlist.txt")

# numpy attributes that are pure dtype/metadata constructors — fine at
# trace time (np.float32(0.5) makes a weakly-typed scalar constant)
_NP_ALLOWED = {"float16", "float32", "float64", "int8", "int16",
               "int32", "int64", "uint8", "uint16", "uint32", "uint64",
               "bool_", "dtype", "iinfo", "finfo"}

# modules whose CALLS inside a traced body are host effects
_EFFECT_MODULES = {"time", "random", "os", "shutil", "subprocess"}

# designed trace-time accounting: fires once per fresh trace on purpose
_SANCTIONED_CALLS = {"trace_event", "note_traced"}

_JIT_WRAPPERS = {"partial", "shard_map"}


def _dotted(rel: str) -> str:
    """Module file path (``pkg/sub/mod.py``) -> dotted module path."""
    mod = rel[:-3].replace(os.sep, ".").replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[:-len(".__init__")]
    return mod


class _Func:
    __slots__ = ("rel", "qual", "node", "env", "cls")

    def __init__(self, rel: str, qual: str, node, env: Dict[str, tuple],
                 cls: Optional[str]):
        self.rel, self.qual, self.node = rel, qual, node
        self.env = env          # visible name -> resolution target
        self.cls = cls          # enclosing class name (for self.X)


class _Index:
    def __init__(self) -> None:
        self.funcs: Dict[Tuple[str, str], _Func] = {}   # (dotted, qual)
        self.by_key: Dict[Tuple[str, str], _Func] = {}  # (rel, qual)
        self.module_globals: Dict[str, Set[str]] = {}
        # unresolved jit targets: (rel, name-to-resolve, env, cls)
        self.pending: List[Tuple[str, str, Dict[str, tuple],
                                 Optional[str]]] = []
        self.roots: List[_Func] = []


def _jit_ref(node: ast.AST) -> bool:
    """Whether ``node`` references jax.jit / jit."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return True
    return isinstance(node, ast.Name) and node.id == "jit"


def _is_jit_decorator(dec: ast.AST) -> bool:
    if _jit_ref(dec):
        return True
    if isinstance(dec, ast.Call):
        f = dec.func
        fname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if _jit_ref(f):
            return True
        if fname == "partial" and dec.args and _jit_ref(dec.args[0]):
            return True
    return False


def _jit_arg_name(arg: ast.AST) -> Optional[str]:
    """The name to resolve for a ``jax.jit(<arg>)`` target: 'f',
    'self.f', 'mod.f', unwrapping partial(...)/shard_map(...)."""
    if isinstance(arg, ast.Name):
        return arg.id
    if isinstance(arg, ast.Attribute) and isinstance(arg.value,
                                                     ast.Name):
        return f"{arg.value.id}.{arg.attr}"
    if isinstance(arg, ast.Call):
        f = arg.func
        fname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if fname in _JIT_WRAPPERS and arg.args:
            return _jit_arg_name(arg.args[0])
    return None


def _scope_defs(body) -> List[ast.AST]:
    """Function/class definitions belonging to this scope: descends
    into compound statements (if/for/while/with/try) but not into
    nested functions or classes — those open scopes of their own."""
    out: List[ast.AST] = []
    stack = list(body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            out.append(n)
            continue
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.stmt, ast.excepthandler)):
                stack.append(child)
    return out


def _index_module(idx: _Index, root: str, path: str) -> None:
    rel = lintlib.rel_to_root(path, root)
    mod = _dotted(rel)
    is_init = os.path.basename(path) == "__init__.py"
    try:
        with open(path, "rb") as f:
            tree = ast.parse(f.read(), filename=path)
    except SyntaxError:
        return
    idx.module_globals[rel] = {
        t.id
        for n in tree.body if isinstance(n, (ast.Assign, ast.AnnAssign))
        for t in (n.targets if isinstance(n, ast.Assign)
                  else [n.target])
        if isinstance(t, ast.Name)}

    env: Dict[str, tuple] = {}

    def note_import(node: ast.AST) -> None:
        if isinstance(node, ast.ImportFrom):
            parts = mod.split(".")
            if node.level:
                # level 1 = current package, 2 = its parent, ...
                keep = len(parts) - node.level + (1 if is_init else 0)
                anchor = parts[:max(keep, 0)]
                target = ".".join(anchor + ([node.module]
                                            if node.module else []))
            else:
                target = node.module or ""
            for alias in node.names:
                env[alias.asname or alias.name] = \
                    ("import", target, alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                env[alias.asname or alias.name.split(".")[0]] = \
                    ("module", alias.name, "")

    # imports anywhere in the module (function-level imports become
    # visible module-wide — an over-approximation we accept)
    for n in ast.walk(tree):
        note_import(n)

    def register(body, prefix: str, cls: Optional[str],
                 scope_env: Dict[str, tuple]) -> Dict[str, tuple]:
        """Register this scope's defs; returns the scope's env (outer
        env + this scope's function names) so a function's stored env
        sees its OWN nested defs — the ``lax.fori_loop(0, n, body, x)``
        callback pattern resolves through it."""
        defs = _scope_defs(body)
        local = dict(scope_env)
        for n in defs:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local[n.name] = ("func", rel, f"{prefix}{n.name}")
        for n in defs:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{n.name}"
                inner = register(n.body, qual + ".", cls, local)
                fn = _Func(rel, qual, n, inner, cls)
                idx.funcs[(mod, qual)] = fn
                idx.by_key[(rel, qual)] = fn
                if any(_is_jit_decorator(d) for d in n.decorator_list):
                    idx.roots.append(fn)
            elif isinstance(n, ast.ClassDef):
                register(n.body, f"{n.name}.", n.name, local)
        return local

    module_env = register(tree.body, "", None, env)

    # jit(...) CALL roots: scan each scope with ITS env, with alias
    # tracking (`f = shard_map(g, ...)` then `jax.jit(f)`)
    def alias_targets(value: ast.AST) -> List[str]:
        """Names a bound value may refer to: ``f = g``, ``f =
        shard_map(g, ...)``, ``f = a if cond else b``."""
        if isinstance(value, ast.Name):
            return [value.id]
        if isinstance(value, ast.IfExp):
            return alias_targets(value.body) \
                + alias_targets(value.orelse)
        if isinstance(value, ast.Call):
            t = _jit_arg_name(value)
            return [t] if t is not None else []
        return []

    def scan_jit_calls(scope_node, scope_env: Dict[str, tuple],
                       cls: Optional[str]) -> None:
        aliases: Dict[str, List[str]] = {}
        subs = list(ast.walk(scope_node)) if not isinstance(
            scope_node, ast.Module) else [
            s for n in scope_node.body for s in ast.walk(n)]
        for sub in subs:
            if isinstance(sub, ast.Assign) \
                    and isinstance(sub.targets[0], ast.Name):
                ts = alias_targets(sub.value)
                if ts:
                    aliases.setdefault(sub.targets[0].id,
                                       []).extend(ts)
        for sub in subs:
            if isinstance(sub, ast.Call) and _jit_ref(sub.func) \
                    and sub.args:
                tgt = _jit_arg_name(sub.args[0])
                if tgt is None:
                    continue
                frontier, resolved = [tgt], []
                for _ in range(4):
                    nxt = []
                    for t in frontier:
                        if t in aliases:
                            nxt.extend(aliases[t])
                        else:
                            resolved.append(t)
                    frontier = nxt
                    if not frontier:
                        break
                for t in resolved + frontier:
                    idx.pending.append((rel, t, scope_env, cls))

    scan_jit_calls(tree, module_env, None)
    for (r, _q), fn in list(idx.by_key.items()):
        if r == rel and fn.node is not None:
            scan_jit_calls(fn.node, fn.env, fn.cls)


def _lookup(idx: _Index, rel: str, env: Dict[str, tuple],
            cls: Optional[str], name: str) -> Optional[_Func]:
    """Resolve 'x' / 'self.x' / 'mod.x' to a package function."""
    if name.startswith("self."):
        if cls:
            return idx.by_key.get((rel, f"{cls}.{name[5:]}"))
        return None
    if "." in name:
        head, _, tail = name.partition(".")
        e = env.get(head)
        if e is None:
            return None
        if e[0] == "module":
            return idx.funcs.get((e[1], tail))
        if e[0] == "import":
            # `from . import predict_device` -> head names a module
            return idx.funcs.get((f"{e[1]}.{e[2]}".lstrip("."), tail))
        return None
    e = env.get(name)
    if e is None:
        return None
    if e[0] == "func":
        return idx.by_key.get((e[1], e[2]))
    if e[0] == "import":
        return idx.funcs.get((e[1], e[2]))
    return None


def _reachable(idx: _Index) -> Dict[Tuple[str, str], _Func]:
    work: List[_Func] = list(idx.roots)
    for rel, tgt, env, cls in idx.pending:
        got = _lookup(idx, rel, env, cls, tgt)
        if got is not None:
            work.append(got)
    seen: Dict[Tuple[str, str], _Func] = {}
    while work:
        fn = work.pop()
        key = (fn.rel, fn.qual)
        if key in seen or fn.node is None:
            continue
        seen[key] = fn
        for sub in ast.walk(fn.node):
            name = None
            if isinstance(sub, ast.Name) \
                    and isinstance(sub.ctx, ast.Load):
                name = sub.id
            elif isinstance(sub, ast.Attribute) \
                    and isinstance(sub.ctx, ast.Load) \
                    and isinstance(sub.value, ast.Name):
                base = sub.value.id
                name = f"self.{sub.attr}" if base == "self" \
                    else f"{base}.{sub.attr}"
            if name is None:
                continue
            got = _lookup(idx, fn.rel, fn.env, fn.cls, name)
            if got is not None and (got.rel, got.qual) not in seen:
                work.append(got)
    return seen


# ---------------------------------------------------------------------------
# findings inside a reachable function
# ---------------------------------------------------------------------------

def _call_name(f: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    """('np', 'sum') for np.sum(...), (None, 'print') for print(...)."""
    if isinstance(f, ast.Name):
        return None, f.id
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Name):
            return f.value.id, f.attr
        if isinstance(f.value, ast.Attribute) \
                and isinstance(f.value.value, ast.Name):
            return f"{f.value.value.id}.{f.value.attr}", f.attr
    return None, None


def _scan_function(fn: _Func, module_globals: Set[str]
                   ) -> List[Tuple[int, str, str]]:
    """(lineno, token, message) findings in one reachable function.
    The function's OWN body only — nested defs are their own reachable
    entries, so findings carry the precise qualname."""
    out: List[Tuple[int, str, str]] = []
    node = fn.node
    locals_: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
            locals_.add(sub.id)

    skip: Set[ast.AST] = set()
    for sub in ast.walk(node):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and sub is not node:
            for inner in ast.walk(sub):
                skip.add(inner)

    for sub in ast.walk(node):
        if sub in skip:
            continue
        if isinstance(sub, ast.Global):
            for g in sub.names:
                out.append((sub.lineno, f"global:{g}",
                            f"mutates module global '{g}'"))
            continue
        if isinstance(sub, (ast.Assign, ast.AugAssign)):
            tgts = sub.targets if isinstance(sub, ast.Assign) \
                else [sub.target]
            for t in tgts:
                base = t
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                if isinstance(base, ast.Name) and base is not t \
                        and base.id not in locals_ \
                        and base.id in module_globals:
                    out.append((sub.lineno, f"global:{base.id}",
                                f"mutates module global "
                                f"'{base.id}' in place"))
            continue
        if not isinstance(sub, ast.Call):
            continue
        mod, name = _call_name(sub.func)
        if name is None:
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "item":
                out.append((sub.lineno, ".item()",
                            "host sync .item() in traced body"))
            continue
        if name in _SANCTIONED_CALLS:
            continue
        if name == "item" and not sub.args:
            out.append((sub.lineno, ".item()",
                        "host sync .item() in traced body"))
        elif name in ("device_get", "block_until_ready"):
            out.append((sub.lineno, name,
                        f"host sync {name} in traced body"))
        elif mod in ("np", "numpy"):
            if name not in _NP_ALLOWED:
                out.append((sub.lineno, f"np.{name}",
                            f"numpy call np.{name} on (potentially) "
                            "traced values"))
        elif mod in ("np.random", "numpy.random"):
            out.append((sub.lineno, f"np.random.{name}",
                        f"host RNG np.random.{name} in traced body"))
        elif mod in _EFFECT_MODULES:
            out.append((sub.lineno, f"{mod}.{name}",
                        f"host side effect {mod}.{name}() in traced "
                        "body"))
        elif mod is None and name == "print":
            out.append((sub.lineno, "print",
                        "print() in traced body (fires once per "
                        "trace, then never again)"))
        elif mod is None and name == "open":
            out.append((sub.lineno, "open",
                        "file I/O in traced body"))
        elif mod is None and name in ("float", "bool") and sub.args:
            if not isinstance(sub.args[0], ast.Constant):
                out.append((sub.lineno, f"{name}()",
                            f"bare {name}() coercion — escapes the "
                            "tracer on traced values"))
    return out


def run(root: str = PACKAGE,
        allowlist_path: str = ALLOWLIST) -> List[str]:
    idx = _Index()
    for path in lintlib.iter_py(root):
        _index_module(idx, root, path)
    reach = _reachable(idx)
    allow = lintlib.load_pin_keys(allowlist_path)
    used: Set[Tuple[str, str, str]] = set()
    findings: List[str] = []
    for (rel, qual), fn in sorted(reach.items()):
        if qual.rsplit(".", 1)[-1] in _SANCTIONED_CALLS:
            continue     # the sanctioned primitives ARE the allowed
            #              trace-time effect; their bodies are exempt
        for lineno, token, msg in sorted(
                _scan_function(fn, idx.module_globals.get(rel, set()))):
            key = (rel, qual, token)
            if key in allow:
                used.add(key)
                continue
            findings.append(f"{rel}:{lineno}: {qual}: {msg}")
    findings.extend(lintlib.stale_pins(allow, used, "purity allowlist"))
    return findings


def reachable_functions(root: str = PACKAGE) -> List[str]:
    """Debug surface: the functions the lint considers traced."""
    idx = _Index()
    for path in lintlib.iter_py(root):
        _index_module(idx, root, path)
    return sorted(f"{rel}:{qual}" for (rel, qual) in _reachable(idx))


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=PACKAGE)
    ap.add_argument("--allowlist", default=ALLOWLIST)
    ap.add_argument("--list-reachable", action="store_true",
                    help="print the inferred traced-function set")
    args = ap.parse_args(argv)
    if args.list_reachable:
        for f in reachable_functions(args.root):
            print(f)
        return 0
    findings = run(args.root, args.allowlist)
    if findings:
        print("purity lint: host side effects inside traced bodies:",
              file=sys.stderr)
        for f in findings:
            print(f"  {f}", file=sys.stderr)
        print(f"\n{len(findings)} finding(s).  Move the effect out of "
              "the traced body, or pin a deliberate trace-time effect "
              "in tools/purity_allowlist.txt (rationale required)",
              file=sys.stderr)
        return 1
    print("purity lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
