"""Observability subsystem (lightgbm_tpu/obs/ — docs/Observability.md).

Covers the ISSUE 3 acceptance surface:

- sync lint green (tools/check_syncs.py; raw device_get /
  block_until_ready / .item() only at allowlisted sites);
- telemetry-off hot path is sync-free: counted ``jax.device_get`` calls
  per iteration match the seed's single batched fetch;
- JSONL traces round-trip through the Perfetto exporter;
- comm-bytes counters match the PR 1 per-shard hist-bytes math;
- metrics aggregation is deterministic and agrees dp == serial;
- satellites: verbosity -> log level mapping, timer atexit gating,
  profiler-window param validation, log_telemetry callback.
"""

import json
import os
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.obs import ObsSession, maybe_session
from lightgbm_tpu.obs.comm import CommLedger, wire_bytes
from lightgbm_tpu.obs.metrics import MetricsRegistry, aggregate_snapshots
from lightgbm_tpu.obs.trace import (Tracer, fence, jsonl_to_chrome,
                                    read_jsonl, timed_fenced)
from lightgbm_tpu.utils.log import Log

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


@pytest.fixture(scope="module", autouse=True)
def _quiet_global_timer():
    """ObsSession flips the process-global timer on (the FunctionTimer
    feed); restore the off default so later test modules' scopes don't
    arm the exit summary."""
    yield
    from lightgbm_tpu.utils.timer import global_timer
    global_timer.enabled = False


def _small_data(n=1200, f=8, seed=3):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, f)
    y = (x[:, 0] - 0.5 * x[:, 1] > 0).astype(np.float32)
    return x, y


def _train(params, n_iter=3, x=None, y=None):
    if x is None:
        x, y = _small_data()
    base = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
            "verbosity": 0, "fused_chunk": 0, "max_bin": 31}
    base.update(params)
    ds = lgb.Dataset(x, label=y, params=base)
    ds.construct()
    bst = lgb.Booster(params=base, train_set=ds)
    for _ in range(n_iter):
        bst.update()
    return bst


# -- sync lint -------------------------------------------------------------

class TestSyncLint:
    def test_library_is_clean(self):
        from check_syncs import find_raw_syncs
        findings = find_raw_syncs()
        assert findings == [], "\n".join(findings)

    def test_lint_catches_raw_syncs_and_stale_entries(self, tmp_path):
        from check_syncs import find_raw_syncs
        root = tmp_path / "pkg"
        root.mkdir()
        (root / "bad.py").write_text(
            "import jax\n"
            "# a comment mentioning jax.device_get(x) must NOT trip\n"
            "def f(x):\n"
            '    """nor a docstring: block_until_ready."""\n'
            "    v = jax.device_get(x)\n"
            "    jax.block_until_ready(x)\n"
            "    return v.item()\n")
        allow = tmp_path / "allow.txt"
        allow.write_text("pkg/gone.py | jax.device_get(y)\n")
        findings = find_raw_syncs(str(root), str(allow))
        joined = "\n".join(findings)
        assert "bad.py:5" in joined and "bad.py:6" in joined \
            and "bad.py:7" in joined
        assert "comment" not in joined and "docstring" not in joined
        assert any("stale allowlist" in f for f in findings)


# -- telemetry-off: sync-free hot path ------------------------------------

class TestTelemetryOff:
    def test_default_has_no_session(self):
        bst = _train({}, n_iter=1)
        assert bst._model._obs is None
        # telemetry=false carries NO obs metrics — only the process-wide
        # compile accounting (utils/compile_cache.py), which is host-side
        # counters with zero device syncs
        snap = bst.telemetry_snapshot()
        assert all(k.startswith("compile.") for k in snap)
        assert {"compile.count", "compile.seconds", "compile.cache_hits",
                "compile.cache_misses", "compile.traces"} <= set(snap)
        assert bst.telemetry_finish() == {}

    def test_device_get_count_per_iteration_unchanged(self, monkeypatch):
        """The masked per-iteration path performs exactly ONE batched
        ``device_get`` per update (the small tree fetch — PROFILE.md's
        'fetch' phase); telemetry=false must not add any."""
        import jax
        x, y = _small_data()
        base = {"objective": "binary", "num_leaves": 7,
                "min_data_in_leaf": 5, "verbosity": 0, "fused_chunk": 0,
                "max_bin": 31, "tpu_learner": "masked"}
        ds = lgb.Dataset(x, label=y, params=base)
        ds.construct()
        bst = lgb.Booster(params=base, train_set=ds)
        bst.update()                       # compile/warm outside the count

        calls = [0]
        real = jax.device_get

        def counting(*a, **kw):
            calls[0] += 1
            return real(*a, **kw)

        monkeypatch.setattr(jax, "device_get", counting)
        for _ in range(3):
            bst.update()
        assert calls[0] == 3, \
            f"expected 1 device_get per iteration, saw {calls[0]} over 3"

    def test_telemetry_on_only_adds_fences(self, monkeypatch):
        """With telemetry=true the extra syncs are exactly the three
        phase fences (grad/grow/score; fetch rides the existing
        device_get) — pinning the span structure."""
        import jax
        x, y = _small_data()
        base = {"objective": "binary", "num_leaves": 7,
                "min_data_in_leaf": 5, "verbosity": 0, "fused_chunk": 0,
                "max_bin": 31, "tpu_learner": "masked", "telemetry": True}
        ds = lgb.Dataset(x, label=y, params=base)
        ds.construct()
        bst = lgb.Booster(params=base, train_set=ds)
        bst.update()

        calls = [0]
        real = jax.device_get

        def counting(*a, **kw):
            calls[0] += 1
            return real(*a, **kw)

        monkeypatch.setattr(jax, "device_get", counting)
        bst.update()
        assert calls[0] == 4               # 1 fetch + 3 phase fences


# -- traces ----------------------------------------------------------------

class TestTrace:
    def test_jsonl_roundtrip_through_perfetto_exporter(self, tmp_path):
        sink = str(tmp_path / "t.jsonl")
        tr = Tracer(sink_path=sink, pid=7)
        with tr.span("outer", iteration=1):
            with tr.span("inner"):
                pass
        tr.instant("marker", note="x")
        tr.close()

        events = read_jsonl(sink)
        assert [e["name"] for e in events] == ["inner", "outer", "marker"]
        assert all(e["pid"] == 7 for e in events)
        outer = next(e for e in events if e["name"] == "outer")
        inner = next(e for e in events if e["name"] == "inner")
        # containment: nesting is recoverable from [ts, ts+dur)
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1
        assert outer["args"] == {"iteration": 1}

        chrome = str(tmp_path / "t.trace.json")
        assert jsonl_to_chrome(sink, chrome) == 3
        loaded = json.load(open(chrome))
        assert loaded["traceEvents"] == events

    def test_jsonl_survives_torn_tail(self, tmp_path):
        sink = tmp_path / "torn.jsonl"
        sink.write_text('{"name": "a", "ph": "X", "ts": 0, "dur": 1}\n'
                        '{"name": "b", "ph"')
        assert [e["name"] for e in read_jsonl(str(sink))] == ["a"]

    def test_fence_returns_value_and_blocks(self):
        import jax.numpy as jnp
        x = jnp.arange(8.0)
        assert fence(x) is x
        assert fence(None) is None
        assert fence({"a": x, "b": 3}) is not None    # non-arrays pass

    def test_timed_fenced(self):
        import jax.numpy as jnp
        tr = Tracer()
        mn, avg = timed_fenced(lambda: jnp.arange(4.0) + 1, iters=3,
                               tracer=tr, name="probe")
        assert 0 < mn <= avg
        assert len(tr.durations("probe")) == 3

    def test_training_emits_phase_spans(self, tmp_path):
        sink = str(tmp_path / "train.jsonl")
        bst = _train({"telemetry": True, "telemetry_trace_file": sink},
                     n_iter=2)
        bst.telemetry_finish()
        names = {e["name"] for e in read_jsonl(sink)}
        assert {"grad", "grow", "fetch", "score"} <= names


# -- metrics ---------------------------------------------------------------

class TestMetrics:
    def test_registry_snapshot_deterministic(self):
        r = MetricsRegistry()
        r.counter("c", a=1).inc(2)
        r.gauge("g").set(5)
        r.histogram("h").observe(0.02)
        s1, s2 = r.snapshot(), r.snapshot()
        assert json.dumps(s1) == json.dumps(s2)
        assert s1["c{a=1}"] == {"type": "counter", "value": 2.0}
        assert s1["g"]["value"] == 5.0
        assert s1["h"]["count"] == 1

    def test_type_conflict_raises(self):
        r = MetricsRegistry()
        r.counter("x")
        with pytest.raises(TypeError):
            r.gauge("x")

    def test_aggregate_counters_histograms_gauges(self):
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        for r, v in ((r1, 1.0), (r2, 3.0)):
            r.counter("n").inc(v)
            r.histogram("h").observe(v)
            r.gauge("same").set(7)
        r1.gauge("differs").set(1)
        r2.gauge("differs").set(2)
        agg = aggregate_snapshots([r1.snapshot(), r2.snapshot()])
        assert agg["n"]["value"] == 4.0
        assert agg["h"]["count"] == 2 and agg["h"]["sum"] == 4.0
        assert agg["h"]["min"] == 1.0 and agg["h"]["max"] == 3.0
        assert agg["same"]["value"] == 7.0
        assert agg["differs{shard=0}"]["value"] == 1.0
        assert agg["differs{shard=1}"]["value"] == 2.0
        # single-snapshot aggregation is identity (sorted)
        assert aggregate_snapshots([r1.snapshot()]) == r1.snapshot()

    def test_training_metrics_populated(self):
        bst = _train({"telemetry": True}, n_iter=3)
        snap = bst.telemetry_snapshot()
        assert snap["train.iterations"]["value"] == 3.0
        assert snap["train.steps_per_tree"]["count"] == 3
        for phase in ("grad", "grow", "fetch", "score"):
            key = f"train.phase_seconds{{phase={phase}}}"
            assert snap[key]["count"] == 3

    def test_fused_chunk_counts_iterations(self):
        x, y = _small_data(2400)
        base = {"objective": "binary", "num_leaves": 7,
                "min_data_in_leaf": 5, "verbosity": 0, "max_bin": 31,
                "telemetry": True, "tpu_learner": "masked",
                "fused_chunk": 4}
        ds = lgb.Dataset(x, label=y, params=base)
        ds.construct()
        bst = lgb.Booster(params=base, train_set=ds)
        assert bst.supports_fused()
        bst.update_chunk(4)
        snap = bst.telemetry_snapshot()
        assert snap["train.iterations"]["value"] == 4.0
        assert snap["train.fused_chunks"]["value"] == 1.0
        assert snap["train.steps_per_tree"]["count"] == 4


# -- comm accounting -------------------------------------------------------

class TestComm:
    def test_wire_model(self):
        assert wire_bytes("psum", 800, 8) == int(2 * 7 / 8 * 800)
        assert wire_bytes("psum_scatter", 800, 8) == 700
        assert wire_bytes("all_gather", 800, 8) == 700
        assert wire_bytes("psum", 800, 1) == 0

    def test_ledger_static_registration(self):
        import jax
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        led = CommLedger(8)
        # registration happens at trace time, idempotently
        from jax.sharding import PartitionSpec as P
        from lightgbm_tpu.parallel import make_mesh
        from lightgbm_tpu.utils.jax_compat import shard_map
        import jax.numpy as jnp
        mesh = make_mesh((8,), ("data",))

        def f(x):
            return led.psum(x, "data", site="t.sum")

        g = jax.jit(shard_map(f, mesh=mesh, in_specs=(P("data"),),
                              out_specs=P()))
        out = g(jnp.ones(16, jnp.float32))
        assert float(out[0]) == 8.0
        (site,) = led.sites()
        assert site.payload_bytes == 2 * 4       # local [2] f32 shard
        assert site.collective == "psum"
        assert site.wire_bytes == wire_bytes("psum", 8, 8)

    def test_dp_counters_match_owner_shard_hist_math(self):
        """comm.payload_bytes{site=dp.hist_reduce} per pass equals
        n_shards x OwnerShardPlan.hist_bytes(1, B) — the PR 1 per-shard
        histogram byte math (bench.py extras / mesh.owner_shard_plan),
        observed in-flight via the telemetry counters."""
        import jax
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        bst = _train({"telemetry": True, "tree_learner": "data",
                      "split_batch": 1}, n_iter=2)
        m = bst._model
        ledger = m.grower.comm
        sites = {s.site: s for s in ledger.sites()}
        plan = m.grower.plan
        per_leaf = plan.hist_bytes(1, m.max_bin)
        n_sh = ledger.axis_size
        assert sites["dp.hist_reduce"].payload_bytes == n_sh * per_leaf
        assert sites["dp.hist_reduce"].wire_bytes == \
            wire_bytes("psum_scatter", n_sh * per_leaf, n_sh)
        # counter = wire bytes x total grower steps over both iterations
        snap = bst.telemetry_snapshot()
        steps = sum(m.step_counts)
        key = "comm.wire_bytes{collective=psum_scatter,site=dp.hist_reduce}"
        assert snap[key]["value"] == sites["dp.hist_reduce"].wire_bytes \
            * steps
        key = "comm.wire_bytes{collective=psum,site=dp.root_sum}"
        assert snap[key]["value"] == sites["dp.root_sum"].wire_bytes * 2
        assert ledger.bytes_per_iteration(1) == sum(
            s.wire_bytes for s in ledger.sites())

    def test_dp_equals_serial_and_aggregation_deterministic(self):
        """Trees (and therefore steps/iteration metrics) agree between
        tree_learner=data and serial; the serial run records zero comm;
        snapshots are byte-deterministic across repeated export."""
        import jax
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        x, y = _small_data(1600)
        serial = _train({"telemetry": True, "tpu_learner": "masked"},
                        n_iter=3, x=x, y=y)
        dp = _train({"telemetry": True, "tree_learner": "data",
                     "split_batch": 1}, n_iter=3, x=x, y=y)
        s_snap, d_snap = serial.telemetry_snapshot(), dp.telemetry_snapshot()
        assert json.dumps(s_snap) == json.dumps(serial.telemetry_snapshot())
        assert s_snap["train.iterations"] == d_snap["train.iterations"]
        for fld in ("count", "counts", "sum", "min", "max"):
            assert s_snap["train.steps_per_tree"][fld] \
                == d_snap["train.steps_per_tree"][fld]
        assert not any(k.startswith("comm.") for k in s_snap)
        assert any(k.startswith("comm.wire_bytes") for k in d_snap)

    def test_bench_comm_extra_math(self):
        from lightgbm_tpu.obs.comm import dp_hist_bytes_per_iter
        from lightgbm_tpu.parallel.mesh import owner_shard_plan
        plan = owner_shard_plan(np.arange(28), 8)
        got = dp_hist_bytes_per_iter(8, plan.chunk, 64, n_steps=30)
        assert got == wire_bytes("psum_scatter",
                                 8 * plan.hist_bytes(1, 64), 8) * 30


# -- satellites ------------------------------------------------------------

class TestVerbosityMapping:
    @pytest.mark.parametrize("verbosity,level", [
        (-5, -1), (-1, -1), (0, 0), (1, 1), (2, 2), (7, 2)])
    def test_reference_semantics(self, verbosity, level):
        old = Log.level
        try:
            Config({"verbosity": verbosity})
            assert Log.level == level
        finally:
            Log.level = old

    def test_verbose_alias(self):
        old = Log.level
        try:
            Config({"verbose": -1})
            assert Log.level == -1
        finally:
            Log.level = old


class TestTimerGating:
    def test_atexit_not_armed_by_import_or_disabled_use(self):
        import atexit

        from lightgbm_tpu.utils.timer import Timer

        t = Timer()
        t.stop("x", t.start("x"))          # disabled: must not arm
        assert not t._atexit_armed
        t.enabled = True
        t.stop("x", t.start("x"))
        assert t._atexit_armed
        atexit.unregister(t.print_summary)  # keep the test run silent

    def test_print_summary_silent_without_stats(self, capsys):
        from lightgbm_tpu.utils.timer import Timer
        t = Timer()
        t.enabled = True
        t.print_summary()
        assert capsys.readouterr().out == ""


class TestSession:
    def test_maybe_session_off_by_default(self):
        assert maybe_session(Config({})) is None
        assert isinstance(maybe_session(Config({"telemetry": True})),
                          ObsSession)

    def test_profile_iters_validation(self):
        with pytest.raises(ValueError):
            Config({"telemetry_profile_iters": [1, 2, 3]})
        cfg = Config({"telemetry_profile_iters": [5]})
        assert cfg.telemetry_profile_iters == [5]

    def test_profiler_window_failure_is_nonfatal(self, tmp_path,
                                                 monkeypatch):
        from lightgbm_tpu.obs.profiler import ProfilerWindow
        import jax.profiler as jp

        def boom(*a, **kw):
            raise RuntimeError("no profiler service")

        monkeypatch.setattr(jp, "start_trace", boom)
        w = ProfilerWindow(0, 1, str(tmp_path / "prof"))
        w.on_iter_begin(0)                 # must not raise
        assert w._dead and not w.active
        w.on_iter_end(0)
        w.finish()


class TestLogTelemetryCallback:
    def test_collects_and_logs(self):
        x, y = _small_data()
        collected = {}
        params = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
                  "min_data_in_leaf": 5, "verbosity": 0, "telemetry": True,
                  "fused_chunk": 0}
        ds = lgb.Dataset(x, label=y, params=params)
        lgb.train(params, ds, num_boost_round=4,
                  callbacks=[lgb.log_telemetry(period=2,
                                               collect=collected)])
        assert sorted(collected) == [2, 4]
        assert collected[4]["train.iterations"]["value"] == 4.0

    def test_cv_collects_per_fold(self):
        x, y = _small_data()
        collected = {}
        params = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
                  "min_data_in_leaf": 5, "verbosity": 0, "telemetry": True,
                  "fused_chunk": 0}
        lgb.cv(params, lgb.Dataset(x, label=y, params=params),
               num_boost_round=2, nfold=2, stratified=False,
               callbacks=[lgb.log_telemetry(period=2, collect=collected)])
        assert sorted(collected) == [2]
        assert isinstance(collected[2], list) and len(collected[2]) == 2
        for snap in collected[2]:
            assert snap["train.iterations"]["value"] == 2.0

    def test_noop_without_telemetry(self):
        x, y = _small_data()
        collected = {}
        params = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
                  "min_data_in_leaf": 5, "verbosity": 0, "fused_chunk": 0}
        ds = lgb.Dataset(x, label=y, params=params)
        lgb.train(params, ds, num_boost_round=2,
                  callbacks=[lgb.log_telemetry(period=1,
                                               collect=collected)])
        assert collected == {}
