"""Wide MXU-shaped histogram contraction (ISSUE 15).

The multi-leaf one-hot contraction grew past the shipped K<=16
super-step widths: C = 3K channel axes lane-pad to MXU 128-multiples
(utils/shapes.bucket_channels, exact zeros sliced off in-kernel), the
split_batch set extends to {32, 64} with budget-aware snapping
(fit_split_batch), the strict grower's masked smaller-child pass rides
the same slot mechanism (hist_overlap — byte-identical by
construction), the block-rows budget accounts the wide accumulator,
and an on-device autotuner (ops/hist_tune.py) picks (K, block_rows) by
measured ms per leaf slot.  These tests pin: kernel exactness at every
width, the overlap path's byte-identity, metric parity of the wide
widths vs strict across sampling/categorical/monotone/quantized
configs, dp==serial through the owner-shard reduce at K=32, the
pad-excluded MFU accounting, and the tuner's persistence.
"""

import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _strip_params(model_text: str) -> str:
    """Model bytes minus the dumped parameter block (a toggled param
    name prints there even when the trees are identical)."""
    return model_text.split("parameters:")[0]


@pytest.fixture(scope="module")
def data():
    rs = np.random.RandomState(11)
    n, f = 900, 10
    x = rs.randn(n, f)
    x[rs.rand(n, f) < 0.03] = np.nan
    logit = (np.nan_to_num(x[:, 0]) * 1.5 - np.nan_to_num(x[:, 1])
             + 0.4 * np.nan_to_num(x[:, 2]) + 0.3 * rs.randn(n))
    y = (logit > 0).astype(np.float32)
    return x, y


def _train(x, y, rounds=3, **over):
    p = {"objective": "binary", "verbosity": -1, "min_data_in_leaf": 5,
         "max_bin": 31, "tpu_learner": "masked", "fused_chunk": 0,
         "num_leaves": 33}
    p.update(over)
    ds = lgb.Dataset(x, label=y, params=p)
    return lgb.train(p, ds, num_boost_round=rounds)


def _auc(y, s):
    order = np.argsort(s)
    r = np.empty(len(s))
    r[order] = np.arange(1, len(s) + 1)
    pos = y > 0
    n1, n0 = pos.sum(), (~pos).sum()
    return (r[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)


# ---------------------------------------------------------------------------
# shape policy units
# ---------------------------------------------------------------------------

class TestShapePolicy:
    def test_bucket_channels(self):
        from lightgbm_tpu.utils.shapes import (HIST_CHANNEL_EXACT_MAX,
                                               bucket_channels)
        # shipped widths stay exact (C=3 strict, 24/48 for K=8/16)
        for c in (3, 6, 24, 48):
            assert bucket_channels(c) == c
        assert HIST_CHANNEL_EXACT_MAX == 48
        # wide widths pad to 128-lane multiples
        assert bucket_channels(96) == 128       # K=32
        assert bucket_channels(192) == 256      # K=64
        assert bucket_channels(129) == 256

    def test_split_batch_set_extended(self):
        from lightgbm_tpu.utils.shapes import (SPLIT_BATCH_SET,
                                               snap_split_batch)
        assert SPLIT_BATCH_SET == (1, 8, 16, 32, 64)
        assert snap_split_batch(20) == 32
        assert snap_split_batch(33) == 64
        assert snap_split_batch(999) == 64
        assert snap_split_batch(16) == 16
        assert snap_split_batch(1) == 1

    def test_fit_split_batch_budget(self):
        from lightgbm_tpu.utils.shapes import fit_split_batch
        assert fit_split_batch(32, 31) == 16    # steps DOWN the set
        assert fit_split_batch(32, 33) == 32
        assert fit_split_batch(64, 40) == 32
        assert fit_split_batch(64, 65) == 64
        assert fit_split_batch(8, 31) == 8      # shipped widths pass
        assert fit_split_batch(1, 31) == 1
        assert fit_split_batch(64, 2) == 1      # nothing fits -> strict

    def test_block_rows_budget_accounts_wide_channels(self):
        from lightgbm_tpu.ops.histogram import hist_block_rows
        # shipped widths: formula byte-identical to the historic one
        assert hist_block_rows(28, 64) == hist_block_rows(28, 64,
                                                          channels=48)
        assert hist_block_rows(968, 256) == \
            hist_block_rows(968, 256, channels=24)
        # wide channels on a wide dataset: the [C, F*Bp] accumulator
        # carry alone exceeds the budget -> block floors at 8 instead
        # of silently overshooting (the pre-fix behavior)
        assert hist_block_rows(968, 256, channels=256) == 8
        # narrow dataset: wide channels only trim the block a little
        assert hist_block_rows(28, 64, channels=256) >= 4096


# ---------------------------------------------------------------------------
# kernel exactness at the new widths
# ---------------------------------------------------------------------------

class TestKernelWidths:
    @pytest.mark.parametrize("k", [32, 64])
    def test_slotted_matches_masked_per_slot(self, k):
        import jax.numpy as jnp
        from lightgbm_tpu.ops.histogram import compute_histogram
        rs = np.random.RandomState(0)
        n, f, B = 3000, 5, 31
        binned = jnp.asarray(rs.randint(0, B, size=(n, f),
                                        dtype=np.uint8))
        vals = jnp.asarray(rs.randn(n, 3).astype(np.float32))
        slot = jnp.asarray(rs.randint(-1, k, size=n, dtype=np.int32))
        h = compute_histogram(binned, vals, num_bins=B, slot=slot,
                              num_slots=k)
        assert h.shape == (f, B, 3 * k)
        for s in (0, k // 2, k - 1):
            m = (slot == s).astype(np.float32)[:, None]
            ref = compute_histogram(binned, vals * m, num_bins=B)
            got = h.reshape(f, B, 3, k)[:, :, :, s]
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=2e-5, atol=2e-4)

    def test_int8_k64_exact(self):
        import jax.numpy as jnp
        from lightgbm_tpu.ops.histogram import compute_histogram
        rs = np.random.RandomState(1)
        n, f, B, k = 2500, 4, 31, 64
        binned = jnp.asarray(rs.randint(0, B, size=(n, f),
                                        dtype=np.uint8))
        vi = jnp.asarray(rs.randint(-50, 50, size=(n, 3),
                                    dtype=np.int8))
        slot = jnp.asarray(rs.randint(0, k, size=n, dtype=np.int32))
        h = compute_histogram(binned, vi, num_bins=B, slot=slot,
                              num_slots=k)
        assert h.dtype == jnp.int32
        s = 9
        ref = np.zeros((f, B, 3), np.int64)
        bn, vn = np.asarray(binned), np.asarray(vi, np.int64)
        for i in np.nonzero(np.asarray(slot) == s)[0]:
            for ff in range(f):
                ref[ff, bn[i, ff]] += vn[i]
        np.testing.assert_array_equal(
            np.asarray(h.reshape(f, B, 3, k)[:, :, :, s]), ref)

    def test_padded_channel_flops_excluded_from_hist_site(self):
        """The in-kernel trace note for ``hist`` carries the USEFUL
        channel flops only; the 128-lane pad lands in ``hist_pad``
        under phase="pad" (the MFU-excluded channel)."""
        import jax.numpy as jnp
        from lightgbm_tpu.obs.flops import (hist_flops_bytes,
                                            padded_bins, traced_sites)
        from lightgbm_tpu.ops.histogram import compute_histogram
        rs = np.random.RandomState(2)
        n, f, B, k = 1000, 3, 15, 32
        binned = jnp.asarray(rs.randint(0, B, size=(n, f),
                                        dtype=np.uint8))
        vals = jnp.asarray(rs.randn(n, 3).astype(np.float32))
        slot = jnp.asarray(rs.randint(0, k, size=n, dtype=np.int32))
        compute_histogram(binned, vals, num_bins=B, slot=slot,
                          num_slots=k)
        sites = traced_sites()
        useful, _ = hist_flops_bytes(n, f, B, channels=3 * k)
        assert sites["hist"].flops == useful
        assert useful == 2 * 3 * k * n * f * padded_bins(B)
        pad = sites["hist_pad"]
        assert pad.phase == "pad"
        # 96 useful channels pad to 128: 32 dead lanes
        assert pad.flops == 2 * (128 - 96) * n * f * padded_bins(B)


# ---------------------------------------------------------------------------
# strict-grower overlap path: byte-identical to the serialized baseline
# ---------------------------------------------------------------------------

class TestStrictOverlap:
    def test_kernel_slot_mask_bitwise_equals_masked(self):
        import jax.numpy as jnp
        from lightgbm_tpu.ops.histogram import compute_histogram
        rs = np.random.RandomState(3)
        n, f, B = 4000, 6, 63
        binned = jnp.asarray(rs.randint(0, B, size=(n, f),
                                        dtype=np.uint8))
        vals = jnp.asarray(rs.randn(n, 3).astype(np.float32))
        mask = jnp.asarray(rs.rand(n) < 0.4)
        sl = jnp.where(mask, jnp.int32(0), jnp.int32(-1))
        h_slot = compute_histogram(binned, vals, num_bins=B, slot=sl,
                                   num_slots=1)
        h_mask = compute_histogram(
            binned, vals * mask.astype(np.float32)[:, None], num_bins=B)
        np.testing.assert_array_equal(np.asarray(h_slot),
                                      np.asarray(h_mask))

    @pytest.mark.parametrize("extra", [
        {},
        {"bagging_fraction": 0.7, "bagging_freq": 1},
        {"quant_train": True},
    ])
    def test_overlap_model_byte_identical(self, data, extra):
        x, y = data
        a = _train(x, y, num_leaves=15, split_batch=1,
                   hist_overlap=True, **extra)
        b = _train(x, y, num_leaves=15, split_batch=1,
                   hist_overlap=False, **extra)
        assert _strip_params(a.model_to_string()) == \
            _strip_params(b.model_to_string())


# ---------------------------------------------------------------------------
# wide-width parity matrix vs strict growth
# ---------------------------------------------------------------------------

_WIDE_CONFIGS = {
    "plain": {},
    "bagging": {"bagging_fraction": 0.7, "bagging_freq": 1},
    "goss": {"data_sample_strategy": "goss"},
    "monotone": {"monotone_constraints": [1, -1] + [0] * 8},
    "quant": {"quant_train": True},
}


@pytest.mark.slow   # exhaustive sweep tier, like test_split_batch.py
class TestWideParity:
    @pytest.mark.parametrize("name", sorted(_WIDE_CONFIGS))
    def test_k32_metric_parity_vs_strict(self, data, name):
        """K=32 changes growth ORDER, not model quality: AUC within a
        small epsilon of strict leaf-wise on every config family."""
        x, y = data
        over = _WIDE_CONFIGS[name]
        strict = _train(x, y, rounds=5, split_batch=1, **over)
        wide = _train(x, y, rounds=5, split_batch=32, **over)
        a1 = _auc(y, strict.predict(x))
        a32 = _auc(y, wide.predict(x))
        assert a32 > a1 - 0.03, (name, a1, a32)

    def test_k64_trains_and_matches(self, data):
        x, y = data
        strict = _train(x, y, rounds=4, num_leaves=65, split_batch=1)
        wide = _train(x, y, rounds=4, num_leaves=65, split_batch=64)
        assert _auc(y, wide.predict(x)) > \
            _auc(y, strict.predict(x)) - 0.03

    def test_k32_categorical(self, data):
        x, y = data
        rs = np.random.RandomState(5)
        xc = np.nan_to_num(x).copy()
        cat = rs.randint(0, 8, x.shape[0]).astype(float)
        y2 = ((cat >= 4) & (np.nan_to_num(x[:, 0]) > -0.5)) \
            .astype(np.float32)
        xc[:, 5] = cat
        aucs = {}
        for sb in (1, 32):
            p = {"objective": "binary", "verbosity": -1,
                 "num_leaves": 33, "min_data_in_leaf": 5,
                 "min_data_per_group": 5, "tpu_learner": "masked",
                 "fused_chunk": 0, "split_batch": sb}
            ds = lgb.Dataset(xc, label=y2, params={"max_bin": 31},
                             categorical_feature=[5])
            bst = lgb.train(p, ds, num_boost_round=6)
            aucs[sb] = _auc(y2, bst.predict(xc))
        assert aucs[32] > 0.9
        assert aucs[32] > aucs[1] - 0.03


class TestWidthContracts:
    """The cheap byte-level pins of the width contract (tier-1; the
    exhaustive parity sweeps above are slow-tier)."""

    def test_over_budget_width_fits_down_byte_identical(self, data):
        """num_leaves=31 at K=32 must run the K=16 program — the same
        bytes an explicit split_batch=16 trains."""
        x, y = data
        a = _train(x, y, num_leaves=31, split_batch=32)
        b = _train(x, y, num_leaves=31, split_batch=16)
        assert _strip_params(a.model_to_string()) == \
            _strip_params(b.model_to_string())

    def test_fused_chunk_carries_k32(self, data):
        """The fused super-step scan threads the wide K: fused ==
        per-iteration byte-identically at split_batch=32."""
        x, y = data
        a = _train(x, y, split_batch=32, fused_chunk=0)
        b = _train(x, y, split_batch=32, fused_chunk=3)
        assert _strip_params(a.model_to_string()) == \
            _strip_params(b.model_to_string())


# ---------------------------------------------------------------------------
# distributed: the owner-shard reduce carries the wide K
# ---------------------------------------------------------------------------

@pytest.mark.slow   # mirrors test_split_batch.py::TestDistributedBatched
class TestDistributedWide:
    def _structure(self, bst):
        return [(list(np.asarray(t.split_feature)),
                 list(np.asarray(t.left_child)))
                for t in bst.trees]

    @pytest.fixture(scope="class")
    def clean_data(self):
        # NaN-free, well-separated data: the f32 dp comparison needs
        # gains without near-ties (psum reorder moves ulps, and the
        # wide top-K ORDER is tie-sensitive — the same caveat the
        # shipped K<=16 dp tests carry); the quant variant below is
        # exact by int32 construction
        rs = np.random.RandomState(3)
        n, f = 1600, 12
        x = rs.randn(n, f)
        y = (x[:, 0] - x[:, 1] + 0.3 * rs.randn(n) > 0) \
            .astype(np.float32)
        return x, y

    def test_dp_owner_shard_structure_equals_serial_at_k32(
            self, clean_data):
        import jax
        if len(jax.devices()) < 4:
            pytest.skip("needs a multi-device mesh")
        x, y = clean_data
        ser = _train(x, y, split_batch=32)
        dp = _train(x, y, split_batch=32, tree_learner="data",
                    mesh_shape=[4])
        assert self._structure(ser) == self._structure(dp)

    def test_dp_quant_int32_reduce_at_k32(self, clean_data):
        """Quantized training's exact int32 histograms through the
        wide owner-shard psum_scatter: structure parity dp == serial
        (the shipped quant contract, test_quant.py, at the new K)."""
        import jax
        if len(jax.devices()) < 4:
            pytest.skip("needs a multi-device mesh")
        x, y = clean_data
        ser = _train(x, y, split_batch=32, quant_train=True)
        dp = _train(x, y, split_batch=32, quant_train=True,
                    tree_learner="data", mesh_shape=[4])
        assert self._structure(ser) == self._structure(dp)

    def test_feature_parallel_carries_k32(self, clean_data):
        import jax
        if len(jax.devices()) < 4:
            pytest.skip("needs a multi-device mesh")
        x, y = clean_data
        ser = _train(x, y, split_batch=32)
        fp = _train(x, y, split_batch=32, tree_learner="feature",
                    mesh_shape=[4])
        assert self._structure(ser) == self._structure(fp)


# ---------------------------------------------------------------------------
# pad-truthful accounting (obs/flops.py + obs/attrib.py)
# ---------------------------------------------------------------------------

class TestPadAccounting:
    def test_ledger_pad_site_only_for_wide_widths(self):
        from lightgbm_tpu.obs.flops import FlopLedger
        led16 = FlopLedger.for_training(10000, 28, 63, split_batch=16)
        assert "hist_pad" not in {s.site for s in led16.sites()}
        led32 = FlopLedger.for_training(10000, 28, 63, split_batch=32)
        sites = {s.site: s for s in led32.sites()}
        assert sites["hist_pad"].phase == "pad"
        from lightgbm_tpu.obs.flops import padded_bins
        assert sites["hist_pad"].flops == \
            2 * (128 - 96) * 10000 * 28 * padded_bins(63)

    def test_intensity_rises_with_k(self):
        """More channels per binned-operand load is the direct
        arithmetic-intensity lever — the acceptance instrument."""
        from lightgbm_tpu.obs.flops import FlopLedger
        inten = {}
        for k in (16, 32, 64):
            led = FlopLedger.for_training(100000, 28, 63, split_batch=k)
            s = {x.site: x for x in led.sites()}["hist"]
            inten[k] = s.flops / s.hbm_bytes
        assert inten[32] > inten[16]
        assert inten[64] > inten[32]

    def test_perf_summary_excludes_pad_from_mfu(self):
        """perf.hist_pad.* is visible, but phase/total aggregation —
        the MFU denominator's numerator — never includes pad FLOPs."""
        from lightgbm_tpu.obs.attrib import perf_summary
        snap = {
            "flops.total{phase=grow,site=hist}": {"value": 1000.0},
            "flops.hbm_bytes{phase=grow,site=hist}": {"value": 100.0},
            "flops.total{phase=pad,site=hist_pad}": {"value": 333.0},
            "flops.hbm_bytes{phase=pad,site=hist_pad}": {"value": 0.0},
            "train.phase_seconds{phase=grow}": {"sum": 1.0},
        }
        out = perf_summary(snap, peaks=(1e4, 1e3))
        assert out["perf.hist_pad.flops"] == 333.0
        assert out["perf.grow.flops"] == 1000.0
        assert out["perf.total.flops"] == 1000.0
        assert out["perf.grow.mfu"] == pytest.approx(1000.0 / 1.0 / 1e4)
        assert "perf.pad.flops" not in out

    def test_booster_perf_keys_at_k32(self, data):
        x, y = data
        bst = _train(x, y, split_batch=32, telemetry=True)
        snap = bst.telemetry_snapshot()
        pad = snap.get("perf.hist_pad.flops", 0.0)
        assert pad > 0
        # the grow phase's flops must be EXACTLY the sum of its own
        # phase=grow counters — i.e. the pad counters (phase=pad) are
        # excluded from the MFU numerator, not merely small
        grow_counters = sum(
            float(v.get("value", 0.0)) for k, v in snap.items()
            if k.startswith("flops.total{") and "phase=grow" in k)
        assert snap["perf.grow.flops"] == pytest.approx(grow_counters)
        pad_counters = sum(
            float(v.get("value", 0.0)) for k, v in snap.items()
            if k.startswith("flops.total{") and "phase=pad" in k)
        assert pad_counters == pytest.approx(pad) and pad_counters > 0
        # ...and the total aggregates PHASES only (a phase block emits
        # .seconds, a site block does not) — no "pad" phase exists
        phase_flops = sum(
            float(snap[k]) for k in snap
            if k.startswith("perf.") and k.endswith(".flops")
            and k != "perf.total.flops"
            and (k[:-len("flops")] + "seconds") in snap)
        assert snap["perf.total.flops"] == pytest.approx(phase_flops)
        assert "perf.pad.flops" not in snap
        assert snap.get("perf.hist.intensity_flops_per_byte", 0) > 0


# ---------------------------------------------------------------------------
# autotuner (ops/hist_tune.py)
# ---------------------------------------------------------------------------

class TestAutotuner:
    def test_sweep_and_persistence(self, tmp_path):
        from lightgbm_tpu.ops import hist_tune
        rec = hist_tune.tune(2000, 4, 15, kmax=32, reps=2,
                             sample_rows=1024)
        assert rec["k"] in (8, 16, 32)
        assert rec["block_rows"] >= 8
        assert rec["ms_per_leaf"] <= rec["ms_per_pass"]
        # ensure(): sweep once, then table hits (memory and disk)
        d = str(tmp_path / "tune")
        c0 = hist_tune.tune_counts()
        r1 = hist_tune.ensure(2000, 4, 15, kmax=32, dir_path=d)
        c1 = hist_tune.tune_counts()
        assert c1["sweeps"] == c0["sweeps"] + 1
        path = os.path.join(d, hist_tune.TUNE_FILE)
        assert os.path.exists(path)
        r2 = hist_tune.ensure(2000, 4, 15, kmax=32, dir_path=d)
        c2 = hist_tune.tune_counts()
        assert c2["sweeps"] == c1["sweeps"] and r2 == r1
        # a fresh process-view miss still resolves from DISK, no sweep
        with hist_tune._LOCK:
            hist_tune._MEM.clear()
        r3 = hist_tune.ensure(2000, 4, 15, kmax=32, dir_path=d)
        assert r3 == r1
        assert hist_tune.tune_counts()["sweeps"] == c2["sweeps"]
        table = json.load(open(path))
        key = next(iter(table))
        assert "kmax32" in key and table[key]["k"] == r1["k"]

    def test_booster_hist_tune_on_uses_choice(self, data, tmp_path):
        from lightgbm_tpu.ops import hist_tune
        x, y = data
        d = str(tmp_path / "cache")
        c0 = hist_tune.tune_counts()["sweeps"]
        bst = _train(x, y, rounds=2, hist_tune="on",
                     compile_cache_dir=d)
        assert hist_tune.tune_counts()["sweeps"] == c0 + 1
        assert os.path.exists(os.path.join(d, hist_tune.TUNE_FILE))
        assert _auc(y, bst.predict(x)) > 0.8
        # second booster on the same shape bucket: zero re-tune
        _train(x, y, rounds=2, hist_tune="on", compile_cache_dir=d)
        assert hist_tune.tune_counts()["sweeps"] == c0 + 1

    def test_hist_tune_off_is_default_and_exact(self, data):
        """hist_tune=off must never consult the tuner — identical
        bytes to a run with the param unset."""
        from lightgbm_tpu.ops import hist_tune
        x, y = data
        c0 = hist_tune.tune_counts()["sweeps"]
        a = _train(x, y, num_leaves=15)
        b = _train(x, y, num_leaves=15, hist_tune="off")
        assert hist_tune.tune_counts()["sweeps"] == c0
        assert _strip_params(a.model_to_string()) == \
            _strip_params(b.model_to_string())

    def test_bad_hist_tune_value_rejected(self, data):
        x, y = data
        with pytest.raises(Exception):
            _train(x, y, rounds=1, hist_tune="sometimes")

    def test_explicit_split_batch_wins_over_tuner(self, data, tmp_path):
        """An explicit width is the user's choice: the tuner engages
        only for split_batch=0 — with an explicit width it must not
        even sweep (a tuned block_rows paired to a different K would
        re-partition the f32 scan against the explicit-width byte
        pins)."""
        from lightgbm_tpu.ops import hist_tune
        x, y = data
        d = str(tmp_path / "cache")
        c0 = hist_tune.tune_counts()["sweeps"]
        a = _train(x, y, split_batch=16, hist_tune="on",
                   compile_cache_dir=d)
        assert hist_tune.tune_counts()["sweeps"] == c0
        assert not os.path.exists(os.path.join(d, hist_tune.TUNE_FILE))
        b = _train(x, y, split_batch=16)
        assert _strip_params(a.model_to_string()) == \
            _strip_params(b.model_to_string())

    def test_tiny_budget_skips_sweep_cleanly(self, data, tmp_path):
        """num_leaves <= 8 admits no set width: hist_tune=on must skip
        the sweep (not crash-and-warn every fit) and train strict."""
        from lightgbm_tpu.ops import hist_tune
        x, y = data
        d = str(tmp_path / "cache")
        c0 = hist_tune.tune_counts()["sweeps"]
        a = _train(x, y, rounds=2, num_leaves=5, split_batch=0,
                   hist_tune="on", compile_cache_dir=d)
        assert hist_tune.tune_counts()["sweeps"] == c0
        b = _train(x, y, rounds=2, num_leaves=5, split_batch=0)
        assert _strip_params(a.model_to_string()) == \
            _strip_params(b.model_to_string())
