"""Random forest mode (reference: /root/reference/src/boosting/rf.hpp:217).

No shrinkage, bagging required; every tree fits the full gradient computed
at the constant init score (rf.hpp ``GetTrainingScore`` returns the
boost-from-average score only), the init bias is folded into every tree
(rf.hpp:137 ``AddBias``), and predictions are averaged over iterations
(``average_output_`` flag, rf.hpp:28).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .gbdt import GBDTModel


class RFModel(GBDTModel):
    _bias_in_every_tree = True
    average_output = True

    def __init__(self, config, train_set, objective, hist_reduce=None):
        if config.bagging_freq <= 0 or not (0.0 < config.bagging_fraction < 1.0):
            raise ValueError("rf requires bagging (bagging_freq>0, "
                             "0<bagging_fraction<1)")
        super().__init__(config, train_set, objective, hist_reduce)
        self._const_score = None

    def _score_for_gradients(self):
        if self._const_score is None:
            init = [0.0] * self.num_class
            if self.objective is not None and self.config.boost_from_average:
                init = [self.objective.boost_from_score(k)
                        for k in range(self.num_class)]
            self._init_scores = init
            self._const_score = jnp.broadcast_to(
                jnp.asarray(init, jnp.float32),
                (self.num_data, self.num_class))
        return self._const_score

    def train_one_iter(self, grad=None, hess=None) -> bool:
        self._score_for_gradients()  # ensure _init_scores exists at iter 0
        self._init_applied_backup = self._init_applied
        # prevent the base from also adding init to the scorers
        self._init_applied = True
        try:
            return super().train_one_iter(grad, hess)
        finally:
            self._init_applied = self._init_applied_backup
