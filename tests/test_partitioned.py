"""The partitioned (performance) grower must match the jitted masked grower
exactly — same trees, same partitions (SURVEY.md §7: subtraction trick +
DataPartition parity)."""

import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.grower import make_grower
from lightgbm_tpu.grower_partitioned import PartitionedGrower
from lightgbm_tpu.ops.split import SplitParams


def _data(n=3000, f=6, b=16, seed=0, bag=False):
    rng = np.random.RandomState(seed)
    binned = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    y = (binned[:, 2] >= b // 2).astype(np.float32) \
        + 0.3 * rng.randn(n).astype(np.float32)
    g = (0.5 - y).astype(np.float32)
    w = (rng.rand(n) < 0.7).astype(np.float32) if bag else np.ones(n, np.float32)
    vals = np.stack([g * w, w, w], axis=1)
    return binned, vals


@pytest.mark.parametrize("bag", [False, True])
@pytest.mark.parametrize("na", [False, True])
def test_matches_masked_grower(bag, na):
    binned, vals = _data(bag=bag)
    n, f = binned.shape
    B, L = 16, 8
    if na:
        # make last bin of feature 0 the NaN bin
        na_bin = np.full(f, -1, np.int32)
        na_bin[0] = B - 1
    else:
        na_bin = np.full(f, -1, np.int32)
    p = SplitParams(min_data_in_leaf=5)
    nb = jnp.full(f, B, jnp.int32)
    nab = jnp.asarray(na_bin)
    fm = jnp.ones(f, bool)

    masked = make_grower(num_leaves=L, num_bins=B, params=p)
    t1 = masked(jnp.asarray(binned), jnp.asarray(vals), fm, nb, nab)
    part = PartitionedGrower(num_leaves=L, num_bins=B, params=p)
    t2 = part(jnp.asarray(binned), jnp.asarray(vals), fm, nb, nab)

    assert int(t1.num_leaves) == int(t2.num_leaves) > 2
    nl = int(t1.num_leaves)
    for k in ("split_feature", "threshold_bin", "default_left",
              "left_child", "right_child"):
        np.testing.assert_array_equal(
            np.asarray(getattr(t1, k))[:nl - 1],
            np.asarray(getattr(t2, k))[:nl - 1], err_msg=k)
    np.testing.assert_allclose(np.asarray(t1.leaf_value)[:nl],
                               np.asarray(t2.leaf_value)[:nl],
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(np.asarray(t1.leaf_count)[:nl],
                               np.asarray(t2.leaf_count)[:nl], atol=0.5)
    np.testing.assert_array_equal(np.asarray(t1.leaf_of_row),
                                  np.asarray(t2.leaf_of_row))


def test_max_depth_respected():
    binned, vals = _data()
    f = binned.shape[1]
    B, L = 16, 16
    p = SplitParams(min_data_in_leaf=5)
    part = PartitionedGrower(num_leaves=L, num_bins=B, params=p, max_depth=2)
    t = part(jnp.asarray(binned), jnp.asarray(vals), jnp.ones(f, bool),
             jnp.full(f, B, jnp.int32), jnp.full(f, -1, jnp.int32))
    assert int(t.num_leaves) <= 4


class TestHistogramPool:
    def test_tiny_pool_same_model(self, binary_data):
        """histogram_pool_size bounding (HistogramPool analog,
        feature_histogram.hpp:1095): evictions force direct child
        reconstruction instead of subtraction; the grown trees must be
        identical."""
        x, y = binary_data
        base = {"objective": "binary", "num_leaves": 31, "max_bin": 63,
                "min_data_in_leaf": 5, "verbosity": -1,
                "enable_bundle": False}
        b1 = lgb.train(base, lgb.Dataset(x, label=y), num_boost_round=5)
        # ~tiny pool: room for only a couple of leaf histograms
        tiny = dict(base, histogram_pool_size=0.0001)
        b2 = lgb.train(tiny, lgb.Dataset(x, label=y), num_boost_round=5)
        # rebuilt-from-scratch histograms round differently in f32 than
        # parent-minus-sibling subtraction, so require quality parity (the
        # reference's f64 CPU pool is bit-exact; GPU docs accept tiny AUC
        # deltas the same way, GPU-Performance.rst:133-160)
        assert len(b1.trees) == len(b2.trees)
        from lightgbm_tpu.metrics import _auc
        a1 = _auc(y, b1.predict(x, raw_score=True), None)
        a2 = _auc(y, b2.predict(x, raw_score=True), None)
        assert abs(a1 - a2) < 0.01, (a1, a2)
        assert np.corrcoef(b1.predict(x), b2.predict(x))[0, 1] > 0.98
