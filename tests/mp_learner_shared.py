"""Shared fixtures for the multi-process feature-/voting-parallel
topology tests — imported by both the spawned worker
(tests/mp_learner_worker.py) and the host test, so data, params, and
mapper fitting are byte-identical in every topology."""

import numpy as np

PARAMS = {
    "objective": "binary",
    "num_leaves": 15,
    "min_data_in_leaf": 5,
    "max_bin": 63,
    "learning_rate": 0.2,
    "verbosity": -1,
}
ROUNDS = 5

# row-sampling variants: under feature-parallel the rows are REPLICATED
# per process, so the sampling draws must be identical on every rank
# (gbdt.py skips the per-rank RNG fold-in for dist == "feature") — these
# exercise exactly that contract
VARIANTS = {
    "": {},
    "goss": {"data_sample_strategy": "goss", "top_rate": 0.2,
             "other_rate": 0.15, "bagging_seed": 5},
    "bag": {"bagging_fraction": 0.7, "bagging_freq": 1,
            "bagging_seed": 5},
}


def global_data(n=4096, f=12, seed=7):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float64)
    y = (x[:, 0] - 0.7 * x[:, 1] + 0.4 * x[:, 2]
         + 0.3 * rng.randn(n) > 0).astype(np.float32)
    return x, y


def full_data_mappers(x):
    from lightgbm_tpu.binning import BinMapper
    from lightgbm_tpu.config import Config
    cfg = Config(dict(PARAMS))
    mappers = []
    for j in range(x.shape[1]):
        m = BinMapper()
        m.find_bin(x[:, j], len(x), cfg.max_bin,
                   cfg.min_data_in_bin, use_missing=cfg.use_missing,
                   zero_as_missing=cfg.zero_as_missing)
        mappers.append(m)
    return mappers
