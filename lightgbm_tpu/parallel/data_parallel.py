"""Data-parallel tree learner: rows sharded over the mesh ``data`` axis.

TPU-native redesign of the reference DataParallelTreeLearner
(/root/reference/src/treelearner/data_parallel_tree_learner.cpp:13-283):

- rows live sharded; every shard builds LOCAL histograms for all features;
- the reference's ``Network::ReduceScatter(hists, HistogramSumReducer)``
  (:185) + ``SyncUpGlobalBestSplit`` allgather (:260) collapse into ONE
  ``lax.psum`` of the histogram tensor over the mesh axis — after which the
  split decision is computed REPLICATED on every shard (no separate
  best-split sync needed, and XLA is free to lower the psum as
  reduce-scatter + all-gather over ICI);
- the root Σgrad/Σhess allreduce (:126-152) falls out of the same psum
  (totals are a histogram marginal);
- row partition stays local (no row data ever moves, like the reference).

The same grower program (grower.py) is used — distribution is a
``shard_map`` wrapper + a psum hook, not a separate learner implementation.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..grower import TreeArrays, make_grower
from ..ops.split import SplitParams


def pad_to_multiple(n: int, k: int) -> int:
    return (n + k - 1) // k * k


def shard_rows(mesh: Mesh, arr, axis: str = "data"):
    """Place a row-major array sharded over the mesh data axis (rows padded
    by the caller to a multiple of the axis size).

    Multi-process (one controller per host, the TPU-pod topology): ``arr``
    is each process's LOCAL rows and the global array is assembled with
    ``make_array_from_process_local_data`` — ``device_put`` of a global
    value is single-controller-only (every process would need the whole
    array, and JAX asserts the values match across processes).  The
    caller must have padded every process to the same local row count."""
    spec = P(axis, *([None] * (np.ndim(arr) - 1)))
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(sharding,
                                                      np.asarray(arr))
    return jax.device_put(jnp.asarray(arr), sharding)


def make_dp_grower(mesh: Mesh, *, num_leaves: int, num_bins: int,
                   params: SplitParams, max_depth: int = -1,
                   block_rows: int = 0, axis: str = "data", efb=None,
                   split_batch: int = 1, mono=None,
                   mono_penalty: float = 0.0, sparse: bool = False):
    """Jitted data-parallel ``grow_tree`` over ``mesh``.

    Inputs: binned [N, F] (or the bundled [N, G] group matrix when ``efb``
    is set) and vals [N, 3] sharded on rows; feature metadata replicated.
    Output tree arrays are replicated; ``leaf_of_row`` stays row-sharded.
    Child histograms use the masked full pass (gather tiers measured slower
    on TPU — PROFILE.md §2), which also keeps every shard's collective
    schedule trivially congruent.  With ``efb`` the psum payload shrinks to
    the bundled group-space histograms — exactly where the reference
    bundles before reduce-scatter (dataset.cpp:239;
    data_parallel_tree_learner.cpp:174-186).
    """
    inner = make_grower(
        num_leaves=num_leaves, num_bins=num_bins, params=params,
        max_depth=max_depth, block_rows=block_rows,
        hist_reduce=lambda h: lax.psum(h, axis),
        sum_reduce=lambda t: lax.psum(t, axis), efb=efb,
        split_batch=split_batch, mono=mono, mono_penalty=mono_penalty,
        jit=False)

    out_specs = TreeArrays(
        num_leaves=P(), split_feature=P(), threshold_bin=P(),
        default_left=P(), left_child=P(), right_child=P(), split_gain=P(),
        leaf_value=P(), leaf_weight=P(), leaf_count=P(), internal_value=P(),
        internal_weight=P(), internal_count=P(), leaf_depth=P(),
        leaf_of_row=P(axis), is_cat_node=P(), cat_rank=P(), n_steps=P())

    if sparse:
        # SparseBinned pytree (sparse_data.py): the flat [N, K] entry
        # matrix shards on rows while the [F] default_bin vector is
        # replicated — a single prefix spec cannot describe both leaves,
        # so the wrapper ships the leaves as separate shard_map arguments
        # and rebuilds the pytree inside (stride/F are static aux, cached
        # per shape).
        from ..sparse_data import SparseBinned
        cache = {}

        def _sparse_fn(stride: int, nf: int):
            def wrapped(flat, db, vals, fm, nb, nab, nabp, ic):
                return inner(SparseBinned(flat, db, stride, nf), vals,
                             fm, nb, nab, nabp, ic)
            return jax.shard_map(
                wrapped, mesh=mesh,
                in_specs=(P(axis, None), P(None), P(axis, None),
                          P(), P(), P(), P(), P()),
                out_specs=out_specs, check_vma=False)

        def grow(binned, vals, feature_mask, num_bin, na_bin, is_cat=None):
            if is_cat is None:
                is_cat = jnp.zeros(num_bin.shape[0], bool)
            key = (binned.stride, binned.num_features)
            if key not in cache:
                cache[key] = jax.jit(_sparse_fn(*key))
            return cache[key](binned.flat, binned.default_bin, vals,
                              feature_mask, num_bin, na_bin, na_bin,
                              is_cat)

        return grow

    f = jax.shard_map(
        inner, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(), P(), P(), P(), P()),
        out_specs=out_specs, check_vma=False)

    def grow(binned, vals, feature_mask, num_bin, na_bin, is_cat=None):
        if is_cat is None:
            is_cat = jnp.zeros(num_bin.shape[0], bool)
        return f(binned, vals, feature_mask, num_bin, na_bin, na_bin, is_cat)

    return jax.jit(grow)
