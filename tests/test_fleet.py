"""Fleet subsystem: vmapped multi-forest training + segment serving.

The contract under test (docs/Fleet.md):

- ``fleet_train`` grows N same-shape boosters inside ONE vmapped
  super-epoch program, and every member is BYTE-IDENTICAL to a solo
  ``lgb.train`` run of that member's params (``fr.member_params[j]``)
  — per-member RNG isolation across bagging, GOSS, sweeps, early
  stopping and quantized training;
- one stacked host fetch per fleet epoch (the solo one-sync-per-epoch
  guarantee, N-wide);
- kill + resume at an epoch boundary restores all N members
  byte-identically from their per-member snapshots;
- the serve-side ``SegmentRouter`` routes request ``segment`` keys
  across co-resident registry versions byte-for-byte with the solo
  predict of each routed model, falls back to the default segment for
  unknown keys, and per-segment promotion never touches the registry's
  current pointer;
- metric label cardinality stays bounded (``serve_metrics_max_versions``
  collapses overflow segments into ``__other__``) and the residency cap
  (``serve_max_resident``) never evicts a version with requests in
  flight.
"""

import glob
import os
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.fleet import (FleetResult, SegmentRouter, expand_members,
                                fleet_train, parse_sweep)

BASE = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
        "max_bin": 31, "min_data_in_leaf": 5, "verbosity": -1,
        "deterministic": True, "superepoch": 8, "fused_eval": True,
        "fused_chunk": 8, "metric": ["binary_logloss"],
        "padded_leaves": True, "split_batch": 1, "tpu_learner": "masked"}


def _data(n=1200, f=10, seed=7):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float32)
    y = (x[:, 0] - 0.5 * x[:, 1] + 0.4 * x[:, 2] * x[:, 3]
         + 0.3 * rng.randn(n) > 0).astype(np.float32)
    return x, y


def _sets(x, y, params, n_train=1000):
    ds = lgb.Dataset(x[:n_train], label=y[:n_train], params=params)
    va = lgb.Dataset(x[n_train:], label=y[n_train:], params=params,
                     reference=ds)
    return ds, va


# ---------------------------------------------------------------------------
# roster expansion


def test_parse_sweep_grid():
    grid = parse_sweep("learning_rate=0.05|0.1;num_leaves=31|63")
    assert len(grid) == 4
    assert {(g["learning_rate"], g["num_leaves"]) for g in grid} == \
        {(0.05, 31), (0.05, 63), (0.1, 31), (0.1, 63)}
    # aliases resolve to the canonical member-axis name
    assert parse_sweep("eta=0.2") == [{"learning_rate": 0.2}]
    assert parse_sweep("") == []


def test_parse_sweep_rejects_non_member_axis():
    with pytest.raises(ValueError, match="member-axis"):
        parse_sweep("max_bin=31|63")
    with pytest.raises(ValueError, match="unknown parameter"):
        parse_sweep("not_a_param=1|2")
    with pytest.raises(ValueError, match="malformed"):
        parse_sweep("learning_rate")


def test_expand_members_precedence_and_paths():
    p = dict(BASE, output_model="m.txt", fleet_members=3,
             fleet_sweep="learning_rate=0.05|0.1")
    # explicit members= wins over the sweep, the sweep over replicas
    mm = expand_members(p, members=[{"seed": 1}, {"seed": 2}])
    assert len(mm) == 2 and mm[1]["seed"] == 2
    mm = expand_members(p)
    assert len(mm) == 2 and mm[0]["learning_rate"] == 0.05
    mm = expand_members(dict(p, fleet_sweep=""))
    assert len(mm) == 3 and mm[2]["seed"] == 2    # cfg.seed=0 + j
    # per-member snapshot/model paths never collide
    assert [m["output_model"] for m in mm] == \
        ["m.txt.member0", "m.txt.member1", "m.txt.member2"]
    with pytest.raises(ValueError, match="member-axis"):
        expand_members(p, members=[{"max_depth": 3}])


# ---------------------------------------------------------------------------
# byte-identity vs solo training (per-member RNG isolation)


def _solo(member_params, x, y, rounds):
    ds, va = _sets(x, y, member_params)
    return lgb.train(dict(member_params), ds, num_boost_round=rounds,
                     valid_sets=[va])


def _assert_fleet_matches_solo(params, members=None, rounds=16):
    x, y = _data()
    ds, va = _sets(x, y, params)
    fr = fleet_train(dict(params), ds, num_boost_round=rounds,
                     valid_sets=[va], members=members)
    assert isinstance(fr, FleetResult) and len(fr) >= 2
    assert fr.epochs >= 1, "the vmapped epoch path must engage"
    for j in range(len(fr)):
        sb = _solo(fr.member_params[j], x, y, rounds)
        assert fr[j].model_to_string() == sb.model_to_string(), \
            f"member {j} diverged from its solo run"
        assert fr[j].best_iteration == sb.best_iteration
    return fr


MATRIX = {
    "bagging_replicas": (
        {"bagging_fraction": 0.7, "bagging_freq": 1, "fleet_members": 2},
        None),
    "lr_leaves_sweep_es": (
        {"fleet_sweep": "learning_rate=0.05|0.1;num_leaves=31|63",
         "early_stopping_round": 5},
        None),
    "goss_grid": (
        {"data_sample_strategy": "goss"},
        [{"bagging_seed": 3}, {"bagging_seed": 11}]),
    "quant_int8": (
        {"quant_train": True, "quant_bits": 8, "fleet_members": 2},
        None),
}


@pytest.mark.parametrize("name", list(MATRIX))
def test_fleet_byte_identity(name):
    extra, members = MATRIX[name]
    _assert_fleet_matches_solo(dict(BASE, **extra), members=members)


def test_fleet_early_stop_members_match_solo():
    # aggressive lr + tight patience: members stop at DIFFERENT rounds
    # (ragged early stop masks finished members inside the scan), and
    # each still matches its solo run's best_iteration and forest
    p = dict(BASE, fleet_members=2, early_stopping_round=3,
             learning_rate=0.5, num_leaves=31)
    fr = _assert_fleet_matches_solo(p, rounds=40)
    assert any(fr.stopped), "expected at least one early-stopped member"


# ---------------------------------------------------------------------------
# one stacked fetch per fleet epoch


def test_fleet_one_fetch_per_epoch():
    from lightgbm_tpu.models.gbdt import GBDTModel
    labels = []
    orig = GBDTModel._eget

    def spy(self, v, label=None):
        labels.append(label)
        return orig(self, v, label)

    x, y = _data()
    p = dict(BASE, fleet_members=2)
    ds, va = _sets(x, y, p)
    GBDTModel._eget = spy
    try:
        fr = fleet_train(dict(p), ds, num_boost_round=16,
                         valid_sets=[va])
    finally:
        GBDTModel._eget = orig
    # 16 rounds at k=8 -> 2 fleet epochs -> 2 stacked fetches carrying
    # ALL members' telemetry; the solo fused fetch never fires
    assert labels.count("fleet_fetch") == fr.epochs == 2
    assert "fused_fetch" not in labels


# ---------------------------------------------------------------------------
# validation guards


def test_fleet_requires_two_members():
    x, y = _data(n=400)
    ds = lgb.Dataset(x, label=y, params=BASE)
    with pytest.raises(ValueError, match="member"):
        fleet_train(dict(BASE), ds, num_boost_round=4,
                    members=[{"seed": 1}])


def test_fleet_rejects_shape_forking_sweep():
    # 15 vs 31 leaves land in DIFFERENT leaf-pad buckets: the roster
    # cannot share one program and must refuse, naming the contract
    x, y = _data(n=400)
    ds = lgb.Dataset(x, label=y, params=BASE)
    with pytest.raises(ValueError, match="program shape|member"):
        fleet_train(dict(BASE), ds, num_boost_round=4,
                    members=[{"num_leaves": 15}, {"num_leaves": 31}])


# ---------------------------------------------------------------------------
# kill + resume at an epoch boundary


def test_fleet_kill_resume_restores_all_members(tmp_path):
    out = str(tmp_path / "m.txt")
    p = dict(BASE, snapshot_freq=8, output_model=out, fleet_members=3,
             bagging_fraction=0.7, bagging_freq=1)
    x, y = _data()

    def run(rounds, resume=False):
        ds, va = _sets(x, y, p)
        pp = dict(p, resume=True) if resume else dict(p)
        return fleet_train(pp, ds, num_boost_round=rounds,
                           valid_sets=[va])

    straight = run(16)
    texts = [b.model_to_string() for b in straight.boosters]
    for f in glob.glob(out + "*"):
        os.unlink(f)

    run(8)                          # "crash" after one epoch (snapshot)
    resumed = run(16, resume=True)
    assert len(resumed) == 3
    for j in range(3):
        assert resumed[j].model_to_string() == texts[j], \
            f"member {j} not restored byte-identically"


# ---------------------------------------------------------------------------
# segment-routed serving


def _train_solo_model(x, y, seed):
    p = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
         "min_data_in_leaf": 5, "verbosity": -1, "deterministic": True,
         "seed": seed, "bagging_seed": 3 + seed}
    return lgb.train(p, lgb.Dataset(x, label=y, params=p),
                     num_boost_round=8)


class TestSegmentRouting:
    def test_router_resolution(self):
        r = SegmentRouter("default")
        assert r.resolve("eu") == (None, True)     # unknown, no default
        r.assign("default", "v1")
        r.assign("eu", "v2")
        assert r.resolve("eu") == ("v2", False)
        assert r.resolve("unknown") == ("v1", True)
        assert r.resolve(None) == ("v1", False)    # unkeyed: no miss
        assert r.fallbacks() == 2
        assert r.unassign("eu") == "v2"
        assert r.resolve("eu") == ("v1", True)
        r.assign("us", "v9")
        assert r.drop_version("v9") == ["us"]

    def test_segment_parity_promote_and_fallback(self, tmp_path):
        from lightgbm_tpu.serve.server import Server
        x, y = _data(n=500)
        paths, solos = [], []
        for j in range(3):
            b = _train_solo_model(x, y, j)
            fp = str(tmp_path / f"m{j}.txt")
            b.save_model(fp)
            paths.append(fp)
            solos.append(lgb.Booster(model_file=fp).predict(x[:16]))
        srv = Server({"verbosity": -1, "shadow_probe_batches": 4,
                      "serve_metrics_max_versions": 2},
                     model_file=paths[0])
        try:
            for _ in range(3):      # feed the shadow-parity gate ring
                srv.predict(x[:16])
            v1, _ = srv.promote(model_file=paths[1], segment="eu")
            v2, _ = srv.promote(model_file=paths[2], segment="us")
            # per-segment promote never moves the default pointer
            assert srv.registry.current().version not in (v1, v2)
            # byte-for-byte parity with each routed model's solo predict
            assert np.array_equal(srv.predict(x[:16], segment="eu"),
                                  solos[1])
            assert np.array_equal(srv.predict(x[:16], segment="us"),
                                  solos[2])
            # unknown key falls back to the default segment's serving
            assert np.array_equal(srv.predict(x[:16], segment="nope"),
                                  solos[0])
            assert np.array_equal(srv.predict(x[:16]), solos[0])
            assert srv.router.fallbacks() >= 1
            snap = srv.metrics_snapshot()
            assert snap["serve.segments"] == {"eu": v1, "us": v2}
            # label cardinality bound: cap=2, the third distinct
            # segment's row counter collapses into __other__
            rows_keys = {k for k in snap
                         if k.startswith("serve.segment_rows")}
            assert "serve.segment_rows{segment=__other__}" in rows_keys
            assert len(rows_keys) <= 3
            # rollback: unassigning routes the segment back to default
            srv.router.unassign("eu")
            assert np.array_equal(srv.predict(x[:16], segment="eu"),
                                  solos[0])
        finally:
            srv.close()

    def test_batcher_never_mixes_segments(self):
        from lightgbm_tpu.serve.batcher import MicroBatcher
        seen = []
        lock = threading.Lock()

        def predict(rows, key=None):
            with lock:
                seen.append((len(rows), key))
            return np.zeros(len(rows)), {"key": key}

        mb = MicroBatcher(predict, max_batch=64, max_wait_ms=20.0)
        try:
            futs = [mb.submit(np.zeros((1, 4)),
                              key=("a", "b", None)[i % 3])
                    for i in range(30)]
            for f in futs:
                f.result(timeout=10)
        finally:
            mb.close()
        # coalescing stops at a key boundary: every dispatched batch
        # carries exactly one segment key (version isolation per batch)
        assert sum(n for n, _ in seen) == 30
        assert {k for _, k in seen} == {"a", "b", None}


# ---------------------------------------------------------------------------
# residency-cap eviction with in-flight pinning (stress)


@pytest.mark.stress
def test_eviction_never_drops_inflight_version(tmp_path):
    # ~100 co-resident versions churn through a small residency cap
    # while requests are IN FLIGHT on pinned versions: the cap must
    # displace idle versions only — no in-flight request ever loses the
    # model it resolved (registry skips versions with inflight > 0)
    from lightgbm_tpu.serve.registry import ModelRegistry
    x, y = _data(n=300)
    bst = _train_solo_model(x, y, 0)
    ms = bst.model_to_string()
    reg = ModelRegistry(max_batch=32, max_resident=8)
    v0 = reg.load(model_str=ms)
    pinned = [reg.get(v0)]
    errors = []
    stop = threading.Event()

    def pinner(served):
        # hold requests open on a pinned version while the churn runs
        try:
            while not stop.is_set():
                served.begin_request()
                try:
                    served.booster.predict(x[:4])
                finally:
                    served.end_request()
        except Exception as e:      # noqa: BLE001
            errors.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=pinner, args=(pinned[0],))
               for _ in range(3)]
    for t in threads:
        t.start()
    try:
        versions = [v0]
        for i in range(100):
            versions.append(reg.load(model_str=ms, activate=False))
            if i == 50:             # pin a mid-churn version too
                served = reg.get(versions[-1])
                pinned.append(served)
                t = threading.Thread(target=pinner, args=(served,))
                t.start()
                threads.append(t)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors
    # the cap held (pinned versions may exceed it transiently)…
    assert len(reg.versions()) <= 8 + len(pinned)
    # …and every pinned version is still resident and lookupable
    for served in pinned:
        assert reg.get(served.version) is served
