"""SHAP feature contributions (TreeSHAP).

Analog of the reference ``Tree::PredictContrib`` / per-path Shapley
(/root/reference/include/LightGBM/tree.h:666, src/io/tree.cpp): the
polynomial-time TreeSHAP recursion with EXTEND/UNWIND path bookkeeping.
Host-side NumPy implementation; output layout matches the reference
(``[n_features + 1]`` per example per class, last column = expected value).
"""

from __future__ import annotations

from typing import List

import numpy as np


class _Path:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, depth: int):
        self.feature_index = np.zeros(depth, np.int32)
        self.zero_fraction = np.zeros(depth, np.float64)
        self.one_fraction = np.zeros(depth, np.float64)
        self.pweight = np.zeros(depth, np.float64)


def _extend(p: _Path, length: int, zero_frac: float, one_frac: float,
            fidx: int) -> None:
    p.feature_index[length] = fidx
    p.zero_fraction[length] = zero_frac
    p.one_fraction[length] = one_frac
    p.pweight[length] = 1.0 if length == 0 else 0.0
    for i in range(length - 1, -1, -1):
        p.pweight[i + 1] += one_frac * p.pweight[i] * (i + 1) / (length + 1)
        p.pweight[i] = zero_frac * p.pweight[i] * (length - i) / (length + 1)


def _unwind(p: _Path, length: int, index: int) -> None:
    one = p.one_fraction[index]
    zero = p.zero_fraction[index]
    n = p.pweight[length]
    for i in range(length - 1, -1, -1):
        if one != 0.0:
            t = p.pweight[i]
            p.pweight[i] = n * (length + 1) / ((i + 1) * one)
            n = t - p.pweight[i] * zero * (length - i) / (length + 1)
        else:
            p.pweight[i] = p.pweight[i] * (length + 1) / (zero * (length - i))
    for i in range(index, length):
        p.feature_index[i] = p.feature_index[i + 1]
        p.zero_fraction[i] = p.zero_fraction[i + 1]
        p.one_fraction[i] = p.one_fraction[i + 1]


def _unwound_sum(p: _Path, length: int, index: int) -> float:
    one = p.one_fraction[index]
    zero = p.zero_fraction[index]
    total = 0.0
    n = p.pweight[length]
    for i in range(length - 1, -1, -1):
        if one != 0.0:
            t = n * (length + 1) / ((i + 1) * one)
            total += t
            n = p.pweight[i] - t * zero * (length - i) / (length + 1)
        else:
            total += p.pweight[i] / (zero * (length - i) / (length + 1))
    return total


def _tree_shap(tree, x: np.ndarray, phi: np.ndarray, node: int, depth: int,
               p: _Path, parent_zero: float, parent_one: float,
               parent_fidx: int) -> None:
    # copy parent path
    q = _Path(depth + 4)
    q.feature_index[:depth + 1] = p.feature_index[:depth + 1]
    q.zero_fraction[:depth + 1] = p.zero_fraction[:depth + 1]
    q.one_fraction[:depth + 1] = p.one_fraction[:depth + 1]
    q.pweight[:depth + 1] = p.pweight[:depth + 1]
    _extend(q, depth, parent_zero, parent_one, parent_fidx)

    if node < 0:  # leaf
        leaf = ~node
        w = tree.leaf_value[leaf]
        for i in range(1, depth + 1):
            total = _unwound_sum(q, depth, i)
            phi[q.feature_index[i]] += total * (q.one_fraction[i]
                                                - q.zero_fraction[i]) * w
        return

    f = int(tree.split_feature[node])
    go_left = bool(tree._decide(node, x[f:f + 1])[0])
    hot = tree.left_child[node] if go_left else tree.right_child[node]
    cold = tree.right_child[node] if go_left else tree.left_child[node]
    w_node = float(tree.internal_count[node]) or 1.0
    hot_cnt = (float(tree.leaf_count[~hot]) if hot < 0
               else float(tree.internal_count[hot]))
    cold_cnt = (float(tree.leaf_count[~cold]) if cold < 0
                else float(tree.internal_count[cold]))
    hot_frac = hot_cnt / w_node
    cold_frac = cold_cnt / w_node

    # undo duplicated feature on the path
    incoming_zero, incoming_one = 1.0, 1.0
    path_idx = -1
    for i in range(1, depth + 1):
        if q.feature_index[i] == f:
            path_idx = i
            break
    if path_idx >= 0:
        incoming_zero = q.zero_fraction[path_idx]
        incoming_one = q.one_fraction[path_idx]
        _unwind(q, depth, path_idx)
        depth -= 1

    _tree_shap(tree, x, phi, hot, depth + 1, q,
               hot_frac * incoming_zero, incoming_one, f)
    _tree_shap(tree, x, phi, cold, depth + 1, q,
               cold_frac * incoming_zero, 0.0, f)


def tree_contrib(tree, x: np.ndarray) -> np.ndarray:
    """SHAP values of one tree for one example; [-1] is the base value."""
    nf = int(tree.split_feature.max()) + 1 if tree.num_nodes() > 0 else 0
    phi = np.zeros(max(nf, len(x)) + 1)
    if tree.num_leaves <= 1:
        phi[-1] += tree.leaf_value[0]
        return phi[:len(x) + 1]
    # expected value = count-weighted mean of leaves
    total = tree.leaf_count.sum()
    phi_base = float((tree.leaf_value * tree.leaf_count).sum() / max(total, 1))
    phi[-1] = phi_base
    p = _Path(4)
    _tree_shap(tree, x, phi, 0, 0, p, 1.0, 1.0, -1)
    out = np.zeros(len(x) + 1)
    out[:min(len(phi) - 1, len(x))] = phi[:min(len(phi) - 1, len(x))]
    out[-1] = phi[-1]
    return out


def predict_contrib(booster, x: np.ndarray, t0: int, t1: int) -> np.ndarray:
    """Booster-level SHAP (LGBM_BoosterPredictForMat + predict_contrib)."""
    n, nf = x.shape
    k = booster._num_tree_per_iteration
    if any(booster.trees[ti].is_linear for ti in range(t0, t1)):
        raise ValueError(
            "pred_contrib (SHAP) is not supported for linear-tree models "
            "(contributions would ignore the leaf linear terms)")
    out = np.zeros((n, k, nf + 1))
    for ti in range(t0, t1):
        t = booster.trees[ti]
        w = booster.tree_weights[ti]
        for i in range(n):
            out[i, ti % k] += w * tree_contrib(t, x[i])
    if booster._average_output and t1 > t0:
        out /= (t1 - t0) // k
    if k == 1:
        return out[:, 0, :]
    return out.reshape(n, k * (nf + 1))
