"""Plotting module smoke tests (test_plotting.py analog, SURVEY.md §4)."""

import matplotlib
matplotlib.use("Agg")

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import plotting


@pytest.fixture(scope="module")
def model():
    rs = np.random.RandomState(0)
    x = rs.randn(800, 5)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float32)
    res = {}
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "max_bin": 31,
                     "verbosity": -1, "metric": "binary_logloss"},
                    lgb.Dataset(x, label=y), num_boost_round=5,
                    valid_sets=[lgb.Dataset(x, label=y, reference=None)],
                    callbacks=[lgb.record_evaluation(res)])
    return bst, res


def test_plot_importance(model):
    bst, _ = model
    ax = plotting.plot_importance(bst)
    assert ax is not None


def test_plot_split_value_histogram(model):
    bst, _ = model
    feat = int(bst.trees[0].split_feature[0])
    ax = plotting.plot_split_value_histogram(bst, feat)
    assert ax is not None


def test_plot_metric(model):
    _, res = model
    ax = plotting.plot_metric(res)
    assert ax is not None


def test_create_tree_digraph(model):
    bst, _ = model
    g = plotting.create_tree_digraph(bst, 0)
    assert g  # dot source or graph object


def test_plot_tree(model):
    import shutil
    if shutil.which("dot") is None:
        pytest.skip("graphviz executable not installed")
    bst, _ = model
    ax = plotting.plot_tree(bst, tree_index=0)
    assert ax is not None


def test_custom_parser_registry(tmp_path):
    """ParserFactory analog: user-registered format handlers."""
    from lightgbm_tpu.data_io import load_text, register_parser
    p = tmp_path / "data.weird"
    p.write_text("1;1.0;2.0\n0;3.0;4.0\n")

    def parse_weird(path, has_header, label_column):
        rows = [ln.split(";") for ln in open(path) if ln.strip()]
        arr = np.asarray(rows, np.float64)
        return arr[:, 1:], arr[:, 0].astype(np.float32)

    register_parser("weird", parse_weird)
    x, y = load_text(str(p), fmt="weird")
    assert x.shape == (2, 2)
    np.testing.assert_array_equal(y, [1, 0])
