"""Per-phase wall-clock attribution of one training iteration on real TPU.

VERDICT r2 task 1 / PROFILE.md §1: attribute every millisecond of a
steady-state iteration to a named phase.  Since the obs subsystem this
script is a THIN consumer: it enables ``telemetry=true`` on the booster
and reads the per-phase spans the training loop itself emits
(grad / grow / fetch / score, models/gbdt.py) — the same spans a
production run records — plus a couple of raw-latency probes timed with
``obs.trace.timed_fenced``.

All fencing goes through ``obs.trace.fence`` (the device_get-of-a-scalar
trick): ``jax.block_until_ready`` is NOT trustworthy on the axon backend
(PROFILE.md methodology note — it can return with work still queued).

Output: a table on stderr + the JSONL trace (convertible to Perfetto
via ``python -c "from lightgbm_tpu.obs.trace import jsonl_to_chrome;
jsonl_to_chrome('profile_iter_trace.jsonl', 'trace.json')"``).

Run: python tools/profile_iter.py [n_rows] [num_leaves]
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    num_leaves = int(sys.argv[2]) if len(sys.argv) > 2 else 31

    rng = np.random.RandomState(0)
    f = 28
    x = rng.randn(n, f).astype(np.float32)
    logit = (1.2 * x[:, 0] - 0.8 * x[:, 1] + 0.6 * x[:, 2] * x[:, 3]
             + 0.4 * np.abs(x[:, 4]) + 0.5 * rng.randn(n))
    y = (logit > 0).astype(np.float32)

    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.obs.trace import Tracer, fence, timed_fenced

    devs = jax.devices()
    print(f"devices={devs}", file=sys.stderr)

    tracer = Tracer(sink_path="profile_iter_trace.jsonl")

    # raw tunnel round-trip: dispatch + fetch of a 4-byte scalar — the
    # latency floor every blocking call pays (PROFILE.md §1)
    one = fence(jnp.float32(1.0) + 0.0)
    t_rt_min, t_rt_avg = timed_fenced(
        lambda: jnp.float32(1.0) + one, iters=20, tracer=tracer,
        name="tunnel_roundtrip")
    print(f"tunnel round-trip (scalar op + fence): "
          f"min {t_rt_min*1e3:.1f} ms avg {t_rt_avg*1e3:.1f} ms",
          file=sys.stderr)

    import lightgbm_tpu as lgb

    params = {"objective": "binary", "num_leaves": num_leaves,
              "learning_rate": 0.1, "max_bin": 63, "min_data_in_leaf": 20,
              "verbosity": 0, "telemetry": True,
              "telemetry_trace_file": "profile_iter_trace.jsonl",
              "fused_chunk": 0}   # per-iteration path: that's what we attribute
    ds = lgb.Dataset(x, label=y, params=params)   # bin at the CLAIMED max_bin
    ds.construct()
    bst = lgb.Booster(params=params, train_set=ds)
    m = bst._model

    # one full update to compile everything
    t0 = time.perf_counter()
    bst.update()
    print(f"compile+iter1: {time.perf_counter()-t0:.1f} s", file=sys.stderr)

    # steady-state reps: the training loop's own phase spans do the
    # attribution — no replicated pipeline, no hand-rolled fences
    reps = 8
    obs = m._obs
    skip = {k: len(obs.tracer.durations(k))
            for k in ("grad", "grow", "fetch", "score")}
    t0 = time.perf_counter()
    for _ in range(reps):
        bst.update()
    fence(m.score)
    total = time.perf_counter() - t0

    print(f"\nper-phase (over {reps} reps), n={n} leaves={num_leaves}:",
          file=sys.stderr)
    phase_sum = 0.0
    for k in ("grad", "grow", "fetch", "score"):
        v = obs.tracer.durations(k)[skip[k]:]
        if not v:
            continue
        phase_sum += min(v)
        print(f"  {k:9s} min {min(v)*1e3:8.1f} ms   avg "
              f"{np.mean(v)*1e3:8.1f} ms", file=sys.stderr)
    print(f"  (sum of phase mins: {phase_sum*1e3:.1f} ms; measured "
          f"{total/reps*1e3:.1f} ms/iter)", file=sys.stderr)

    snap = bst.telemetry_finish()
    it = snap.get("train.iterations", {}).get("value", 0)
    isec = snap.get("train.iter_seconds", {})
    if isec.get("count"):
        print(f"\nmetrics: {it:g} iters, "
              f"mean {isec['sum']/isec['count']*1e3:.1f} ms/iter; "
              f"trace -> profile_iter_trace.jsonl", file=sys.stderr)


if __name__ == "__main__":
    main()
