"""Benchmark: HIGGS-shaped binary classification training throughput.

Mirrors the reference's headline experiment (docs/Experiments.rst: HIGGS,
500 iterations, num_leaves=255 -> 130.094 s on 2x E5-2690v4, i.e. 3.843
iters/s; GPU docs recommend 63 bins for accelerator runs,
docs/GPU-Performance.rst:108-124).

Primary metric (round-over-round comparable): steady-state iters/s on a
1M-row slice at 31 leaves / 63 bins; ``vs_baseline`` is against the
reference's full-size 3.843 iters/s.  ``extra`` carries the
baseline-shaped points: strict leaf-wise growth, a 255-leaf run (the
baseline's own tree shape), a 10M-row scaling point, and an
Epsilon-shaped wide point (400k x 2000 dense, GPU-Performance.rst:63).

Capture discipline (VERDICT r3 task 1 — a perf round whose number can't
be captured is a failed perf round):

- The parent first PROBES the TPU claim in a disposable child (the axon
  tunnel is exclusive and can wedge: a killed mid-claim process blocks
  every later ``jax.devices()`` for hours).  A hung probe is diagnosed
  as a wedge and the parent goes STRAIGHT to the CPU fallback instead of
  burning the round's budget on retries that cannot succeed.
- The primary point runs in a child with a HARD 600 s budget; one quick
  retry (300 s) and then the CPU fallback.  Extras run in a SEPARATE
  child afterwards that can die without losing the primary.
- Every measured point is appended to ``BENCH_POINTS.jsonl`` (next to
  this file) the moment it lands, and the primary metric line is printed
  to stdout immediately — a timeout kill loses at most the point in
  flight.  The parent merges file + partial stdout and always emits
  exactly ONE final JSON line {"metric", "value", "unit",
  "vs_baseline"[, "extra"][, "error"]}.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

BASELINE_IPS = 500.0 / 130.094  # reference HIGGS CPU (Experiments.rst:113)
METRIC = "higgs1m_binary_train_iters_per_sec"
N_ROWS, N_FEAT = 1_000_000, 28
PRIMARY_LEAVES, PRIMARY_MAX_BIN = 31, 63
PRIMARY_PADDED_BIN = 64          # ops/histogram.py pads the bin axis to 64
POINTS_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_POINTS.jsonl")

PROBE_TIMEOUT = 150              # healthy claims take ~0.1 s (BENCH_r02)
PRIMARY_TIMEOUT = 600            # hard cap, VERDICT r3 task 1
QUICK_TIMEOUT = 300
EXTRAS_TIMEOUT = 600
CPU_TIMEOUT = 420

# bf16/f32 MXU peak per chip for MFU estimate; unknown kinds report FLOP/s.
PEAK_FLOPS = {
    "v5lite": 197e12, "v5e": 197e12, "v5p": 459e12,
    "v4": 275e12, "v6e": 918e12, "v6lite": 918e12,
}


def _record_point(name, **kv):
    """Append one measured point to the results file IMMEDIATELY (crash /
    timeout safe) and mirror it to stderr for the log tail."""
    rec = {"point": name, **kv}
    try:
        with open(POINTS_FILE, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError as e:
        print(f"[bench] point-file write failed: {e}", file=sys.stderr)
    print(f"[bench] point {rec}", file=sys.stderr, flush=True)


def _peak_for(devs):
    """MXU peak FLOP/s for the claimed device kind, or None if unknown."""
    kind = devs[0].device_kind.lower().replace(" ", "")
    return next((v for k, v in PEAK_FLOPS.items() if k in kind), None)


def _hist_flops_per_iter(n: int, leaves: int) -> float:
    """Useful histogram FLOPs per boosting iteration (one-hot
    contraction, (leaves-1) smaller-child passes)."""
    return 2.0 * 3 * n * N_FEAT * PRIMARY_PADDED_BIN * (leaves - 1)


def make_higgs_like(n: int, f: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float32)
    logit = (1.2 * x[:, 0] - 0.8 * x[:, 1] + 0.6 * x[:, 2] * x[:, 3]
             + 0.4 * np.abs(x[:, 4]) + 0.5 * rng.randn(n))
    y = (logit > 0).astype(np.float32)
    return x, y


def make_epsilon_like(n: int, f: int, seed: int = 3):
    """Epsilon-shaped wide dense data (400k x 2000), generated in f32
    row-chunks so the host never holds an f64 copy (~6.4 GB)."""
    rng = np.random.RandomState(seed)
    x = np.empty((n, f), dtype=np.float32)
    chunk = max(1, 50_000_000 // f)
    for lo in range(0, n, chunk):
        hi = min(n, lo + chunk)
        x[lo:hi] = rng.standard_normal((hi - lo, f)).astype(np.float32)
    w = rng.standard_normal(16).astype(np.float32)
    logit = x[:, :16] @ w + 0.5 * rng.standard_normal(n).astype(np.float32)
    y = (logit > 0).astype(np.float32)
    return x, y


def _train_point(lgb, x, y, num_leaves, chunk, n_chunks, tag, ds=None,
                 split_batch=0, max_bin=PRIMARY_MAX_BIN):
    """Train one config; returns (ips, auc, ds) steady-state over n_chunks
    fused chunks (or per-iter updates when fusion is unavailable).  Pass
    ``ds`` to reuse an already-binned dataset (num_leaves is a Booster
    param; binning is identical across points on the same data).
    split_batch: 0 = config auto (strict below 64 leaves, batched above),
    explicit K pins the grower's super-step width (grower.py)."""
    params = {
        "objective": "binary", "num_leaves": num_leaves,
        "learning_rate": 0.1, "max_bin": max_bin,
        "min_data_in_leaf": 20, "verbosity": 0,
        "split_batch": split_batch,
    }
    t0 = time.time()
    if ds is None:
        ds = lgb.Dataset(x, label=y, params=params)
        ds.construct()
    t_bin = time.time() - t0

    bst = lgb.Booster(params=dict(params, fused_chunk=chunk),
                      train_set=ds)
    m = bst._model
    fused = m.supports_fused() and chunk > 1

    t0 = time.time()
    if fused:
        m.train_chunk(chunk)          # includes XLA compile
    else:
        bst.update()
    np.asarray(m.score)
    t_compile = time.time() - t0

    t0 = time.time()
    start_iter = m.iter_
    if fused:
        for _ in range(n_chunks):
            if m.train_chunk(chunk):
                break                 # no-split stop: count only real iters
    else:
        for _ in range(n_chunks * chunk):
            if bst.update():
                break
    np.asarray(m.score)               # hard sync
    dt = time.time() - t0
    iters = m.iter_ - start_iter
    ips = iters / max(dt, 1e-9)

    from lightgbm_tpu.metrics import _auc
    auc = _auc(y, np.asarray(m.train_score())[:, 0], None)
    print(f"[bench] {tag}: bin={t_bin:.1f}s compile+warm={t_compile:.1f}s "
          f"steady={dt:.1f}s/{iters} iters -> {ips:.3f} iters/s "
          f"(train-AUC={auc:.4f}, fused={fused})",
          file=sys.stderr, flush=True)
    return ips, auc, ds


def _claim_device(cpu: bool):
    print("[bench] importing jax / claiming device...", file=sys.stderr,
          flush=True)
    t_dev = time.time()
    import jax
    if cpu:
        # in-process override, NOT the JAX_PLATFORMS env var: the axon
        # sitecustomize pins the platform config at interpreter start, so
        # the env var is ignored and jax.devices() would still try to
        # claim the (possibly wedged) TPU tunnel; jax.config.update is
        # the supported escape (same pattern as tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    print(f"[bench] devices={devs} ({time.time() - t_dev:.1f}s)",
          file=sys.stderr, flush=True)
    return devs


def child_probe() -> None:
    """Disposable TPU-claim probe: prints a marker line on success."""
    devs = _claim_device(cpu=False)
    print(f"PROBE_OK {devs[0].device_kind}", flush=True)


def child_primary() -> None:
    """The primary measurement; prints the JSON metric line ASAP."""
    quick = os.environ.get("_BENCH_QUICK") == "1"
    cpu = os.environ.get("_BENCH_CPU") == "1"
    devs = _claim_device(cpu=cpu)
    import lightgbm_tpu as lgb

    n = N_ROWS if not cpu else N_ROWS // 10
    x, y = make_higgs_like(n, N_FEAT)

    # primary: 1M x 28, 31 leaves, 8-way batched super-steps (the
    # framework's fast growth mode; AUC reported alongside so quality is
    # auditable against the strict point below)
    ips1, auc1, ds1 = _train_point(lgb, x, y, num_leaves=PRIMARY_LEAVES,
                                   chunk=4 if quick else 25,
                                   n_chunks=1 if quick else 4,
                                   tag="1M/31leaf/sb8", split_batch=8)
    rec = {
        "metric": METRIC,
        "value": round(ips1, 3),
        "unit": ("iters/s (1M rows x 28 feat, 31 leaves, 63 bins, "
                 "split_batch=8)"),
        "vs_baseline": round(ips1 / BASELINE_IPS, 3),
    }
    if cpu:
        rec["unit"] += f" [CPU fallback, {n} rows]"
    # persist + emit the primary record NOW: a later timeout kill (or a
    # hang in the strict point) must not discard it
    _record_point("primary", auc=round(float(auc1), 4), cpu=cpu, **rec)
    print(json.dumps(rec), flush=True)

    # observability: achieved histogram FLOP/s + MFU estimate
    achieved = _hist_flops_per_iter(n, PRIMARY_LEAVES) * ips1
    peak = _peak_for(devs)
    mfu = f"{achieved / peak:.1%}" if peak else "n/a"
    print(f"[bench] primary {ips1:.2f} iters/s train-AUC={auc1:.4f} "
          f"hist~{achieved / 1e12:.2f} TFLOP/s (MFU~{mfu} of "
          f"{devs[0].device_kind})", file=sys.stderr, flush=True)

    if not quick and not cpu:
        # strict leaf-wise growth (split_batch=1): round-over-round
        # comparable with BENCH_r02/r03 history + the AUC quality anchor
        try:
            ips0, auc0, _ = _train_point(lgb, x, y,
                                         num_leaves=PRIMARY_LEAVES,
                                         chunk=25, n_chunks=2,
                                         tag="1M/31leaf/strict", ds=ds1,
                                         split_batch=1)
            _record_point("higgs1m_31leaf_strict", value=round(ips0, 3),
                          auc=round(float(auc0), 4))
        except Exception as e:
            _record_point("higgs1m_31leaf_strict",
                          error=f"{type(e).__name__}: {e}"[:200])


def child_extras() -> None:
    """The non-primary points, each persisted as it lands.  Runs in its
    own child AFTER the primary is safe; a wedge/timeout here costs only
    the points not yet reached."""
    devs = _claim_device(cpu=os.environ.get("_BENCH_CPU") == "1")
    import lightgbm_tpu as lgb

    x, y = make_higgs_like(N_ROWS, N_FEAT)

    # the baseline's own 255-leaf tree shape (VERDICT r2 task 3a; the
    # vs_baseline that matters most — 3.843 iters/s IS this shape).
    # auto split_batch=16 -> M=3K=48 of the MXU's 128 rows; the achieved
    # histogram FLOP/s double as the MFU evidence for VERDICT r3 task 3.
    try:
        ips2, auc2, _ = _train_point(lgb, x, y, num_leaves=255, chunk=4,
                                     n_chunks=2, tag="1M/255leaf")
        flops = _hist_flops_per_iter(N_ROWS, 255) * ips2
        peak = _peak_for(devs)
        _record_point("higgs1m_255leaf", value=round(ips2, 3),
                      auc=round(float(auc2), 4),
                      vs_baseline=round(ips2 / BASELINE_IPS, 3),
                      hist_tflops=round(flops / 1e12, 2),
                      mfu=round(flops / peak, 4) if peak else None)
    except Exception as e:
        _record_point("higgs1m_255leaf",
                      error=f"{type(e).__name__}: {e}"[:200])

    # Epsilon-shaped wide point (VERDICT r3 task 6: 400k x 2000 dense)
    try:
        xe, ye = make_epsilon_like(400_000, 2000)
        ipse, auce, _ = _train_point(lgb, xe, ye, num_leaves=PRIMARY_LEAVES,
                                     chunk=4, n_chunks=2,
                                     tag="400k/2000f/31leaf", split_batch=8)
        _record_point("epsilon400k_2000f", value=round(ipse, 3),
                      auc=round(float(auce), 4))
        del xe, ye
    except Exception as e:
        _record_point("epsilon400k_2000f",
                      error=f"{type(e).__name__}: {e}"[:200])

    # 10M-row scaling point (VERDICT r2 task 3b)
    try:
        x10 = np.concatenate([x] * 10, axis=0)
        rng = np.random.RandomState(7)
        for i in range(10):     # chunked f32 noise: no 2 GB f64 spike
            sl = slice(i * N_ROWS, (i + 1) * N_ROWS)
            x10[sl] += (rng.standard_normal(
                (N_ROWS, N_FEAT)).astype(np.float32) * 1e-3)
        y10 = np.concatenate([y] * 10)
        ips3, auc3, _ = _train_point(lgb, x10, y10, num_leaves=31,
                                     chunk=8, n_chunks=2,
                                     tag="10M/31leaf/sb8", split_batch=8)
        _record_point("higgs10m", value=round(ips3, 3),
                      auc=round(float(auc3), 4))
    except Exception as e:
        _record_point("higgs10m", error=f"{type(e).__name__}: {e}"[:200])


def _metric_line(stdout: str):
    """Last JSON metric line in a (possibly partial) stdout, or None."""
    found = None
    for line in (stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{") and METRIC in line:
            found = line
    return found


def run_child(mode: str, timeout: int, extra_env=None, orphan=False):
    """Run one child; returns (stdout_text, err_summary).

    orphan=True (the probe): on timeout the child is LEFT RUNNING, not
    killed — SIGKILLing a client mid-TPU-claim is exactly what wedges
    the axon relay ('grant unclaimed past timeout'); an orphan that
    eventually gets the grant exits cleanly a moment later and releases
    it, merely delaying (not breaking) the next claimer."""
    env = dict(os.environ, _BENCH_CHILD=mode)
    env.update(extra_env or {})
    out_f = open(POINTS_FILE + f".{mode}.out", "w+")
    err_f = open(POINTS_FILE + f".{mode}.err", "w+")
    p = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                         env=env, stdout=out_f, stderr=err_f, text=True)
    try:
        p.wait(timeout=timeout)
        timed_out = False
    except subprocess.TimeoutExpired:
        timed_out = True
        if not orphan:
            p.kill()
            p.wait()

    def _read(f):
        f.flush()
        f.seek(0)
        return f.read()
    out, err_txt = _read(out_f), _read(err_f)
    out_f.close()
    err_f.close()
    sys.stderr.write(err_txt[-4000:])
    if timed_out:
        return out, f"timeout after {timeout}s" + \
            (" (left running, not killed mid-claim)" if orphan else "")
    err = None if p.returncode == 0 else f"rc={p.returncode}"
    return out, err


def _read_points():
    pts = []
    try:
        with open(POINTS_FILE) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        pts.append(json.loads(line))
                    except ValueError:
                        pass
    except OSError:
        pass
    return pts


def main():
    mode = os.environ.get("_BENCH_CHILD")
    if mode:
        {"probe": child_probe, "primary": child_primary,
         "extras": child_extras}[mode]()
        return

    # fresh points file per run; the old one is superseded
    try:
        os.replace(POINTS_FILE, POINTS_FILE + ".prev")
    except OSError:
        pass
    _record_point("run_start", t=time.strftime("%Y-%m-%dT%H:%M:%S"))

    errors = []
    # --- 1. probe the TPU claim (wedge detection, see module docstring) --
    tpu_ok = False
    for i in range(2):
        t0 = time.time()
        out, err = run_child("probe", timeout=PROBE_TIMEOUT, orphan=True)
        if "PROBE_OK" in (out or ""):
            tpu_ok = True
            break
        diag = ("wedged: claim hung (timeout-killed client holds the "
                "relay grant)" if err and "timeout" in err
                else f"claim failed fast ({err}) after "
                     f"{time.time() - t0:.0f}s")
        errors.append(f"probe{i + 1}: {diag}")
        print(f"[bench] TPU probe {i + 1} failed: {diag}", file=sys.stderr,
              flush=True)
        if err and "timeout" in err:
            break                    # a wedge does not clear in 30 s
        time.sleep(30)               # fast Unavailable may be transient
    _record_point("probe", tpu_ok=tpu_ok, errors=errors[:])

    # --- 2. primary point (hard-capped) ---------------------------------
    line = None
    if tpu_ok:
        out, err = run_child("primary", timeout=PRIMARY_TIMEOUT)
        line = _metric_line(out)
        if not line:
            errors.append(f"primary: {err or 'no JSON line'}")
            print("[bench] primary failed; quick retry...", file=sys.stderr,
                  flush=True)
            out, err = run_child("primary", timeout=QUICK_TIMEOUT,
                                 extra_env={"_BENCH_QUICK": "1"})
            line = _metric_line(out)
            if not line:
                errors.append(f"primary-quick: {err or 'no JSON line'}")
    degraded = None
    if not line:
        # last resort: reduced CPU run — an honest degraded number beats
        # none (and records the wedge diagnosis machine-readably)
        out, err = run_child("primary", timeout=CPU_TIMEOUT,
                             extra_env={"_BENCH_CPU": "1",
                                        "_BENCH_QUICK": "1"})
        line = _metric_line(out)
        if line:
            degraded = ("degraded: accelerator unavailable, CPU fallback; "
                        + "; ".join(errors))
        else:
            errors.append(f"cpu-fallback: {err or 'no JSON line'}")

    # --- 3. extras in their own killable child --------------------------
    # only when the TPU primary itself succeeded: a degraded CPU capture
    # means the TPU path is broken and another 600 s child would burn
    # the budget the capture discipline exists to protect
    if line and tpu_ok and not degraded:
        run_child("extras", timeout=EXTRAS_TIMEOUT)

    # --- 4. merge + emit exactly one line -------------------------------
    if not line:
        rec = {"metric": METRIC, "value": 0.0, "unit": "iters/s",
               "vs_baseline": 0.0, "error": "; ".join(errors)}
        _record_point("final", **rec)
        print(json.dumps(rec), flush=True)
        return
    rec = json.loads(line)
    extra = {}
    for p in _read_points():
        name = p.get("point")
        if name in (None, "run_start", "probe", "final", "primary"):
            if name == "primary" and "auc" in p:
                extra["higgs1m_31leaf_sb8_auc"] = p["auc"]
            continue
        if "value" in p:
            extra[name + "_iters_per_sec"] = p["value"]
            if "auc" in p:
                extra[name + "_auc"] = p["auc"]
            if "vs_baseline" in p:
                extra[name + "_vs_baseline"] = p["vs_baseline"]
        elif "error" in p:
            extra[name + "_error"] = p["error"]
    if extra:
        rec["extra"] = extra
    if degraded:
        rec["error"] = degraded
    _record_point("final", **rec)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
