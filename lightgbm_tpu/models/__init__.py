from .gbdt import GBDTModel, create_boosting
