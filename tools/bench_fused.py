"""Super-epoch / fused training sweep: syncs per iteration + iters/s.

Run: python tools/bench_fused.py [n_rows] [num_leaves] [ks] [rounds]

  ks      comma list of epoch sizes, default ``1,8,32,99``; ``k=1`` is
          the per-iteration baseline (``superepoch=-1``)
  rounds  boosting rounds per timed run (default: 2 epochs per k,
          16 for the baseline)

Each k runs twice — with one validation set (plus a never-firing
early-stopping callback, so the traced eval and the in-scan vote are in
the measured path) and without — training end to end through
``lgb.train``.  Host syncs are counted by wrapping ``jax.device_get``
(every training fetch routes through ``GBDTModel._eget`` —
tools/sync_allowlist.txt); a super-epoch must show ``1/k`` syncs per
iteration, the baseline ~1+/iteration.  A warmup run of the same shape
precedes each timed run so compile cost is excluded.

``sweep()`` is importable: bench.py folds the returned dict into its
extras as ``superepoch_<key>`` (tools/perf_budget.txt pins the headline
``superepoch_iters_per_s`` / ``superepoch_sync_count_per_iter``).
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")


def _make_data(n, f=28, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float32)
    logit = (1.2 * x[:, 0] - 0.8 * x[:, 1] + 0.6 * x[:, 2] * x[:, 3]
             + 0.4 * np.abs(x[:, 4]) + 0.5 * rng.randn(n))
    y = (logit > 0).astype(np.float32)
    return x, y


def _one_run(lgb, dtr, dva, params, rounds, count_syncs=False):
    """One lgb.train; returns (seconds, device_get count)."""
    import jax
    cbs = [lgb.record_evaluation({})]
    vs, vn = [], []
    if dva is not None:
        vs, vn = [dva], ["va"]
        cbs.append(lgb.early_stopping(10 * rounds, verbose=False))
    count = [0]
    orig = jax.device_get

    def counting(v):
        count[0] += 1
        return orig(v)

    if count_syncs:
        jax.device_get = counting
    t0 = time.time()
    try:
        bst = lgb.train(dict(params), dtr, num_boost_round=rounds,
                        valid_sets=vs, valid_names=vn, callbacks=cbs)
    finally:
        jax.device_get = orig
    dt = time.time() - t0
    assert len(bst.trees) == rounds, \
        f"expected {rounds} trees, got {len(bst.trees)}"
    return dt, count[0]


def sweep(n_rows=200_000, num_leaves=31, ks=(1, 8, 32, 99),
          rounds=None, n_feat=28, log=None):
    """{key: value} over k x {valid, novalid}; see module docstring."""
    import lightgbm_tpu as lgb
    x, y = _make_data(n_rows + n_rows // 4, n_feat)
    base = {"objective": "binary", "num_leaves": num_leaves,
            "learning_rate": 0.1, "max_bin": 63, "min_data_in_leaf": 20,
            "verbosity": -1, "tpu_learner": "masked",
            # bound depth so the in-scan traversal budget
            # (utils/shapes.traversal_steps) stays tight
            "max_depth": 8, "metric": ["binary_logloss"]}
    dtr = lgb.Dataset(x[:n_rows], label=y[:n_rows], params=base)
    dva = lgb.Dataset(x[n_rows:], label=y[n_rows:], reference=dtr)
    dtr.construct()
    dva.construct()

    out = {}
    for k in ks:
        if k == 1:
            p = dict(base, superepoch=-1, fused_chunk=0,
                     fused_eval="true")
            r = rounds or 16
        else:
            p = dict(base, superepoch=k, fused_chunk=k)
            r = rounds or 2 * k
        for with_valid in (True, False):
            tag = f"k{k}_{'valid' if with_valid else 'novalid'}"
            va = dva if with_valid else None
            try:
                _one_run(lgb, dtr, va, p, r)            # warm/compile
                dt, syncs = _one_run(lgb, dtr, va, p, r,
                                     count_syncs=True)
            except Exception as e:                      # noqa: BLE001
                out[f"{tag}_error"] = f"{type(e).__name__}: {e}"[:120]
                continue
            ips = r / dt
            spi = syncs / r
            out[f"{tag}_iters_per_s"] = round(ips, 3)
            out[f"{tag}_syncs_per_iter"] = round(spi, 4)
            if log:
                log(f"{tag}: {r} rounds in {dt:.2f}s -> "
                    f"{ips:.2f} iters/s, {spi:.3f} syncs/iter")
    # headline keys (tools/perf_budget.txt pins): the acceptance shape
    # is k=32 with one valid set + ES — beat per-iteration, 1 sync/epoch
    if "k32_valid_iters_per_s" in out:
        out["iters_per_s"] = out["k32_valid_iters_per_s"]
        out["sync_count_per_iter"] = out["k32_valid_syncs_per_iter"]
        if "k1_valid_iters_per_s" in out:
            out["superepoch_over_periter"] = round(
                out["k32_valid_iters_per_s"]
                / max(out["k1_valid_iters_per_s"], 1e-9), 3)
    return out


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 200_000
    num_leaves = int(sys.argv[2]) if len(sys.argv) > 2 else 31
    ks = tuple(int(s) for s in sys.argv[3].split(",")) \
        if len(sys.argv) > 3 else (1, 8, 32, 99)
    rounds = int(sys.argv[4]) if len(sys.argv) > 4 else None

    import jax
    print(f"devices={jax.devices()}", file=sys.stderr, flush=True)
    res = sweep(n, num_leaves, ks, rounds,
                log=lambda m: print(m, file=sys.stderr, flush=True))
    import json
    print(json.dumps(res, sort_keys=True))


if __name__ == "__main__":
    main()
