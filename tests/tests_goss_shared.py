"""Shared fixtures for the multi-process GOSS equality test — imported by
both the spawned worker (tests/mp_goss_worker.py) and the host test, so
data, params, and mapper fitting are byte-identical in every topology."""

import numpy as np

GOSS_PARAMS = {
    "objective": "binary",
    "num_leaves": 15,
    "min_data_in_leaf": 5,
    "max_bin": 63,
    "data_sample_strategy": "goss",
    "top_rate": 0.2,
    "other_rate": 0.15,
    "bagging_seed": 5,
    "tpu_learner": "masked",   # the topology-invariant learner
    "verbosity": -1,
}
ROUNDS = 5


def global_data(n=4096, f=10, seed=3):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float64)
    y = (x[:, 0] - 0.7 * x[:, 1] + 0.3 * rng.randn(n) > 0) \
        .astype(np.float32)
    return x, y


def full_data_mappers(x):
    """Bin mappers fitted on the FULL data — deterministic, so every
    process (and the single-process reference) bins identically."""
    from lightgbm_tpu.binning import BinMapper
    from lightgbm_tpu.config import Config
    cfg = Config(dict(GOSS_PARAMS))
    mappers = []
    for j in range(x.shape[1]):
        m = BinMapper()
        m.find_bin(x[:, j], len(x), cfg.max_bin,
                   cfg.min_data_in_bin, use_missing=cfg.use_missing,
                   zero_as_missing=cfg.zero_as_missing)
        mappers.append(m)
    return mappers


def tree_records(bst):
    """Structure + leaf values for every tree, for cross-topology
    comparison."""
    recs = []
    for t in bst._model.models:
        recs.append({
            "split_feature": [int(v) for v in t.split_feature],
            "threshold_bin": [int(v) for v in t.threshold_bin],
            "leaf_value": [float(v) for v in t.leaf_value],
        })
    return recs


def synthetic_grads(n, seed=11):
    """Varied deterministic gradients so GOSS's two strata are non-trivial
    (constant |g|h would make every row 'top')."""
    rng = np.random.RandomState(seed)
    g = rng.randn(n).astype(np.float32)
    h = np.full(n, 0.25, np.float32)
    return g, h


def shard_bounds(n, nproc):
    """The contiguous row partition launch.row_shard uses."""
    parts = np.array_split(np.arange(n), nproc)
    return [(int(p[0]), int(p[-1]) + 1) for p in parts]
