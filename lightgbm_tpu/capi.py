"""ctypes binding to the native C API inference runtime.

Mirrors the reference's Python->C prediction path (basic.py:112 _load_lib,
_InnerPredictor -> LGBM_BoosterPredictForMat, c_api.h:1072): the model is
parsed and traversed entirely in C++ (native/capi.cpp), with OpenMP row
parallelism — a dependency-free deployment predictor for models trained by
the JAX/TPU layer.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Optional

import numpy as np

from .native import load_lib

_PRED_NORMAL = 0
_PRED_RAW = 1
_PRED_LEAF = 2

_lock = threading.Lock()
_lib = None
_lib_failed = False


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        lib = load_lib("capi.cpp", "libcapi.so")
        if lib is None:
            _lib_failed = True
            return None
        lib.LGBM_GetLastError.restype = ctypes.c_char_p
        lib.LGBM_BoosterLoadModelFromString.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_void_p)]
        lib.LGBM_BoosterCreateFromModelfile.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_void_p)]
        lib.LGBM_BoosterFree.argtypes = [ctypes.c_void_p]
        for name in ("LGBM_BoosterGetNumClasses", "LGBM_BoosterGetNumFeature",
                     "LGBM_BoosterGetCurrentIteration"):
            getattr(lib, name).argtypes = [ctypes.c_void_p,
                                           ctypes.POINTER(ctypes.c_int)]
        lib.LGBM_BoosterPredictForMat.argtypes = [
            ctypes.c_void_p,
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")]
        lib.LGBM_BoosterPredictForCSR.argtypes = [
            ctypes.c_void_p,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")]
        lib.LGBM_BoosterPredictForFile.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p]
        _lib = lib
    return _lib


def native_available() -> bool:
    return _get_lib() is not None


class NativeBooster:
    """Inference-only booster backed by the C++ runtime.

    Load a saved model file (or string) and predict without JAX in the
    loop — the deployment-side analog of ``Booster`` prediction.
    """

    def __init__(self, model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError("native C API library unavailable "
                               "(g++ build failed)")
        self._lib = lib
        self._handle = ctypes.c_void_p()
        niter = ctypes.c_int()
        if model_file is not None:
            rc = lib.LGBM_BoosterCreateFromModelfile(
                model_file.encode(), ctypes.byref(niter),
                ctypes.byref(self._handle))
        elif model_str is not None:
            rc = lib.LGBM_BoosterLoadModelFromString(
                model_str.encode(), ctypes.byref(niter),
                ctypes.byref(self._handle))
        else:
            raise ValueError("need model_file or model_str")
        if rc != 0:
            raise RuntimeError(lib.LGBM_GetLastError().decode())
        self.num_iterations = niter.value

    def _get_int(self, fname: str) -> int:
        out = ctypes.c_int()
        getattr(self._lib, fname)(self._handle, ctypes.byref(out))
        return out.value

    @property
    def num_classes(self) -> int:
        return self._get_int("LGBM_BoosterGetNumClasses")

    @property
    def num_feature(self) -> int:
        return self._get_int("LGBM_BoosterGetNumFeature")

    def current_iteration(self) -> int:
        return self._get_int("LGBM_BoosterGetCurrentIteration")

    def predict(self, data, raw_score: bool = False, pred_leaf: bool = False,
                start_iteration: int = 0,
                num_iteration: int = -1) -> np.ndarray:
        """Dense ndarray or scipy CSR/CSC input (the sparse path stays in
        C via LGBM_BoosterPredictForCSR — c_api.h:815 parity)."""
        sparse = hasattr(data, "tocsr") and not isinstance(data, np.ndarray)
        if sparse:
            m = data.tocsr()
            nrow, ncol = m.shape
        else:
            x = np.ascontiguousarray(np.asarray(data, np.float64))
            if x.ndim == 1:
                x = x.reshape(1, -1)
            nrow, ncol = x.shape
        k = self.num_classes
        if pred_leaf:
            ptype = _PRED_LEAF
            total = self.current_iteration()
            used = total - start_iteration if num_iteration <= 0 else \
                min(num_iteration, total - start_iteration)
            width = max(used, 0) * self._trees_per_iter()
        else:
            ptype = _PRED_RAW if raw_score else _PRED_NORMAL
            width = k
        out = np.zeros((nrow, width), np.float64)
        out_len = ctypes.c_int64()
        if sparse:
            indptr = np.ascontiguousarray(m.indptr, np.int32)
            indices = np.ascontiguousarray(m.indices, np.int32)
            vals = np.ascontiguousarray(m.data, np.float64)
            rc = self._lib.LGBM_BoosterPredictForCSR(
                self._handle, indptr, len(indptr), indices, vals,
                len(vals), ncol, ptype, start_iteration, num_iteration,
                ctypes.byref(out_len), out)
        else:
            rc = self._lib.LGBM_BoosterPredictForMat(
                self._handle, x, nrow, ncol, ptype, start_iteration,
                num_iteration, ctypes.byref(out_len), out)
        if rc != 0:
            raise RuntimeError(self._lib.LGBM_GetLastError().decode())
        width_actual = out_len.value // nrow if nrow else width
        out = out[:, :width_actual] if width_actual < width else out
        if pred_leaf:
            return out.astype(np.int32)
        return out if k > 1 else out[:, 0]

    def predict_file(self, data_filename: str, result_filename: str,
                     has_header: bool = False, raw_score: bool = False,
                     pred_leaf: bool = False, start_iteration: int = 0,
                     num_iteration: int = -1) -> None:
        """CSV/TSV/LibSVM file -> predictions file, entirely in C
        (LGBM_BoosterPredictForFile, c_api.h:749; Predictor task=predict)."""
        ptype = _PRED_LEAF if pred_leaf else (
            _PRED_RAW if raw_score else _PRED_NORMAL)
        rc = self._lib.LGBM_BoosterPredictForFile(
            self._handle, data_filename.encode(), int(has_header), ptype,
            start_iteration, num_iteration, result_filename.encode())
        if rc != 0:
            raise RuntimeError(self._lib.LGBM_GetLastError().decode())

    def _trees_per_iter(self) -> int:
        return self.num_classes if self.num_classes > 1 else 1

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle and getattr(self, "_lib", None) is not None:
            self._lib.LGBM_BoosterFree(handle)
            self._handle = None
