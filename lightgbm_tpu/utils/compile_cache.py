"""Persistent XLA compilation cache + process-wide compile accounting.

Two concerns live here because they are two halves of one feature —
making compile time a managed, *measured* resource (ROADMAP item 4:
BENCH_r02 paid 73.4 s of compile before the first iteration vs 84 s of
steady state for 99 iterations):

1. :func:`enable_persistent_cache` points jax at an on-disk compilation
   cache so later processes on the same host warm-start every compile
   (train -> serve included).  The cache directory is keyed by the
   host's CPU feature fingerprint because XLA:CPU AOT entries are
   machine-specific and this can run in environments that migrate
   between heterogeneous hosts — a cache written on one host fails
   every load on another ("Target machine feature ... is not
   supported"), costing the failed loads on top of the recompiles
   (measured: 25 cold minutes for the test suite).  A user's pre-set
   ``JAX_COMPILATION_CACHE_DIR`` (or an explicit ``compile_cache_dir``
   param) is RESPECTED, never clobbered.  Config wiring:
   ``compile_cache`` / ``compile_cache_dir`` /
   ``compile_cache_min_compile_s`` / ``compile_cache_min_entry_bytes``
   (engine.train / Booster / cli / serve bring-up via
   :func:`maybe_enable_from_config`).

2. :func:`install_compile_counters` + :func:`trace_event` make
   warm-start observable instead of assumed: process-global counters of
   backend compiles / persistent-cache hits+misses / compile seconds
   (fed by ``jax.monitoring``), and named trace counters bumped at
   trace time by the library's jitted entry points (grower, fused
   chunk, traversal, forest).  Surfaced through
   ``Booster.telemetry_snapshot()``, the serve ``/metrics`` endpoint,
   ``bench.py`` records, and pinned by tools/check_retraces.py.
"""

from __future__ import annotations

import getpass
import hashlib
import os
import tempfile
import threading
from typing import Dict, Optional


def machine_tag() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.sha256(line.encode()).hexdigest()[:10]
    except OSError:
        pass
    import platform
    return hashlib.sha256(platform.processor().encode()).hexdigest()[:10]


def default_cache_dir() -> str:
    """The per-user, per-host-fingerprint cache path used when neither
    the caller nor the environment chose one."""
    return os.path.join(
        tempfile.gettempdir(),
        f"lgbtpu_jax_cache_{getpass.getuser()}_{machine_tag()}")


def configured_cache_dir():
    """The cache dir jax is ALREADY configured with (from a previous
    enable, a user's ``jax.config.update``, or the
    ``JAX_COMPILATION_CACHE_DIR`` env var), or None."""
    try:
        import jax
        d = jax.config.jax_compilation_cache_dir
    except Exception:
        d = None
    return d or os.environ.get("JAX_COMPILATION_CACHE_DIR") or None


def enable_persistent_cache(min_compile_secs: float = 0.5,
                            cache_dir: Optional[str] = None,
                            min_entry_bytes: int = 0) -> str:
    """Enable the persistent compilation cache; returns the path used.

    Precedence for the directory: explicit ``cache_dir`` argument >
    an already-configured dir (jax config or the
    ``JAX_COMPILATION_CACHE_DIR`` env var — a user's choice is
    respected, not clobbered) > the per-host default.  The persistence
    thresholds are parameters (they used to be hardwired to
    ``min_entry_size=0``, silently overriding a user's tuning), and a
    threshold pinned via its jax env var
    (``JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS`` /
    ``JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES``) is likewise left
    alone."""
    import jax
    path = cache_dir or configured_cache_dir() or default_cache_dir()
    jax.config.update("jax_compilation_cache_dir", path)
    if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
    if "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES" not in os.environ:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          int(min_entry_bytes))
    install_compile_counters()
    return path


def maybe_enable_from_config(config) -> Optional[str]:
    """Config-driven bring-up used by Booster / engine.train / cli /
    serve: enables the persistent cache when ``compile_cache`` is on
    (the default) and always installs the compile counters so
    ``compile.*`` telemetry works even with the cache disabled.
    Idempotent and cheap; returns the cache path or None."""
    install_compile_counters()
    if not getattr(config, "compile_cache", True):
        return None
    return enable_persistent_cache(
        min_compile_secs=getattr(config, "compile_cache_min_compile_s",
                                 0.5),
        cache_dir=getattr(config, "compile_cache_dir", "") or None,
        min_entry_bytes=getattr(config, "compile_cache_min_entry_bytes",
                                0))


# ---------------------------------------------------------------------------
# Process-wide compile accounting
# ---------------------------------------------------------------------------

# jax.monitoring event names this build of jax emits (jax 0.4.x:
# jax/_src/dispatch.py BACKEND_COMPILE_EVENT, jax/_src/compiler.py /
# compilation_cache.py cache hit/miss record_event calls).  Matched by
# substring so a renamed prefix degrades to "not counted", never to a
# crash.
_BACKEND_COMPILE = "backend_compile"
_CACHE_HIT = "cache_hits"
_CACHE_MISS = "cache_misses"

_STATS_LOCK = threading.Lock()
_COMPILE_STATS = {"count": 0, "seconds": 0.0,
                  "cache_hits": 0, "cache_misses": 0}
_COUNTERS_INSTALLED = [False]


def install_compile_counters() -> bool:
    """Register the process-global jax.monitoring listeners feeding
    :func:`compile_stats`.  Listeners cannot be unregistered, so this
    installs exactly once; returns False when the monitoring surface is
    unavailable."""
    if _COUNTERS_INSTALLED[0]:
        return True
    try:
        from jax import monitoring
    except Exception:
        return False

    def _on_duration(event: str, duration: float, **kw) -> None:
        if _BACKEND_COMPILE in event:
            with _STATS_LOCK:
                _COMPILE_STATS["count"] += 1
                _COMPILE_STATS["seconds"] += float(duration)

    def _on_event(event: str, **kw) -> None:
        if _CACHE_HIT in event:
            with _STATS_LOCK:
                _COMPILE_STATS["cache_hits"] += 1
        elif _CACHE_MISS in event:
            with _STATS_LOCK:
                _COMPILE_STATS["cache_misses"] += 1

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
    except Exception:
        return False
    _COUNTERS_INSTALLED[0] = True
    return True


def compile_stats() -> Dict[str, float]:
    """Snapshot of process-wide compile accounting: backend compile
    REQUESTS (count/seconds — jax emits the duration event on
    persistent-cache hits too, just with the near-zero load time) and
    persistent-cache hits/misses (``cache_misses`` is the
    fresh-compile count).  Zeros until
    :func:`install_compile_counters` ran (Booster/serve bring-up
    installs it)."""
    with _STATS_LOCK:
        return dict(_COMPILE_STATS)


# ---------------------------------------------------------------------------
# Named trace counters (retrace-budget lint)
# ---------------------------------------------------------------------------

_TRACE_COUNTS: Dict[str, int] = {}
_TRACE_PREFIX = "/lgbtpu/trace/"


def trace_event(name: str) -> None:
    """Record one TRACE of a named jitted program.  Called as a Python
    side effect from inside the traced function body, so it fires once
    per fresh jit cache entry and never per execution.  Mirrored into
    ``jax.monitoring`` under ``/lgbtpu/trace/<name>`` so external
    listeners (tools/check_retraces.py) can count without importing
    library internals."""
    with _STATS_LOCK:
        _TRACE_COUNTS[name] = _TRACE_COUNTS.get(name, 0) + 1
    try:
        from jax import monitoring
        monitoring.record_event(_TRACE_PREFIX + name)
    except Exception:
        pass


def trace_counts() -> Dict[str, int]:
    """Per-name trace counters for this process (deterministic: traces
    are independent of the persistent cache's disk state — a cache hit
    skips the COMPILE, never the trace)."""
    with _STATS_LOCK:
        return dict(_TRACE_COUNTS)


def trace_total() -> int:
    with _STATS_LOCK:
        return sum(_TRACE_COUNTS.values())


def compile_snapshot(traces: str = "total") -> Dict[str, object]:
    """The ``compile.*`` key block shared by every telemetry surface
    (``Booster.telemetry_snapshot`` and the serve ``/metrics``
    snapshot): compile requests, persistent-cache hits/misses, and the
    library trace counters — as a total (``traces="total"``) or the
    per-program breakdown (``traces="by_name"``)."""
    cs = compile_stats()
    return {
        "compile.count": cs["count"],
        "compile.seconds": cs["seconds"],
        "compile.cache_hits": cs["cache_hits"],
        "compile.cache_misses": cs["cache_misses"],
        "compile.traces": (trace_counts() if traces == "by_name"
                           else trace_total()),
    }


def watch_compiles(metrics, tracer=None) -> bool:
    """Feed XLA compile / compilation-cache events into an obs
    MetricsRegistry (+ optional Tracer instants): compile durations as
    a ``jax.compile_seconds`` histogram, cache hits/misses and other
    compile-adjacent counters as ``jax.events{event=...}``, and the
    library's own trace events as ``jax.traces{name=...}``.

    Uses ``jax.monitoring``'s public listener hooks; listeners are
    process-global and cannot be unregistered, so the registered
    closures forward to whatever registry/tracer was CURRENT at
    registration — callers register once per session (obs.ObsSession).
    Returns False when the monitoring surface is unavailable."""
    try:
        from jax import monitoring
    except Exception:
        return False

    def _on_duration(event: str, duration: float, **kw) -> None:
        if "compil" not in event:
            return
        metrics.histogram("jax.compile_seconds",
                          event=event).observe(duration)
        if tracer is not None:
            tracer.instant("jax_compile", event=event, seconds=duration)

    def _on_event(event: str, **kw) -> None:
        if event.startswith(_TRACE_PREFIX):
            metrics.counter("jax.traces",
                            name=event[len(_TRACE_PREFIX):]).inc()
            return
        if "compil" not in event and "cache" not in event:
            return
        metrics.counter("jax.events", event=event).inc()
        if tracer is not None and "cache" in event:
            tracer.instant("jax_cache", event=event)

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
    except Exception:
        return False
    return True
