"""On-device (K, block_rows) autotuner for the histogram contraction.

The contraction's two tunables are structural constants of the grower
trace: the super-step width K (``split_batch`` — how many leaves share
one C=3K one-hot contraction) and the row-block size of the
``lax.scan`` (``hist_block_rows``'s budget heuristic, a number measured
once on one v5e and hard-coded since).  Neither is knowable from shapes
alone — the measured sweet spot moved between CPU and TPU and between
f32 and int8 operands (tools/bench_hist.py history) — so this module
measures instead of guessing:

- **one-shot sweep** (:func:`tune`): time the SHIPPED
  ``compute_histogram`` (never a bench-local variant) over the eligible
  ``SPLIT_BATCH_SET`` widths x a small block_rows neighborhood of the
  budget heuristic, on a synthetic row sample bucketed from the real
  shape.  The score is **ms per leaf slot** (= ms/pass / K): a K=32
  pass may cost more wall time than a K=16 pass and still win, because
  it retires twice the leaves per binned-matrix load.
- **persisted next to the compile cache** (:func:`ensure`): the chosen
  record is keyed by (platform, pow2 row bucket, histogram columns,
  padded bins, vals itemsize, eligible-K ceiling) and merged into
  ``hist_tune.json`` in the same directory family as the persistent
  XLA compile cache (utils/compile_cache.py precedence), so the FIRST
  fit per (platform, shape-bucket) pays the sweep and every later
  process — including a fresh interpreter — reuses both the choice and
  the compiled traces it leads to (zero re-tune, zero re-compile;
  tests/test_zretrace.py pins it).
- ``hist_tune=off`` (the default) never calls into this module: shapes,
  traces and models are exactly the pre-tuner ones.

The tuned K feeds ``split_batch`` resolution (models/gbdt.py) and so
CHANGES THE GROWN TREES (a K-way super-step is a different — equally
valid — best-first growth order); ``hist_tune=on`` therefore trades
cross-platform model determinism for measured throughput.  The tuned
block_rows only re-partitions the scan, but f32 accumulation order
follows the partition, so it is applied the same way: only under
``hist_tune=on``, and recorded in bench extras for provenance.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

_LOCK = threading.Lock()
_COUNTS = {"sweeps": 0, "hits": 0}
_MEM: Dict[str, dict] = {}          # process-level merged table view

TUNE_FILE = "hist_tune.json"

# sweep bounds: the sample is big enough that the scan has multiple
# blocks at every candidate (block sizing is the thing under test) and
# small enough that a full sweep stays a few seconds on CPU
_SAMPLE_ROWS_CAP = 1 << 17
_SWEEP_REPS = 3


def tune_counts() -> Dict[str, int]:
    """Process-wide sweep/lookup counters — the warm-start test's
    instrument (a second process against a warm table must report
    ``sweeps == 0``)."""
    with _LOCK:
        return dict(_COUNTS)


def tune_dir(config=None) -> str:
    """Directory the tune table lives in: the explicit
    ``compile_cache_dir`` param, else the compile cache directory jax
    is already configured with, else the per-user per-host default —
    the same precedence as the persistent compile cache, because the
    table's lifetime should match the traces its choices produce."""
    d = getattr(config, "compile_cache_dir", "") if config is not None \
        else ""
    if d:
        return d
    from ..utils.compile_cache import configured_cache_dir, \
        default_cache_dir
    return configured_cache_dir() or default_cache_dir()


def shape_key(platform: str, n_rows: int, n_cols: int, num_bins: int,
              itemsize: int, kmax: int) -> str:
    """Bucketed lookup key: rows round to pow2 (one sweep covers a
    whole row bucket, like every other trace-relevant dim in
    utils/shapes.py), the rest are exact trace constants."""
    from ..obs.flops import padded_bins
    from ..utils.shapes import round_up_pow2
    return (f"{platform}|r{round_up_pow2(max(int(n_rows), 1))}"
            f"|c{int(n_cols)}|b{padded_bins(num_bins)}"
            f"|i{int(itemsize)}|kmax{int(kmax)}")


def _load_table(path: str) -> Dict[str, dict]:
    try:
        with open(path) as f:
            obj = json.load(f)
        return obj if isinstance(obj, dict) else {}
    except (OSError, ValueError):
        return {}


def _store(dir_path: str, key: str, rec: dict) -> None:
    """Read-merge-replace under the process lock; atomic on disk
    (temp + os.replace) so concurrent writers can interleave but never
    tear the JSON."""
    from ..utils.resilience import atomic_write
    path = os.path.join(dir_path, TUNE_FILE)
    os.makedirs(dir_path, exist_ok=True)
    table = _load_table(path)
    table[key] = rec
    atomic_write(path, json.dumps(table, indent=1, sort_keys=True))


def candidate_widths(kmax: int) -> List[int]:
    """Eligible super-step widths: the shipped set above 1, capped by
    the leaf budget's ceiling (utils/shapes.fit_split_batch is the
    per-model clamp; ``kmax`` keys the sweep so 31-leaf and 255-leaf
    shapes tune their own eligible sets)."""
    from ..utils.shapes import SPLIT_BATCH_SET
    return [k for k in SPLIT_BATCH_SET if 1 < k <= int(kmax)]


def _block_candidates(n_cols: int, num_bins: int, itemsize: int,
                      k: int) -> List[int]:
    from ..obs.flops import padded_bins
    from ..ops.histogram import HIST_BLOCK_ROWS, hist_block_rows
    from ..utils.shapes import bucket_channels
    b0 = hist_block_rows(n_cols, padded_bins(num_bins), itemsize,
                         channels=bucket_channels(3 * k))
    cands = {b0, max(8, (b0 // 2) // 8 * 8),
             min(HIST_BLOCK_ROWS, b0 * 2)}
    return sorted(cands)


def _measure_ms(binned, vals, slot, k: int, block_rows: int,
                num_bins: int, reps: int) -> float:
    """Wall ms of one slotted pass, amortized over ``reps`` in-graph
    repetitions (the tunnel-latency discipline of tools/bench_hist.py)
    and fenced the PROFILE.md way."""
    import time

    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..obs.trace import fence
    from .histogram import compute_histogram

    @jax.jit
    def rep(b, v, s):
        def body(i, acc):
            h = compute_histogram(b, v, num_bins=num_bins,
                                  block_rows=block_rows, slot=s + 0 * i,
                                  num_slots=k)
            return acc + h.astype(jnp.float32)
        z = compute_histogram(b, v, num_bins=num_bins,
                              block_rows=block_rows, slot=s, num_slots=k)
        return lax.fori_loop(0, reps, body,
                             jnp.zeros_like(z, jnp.float32))

    fence(rep(binned, vals, slot))           # compile + warm
    t0 = time.perf_counter()
    fence(rep(binned, vals, slot))
    return (time.perf_counter() - t0) / reps * 1e3


def tune(n_rows: int, n_cols: int, num_bins: int, itemsize: int = 4,
         kmax: int = 64, reps: int = _SWEEP_REPS,
         sample_rows: Optional[int] = None) -> dict:
    """Run the sweep and return the winning record (no persistence —
    :func:`ensure` owns the table).  Synthetic operands at the training
    dtypes: uint8 bins, f32 or int8/int16 accumulands by ``itemsize``,
    uniform random slots so every width does real multi-leaf work."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..utils.shapes import round_up_pow2

    widths = candidate_widths(kmax)
    if not widths:
        raise ValueError(
            f"no eligible super-step width under kmax={kmax} (the leaf "
            "budget admits only strict growth — nothing to tune)")
    n = int(sample_rows) if sample_rows else \
        min(_SAMPLE_ROWS_CAP, round_up_pow2(max(int(n_rows), 1)))
    rng = np.random.RandomState(0)
    binned = jnp.asarray(rng.randint(0, max(int(num_bins), 2),
                                     size=(n, int(n_cols)),
                                     dtype=np.uint8))
    if int(itemsize) == 4:
        vals = jnp.asarray(rng.randn(n, 3).astype(np.float32))
    else:
        dt = np.int8 if int(itemsize) == 1 else np.int16
        vals = jnp.asarray(rng.randint(-100, 100, size=(n, 3), dtype=dt))
    best = None
    for k in widths:
        slot = jnp.asarray(rng.randint(0, k, size=n, dtype=np.int32))
        for blk in _block_candidates(n_cols, num_bins, itemsize, k):
            ms = _measure_ms(binned, vals, slot, k, blk, int(num_bins),
                             int(reps))
            if best is None or ms / k < best["ms_per_leaf"]:
                best = {"k": k, "block_rows": blk,
                        "ms_per_pass": round(ms, 4),
                        "ms_per_leaf": round(ms / k, 5)}
    best.update(platform=jax.devices()[0].platform,
                sample_rows=n, n_cols=int(n_cols),
                num_bins=int(num_bins), itemsize=int(itemsize),
                kmax=int(kmax), reps=int(reps))
    with _LOCK:
        _COUNTS["sweeps"] += 1
    return best


def ensure(n_rows: int, n_cols: int, num_bins: int, itemsize: int = 4,
           kmax: int = 64, dir_path: Optional[str] = None,
           config=None) -> dict:
    """Lookup-or-tune: the driver-facing entry.  Process memo → on-disk
    table → fresh sweep (persisted).  Returns the winning record; the
    caller snaps/clamps ``record["k"]`` through
    ``utils/shapes.fit_split_batch`` before use."""
    import jax
    d = dir_path or tune_dir(config)
    key = shape_key(jax.devices()[0].platform, n_rows, n_cols, num_bins,
                    itemsize, kmax)
    with _LOCK:
        rec = _MEM.get(key)
        if rec is not None:
            _COUNTS["hits"] += 1
            return rec
    table = _load_table(os.path.join(d, TUNE_FILE))
    rec = table.get(key)
    if isinstance(rec, dict) and "k" in rec and "block_rows" in rec:
        with _LOCK:
            _MEM[key] = rec
            _COUNTS["hits"] += 1
        return rec
    rec = tune(n_rows, n_cols, num_bins, itemsize=itemsize, kmax=kmax)
    _store(d, key, rec)
    with _LOCK:
        _MEM[key] = rec
    return rec
