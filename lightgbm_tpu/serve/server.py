"""Serving frontends: in-process ``Server`` API + stdlib HTTP endpoint.

``Server`` wires the subsystem together: a :class:`~.registry.ModelRegistry`
(initial model from a ``Booster``, a model file/string, or the newest
complete training snapshot), a :class:`~.batcher.MicroBatcher` sized by
the ``serve_*`` config params, and the PR-3 obs subsystem — ``serve.*``
metrics always collect (they are host-side counters, no device syncs);
spans/JSONL/profiler ride the usual ``telemetry`` switch.

Predictions go through ``Booster.predict`` of the batch's resolved
model version — which itself routes through the bucketed
:class:`~.engine.PredictorEngine` — so serve results are byte-identical
to a direct ``Booster.predict`` call on the same rows, micro-batch
coalescing included (elementwise routing + per-row accumulation make
batch composition invisible; tests/test_serve.py proves it across the
objective/feature matrix).  With ``serve_device_binning`` the batch
instead rides the engine's fused device-resident program
(``fused_predict``: one jit, one sync — docs/Serving.md
"Device-resident fast path"); models the fused path cannot serve (or
that failed the self-check gate) demote to the host walk, counted in
``serve.host_fallback_batches``.

``start_http`` exposes the same Server over a stdlib-only
``ThreadingHTTPServer``:

- ``POST /predict``  ``{"rows": [[...], ...], "deadline_ms": ...}`` ->
  ``{"predictions": ..., "model_version": ..., "num_rows": ...}``;
  429 + ``Retry-After`` on backpressure, 503 + ``Retry-After`` while
  the circuit breaker is open, 504 past the deadline, 503 while
  draining, 400 on malformed input.
- ``POST /reload``   ``{"model_file": ...}`` (or ``{"snapshot": out}``,
  optional ``"sha256"`` to pin the artifact) -> hot swap, in-flight
  requests finish on the old version; 409 on checksum mismatch (the
  current version keeps serving).
- ``POST /promote``  GATED promotion (pipeline/continual.py): the
  candidate activates only after SHA verification + engine self-check
  + a shadow-traffic parity probe over the last K live batches; 409
  with the refusing stage + reason on failure (the incumbent keeps
  serving, the candidate never took a request).
- ``GET /freshness`` serving staleness: current version + age,
  continual generations published / rolled back, and the
  chunk-arrival-to-serving lag when a ContinualTrainer is attached.
- ``POST /drain``    graceful shutdown prologue: refuse new work,
  finish queued work within ``serve_drain_s``; ``/healthz`` flips to
  503 so load balancers stop routing here.
- ``GET /healthz``   readiness + current model version + queue depth +
  breaker state: 200 while ``ok``/``degraded``, 503 when draining or
  model-less.
- ``GET /metrics``   deterministic JSON metrics snapshot
  (``serve.latency`` quantiles included) + engine compile stats.

CLI: ``python -m lightgbm_tpu serve input_model=model.txt`` (or
``task=serve`` in a config file) — see cli.py.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..config import Config
from ..utils.log import Log
from ..utils.resilience import RetryPolicy
from .batcher import (BacklogFull, BatcherClosed, DeadlineExceeded,
                      MicroBatcher)
from .breaker import CircuitOpen, ServeBreaker
from .registry import ArtifactVerificationError, ModelRegistry, NoModelError


class Server:
    """Long-lived in-process prediction service.

    Thread topology: HTTP handler threads (ThreadingHTTPServer) call
    ``submit``/``reload``/``promote``/``health``/``metrics_snapshot``
    concurrently; the batcher worker thread calls ``_predict_batch``;
    a ContinualTrainer may call ``promote``/``shadow_batches`` from its
    own loop thread.

    Lock contract (tools/analyze/check_races.py):
        _lock guards: _versions_loaded, _closed, _seg_labels
        registry type: lightgbm_tpu/serve/registry.py:ModelRegistry
        router type: lightgbm_tpu/fleet/router.py:SegmentRouter
        batcher type: lightgbm_tpu/serve/batcher.py:MicroBatcher
        breaker type: lightgbm_tpu/serve/breaker.py:ServeBreaker

    ``_shadow_ring`` is deliberately lock-free: deque appends are
    atomic under the GIL and ``shadow_batches`` snapshots via
    ``list()``; the ring holds references only."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 booster=None, model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        self.config = params if isinstance(params, Config) \
            else Config(params or {})
        cfg = self.config
        # serve bring-up shares the training processes' persistent
        # compile cache (train -> serve warm start) and installs the
        # compile counters surfaced by /metrics
        from ..utils.compile_cache import maybe_enable_from_config
        maybe_enable_from_config(cfg)
        from ..obs import MetricsRegistry, maybe_session
        self.obs = maybe_session(cfg)
        self.metrics = self.obs.metrics if self.obs is not None \
            else MetricsRegistry()
        self.tracer = self.obs.tracer if self.obs is not None else None
        self.registry = ModelRegistry(
            max_batch=cfg.serve_max_batch,
            min_bucket=cfg.serve_min_bucket,
            verify_artifacts=cfg.serve_verify_artifacts,
            device_binning=cfg.serve_device_binning,
            packed=cfg.serve_packed_tables,
            max_resident=cfg.serve_max_resident)
        # versions EVER activated (not currently registered — unload()
        # can hide history): gates the perf.forest achieved-rate join,
        # whose all-time rows/latency counters only describe one model.
        # Written from HTTP handler threads (reload/promote) — guarded
        self._lock = threading.Lock()
        self._versions_loaded = 0
        # segment -> version routing over the co-resident registry
        # (fleet serving, docs/Fleet.md): per-request ``segment`` keys
        # resolve here; unknown keys fall back to the default segment
        from ..fleet.router import SegmentRouter
        self.router = SegmentRouter(cfg.serve_default_segment)
        # distinct segment labels already granted their own metric
        # series (bounded by serve_metrics_max_versions; _seg_label)
        self._seg_labels: set = set()
        model_file = model_file or (cfg.input_model or None)
        if booster is not None or model_file or model_str:
            self.registry.load(model_file=model_file,
                               model_str=model_str, booster=booster)
            self._versions_loaded = 1
        elif cfg.resume and cfg.output_model:
            # serve the newest complete snapshot of a (possibly still
            # running) training job
            self.registry.load_snapshot(cfg.output_model)
            self._versions_loaded = 1
        self.breaker = ServeBreaker(
            failures=cfg.serve_breaker_failures,
            cooldown_ms=cfg.serve_breaker_cooldown_ms,
            metrics=self.metrics) \
            if cfg.serve_breaker_failures > 0 else None
        self.batcher = MicroBatcher(
            self._predict_batch,
            max_batch=cfg.serve_max_batch,
            max_wait_ms=cfg.serve_max_wait_ms,
            queue_rows=cfg.serve_queue_rows,
            # serve-scaled backoff: the bring-up defaults (1 s base)
            # would stall the single worker for seconds on a path whose
            # latency budget is serve_max_wait_ms
            retry_policy=RetryPolicy(
                max_attempts=max(1, cfg.serve_retries + 1),
                base_delay_s=0.02, max_delay_s=0.25),
            default_deadline_ms=cfg.serve_deadline_ms,
            breaker=self.breaker,
            metrics=self.metrics, tracer=self.tracer)
        self._t0 = time.time()
        self._closed = False
        # shadow-traffic ring (pipeline/continual.py): the last K live
        # batches, replayed through a promotion candidate by the
        # shadow-parity gate.  Array REFERENCES only — no copy, no
        # device work, bounded by shadow_probe_batches
        from collections import deque
        # maxlen=0 (shadow_probe_batches=0) keeps the ring permanently
        # empty: the replay probe is disabled, not clamped to 1
        self._shadow_ring = deque(
            maxlen=max(0, cfg.shadow_probe_batches))
        # attached ContinualTrainer (trainer constructor sets it):
        # GET /freshness reads its generation/lag state when present
        self.continual = None
        # flight recorder (obs/blackbox.py): per-batch records, dumped
        # on a batch failure; None (zero-cost) unless telemetry_blackbox
        from ..obs.blackbox import maybe_recorder
        self.recorder = maybe_recorder(
            cfg, default_path="lgbtpu_serve_blackbox.jsonl",
            meta={"surface": "serve"})

    # -- batch execution (worker thread) -----------------------------------
    def _resolve_served(self, segment):
        """The ServedModel for a batch's routing key: the router maps
        ``segment`` to a registry version (default-segment fallback for
        unknown keys); an unrouted/evicted resolution serves the
        registry's current model.  ``segment=None`` (unkeyed request)
        is exactly the pre-fleet path."""
        if segment is None:
            return self.registry.current()
        ver, fell_back = self.router.resolve(segment)
        if fell_back:
            self.metrics.counter("serve.segment_fallbacks").inc()
        if ver is None:
            return self.registry.current()
        try:
            return self.registry.get(ver)
        except KeyError:
            # the routed version was unloaded/evicted underneath the
            # assignment: drop the stale routes and serve current —
            # a routing gap degrades to the default model, never a 500
            for seg in self.router.drop_version(ver):
                Log.warning(f"serve: segment {seg!r} pointed at "
                            f"unloaded model {ver}; rerouting to "
                            "default")
            self.metrics.counter("serve.segment_fallbacks").inc()
            return self.registry.current()

    def _seg_label(self, segment) -> str:
        """Bounded-cardinality metric label for a segment: the first
        ``serve_metrics_max_versions`` distinct segments keep their own
        label; the rest aggregate under ``__other__`` so an unbounded
        key space cannot bloat the exposition."""
        cap = self.config.serve_metrics_max_versions
        if cap <= 0:
            return "__other__"
        s = str(segment)
        with self._lock:
            if s in self._seg_labels:
                return s
            if len(self._seg_labels) < cap:
                self._seg_labels.add(s)
                return s
        return "__other__"

    def _predict_batch(self, rows: np.ndarray,
                       segment=None) -> Tuple[np.ndarray, dict]:
        from ..utils import faultinject
        t0 = time.perf_counter() if self.recorder is not None else 0.0
        try:
            faultinject.check("serve_batch")   # chaos site (soak harness)
            served = self._resolve_served(segment)  # resolved per
            # batch: requests already in this batch finish on it even
            # if a reload or segment reassignment lands now
            served.begin_request()             # residency-cap eviction
            # skips versions with requests in flight (registry.py)
            try:
                if self.config.serve_device_binning:
                    eng = served.engine
                    if eng is not None and eng.fused_reason is None:
                        # device-resident fast path: ONE jitted
                        # bin->traverse->accumulate->transform program,
                        # one host<->device sync (the final score fetch)
                        out = eng.fused_predict(rows)
                        self.metrics.counter("serve.fused_batches").inc()
                    else:
                        # demoted (failed self-check discarded the
                        # engine) or fused-incapable (linear trees,
                        # f32-inexact categories): the always-correct
                        # host walk serves — slower, never wrong, never
                        # refused
                        self.metrics.counter(
                            "serve.host_fallback_batches").inc()
                        out = served.booster.predict(rows)
                else:
                    out = served.booster.predict(rows)
            finally:
                served.end_request()
            self._shadow_ring.append(rows)     # shadow-parity gate feed
        except Exception as e:
            if self.recorder is not None:
                # the batch-failure path is a flight-recorder trigger:
                # the dump carries the trailing per-batch records the
                # breaker/outage post-mortem needs
                self.recorder.record(event="batch_error",
                                     rows=int(len(rows)),
                                     error=f"{type(e).__name__}: {e}")
                self.recorder.dump("serve_batch_failure")
            raise
        if self.recorder is not None:
            self.recorder.record(rows=int(len(rows)),
                                 model_version=served.version,
                                 dur_s=round(time.perf_counter() - t0, 6))
        info = {"model_version": served.version}
        if segment is not None:
            info["segment"] = str(segment)
            self.metrics.counter(
                "serve.segment_rows",
                segment=self._seg_label(segment)).inc(len(rows))
        return np.asarray(out), info

    # -- client surface ----------------------------------------------------
    def predict(self, rows, timeout: Optional[float] = None,
                deadline_ms: Optional[float] = None,
                segment: Optional[str] = None) -> np.ndarray:
        """Predict through the micro-batching queue; blocks for the
        result.  Raises :class:`~.batcher.BacklogFull` under
        backpressure, :class:`~.breaker.CircuitOpen` while the breaker
        is open, :class:`~.batcher.DeadlineExceeded` past the
        deadline.  ``segment`` routes to that segment's promoted model
        version (fleet serving; unknown keys fall back to the default
        segment)."""
        return self.submit(rows, deadline_ms=deadline_ms,
                           segment=segment).result(timeout)

    def submit(self, rows, deadline_ms: Optional[float] = None,
               segment: Optional[str] = None):
        """Enqueue and return the :class:`PredictionFuture` (the
        non-blocking form of :meth:`predict`).  ``deadline_ms``
        overrides the ``serve_deadline_ms`` default for this request;
        ``segment`` is the fleet routing key — requests with different
        segments never share a device batch (they may resolve to
        different models)."""
        span = (self.tracer.span("serve.request", rows=len(rows))
                if self.tracer is not None else None)
        try:
            return self.batcher.submit(
                np.asarray(rows, np.float64), deadline_ms=deadline_ms,
                key=None if segment is None else str(segment))
        finally:
            # rejected submissions (breaker open, backlog, deadline,
            # draining) are exactly the events an outage trace needs —
            # the span must emit on every path
            if span is not None:
                span.end()

    def reload(self, model_file: Optional[str] = None,
               model_str: Optional[str] = None, booster=None,
               snapshot: Optional[str] = None,
               expected_sha256: Optional[str] = None,
               version: Optional[str] = None) -> str:
        """Load a new model version and atomically swap it in; returns
        the new version id (auto-assigned unless ``version`` names
        one).  A failed load (unreadable file, checksum mismatch,
        injected fault) leaves the current version serving and counts
        ``serve.reload_failures``."""
        try:
            if snapshot is not None:
                version = self.registry.load_snapshot(
                    snapshot, version=version,
                    expected_sha256=expected_sha256)
            else:
                version = self.registry.load(
                    model_file=model_file, model_str=model_str,
                    booster=booster, expected_sha256=expected_sha256,
                    version=version)
        except BaseException:
            self.metrics.counter("serve.reload_failures").inc()
            raise
        with self._lock:        # reload/promote race from HTTP threads
            self._versions_loaded += 1
        Log.info(f"serve: activated model {version}")
        return version

    # -- continual surface -------------------------------------------------
    def shadow_batches(self):
        """The last K live request batches (shadow_probe_batches ring) —
        the replay traffic of the shadow-parity promotion gate."""
        return list(self._shadow_ring)

    def promote(self, snapshot: Optional[str] = None,
                model_file: Optional[str] = None,
                expected_sha256: Optional[str] = None,
                version: Optional[str] = None,
                segment: Optional[str] = None):
        """GATED promotion (``POST /promote``): unlike :meth:`reload`,
        the candidate activates only after the two-stage gate — SHA
        verification + engine self-check, then the shadow-traffic
        parity probe over the live-batch ring against the incumbent
        (pipeline/continual.py ``gated_promote``).  A refusal raises
        :class:`~..pipeline.continual.GateFailure`, counts
        ``continual.rollbacks``, and leaves the incumbent serving —
        the candidate never takes a request.

        ``segment`` scopes the promotion to one routing key: the
        candidate runs the SAME full gate but on success only that
        segment is re-pointed at it (fleet router) — the default model
        and every other segment keep serving what they served.  A
        refusal likewise leaves the segment's previous assignment
        untouched."""
        from ..pipeline.continual import GateFailure, gated_promote
        try:
            v, gate = gated_promote(
                self.registry, snapshot=snapshot, model_file=model_file,
                expected_sha256=expected_sha256, cfg=self.config,
                batches=self.shadow_batches(), metrics=self.metrics,
                version=version, activate=segment is None)
        except (GateFailure, ArtifactVerificationError):
            # a REFUSED candidate is a rollback; a malformed operator
            # call (bad args, missing file) is not
            self.metrics.counter("continual.rollbacks").inc()
            raise
        with self._lock:
            self._versions_loaded += 1
        self.metrics.counter("continual.published").inc()
        if segment is not None:
            self.router.assign(segment, v)
            self.metrics.counter("serve.segment_promotes").inc()
            Log.info(f"serve: gated promotion routed segment "
                     f"{segment!r} -> model {v}")
        else:
            Log.info(f"serve: gated promotion activated model {v}")
        return v, gate

    def freshness(self) -> dict:
        """``GET /freshness``: how stale is what this replica serves —
        current version + its age, continual generation counters, and
        the chunk-arrival-to-serving lag when a ContinualTrainer is
        attached (its headline freshness guarantee)."""
        now = time.time()
        try:
            cur = self.registry.current()
        except NoModelError:
            cur = None
        out = {
            "model_version": cur.version if cur else None,
            "model_source": cur.source if cur else None,
            "model_loaded_at": cur.loaded_at if cur else None,
            "model_age_s": round(now - cur.loaded_at, 3) if cur else None,
            "generations_published":
                self.metrics.counter("continual.published").value,
            "generations_rolled_back":
                self.metrics.counter("continual.rollbacks").value,
        }
        ct = self.continual
        if ct is not None:
            # ONE-lock snapshot: three separate field reads would let a
            # promote land in between and report generation N next to
            # generation N+1's publish record
            out.update(ct.freshness_snapshot(now))
        else:
            # no trainer attached: the model's age IS the only lag
            # signal this replica has
            out["freshness_lag_s"] = out["model_age_s"]
        return out

    # -- lifecycle ---------------------------------------------------------
    @property
    def draining(self) -> bool:
        return self.batcher.draining

    def drain(self, timeout_s: Optional[float] = None) -> dict:
        """Graceful shutdown prologue: refuse new work, finish what is
        queued (bounded by ``timeout_s``, default ``serve_drain_s``),
        report the outcome.  The server stays alive (health answers,
        metrics export) until :meth:`close` — the LB-friendly sequence
        is drain, observe ``/healthz`` flip to 503, then close."""
        try:
            timeout_s = self.config.serve_drain_s if timeout_s is None \
                else float(timeout_s)
        except (TypeError, ValueError):
            timeout_s = self.config.serve_drain_s
        self.batcher.begin_drain()
        if timeout_s > 0:
            # the drain budget is enforced by the resilience watchdog's
            # cancel-and-raise mode (the same deadline machinery the
            # elastic collective timeout uses): a drain that wedges —
            # e.g. an in-flight batch stuck in a hung device call, so
            # the idle condition can never fire — dumps all-thread
            # stacks and raises in THIS thread instead of hanging
            # shutdown; the abandoned waiter is harmless (daemon,
            # wakes into a discarded result)
            from ..utils.resilience import Watchdog, WatchdogTimeout
            try:
                drained = Watchdog(
                    timeout_s, label="serve drain",
                    on_timeout="raise").run(self.batcher.wait_idle)
            except WatchdogTimeout:
                drained = False
        else:
            drained = self.batcher.wait_idle(timeout_s)
        leftover = self.batcher.depth_rows
        if drained:
            Log.info("serve: drained (all accepted requests answered)")
        else:
            Log.warning(f"serve: drain timed out after {timeout_s:g}s "
                        f"({leftover} rows still queued)")
        return {"drained": drained, "leftover_rows": leftover,
                "timeout_s": timeout_s}

    def health(self) -> dict:
        try:
            model = self.registry.current().describe()
            status = "ok"
        except NoModelError:
            model, status = None, "no_model"
        if self.batcher.draining or self._closed:
            status = "draining" if not self._closed else "stopped"
        elif status == "ok" and self.breaker is not None \
                and self.breaker.state() != "closed":
            # the device side is failing (or on probation): alive, but
            # a load balancer should prefer healthier replicas
            status = "degraded"
        out = {"status": status,
               # readiness: may an LB route NEW traffic here?  Degraded
               # stays ready — the breaker's half-open probe IS a
               # client request, so draining a degraded replica would
               # starve it of the traffic that closes the circuit
               "ready": status in ("ok", "degraded"),
               "model": model,
               "queue_depth_rows": self.batcher.depth_rows,
               "uptime_s": round(time.time() - self._t0, 3),
               "versions": self.registry.versions()}
        if self.breaker is not None:
            out["breaker"] = self.breaker.describe()
        return out

    def metrics_snapshot(self) -> dict:
        if self.breaker is not None:
            # the OPEN->HALF_OPEN transition is lazy (clock-driven, no
            # event): refresh so an idle replica's exported state can't
            # go stale against /healthz
            self.breaker.refresh_gauge()
        snap = dict(self.metrics.snapshot())
        lat = snap.get("serve.latency")
        if lat and lat.get("count"):
            from ..obs.metrics import Histogram
            h = Histogram(tuple(lat["buckets"]))
            h.counts, h.count = list(lat["counts"]), lat["count"]
            h.sum, h.min, h.max = lat["sum"], lat["min"], lat["max"]
            snap["serve.latency_quantiles"] = {
                "p50_s": h.quantile(0.5), "p99_s": h.quantile(0.99)}
        try:
            engine = self.registry.current().engine
            if engine is not None:
                snap["serve.engine"] = engine.compile_stats()
                # perf.* roofline gauges for the forest-traversal path
                # (obs/flops.py formulas + obs/attrib.py peak table):
                # static per-row accounting always; achieved rates when
                # latency history exists.  serve.latency is
                # client-observed (queueing included), so the achieved
                # FLOP/s is a LOWER bound on the device rate.
                from ..obs.attrib import config_peaks, roofline
                # per-path static accounting (obs/flops.py): the fused
                # one-jit program bins/accumulates/transforms on device,
                # so its per-row flops/bytes differ from the host-binned
                # traversal — the ledger note inside the fused trace and
                # this join use the SAME formula, keeping perf.forest.*
                # truthful for whichever path serves
                fl, hb = engine.per_row_flops_bytes(
                    fused=self.config.serve_device_binning)
                snap["perf.forest.flops_per_row"] = fl
                snap["perf.forest.hbm_bytes_per_row"] = hb
                # achieved rates join the CURRENT engine's per-row
                # accounting with the ALL-TIME rows/latency counters —
                # only meaningful while one model version has ever
                # served (after a hot swap the counters mix models, so
                # the join degrades to the static per-row keys above)
                rows = snap.get("serve.rows", {}).get("value", 0.0)
                lat = snap.get("serve.latency") or {}
                secs = float(lat.get("sum", 0.0)) if lat.get("count") \
                    else 0.0
                pf, pb = config_peaks(self.config)
                # intensity/bound are per-row ratios — always valid
                for k, v in roofline(fl, hb, 0, pf, pb).items():
                    snap[f"perf.forest.{k}"] = v
                with self._lock:
                    versions_loaded = self._versions_loaded
                if versions_loaded <= 1:
                    for k, v in roofline(fl * rows, hb * rows, secs,
                                         pf, pb).items():
                        snap[f"perf.forest.{k}"] = v
        except NoModelError:
            pass
        # segment routing table — bounded by the same label cap as the
        # per-segment counters so a hostile key stream can't bloat the
        # export (overflow collapses into a count, not a key list)
        segs = self.router.snapshot()
        if segs:
            cap = max(0, int(self.config.serve_metrics_max_versions))
            items = sorted(segs.items())
            snap["serve.segments"] = dict(items[:cap])
            if len(items) > cap:
                snap["serve.segments_overflow"] = len(items) - cap
            snap["serve.segments_total"] = len(items)
        # process-wide compile accounting (utils/compile_cache.py): the
        # serving replica's warm-start evidence — backend compiles,
        # persistent-cache hits/misses, and per-program trace counts
        from ..utils.compile_cache import compile_snapshot
        snap.update(compile_snapshot(traces="by_name"))
        return snap

    def close(self) -> None:
        with self._lock:        # close-once latch: two racing closers
            if self._closed:    # must not double-close the sinks
                return
            self._closed = True
        self.batcher.close()
        if self.recorder is not None:
            self.recorder.close()
        if self.obs is not None:
            self.obs.finish()


# ---------------------------------------------------------------------------
# HTTP frontend (stdlib only)
# ---------------------------------------------------------------------------

class HttpFrontend:
    """Handle for a running HTTP frontend (``.port``, ``.close()``)."""

    def __init__(self, httpd, thread: Optional[threading.Thread]):
        self._httpd = httpd
        self._thread = thread
        self.host, self.port = httpd.server_address[:2]

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def start_http(server: Server, host: str = "127.0.0.1", port: int = 0,
               background: bool = True) -> HttpFrontend:
    """Expose ``server`` over HTTP; ``port=0`` picks a free port (read
    it back from the returned handle)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):       # route through Log
            Log.debug("serve-http: " + fmt % args)

        def _send(self, code: int, payload: dict,
                  headers: Optional[dict] = None) -> None:
            self._send_text(code, json.dumps(payload),
                            "application/json", headers)

        def _send_text(self, code: int, text: str, content_type: str,
                       headers: Optional[dict] = None) -> None:
            body = text.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            from urllib.parse import parse_qs, urlparse
            u = urlparse(self.path)
            if u.path == "/healthz":
                h = server.health()
                # readiness semantics for load balancers: 200 only
                # while NEW traffic should be routed here; a draining
                # or model-less replica answers (liveness) with 503.
                # health() computes "ready" — route on it so code and
                # body can never disagree
                self._send(200 if h["ready"] else 503, h)
            elif u.path == "/freshness":
                self._send(200, server.freshness())
            elif u.path == "/metrics":
                snap = server.metrics_snapshot()
                if parse_qs(u.query).get("format", [""])[0] == "prom":
                    # Prometheus text exposition (obs/metrics.py),
                    # covering the perf.* gauges and serve histograms
                    from ..obs.metrics import prometheus_text
                    self._send_text(
                        200, prometheus_text(snap),
                        "text/plain; version=0.0.4; charset=utf-8")
                else:
                    self._send(200, snap)
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
            except (ValueError, TypeError) as e:
                self._send(400, {"error": f"bad JSON: {e}"})
                return
            if self.path == "/predict":
                self._predict(req)
            elif self.path == "/reload":
                self._reload(req)
            elif self.path == "/promote":
                self._promote(req)
            elif self.path == "/drain":
                self._send(200, server.drain(req.get("timeout_s")))
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

        def _current_version(self):
            try:
                return server.registry.current().version
            except NoModelError:
                return None

        def _predict(self, req: dict) -> None:
            rows = req.get("rows")
            if rows is None:
                self._send(400, {"error": "missing 'rows'"})
                return
            try:
                arr = np.asarray(rows, np.float64)
                if arr.ndim == 1:
                    arr = arr.reshape(1, -1)
                if arr.ndim != 2:
                    raise ValueError(f"rows must be 2-D, got "
                                     f"{arr.ndim}-D")
            except (ValueError, TypeError) as e:
                self._send(400, {"error": f"bad rows: {e}"})
                return
            deadline_ms = req.get("deadline_ms")
            timeout_s = req.get("timeout_s", 30.0)
            try:
                if deadline_ms is not None:
                    deadline_ms = float(deadline_ms)
                timeout_s = float(timeout_s)
            except (ValueError, TypeError) as e:
                # malformed knobs are the client's fault — 400, like
                # bad rows, not the catch-all 500 below
                self._send(400, {"error": f"bad deadline_ms or "
                                          f"timeout_s: {e}"})
                return
            segment = req.get("segment")
            if segment is not None:
                segment = str(segment)
            try:
                fut = server.submit(arr, deadline_ms=deadline_ms,
                                    segment=segment)
                pred = fut.result(timeout=timeout_s)
            except BacklogFull as e:
                self._send(429, {"error": str(e),
                                 "retry_after_ms": e.retry_after_ms},
                           headers={"Retry-After": str(max(
                               1, int(e.retry_after_ms / 1000 + 0.5)))})
                return
            except CircuitOpen as e:
                # the device side is failing: reject up front with the
                # breaker's cooldown as the back-off hint
                self._send(503, {"error": str(e),
                                 "retry_after_ms": e.retry_after_ms},
                           headers={"Retry-After": str(max(
                               1, int(e.retry_after_ms / 1000 + 0.5)))})
                return
            except DeadlineExceeded as e:
                self._send(504, {"error": str(e),
                                 "deadline_ms": e.deadline_ms,
                                 "where": e.where})
                return
            except BatcherClosed as e:       # draining or shut down
                self._send(503, {"error": str(e),
                                 "draining": server.draining})
                return
            except NoModelError as e:
                self._send(503, {"error": str(e)})
                return
            except Exception as e:          # noqa: BLE001 — request-scoped
                from ..basic import LightGBMError
                # a malformed REQUEST (wrong feature count, bad shape)
                # is the client's fault — 400, not 500; per-width batch
                # coalescing guarantees it failed alone
                code = 400 if isinstance(e, (ValueError, LightGBMError)) \
                    else 500
                self._send(code,
                           {"error": f"{type(e).__name__}: {e}"})
                return
            body = {
                "predictions": np.asarray(pred).tolist(),
                "num_rows": int(len(arr)),
                "model_version": fut.info.get("model_version")}
            if segment is not None:
                body["segment"] = fut.info.get("segment", segment)
            self._send(200, body)

        def _reload(self, req: dict) -> None:
            try:
                version = server.reload(
                    model_file=req.get("model_file"),
                    model_str=req.get("model_str"),
                    snapshot=req.get("snapshot"),
                    expected_sha256=req.get("sha256"))
            except ArtifactVerificationError as e:
                # the artifact is not what the caller said it was —
                # conflict, not client-syntax error; current version
                # keeps serving.  The BODY carries the verification
                # failure reason (which file, which checksums) plus the
                # version still serving — a deploy script retrying on a
                # bare 409 has nothing to page the operator with
                self._send(409, {"error": str(e),
                                 "reason": str(e),
                                 "verification": "failed",
                                 "current_version":
                                     self._current_version()})
                return
            except Exception as e:          # noqa: BLE001 — operator call
                self._send(400,
                           {"error": f"{type(e).__name__}: {e}"})
                return
            self._send(200, {"model_version": version})

        def _promote(self, req: dict) -> None:
            """Gated promotion: 200 with the gate report on pass; 409
            with the stage + reason on any gate refusal (verification,
            self-check, shadow parity) — the incumbent keeps serving
            and the candidate never took a request."""
            from ..pipeline.continual import GateFailure
            segment = req.get("segment")
            if segment is not None:
                segment = str(segment)
            try:
                version, gate = server.promote(
                    snapshot=req.get("snapshot"),
                    model_file=req.get("model_file"),
                    expected_sha256=req.get("sha256"),
                    segment=segment)
            except ArtifactVerificationError as e:
                self._send(409, {"error": str(e), "reason": str(e),
                                 "stage": "verify",
                                 "current_version":
                                     self._current_version()})
                return
            except GateFailure as e:
                self._send(409, {"error": str(e), "reason": e.reason,
                                 "stage": e.stage,
                                 "current_version":
                                     self._current_version()})
                return
            except Exception as e:          # noqa: BLE001 — operator call
                self._send(400,
                           {"error": f"{type(e).__name__}: {e}"})
                return
            body = {"model_version": version, "gate": gate}
            if segment is not None:
                body["segment"] = segment
            self._send(200, body)

    httpd = ThreadingHTTPServer((host, port), Handler)
    httpd.daemon_threads = True
    thread = None
    if background:
        thread = threading.Thread(target=httpd.serve_forever,
                                  name="lgbtpu-serve-http", daemon=True)
        thread.start()
    Log.info(f"serve: HTTP frontend on "
             f"http://{httpd.server_address[0]}:"
             f"{httpd.server_address[1]}")
    return HttpFrontend(httpd, thread)
