"""End-to-end ``tree_learner=data|feature|voting`` through the user API.

The reference dispatches the parallel learners in its factory
(/root/reference/src/treelearner/tree_learner.cpp:16-64) and tests them by
simulating machines with localhost-socket subprocesses
(tests/distributed/_test_distributed.py:79-100); here the 8-virtual-device
CPU mesh IS the cluster, and ``lgb.train`` with a parallel tree_learner
must produce the same model as serial training
(data_parallel_tree_learner.cpp:13-283 behavior contract).
"""

import jax
import numpy as np
import pytest

import lightgbm_tpu as lgb

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")

BASE = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
        "learning_rate": 0.1, "max_bin": 63, "verbosity": -1}


def _train(params, x, y, nrounds=10):
    ds = lgb.Dataset(x, label=y)
    return lgb.train(dict(params), ds, num_boost_round=nrounds)


def _assert_same_model(bst_a, bst_b):
    assert len(bst_a.trees) == len(bst_b.trees)
    for ts, td in zip(bst_a.trees, bst_b.trees):
        np.testing.assert_array_equal(ts.split_feature, td.split_feature)
        np.testing.assert_array_equal(ts.left_child, td.left_child)
        np.testing.assert_allclose(ts.leaf_value, td.leaf_value,
                                   rtol=1e-4, atol=1e-5)


class TestDataParallelE2E:
    @pytest.mark.parametrize("owner", [True, False])
    def test_matches_serial(self, binary_data, owner):
        x, y = binary_data
        bst_s = _train(BASE, x, y)
        bst_d = _train(dict(BASE, tree_learner="data",
                            dp_owner_shard=owner), x, y)
        assert bst_d._model._dist == "data"
        assert bst_d._model._mesh.shape["data"] == 8
        assert bst_d._model.grower.owner_shard is owner
        if owner:
            # per-shard histogram carry rows = ceil(F/8), not F
            assert bst_d._model.grower.plan.chunk == -(-x.shape[1] // 8)
        _assert_same_model(bst_s, bst_d)
        np.testing.assert_allclose(bst_s.predict(x), bst_d.predict(x),
                                   rtol=1e-4, atol=1e-5)

    def test_row_padding(self, binary_data):
        # 3997 rows over 8 shards forces zero-weight row padding
        x, y = binary_data
        x, y = x[:3997], y[:3997]
        bst_s = _train(BASE, x, y)
        bst_d = _train(dict(BASE, tree_learner="data"), x, y)
        assert bst_d._model._row_pad == 3
        _assert_same_model(bst_s, bst_d)

    def test_num_machines_auto_promotes(self, binary_data):
        # CheckParamConflict (config.cpp:139): num_machines>1 promotes
        # serial -> data; mesh size follows num_machines
        x, y = binary_data
        bst = _train(dict(BASE, num_machines=2), x, y, nrounds=3)
        assert bst._model._dist == "data"
        assert bst._model._mesh.shape["data"] == 2

    def test_mesh_shape_param(self, binary_data):
        x, y = binary_data
        bst = _train(dict(BASE, tree_learner="data", mesh_shape=[4]), x, y,
                     nrounds=3)
        assert bst._model._mesh.shape["data"] == 4

    def test_bagging_and_valid(self, binary_data):
        x, y = binary_data
        ds = lgb.Dataset(x[:3000], label=y[:3000])
        vs = lgb.Dataset(x[3000:], label=y[3000:], reference=ds)
        evals = {}
        bst = lgb.train(dict(BASE, tree_learner="data", bagging_freq=1,
                             bagging_fraction=0.8),
                        ds, num_boost_round=10, valid_sets=[vs],
                        valid_names=["v"],
                        callbacks=[lgb.record_evaluation(evals)])
        ll = evals["v"]["binary_logloss"]
        assert ll[-1] < ll[0]

    def test_node_controls_rejected(self, binary_data):
        """Monotone 'basic' is SUPPORTED under the data-parallel learner
        now (tests/test_constraints.py TestMonotoneMasked); the remaining
        host-orchestrated controls still reject with a clear error."""
        x, y = binary_data
        with pytest.raises(ValueError, match="tree_learner=data"):
            _train(dict(BASE, tree_learner="data",
                        monotone_constraints=[1] * x.shape[1],
                        monotone_constraints_method="intermediate"),
                   x, y, 1)
        with pytest.raises(ValueError, match="tree_learner=data"):
            _train(dict(BASE, tree_learner="data",
                        feature_fraction_bynode=0.5), x, y, 1)


class TestFeatureParallelE2E:
    def test_matches_serial(self, binary_data):
        x, y = binary_data
        bst_s = _train(BASE, x, y)
        bst_f = _train(dict(BASE, tree_learner="feature"), x, y)
        assert bst_f._model._dist == "feature"
        # 20 features over 8 shards -> padded to 24
        assert bst_f._model._feat_pad == 4
        _assert_same_model(bst_s, bst_f)
        np.testing.assert_allclose(bst_s.predict(x), bst_f.predict(x),
                                   rtol=1e-4, atol=1e-5)


class TestVotingParallelE2E:
    def test_quality(self, binary_data):
        # vote compression changes the model; quality must stay close
        # (PV-tree guarantee, voting_parallel_tree_learner.cpp)
        x, y = binary_data
        bst_s = _train(BASE, x, y, nrounds=20)
        bst_v = _train(dict(BASE, tree_learner="voting", top_k=5), x, y,
                       nrounds=20)
        assert bst_v._model._dist == "voting"
        from lightgbm_tpu.metrics import _auc
        auc_s = _auc(y, bst_s.predict(x, raw_score=True), None)
        auc_v = _auc(y, bst_v.predict(x, raw_score=True), None)
        assert auc_v > auc_s - 0.01

    def test_full_vote_matches_serial(self, binary_data):
        # contract: with top_k >= num_features the vote selects EVERY
        # feature, the filtered psum degenerates to the full data-parallel
        # reduction, and the tree must equal serial exactly — this pins
        # the vote statistic's validity masks (min_data/min_hessian with
        # the per-rank /num_machines rescale,
        # voting_parallel_tree_learner.cpp:61-63): an over-strict local
        # mask would veto features and break the equality
        x, y = binary_data
        bst_s = _train(BASE, x, y, nrounds=5)
        bst_v = _train(dict(BASE, tree_learner="voting",
                            top_k=x.shape[1]), x, y, nrounds=5)
        _assert_same_model(bst_s, bst_v)

    def test_local_constraint_rescale(self):
        # min_data_in_leaf near the LOCAL shard size: unscaled local
        # constraints would invalidate every candidate on every shard
        # (8 shards x 500 rows; min_data_in_leaf=300 < 500 but every
        # balanced local child has ~<300 rows), the vote would select
        # arbitrary features and quality would collapse
        rs = np.random.RandomState(13)
        n, f = 4000, 12
        x = rs.randn(n, f)
        y = (x[:, 3] - x[:, 5] > 0).astype(np.float32)
        bst = _train(dict(BASE, tree_learner="voting", top_k=2,
                          min_data_in_leaf=300, num_leaves=4), x, y,
                     nrounds=5)
        from lightgbm_tpu.metrics import _auc
        auc = _auc(y, bst.predict(x, raw_score=True), None)
        assert auc > 0.9
        used = {int(ft) for t in bst.trees
                for ft in t.split_feature[:t.num_nodes()]}
        assert used <= {3, 5}, f"voted splits on noise features: {used}"


class TestVotingRootTotals:
    def test_unvoted_feature0_keeps_root_totals(self):
        # regression: root aggregates must not flow through the
        # vote-filtered histogram — with f >> 2*top_k and feature 0
        # uninformative, the vote zeroes hist[0] and a hist-derived total
        # would corrupt the root (leaf_output, counts, right_sum)
        rs = np.random.RandomState(11)
        n, f = 4000, 16
        x = rs.randn(n, f)
        y = (x[:, 7] > 0).astype(np.float32)
        bst = _train(dict(BASE, tree_learner="voting", top_k=2), x, y,
                     nrounds=2)
        t = bst.trees[0]
        assert t.internal_count[0] == n
        assert int(t.split_feature[0]) == 7


class TestMulticlassDistributed:
    def test_multiclass_data_parallel(self):
        rs = np.random.RandomState(7)
        n, f = 1600, 10
        x = rs.randn(n, f)
        yc = (x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int)
        params = dict(BASE, objective="multiclass", num_class=3,
                      tree_learner="data")
        params.pop("max_bin")
        bst = _train(params, x, yc.astype(np.float32))
        pred = bst.predict(x)
        assert pred.shape == (n, 3)
        acc = (pred.argmax(1) == yc).mean()
        assert acc > 0.85


class TestEFBDataParallel:
    """EFB under tree_learner=data (VERDICT r2 task 7): bundles shrink the
    histogram psum payload exactly where it is biggest (wide sparse data,
    dataset.cpp:239; data_parallel_tree_learner.cpp:174-186)."""

    @staticmethod
    def _epsilon_shaped(n=4096, groups=400, per=5, seed=0):
        """Wide sparse data: `groups` bundles of `per` mutually exclusive
        indicator features (Epsilon-like width: groups*per columns)."""
        rng = np.random.RandomState(seed)
        x = np.zeros((n, groups * per), np.float32)
        for g in range(groups):
            pick = rng.randint(0, per + 1, n)     # 0 = none active
            for j in range(per):
                rows = pick == j + 1
                x[rows, g * per + j] = rng.rand(int(rows.sum())) + 0.5
        y = (x[:, 0] + 2.0 * x[:, 5] - x[:, 10] + x[:, 15]
             > 0.8).astype(np.float32)
        return x, y

    @pytest.mark.parametrize("sb", [1, 8])
    def test_efb_on_matches_efb_off_and_serial(self, sb):
        x, y = self._epsilon_shaped()
        p = dict(BASE, tree_learner="data", num_leaves=7, split_batch=sb)
        b_on = _train(dict(p, enable_bundle=True), x, y, nrounds=5)
        b_off = _train(dict(p, enable_bundle=False), x, y, nrounds=5)
        b_ser = _train(dict(BASE, num_leaves=7, enable_bundle=True,
                            split_batch=sb), x, y, nrounds=5)
        # owner-shard engaged, with the GROUP axis chunked when bundling:
        # each shard's histogram carry holds ceil(G/8) group rows
        m = b_on._model
        assert getattr(m.grower, "owner_shard", False)
        n_groups = b_on.train_set.binned.shape[1]
        assert m.grower.plan.chunk == -(-n_groups // 8)
        # without bundles the chunk axis is the flat feature axis
        m_off = b_off._model
        assert m_off.grower.plan.chunk == -(-x.shape[1] // 8)

        def same(a, b):
            # identical split structure; leaf values only to ~1e-3:
            # group-space vs feature-space f32 histogram accumulation
            # rounds differently under the per-shard psum
            for ts, td in zip(a.trees, b.trees):
                np.testing.assert_array_equal(ts.split_feature,
                                              td.split_feature)
                np.testing.assert_array_equal(ts.left_child, td.left_child)
                np.testing.assert_allclose(ts.leaf_value, td.leaf_value,
                                           rtol=1e-3, atol=1e-4)

        same(b_on, b_off)
        if sb == 1:
            same(b_on, b_ser)
        else:
            # split_batch>1 on this one-hot data hits EXACTLY-tied leaf
            # gains, and the super-step top_k order then follows f32
            # last-bit reduction differences — serial's own trees flip
            # between iterations here, and the legacy full-psum dp
            # diverges from serial identically to owner-shard (verified:
            # dp_owner_shard=false produces bit-identical trees to true).
            # Pin quality instead of tie order for the batched case.
            from lightgbm_tpu.metrics import _auc
            auc_dp = _auc(y, b_on.predict(x, raw_score=True), None)
            auc_ser = _auc(y, b_ser.predict(x, raw_score=True), None)
            assert auc_dp > auc_ser - 0.01

    def test_width_reduction(self):
        x, y = self._epsilon_shaped()
        p = dict(BASE, num_leaves=7, tree_learner="data")
        ds = lgb.Dataset(x, label=y, params=p)
        bst = lgb.train(p, ds, num_boost_round=2)
        m = bst._model
        assert m._use_efb, "EFB should be active under tree_learner=data"
        n_groups = m.binned_dev.shape[1]
        n_features = x.shape[1]
        assert n_groups <= n_features // 3, \
            f"expected >=3x width reduction, got {n_groups}/{n_features}"
        assert len(bst.trees) == 2
