"""Subprocess worker for the elastic kill -9 matrix
(tests/test_zelastic.py, the ``continual_worker.py`` mold).

Three modes over ONE deterministic dataset and parameter set:

- ``worker <rank> <machines>`` — one rank of a 2-process
  ``jax.distributed`` data-parallel elastic run (gloo collectives on
  CPU, 1 device per process).  Rank 1 SIGKILLs itself mid-iteration
  (after the snapshot at ``KILL_AFTER_ITER`` landed); rank 0 must
  detect the loss via the elastic liveness layer (heartbeat staleness
  or the collective deadline — whichever classifies first), persist
  the failure, and exit with :data:`SHRINK_RC` carrying a
  ``shrink.json`` marker (survivors + detection seconds) — the
  pod-launcher contract of ``ElasticShrinkRequired``.
- ``resume`` — the relaunched survivor: single process over the FULL
  data; ``resume=true`` must locate the 2-process run's snapshot (its
  manifest carries the GLOBAL score + full-data fingerprint) and
  finish the remaining rounds on the shrunk (serial) topology.
  Writes ``final.txt`` and prints ``WORKER_DONE``.
- ``serial`` — the uninterrupted single-process oracle; writes
  ``serial.txt``.

With ``quant_train=true`` (int32 histograms) ``final.txt`` must be
BYTE-IDENTICAL to ``serial.txt``; the f32 histogram path is asserted
to metric-epsilon by the driver instead.

Usage: python elastic_worker.py <outdir> <mode> [rank] [machines]
"""

import json
import os
import signal
import sys
import time

ROUNDS = 10
KILL_AFTER_ITER = 4      # rank 1 dies right after this iteration's
#                          callback — one iteration past the snapshot
SNAPSHOT_FREQ = 2
SHRINK_RC = 42


def _data(n=320, f=6, seed=11):
    import numpy as np
    rs = np.random.RandomState(seed)
    x = rs.randn(n, f)
    y = (x[:, 0] - 0.6 * x[:, 1] + 0.2 * rs.randn(n) > 0) \
        .astype("float32")
    return x, y


def _params(outdir, quant: bool):
    return {"objective": "binary", "num_leaves": 8, "max_bin": 31,
            "min_data_in_leaf": 5, "verbosity": -1,
            "quant_train": bool(quant),
            "output_model": os.path.join(outdir, "m.txt"),
            "snapshot_freq": SNAPSHOT_FREQ, "snapshot_keep": 0,
            "elastic_enable": True,
            "elastic_heartbeat_dir": os.path.join(outdir, "hb"),
            "elastic_heartbeat_interval_s": 0.2,
            "elastic_heartbeat_timeout_s": 2.0,
            "elastic_collective_timeout_s": 4.0,
            "elastic_recover_timeout_s": 60.0}


def main():
    outdir, mode = sys.argv[1], sys.argv[2]
    quant = os.environ.get("ELASTIC_WORKER_QUANT", "1") != "0"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from lightgbm_tpu.utils.compile_cache import enable_persistent_cache
    enable_persistent_cache()
    x, y = _data()
    params = _params(outdir, quant)

    if mode == "serial":
        import lightgbm_tpu as lgb
        p = {k: v for k, v in params.items()
             if not k.startswith("elastic_") and k != "snapshot_freq"}
        bst = lgb.train(p, lgb.Dataset(x, label=y),
                        num_boost_round=ROUNDS)
        with open(os.path.join(outdir, "serial.txt"), "w",
                  encoding="utf-8") as f:
            f.write(bst.model_to_string().split("parameters:")[0])
        print("WORKER_DONE serial", flush=True)
        return

    from lightgbm_tpu.parallel import elastic

    if mode == "resume":
        bst = elastic.elastic_train(dict(params, tree_learner="serial"),
                                    x, y, num_boost_round=ROUNDS)
        with open(os.path.join(outdir, "final.txt"), "w",
                  encoding="utf-8") as f:
            f.write(bst.model_to_string().split("parameters:")[0])
        print(f"WORKER_DONE resume trees={len(bst.trees)}", flush=True)
        return

    assert mode == "worker"
    rank, machines = int(sys.argv[3]), sys.argv[4]
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    from lightgbm_tpu.parallel import launch
    entries = [m for m in machines.split(",") if m]
    launch.init(coordinator_address=entries[0],
                num_processes=len(entries), process_id=rank)

    last_iter_t = {"t": time.time()}

    def on_iter(env):
        last_iter_t["t"] = time.time()
        if rank == 1 and env.iteration + 1 == KILL_AFTER_ITER:
            # the kill -9: a preempted host vanishes without unwinding
            print("WORKER_KILLING_SELF", flush=True)
            os.kill(os.getpid(), signal.SIGKILL)

    p = dict(params, tree_learner="data", num_machines=len(entries))
    try:
        bst = elastic.elastic_train(p, x, y, num_boost_round=ROUNDS,
                                    callbacks=[on_iter])
    except elastic.ElasticShrinkRequired as e:
        detect_s = time.time() - last_iter_t["t"]
        with open(os.path.join(outdir, f"shrink_{rank}.json"), "w",
                  encoding="utf-8") as f:
            json.dump({"kind": e.kind, "survivors": e.survivors,
                       "detect_s": round(detect_s, 3),
                       "rank": rank}, f)
        print(f"WORKER_SHRINK kind={e.kind} detect_s={detect_s:.2f}",
              flush=True)
        # os._exit: the dead peer makes jax.distributed's atexit
        # shutdown barrier unreachable — exiting through it would hang
        # this process on the very failure it just classified
        os._exit(SHRINK_RC)
    # rank 0 only reaches here if the peer never died (a test bug)
    print(f"WORKER_DONE unexpected trees={len(bst.trees)}", flush=True)


if __name__ == "__main__":
    main()
