/* R .C-convention shim over the LGBM_Train* C ABI (libcapi_train.so).
 *
 * R's .C foreign-function interface passes every argument as a pointer
 * and cannot return opaque handles, so — exactly like the reference's
 * own R-package glue (R-package/src/lightgbm_R.cpp wraps c_api.h calls
 * behind R-callable entry points) — a thin C shim adapts the ABI to the
 * calling convention.  This one drives the full train lifecycle
 * (dataset create -> set label -> booster create -> N UpdateOneIter ->
 * SaveModel -> PredictForMat) in one call; granular handle-table
 * wrappers would follow the same pattern.
 *
 * R matrices arrive COLUMN-major (Fortran layout); the ABI wants
 * row-major, so the shim transposes.  Labels arrive as R doubles and
 * are narrowed to the float32 the "label" field stores.
 *
 * Build:  gcc -O2 -shared -fPIC lgbtpu_shim.c -o lgbtpu_shim.so \
 *             /path/to/libcapi_train.so -Wl,-rpath,<dir-of-libcapi> \
 *             -Wl,-rpath,<dir-of-libpythonX.Y>
 * Use:    dyn.load("lgbtpu_shim.so"); .C("lgbtpu_smoke", ...) — see
 *         smoke.R next to this file.
 */

#include <stdio.h>
#include <stdlib.h>

typedef void* H;
extern const char* LGBM_TrainGetLastError(void);
extern int LGBM_TrainDatasetCreateFromMat(const double*, int, int,
                                          const char*, H, H*);
extern int LGBM_TrainDatasetSetField(H, const char*, const void*, int, int);
extern int LGBM_TrainDatasetFree(H);
extern int LGBM_TrainBoosterCreate(H, const char*, H*);
extern int LGBM_TrainBoosterUpdateOneIter(H, int*);
extern int LGBM_TrainBoosterSaveModel(H, int, int, const char*);
extern int LGBM_TrainBoosterPredictForMat(H, const double*, int, int, int,
                                          int, int, long long, double*,
                                          long long*);
extern int LGBM_TrainBoosterFree(H);

#define CHECK(rc) do { if ((rc) != 0) {                                   \
    fprintf(stderr, "lgbtpu_smoke: %s\n", LGBM_TrainGetLastError());      \
    goto cleanup; } } while (0)

void lgbtpu_smoke(double* x_colmajor, int* n_, int* f_, double* y_,
                  char** ds_params, char** bst_params, int* rounds_,
                  char** model_path, double* out_pred, int* status) {
  int n = *n_, f = *f_, i, j, fin = 0;
  long long out_len = 0;
  H ds = 0, bst = 0;
  double* x = (double*)malloc(sizeof(double) * (size_t)n * (size_t)f);
  float* y = (float*)malloc(sizeof(float) * (size_t)n);
  *status = 1;
  if (!x || !y) goto cleanup;
  for (i = 0; i < n; ++i)
    for (j = 0; j < f; ++j)
      x[(size_t)i * f + j] = x_colmajor[(size_t)j * n + i];
  for (i = 0; i < n; ++i) y[i] = (float)y_[i];

  CHECK(LGBM_TrainDatasetCreateFromMat(x, n, f, ds_params[0], 0, &ds));
  CHECK(LGBM_TrainDatasetSetField(ds, "label", y, n, 0));
  CHECK(LGBM_TrainBoosterCreate(ds, bst_params[0], &bst));
  for (i = 0; i < *rounds_; ++i)
    CHECK(LGBM_TrainBoosterUpdateOneIter(bst, &fin));
  if (model_path[0] && model_path[0][0])
    CHECK(LGBM_TrainBoosterSaveModel(bst, 0, -1, model_path[0]));
  CHECK(LGBM_TrainBoosterPredictForMat(bst, x, n, f, 0, 0, -1, n,
                                       out_pred, &out_len));
  *status = (out_len == n) ? 0 : 2;
cleanup:
  if (bst) LGBM_TrainBoosterFree(bst);
  if (ds) LGBM_TrainDatasetFree(ds);
  free(x);
  free(y);
}
