"""Per-objective QUALITY gates (VERDICT r3 weak 5: 'training runs' is
not a gate).  Every objective family must actually optimize its own
loss: training N rounds must beat the constant-prediction baseline on
that loss by a meaningful margin, and the specialized objectives must
beat (or match) plain L2 on THEIR loss — the property the reference's
test_engine.py asserts with golden metric values."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _reg_data(n=3000, f=8, seed=0, noise="normal"):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, f)
    signal = 2.0 * x[:, 0] - x[:, 1] + 0.5 * x[:, 2] * x[:, 3]
    if noise == "normal":
        y = signal + 0.3 * rs.randn(n)
    elif noise == "heavy":           # outliers: the robust-loss regime
        y = signal + 0.3 * rs.standard_t(1.5, size=n)
    return x, y.astype(np.float64)


def _train(params, x, y, rounds=40):
    p = dict(params, verbosity=-1, num_leaves=15, max_bin=63,
             min_data_in_leaf=10, learning_rate=0.1)
    return lgb.train(p, lgb.Dataset(x, label=y, params=p),
                     num_boost_round=rounds)


def _l1(pred, y):
    return float(np.mean(np.abs(pred - y)))


def _l2(pred, y):
    return float(np.mean((pred - y) ** 2))


class TestRegressionFamilies:
    def test_l2_beats_baseline(self):
        x, y = _reg_data()
        bst = _train({"objective": "regression"}, x, y)
        base = _l2(np.full_like(y, y.mean()), y)
        got = _l2(bst.predict(x), y)
        assert got < 0.25 * base, f"l2 {got} vs baseline {base}"

    @pytest.mark.parametrize("obj", ["regression_l1", "huber", "fair"])
    def test_robust_beats_l2_under_outliers(self, obj):
        # heavy-tailed noise: robust losses must beat plain L2 on MAE
        x, y = _reg_data(noise="heavy", seed=3)
        robust = _train({"objective": obj}, x, y)
        plain = _train({"objective": "regression"}, x, y)
        mae_r = _l1(robust.predict(x), y)
        mae_p = _l1(plain.predict(x), y)
        base = _l1(np.full_like(y, np.median(y)), y)
        assert mae_r < 0.6 * base, f"{obj} MAE {mae_r} vs baseline {base}"
        assert mae_r < mae_p * 1.02, \
            f"{obj} MAE {mae_r} should beat/match L2's {mae_p} on outliers"

    def test_quantile_pinball(self):
        # the alpha-quantile objective must beat the others on ITS loss
        x, y = _reg_data(seed=4)
        alpha = 0.8

        def pinball(pred):
            d = y - pred
            return float(np.mean(np.maximum(alpha * d, (alpha - 1) * d)))

        q = _train({"objective": "quantile", "alpha": alpha}, x, y)
        l2 = _train({"objective": "regression"}, x, y)
        base = pinball(np.full_like(y, np.quantile(y, alpha)))
        got = pinball(q.predict(x))
        assert got < 0.5 * base, f"pinball {got} vs baseline {base}"
        assert got < pinball(l2.predict(x)), \
            "quantile objective must beat L2 on pinball loss"
        # and the predictions sit near the conditional quantile: ~alpha
        # of residuals below the prediction
        frac_below = float((y <= q.predict(x)).mean())
        assert abs(frac_below - alpha) < 0.1, frac_below

    def test_mape_relative_error(self):
        rs = np.random.RandomState(5)
        x = rs.randn(3000, 6)
        y = np.exp(1.5 * x[:, 0]) * (1 + 0.1 * rs.randn(3000))
        y = np.abs(y) + 0.1
        m = _train({"objective": "mape"}, x, y)
        rel = float(np.mean(np.abs(m.predict(x) - y) / y))
        base = float(np.mean(np.abs(np.median(y) - y) / y))
        assert rel < 0.6 * base, f"MAPE {rel} vs baseline {base}"

    @pytest.mark.parametrize("obj,inv", [("poisson", np.log),
                                         ("gamma", np.log),
                                         ("tweedie", np.log)])
    def test_log_link_families_fit_rate(self, obj, inv):
        rs = np.random.RandomState(6)
        x = rs.randn(3000, 6)
        rate = np.exp(0.8 * x[:, 0] - 0.4 * x[:, 1])
        y = rs.poisson(rate).astype(np.float64) if obj == "poisson" \
            else rate * (1 + 0.2 * rs.randn(3000)) ** 2
        y = np.maximum(y, 1e-3 if obj != "poisson" else 0.0)
        bst = _train({"objective": obj}, x, y, rounds=60)
        pred = bst.predict(x)
        assert (pred > 0).all()
        # deviance-style gate: correlation of log-rate recovered
        corr = np.corrcoef(inv(np.maximum(pred, 1e-9)),
                           0.8 * x[:, 0] - 0.4 * x[:, 1])[0, 1]
        assert corr > 0.85, f"{obj} log-rate corr {corr}"


class TestClassificationFamilies:
    def test_binary_logloss_beats_baseline(self):
        rs = np.random.RandomState(7)
        x = rs.randn(3000, 8)
        p_true = 1 / (1 + np.exp(-(1.5 * x[:, 0] - x[:, 1])))
        y = (rs.rand(3000) < p_true).astype(np.float64)
        bst = _train({"objective": "binary"}, x, y)
        pred = np.clip(bst.predict(x), 1e-9, 1 - 1e-9)
        ll = float(-np.mean(y * np.log(pred) + (1 - y) * np.log(1 - pred)))
        pbar = y.mean()
        base = float(-(pbar * np.log(pbar) + (1 - pbar) * np.log(1 - pbar)))
        assert ll < 0.75 * base, f"logloss {ll} vs baseline {base}"

    def test_cross_entropy_probability_labels(self):
        # cross_entropy accepts soft labels in [0, 1]
        rs = np.random.RandomState(8)
        x = rs.randn(2500, 6)
        y = 1 / (1 + np.exp(-(x[:, 0] - 0.5 * x[:, 1])))  # soft targets
        bst = _train({"objective": "cross_entropy"}, x, y)
        pred = np.clip(bst.predict(x), 1e-9, 1 - 1e-9)
        xe = float(-np.mean(y * np.log(pred)
                            + (1 - y) * np.log(1 - pred)))
        pbar = y.mean()
        base = float(-np.mean(y * np.log(pbar)
                              + (1 - y) * np.log(1 - pbar)))
        # soft labels carry an irreducible entropy floor H(y): gate on
        # closing most of the gap between the constant baseline and it
        floor = float(-np.mean(y * np.log(y) + (1 - y) * np.log(1 - y)))
        assert xe < floor + 0.35 * (base - floor), \
            f"xent {xe} vs baseline {base}, floor {floor}"
        # calibrated: mean prediction matches mean soft label
        assert abs(pred.mean() - y.mean()) < 0.02

    def test_multiclass_beats_uniform(self):
        rs = np.random.RandomState(9)
        x = rs.randn(3000, 6)
        logits = np.stack([x[:, 0], x[:, 1], -x[:, 0] - x[:, 1]], axis=1)
        y = logits.argmax(axis=1).astype(np.float64)
        for obj in ("multiclass", "multiclassova"):
            bst = _train({"objective": obj, "num_class": 3}, x, y)
            p = np.clip(bst.predict(x), 1e-9, 1.0)
            ll = float(np.mean(-np.log(
                p[np.arange(len(y)), y.astype(int)])))
            assert ll < 0.5 * np.log(3), f"{obj} logloss {ll}"
