"""Fused-chunk training parity: k train_one_iter calls == one train_chunk(k).

The fused path (GBDTModel.train_chunk) must produce byte-identical model
strings to the per-iteration path — same grower, same RNG streams (feature
masks pre-drawn host-side, GOSS keys seeded by iteration index in-graph).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=1200, f=12, seed=3):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float32)
    y = (x[:, 0] - 0.5 * x[:, 1] + 0.3 * rng.randn(n) > 0).astype(np.float32)
    return x, y


def _train(params, x, y, rounds=23):
    ds = lgb.Dataset(x, label=y)
    return lgb.train(dict(params), ds, num_boost_round=rounds)


def _norm(model_str):
    """Model string minus the recorded fused_chunk param (the one line
    that legitimately differs between the two paths)."""
    return "\n".join(l for l in model_str.splitlines()
                     if not l.startswith("[fused_chunk:"))


BASE = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
        "max_bin": 31, "min_data_in_leaf": 5, "verbosity": -1,
        "tpu_learner": "masked"}


@pytest.mark.parametrize("extra", [
    {},
    {"feature_fraction": 0.6},
    {"data_sample_strategy": "goss", "top_rate": 0.3, "other_rate": 0.3},
    {"objective": "regression"},
])
def test_fused_matches_per_iter(extra):
    x, y = _data()
    p_fused = dict(BASE, fused_chunk=10, **extra)
    p_plain = dict(BASE, fused_chunk=0, **extra)
    b_fused = _train(p_fused, x, y)
    b_plain = _train(p_plain, x, y)
    assert len(b_fused.trees) == len(b_plain.trees)
    assert _norm(b_fused.model_to_string()) == _norm(b_plain.model_to_string())
    pred_f = b_fused.predict(x)
    pred_p = b_plain.predict(x)
    np.testing.assert_allclose(pred_f, pred_p, rtol=1e-6)


def test_fused_stump_stops_training():
    # constant labels -> no split possible -> both paths stop with the
    # same single stump tree
    x, _ = _data(400, 6)
    y = np.ones(400, np.float32)
    b_fused = _train(dict(BASE, fused_chunk=8, objective="regression"),
                     x, y, rounds=16)
    b_plain = _train(dict(BASE, fused_chunk=0, objective="regression"),
                     x, y, rounds=16)
    assert len(b_fused.trees) == len(b_plain.trees)
    assert _norm(b_fused.model_to_string()) == _norm(b_plain.model_to_string())


def test_fused_mid_chunk_stump_parity():
    # feature_fraction can draw an unsplittable mask mid-chunk (stump);
    # per-iter semantics stop training THERE.  The fused scan must not let
    # later iterations (whose masks could split) leak deltas into the
    # score (code-review r3 finding: dead-flag in the scan carry).
    rng = np.random.RandomState(0)
    n = 2000
    x = np.column_stack([rng.randn(n), rng.randn(n)]).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    p = dict(BASE, num_leaves=7, feature_fraction=0.5,
             min_gain_to_split=50.0, min_data_in_leaf=5)
    b_fused = _train(dict(p, fused_chunk=10), x, y, rounds=20)
    b_plain = _train(dict(p, fused_chunk=0), x, y, rounds=20)
    # the uninformative feature's mask must have produced a stump early
    assert len(b_plain.trees) < 20, \
        "test setup: expected an early stump under feature_fraction"
    assert len(b_fused.trees) == len(b_plain.trees)
    assert _norm(b_fused.model_to_string()) == _norm(b_plain.model_to_string())
    np.testing.assert_allclose(
        np.asarray(b_fused._model.train_score()),
        np.asarray(b_plain._model.train_score()), rtol=1e-6)


def test_fused_respects_remainder():
    # rounds not divisible by the chunk: remainder runs per-iter, total
    # tree count must still be exact
    x, y = _data()
    b = _train(dict(BASE, fused_chunk=10), x, y, rounds=17)
    assert len(b.trees) == 17


def test_fused_bagging_parity():
    # bagging masks are drawn IN-GRAPH keyed by the refresh epoch
    # (gbdt.cpp:230-264 analog), so bagging configs fuse and the fused
    # chunk reproduces the per-iteration models exactly
    x, y = _data()
    p = dict(BASE, bagging_freq=2, bagging_fraction=0.7)
    b_fused = _train(dict(p, fused_chunk=6), x, y, rounds=12)
    b_plain = _train(dict(p, fused_chunk=0), x, y, rounds=12)
    assert b_fused._model.supports_fused()
    assert len(b_fused.trees) == 12
    assert _norm(b_fused.model_to_string()) == _norm(b_plain.model_to_string())
    np.testing.assert_allclose(
        np.asarray(b_fused._model.train_score()),
        np.asarray(b_plain._model.train_score()), rtol=1e-6)


def test_fused_pos_neg_bagging_parity():
    # pos/neg bagging (binary objective) routes through the same in-graph
    # draw with the device label vector
    x, y = _data()
    p = dict(BASE, bagging_freq=1, pos_bagging_fraction=0.8,
             neg_bagging_fraction=0.5)
    b_fused = _train(dict(p, fused_chunk=5), x, y, rounds=10)
    b_plain = _train(dict(p, fused_chunk=0), x, y, rounds=10)
    assert b_fused._model.supports_fused()
    assert _norm(b_fused.model_to_string()) == _norm(b_plain.model_to_string())


def test_bagging_mask_refresh_epochs():
    # same mask within a bagging_freq window, different across windows
    x, y = _data()
    p = dict(BASE, bagging_freq=3, bagging_fraction=0.6)
    b = _train(p, x, y, rounds=1)
    m = b._model
    w0 = np.asarray(m._bagging_w(jnp.int32(0)))
    w2 = np.asarray(m._bagging_w(jnp.int32(2)))
    w3 = np.asarray(m._bagging_w(jnp.int32(3)))
    np.testing.assert_array_equal(w0, w2)
    assert (w0 != w3).any()
    frac = w0.mean()
    assert 0.5 < frac < 0.7
