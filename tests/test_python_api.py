"""python-package convenience surface parity (basic.py):
Booster.attr/set_attr, feature_name, shuffle_models, bounds,
get_leaf_output, get_split_value_histogram, trees_to_dataframe, eval;
Dataset get/set_field, get_data, set_reference, set_feature_name,
feature_num_bin, get_ref_chain, add_features_from."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=1000, f=6, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, f)
    y = (x[:, 0] - 0.5 * x[:, 1] > 0).astype(np.float32)
    return x, y


P = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
     "verbosity": -1}


@pytest.fixture(scope="module")
def bst():
    x, y = _data()
    return lgb.train(dict(P), lgb.Dataset(x, label=y), num_boost_round=6)


def test_predict_disable_shape_check(bst):
    """ADVICE r5 #2: the feature-count mismatch raise honors
    predict_disable_shape_check (config or predict-time override) and the
    error message names the param (reference c_api predict contract)."""
    x, _ = _data(n=64)
    with pytest.raises(lgb.LightGBMError,
                       match="predict_disable_shape_check"):
        bst.predict(x[:, :4])
    # narrower data with the check disabled: the missing tail zero-fills
    # (the reference Predictor's zero-initialized row buffer) — identical
    # to explicitly passing zeros for those features
    p_narrow = bst.predict(x[:, :4], predict_disable_shape_check=True)
    assert p_narrow.shape == (64,) and np.isfinite(p_narrow).all()
    x_zeroed = np.concatenate([x[:, :4], np.zeros((64, x.shape[1] - 4))],
                              axis=1)
    np.testing.assert_allclose(p_narrow, bst.predict(x_zeroed), rtol=1e-12)
    # wider data: extra columns are ignored -> identical to exact-width
    x_wide = np.concatenate([x, np.ones((64, 2))], axis=1)
    p_wide = bst.predict(x_wide, predict_disable_shape_check=True)
    np.testing.assert_allclose(p_wide, bst.predict(x), rtol=1e-12)
    # config-level flag works without the per-call override
    x2, y2 = _data(n=500)
    bst2 = lgb.train(dict(P, predict_disable_shape_check=True),
                     lgb.Dataset(x2, label=y2), num_boost_round=2)
    assert np.isfinite(bst2.predict(x2[:8, :4])).all()


def test_train_fobj_positional_slot():
    """ADVICE r5 #1: train() takes fobj in the reference positional slot
    (between valid_names and feval), matching cv() — a reference-style
    positional call must bind the custom objective correctly."""
    x, y = _data(n=600)
    ds = lgb.Dataset(x, label=y)

    def fobj(preds, dsx):
        lbl = np.asarray(dsx.get_label())
        p = 1.0 / (1.0 + np.exp(-preds))
        return p - lbl, p * (1.0 - p)

    feval_calls = []

    def feval(score, dsx):
        feval_calls.append(1)
        return ("dummy", float(np.mean(score)), False)

    vs = lgb.Dataset(x[:100], label=y[:100], reference=ds)
    pc = dict(P, objective="custom")
    # positional: (params, ds, rounds, valid_sets, valid_names, FOBJ, FEVAL)
    bst = lgb.train(pc, ds, 4, [vs], ["v"], fobj, feval)
    assert len(bst.trees) == 4
    assert feval_calls, "positional feval was not used as the eval metric"
    # keyword spelling unchanged
    bst_kw = lgb.train(dict(pc), ds, 4, fobj=fobj)
    np.testing.assert_allclose(bst.predict(x[:16], raw_score=True),
                               bst_kw.predict(x[:16], raw_score=True),
                               rtol=1e-6)


def test_attr_roundtrip(bst):
    assert bst.attr("k") is None
    bst.set_attr(k="v", n=3)
    assert bst.attr("k") == "v" and bst.attr("n") == "3"
    bst.set_attr(k=None)
    assert bst.attr("k") is None


def test_feature_name_and_bounds(bst):
    assert len(bst.feature_name()) == 6
    assert bst.upper_bound() > bst.lower_bound()
    assert bst.get_leaf_output(0, 0) == float(bst.trees[0].leaf_value[0])


def test_shuffle_models_preserves_predictions_modulo_order(bst):
    import copy
    x, _ = _data()
    b = copy.deepcopy(bst)
    before = b.predict(x[:50], raw_score=True)
    b.shuffle_models()
    # additive model: prediction is order-invariant; tree multiset same
    np.testing.assert_allclose(b.predict(x[:50], raw_score=True), before,
                               rtol=1e-9)
    assert len(b.trees) == len(bst.trees)


def test_split_value_histogram(bst):
    counts, edges = bst.get_split_value_histogram(0)
    assert counts.sum() == sum(
        (t.split_feature[:t.num_nodes()] == 0).sum() for t in bst.trees)


def test_trees_to_dataframe(bst):
    pd = pytest.importorskip("pandas")
    df = bst.trees_to_dataframe()
    n_nodes = sum(t.num_nodes() for t in bst.trees)
    n_leaves = sum(t.num_leaves for t in bst.trees)
    assert len(df) == n_nodes + n_leaves
    assert set(df["tree_index"]) == set(range(len(bst.trees)))
    # root rows carry the full data count
    roots = df[df["node_index"] == "0-S0"]
    assert int(roots["count"].iloc[0]) == 1000


def test_booster_eval_arbitrary_dataset(bst):
    x, y = _data(seed=1)
    res = bst.eval(lgb.Dataset(x, label=y, free_raw_data=False), "holdout")
    assert res and res[0][0] == "holdout"
    names = {r[1] for r in res}
    assert "binary_logloss" in names
    ll = next(r[2] for r in res if r[1] == "binary_logloss")
    assert 0.0 < ll < 0.6


class TestDatasetSurface:
    def test_fields_and_data(self):
        x, y = _data(300, 4, seed=2)
        w = np.abs(np.random.RandomState(3).randn(300)).astype(np.float32)
        ds = lgb.Dataset(x, label=y, free_raw_data=False)
        ds.set_field("weight", w)
        ds.construct()
        np.testing.assert_allclose(ds.get_field("weight"), w, rtol=1e-6)
        np.testing.assert_allclose(ds.get_field("label"), y, rtol=1e-6)
        assert ds.get_data().shape == (300, 4)
        assert ds.get_init_score() is None

    def test_reference_chain_and_set_reference(self):
        x, y = _data(300, 4, seed=4)
        train = lgb.Dataset(x, label=y)
        valid = lgb.Dataset(x[:100], label=y[:100])
        valid.set_reference(train)
        train.construct()
        valid.construct()
        chain = valid.get_ref_chain()
        assert chain[0] is valid and chain[1] is train
        # aligned binning
        assert valid.feature_num_bin(0) == train.feature_num_bin(0)
        with pytest.raises(ValueError):
            valid.set_reference(train)   # post-construction

    def test_set_feature_name(self):
        x, y = _data(200, 3, seed=5)
        ds = lgb.Dataset(x, label=y)
        ds.set_feature_name(["a", "b", "c"])
        ds.construct()
        assert ds.feature_names == ["a", "b", "c"]

    def test_add_features_from_trains(self):
        x, y = _data(400, 3, seed=6)
        x2 = np.random.RandomState(7).randn(400, 2)
        a = lgb.Dataset(x, label=y, free_raw_data=False)
        b = lgb.Dataset(x2, free_raw_data=False)
        a.construct()
        b.construct()
        a.add_features_from(b)
        assert a.num_total_features == 5
        bst = lgb.train(dict(P), a, num_boost_round=4)
        assert bst.num_feature() == 5
        assert np.isfinite(bst.predict(np.hstack([x, x2])[:20])).all()


def test_trees_to_dataframe_depth(bst):
    pytest.importorskip("pandas")
    df = bst.trees_to_dataframe()
    roots = df[df["node_index"].str.endswith("-S0")]
    assert (roots["node_depth"] == 1).all()
    assert df["node_depth"].notna().all()
    # every child is exactly one deeper than its parent
    by_idx = df.set_index("node_index")
    for _, r in df[df["parent_index"].notna()].iterrows():
        assert r["node_depth"] == by_idx.loc[r["parent_index"],
                                             "node_depth"] + 1


def test_eval_sparse_and_freed_raw(bst):
    from scipy.sparse import csr_matrix
    x, y = _data(seed=8)
    res = bst.eval(lgb.Dataset(csr_matrix(x), label=y), "sparse_hold")
    assert res[0][0] == "sparse_hold"
    # raw captured before construct() even with free_raw_data default
    res2 = bst.eval(lgb.Dataset(x, label=y), "dense_hold")
    assert np.isfinite(res2[0][2])


def test_train_data_name():
    x, y = _data(400, 4, seed=9)
    b = lgb.train(dict(P), lgb.Dataset(x, label=y), num_boost_round=2)
    b.set_train_data_name("my_train")
    assert b.eval_train()[0][0] == "my_train"


def test_get_data_raises_after_free():
    x, y = _data(200, 3, seed=10)
    ds = lgb.Dataset(x, label=y, free_raw_data=True)
    lgb.train(dict(P), ds, num_boost_round=1)
    with pytest.raises(ValueError, match="free_raw_data"):
        ds.get_data()


def test_set_feature_name_wrong_size_fails_early():
    x, y = _data(200, 3, seed=11)
    ds = lgb.Dataset(x, label=y)
    with pytest.raises(ValueError, match="2 names for 3 features"):
        ds.set_feature_name(["a", "b"])


def test_eval_on_loaded_model(bst):
    x, y = _data(seed=12)
    loaded = lgb.Booster(model_str=bst.model_to_string())
    res = loaded.eval(lgb.Dataset(x, label=y, free_raw_data=False), "h")
    assert res and np.isfinite(res[0][2])


# --- round-5 advisor regressions (ADVICE r4): merge order, shuffle
# sequence, weighted bounds, reset_training_data guard ----------------

def test_merge_puts_other_trees_first(bst):
    """GBDT::MergeFrom (gbdt.h:63-80) pushes the OTHER booster's models
    first; tree indices of order-sensitive consumers must match."""
    import copy
    x, y = _data(seed=5)
    a = copy.deepcopy(bst)
    b = lgb.train(dict(P), lgb.Dataset(x, label=y), num_boost_round=2)
    a_first_leaf = float(a.trees[0].leaf_value[0])
    b_first_leaf = float(b.trees[0].leaf_value[0])
    a._merge_from(b)
    assert len(a.trees) == len(bst.trees) + 2
    # other's trees lead, self's follow
    assert float(a.trees[0].leaf_value[0]) == b_first_leaf
    assert float(a.trees[2].leaf_value[0]) == a_first_leaf
    # merged prediction == sum of the two ensembles
    pred = a.predict(x[:50], raw_score=True)
    np.testing.assert_allclose(
        pred,
        bst.predict(x[:50], raw_score=True)
        + b.predict(x[:50], raw_score=True), rtol=1e-6)


def test_merge_string_loaded_keeps_device_tail(bst):
    """Merging a string-loaded booster (no device trees) must keep
    device_trees aligned to the TAIL of models — add_valid_set
    (models/gbdt.py) replays the first len(models)-len(device_trees)
    trees host-side (ADVICE r4 medium #2)."""
    x, y = _data()
    a = lgb.train(dict(P), lgb.Dataset(x, label=y), num_boost_round=4)
    b = lgb.Booster(model_str=a.model_to_string())
    n_trees = len(a.trees)
    n_dev_before = len(a._model.device_trees)
    a._merge_from(b)
    m = a._model
    assert len(m.device_trees) == n_dev_before
    n_host_only = len(m.models) - len(m.device_trees)
    # the host-only head is exactly the merged-in (string-loaded) trees
    assert n_host_only == len(b.trees) + (n_trees - n_dev_before)
    # validation scoring must still see all trees (tail invariant holds)
    pred = a.predict(x[:20], raw_score=True)
    np.testing.assert_allclose(
        pred, 2.0 * b.predict(x[:20], raw_score=True), rtol=1e-6)


def test_shuffle_models_reference_sequence(bst):
    """ShuffleModels uses the reference's fixed Random(17) partial
    Fisher-Yates (gbdt.h:82-105, utils/random.h LCG), NOT a numpy
    stream — verify against an independent emulation."""
    import copy
    b = copy.deepcopy(bst)
    n = len(b.trees)
    orig = [float(t.leaf_value[0]) for t in b.trees]
    b.shuffle_models()
    lcg = 17
    idx = list(range(n))
    for i in range(0, n - 1):
        lcg = (214013 * lcg + 2531011) & 0xFFFFFFFF
        j = ((lcg >> 16) & 0x7FFF) % (n - (i + 1)) + i + 1
        idx[i], idx[j] = idx[j], idx[i]
    expect = [orig[idx[i]] for i in range(n)]
    got = [float(t.leaf_value[0]) for t in b.trees]
    assert got == expect


def test_bounds_scale_by_tree_weights(bst):
    """lower/upper_bound must scale per-tree extrema by tree_weights
    (this framework applies DART/RF weights at predict time)."""
    b = lgb.Booster(model_str=bst.model_to_string())
    lo0, hi0 = b.lower_bound(), b.upper_bound()
    b.tree_weights = [0.5] * len(b.trees)
    assert b.lower_bound() == pytest.approx(0.5 * lo0)
    assert b.upper_bound() == pytest.approx(0.5 * hi0)


def test_reset_training_data_requires_raw(bst):
    import copy
    x, y = _data(seed=9)
    b = copy.deepcopy(bst)
    ds = lgb.Dataset(x, label=y, params=dict(P), free_raw_data=True)
    ds.construct()
    ds.raw_data = None
    with pytest.raises(ValueError, match="raw values"):
        b.reset_training_data(ds)


def test_dataset_params_are_binning_base():
    """Reference _update_params semantics (basic.py: train params are
    update()d ONTO dataset params): Dataset(params={'max_bin': k}) keeps
    its k bins when the train-time params don't mention binning — the
    lifecycle every C-API client uses (binning params at DatasetCreate,
    training params at BoosterCreate)."""
    x, y = _data(seed=12)
    ds = lgb.Dataset(x, label=y, params={"max_bin": 15, "verbosity": -1})
    b = lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1}, ds, num_boost_round=2)
    assert max(m.num_bin for m in b.train_set.bin_mappers) <= 16
    # train-time params still OVERRIDE on conflict
    ds2 = lgb.Dataset(x, label=y, params={"max_bin": 15, "verbosity": -1})
    b2 = lgb.train({"objective": "binary", "num_leaves": 7, "max_bin": 31,
                    "verbosity": -1}, ds2, num_boost_round=2)
    nb = max(m.num_bin for m in b2.train_set.bin_mappers)
    assert 16 < nb <= 32, nb


def test_predict_start_iteration_slices_sum():
    """predict(start_iteration, num_iteration) slices must sum to the
    full raw prediction (basic.py contract; the reference's own test of
    this couples it to an early-stopping trajectory)."""
    x, y = _data(seed=13)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(x, label=y),
                    num_boost_round=20)
    full = bst.predict(x, raw_score=True)
    sliced = sum(bst.predict(x, start_iteration=s, num_iteration=7,
                             raw_score=True) for s in range(0, 20, 7))
    np.testing.assert_allclose(sliced, full, rtol=1e-9)
    # start>0 with num_iteration<=0 takes all REMAINING trees
    np.testing.assert_allclose(
        bst.predict(x, start_iteration=5, num_iteration=-1, raw_score=True),
        bst.predict(x, start_iteration=5, num_iteration=15, raw_score=True))


def test_booster_pickle_copy_roundtrip():
    import copy
    import pickle
    x, y = _data(seed=14)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(x, label=y),
                    num_boost_round=4)
    p0 = bst.predict(x)
    for clone in (pickle.loads(pickle.dumps(bst)), copy.copy(bst),
                  copy.deepcopy(bst)):
        np.testing.assert_array_equal(clone.predict(x), p0)
    # best_iteration/best_score survive every clone path (a stale
    # shadowing __deepcopy__ once silently dropped them)
    bst.best_iteration = 2
    bst.best_score = {"valid": {"l2": 1.0}}
    for clone in (pickle.loads(pickle.dumps(bst)), copy.copy(bst),
                  copy.deepcopy(bst)):
        assert clone.best_iteration == 2
        assert clone.best_score == {"valid": {"l2": 1.0}}
        np.testing.assert_array_equal(clone.predict(x),
                                      bst.predict(x, num_iteration=2))
    # explicit num_iteration<=0 means ALL trees even when best is set
    np.testing.assert_array_equal(bst.predict(x, num_iteration=-1), p0)
