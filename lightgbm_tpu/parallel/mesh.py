"""Device-mesh construction for distributed training.

Replaces the reference's whole communication stack
(/root/reference/src/network/: hand-rolled Bruck allgather
network.cpp:156, recursive-halving reduce-scatter :249, socket/MPI linkers)
with ``jax.sharding.Mesh`` + XLA collectives over ICI/DCN — the schedule is
owned by the compiler (SURVEY.md §2.5 TPU mapping).  Multi-host
initialization goes through ``jax.distributed`` (the ``LGBM_NetworkInit``
analog, c_api.h:1350) which wires the same collectives across hosts.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(shape: Optional[Sequence[int]] = None,
              axis_names: Sequence[str] = ("data",),
              devices=None) -> Mesh:
    """Build a mesh over the available devices.

    shape=None uses all devices on one ``data`` axis (the GBDT scale axis —
    rows; SURVEY.md §2.6: data-parallel is the reference's main distributed
    mode, docs/Experiments.rst Criteo scaling).
    """
    devs = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devs),)
    shape = tuple(int(s) for s in shape)
    n = int(np.prod(shape))
    if n > len(devs):
        raise ValueError(f"mesh needs {n} devices, have {len(devs)}")
    mesh_devs = np.asarray(devs[:n]).reshape(shape)
    if len(axis_names) != len(shape):
        axis_names = tuple(f"axis{i}" for i in range(len(shape)))
    return Mesh(mesh_devs, tuple(axis_names))


def default_mesh(num: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    num = num or len(devs)
    return make_mesh((num,), ("data",), devs)


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bring-up (jax.distributed) — the ``Network::Init`` /
    ``LGBM_NetworkInit`` analog (network.cpp, c_api.h:1350).  On TPU pods
    arguments are auto-detected from the runtime environment."""
    kwargs = {}
    if coordinator_address is not None:
        kwargs.update(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    jax.distributed.initialize(**kwargs)
