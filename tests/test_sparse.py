"""Sparse (scipy CSR/CSC) dataset construction and prediction
(LGBM_DatasetCreateFromCSR/CSC + LGBM_BoosterPredictForCSR analogs,
/root/reference/include/LightGBM/c_api.h:109-313, basic.py sparse paths).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def sparse_data():
    rs = np.random.RandomState(7)
    n, f = 3000, 30
    dense = rs.randn(n, f)
    # 80% of entries zeroed -> genuinely sparse
    dense[rs.rand(n, f) < 0.8] = 0.0
    y = (dense[:, 0] - dense[:, 1] + 0.5 * dense[:, 2] > 0).astype(np.float32)
    return dense, y


PARAMS = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
          "max_bin": 63, "min_data_in_leaf": 5, "verbosity": -1}


def test_csr_matches_dense_training(sparse_data):
    dense, y = sparse_data
    csr = sp.csr_matrix(dense)
    bst_d = lgb.train(PARAMS, lgb.Dataset(dense, label=y), num_boost_round=15)
    bst_s = lgb.train(PARAMS, lgb.Dataset(csr, label=y), num_boost_round=15)
    pd = bst_d.predict(dense, raw_score=True)
    ps = bst_s.predict(dense, raw_score=True)
    np.testing.assert_allclose(pd, ps, rtol=1e-5, atol=1e-5)


def test_csc_construct(sparse_data):
    dense, y = sparse_data
    csc = sp.csc_matrix(dense)
    ds = lgb.Dataset(csc, label=y).construct()
    assert ds.num_data == dense.shape[0]
    ds_ref = lgb.Dataset(dense, label=y).construct()
    np.testing.assert_array_equal(ds.feature_binned(), ds_ref.feature_binned())


def test_csr_predict(sparse_data):
    dense, y = sparse_data
    bst = lgb.train(PARAMS, lgb.Dataset(dense, label=y), num_boost_round=10)
    p_dense = bst.predict(dense)
    p_csr = bst.predict(sp.csr_matrix(dense))
    np.testing.assert_allclose(p_dense, p_csr, rtol=1e-6)


def test_csr_valid_set(sparse_data):
    dense, y = sparse_data
    tr = lgb.Dataset(sp.csr_matrix(dense[:2000]), label=y[:2000])
    va = lgb.Dataset(sp.csr_matrix(dense[2000:]), label=y[2000:], reference=tr)
    res = {}
    from lightgbm_tpu.callback import record_evaluation
    lgb.train(PARAMS, tr, num_boost_round=10, valid_sets=[va],
              callbacks=[record_evaluation(res)])
    assert res
