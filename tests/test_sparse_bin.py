"""Sparse binned storage (sparse_data.py — sparse_bin.hpp:73 /
multi_val_sparse_bin.hpp analog): layout ops vs the dense reference
implementations, end-to-end training equality, persistence, and the
Allstate-class memory budget (VERDICT r4 task 4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import scipy.sparse as sps

from lightgbm_tpu import sparse_data as spd
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import Dataset
from lightgbm_tpu.engine import train
from lightgbm_tpu.ops.histogram import compute_histogram


def _rand_sparse(rng, n, f, nnz_row, nbins=16):
    """Random CSR whose values land in ~nbins distinct positive values."""
    rows = np.repeat(np.arange(n), nnz_row)
    cols = rng.integers(0, f, size=n * nnz_row)
    # dedupe (row, col) pairs so CSR doesn't sum duplicates into new values
    key = rows.astype(np.int64) * f + cols
    _, first = np.unique(key, return_index=True)
    rows, cols = rows[first], cols[first]
    vals = rng.integers(1, nbins, size=len(rows)).astype(np.float64)
    return sps.csr_matrix((vals, (rows, cols)), shape=(n, f))


def _to_sparse_binned(dense_bins, default_bin, stride):
    """Build the k-hot layout directly from a dense bin matrix."""
    n, f = dense_bins.shape
    rows, cols = np.nonzero(dense_bins != default_bin[None, :])
    flat = cols * stride + dense_bins[rows, cols]
    return spd.build_khot(rows.astype(np.int64), flat.astype(np.int32),
                          default_bin, n, stride, f)


class TestLayoutOps:
    def setup_method(self):
        rng = np.random.default_rng(7)
        self.n, self.f, self.b = 257, 11, 8
        self.dense = rng.integers(0, self.b, size=(self.n, self.f)) \
            .astype(np.int32)
        self.default_bin = rng.integers(0, self.b, size=self.f) \
            .astype(np.int32)
        self.sp = _to_sparse_binned(self.dense, self.default_bin,
                                    self.b).to_device()

    def test_column_matches_dense(self):
        for feat in [0, 3, self.f - 1]:
            got = np.asarray(spd.column(self.sp, jnp.int32(feat)))
            np.testing.assert_array_equal(got, self.dense[:, feat])

    def test_column_per_row_matches_dense(self):
        rng = np.random.default_rng(3)
        feat_r = rng.integers(0, self.f, size=self.n).astype(np.int32)
        got = np.asarray(spd.column_per_row(self.sp, jnp.asarray(feat_r)))
        np.testing.assert_array_equal(
            got, self.dense[np.arange(self.n), feat_r])

    def test_histogram_matches_dense(self):
        rng = np.random.default_rng(11)
        vals = jnp.asarray(rng.normal(size=(self.n, 3)).astype(np.float32))
        want = compute_histogram(jnp.asarray(self.dense), vals,
                                 num_bins=self.b)
        got = spd.histogram(self.sp, vals, num_bins=self.b)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-4)

    def test_histogram_sloted_matches_dense(self):
        rng = np.random.default_rng(13)
        vals = jnp.asarray(rng.normal(size=(self.n, 3)).astype(np.float32))
        slot = jnp.asarray(rng.integers(-1, 4, size=self.n).astype(np.int32))
        want = compute_histogram(jnp.asarray(self.dense), vals,
                                 num_bins=self.b, slot=slot, num_slots=4)
        got = spd.histogram(self.sp, vals, num_bins=self.b, slot=slot,
                            num_slots=4)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-4)

    def test_histogram_masked_rows(self):
        """vals zeroed outside a 'leaf' — the masked-grower discipline."""
        rng = np.random.default_rng(17)
        vals = rng.normal(size=(self.n, 3)).astype(np.float32)
        mask = rng.integers(0, 2, size=self.n).astype(np.float32)
        vals = jnp.asarray(vals * mask[:, None])
        want = compute_histogram(jnp.asarray(self.dense), vals,
                                 num_bins=self.b)
        got = spd.histogram(self.sp, vals, num_bins=self.b)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-4)

    def test_densify_roundtrip(self):
        host = _to_sparse_binned(self.dense, self.default_bin, self.b)
        np.testing.assert_array_equal(host.densify(), self.dense)


class TestDatasetSelection:
    def test_sparse_chosen_for_wide_sparse_input(self):
        rng = np.random.default_rng(5)
        # a shape where k-hot decisively beats dense/EFB: enough rows
        # that every column pair overlaps somewhere (~5 shared rows
        # expected), so exclusive bundling is impossible and the
        # dense alternative stays [N, ~F] wide while k stays ~nnz/row
        # (the old 400x600x40 shape sat on the size crossover and
        # flipped when the bundling search improved)
        x = _rand_sparse(rng, 2000, 600, 30)
        y = rng.normal(size=2000)
        ds = Dataset(x, label=y).construct(Config({"min_data_in_leaf": 5}))
        assert ds.binned_sparse is not None
        assert ds.binned is None
        assert ds.binned_sparse.flat.shape[0] == 2000
        # the layout really is smaller than the dense alternative
        assert ds.binned_sparse.nbytes() < 2000 * ds.num_features

    def test_dense_kept_for_narrow_input(self):
        rng = np.random.default_rng(6)
        x = sps.csr_matrix(rng.normal(size=(300, 8)))
        y = rng.normal(size=300)
        ds = Dataset(x, label=y).construct(Config({}))
        assert ds.binned_sparse is None
        assert ds.binned is not None

    def test_enable_sparse_false_respected(self):
        rng = np.random.default_rng(7)
        x = _rand_sparse(rng, 400, 600, 40)
        ds = Dataset(x, label=rng.normal(size=400)) \
            .construct(Config({"enable_sparse": False}))
        assert ds.binned_sparse is None

    def test_subset_and_binary_roundtrip(self, tmp_path):
        rng = np.random.default_rng(8)
        x = _rand_sparse(rng, 2000, 600, 30)  # see size-crossover note above
        y = rng.normal(size=2000)
        ds = Dataset(x, label=y).construct(Config({}))
        assert ds.binned_sparse is not None
        sub = ds.subset(np.arange(100, 200))
        np.testing.assert_array_equal(sub.binned_sparse.flat,
                                      ds.binned_sparse.flat[100:200])
        p = str(tmp_path / "sparse.bin")
        ds.save_binary(p)
        ds2 = Dataset.load_binary(p)
        assert ds2.binned_sparse is not None
        np.testing.assert_array_equal(ds2.binned_sparse.flat,
                                      ds.binned_sparse.flat)
        np.testing.assert_array_equal(ds2.binned_sparse.default_bin,
                                      ds.binned_sparse.default_bin)


class TestTrainingEquality:
    """Sparse-vs-dense storage must be a pure layout change: same bins in,
    same trees out (up to float-accumulation-order noise in histograms)."""

    def _make(self, n=800, f=300, nnz=30, seed=21):
        rng = np.random.default_rng(seed)
        x = _rand_sparse(rng, n, f, nnz)
        xd = np.asarray(x.todense())
        w = rng.normal(size=f) * (rng.random(f) < 0.2)
        y = xd @ w + rng.normal(size=n) * 0.1
        return x, xd, y

    @pytest.mark.parametrize("extra", [{}, {"split_batch": 4},
                                       {"bagging_fraction": 0.7,
                                        "bagging_freq": 1}])
    def test_sparse_equals_dense(self, extra):
        x, xd, y = self._make()
        params = {"objective": "regression", "num_leaves": 15,
                  "learning_rate": 0.2, "min_data_in_leaf": 5,
                  "verbose": -1, "enable_bundle": False,
                  "tpu_learner": "masked", **extra}
        ds_sp = Dataset(x, label=y)
        bst_sp = train(params, ds_sp, num_boost_round=8)
        assert ds_sp.binned_sparse is not None, \
            "test premise: the sparse layout must have been selected"
        ds_de = Dataset(xd, label=y)
        bst_de = train(params, ds_de, num_boost_round=8)
        assert ds_de.binned_sparse is None
        pred_sp = bst_sp.predict(xd)
        pred_de = bst_de.predict(xd)
        np.testing.assert_allclose(pred_sp, pred_de, rtol=2e-4, atol=2e-4)

    def test_sparse_with_valid_set_early_stopping(self):
        x, xd, y = self._make(seed=23)
        params = {"objective": "regression", "num_leaves": 15,
                  "metric": "l2", "verbose": -1, "min_data_in_leaf": 5,
                  "tpu_learner": "masked"}
        dtr = Dataset(x[:600], label=y[:600])
        dva = Dataset(x[600:], label=y[600:], reference=dtr)
        res = {}
        from lightgbm_tpu.callback import record_evaluation
        bst = train(params, dtr, num_boost_round=10, valid_sets=[dva],
                    callbacks=[record_evaluation(res)])
        assert len(res["valid_0"]["l2"]) == 10
        # the recorded valid metric must match recomputing from scratch
        pred = bst.predict(np.asarray(x[600:].todense()))
        l2 = float(np.mean((pred - y[600:]) ** 2))
        assert abs(l2 - res["valid_0"]["l2"][-1]) < 1e-4


class TestAllstateBudget:
    """The Allstate-shaped width claim (docs/Width-Limits.md): a dataset of
    the reference benchmark's SHAPE (scaled rows, full 4228-col width)
    constructs into the sparse layout under a computed budget and trains a
    tree.  The full 13.2M-row budget is arithmetic over the same per-row
    cost, asserted here."""

    def test_allstate_shaped_construct_and_train(self):
        rng = np.random.default_rng(31)
        n, f, nnz = 20_000, 4228, 35   # dummy-encoded categorical shape
        x = _rand_sparse(rng, n, f, nnz, nbins=3)
        y = (rng.random(n) < 0.3).astype(np.float64)
        ds = Dataset(x, label=y)
        bst = train({"objective": "binary", "num_leaves": 31,
                     "verbose": -1, "tpu_learner": "masked"},
                    ds, num_boost_round=2)
        assert ds.binned_sparse is not None
        k = ds.binned_sparse.k
        bytes_row = k * 4
        # scaled to the reference Allstate rows (docs/Experiments.rst:32):
        # the binned matrix must fit a single v5e's 16 GB with room for
        # scores + histograms (Width-Limits.md budget terms)
        full_bytes = 13_200_000 * bytes_row
        assert full_bytes < 8 * 2**30, \
            f"k-hot layout {full_bytes/2**30:.1f} GB at 13.2M rows"
        # and it beat dense [N, F] by a wide margin
        assert bytes_row * 8 < f
        assert bst.predict(np.asarray(x[:50].todense())).shape == (50,)


class TestSparseDataParallel:
    def test_sparse_under_data_parallel_matches_serial(self):
        """Sparse storage rides the mesh data-parallel learner (the path
        docs/Width-Limits.md prescribes for over-budget width): 4-way
        row-sharded training must equal serial sparse training."""
        rng = np.random.default_rng(41)
        x = _rand_sparse(rng, 1024, 300, 30)
        xd = np.asarray(x.todense())
        y = rng.normal(size=1024) + xd[:, :3].sum(axis=1)
        params = {"objective": "regression", "num_leaves": 15,
                  "min_data_in_leaf": 5, "verbose": -1,
                  "tpu_learner": "masked"}
        ds1 = Dataset(x, label=y)
        b1 = train(params, ds1, num_boost_round=4)
        assert ds1.binned_sparse is not None
        ds2 = Dataset(x, label=y)
        b2 = train(dict(params, tree_learner="data", num_machines=4),
                   ds2, num_boost_round=4)
        assert ds2.binned_sparse is not None
        np.testing.assert_allclose(b1.predict(xd), b2.predict(xd),
                                   rtol=2e-4, atol=2e-4)
