"""Ranking objectives/metrics + SHAP contribution tests
(test_engine.py ranking & contrib sections analog, SURVEY.md §4)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _ranking_data(n_q=50, q_size=16, seed=0):
    rs = np.random.RandomState(seed)
    n = n_q * q_size
    x = rs.randn(n, 6)
    rel = 1.5 * x[:, 0] + x[:, 1] + 0.3 * rs.randn(n)
    y = np.zeros(n, np.int32)
    for q in range(n_q):
        s = slice(q * q_size, (q + 1) * q_size)
        ranks = np.argsort(np.argsort(-rel[s]))
        y[s] = np.clip(3 - ranks // 4, 0, 3)
    return x, y, [q_size] * n_q


class TestRanking:
    @pytest.mark.parametrize("obj", ["lambdarank", "rank_xendcg"])
    def test_ndcg_improves(self, obj):
        x, y, group = _ranking_data()
        p = {"objective": obj, "num_leaves": 15, "max_bin": 63,
             "min_data_in_leaf": 5, "metric": ["ndcg"], "eval_at": [5]}
        ds = lgb.Dataset(x, label=y, group=group)
        vx, vy, vg = _ranking_data(seed=1)
        vds = lgb.Dataset(vx, label=vy, group=vg, reference=ds)
        rec = {}
        bst = lgb.train(p, ds, num_boost_round=30, valid_sets=[vds],
                        callbacks=[lgb.record_evaluation(rec)])
        ndcg = rec["valid_0"]["ndcg@5"]
        assert ndcg[-1] > ndcg[0]
        assert ndcg[-1] > 0.80, f"ndcg@5 {ndcg[-1]}"

    def test_ndcg_metric_perfect_and_random(self):
        from lightgbm_tpu.metrics import NDCGMetric
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.dataset import Metadata
        cfg = Config({"objective": "lambdarank", "eval_at": [3]})
        md = Metadata(8)
        md.set_label(np.array([3, 2, 1, 0, 3, 2, 1, 0], np.float32))
        md.set_group([4, 4])
        m = NDCGMetric(cfg)
        m.init(md, 8)
        perfect = m.eval(np.array([4., 3, 2, 1, 4, 3, 2, 1]))
        assert perfect[0][1] == pytest.approx(1.0)
        worst = m.eval(np.array([1., 2, 3, 4, 1, 2, 3, 4]))
        assert worst[0][1] < 1.0


class TestSHAP:
    def test_contrib_sums_to_prediction(self, binary_data):
        x, y = binary_data
        p = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
             "min_data_in_leaf": 20}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=5)
        xs = x[:20]
        contrib = bst.predict(xs, pred_contrib=True)
        assert contrib.shape == (20, x.shape[1] + 1)
        raw = bst.predict(xs, raw_score=True)
        np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-4,
                                   atol=1e-4)

    def test_contrib_regression(self, regression_data):
        x, y = regression_data
        p = {"objective": "regression", "num_leaves": 7, "max_bin": 31}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=3)
        xs = x[:10]
        contrib = bst.predict(xs, pred_contrib=True)
        raw = bst.predict(xs, raw_score=True)
        np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-4,
                                   atol=1e-4)
        # uninformative feature contributes ~nothing
        # (feature with no splits has zero attribution)
        imp = bst.feature_importance("split")
        for f in range(x.shape[1]):
            if imp[f] == 0:
                np.testing.assert_allclose(contrib[:, f], 0.0, atol=1e-9)


def test_skewed_query_sizes_bucketed():
    """Yahoo-LTR-shaped skew (many tiny queries + a few huge ones) must
    not pad everything to the global max: _pad_queries buckets by size so
    the pairwise tensors track actual work (VERDICT r2 weak #8)."""
    rs = np.random.RandomState(11)
    sizes = [8] * 200 + [30] * 40 + [500] * 2   # maxq=500, most <= 8
    n = sum(sizes)
    x = rs.randn(n, 8)
    rel = np.clip((x[:, 0] + 0.5 * rs.randn(n)) * 1.2 + 1.5, 0, 4)
    y = rel.astype(np.float32).round()
    group = np.asarray(sizes)

    from lightgbm_tpu.objectives import _pad_queries
    b = np.concatenate([[0], np.cumsum(group)])
    buckets = _pad_queries(b)
    caps = [mb for _, _, _, mb in buckets]
    # small queries must NOT be padded to 500
    assert min(caps) <= 16 and max(caps) == 500
    assert sum(q.shape[0] for q, _, _, _ in buckets) == len(sizes)
    # padded area is a small multiple of the real rows, not Q*maxq
    padded = sum(q.shape[0] * mb for q, _, _, mb in buckets)
    assert padded < 3 * n < len(sizes) * 500

    ds = lgb.Dataset(x, label=y, group=group)
    bst = lgb.train({"objective": "lambdarank", "num_leaves": 15,
                     "min_data_in_leaf": 5, "verbose": -1,
                     "eval_at": [5], "metric": "ndcg"},
                    ds, num_boost_round=20)
    res = bst.eval_train()
    ndcg = [v for _, name, v, _ in res if "ndcg" in name][0]
    assert ndcg > 0.75, ndcg


def test_xendcg_skewed_buckets():
    rs = np.random.RandomState(12)
    sizes = [6] * 100 + [120] * 3
    n = sum(sizes)
    x = rs.randn(n, 6)
    y = np.clip(x[:, 0] + 0.3 * rs.randn(n) + 1.0, 0, 3).round().astype(np.float32)
    ds = lgb.Dataset(x, label=y, group=np.asarray(sizes))
    bst = lgb.train({"objective": "rank_xendcg", "num_leaves": 15,
                     "min_data_in_leaf": 5, "verbose": -1,
                     "eval_at": [5], "metric": "ndcg"},
                    ds, num_boost_round=20)
    res = bst.eval_train()
    ndcg = [v for _, name, v, _ in res if "ndcg" in name][0]
    assert ndcg > 0.7, ndcg
