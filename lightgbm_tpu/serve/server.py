"""Serving frontends: in-process ``Server`` API + stdlib HTTP endpoint.

``Server`` wires the subsystem together: a :class:`~.registry.ModelRegistry`
(initial model from a ``Booster``, a model file/string, or the newest
complete training snapshot), a :class:`~.batcher.MicroBatcher` sized by
the ``serve_*`` config params, and the PR-3 obs subsystem — ``serve.*``
metrics always collect (they are host-side counters, no device syncs);
spans/JSONL/profiler ride the usual ``telemetry`` switch.

Predictions go through ``Booster.predict`` of the batch's resolved
model version — which itself routes through the bucketed
:class:`~.engine.PredictorEngine` — so serve results are byte-identical
to a direct ``Booster.predict`` call on the same rows, micro-batch
coalescing included (elementwise routing + per-row accumulation make
batch composition invisible; tests/test_serve.py proves it across the
objective/feature matrix).

``start_http`` exposes the same Server over a stdlib-only
``ThreadingHTTPServer``:

- ``POST /predict``  ``{"rows": [[...], ...]}`` ->
  ``{"predictions": ..., "model_version": ..., "num_rows": ...}``;
  429 + ``Retry-After`` on backpressure, 400 on malformed input.
- ``POST /reload``   ``{"model_file": ...}`` (or ``{"snapshot": out}``)
  -> hot swap, in-flight requests finish on the old version.
- ``GET /healthz``   liveness + current model version + queue depth.
- ``GET /metrics``   deterministic JSON metrics snapshot
  (``serve.latency`` quantiles included) + engine compile stats.

CLI: ``python -m lightgbm_tpu serve input_model=model.txt`` (or
``task=serve`` in a config file) — see cli.py.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..config import Config
from ..utils.log import Log
from ..utils.resilience import RetryPolicy
from .batcher import BacklogFull, MicroBatcher
from .registry import ModelRegistry, NoModelError


class Server:
    """Long-lived in-process prediction service."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 booster=None, model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        self.config = params if isinstance(params, Config) \
            else Config(params or {})
        cfg = self.config
        from ..obs import MetricsRegistry, maybe_session
        self.obs = maybe_session(cfg)
        self.metrics = self.obs.metrics if self.obs is not None \
            else MetricsRegistry()
        self.tracer = self.obs.tracer if self.obs is not None else None
        self.registry = ModelRegistry(
            max_batch=cfg.serve_max_batch,
            min_bucket=cfg.serve_min_bucket)
        model_file = model_file or (cfg.input_model or None)
        if booster is not None or model_file or model_str:
            self.registry.load(model_file=model_file,
                               model_str=model_str, booster=booster)
        elif cfg.resume and cfg.output_model:
            # serve the newest complete snapshot of a (possibly still
            # running) training job
            self.registry.load_snapshot(cfg.output_model)
        self.batcher = MicroBatcher(
            self._predict_batch,
            max_batch=cfg.serve_max_batch,
            max_wait_ms=cfg.serve_max_wait_ms,
            queue_rows=cfg.serve_queue_rows,
            # serve-scaled backoff: the bring-up defaults (1 s base)
            # would stall the single worker for seconds on a path whose
            # latency budget is serve_max_wait_ms
            retry_policy=RetryPolicy(
                max_attempts=max(1, cfg.serve_retries + 1),
                base_delay_s=0.02, max_delay_s=0.25),
            metrics=self.metrics, tracer=self.tracer)
        self._t0 = time.time()
        self._closed = False

    # -- batch execution (worker thread) -----------------------------------
    def _predict_batch(self, rows: np.ndarray) -> Tuple[np.ndarray, dict]:
        served = self.registry.current()   # resolved per batch: requests
        # already in this batch finish on it even if a reload lands now
        if self.config.serve_device_binning and served.engine is not None:
            out = served.engine.predict(rows, device_binning=True)
        else:
            out = served.booster.predict(rows)
        return np.asarray(out), {"model_version": served.version}

    # -- client surface ----------------------------------------------------
    def predict(self, rows, timeout: Optional[float] = None) -> np.ndarray:
        """Predict through the micro-batching queue; blocks for the
        result.  Raises :class:`~.batcher.BacklogFull` under
        backpressure."""
        return self.submit(rows).result(timeout)

    def submit(self, rows):
        """Enqueue and return the :class:`PredictionFuture` (the
        non-blocking form of :meth:`predict`)."""
        span = (self.tracer.span("serve.request", rows=len(rows))
                if self.tracer is not None else None)
        fut = self.batcher.submit(np.asarray(rows, np.float64))
        if span is not None:
            span.end()
        return fut

    def reload(self, model_file: Optional[str] = None,
               model_str: Optional[str] = None, booster=None,
               snapshot: Optional[str] = None) -> str:
        """Load a new model version and atomically swap it in; returns
        the new version id."""
        if snapshot is not None:
            version = self.registry.load_snapshot(snapshot)
        else:
            version = self.registry.load(model_file=model_file,
                                         model_str=model_str,
                                         booster=booster)
        Log.info(f"serve: activated model {version}")
        return version

    def health(self) -> dict:
        try:
            model = self.registry.current().describe()
            status = "ok"
        except NoModelError:
            model, status = None, "no_model"
        return {"status": status, "model": model,
                "queue_depth_rows": self.batcher.depth_rows,
                "uptime_s": round(time.time() - self._t0, 3),
                "versions": self.registry.versions()}

    def metrics_snapshot(self) -> dict:
        snap = dict(self.metrics.snapshot())
        lat = snap.get("serve.latency")
        if lat and lat.get("count"):
            from ..obs.metrics import Histogram
            h = Histogram(tuple(lat["buckets"]))
            h.counts, h.count = list(lat["counts"]), lat["count"]
            h.sum, h.min, h.max = lat["sum"], lat["min"], lat["max"]
            snap["serve.latency_quantiles"] = {
                "p50_s": h.quantile(0.5), "p99_s": h.quantile(0.99)}
        try:
            engine = self.registry.current().engine
            if engine is not None:
                snap["serve.engine"] = engine.compile_stats()
        except NoModelError:
            pass
        return snap

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.batcher.close()
        if self.obs is not None:
            self.obs.finish()


# ---------------------------------------------------------------------------
# HTTP frontend (stdlib only)
# ---------------------------------------------------------------------------

class HttpFrontend:
    """Handle for a running HTTP frontend (``.port``, ``.close()``)."""

    def __init__(self, httpd, thread: Optional[threading.Thread]):
        self._httpd = httpd
        self._thread = thread
        self.host, self.port = httpd.server_address[:2]

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def start_http(server: Server, host: str = "127.0.0.1", port: int = 0,
               background: bool = True) -> HttpFrontend:
    """Expose ``server`` over HTTP; ``port=0`` picks a free port (read
    it back from the returned handle)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):       # route through Log
            Log.debug("serve-http: " + fmt % args)

        def _send(self, code: int, payload: dict,
                  headers: Optional[dict] = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._send(200, server.health())
            elif self.path == "/metrics":
                self._send(200, server.metrics_snapshot())
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
            except (ValueError, TypeError) as e:
                self._send(400, {"error": f"bad JSON: {e}"})
                return
            if self.path == "/predict":
                self._predict(req)
            elif self.path == "/reload":
                self._reload(req)
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

        def _predict(self, req: dict) -> None:
            rows = req.get("rows")
            if rows is None:
                self._send(400, {"error": "missing 'rows'"})
                return
            try:
                arr = np.asarray(rows, np.float64)
                if arr.ndim == 1:
                    arr = arr.reshape(1, -1)
                if arr.ndim != 2:
                    raise ValueError(f"rows must be 2-D, got "
                                     f"{arr.ndim}-D")
            except (ValueError, TypeError) as e:
                self._send(400, {"error": f"bad rows: {e}"})
                return
            try:
                fut = server.submit(arr)
                pred = fut.result(timeout=req.get("timeout_s", 30.0))
            except BacklogFull as e:
                self._send(429, {"error": str(e),
                                 "retry_after_ms": e.retry_after_ms},
                           headers={"Retry-After": str(max(
                               1, int(e.retry_after_ms / 1000 + 0.5)))})
                return
            except NoModelError as e:
                self._send(503, {"error": str(e)})
                return
            except Exception as e:          # noqa: BLE001 — request-scoped
                from ..basic import LightGBMError
                # a malformed REQUEST (wrong feature count, bad shape)
                # is the client's fault — 400, not 500; per-width batch
                # coalescing guarantees it failed alone
                code = 400 if isinstance(e, (ValueError, LightGBMError)) \
                    else 500
                self._send(code,
                           {"error": f"{type(e).__name__}: {e}"})
                return
            self._send(200, {
                "predictions": np.asarray(pred).tolist(),
                "num_rows": int(len(arr)),
                "model_version": fut.info.get("model_version")})

        def _reload(self, req: dict) -> None:
            try:
                version = server.reload(
                    model_file=req.get("model_file"),
                    model_str=req.get("model_str"),
                    snapshot=req.get("snapshot"))
            except Exception as e:          # noqa: BLE001 — operator call
                self._send(400,
                           {"error": f"{type(e).__name__}: {e}"})
                return
            self._send(200, {"model_version": version})

    httpd = ThreadingHTTPServer((host, port), Handler)
    httpd.daemon_threads = True
    thread = None
    if background:
        thread = threading.Thread(target=httpd.serve_forever,
                                  name="lgbtpu-serve-http", daemon=True)
        thread.start()
    Log.info(f"serve: HTTP frontend on "
             f"http://{httpd.server_address[0]}:"
             f"{httpd.server_address[1]}")
    return HttpFrontend(httpd, thread)
