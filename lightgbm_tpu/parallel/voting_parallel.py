"""Voting-parallel learner: communication-compressed data parallelism.

TPU-native redesign of the reference VotingParallelTreeLearner (PV-tree,
/root/reference/src/treelearner/voting_parallel_tree_learner.cpp:15-507):
rows are sharded like data-parallel, but instead of reducing histograms for
ALL features, each shard votes its local top-k features (by local split
gain), the global vote selects the top-2k (``GlobalVoting``,
voting_parallel_tree_learner.cpp:150-181), and only those features'
histograms cross the interconnect.

Implementation: the psum hook zeroes non-voted features before reducing —
a zero histogram can never produce a valid split (count constraints), so
no separate search mask is needed.  Because the voted feature set changes
per split, the subtraction trick is disabled (both children constructed),
matching the reference's CopyLocalHistogram behavior of syncing both.

Quantized training (``quant``): the vote statistic needs real-valued
gains, so the hook dequantizes its LOCAL int32 histogram with the
iteration's shared scales (grower.py passes them to the reduce hook) —
the reduced tensor itself stays exact int32 (an integer psum, bitwise
order-independent).

Leaf-budget trace sharing (ROADMAP item 1 remainder): ``padded_leaves``
threads through to the shared grower, the actual budget rides per call
as the traced ``max_leaves`` scalar, and the jitted shard_map program is
memoized process-wide — a ``num_leaves`` sweep inside one bucket runs
ONE voting-grower trace (pinned by tools/check_retraces.py).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..grower import TreeArrays, make_grower
from ..obs.comm import CommLedger
from ..ops.split import SplitParams, dequantize_hist
from ..utils.jax_compat import shard_map
from ..utils.memo import memo_get_or_build

# process-level memo of jitted voting growers (same role as grower.py's
# _SHARED_GROWERS): keyed on devices + every trace-relevant static, so
# a leaf sweep inside one padded bucket shares ONE shard_map trace.
_SHARED: "OrderedDict[tuple, tuple]" = OrderedDict()
_SHARED_MAX = 16
_SHARED_LOCK = threading.Lock()


def _local_feature_gains(h: jax.Array, params: SplitParams,
                         n_shards: int) -> jax.Array:
    """Per-feature best LOCAL split gain from a local histogram [F, B, 3]
    — the vote statistic.  Matches the reference's local search setup:
    L1/L2-regularized gains with the per-rank constraint rescale
    ``min_data_in_leaf /= num_machines`` / ``min_sum_hessian_in_leaf /=
    num_machines`` (voting_parallel_tree_learner.cpp:61-63 — a shard
    only sees ~1/M of any leaf's rows, so unscaled constraints would
    veto splits the GLOBAL histogram easily clears)."""
    md = max(float(params.min_data_in_leaf) / n_shards, 1.0) - 0.5
    mh = float(params.min_sum_hessian_in_leaf) / n_shards
    l1, l2 = float(params.lambda_l1), float(params.lambda_l2)
    eps = 1e-10
    cum = jnp.cumsum(h, axis=1)
    total = cum[:, -1:, :]
    gl, hl = cum[..., 0], cum[..., 1]
    gr = total[..., 0] - cum[..., 0]
    hr = total[..., 1] - cum[..., 1]
    cl, cr = cum[..., 2], total[..., 2] - cum[..., 2]

    def tl1(g):
        if l1 <= 0.0:
            return g
        return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)

    gains = (tl1(gl) ** 2 / (hl + l2 + eps)
             + tl1(gr) ** 2 / (hr + l2 + eps))
    valid = (cl >= md) & (cr >= md) & (hl >= mh) & (hr >= mh)
    gains = jnp.where(valid, gains, -jnp.inf)
    return jnp.max(gains, axis=1)                       # [F]


def make_voting_grower(mesh: Mesh, *, num_leaves: int, num_bins: int,
                       params: SplitParams, top_k: int = 20,
                       max_depth: int = -1, block_rows: int = 0,
                       axis: str = "data", padded_leaves=None,
                       quant=None):
    """Jitted voting-parallel ``grow_tree`` over ``mesh`` (rows sharded)."""

    key = (tuple(int(d.id) for d in np.ravel(mesh.devices)), axis,
           int(padded_leaves) if padded_leaves else None,
           None if padded_leaves else int(num_leaves),
           int(num_bins), params, int(top_k), int(max_depth),
           int(block_rows), quant)
    jitted, ledger = memo_get_or_build(
        _SHARED, _SHARED_LOCK, _SHARED_MAX, key,
        lambda: _build(mesh, num_leaves=num_leaves, num_bins=num_bins,
                       params=params, top_k=top_k, max_depth=max_depth,
                       block_rows=block_rows, axis=axis,
                       padded_leaves=padded_leaves, quant=quant))

    def grow(binned, vals, feature_mask, num_bin, na_bin, is_cat=None,
             max_leaves=None, rng_iter=None):
        if is_cat is None:
            is_cat = jnp.zeros(num_bin.shape[0], bool)
        ml = jnp.int32(num_leaves if max_leaves is None else max_leaves)
        ri = jnp.int32(0 if rng_iter is None else rng_iter)
        return jitted(binned, vals, feature_mask, num_bin, na_bin, na_bin,
                      is_cat, ml, ri)

    grow.comm = ledger
    return grow


def _build(mesh: Mesh, *, num_leaves, num_bins, params, top_k, max_depth,
           block_rows, axis, padded_leaves, quant):
    n_shards = mesh.shape[axis]
    ledger = CommLedger(n_shards)     # static comm-bytes sites (obs/comm)

    def vote_reduce(h, scales=None):
        f = h.shape[0]
        k = min(top_k, f)
        # quantized training: the vote statistic needs real values;
        # the LOCAL dequantization is scan-shaped work, the reduced
        # tensor stays exact int32
        h_stat = h if scales is None else dequantize_hist(h, scales)
        gains = _local_feature_gains(h_stat, params, n_shards)
        _, local_top = lax.top_k(gains, k)              # [k]
        onehot = jnp.zeros(f, jnp.float32).at[local_top].add(1.0)
        votes = ledger.psum(onehot, axis,
                            site="voting.votes")        # [F] vote counts
        # global top-2k by votes (ties: summed local gains)
        gain_sum = ledger.psum(jnp.where(jnp.isfinite(gains), gains, 0.0),
                               axis, site="voting.gains")
        score = votes * 1e12 + gain_sum
        k2 = min(2 * k, f)
        _, selected = lax.top_k(score, k2)
        sel_mask = jnp.zeros(f, bool).at[selected].set(True)
        # the ledger records the full zero-masked [F, B, 3] payload —
        # the tensor XLA actually reduces; the reference's
        # CopyLocalHistogram would ship only the voted k2/F slice.
        # jnp.where (not *) keeps the int32 dtype under quant
        return ledger.psum(jnp.where(sel_mask[:, None, None], h,
                                     jnp.zeros((), h.dtype)), axis,
                           site="voting.hist")

    from .data_parallel import _quant_hooks
    inner = make_grower(
        num_leaves=num_leaves, num_bins=num_bins, params=params,
        max_depth=max_depth, block_rows=block_rows,
        hist_reduce=vote_reduce, subtract=False,
        # root totals must NOT come through the vote-filtered histogram
        sum_reduce=lambda t: ledger.psum(t, axis, site="voting.root_sum",
                                         cadence="tree"),
        padded_leaves=padded_leaves,
        **_quant_hooks(axis, ledger, quant, site="voting.quant_scale"),
        jit=False)

    out_specs = TreeArrays(
        num_leaves=P(), split_feature=P(), threshold_bin=P(),
        default_left=P(), left_child=P(), right_child=P(), split_gain=P(),
        leaf_value=P(), leaf_weight=P(), leaf_count=P(), internal_value=P(),
        internal_weight=P(), internal_count=P(), leaf_depth=P(),
        leaf_of_row=P(axis), is_cat_node=P(), cat_rank=P(), n_steps=P())

    def wrapped(binned, vals, fm, nb, na, nabp, ic, ml, ri):
        return inner(binned, vals, fm, nb, na, nabp, ic, rng_iter=ri,
                     max_leaves=ml)

    f = shard_map(
        wrapped, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(), P(), P(), P(), P(),
                  P(), P()),
        out_specs=out_specs, check_vma=False)

    return jax.jit(f), ledger
