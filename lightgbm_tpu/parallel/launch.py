"""Multi-host training orchestration — the Dask-layer analog.

The reference ships a process-orchestration layer
(/root/reference/python-package/lightgbm/dask.py:393-810: allocate ports,
build the ``machines`` parameter, run one trainer per worker wired through
``LGBM_NetworkInit``; docs/Parallel-Learning-Guide.rst:45-140 for
MPI/Kubeflow).  On TPU pods the runtime already provides process bring-up,
so the analog collapses to: initialize ``jax.distributed`` (one process per
host, auto-detected on TPU), build the global mesh, and run the SAME
training call on every process with per-process data shards — SPMD instead
of a task scheduler.

Typical pod usage (same script on every host)::

    import lightgbm_tpu as lgb
    from lightgbm_tpu.parallel import launch

    launch.init()                      # no-op off-pod / single process
    shard = launch.row_shard(load_my_rows())   # this host's rows
    mappers = launch.global_bin_mappers(shard.sample(200_000), config)
    ds = lgb.Dataset(shard.x, label=shard.y, bin_mappers=mappers)
    bst = lgb.train({"tree_learner": "data", ...}, ds)
"""

from __future__ import annotations

import time
from typing import Callable, List, NamedTuple, Optional

import numpy as np

from ..config import Config


class RowShard(NamedTuple):
    """This process's row partition.  ``weight`` and the global row
    range ``[row_start, row_stop)`` are populated by :func:`row_shard`
    (``row_stop == 0`` on direct per-host wraps where the global
    placement is unknown) — keeping row/label/weight partitioning in
    ONE authority so they cannot drift."""
    x: np.ndarray
    y: Optional[np.ndarray]
    process_index: int
    process_count: int
    weight: Optional[np.ndarray] = None
    row_start: int = 0
    row_stop: int = 0

    def sample(self, cnt: int, seed: int = 3) -> np.ndarray:
        from ..dataset import _sample_rows
        rng = np.random.RandomState(seed + self.process_index)
        n = len(self.x)
        if cnt >= n:
            return self.x
        return self.x[_sample_rows(rng, n, cnt)]


def init(coordinator_address: Optional[str] = None,
         num_processes: Optional[int] = None,
         process_id: Optional[int] = None,
         machines: Optional[str] = None,
         local_listen_port: int = 12400,
         retries: int = 2,
         timeout_s: float = 300.0) -> None:
    """Bring up jax.distributed (LGBM_NetworkInit / dask._train machinery
    analog).  ``machines`` accepts the reference's "ip1:port1,ip2:port2"
    parameter format (config.h machines / dask.py:700) — the first entry
    becomes the coordinator; rank is inferred by matching the local host.
    On TPU pods, call with no arguments: everything is auto-detected.

    The initialize attempt runs under the resilience layer
    (utils/resilience.py — the reference's socket linker retries its
    connect loop the same way, network/linkers_socket.cpp):
    ``retries`` jittered-backoff re-attempts for classified-transient
    failures (UNAVAILABLE, timeouts, refused connections), a hard
    ``timeout_s`` deadline, and a faulthandler watchdog so a wedged
    bring-up dumps stacks instead of hanging silently.  Fatal errors
    (bad arguments) surface immediately.

    MUST run before any other JAX call (jax.distributed.initialize refuses
    to run once XLA backends exist) — so no jax.* probing happens here
    before the initialize attempt."""
    import jax

    from ..utils import faultinject
    from ..utils.resilience import RetryPolicy, Watchdog, retry_call

    if getattr(init, "_done", False):
        return
    if machines:
        entries = [m.strip() for m in machines.split(",") if m.strip()]
        if coordinator_address is None:
            coordinator_address = entries[0]
        if num_processes is None:
            num_processes = len(entries)
        if process_id is None:
            import socket
            names = {socket.gethostname(), "127.0.0.1", "localhost"}
            try:
                names.add(socket.gethostbyname(socket.gethostname()))
            except OSError:
                pass
            process_id = next(
                (i for i, e in enumerate(entries)
                 if e.rsplit(":", 1)[0] in names), None)
            if process_id is None:
                raise ValueError(
                    f"local host not found in machines={machines!r}")
    fail_t = getattr(init, "_fail_t", None)
    if coordinator_address is None and fail_t is not None \
            and timeout_s > 0 \
            and time.monotonic() - fail_t < timeout_s:
        # a recent AUTO bring-up failure: proceed solo without burning
        # another full retry/watchdog budget per train() call.  The
        # pre-elastic code latched _done here PERMANENTLY; a cooldown
        # (one deadline's worth) keeps the failure retryable for the
        # elastic ladder without re-paying the deadline every call
        return

    def _bring_up():
        faultinject.check("device_claim")
        if coordinator_address is not None:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
        else:
            jax.distributed.initialize()

    policy = RetryPolicy.for_bringup(retries, timeout_s)
    try:
        with Watchdog(timeout_s, label="jax.distributed bring-up"):
            retry_call(_bring_up, policy=policy,
                       label="jax.distributed bring-up")
        # latched ONLY on successful bring-up: a failed or timed-out
        # initialize must stay retryable — the elastic recovery ladder
        # (parallel/elastic.py) re-attempts bring-up after a claim
        # wedge, and a latched failure would permanently short-circuit
        # every later attempt into the degraded path
        init._done = True
        init._fail_t = None
    except (RuntimeError, ValueError) as e:
        if coordinator_address is not None:
            # an explicitly-requested multi-host launch failing must be
            # loud: silently degrading to single-process would later hang
            # in collectives or fit divergent bin mappers per host
            raise RuntimeError(
                f"jax.distributed.initialize failed for explicit "
                f"coordinator {coordinator_address!r}: {e}") from e
        # auto-detect path on single-process / already-initialized
        # runtimes: proceed solo, the same way the reference CLI falls
        # back to serial when num_machines=1 — but say so (and do NOT
        # latch _done: the next caller may retry the bring-up once the
        # cooldown above lapses)
        init._fail_t = time.monotonic()
        from ..utils.log import Log
        Log.warning(f"jax.distributed auto-init unavailable ({e}); "
                    "continuing single-process")


def row_shard(x: np.ndarray, y: Optional[np.ndarray] = None,
              process_index: Optional[int] = None,
              process_count: Optional[int] = None,
              weight: Optional[np.ndarray] = None) -> RowShard:
    """Deterministic contiguous row partition of a globally-loaded array
    (the per-rank partitioning of dataset_loader.cpp:203-298).  When data
    is already loaded per-host, wrap it in a RowShard directly."""
    import jax
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    parts = np.array_split(np.arange(len(x)), pc)
    idx = parts[pi]
    return RowShard(x=x[idx], y=None if y is None else y[idx],
                    process_index=pi, process_count=pc,
                    weight=None if weight is None
                    else np.asarray(weight)[idx],
                    row_start=int(idx[0]) if len(idx) else 0,
                    row_stop=int(idx[-1]) + 1 if len(idx) else 0)


def global_bin_mappers(local_sample: np.ndarray, config: Config,
                       cat_idx: Optional[set] = None,
                       allgather: Optional[Callable] = None) -> List:
    """Globally-consistent bin mappers from per-host samples
    (dist_data.distributed_bin_mappers; dataset_loader.cpp:1104-1186)."""
    from .dist_data import distributed_bin_mappers
    return distributed_bin_mappers(local_sample, config, cat_idx=cat_idx,
                                   allgather=allgather)
