"""Compiled predictor engine: SoA ensemble + bucketed compile cache.

The training loop already owns a fast binned traversal
(``predict_device.traverse_tree_binned``), but nothing exposed it to
callers at serving time — ``Booster.predict`` walked host trees row
group by row group.  This module flattens a trained ensemble ONCE into
stacked structure-of-arrays device tensors (the SoA layout
arXiv:2011.02022 and arXiv:1706.08359 identify as where GBDT inference
throughput lives) and runs the whole-forest traversal
(``predict_device.traverse_forest_binned``) under a compile cache keyed
by (model fingerprint, padded batch bucket):

- **Model-derived binning.**  Each feature's bin table is the sorted
  set of split thresholds the ENSEMBLE actually uses (not the training
  ``BinMapper`` — a loaded model file has no mappers).  With
  ``bin(x) = searchsorted(T_f, x, side="left")`` the reference decision
  ``x <= threshold`` is EXACTLY ``bin(x) <= index(threshold)``, so
  traversal over bins reproduces ``tree_model.Tree.predict_leaf``
  bit-for-bit.  Binning runs host-side in float64 — the one stage that
  cannot run in f32 without breaking bit-exact parity (a raw value that
  ties a threshold after f32 rounding may cross it); the opt-in
  ``serve_device_binning`` mode moves it on-device in f32 for
  throughput at the cost of exactness on such ties.
- **Bucketed batches.**  Row counts round up to power-of-two buckets
  (floored at ``min_bucket``, capped at ``max_batch`` when set), so the
  number of distinct traversal shapes — and therefore XLA compiles —
  is bounded by ~log2(max_batch) per model, measured by
  ``predict_device.forest_trace_count`` and surfaced via
  ``compile_stats()`` / ``utils/compile_cache.watch_compiles``.
- **Exact scores.**  The device returns leaf ids; leaf values are
  accumulated HOST-side in float64 in tree order — the same float ops,
  in the same order, as ``Booster.predict``, so engine scores (and the
  serve path built on them) are byte-identical to the reference
  predictor, linear trees and DART/RF tree weights included.
- **Fused device-resident fast path** (``fused_predict``, the
  ``serve_device_binning`` serving mode): binning, the whole-forest
  traversal, the tree-order leaf-value accumulation AND the objective
  output transform run as ONE jitted program
  (``predict_device.fused_forest_predict``) so the only host<->device
  sync per batch is the final ``[rows, out]`` score fetch — the
  ``[rows, trees]`` leaf-id fetch plus host f64 accumulation of the
  exact path collapses to a single small transfer (PROFILE.md measured
  ~67 ms per blocking round trip on a tunneled v5e; the sync count,
  not the traversal math, caps ``serve_rows_per_s``).  The fused
  accumulation is f32 in tree order; its parity contract is
  :meth:`_fused_reference` — a host recomputation of exactly those f32
  ops — enforced byte-for-byte by :meth:`self_check` on probe rows
  where f32 and f64 binning provably agree.  Models the fused program
  cannot represent (linear-leaf outputs need raw-feature host math;
  categories beyond f32's exact integer range) serve via the host
  paths and are counted in ``serve.host_fallback_batches``.
- **Packed tables** (``serve_packed_tables``): the flattened node
  tables pack to the narrowest dtype the model allows — thresholds to
  uint8/uint16 by bin count, children/features/cat-indices by
  node/feature count — shrinking the per-model HBM footprint ~4x
  (gathered values widen to int32 on device, so decisions are
  identical), which is the headroom multi-model co-hosting spends.
  Node/leaf/step axes pad to the shared pow2 policy
  (``utils/shapes.py`` bucket_nodes/bucket_leaf_slots/bucket_steps),
  so co-hosted versions of one model family land on identical SoA
  shapes and share every compiled serve trace.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.shapes import (bucket_bins, bucket_leaf_slots, bucket_nodes,
                            bucket_rows, bucket_steps)

_CAT_BIT = 1
_DEFAULT_LEFT_BIT = 2
_MISSING_SHIFT = 2
_ALWAYS_LEFT = np.int32(1 << 30)   # stump sentinel threshold: rank <= this
_F32_EXACT_INT = float(1 << 24)    # |ints| below this are f32-exact


class EngineUnsupported(ValueError):
    """Model shape the SoA engine cannot represent (callers fall back to
    the host-tree path)."""


class _FeatureTable:
    """Per-feature model-derived bin table."""

    __slots__ = ("kind", "thresholds", "cats", "miss_nan", "na_bin",
                 "num_bins")

    def __init__(self, kind: str):
        self.kind = kind                    # "num" | "cat" | "unused"
        self.thresholds = np.empty(0, np.float64)
        self.cats = np.empty(0, np.int64)
        self.miss_nan = False               # any node routes NaN by flag
        self.na_bin = -1
        self.num_bins = 1


def _feature_tables(trees, num_features: int) -> List[_FeatureTable]:
    tables = [_FeatureTable("unused") for _ in range(num_features)]
    thr_acc: Dict[int, List[np.ndarray]] = {}
    cat_acc: Dict[int, set] = {}
    miss_acc: Dict[int, set] = {}
    for t in trees:
        n = t.num_nodes()
        if n == 0:
            continue
        sf = t.split_feature[:n]
        dt = t.decision_type[:n]
        is_cat = (dt & _CAT_BIT) != 0
        miss = (dt >> _MISSING_SHIFT) & 3
        for f in np.unique(sf[~is_cat]):
            m = (sf == f) & ~is_cat
            thr_acc.setdefault(int(f), []).append(t.threshold[:n][m])
            # miss kind 2 (NaN) routes NaN by the node's default_left
            # flag; kinds 0/1 convert NaN to 0.0 first
            # (tree_model._decide) — record which behaviors appear
            miss_acc.setdefault(int(f), set()).update(
                {2} if (miss[m] == 2).any() else set())
            miss_acc[int(f)].update(
                {0} if (miss[m] != 2).any() else set())
        for i in np.nonzero(is_cat)[0]:
            f = int(sf[i])
            ci = int(t.threshold[i])
            lo, hi = t.cat_boundaries[ci], t.cat_boundaries[ci + 1]
            words = t.cat_threshold[lo:hi]
            cset = cat_acc.setdefault(f, set())
            for wi, w in enumerate(words):
                w = int(w)
                while w:
                    b = w & -w
                    cset.add(32 * wi + b.bit_length() - 1)
                    w ^= b
    for f, chunks in thr_acc.items():
        if f in cat_acc:
            raise EngineUnsupported(
                f"feature {f} has both numerical and categorical splits")
        if len(miss_acc[f]) > 1:
            # a trained model never mixes NaN-routing and NaN-converting
            # nodes on one feature (they come from one BinMapper); a
            # hand-merged model could — refuse rather than mispredict
            raise EngineUnsupported(
                f"feature {f} mixes NaN-routing and NaN-converting "
                "split nodes")
        tab = tables[f]
        tab.kind = "num"
        tab.miss_nan = miss_acc[f] == {2}
        tab.thresholds = np.unique(np.concatenate(chunks))
        # bins 0..len(T) from searchsorted, +1 reserved NaN bin when the
        # feature routes NaN by flag
        tab.na_bin = len(tab.thresholds) + 1 if tab.miss_nan else -1
        tab.num_bins = len(tab.thresholds) + (2 if tab.miss_nan else 1)
    for f, cset in cat_acc.items():
        tab = tables[f]
        tab.kind = "cat"
        tab.cats = np.asarray(sorted(cset), np.int64)
        tab.num_bins = len(tab.cats) + 1        # + unseen/NaN sentinel
    return tables


# one shared jitted traversal for ALL engines: two engines whose SoA
# shapes match (common in tests and A/B model versions) reuse the same
# compile-cache entries — the model arrays travel as call arguments, so
# the cache key is (shapes, steps), never the model content
_shared_traverse = None
_shared_fused = None


def _traverse_jit():
    global _shared_traverse
    if _shared_traverse is None:
        import jax
        from ..predict_device import traverse_forest_binned
        _shared_traverse = jax.jit(traverse_forest_binned,
                                   static_argnames=("steps",))
    return _shared_traverse


def _fused_jit():
    global _shared_fused
    if _shared_fused is None:
        import jax
        from ..predict_device import fused_forest_predict
        _shared_fused = jax.jit(
            fused_forest_predict,
            static_argnames=("steps", "num_class", "transform"))
    return _shared_fused


# objective output transforms, canonicalized so CO-HOSTED model versions
# share fused traces: ``transform`` is a STATIC jit argument (hashed by
# identity), and two boosters of one family carry two distinct-but-equal
# objective instances — keying the transform by (class, output-relevant
# params) hands every equal-config objective the SAME callable, hence
# the same trace.  The cached callable binds the class's unbound
# ``convert_output`` to a minimal shim carrying only the params the
# conversions read (``self.sigmoid``, objectives.py) — never the
# objective instance itself, whose training-side label/weight arrays
# must not be pinned process-wide by a serve-path cache.
_TRANSFORM_CACHE: Dict[tuple, object] = {}
_TRANSFORM_LOCK = threading.Lock()


class _TransformSelf:
    """Stand-in ``self`` for a cached output transform."""

    __slots__ = ("sigmoid",)

    def __init__(self, sigmoid: float):
        self.sigmoid = sigmoid


def _transform_for(objective):
    if objective is None:
        return None
    sigmoid = float(getattr(objective, "sigmoid", 0.0) or 0.0)
    key = (type(objective).__module__, type(objective).__qualname__,
           sigmoid)
    with _TRANSFORM_LOCK:
        fn = _TRANSFORM_CACHE.get(key)
        if fn is None:
            import functools
            fn = functools.partial(type(objective).convert_output,
                                   _TransformSelf(sigmoid))
            _TRANSFORM_CACHE[key] = fn
    return fn


class PredictorEngine:
    """One trained ensemble, flattened for batched device traversal.

    Thread-safe: ``leaf_ids``/``raw_scores``/``predict``/
    ``fused_predict`` may be called concurrently (the jit cache and
    host accumulation are functional; the bucket ledger and the lazy
    device-table uploads are lock-guarded).

    Lock contract (tools/analyze/check_races.py):
        _lock guards: _buckets_seen, _fused_buckets
        _lock guards: _bin_dev, _fused_dev

    All other attributes are frozen at construction.
    """

    def __init__(self, trees, tree_weights, num_class: int,
                 num_features: int, objective=None,
                 average_output: bool = False, *,
                 max_batch: Optional[int] = None, min_bucket: int = 16,
                 fingerprint: Optional[str] = None, packed: bool = True):
        import jax.numpy as jnp

        self.trees = list(trees)
        self.tree_weights = list(tree_weights)
        self.num_class = max(1, int(num_class))
        self.num_features = int(num_features)
        self.objective = objective
        self.average_output = bool(average_output)
        self.max_batch = int(max_batch) if max_batch else None
        self.min_bucket = max(1, int(min_bucket))
        self.packed = bool(packed)
        if self.max_batch is not None:
            self.min_bucket = min(self.min_bucket, self.max_batch)
        if self.num_features < 1:
            raise EngineUnsupported("model has no features")

        self.tables = _feature_tables(self.trees, self.num_features)
        self._build_soa()
        self.fingerprint = fingerprint or self._fingerprint()
        self._lock = threading.Lock()
        self._buckets_seen: Dict[int, int] = {}
        self._fused_buckets: Dict[int, int] = {}

        d = self._dev = {}
        packed_arrays = self._packed_host_arrays()
        for name, arr in packed_arrays.items():
            d[name] = jnp.asarray(arr)
        d["default_left"] = jnp.asarray(self._default_left, jnp.bool_)
        d["is_cat_node"] = jnp.asarray(self._is_cat_node, jnp.bool_)
        d["na_bin"] = jnp.asarray(self._na_bin, jnp.int32)
        self._bin_dev = None               # lazy device-binning tables

        # fused-path availability + parity contract pieces: the f32
        # leaf table and weights the device will gather (and the host
        # reference oracle replays), the RF averaging denominator, the
        # canonicalized objective transform
        self._leaf_f32 = np.zeros(
            (len(self.trees), self._leaf_slots), np.float32)
        if len(self.trees):
            self._leaf_f32[:, :self.leaf_values.shape[1]] = \
                self.leaf_values.astype(np.float32)
        # the ONE f32 weight vector both the device program and its
        # host parity oracle read — a single array so they can never
        # drift apart
        self._w32 = np.asarray(
            [self.tree_weights[t] if t < len(self.tree_weights) else 1.0
             for t in range(len(self.trees))], np.float32)
        t1, k = len(self.trees), self.num_class
        self._avg_denom = float(max(t1 // k, 1)) \
            if (self.average_output and t1 > 0) else 1.0
        self._transform = _transform_for(objective)
        self.fused_reason: Optional[str] = None
        if not self.trees:
            self.fused_reason = "model has no trees"
        elif any(t.is_linear for t in self.trees):
            self.fused_reason = ("linear-leaf outputs need raw-feature "
                                 "host math")
        elif self._device_bin_err:
            self.fused_reason = self._device_bin_err
        self._fused_dev = None             # lazy leaf/weight upload

        # per-model device-resident footprint — the number an operator
        # sizes serve_max_resident from, so EVERYTHING resident counts:
        # packed node tables, leaf values, tree weights, and the fused
        # path's binning tables (f32 [F, padded-B] thresholds +
        # [F, padded-C] categories + two [F] int32 vectors)
        F = self.num_features
        bin_table_bytes = 0
        if self._device_bin_err is None:
            pb, pc = self._bin_table_widths()
            bin_table_bytes = F * pb * 4 + F * pc * 4 + 2 * F * 4
        self.table_bytes = int(
            sum(a.nbytes for a in packed_arrays.values())
            + self._default_left.nbytes + self._is_cat_node.nbytes
            + self._na_bin.nbytes + self._leaf_f32.nbytes
            + 4 * len(self.trees) + bin_table_bytes)

    @property
    def fused_ok(self) -> bool:
        """Whether :meth:`fused_predict` can serve this model."""
        return self.fused_reason is None

    def _traverse(self, binned):
        d = self._dev
        return _traverse_jit()(
            binned, d["split_feature"], d["threshold_bin"],
            d["default_left"], d["left_child"], d["right_child"],
            d["na_bin"], d["is_cat_node"], d["cat_index"],
            d["cat_table"], steps=self._steps)

    # -- construction ------------------------------------------------------
    @staticmethod
    def _uint_dtype(max_val: int):
        """Narrowest unsigned dtype holding [0, max_val]."""
        if max_val <= np.iinfo(np.uint8).max:
            return np.uint8
        if max_val <= np.iinfo(np.uint16).max:
            return np.uint16
        return np.int32

    @staticmethod
    def _int_dtype(min_val: int, max_val: int):
        """Narrowest signed dtype holding [min_val, max_val]."""
        for dt in (np.int8, np.int16):
            ii = np.iinfo(dt)
            if ii.min <= min_val and max_val <= ii.max:
                return dt
        return np.int32

    def _packed_host_arrays(self) -> Dict[str, np.ndarray]:
        """The node tables at their device dtypes (serve_packed_tables:
        narrowest dtype the model's bin/node/feature counts allow;
        ``packed=False`` keeps everything int32).  The stump sentinel
        threshold re-encodes as the packed dtype's max — every real
        rank is strictly below it, so ``rank <= sentinel`` stays
        always-true.  Values widen back to int32 after each device
        gather (predict_device._forest_walk), so packing changes HBM
        bytes, never decisions."""
        out: Dict[str, np.ndarray] = {}
        if not self.packed:
            out["split_feature"] = self._split_feature
            out["threshold_bin"] = self._threshold_bin
            out["left_child"] = self._left_child
            out["right_child"] = self._right_child
            out["cat_index"] = self._cat_index
            out["cat_table"] = self._cat_table
            return out
        M = self._split_feature.shape[1] if self._split_feature.size \
            else 1
        L = self._leaf_slots
        max_rank = max([t.num_bins - 1 for t in self.tables] + [1])
        thr_dt = self._uint_dtype(max_rank + 1)   # +1: sentinel slot
        sentinel = np.iinfo(thr_dt).max
        out["threshold_bin"] = np.where(
            self._threshold_bin == _ALWAYS_LEFT, sentinel,
            self._threshold_bin).astype(thr_dt)
        child_dt = self._int_dtype(-L, M - 1)
        out["left_child"] = self._left_child.astype(child_dt)
        out["right_child"] = self._right_child.astype(child_dt)
        out["split_feature"] = self._split_feature.astype(
            self._uint_dtype(max(self.num_features - 1, 0)))
        out["cat_index"] = self._cat_index.astype(
            self._uint_dtype(max(len(self._cat_table) - 1, 0)))
        out["cat_table"] = self._cat_table.astype(np.uint8)
        return out

    def _build_soa(self) -> None:
        trees = self.trees
        T = len(trees)
        # node/leaf slots pad to the shared pow2 policy so co-hosted
        # versions of one model family (hot-swap / shadow) land on
        # identical SoA shapes and reuse each other's compiled serve
        # programs; padded slots cost table memory only
        M = bucket_nodes(max([t.num_nodes() for t in trees] + [1]))
        L = bucket_leaf_slots(max([t.num_leaves for t in trees] + [1]))
        self._leaf_slots = L
        self._split_feature = np.zeros((T, M), np.int32)
        self._threshold_bin = np.zeros((T, M), np.int32)
        self._default_left = np.zeros((T, M), bool)
        self._left_child = np.full((T, M), -1, np.int32)
        self._right_child = np.full((T, M), -1, np.int32)
        self._is_cat_node = np.zeros((T, M), bool)
        self._cat_index = np.zeros((T, M), np.int32)
        self.leaf_values = np.zeros((T, L), np.float64)
        self._na_bin = np.asarray([tab.na_bin for tab in self.tables],
                                  np.int32)
        cat_rows: List[np.ndarray] = []
        max_cat_bins = max([tab.num_bins for tab in self.tables
                            if tab.kind == "cat"] + [1])
        depth = 1
        for ti, t in enumerate(trees):
            n = t.num_nodes()
            self.leaf_values[ti, :t.num_leaves] = t.leaf_value[:t.num_leaves]
            if t.num_leaves <= 1:
                # stump: the padded root routes every row (NaN included)
                # to leaf 0
                self._threshold_bin[ti, 0] = _ALWAYS_LEFT
                self._default_left[ti, 0] = True
                continue
            depth = max(depth, t.max_depth())
            sf = t.split_feature[:n]
            dt = t.decision_type[:n]
            is_cat = (dt & _CAT_BIT) != 0
            self._split_feature[ti, :n] = sf
            self._default_left[ti, :n] = (dt & _DEFAULT_LEFT_BIT) != 0
            self._left_child[ti, :n] = t.left_child[:n]
            self._right_child[ti, :n] = t.right_child[:n]
            self._is_cat_node[ti, :n] = is_cat
            for f in np.unique(sf[~is_cat]):
                tab = self.tables[int(f)]
                m = (sf == f) & ~is_cat
                self._threshold_bin[ti, :n][m] = np.searchsorted(
                    tab.thresholds, t.threshold[:n][m], side="left")
            for i in np.nonzero(is_cat)[0]:
                tab = self.tables[int(sf[i])]
                # rank row over the feature's model-wide category table:
                # 0 = in this node's left set, 1 = not (sentinel bin —
                # unseen / negative / NaN — is always 1 -> right, the
                # _cat_contains fall-through)
                row = np.ones(max_cat_bins, np.int32)
                if len(tab.cats):
                    contained = t._cat_contains(
                        int(t.threshold[i]), tab.cats.astype(np.float64))
                    row[:len(tab.cats)] = np.where(contained, 0, 1)
                self._cat_index[ti, i] = len(cat_rows)
                cat_rows.append(row)
                # threshold_bin stays 0: go left iff rank <= 0
        self._cat_table = (np.stack(cat_rows) if cat_rows
                           else np.zeros((1, 1), np.int32))
        self._steps = bucket_steps(depth)
        # host->device transfer dtype for host-binned batches: bins are
        # bounded by the model's own table sizes, so the [N, F] binned
        # matrix usually crosses the wire as uint8
        max_bin = max([tab.num_bins - 1 for tab in self.tables] + [1])
        self._bin_dtype = self._uint_dtype(max_bin) if self.packed \
            else np.int32
        # device binning needs every categorical value f32-exact (the
        # fused path compares trunc(f32 x) against an f32 category
        # table); a model using categories at/above 2^24 serves via the
        # host paths instead
        self._device_bin_err: Optional[str] = None
        for f, tab in enumerate(self.tables):
            if tab.kind == "cat" and len(tab.cats) \
                    and float(np.abs(tab.cats).max()) >= _F32_EXACT_INT:
                self._device_bin_err = (
                    f"feature {f} uses categories beyond f32's exact "
                    f"integer range (>= 2^24); device binning would "
                    "misroute them")
                break

    def _fingerprint(self) -> str:
        h = hashlib.sha256()
        h.update(f"{len(self.trees)}:{self.num_class}:"
                 f"{self.num_features}".encode())
        for arr in (self._split_feature, self._threshold_bin,
                    self._left_child, self.leaf_values,
                    np.asarray(self.tree_weights, np.float64)):
            h.update(np.ascontiguousarray(arr).tobytes())
        return h.hexdigest()[:16]

    # -- binning -----------------------------------------------------------
    def bin_rows(self, x: np.ndarray) -> np.ndarray:
        """Exact host-side (f64) model-derived binning: [n, F] float ->
        [n, F] int32 in each feature's own bin space."""
        x = np.asarray(x, np.float64)
        out = np.zeros(x.shape, np.int32)
        for f, tab in enumerate(self.tables):
            if tab.kind == "num":
                v = x[:, f]
                isnan = np.isnan(v)
                if tab.miss_nan:
                    out[:, f] = np.where(
                        isnan, tab.na_bin,
                        np.searchsorted(tab.thresholds,
                                        np.where(isnan, 0.0, v), "left"))
                else:
                    out[:, f] = np.searchsorted(
                        tab.thresholds, np.where(isnan, 0.0, v), "left")
            elif tab.kind == "cat" and len(tab.cats):
                v = x[:, f]
                # trunc-toward-zero + NaN/inf -> -1, exactly
                # tree_model._decide's CategoricalDecision input mapping
                iv = np.where(np.isfinite(v), v, -1.0).astype(np.int64)
                pos = np.searchsorted(tab.cats, iv)
                pos = np.clip(pos, 0, len(tab.cats) - 1)
                out[:, f] = np.where(tab.cats[pos] == iv, pos,
                                     len(tab.cats))
        return out

    def _bucket(self, n: int) -> int:
        # the ONE shared bucketing policy (utils/shapes.py) — the same
        # pow2-with-floor rule now also buckets validation-set rows and
        # (via bucket_leaves) the grower's leaf budget
        return bucket_rows(n, min_bucket=self.min_bucket,
                           cap=self.max_batch)

    def _bin_table_widths(self) -> Tuple[int, int]:
        """Padded (threshold, category) table widths: pow2 via the
        shared policy — the widths are part of the fused program's
        signature, and a co-hosted version with a few more distinct
        thresholds must not re-trace."""
        b = bucket_bins(
            max([len(t.thresholds) for t in self.tables] + [1]))
        c = max([len(t.cats) for t in self.tables] + [0])
        return b, (bucket_bins(c, floor=4) if c else 0)

    def _device_bin_tables(self):
        import jax.numpy as jnp
        if self._device_bin_err:
            raise EngineUnsupported(self._device_bin_err)
        dev = self._bin_dev
        if dev is not None:
            # lock-free fast path (tools/race_allowlist.txt): the tuple
            # is published whole under the lock below, so a non-None
            # read is a complete table set — taking the lock here would
            # serialize every serve chunk on a read-only access
            return dev
        # build-once under the lock: two first-batch threads must not
        # upload the tables twice (wasted HBM + a fused/self-check
        # batch briefly reading tables the other thread re-binds)
        with self._lock:
            if self._bin_dev is None:
                F = self.num_features
                B, C = self._bin_table_widths()
                thr = np.full((F, B), np.inf, np.float32)
                zero_bin = np.zeros(F, np.int32)
                cat_vals = np.full((F, C), np.inf, np.float32)
                cat_len = np.zeros(F, np.int32)
                for f, tab in enumerate(self.tables):
                    if tab.kind == "num":
                        thr[f, :len(tab.thresholds)] = tab.thresholds
                        zero_bin[f] = np.searchsorted(tab.thresholds,
                                                      0.0, "left")
                    elif tab.kind == "cat" and len(tab.cats):
                        cat_vals[f, :len(tab.cats)] = tab.cats
                        cat_len[f] = len(tab.cats)
                self._bin_dev = (jnp.asarray(thr),
                                 jnp.asarray(zero_bin),
                                 jnp.asarray(cat_vals),
                                 jnp.asarray(cat_len))
            return self._bin_dev

    # -- traversal ---------------------------------------------------------
    def leaf_ids(self, x: np.ndarray,
                 device_binning: bool = False) -> np.ndarray:
        """Leaf index per (row, tree): [n, F] raw floats -> [n, T] int32.
        Batches above the bucket cap are processed in max-bucket chunks;
        zero rows never touch the device."""
        import jax
        x = np.asarray(x, np.float64)
        n = len(x)
        T = len(self.trees)
        if n == 0 or T == 0:
            return np.zeros((n, T), np.int32)
        cap = self._bucket(n)
        chunks = []
        for lo in range(0, n, cap):
            sub = x[lo:lo + cap]
            bucket = self._bucket(len(sub))
            with self._lock:
                self._buckets_seen[bucket] = \
                    self._buckets_seen.get(bucket, 0) + 1
            if device_binning:
                thr, zero_bin, cat_vals, cat_len = \
                    self._device_bin_tables()
                from ..predict_device import bin_rows_device_full
                xpad = np.zeros((bucket, self.num_features), np.float32)
                xpad[:len(sub)] = sub
                binned = bin_rows_device_full(
                    jax.numpy.asarray(xpad), thr, self._dev["na_bin"],
                    zero_bin, cat_vals, cat_len)
            else:
                pad = np.zeros((bucket, self.num_features),
                               self._bin_dtype)
                pad[:len(sub)] = self.bin_rows(sub)
                binned = jax.numpy.asarray(pad)
            # the serve hot path's ONE device fetch: leaf ids are the
            # data the host accumulation genuinely needs
            out = jax.device_get(self._traverse(binned))
            chunks.append(np.asarray(out[:len(sub)], np.int32))
        return np.concatenate(chunks, axis=0)

    # -- fused device-resident path ----------------------------------------
    def _fused_dev_arrays(self):
        import jax.numpy as jnp
        dev = self._fused_dev
        if dev is not None:
            return dev          # lock-free fast path, published whole
        with self._lock:        # build-once (see _device_bin_tables)
            if self._fused_dev is None:
                self._fused_dev = (
                    jnp.asarray(self._leaf_f32),
                    jnp.asarray(self._w32),
                    jnp.asarray(np.float32(self._avg_denom)))
            return self._fused_dev

    def _fused_call(self, xdev, transform):
        d = self._dev
        thr, zero_bin, cat_vals, cat_len = self._device_bin_tables()
        leaf_value, tree_weight, avg_denom = self._fused_dev_arrays()
        return _fused_jit()(
            xdev, thr, d["na_bin"], zero_bin, cat_vals, cat_len,
            d["split_feature"], d["threshold_bin"], d["default_left"],
            d["left_child"], d["right_child"], d["is_cat_node"],
            d["cat_index"], d["cat_table"], leaf_value, tree_weight,
            avg_denom, steps=self._steps, num_class=self.num_class,
            transform=transform)

    def fused_predict(self, x: np.ndarray,
                      raw_score: bool = False) -> np.ndarray:
        """Full prediction through the ONE-jit device-resident program
        (bin -> traverse -> accumulate -> transform on device): [n, F]
        raw floats -> final f32 scores, with a SINGLE host<->device
        sync per bucket chunk — the final score fetch.  Raises
        :class:`EngineUnsupported` when :attr:`fused_reason` is set
        (linear trees, f32-inexact categories); callers fall back to
        the host paths (serve/server.py counts
        ``serve.host_fallback_batches``).  Accumulation is f32 in tree
        order — the contract :meth:`_fused_reference` replays and
        :meth:`self_check` enforces; vs the exact host path the
        difference is the f64->f32 accumulation rounding, documented
        as ``serve_device_binning``'s accepted cost."""
        import jax
        if self.fused_reason is not None:
            raise EngineUnsupported(self.fused_reason)
        x = np.asarray(x, np.float64)
        n = len(x)
        k = self.num_class
        if n == 0:
            return np.zeros((0, k) if k > 1 else (0,), np.float32)
        transform = None if raw_score else self._transform
        cap = self._bucket(n)
        chunks = []
        for lo in range(0, n, cap):
            sub = x[lo:lo + cap]
            bucket = self._bucket(len(sub))
            with self._lock:
                self._fused_buckets[bucket] = \
                    self._fused_buckets.get(bucket, 0) + 1
            xpad = np.zeros((bucket, self.num_features), np.float32)
            xpad[:len(sub)] = sub
            scores = self._fused_call(jax.numpy.asarray(xpad), transform)
            # the fused serve hot path's ONE device fetch: the final
            # [rows, out] scores (tools/sync_allowlist.txt)
            out = jax.device_get(scores)
            chunks.append(np.asarray(out[:len(sub)]))
        return np.concatenate(chunks, axis=0)

    def _fused_reference(self, x: np.ndarray,
                         raw_score: bool = False) -> np.ndarray:
        """Host oracle for the fused path's parity contract: the SAME
        f32 float ops, in the same order, over leaves from the host
        tree walk — f32 leaf-value gather, f32 weight multiply, f32
        tree-order accumulation, f32 RF averaging, then the shared
        objective transform.  ``self_check`` compares
        :meth:`fused_predict` against this byte-for-byte on rows where
        f32 and f64 binning provably agree, so the comparison isolates
        the device binning + traversal + accumulation."""
        x = np.asarray(x, np.float64)
        n = len(x)
        k = self.num_class
        T = len(self.trees)
        if n == 0 or T == 0:
            return np.zeros((0, k) if k > 1 else (0,), np.float32)
        leaves = np.stack([t.predict_leaf(x) for t in self.trees],
                          axis=1).astype(np.int32)
        vals = self._leaf_f32[np.arange(T)[None, :], leaves]
        prods = vals * self._w32[None, :]
        score = np.zeros((n, k), np.float32)
        for ti in range(T):
            score[:, ti % k] += prods[:, ti]
        score = score / np.float32(self._avg_denom)
        out = score if k > 1 else score[:, 0]
        if not raw_score and self._transform is not None:
            import jax.numpy as jnp
            out = np.asarray(self._transform(jnp.asarray(out)))
        return out

    # -- scoring -----------------------------------------------------------
    def raw_scores(self, x: np.ndarray, t0: int = 0,
                   t1: Optional[int] = None,
                   leaves: Optional[np.ndarray] = None,
                   device_binning: bool = False) -> np.ndarray:
        """[n, num_class] float64 raw scores over trees [t0, t1) —
        float-op-for-float-op identical to ``Booster.predict``'s host
        accumulation (tree order, f64, tree_weights applied)."""
        x = np.asarray(x, np.float64)
        t1 = len(self.trees) if t1 is None else t1
        k = self.num_class
        if leaves is None:
            leaves = self.leaf_ids(x, device_binning=device_binning)
        score = np.zeros((len(x), k))
        for ti in range(t0, t1):
            t = self.trees[ti]
            w = self.tree_weights[ti] if ti < len(self.tree_weights) else 1.0
            lv = leaves[:, ti]
            vals = t.linear_leaf_outputs(lv, x) if t.is_linear \
                else t.leaf_value[lv]
            score[:, ti % k] += w * vals
        return score

    def predict(self, x, raw_score: bool = False,
                device_binning: bool = False) -> np.ndarray:
        """Full-model prediction with the ``Booster.predict`` output
        contract (averaging for RF, objective output conversion — the
        shared ``booster._finalize_score`` tail)."""
        from ..booster import _finalize_score
        x = np.asarray(x, np.float64)
        k = self.num_class
        n, t1 = len(x), len(self.trees)
        if n == 0:
            out_f32 = not raw_score and self.objective is not None
            shape = (0, k) if k > 1 else (0,)
            return np.zeros(shape, np.float32 if out_f32 else np.float64)
        score = self.raw_scores(x, device_binning=device_binning)
        return _finalize_score(score, k, self.objective,
                               self.average_output, 0, t1, raw_score)

    # -- verification ------------------------------------------------------
    def _probe_candidates(self) -> List[np.ndarray]:
        """Per-feature probe values aimed at the engine's risk surface:
        the model's own split thresholds (exact tie inputs — the values
        f32 rounding would misroute), midpoints between consecutive
        thresholds, out-of-range values, NaN, and every categorical's
        in/out-of-set and unseen values."""
        cands: List[np.ndarray] = []
        for tab in self.tables:
            if tab.kind == "num" and len(tab.thresholds):
                t = tab.thresholds
                mids = (t[:-1] + t[1:]) / 2.0 if len(t) > 1 \
                    else np.empty(0)
                c = np.concatenate([t, mids, [t[0] - 1.0, t[-1] + 1.0,
                                              0.0, np.nan]])
            elif tab.kind == "cat" and len(tab.cats):
                c = np.concatenate([tab.cats.astype(np.float64),
                                    [tab.cats[-1] + 1.0, -1.0, np.nan]])
            else:
                c = np.zeros(1)
            cands.append(c)
        return cands

    def _f32_consensus_mask(self, x: np.ndarray) -> np.ndarray:
        """Rows whose f32 on-device binning provably agrees with the
        exact f64 binning — only those can be byte-compared against the
        host walk (``serve_device_binning`` documents tie inexactness
        as the mode's accepted cost, so tie rows prove nothing)."""
        exact = self.bin_rows(x)
        ok = np.ones(len(x), bool)
        for f, tab in enumerate(self.tables):
            if tab.kind == "cat" and len(tab.cats):
                # integer-exact on device IF trunc(f32 x) == trunc(f64
                # x): only f32 rounding of the raw value can diverge
                v = x[:, f]
                iv64 = np.where(np.isfinite(v), v, -1.0).astype(np.int64)
                vf = v.astype(np.float32)
                iv32 = np.where(np.isfinite(vf), np.trunc(vf), -1.0)
                ok &= iv32 == iv64
                continue
            if tab.kind != "num" or not len(tab.thresholds):
                continue
            v = x[:, f]
            isnan = np.isnan(v)
            # mirror bin_rows_device: f32 value vs f32 threshold table;
            # NaN takes the f64-derived na/zero fallback, never f32 ops
            b32 = np.searchsorted(
                tab.thresholds.astype(np.float32),
                np.where(isnan, 0.0, v).astype(np.float32),
                side="left").astype(np.int64)
            nan_bin = tab.na_bin if tab.miss_nan else np.searchsorted(
                tab.thresholds, 0.0, side="left")
            b32 = np.where(isnan, nan_bin, b32)
            ok &= b32 == exact[:, f]
        return ok

    def self_check(self, max_rows: int = 64,
                   max_total_rows: int = 4096,
                   device_binning: bool = False) -> bool:
        """Post-build parity canary: traverse deterministic probe
        batches on the device and require the scores to be
        byte-identical to the host tree walk
        (``Tree.predict_leaf`` leaves fed through the SAME
        :meth:`raw_scores` accumulation, so the comparison isolates
        exactly the device traversal + binning).  Probes run in
        ``max_rows`` chunks until EVERY feature's candidate list has
        cycled through (capped at ``max_total_rows`` for pathological
        models), so all thresholds are exercised, not just the first
        chunk's worth.  ``device_binning`` additionally verifies the
        f32 on-device binning path the server will actually use under
        ``serve_device_binning`` — restricted to probe rows where f32
        and f64 binning provably agree (tie rows are the mode's
        documented inexactness, not an engine defect); a model device
        binning cannot represent at all (categoricals) raises
        :class:`EngineUnsupported` out of this check, which
        registry.load treats as failed.  True = verified; False = the
        compiled artifact disagrees with the model it was built from
        (a flattening bug, a device numeric surprise) — callers fall
        back to the host walk rather than serve wrong predictions
        (serve/registry.py).

        With ``device_binning`` and a fused-capable model the probe
        additionally gates the FUSED one-jit path
        (:meth:`fused_predict`): its scores must byte-match
        :meth:`_fused_reference` — the host replay of the same f32
        tree-order accumulation — on the consensus rows.  A failure
        here demotes the model to the host walk
        (``serve.host_fallback_batches``) instead of refusing
        traffic."""
        from ..utils import faultinject
        # chaos site (tools/soak_serve.py): a failing self-check must
        # DEMOTE the engine to the host walk, never drop requests
        faultinject.check("serve_self_check")
        cands = self._probe_candidates()
        if not cands or not self.trees:
            return True
        total = min(max(len(c) for c in cands), max_total_rows)
        for off in range(0, total, max_rows):
            rows = min(max_rows, total - off)
            probe = np.zeros((rows, self.num_features), np.float64)
            idx = off + np.arange(rows)
            for f, c in enumerate(cands):
                probe[:, f] = c[idx % len(c)]
            host_leaves = np.stack(
                [t.predict_leaf(probe) for t in self.trees],
                axis=1).astype(np.int32)
            host = self.raw_scores(probe, leaves=host_leaves)
            if not np.array_equal(self.raw_scores(probe), host):
                return False
            if device_binning:
                mask = self._f32_consensus_mask(probe)
                if mask.any():
                    if not np.array_equal(
                            self.raw_scores(probe[mask],
                                            device_binning=True),
                            host[mask]):
                        return False
                    if self.fused_reason is None and not np.array_equal(
                            self.fused_predict(probe[mask]),
                            self._fused_reference(probe[mask])):
                        return False
        return True

    # -- introspection -----------------------------------------------------
    def per_row_flops_bytes(self, fused: bool = False) -> Tuple[int, int]:
        """Static (flops, hbm_bytes) per served row — the numbers the
        serve ``/metrics`` roofline join (``perf.forest.*``) uses, kept
        truthful per path: the fused formula covers on-device binning +
        traversal + accumulation + transform at the PACKED table
        itemsize; the host-binned path covers the traversal only."""
        from ..obs.flops import (fused_forest_flops_bytes,
                                 traverse_flops_bytes)
        if fused and self.fused_reason is None:
            # padded table width — the comparisons the hardware runs
            B, _ = self._bin_table_widths()
            return fused_forest_flops_bytes(
                1, len(self.trees), self._steps, self.num_features, B,
                self.num_class,
                table_itemsize=self._dev["threshold_bin"].dtype.itemsize)
        return traverse_flops_bytes(
            1, len(self.trees), self._steps, self.num_features,
            binned_itemsize=np.dtype(self._bin_dtype).itemsize)

    def compile_stats(self) -> dict:
        """Bucketed-compile-cache ledger: buckets used (with hit
        counts, host-binned and fused paths separately), the bound on
        distinct traversal shapes, the process-wide trace counters
        (``predict_device.forest_trace_count`` /
        ``fused_trace_count``), fused availability and the packed
        node-table footprint."""
        from ..predict_device import forest_trace_count, fused_trace_count
        with self._lock:
            buckets = dict(sorted(self._buckets_seen.items()))
            fused_buckets = dict(sorted(self._fused_buckets.items()))
        cap = self.max_batch or max(list(buckets) + list(fused_buckets)
                                    + [self.min_bucket])
        import math
        bound = int(math.ceil(math.log2(max(cap, 2)))) + 1
        return {"fingerprint": self.fingerprint, "buckets": buckets,
                "fused_buckets": fused_buckets,
                "max_compiles_bound": bound,
                "forest_traces_process": forest_trace_count(),
                "fused_traces_process": fused_trace_count(),
                "fused": self.fused_reason is None,
                "fused_reason": self.fused_reason,
                "packed": self.packed,
                "table_bytes": self.table_bytes,
                "threshold_dtype":
                    str(self._dev["threshold_bin"].dtype),
                "child_dtype": str(self._dev["left_child"].dtype),
                "steps": self._steps, "num_trees": len(self.trees)}

    @classmethod
    def from_booster(cls, booster, *, max_batch: Optional[int] = None,
                     min_bucket: int = 16,
                     packed: bool = True) -> "PredictorEngine":
        """Flatten a ``Booster`` (live or loaded from a model file)."""
        return cls(booster.trees, booster.tree_weights,
                   booster._num_tree_per_iteration,
                   booster.num_feature(),
                   objective=getattr(booster, "objective", None),
                   average_output=booster._average_output,
                   max_batch=max_batch, min_bucket=min_bucket,
                   packed=packed)
