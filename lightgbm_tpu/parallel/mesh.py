"""Device-mesh construction for distributed training.

Replaces the reference's whole communication stack
(/root/reference/src/network/: hand-rolled Bruck allgather
network.cpp:156, recursive-halving reduce-scatter :249, socket/MPI linkers)
with ``jax.sharding.Mesh`` + XLA collectives over ICI/DCN — the schedule is
owned by the compiler (SURVEY.md §2.5 TPU mapping).  Multi-host
initialization goes through ``jax.distributed`` (the ``LGBM_NetworkInit``
analog, c_api.h:1350) which wires the same collectives across hosts.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(shape: Optional[Sequence[int]] = None,
              axis_names: Sequence[str] = ("data",),
              devices=None) -> Mesh:
    """Build a mesh over the available devices.

    shape=None uses all devices on one ``data`` axis (the GBDT scale axis —
    rows; SURVEY.md §2.6: data-parallel is the reference's main distributed
    mode, docs/Experiments.rst Criteo scaling).
    """
    devs = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devs),)
    shape = tuple(int(s) for s in shape)
    n = int(np.prod(shape))
    if n > len(devs):
        raise ValueError(f"mesh needs {n} devices, have {len(devs)}")
    mesh_devs = np.asarray(devs[:n]).reshape(shape)
    if len(axis_names) != len(shape):
        axis_names = tuple(f"axis{i}" for i in range(len(shape)))
    return Mesh(mesh_devs, tuple(axis_names))


def default_mesh(num: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    num = num or len(devs)
    return make_mesh((num,), ("data",), devs)


class OwnerShardPlan(NamedTuple):
    """Owner-shard chunking of the histogram (feature-group) axis for the
    data-parallel reduce-scatter (data_parallel_tree_learner.cpp:174-186:
    after ``Network::ReduceScatter`` each rank holds only ITS features'
    global histograms).

    chunk:      histogram rows owned per shard, ``ceil(G / n_shards)``
                (G = EFB group count, or F without bundling) — the dp
                grower's per-shard histogram carry is [L, chunk, B, 3]
    fmax:       split-scan width per shard = max features owned by any
                shard (> chunk only when EFB bundles several features
                into one owned group)
    shard_feat: [n_shards, fmax] int32 — GLOBAL feature id behind each
                shard's local scan slot; -1 = padding (scan-masked)
    """
    chunk: int
    fmax: int
    shard_feat: np.ndarray

    @property
    def n_shards(self) -> int:
        return self.shard_feat.shape[0]

    def hist_bytes(self, num_leaves: int, padded_bins: int,
                   scratch: int = 0) -> int:
        """Per-shard histogram-state bytes at a leaf budget (f32 g/h/c)."""
        return (num_leaves + scratch) * self.chunk * padded_bins * 3 * 4


def owner_shard_plan(group_of: np.ndarray, n_shards: int) -> OwnerShardPlan:
    """Partition the histogram axis (EFB groups; features when unbundled,
    where ``group_of`` is the identity) into ``n_shards`` equal chunks and
    map every owned group back to its global feature ids.  Host-side and
    cheap — computed once per (feature count, mesh) pair."""
    group_of = np.asarray(group_of, np.int64)
    g = int(group_of.max()) + 1 if group_of.size else 1
    chunk = -(-g // n_shards)
    owned = [np.nonzero((group_of >= s * chunk)
                        & (group_of < (s + 1) * chunk))[0]
             for s in range(n_shards)]
    fmax = max(1, max(len(o) for o in owned))
    shard_feat = np.full((n_shards, fmax), -1, np.int32)
    for s, o in enumerate(owned):
        shard_feat[s, :len(o)] = o
    return OwnerShardPlan(chunk=chunk, fmax=fmax, shard_feat=shard_feat)


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     retries: int = 2,
                     timeout_s: float = 300.0) -> None:
    """Multi-host bring-up (jax.distributed) — the ``Network::Init`` /
    ``LGBM_NetworkInit`` analog (network.cpp, c_api.h:1350).  On TPU pods
    arguments are auto-detected from the runtime environment.

    Runs under the resilience layer (utils/resilience.py): transient
    bring-up failures are retried ``retries`` times with jittered
    backoff under a ``timeout_s`` deadline, and a faulthandler watchdog
    dumps all-thread stacks if the blocking initialize wedges (the
    round-5 failure mode: a 10 h silent hang)."""
    from ..utils import faultinject
    from ..utils.resilience import RetryPolicy, Watchdog, retry_call

    kwargs = {}
    if coordinator_address is not None:
        kwargs.update(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)

    def _bring_up():
        faultinject.check("device_claim")
        jax.distributed.initialize(**kwargs)

    policy = RetryPolicy.for_bringup(retries, timeout_s)
    with Watchdog(timeout_s, label="jax.distributed bring-up"):
        retry_call(_bring_up, policy=policy,
                   label="jax.distributed bring-up")
