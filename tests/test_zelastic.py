"""Elastic pod-scale training (parallel/elastic.py; ISSUE 14).

Covers the liveness layer (watchdog cancel-and-raise mode, heartbeats,
collective deadline), the shrink-to-survive recovery ladder (chaos soak
via tools/soak_train.py), the topology-volatile snapshot signature, the
``launch.init`` success-only latch, and the kill -9 subprocess matrix:
a 2-process ``jax.distributed`` run losing a worker mid-iteration must
detect the loss within the heartbeat deadline, persist the shrink
request, and — relaunched shrunk — converge byte-identically (int32
quant path) to an uninterrupted serial run."""

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.parallel import elastic
from lightgbm_tpu.utils import faultinject
from lightgbm_tpu.utils.resilience import (Watchdog, WatchdogTimeout,
                                           is_retryable_device_error)

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.clear()
    yield
    faultinject.clear()


def _small_data(n=300, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 6)
    y = (x[:, 0] - x[:, 1] > 0).astype("float32")
    return x, y


def _trees(bst_or_text):
    text = bst_or_text if isinstance(bst_or_text, str) \
        else bst_or_text.model_to_string()
    return text.split("parameters:")[0].split("feature_infos")[1]


# ---------------------------------------------------------------------------
# Watchdog cancel-and-raise mode (utils/resilience.py)
# ---------------------------------------------------------------------------

class TestWatchdogRaiseMode:
    def test_deadline_raises_classified_timeout_in_waiting_thread(self):
        wd = Watchdog(0.3, label="wedged call", on_timeout="raise")
        t0 = time.monotonic()
        with pytest.raises(WatchdogTimeout) as ei:
            wd.run(time.sleep, 5.0)
        assert time.monotonic() - t0 < 3.0       # not the sleep's 5 s
        assert "wedged call" in str(ei.value)
        # the classifier must treat the abandoned call as transient so
        # retry/backoff and the elastic ladder re-attempt it
        assert is_retryable_device_error(ei.value)

    def test_raise_mode_returns_value_and_relays_exceptions(self):
        wd = Watchdog(5.0, on_timeout="raise")
        assert wd.run(lambda a, b=0: a + b, 2, b=3) == 5
        with pytest.raises(KeyError):
            Watchdog(5.0, on_timeout="raise").run(
                lambda: (_ for _ in ()).throw(KeyError("x")))

    def test_dump_only_stays_default(self):
        # REGRESSION CONTRACT: the historical dump-only behavior is the
        # default — run() executes inline and NEVER raises on overrun
        wd = Watchdog(0.05)
        assert wd.on_timeout == "dump"
        t0 = time.monotonic()
        assert wd.run(lambda: (time.sleep(0.2), "done")[1]) == "done"
        assert time.monotonic() - t0 >= 0.2      # ran to completion
        with Watchdog(0.05, label="cm"):         # CM form unchanged
            time.sleep(0.1)

    def test_disabled_timeout_runs_inline(self):
        assert Watchdog(0.0, on_timeout="raise").run(lambda: 7) == 7

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError):
            Watchdog(1.0, on_timeout="explode")


# ---------------------------------------------------------------------------
# Fault-injection sites: hang action, new site defaults
# ---------------------------------------------------------------------------

class TestHangSites:
    def test_hang_is_default_for_wedge_sites_and_bounded(self, monkeypatch):
        monkeypatch.setenv(faultinject.HANG_ENV_VAR, "0.2")
        faultinject.configure("collective_hang:1")
        t0 = time.monotonic()
        faultinject.check("collective_hang")     # blocks ~0.2 s, no raise
        assert 0.15 <= time.monotonic() - t0 < 2.0

    def test_claim_wedge_known_and_hangs(self, monkeypatch):
        monkeypatch.setenv(faultinject.HANG_ENV_VAR, "0.1")
        faultinject.configure("claim_wedge:1")
        t0 = time.monotonic()
        faultinject.check("claim_wedge")
        assert time.monotonic() - t0 >= 0.05

    def test_explicit_actions_still_validated(self):
        with pytest.raises(ValueError):
            faultinject.configure("collective_hang:1:melt")
        faultinject.configure("collective_hang:1:raise")
        with pytest.raises(faultinject.InjectedFault):
            faultinject.check("collective_hang")


# ---------------------------------------------------------------------------
# Liveness: heartbeat writer + staleness monitor + guarded fetch
# ---------------------------------------------------------------------------

class TestLiveness:
    def test_heartbeat_and_monitor_detect_stale_peer(self, tmp_path):
        hb1 = elastic.Heartbeat(str(tmp_path), 1, interval_s=0.1).start()
        mon = elastic.HeartbeatMonitor(str(tmp_path), 0, timeout_s=0.6,
                                       interval_s=0.1)
        try:
            deadline = time.monotonic() + 3.0
            while 1 not in mon.peers() and time.monotonic() < deadline:
                mon.check()                     # registers the live peer
                time.sleep(0.05)
            assert mon.peers() == [1]
            mon.check()                         # fresh: no failure
        finally:
            hb1.stop()                          # the "kill"
        t0 = time.monotonic()
        with pytest.raises(elastic.ElasticFailure) as ei:
            while True:
                time.sleep(0.05)
                mon.check()
                if time.monotonic() - t0 > 5.0:
                    break
        assert ei.value.kind == "host_loss"
        # detected within the heartbeat deadline (+ slack for the scan
        # rate limit)
        assert time.monotonic() - t0 < 2.5

    def test_monitor_skew_immune_progress_based(self, tmp_path):
        # liveness is judged by observed mtime PROGRESS on the
        # monitor's monotonic clock, not by now - mtime: a live peer
        # whose host (or fileserver) clock is far behind must register
        # and stay fresh, while a relic file that never advances must
        # never become a peer
        mon = elastic.HeartbeatMonitor(str(tmp_path), 0, timeout_s=0.5,
                                       interval_s=0.1)
        path = os.path.join(str(tmp_path), "hb_7.json")
        skew = 120.0                      # absolute mtimes hopelessly stale

        def beat(k):
            with open(path, "w", encoding="utf-8") as f:
                f.write("{}")
            t = time.time() - skew + 0.05 * k
            os.utime(path, (t, t))

        beat(0)
        assert mon._scan() == ([], [])    # relic so far: not a peer
        for k in range(1, 4):             # advancing = alive, just skewed
            time.sleep(0.02)
            mon._scan()
            beat(k)
        fresh, lost = mon._scan()
        assert (fresh, lost) == ([7], [])
        t0 = time.monotonic()             # stops beating -> lost
        with pytest.raises(elastic.ElasticFailure) as ei:
            while time.monotonic() - t0 < 5.0:
                time.sleep(0.05)
                mon.check()
        assert ei.value.kind == "host_loss"
        assert time.monotonic() - t0 < 2.5

    def test_survivors_include_self_and_fresh_peers(self, tmp_path):
        hb = elastic.Heartbeat(str(tmp_path), 3, interval_s=0.1).start()
        try:
            mon = elastic.HeartbeatMonitor(str(tmp_path), 0,
                                           timeout_s=5.0, interval_s=0.1)
            assert mon.survivors() == [0, 3]
        finally:
            hb.stop()

    def test_guarded_get_bounds_a_wedged_fetch(self, monkeypatch):
        import jax.numpy as jnp
        monkeypatch.setenv(faultinject.HANG_ENV_VAR, "5")
        faultinject.configure("collective_hang:1")
        t0 = time.monotonic()
        with pytest.raises(elastic.ElasticFailure) as ei:
            elastic.guarded_get(jnp.ones(3), 0.3, site="fetch")
        assert ei.value.kind == "collective_timeout"
        assert time.monotonic() - t0 < 3.0
        faultinject.clear()
        out = elastic.guarded_get(jnp.arange(3), 5.0)
        np.testing.assert_array_equal(out, [0, 1, 2])

    def test_check_peers_host_loss_injection(self):
        faultinject.configure("host_loss:1")
        with pytest.raises(elastic.ElasticFailure) as ei:
            elastic.check_peers()
        assert ei.value.kind == "host_loss"
        faultinject.clear()
        elastic.check_peers()                   # disarmed: no-op

    def test_failure_kind_classification(self):
        assert elastic.failure_kind(
            elastic.ElasticFailure("host_loss")) == "host_loss"
        assert elastic.failure_kind(
            WatchdogTimeout("x", 1.0)) == "collective_timeout"
        assert elastic.failure_kind(
            RuntimeError("UNAVAILABLE: claim hung")) == "bringup"
        assert elastic.failure_kind(TypeError("bug")) is None


# ---------------------------------------------------------------------------
# Config + snapshot-signature contracts
# ---------------------------------------------------------------------------

class TestElasticConfig:
    def test_validation(self):
        from lightgbm_tpu.config import Config
        with pytest.raises(ValueError):
            Config({"elastic_heartbeat_interval_s": 0})
        with pytest.raises(ValueError):
            Config({"elastic_heartbeat_interval_s": 2.0,
                    "elastic_heartbeat_timeout_s": 1.0})
        with pytest.raises(ValueError):
            Config({"elastic_retries": -1})
        with pytest.raises(ValueError):
            Config({"elastic_collective_timeout_s": -1})
        Config({"elastic_enable": True})        # defaults coherent

    def test_signature_topology_volatile_only_under_elastic(self):
        from lightgbm_tpu.snapshot import params_signature
        base = {"objective": "binary", "num_leaves": 15}
        el = dict(base, elastic_enable=True)
        # elastic: topology + every elastic_* knob is run control
        assert params_signature(dict(el, tree_learner="data",
                                     mesh_shape=[8])) \
            == params_signature(dict(el, tree_learner="serial"))
        assert params_signature(
            dict(el, elastic_collective_timeout_s=7.0)) \
            == params_signature(el)
        # non-elastic: topology stays signature-relevant
        assert params_signature(dict(base, tree_learner="data")) \
            != params_signature(dict(base, tree_learner="serial"))
        # the model surface still invalidates under elastic
        assert params_signature(dict(el, num_leaves=31)) \
            != params_signature(el)

    def test_disabled_elastic_is_byte_identical(self):
        x, y = _small_data()
        p = {"objective": "binary", "num_leaves": 8, "max_bin": 31,
             "min_data_in_leaf": 5, "verbosity": -1}
        b_off = lgb.train(dict(p), lgb.Dataset(x, label=y),
                          num_boost_round=4)
        b_on = lgb.train(dict(p, elastic_enable=True),
                         lgb.Dataset(x, label=y), num_boost_round=4)
        assert _trees(b_off) == _trees(b_on)


class TestMultiProcessResumeContract:
    def test_global_fp_override_and_score_slicing(self, tmp_path):
        # the survivors>1 relaunch contract, unit-level: a SHARD
        # dataset carrying elastic_global_fingerprint must match a
        # manifest stamped with the GLOBAL fingerprint, and engine
        # resume must slice the global score to elastic_row_range —
        # without both, a multi-process relaunch silently restarts
        # from iteration 0 (or crashes feeding a global score to a
        # shard-sized dataset)
        from lightgbm_tpu import engine
        from lightgbm_tpu.dataset import fingerprint_arrays
        from lightgbm_tpu.snapshot import find_latest_snapshot
        x, y = _small_data(200)
        p = {"objective": "binary", "num_leaves": 8, "max_bin": 31,
             "min_data_in_leaf": 5, "verbosity": -1,
             "elastic_enable": True, "snapshot_freq": 2,
             "output_model": str(tmp_path / "m.txt")}
        lgb.train(dict(p), lgb.Dataset(x, label=y), num_boost_round=4)
        # forge the global-state manifest a pc>1 run would write: the
        # serial snapshot's score/fingerprint ARE global here (pc=1),
        # so only the shard side of the contract needs exercising
        shard = lgb.Dataset(x[:50], label=y[:50])
        from lightgbm_tpu.snapshot import params_signature
        sig = params_signature(dict(p))
        # the shard's own fingerprint must NOT match the manifest
        assert find_latest_snapshot(str(tmp_path / "m.txt"), sig,
                                    shard) is None
        shard.elastic_global_fingerprint = fingerprint_arrays(y, None)
        found = find_latest_snapshot(str(tmp_path / "m.txt"), sig,
                                     shard)
        assert found is not None and found[0] >= 2
        assert found[2].shape[0] == 200          # global rows
        # engine resume on the shard: global score sliced to [0, 50) —
        # an unsliced 200-row init score would raise on the 50-row set
        shard.elastic_row_range = (0, 50)
        bst = engine.train(dict(p, resume=True), shard,
                           num_boost_round=4)
        assert len(bst.trees) >= 4


class TestLaunchLatch:
    def test_done_latched_only_on_success(self, monkeypatch):
        from lightgbm_tpu.parallel import launch
        import jax
        monkeypatch.delattr(launch.init, "_done", raising=False)
        launch.init._fail_t = None
        calls = {"n": 0}

        def failing_init(**kw):
            calls["n"] += 1
            raise RuntimeError("UNAVAILABLE: coordination service down")

        monkeypatch.setattr(jax.distributed, "initialize", failing_init)
        launch.init(retries=0, timeout_s=0)     # auto path: warn + solo
        # the failed bring-up must NOT latch: a later attempt retries
        assert not getattr(launch.init, "_done", False)
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: None)
        launch.init(retries=0, timeout_s=0)
        assert launch.init._done is True
        assert launch.init._fail_t is None
        assert calls["n"] == 1
        monkeypatch.delattr(launch.init, "_done", raising=False)

    def test_auto_failure_cooldown_skips_reattempt(self, monkeypatch):
        # the pre-elastic code latched _done permanently after a failed
        # AUTO bring-up; elastic made it retryable — but a cooldown of
        # one deadline must keep a permanently-down coordination
        # service from re-burning the full retry budget on EVERY
        # train() call
        from lightgbm_tpu.parallel import launch
        import jax
        monkeypatch.delattr(launch.init, "_done", raising=False)
        launch.init._fail_t = None
        calls = {"n": 0}

        def failing_init(**kw):
            calls["n"] += 1
            raise RuntimeError("UNAVAILABLE: coordination service down")

        monkeypatch.setattr(jax.distributed, "initialize", failing_init)
        launch.init(retries=0, timeout_s=30.0)   # fails, stamps _fail_t
        assert calls["n"] == 1
        launch.init(retries=0, timeout_s=30.0)   # inside cooldown: solo
        assert calls["n"] == 1
        launch.init._fail_t = time.monotonic() - 60.0   # cooldown over
        launch.init(retries=0, timeout_s=30.0)   # retried
        assert calls["n"] == 2
        launch.init._fail_t = None
        monkeypatch.delattr(launch.init, "_done", raising=False)


# ---------------------------------------------------------------------------
# Recovery ladder: in-process chaos soak (tools/soak_train.py)
# ---------------------------------------------------------------------------

class TestRecoveryLadder:
    def test_chaos_soak_shrinks_and_matches_serial(self, tmp_path):
        sys.path.insert(0, os.path.join(os.path.dirname(HERE), "tools"))
        import soak_train
        elastic.reset_metrics()
        rep = soak_train.run_soak_train(
            rounds=10, n_rows=350, mesh=4, hang_s=4.0,
            collective_timeout_s=0.8, budget_s=180.0,
            workdir=str(tmp_path))
        assert rep["violations"] == [], rep
        assert rep["report"]["shrinks"] >= 1
        assert rep["report"]["recoveries"] >= 1
        kinds = {f["kind"] for f in rep["report"]["failures"]}
        assert "collective_timeout" in kinds
        # failure events persisted next to the model
        ev_path = os.path.join(str(tmp_path),
                               "soak_model.txt.elastic.jsonl")
        events = [json.loads(ln)
                  for ln in open(ev_path, encoding="utf-8")]
        assert any(e["event"] == "shrink" for e in events)
        assert any(e["event"] == "recovered" for e in events)

    def test_ladder_reraises_unclassified_errors(self, tmp_path):
        x, y = _small_data(120)
        # CEGB is unsupported under tree_learner=data: a programming /
        # configuration error the ladder must surface, never retry
        p = {"objective": "binary", "tree_learner": "data",
             "mesh_shape": [2], "cegb_penalty_split": 0.5,
             "verbosity": -1,
             "output_model": str(tmp_path / "m.txt")}
        with pytest.raises(ValueError) as ei:
            elastic.elastic_train(p, x, y, num_boost_round=2)
        assert elastic.failure_kind(ei.value) is None


# ---------------------------------------------------------------------------
# kill -9 of a mesh worker mid-iteration (2 REAL jax.distributed
# processes, gloo collectives), then shrunk-relaunch convergence
# ---------------------------------------------------------------------------

def _free_ports(n):
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


class TestKillMeshWorker:
    def test_kill9_detect_shrink_resume_bitwise(self, tmp_path):
        import elastic_worker as ew
        outdir = str(tmp_path)
        env = dict(os.environ, ELASTIC_WORKER_QUANT="1")
        env.pop("XLA_FLAGS", None)      # workers pin their own topology
        worker = os.path.join(HERE, "elastic_worker.py")
        ports = _free_ports(2)
        machines = ",".join(f"127.0.0.1:{p}" for p in ports)

        logs = [open(os.path.join(outdir, f"w{r}.log"), "w+")
                for r in (0, 1)]
        procs = [subprocess.Popen(
            [sys.executable, worker, outdir, "worker", str(r), machines],
            env=env, stdout=logs[r], stderr=subprocess.STDOUT)
            for r in (0, 1)]
        t0 = time.monotonic()
        rcs = [p.wait(timeout=240) for p in procs]
        wall = time.monotonic() - t0
        outs = []
        for lg in logs:
            lg.flush()
            lg.seek(0)
            outs.append(lg.read())
            lg.close()
        # rank 1 SIGKILLed itself mid-iteration
        assert "WORKER_KILLING_SELF" in outs[1], outs[1][-2000:]
        assert rcs[1] == -9, (rcs, outs[1][-500:])
        # rank 0 classified the loss and requested a shrink
        assert rcs[0] == ew.SHRINK_RC, (rcs, outs[0][-3000:])
        marker = json.load(open(os.path.join(outdir, "shrink_0.json"),
                                encoding="utf-8"))
        assert marker["kind"] in ("host_loss", "collective_timeout",
                                  "bringup")
        assert marker["survivors"] == [0]
        # detection bounded by the liveness deadlines (heartbeat 2 s /
        # collective 4 s), not by the 240 s harness timeout
        assert marker["detect_s"] < 15.0, marker
        assert wall < 200.0
        # a COMPLETE snapshot from before the kill exists with GLOBAL
        # state (full-data fingerprint + full-row score)
        from lightgbm_tpu.snapshot import find_latest_complete_snapshot
        found = find_latest_complete_snapshot(
            os.path.join(outdir, "m.txt"))
        assert found is not None and found[0] >= ew.SNAPSHOT_FREQ
        man = json.load(open(found[1] + ".manifest.json",
                             encoding="utf-8"))
        assert man["num_data"] == 320   # global rows, not a shard

        # shrunk relaunch (the pod-launcher contract): must resume the
        # 2-process snapshot and finish the remaining rounds
        r = subprocess.run([sys.executable, worker, outdir, "resume"],
                           env=env, capture_output=True, text=True,
                           timeout=240)
        assert "WORKER_DONE resume" in r.stdout, \
            r.stdout[-2000:] + r.stderr[-3000:]
        # uninterrupted serial oracle
        r2 = subprocess.run([sys.executable, worker, outdir, "serial"],
                            env=env, capture_output=True, text=True,
                            timeout=240)
        assert "WORKER_DONE serial" in r2.stdout, r2.stderr[-3000:]
        final = open(os.path.join(outdir, "final.txt"),
                     encoding="utf-8").read()
        serial = open(os.path.join(outdir, "serial.txt"),
                      encoding="utf-8").read()
        # int32 quant path: dp histograms == serial bitwise, so the
        # kill + shrink + resume run is BYTE-IDENTICAL to never failing
        assert _trees(final) == _trees(serial)
