"""Distributed dataset construction: sharded bin-mapper fitting.

Analog of the reference's distributed binning
(/root/reference/src/io/dataset_loader.cpp:1104-1186): with rows partitioned
across processes, features are sharded across ranks (balanced contiguous
slices), each rank runs FindBin on its own sample for its feature slice,
and the serialized mappers are allgathered so every process ends up with
identical global bin boundaries.

The collective rides jax.distributed (multihost_utils.process_allgather)
instead of the reference's hand-rolled socket Allgather (network.cpp:156);
an injectable ``allgather`` hook keeps it testable in-process.
"""

from __future__ import annotations

import pickle
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..binning import BinMapper, BinType
from ..config import Config


def shard_features(num_features: int, num_machines: int):
    """Contiguous balanced feature slices (dataset_loader.cpp:1106-1117)."""
    step = max((num_features + num_machines - 1) // num_machines, 1)
    start, length = [0] * num_machines, [0] * num_machines
    for i in range(num_machines - 1):
        length[i] = min(step, num_features - start[i])
        start[i + 1] = start[i] + length[i]
    length[num_machines - 1] = num_features - start[num_machines - 1]
    return start, length


def _jax_allgather_bytes(payload: bytes) -> List[bytes]:
    """Variable-length byte allgather over jax.distributed processes."""
    import jax
    from jax.experimental import multihost_utils

    arr = np.frombuffer(payload, np.uint8)
    n = np.int64(len(arr))
    sizes = np.asarray(multihost_utils.process_allgather(n))
    maxlen = int(sizes.max())
    padded = np.zeros(maxlen, np.uint8)
    padded[:len(arr)] = arr
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    gathered = gathered.reshape(jax.process_count(), maxlen)
    return [gathered[i, :int(sizes[i])].tobytes()
            for i in range(jax.process_count())]


def distributed_bin_mappers(
        local_sample: np.ndarray, config: Config,
        cat_idx: Optional[set] = None,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        allgather: Optional[Callable[[bytes], List[bytes]]] = None,
) -> List[BinMapper]:
    """Fit globally-consistent bin mappers from per-process row shards.

    local_sample: this process's sampled raw rows [n_local_sample, F]
    Returns the full list of F bin mappers, identical on every process.
    """
    cat_idx = cat_idx or set()
    if process_index is None or process_count is None:
        import jax
        process_index = jax.process_index()
        process_count = jax.process_count()
    if allgather is None:
        allgather = _jax_allgather_bytes

    f_total = local_sample.shape[1]
    start, length = shard_features(f_total, process_count)
    lo = start[process_index]
    hi = lo + length[process_index]
    own: List[dict] = []
    n = len(local_sample)
    mbf = config.max_bin_by_feature
    for f in range(lo, hi):
        m = BinMapper()
        mb = int(mbf[f]) if mbf else config.max_bin
        bt = BinType.CATEGORICAL if f in cat_idx else BinType.NUMERICAL
        m.find_bin(local_sample[:, f], n, mb, config.min_data_in_bin,
                   min_split_data=config.min_data_in_leaf,
                   pre_filter=config.feature_pre_filter, bin_type=bt,
                   use_missing=config.use_missing,
                   zero_as_missing=config.zero_as_missing)
        own.append(m.to_state())
    shards = allgather(pickle.dumps(own, protocol=4))
    mappers: List[BinMapper] = []
    for blob in shards:
        for st in pickle.loads(blob):
            mappers.append(BinMapper.from_state(st))
    if len(mappers) != f_total:
        raise RuntimeError(
            f"distributed binning produced {len(mappers)} mappers for "
            f"{f_total} features — rank slices out of sync")
    return mappers
