"""Batched low-latency inference serving subsystem.

Turns a trained model into a long-lived, high-throughput prediction
service (ROADMAP north star: "serving heavy traffic"), following the
dedicated-GBDT-inference-engine literature (arXiv:2011.02022 SoA tree
layouts, arXiv:1706.08359 batched device traversal):

- ``engine``    compiled predictor: the ensemble flattened ONCE into
                packed SoA device arrays, rows binned into
                model-derived bin space, whole-forest traversal under a
                bucketed compile cache (batch sizes round up to
                power-of-two buckets so XLA compiles are bounded by
                log2(max_batch)); under ``serve_device_binning`` the
                whole batch — bin, traverse, accumulate, transform —
                runs as ONE jitted device-resident program with a
                single final-score fetch (docs/Serving.md
                "Device-resident fast path").
- ``batcher``   micro-batching queue: a worker thread coalesces
                concurrent requests under ``serve_max_batch`` /
                ``serve_max_wait_ms`` with a bounded queue and explicit
                reject-with-retry-after backpressure.
- ``registry``  versioned model registry with atomic hot swap;
                in-flight requests finish on the version they started
                on.
- ``breaker``   serving circuit breaker: admission-time rejection
                (503 + Retry-After) while the device side is failing,
                half-open probes with exponentially backed-off
                cooldowns (``utils/resilience.CircuitBreaker``).
- ``server``    in-process ``Server`` API + stdlib-only HTTP frontend
                (``/predict``, ``/healthz``, ``/metrics``, ``/drain``),
                wired into the obs subsystem (``serve.*`` metrics,
                per-batch spans).

Hardening (deadlines, breaker, graceful drain, verified artifacts,
chaos soak harness): docs/Serving.md "Hardening" and
tools/soak_serve.py.
"""

from __future__ import annotations

from .batcher import (BacklogFull, BatcherClosed, BatcherDraining,
                      DeadlineExceeded, MicroBatcher)
from .breaker import CircuitOpen, ServeBreaker
from .engine import EngineUnsupported, PredictorEngine
from .registry import (ArtifactVerificationError, ModelRegistry,
                       NoModelError, ServedModel)
from .server import Server, start_http

__all__ = [
    "ArtifactVerificationError", "BacklogFull", "BatcherClosed",
    "BatcherDraining", "CircuitOpen", "DeadlineExceeded",
    "EngineUnsupported", "MicroBatcher", "ModelRegistry", "NoModelError",
    "PredictorEngine", "ServeBreaker", "ServedModel", "Server",
    "start_http",
]
