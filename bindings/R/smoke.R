# R binding smoke test for lightgbm_tpu's LGBM_Train* C ABI.
#
# Usage:
#   Rscript smoke.R <lgbtpu_shim.so> <x.csv> <y.csv> <model.txt> <pred.csv>
#
# Loads the .C-convention shim (lgbtpu_shim.c), trains 5 boosting
# iterations on the CSV data, saves the model in the reference text
# format, and writes the predictions — which the pytest harness
# (tests/test_r_binding.py) compares against the Python API trained on
# identical data.  The reference's R package drives c_api.h through the
# same dyn.load + C-glue pattern (R-package/R/lgb.train.R ->
# lightgbm_R.cpp).

a <- commandArgs(trailingOnly = TRUE)
stopifnot(length(a) == 5)
shim <- a[[1]]; xcsv <- a[[2]]; ycsv <- a[[3]]
model <- a[[4]]; predcsv <- a[[5]]

dyn.load(shim)
stopifnot(is.loaded("lgbtpu_smoke"))

x <- as.matrix(read.csv(xcsv, header = FALSE))
y <- scan(ycsv, quiet = TRUE)
n <- nrow(x); f <- ncol(x)
stopifnot(length(y) == n)

r <- .C("lgbtpu_smoke",
        as.double(x),                    # column-major; shim transposes
        as.integer(n), as.integer(f),
        as.double(y),
        "max_bin=63 verbosity=-1",
        "objective=binary num_leaves=15 learning_rate=0.1 verbosity=-1",
        as.integer(5),
        model,
        pred = double(n),
        status = integer(1))
stopifnot(r$status == 0)

write(r$pred, predcsv, ncolumns = 1)
cat(sprintf("R smoke ok: n=%d f=%d acc=%.3f\n", n, f,
            mean((r$pred > 0.5) == (y > 0.5))))
