"""Distributed learner tests on a virtual 8-device CPU mesh.

The reference tests distributed training by simulating machines with
localhost sockets (tests/distributed/_test_distributed.py); here the mesh
IS the simulation: data-parallel and feature-parallel growers must produce
exactly the same tree as the serial grower.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.grower import make_grower
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.parallel import (make_dp_grower, make_fp_grower, make_mesh,
                                   make_voting_grower, shard_rows)


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh((8,), ("data",))


@pytest.fixture(scope="module")
def mesh_feat():
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    return make_mesh((4,), ("feature",))


def _data(n=4096, f=8, b=16, seed=0):
    rng = np.random.RandomState(seed)
    binned = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    y = (binned[:, 2] >= b // 2).astype(np.float32) \
        + 0.3 * rng.randn(n).astype(np.float32)
    g = (0.5 - y).astype(np.float32)
    vals = np.stack([g, np.ones(n, np.float32), np.ones(n, np.float32)], axis=1)
    return binned, vals


def _tree_fields(tree, skip=("leaf_of_row",)):
    return {k: np.asarray(v) for k, v in tree._asdict().items()
            if k not in skip}


class TestDataParallel:
    def test_matches_serial(self, mesh8):
        binned, vals = _data()
        F, B, L = binned.shape[1], 16, 8
        p = SplitParams(min_data_in_leaf=5)
        nb = jnp.full(F, B, jnp.int32)
        na = jnp.full(F, -1, jnp.int32)
        fm = jnp.ones(F, bool)

        serial = make_grower(num_leaves=L, num_bins=B, params=p)
        t_ser = serial(jnp.asarray(binned), jnp.asarray(vals), fm, nb, na)

        dp = make_dp_grower(mesh8, num_leaves=L, num_bins=B, params=p)
        t_dp = dp(shard_rows(mesh8, binned), shard_rows(mesh8, vals),
                  fm, nb, na)

        ser_f = _tree_fields(t_ser)
        dp_f = _tree_fields(t_dp)
        assert int(t_ser.num_leaves) == int(t_dp.num_leaves) > 2
        for k in ("split_feature", "threshold_bin", "left_child", "right_child"):
            np.testing.assert_array_equal(ser_f[k], dp_f[k], err_msg=k)
        np.testing.assert_allclose(ser_f["leaf_value"], dp_f["leaf_value"],
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(ser_f["leaf_count"], dp_f["leaf_count"])
        # row partition agrees (dp leaf_of_row is row-sharded, same order)
        np.testing.assert_array_equal(np.asarray(t_ser.leaf_of_row),
                                      np.asarray(t_dp.leaf_of_row))

    def test_uneven_work_masking(self, mesh8):
        # zero-weight rows on some shards (bagging) keep results consistent
        binned, vals = _data(seed=3)
        vals[::3, :] = 0.0  # "out of bag"
        F, B, L = binned.shape[1], 16, 6
        p = SplitParams(min_data_in_leaf=5)
        nb = jnp.full(F, B, jnp.int32)
        na = jnp.full(F, -1, jnp.int32)
        fm = jnp.ones(F, bool)
        serial = make_grower(num_leaves=L, num_bins=B, params=p)
        t_ser = serial(jnp.asarray(binned), jnp.asarray(vals), fm, nb, na)
        dp = make_dp_grower(mesh8, num_leaves=L, num_bins=B, params=p)
        t_dp = dp(shard_rows(mesh8, binned), shard_rows(mesh8, vals), fm, nb, na)
        np.testing.assert_array_equal(np.asarray(t_ser.split_feature),
                                      np.asarray(t_dp.split_feature))
        np.testing.assert_allclose(np.asarray(t_ser.leaf_value),
                                   np.asarray(t_dp.leaf_value),
                                   rtol=1e-4, atol=1e-5)


class TestVotingParallel:
    def test_quality_with_vote_compression(self, mesh8):
        binned, vals = _data(n=4096, f=8)
        F, B, L = binned.shape[1], 16, 8
        p = SplitParams(min_data_in_leaf=5)
        nb = jnp.full(F, B, jnp.int32)
        na = jnp.full(F, -1, jnp.int32)
        fm = jnp.ones(F, bool)
        vp = make_voting_grower(mesh8, num_leaves=L, num_bins=B, params=p,
                                top_k=2)
        t = vp(shard_rows(mesh8, binned), shard_rows(mesh8, vals), fm, nb, na)
        assert int(t.num_leaves) > 2
        # informative feature must still be found despite vote compression
        assert int(np.asarray(t.split_feature)[0]) == 2
        bc = np.bincount(np.asarray(t.leaf_of_row),
                         minlength=int(t.num_leaves))
        np.testing.assert_allclose(bc[:int(t.num_leaves)],
                                   np.asarray(t.leaf_count)[:int(t.num_leaves)])


class TestFeatureParallel:
    def test_matches_serial(self, mesh_feat):
        binned, vals = _data(n=2048, f=8)
        F, B, L = binned.shape[1], 16, 8
        p = SplitParams(min_data_in_leaf=5)
        nb = jnp.full(F, B, jnp.int32)
        na = jnp.full(F, -1, jnp.int32)
        fm = jnp.ones(F, bool)

        serial = make_grower(num_leaves=L, num_bins=B, params=p)
        t_ser = serial(jnp.asarray(binned), jnp.asarray(vals), fm, nb, na)

        fp = make_fp_grower(mesh_feat, num_features=F, num_leaves=L,
                            num_bins=B, params=p)
        t_fp = fp(jnp.asarray(binned), jnp.asarray(vals), fm, nb, na, na)

        assert int(t_ser.num_leaves) == int(t_fp.num_leaves) > 2
        for k in ("split_feature", "threshold_bin", "left_child", "right_child"):
            np.testing.assert_array_equal(np.asarray(getattr(t_ser, k)),
                                          np.asarray(getattr(t_fp, k)),
                                          err_msg=k)
        np.testing.assert_allclose(np.asarray(t_ser.leaf_value),
                                   np.asarray(t_fp.leaf_value),
                                   rtol=1e-4, atol=1e-5)
