"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh, the same way the reference
simulates multi-machine training with localhost sockets
(/root/reference/tests/distributed/_test_distributed.py) — see SURVEY.md §4.

NOTE on platform forcing: the environment's sitecustomize imports jax and
registers the TPU (axon) PJRT plugin at interpreter start, freezing
``jax_platforms``; setting the JAX_PLATFORMS env var here is too late.
``jax.config.update`` below is the supported override and prevents the TPU
backend from initializing during tests (the TPU tunnel is exclusive and
slow to claim).
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite's wall clock is dominated by
# XLA compiles (hundreds of jit variants across growers / shapes), and
# every run used to pay them from scratch.  Machine-keyed (this
# environment migrates between heterogeneous hosts and XLA:CPU AOT
# entries are machine-specific) — see utils/compile_cache.py and
# docs/Testing.md for the measured cost of getting this wrong.
from lightgbm_tpu.utils.compile_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()

import faulthandler  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Per-test hang watchdog: a wedged collective / device claim used to eat
# the whole tier-1 870 s budget silently (the outer `timeout -k 10 870`
# kills pytest with NO traceback).  Arm a faulthandler dump per test: any
# test still running after this many seconds dumps all-thread stacks to
# stderr (repeating, non-fatal) so the hang is attributable to a line of
# code.  Same mechanism as lightgbm_tpu.utils.resilience.Watchdog — the
# timer is process-global, so a Watchdog used INSIDE a test takes over
# until it exits (its cancel also clears this per-test timer; acceptable).
FAULTHANDLER_TEST_TIMEOUT_S = 300.0


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_protocol(item, nextitem):
    faulthandler.dump_traceback_later(FAULTHANDLER_TEST_TIMEOUT_S,
                                      repeat=True)
    yield
    faulthandler.cancel_dump_traceback_later()

# Skip budget (VERDICT r2: a regressing guard skipped instead of failing
# and nobody noticed).  On the standard harness — virtual 8-device CPU
# mesh, full toolchain — exactly two skips are expected: the
# graphviz-executable plotting skip and the R-binding smoke test
# (test_r_binding.py, needs Rscript; its shim-compile/link guard still
# RUNS without R).  Every new skip must either be fixed or the budget
# consciously raised here with a comment.
SKIP_BUDGET = 2
_skips: list = []


def pytest_runtest_logreport(report):
    if report.skipped:
        _skips.append(f"{report.nodeid}: {report.longrepr[2] if isinstance(report.longrepr, tuple) else report.longrepr}")


def pytest_sessionfinish(session, exitstatus):
    # only enforce on the standard full-suite harness (virtual CPU mesh);
    # single-chip TPU runs legitimately skip the 8-device tests
    if jax.default_backend() != "cpu" or len(jax.devices()) < 8:
        return
    if session.config.args and any("::" in a for a in session.config.args):
        return                       # targeted runs, not the full suite
    if len(_skips) > SKIP_BUDGET and exitstatus == 0:
        lines = "\n  ".join(_skips)
        print(f"\nERROR: {len(_skips)} skipped tests exceed the skip "
              f"budget ({SKIP_BUDGET}):\n  {lines}", flush=True)
        session.exitstatus = 1


@pytest.fixture(scope="session")
def rng():
    return np.random.RandomState(42)


@pytest.fixture(scope="session")
def binary_data():
    """Synthetic binary classification set (sklearn-style, utils.py analog)."""
    rs = np.random.RandomState(0)
    n, f = 4000, 20
    x = rs.randn(n, f)
    logit = x[:, 0] * 1.5 - x[:, 1] + 0.5 * x[:, 2] * x[:, 3] + 0.3 * rs.randn(n)
    y = (logit > 0).astype(np.float32)
    return x, y


@pytest.fixture(scope="session")
def regression_data():
    rs = np.random.RandomState(1)
    n, f = 4000, 15
    x = rs.randn(n, f)
    y = (2.0 * x[:, 0] + x[:, 1] ** 2 - 1.5 * x[:, 2] + 0.1 * rs.randn(n)).astype(np.float32)
    return x, y
