"""Vectorized best-split search over histograms.

Replaces the reference's per-feature sequential threshold scan
``FeatureHistogram::FindBestThresholdSequentially``
(/root/reference/src/treelearner/feature_histogram.hpp:856-1050) and the CUDA
``FindBestSplitsForLeafKernel``
(/root/reference/src/treelearner/cuda/cuda_best_split_finder.cu:603): the
two directional scans (missing->right / missing->left) become cumulative
sums + masked argmax over a ``[2, F, B]`` gain tensor — branchless, all
features at once on the VPU.

Gain / leaf-output math follows feature_histogram.hpp:737-854
(``ThresholdL1``, ``CalculateSplittedLeafOutput``, ``GetSplitGains``) with
lambda_l1 / lambda_l2 / max_delta_step / path_smooth.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

kEpsilon = 1e-15
kMinScore = -jnp.inf


def dequantize_hist(hist: jax.Array, scales: jax.Array) -> jax.Array:
    """Quantized-training dequantization AT SPLIT-SCAN TIME: an exact
    int32 histogram (or [3] leaf-total vector) whose trailing axis is
    the (grad, hess, count) channel block becomes the real-valued f32
    tensor the gain/leaf-value math below consumes.

    The int32 accumulation (ops/histogram.py integer path) is exact, so
    this one widening multiply is the ONLY place quantization noise
    enters the split scan — totals and every cumsum derived from them
    are deterministic integers times the iteration's shared scale, and
    split selection is bit-reproducible across serial and every
    sharded learner (the f32 path only guarantees that per compiled
    program).  ``scales`` [3] broadcasts over a 3-channel trailing axis
    and tiles over the split_batch 3K channel blocks.

    A trace-time flop/byte note (obs/flops.py "dequant") is recorded by
    the grower at its call sites, not here — this helper also runs on
    tiny [3] totals where a per-call note would misattribute shapes.
    """
    c = hist.shape[-1]
    s = scales
    if c != s.shape[-1]:            # split_batch: 3K channels tile [3]
        s = jnp.tile(s, c // s.shape[-1])
    return hist.astype(jnp.float32) * s


class SplitParams(NamedTuple):
    """Static split hyperparameters (hashable; closed over at jit time)."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    path_smooth: float = 0.0
    # categorical (feature_histogram.hpp:278 FindBestThresholdCategoricalInner)
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    min_data_per_group: int = 100


class SplitResult(NamedTuple):
    """Per-leaf best split (SplitInfo analog, split_info.hpp:55).

    The decision is uniformly "go left iff bin_rank[bin] <= threshold":
    numerical splits use the identity rank (bin order), categorical splits
    the gradient-ratio ordering of the chosen subset — one partition
    predicate serves both (tree.h Numerical/CategoricalDecision collapse).
    """
    gain: jax.Array          # f32; <=0 / -inf when invalid
    feature: jax.Array       # int32 (used-feature slot)
    threshold: jax.Array     # int32 rank threshold
    default_left: jax.Array  # bool
    left_sum: jax.Array      # [3] (g, h, count)
    right_sum: jax.Array     # [3]
    left_output: jax.Array   # f32 leaf output
    right_output: jax.Array  # f32
    is_cat: jax.Array        # bool
    bin_rank: jax.Array      # [B] int32 rank of each bin in the decision order


def globalize_feature(res: SplitResult, gfid: jax.Array) -> SplitResult:
    """Map a chunk-local winning feature slot back to its GLOBAL feature
    id via the owner-shard slot map ``gfid`` [f_local] (-1 = padding).

    Used by the sharded learners (feature-parallel contiguous slices map
    with an offset instead; the data-parallel owner-shard chunks are
    non-contiguous under EFB, hence the explicit map).  A pad slot can
    only win when every candidate is invalid (gain -inf), in which case
    the serial scan's argmax also degenerates to slot 0 — clamping to
    feature 0 keeps the two bit-identical."""
    return res._replace(feature=jnp.maximum(jnp.take(gfid, res.feature), 0))


def gather_best(res: SplitResult, axis_name: str) -> SplitResult:
    """``SyncUpGlobalBestSplit`` (parallel_tree_learner.h:191): allgather
    each shard's best candidate over ``axis_name`` and keep the winner.
    This is the entire cross-shard communication of a split decision — a
    few scalars plus the [B] decision-rank vector, never a histogram.
    ``res.feature`` must already be a GLOBAL feature id (see
    ``globalize_feature``).

    Exact-gain ties across shards break toward the LOWEST GLOBAL FEATURE
    ID, matching the serial scan's flat argmax — lowest-shard-index would
    instead follow EFB group order, which need not follow feature order
    (duplicated columns bundled into different groups would then split on
    a different feature than serial).  Within a shard the local argmax
    already reproduces serial's (dir, feature, bin) order."""
    g = lax.all_gather(res, axis_name)       # one collective: pytree [S, ...]
    tie = g.gain == jnp.max(g.gain)
    win = jnp.argmin(jnp.where(tie, g.feature, jnp.int32(2 ** 30)))
    return jax.tree.map(lambda a: a[win], g)


def threshold_l1(s: jax.Array, l1: float) -> jax.Array:
    """ThresholdL1 (feature_histogram.hpp:751)."""
    if l1 <= 0.0:
        return s
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def monotone_penalty_factor(penalty: float, depth):
    """ComputeMonotoneSplitGainPenalty (monotone_constraints.hpp:355):
    depth-based gain de-rating applied to monotone features.  ``depth``
    may be a traced array or a host scalar; the single definition keeps
    the masked and partitioned learners bit-consistent."""
    pen = float(penalty)
    d = jnp.asarray(depth, jnp.float32)
    return jnp.where(
        pen >= d + 1.0, 1e-15,
        jnp.where(pen <= 1.0, 1.0 - pen / (2.0 ** d) + 1e-15,
                  1.0 - 2.0 ** (pen - 1.0 - d) + 1e-15))


def leaf_output(sum_g, sum_h, p: SplitParams, parent_output=None,
                count=None):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:742-764): raw
    Newton step -> L1 threshold -> max_delta_step clamp -> path smoothing
    (in the reference's order: the clamp applies to the RAW output, then
    the smoothed blend may exceed it toward the parent).

    Path smoothing blends with the leaf's DATA COUNT ``count``
    (feature_histogram.hpp:760-761 ``num_data``), not its hessian weight —
    they differ for every non-unit-hessian objective."""
    num = -threshold_l1(sum_g, p.lambda_l1)
    denom = sum_h + p.lambda_l2
    out = num / jnp.maximum(denom, kEpsilon)
    if p.max_delta_step > 0.0:
        out = jnp.clip(out, -p.max_delta_step, p.max_delta_step)
    if p.path_smooth > 0.0 and parent_output is not None:
        # ret * (n/s)/(n/s + 1) + parent/(n/s + 1)
        n_data = sum_h if count is None else count
        smooth_w = n_data / (n_data + p.path_smooth)
        out = out * smooth_w + parent_output * (1.0 - smooth_w)
    return out


def leaf_gain(sum_g, sum_h, p: SplitParams, parent_output=None, count=None):
    """GetLeafGain (feature_histogram.hpp:790-820): gain of a leaf with the
    (possibly clipped/smoothed) optimal output."""
    if p.max_delta_step <= 0.0 and p.path_smooth <= 0.0:
        t = threshold_l1(sum_g, p.lambda_l1)
        return t * t / jnp.maximum(sum_h + p.lambda_l2, kEpsilon)
    out = leaf_output(sum_g, sum_h, p, parent_output, count)
    tg = threshold_l1(sum_g, p.lambda_l1)
    # GetLeafGainGivenOutput: -(2*G̃*w + (H+λ2)*w²)
    return -(2.0 * tg * out + (sum_h + p.lambda_l2) * out * out)


def _numerical_candidates(hist, total, num_bin, na_bin, feature_mask,
                          params: SplitParams, parent_out, rand_bin=None):
    """Gain tensor [2, F, B] over (missing-direction, feature, threshold).

    rand_bin: [F] int32 or None — extra_trees mode (extremely randomized
    trees, feature_histogram.hpp:116): each feature is only allowed to
    split at its one pre-drawn random threshold bin.
    """
    f, b, _ = hist.shape
    cum = jnp.cumsum(hist, axis=1)                      # [F, B, 3] inclusive
    bins = jnp.arange(b, dtype=jnp.int32)

    has_na = (na_bin >= 0)
    na_vals = jnp.where(has_na[:, None],
                        jnp.take_along_axis(
                            hist, jnp.maximum(na_bin, 0)[:, None, None]
                            .repeat(3, axis=2), axis=1)[:, 0, :],
                        0.0)                            # [F, 3]

    # dir 0: missing -> right. left(b) = cum[b]  (na bin == last, never left)
    # dir 1: missing -> left.  left(b) = cum[b] + hist[na]
    left0 = cum
    left1 = cum + na_vals[:, None, :]
    lefts = jnp.stack([left0, left1], axis=0)           # [2, F, B, 3]
    rights = total[None, None, None, :] - lefts

    gl, hl, cl = lefts[..., 0], lefts[..., 1], lefts[..., 2]
    gr, hr, cr = rights[..., 0], rights[..., 1], rights[..., 2]

    gain_l = leaf_gain(gl, hl, params, parent_out, cl)
    gain_r = leaf_gain(gr, hr, params, parent_out, cr)
    # gain_shift smooths too (BeforeNumercal, feature_histogram.hpp:104-105
    # passes num_data + parent_output into the leaf's own GetLeafGain)
    gain_shift = leaf_gain(total[0], total[1], params, parent_out, total[2])
    split_gain = gain_l + gain_r - (gain_shift + params.min_gain_to_split)

    # validity masks (FindBestThresholdSequentially early-continue conditions)
    md = float(params.min_data_in_leaf) - 0.5
    mh = params.min_sum_hessian_in_leaf
    # threshold range: b <= num_bin - 2 excluding the NaN bin from the scan
    max_t = jnp.where(has_na, num_bin - 2, num_bin - 2)  # na bin = num_bin-1
    valid = (bins[None, None, :] <= max_t[None, :, None])
    valid &= feature_mask[None, :, None]
    valid &= (cl >= md) & (cr >= md)
    valid &= (hl >= mh) & (hr >= mh)
    valid &= split_gain > kEpsilon
    # dir-1 scan only exists for features with a NaN bin
    valid &= jnp.stack([jnp.ones((f, b), bool),
                        jnp.broadcast_to(has_na[:, None], (f, b))], axis=0)
    if rand_bin is not None:
        valid &= (bins[None, None, :] == rand_bin[None, :, None])

    gains = jnp.where(valid, split_gain, kMinScore)     # [2, F, B]
    return gains, lefts


def _categorical_candidates(hist, total, num_bin, cat_mask,
                            params: SplitParams, parent_out):
    """Categorical subset candidates (FindBestThresholdCategoricalInner,
    feature_histogram.hpp:278): one-vs-rest when few categories, else a
    two-direction scan over bins sorted by grad/hess ratio.

    Returns (gains [3, F, B], lefts [3, F, B, 3], orders [3, F, B]):
    scan modes = (one-vs-rest, ratio-ascending, ratio-descending); ``orders``
    maps scan position -> bin id.
    """
    f, b, _ = hist.shape
    pcat = params._replace(lambda_l2=params.lambda_l2 + params.cat_l2)
    g, h, c = hist[..., 0], hist[..., 1], hist[..., 2]
    used = c >= max(0.5, float(params.min_data_per_group) - 0.5)
    n_used = used.sum(axis=1)                            # [F]
    positions = jnp.arange(b, dtype=jnp.int32)

    # ratio ordering (cat_smooth regularized), unused bins pushed last
    ratio = g / (h + params.cat_smooth)
    big = jnp.float32(1e30)
    key_asc = jnp.where(used, ratio, big)
    order_asc = jnp.argsort(key_asc, axis=1).astype(jnp.int32)    # [F, B]
    key_desc = jnp.where(used, -ratio, big)
    order_desc = jnp.argsort(key_desc, axis=1).astype(jnp.int32)
    order_ovr = jnp.broadcast_to(positions[None, :], (f, b)).astype(jnp.int32)
    orders = jnp.stack([order_ovr, order_asc, order_desc])         # [3, F, B]

    hist3 = jnp.broadcast_to(hist[None], (3, f, b, 3))
    sorted_hist = jnp.take_along_axis(hist3, orders[..., None], axis=2)
    cum = jnp.cumsum(sorted_hist, axis=2)                # [3, F, B, 3]
    # mode 0 = one-vs-rest: left = single bin at this position
    lefts = cum.at[0].set(sorted_hist[0])
    rights = total[None, None, None, :] - lefts

    gl, hl, cl = lefts[..., 0], lefts[..., 1], lefts[..., 2]
    gr, hr, cr = rights[..., 0], rights[..., 1], rights[..., 2]
    gain_l = leaf_gain(gl, hl, pcat, parent_out, cl)
    gain_r = leaf_gain(gr, hr, pcat, parent_out, cr)
    gain_shift = leaf_gain(total[0], total[1], pcat, parent_out, total[2])
    split_gain = gain_l + gain_r - (gain_shift + params.min_gain_to_split)

    md = float(params.min_data_in_leaf) - 0.5
    mh = params.min_sum_hessian_in_leaf
    pos = positions[None, None, :]
    few = (n_used <= params.max_cat_to_onehot)[None, :, None]      # [1, F, 1]
    # mode 0 valid at positions whose bin is used; modes 1-2 at prefix
    # lengths 1..min(max_cat_threshold, n_used-1)
    used3 = jnp.take_along_axis(jnp.broadcast_to(used[None], (3, f, b)),
                                orders, axis=2)
    valid = jnp.zeros((3, f, b), bool)
    valid = valid.at[0].set(few[0] & used3[0])
    k_max = jnp.minimum(params.max_cat_threshold,
                        n_used - 1)[None, :, None]                 # prefix cap
    prefix_ok = (pos < k_max) & (~few)
    valid = valid.at[1].set(prefix_ok[0] & used3[1])
    valid = valid.at[2].set(prefix_ok[0] & used3[2])
    valid &= cat_mask[None, :, None]
    valid &= (cl >= md) & (cr >= md)
    valid &= (hl >= mh) & (hr >= mh)
    valid &= split_gain > kEpsilon

    gains = jnp.where(valid, split_gain, kMinScore)
    return gains, lefts, orders


def _monotone_adjust(gains, lefts, total, mono, out_lo, out_hi, dir_axis,
                     params: SplitParams, parent_out, mono_bounds=None):
    """Monotone-constraint filter ('basic' method,
    monotone_constraints.hpp BasicLeafConstraints): clamp candidate child
    outputs to the leaf's allowed range, recompute gains with the clamped
    outputs (GetLeafGainGivenOutput), and invalidate splits whose direction
    violates the feature's monotonicity.

    mono_bounds ('advanced' method, AdvancedLeafConstraints analog):
    optional (lo_l, hi_l, lo_r, hi_r) per-(feature, threshold-bin) [F, B]
    bound tensors — the allowed range of each CHILD as a function of the
    candidate threshold, so a split is only constrained by opposite
    leaves whose region actually overlaps that child's region."""
    rights = total[None, None, None, :] - lefts
    out_l = leaf_output(lefts[..., 0], lefts[..., 1], params, parent_out,
                        lefts[..., 2])
    out_r = leaf_output(rights[..., 0], rights[..., 1], params, parent_out,
                        rights[..., 2])
    if mono_bounds is not None:
        lo_l, hi_l, lo_r, hi_r = (b[None] for b in mono_bounds)  # [1,F,B]
        cl_l = jnp.clip(out_l, lo_l, hi_l)
        cl_r = jnp.clip(out_r, lo_r, hi_r)
    else:
        cl_l = jnp.clip(out_l, out_lo, out_hi)
        cl_r = jnp.clip(out_r, out_lo, out_hi)

    def gain_given(sums, out):
        tg = threshold_l1(sums[..., 0], params.lambda_l1)
        return -(2.0 * tg * out + (sums[..., 1] + params.lambda_l2) * out * out)

    mono_f = mono[None, :, None]                       # broadcast over dirs/bins
    was_valid = gains > kMinScore
    clamped = (cl_l != out_l) | (cl_r != out_r)
    new_gain = (gain_given(lefts, cl_l) + gain_given(rights, cl_r)
                - (leaf_gain(total[0], total[1], params)
                   + params.min_gain_to_split))
    gains = jnp.where(was_valid & clamped, new_gain, gains)
    ok = jnp.where(mono_f > 0, cl_l <= cl_r,
                   jnp.where(mono_f < 0, cl_l >= cl_r, True))
    return jnp.where(was_valid & ok & (gains > kEpsilon), gains, kMinScore)


def find_best_split(hist: jax.Array, total: jax.Array, num_bin: jax.Array,
                    na_bin: jax.Array, feature_mask: jax.Array,
                    params: SplitParams, parent_output: jax.Array = None,
                    is_cat: jax.Array = None, mono: jax.Array = None,
                    out_lo: jax.Array = None, out_hi: jax.Array = None,
                    gain_penalty: jax.Array = None,
                    gain_scale: jax.Array = None,
                    rand_bin: jax.Array = None,
                    mono_bounds=None) -> SplitResult:
    """Best split for one leaf across numerical and categorical features.

    hist:         [F, B, 3] f32 — per-feature histograms (g, h, count)
    total:        [3] parent aggregates
    num_bin:      [F] int32 valid bin count per feature
    na_bin:       [F] int32 NaN-bin index or -1
    feature_mask: [F] bool — feature_fraction / interaction constraint mask
    is_cat:       [F] bool — categorical feature flags (None = none)
    mono:         [F] int32 — monotone constraints -1/0/+1 (None = none)
    out_lo/out_hi: scalar allowed output range of this leaf (monotone)
    """
    f, b, _ = hist.shape
    # static FLOP/byte note from the traced shapes (obs/flops.py): one
    # candidate leaf's scan — fires at trace time only; under the
    # grower's vmap the recorded unit is the per-leaf scan
    from ..obs.flops import note_traced, split_scan_flops_bytes
    note_traced("split_scan", *split_scan_flops_bytes(f, b, n_leaves=1),
                phase="grow")
    parent_out = leaf_output(total[0], total[1], params) \
        if parent_output is None else parent_output

    num_mask = feature_mask if is_cat is None else (feature_mask & (~is_cat))
    ngains, nlefts = _numerical_candidates(hist, total, num_bin, na_bin,
                                           num_mask, params, parent_out,
                                           rand_bin)
    if mono is not None:
        ngains = _monotone_adjust(ngains, nlefts, total, mono, out_lo, out_hi,
                                  0, params, parent_out, mono_bounds)
    if gain_scale is not None:
        # per-feature multiplicative gain scale: monotone_penalty
        # (ComputeMonotoneSplitGainPenalty, monotone_constraints.hpp:355)
        # and/or feature_contri (feature_histogram.hpp gain *= contri)
        ngains = jnp.where(ngains > kMinScore,
                           ngains * gain_scale[None, :, None], ngains)
    if gain_penalty is not None:
        # CEGB per-feature acquisition penalty subtracted from candidate
        # gains (cost_effective_gradient_boosting.hpp:70-78 DeltaGain)
        pen = gain_penalty[None, :, None]
        ngains = jnp.where(ngains > kMinScore,
                           jnp.where(ngains - pen > kEpsilon,
                                     ngains - pen, kMinScore), ngains)
    nflat = ngains.reshape(-1)
    nbest = jnp.argmax(nflat)
    nbest_gain = nflat[nbest]

    if is_cat is not None:
        cat_mask = feature_mask & is_cat
        cgains, clefts, corders = _categorical_candidates(
            hist, total, num_bin, cat_mask, params, parent_out)
        if gain_scale is not None:
            cgains = jnp.where(cgains > kMinScore,
                               cgains * gain_scale[None, :, None], cgains)
        if gain_penalty is not None:
            cpen = gain_penalty[None, :, None]
            cgains = jnp.where(cgains > kMinScore,
                               jnp.where(cgains - cpen > kEpsilon,
                                         cgains - cpen, kMinScore), cgains)
        cflat = cgains.reshape(-1)
        cbest = jnp.argmax(cflat)
        cbest_gain = cflat[cbest]
    else:
        cbest_gain = jnp.float32(kMinScore)

    use_cat = (is_cat is not None) and True
    iota_rank = jnp.arange(b, dtype=jnp.int32)

    def build_numerical():
        best_dir = nbest // (f * b)
        rem = nbest % (f * b)
        best_f = (rem // b).astype(jnp.int32)
        best_b = (rem % b).astype(jnp.int32)
        left_sum = nlefts[best_dir, best_f, best_b]
        return (nbest_gain, best_f, best_b, best_dir == 1, left_sum,
                jnp.bool_(False), iota_rank)

    if is_cat is None:
        g_, f_, t_, d_, ls_, ic_, rank_ = build_numerical()
    else:
        def build_categorical():
            mode = cbest // (f * b)
            rem = cbest % (f * b)
            best_f = (rem // b).astype(jnp.int32)
            pos = (rem % b).astype(jnp.int32)
            left_sum = clefts[mode, best_f, pos]
            order = corders[mode, best_f]                 # [B] pos -> bin
            rank = jnp.argsort(order).astype(jnp.int32)   # bin -> pos
            # one-vs-rest: single bin at `pos` goes left -> rank 0 only
            rank_ovr = jnp.where(iota_rank == order[pos], 0, b).astype(jnp.int32)
            rank = jnp.where(mode == 0, rank_ovr, rank)
            thr = jnp.where(mode == 0, 0, pos).astype(jnp.int32)
            return (cbest_gain, best_f, thr, jnp.bool_(False), left_sum,
                    jnp.bool_(True), rank)

        take_num = nbest_gain >= cbest_gain
        nvals = build_numerical()
        cvals = build_categorical()
        g_, f_, t_, d_, ls_, ic_, rank_ = jax.tree.map(
            lambda a, c: jnp.where(take_num, a, c), nvals, cvals)

    right_sum = total - ls_
    # categorical splits regularize leaf outputs with l2 + cat_l2
    pcat = params._replace(lambda_l2=params.lambda_l2 + params.cat_l2)
    lo = jnp.where(ic_,
                   leaf_output(ls_[0], ls_[1], pcat, parent_out, ls_[2]),
                   leaf_output(ls_[0], ls_[1], params, parent_out, ls_[2]))
    ro = jnp.where(ic_,
                   leaf_output(right_sum[0], right_sum[1], pcat, parent_out,
                               right_sum[2]),
                   leaf_output(right_sum[0], right_sum[1], params, parent_out,
                               right_sum[2]))
    if mono is not None:
        if mono_bounds is not None:
            lo_l, hi_l, lo_r, hi_r = mono_bounds
            # categorical winners: t_ is a category rank, not an interval
            # threshold, and the children are not f_-intervals — clamp
            # with the tightest bound over ALL thresholds of the feature
            # (conservative).  If that intersection is empty (mutually
            # contradictory neighbor bounds), no output satisfies every
            # constraint; keep the interval well-ordered so clip stays
            # deterministic (lower bound wins) instead of returning the
            # violated hi.
            l_lo = jnp.where(ic_, jnp.max(lo_l[f_]), lo_l[f_, t_])
            l_hi = jnp.where(ic_,
                             jnp.maximum(jnp.min(hi_l[f_]), jnp.max(lo_l[f_])),
                             hi_l[f_, t_])
            r_lo = jnp.where(ic_, jnp.max(lo_r[f_]), lo_r[f_, t_])
            r_hi = jnp.where(ic_,
                             jnp.maximum(jnp.min(hi_r[f_]), jnp.max(lo_r[f_])),
                             hi_r[f_, t_])
            lo = jnp.clip(lo, l_lo, l_hi)
            ro = jnp.clip(ro, r_lo, r_hi)
        else:
            lo = jnp.clip(lo, out_lo, out_hi)
            ro = jnp.clip(ro, out_lo, out_hi)
    return SplitResult(
        gain=g_, feature=f_.astype(jnp.int32),
        threshold=t_.astype(jnp.int32), default_left=d_,
        left_sum=ls_, right_sum=right_sum,
        left_output=lo.astype(jnp.float32),
        right_output=ro.astype(jnp.float32),
        is_cat=ic_, bin_rank=rank_.astype(jnp.int32),
    )
