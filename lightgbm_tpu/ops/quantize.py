"""Low-precision grad/hess packing for quantized histogram training.

ROADMAP item 3 / docs/Quantized-Training.md: the histogram contraction
(ops/histogram.py) is memory-bound — it drags f32 (grad, hess, weight)
through HBM on every pass (the roofline ledger, obs/flops.py, proves
where).  The fix bit-serial GBDT accelerators exploit ("Booster: An
Accelerator for Gradient Boosting Decision Trees", arXiv:2011.02022)
and upstream LightGBM later shipped as quantized training: pack the
per-row accumulands to int8/int16 with ONE shared scale per channel per
boosting iteration, accumulate **exact int32** histograms, and
dequantize only when the split scan needs real-valued gains
(ops/split.py ``dequantize_hist``).

Scheme
------
- scale: per-channel ``max|v| / qmax`` over ALL rows of the iteration
  (a traced scalar — no host read; distributed learners ``pmax`` the
  [3] vector so every shard quantizes identically).
- rounding: **stochastic** by default — ``floor(v/s + u)`` with
  ``u ~ U[0,1)`` drawn from a counter-based hash of (GLOBAL row id,
  channel, iteration, seed).  Keying by the global row id (not the
  shard-local position) makes ``tree_learner=data`` quantize each row
  exactly as serial does, and keying by the iteration makes
  crash+resume replay the SAME rounding stream as a straight run
  (snapshot resume fast-forwards the iteration offset, models/gbdt.py
  ``set_resume_state``).  ``quant_round=nearest`` is the deterministic
  biased alternative.
- accumulation: the one-hot contraction runs on integer operands with
  ``preferred_element_type=int32`` — int32 addition is exact and
  order-independent, so the quant path's dp==serial histogram identity
  is BITWISE (stronger than the f32 path, where reduction order is
  only fixed per compiled program).

Zero rows stay zero under both roundings (``floor(0 + u) = 0`` for
``u < 1``), so out-of-bag / padded rows never leak into histograms.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


class QuantSpec(NamedTuple):
    """Static quantized-training configuration (hashable: part of the
    grower's process-level memo key, grower.py ``_grower_key``)."""
    bits: int = 8            # 8 -> int8 lanes, 16 -> int16
    stochastic: bool = True  # stochastic (unbiased) vs nearest rounding
    seed: int = 0            # folded into the per-iteration rounding key

    @property
    def qmax(self) -> int:
        return (1 << (self.bits - 1)) - 1

    @property
    def dtype(self):
        return jnp.int8 if self.bits == 8 else jnp.int16

    @property
    def itemsize(self) -> int:
        return 1 if self.bits == 8 else 2


def _fmix32(x: jnp.ndarray) -> jnp.ndarray:
    """murmur3 finalizer on uint32 lanes — the counter-based RNG core.
    jax.random.fold_in per row would be orders of magnitude slower and
    could not be sliced by global row id across shards."""
    x = x.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    return x ^ (x >> 16)


def counter_uniform(row_id: jnp.ndarray, n_chan: int, iter_key,
                    seed) -> jnp.ndarray:
    """[N, n_chan] U[0,1) keyed by (global row id, channel, iteration,
    seed) — identical values for a row regardless of which shard holds
    it.  Top 24 bits only, so the f32 conversion is exact and the
    result is strictly < 1 (floor(x + u) can never over-round).
    ``seed`` may be a traced int32 (the fleet's per-member rounding
    seed rides the vmapped member axis); the uint32 product below is
    mod-2^32 identical to the historic host-side ``int(seed) *
    2654435761 & 0xFFFFFFFF`` expression."""
    if isinstance(seed, (int, np.integer)):
        seed = np.uint32(int(seed) & 0xFFFFFFFF)
    k = _fmix32(jnp.asarray(iter_key).astype(jnp.uint32)
                ^ (jnp.asarray(seed).astype(jnp.uint32)
                   * jnp.uint32(2654435761)))
    chan = jnp.arange(n_chan, dtype=jnp.uint32)
    h = _fmix32(row_id.astype(jnp.uint32)[:, None]
                * jnp.uint32(0x9E3779B9)
                ^ (chan[None, :] * jnp.uint32(0x85EBCA6B)) ^ k)
    return (h >> 8).astype(jnp.float32) * jnp.float32(2.0 ** -24)


def quant_scales(vals: jnp.ndarray, qmax: int,
                 floor: float = 1e-30) -> jnp.ndarray:
    """Per-channel shared scale [C] f32: ``max|v| / qmax`` (floored so
    an all-zero channel dequantizes to exact zeros instead of NaN).
    Distributed learners must ``pmax`` this vector across shards before
    quantizing (grower.py ``scale_reduce`` hook) so the shared scale is
    GLOBAL — the dp==serial identity depends on it."""
    m = jnp.max(jnp.abs(vals), axis=0)
    return jnp.maximum(m, jnp.float32(floor)) / jnp.float32(int(qmax))


def quantize_stack(vals: jnp.ndarray, scales: jnp.ndarray,
                   spec: QuantSpec, iter_key,
                   row_offset, seed=None) -> jnp.ndarray:
    """[N, C] f32 -> [N, C] int8/int16 with the iteration's shared
    scales.  ``row_offset`` is this shard's global row offset (0 for
    serial / replicated-row learners).  ``seed`` (optional, possibly
    traced) overrides ``spec.seed`` — the fleet trainer's per-member
    rounding seed, which cannot live in the static spec."""
    x = vals / scales[None, :]
    if spec.stochastic:
        rows = jnp.asarray(row_offset, jnp.int32) \
            + jnp.arange(vals.shape[0], dtype=jnp.int32)
        u = counter_uniform(rows, vals.shape[1], iter_key,
                            spec.seed if seed is None else seed)
        q = jnp.floor(x + u)
    else:
        q = jnp.round(x)
    qmax = spec.qmax
    return jnp.clip(q, -qmax, qmax).astype(spec.dtype)
