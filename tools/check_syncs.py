"""Sync lint: flag raw host-sync calls in the library hot paths.

PROFILE.md measured ~67 ms per blocking host round trip on a tunneled
TPU — a stray ``jax.device_get`` / ``block_until_ready`` / ``.item()``
in the training path is a silent 60+ ms/iteration regression, and
``block_until_ready`` additionally *lies* on the axon backend (returns
with work still queued), so even intentional fences must go through
``obs.trace.fence``.  This lint keeps both properties true structurally:

- every raw sync call in ``lightgbm_tpu/`` (outside ``obs/trace.py``,
  the one module allowed to own the primitive) must be listed in
  ``tools/sync_allowlist.txt``;
- the allowlist pins (file, exact stripped source line), so MOVING a
  legitimate sync is cheap (re-pin) but ADDING one is a conscious act.

Comments and string literals are ignored (tokenize-based), so
documentation may mention the calls freely.

Run via the unified driver (``python tools/lint.py``; tier-1), or
standalone (``python tools/check_syncs.py``; exit 1 on findings), or
in-process (tests/test_observability.py calls ``find_raw_syncs``).
The parsing/stale-entry plumbing lives in ``tools/analyze/lintlib.py``,
shared with the retrace/race/purity lints.
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from analyze import lintlib                              # noqa: E402

REPO = lintlib.REPO
PACKAGE = lintlib.PACKAGE
ALLOWLIST = os.path.join(REPO, "tools", "sync_allowlist.txt")

# the module that owns the fence primitive; everything inside may sync
EXEMPT = {os.path.join("lightgbm_tpu", "obs", "trace.py")}

_SYNC_RE = re.compile(
    r"device_get\s*\(|block_until_ready\b|\.item\s*\(\s*\)")


def load_allowlist(path: str = ALLOWLIST) -> Set[Tuple[str, str]]:
    """Entries are ``relative/path.py | exact stripped source line``."""
    return {key for key, _ in lintlib.parse_pins(path, 2)}


def find_raw_syncs(root: str = PACKAGE,
                   allowlist_path: str = ALLOWLIST) -> List[str]:
    """All unallowlisted raw sync call sites, as
    ``path:lineno: stripped line`` strings (empty list = lint green).
    Also reports allowlist entries that no longer match anything, so
    the list cannot rot."""
    allow = load_allowlist(allowlist_path)
    used: Set[Tuple[str, str]] = set()
    findings: List[str] = []
    for path in lintlib.iter_py(root):
        rel = lintlib.rel_to_root(path, root)
        if rel in EXEMPT:
            continue
        for lineno, code in sorted(lintlib.code_lines(path).items()):
            if not _SYNC_RE.search(code):
                continue
            # the allowlist pins the ORIGINAL stripped line text
            with open(path) as f:
                stripped = f.read().splitlines()[lineno - 1].strip()
            key = (rel, stripped)
            if key in allow:
                used.add(key)
                continue
            findings.append(f"{rel}:{lineno}: {stripped}")
    findings.extend(lintlib.stale_pins(allow, used, "allowlist"))
    return findings


def main() -> int:
    findings = find_raw_syncs()
    if findings:
        print("sync lint: raw device_get/block_until_ready/.item() "
              "outside obs.trace.fence:", file=sys.stderr)
        for f in findings:
            print(f"  {f}", file=sys.stderr)
        print(f"\n{len(findings)} finding(s).  Route fences through "
              "lightgbm_tpu.obs.trace.fence, or pin a genuinely "
              "necessary sync in tools/sync_allowlist.txt",
              file=sys.stderr)
        return 1
    print("sync lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
