"""Static FLOP + HBM-byte accounting for the compute hot paths.

The compute-side mirror of ``obs/comm.py``'s trace-time static
accounting trick: "GPU-acceleration for Large-scale Tree Boosting"
(arXiv:1706.08359) and "Booster" (arXiv:2011.02022) justify their
kernels with op-level FLOP/byte budgets; here the same numbers are
derived STATICALLY from shapes, in two complementary channels that
share ONE set of formula functions:

1. ``note_traced(site, ...)`` — called as a Python side effect inside
   the traced bodies of the histogram contraction
   (``ops/histogram.py``), the split scan (``ops/split.py``), the
   grower's row partition (``grower.py``), the score update
   (``models/gbdt.py``) and the tree/forest traversals
   (``predict_device.py``).  Fires once per fresh jit trace (never per
   execution), records (flops, hbm_bytes) for the shapes actually
   traced, and overwrites idempotently on retrace — zero runtime cost,
   zero extra syncs.  ``traced_sites()`` is the process-wide view.

2. ``FlopLedger`` — the per-model site table the GBDT driver builds
   from its LOGICAL GLOBAL shapes (rows x features x bins, independent
   of sharding), so the accounting is deterministic, identical between
   ``tree_learner=data`` and serial, and non-empty even when a warm jit
   cache means nothing re-traces.  ``obs.ObsSession.record_flops``
   turns the site table into per-iteration ``flops.*`` counters, and
   ``obs/attrib.py`` joins them with the fenced phase spans into
   ``perf.*`` roofline keys.

FLOP conventions (documented so the numbers are comparable run to
run, not because the constants are exact):

- histogram: 2 FLOPs per multiply-add of the one-hot contraction —
  ``2 * C * N * F * Bp`` per full-N pass (the MXU useful work; padded
  bins included because the hardware computes them).  This is exactly
  the formula ``bench.py`` used to carry privately.
- split scan / partition / traversal: elementwise-op estimates with
  per-cell constants documented at each formula.

HBM-byte convention: bytes that MUST cross HBM for the op — operand
reads + result writes, assuming perfect fusion of generated
intermediates (the XLA behavior ``ops/histogram.py`` measured: the
one-hot never materializes).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
from typing import Dict, NamedTuple, Tuple


class FlopSite(NamedTuple):
    site: str         # stable call-site name, e.g. "hist"
    phase: str        # iteration phase the time lands in (grad/grow/score)
    flops: int        # FLOPs per execution of the site
    hbm_bytes: int    # HBM bytes per execution (reads + writes)
    cadence: str      # "step" (per grower loop step) | "iter" (per iter)


def padded_bins(num_bins: int) -> int:
    """The histogram kernel's padded bin axis (ops/histogram.py pads to
    a multiple of 64 so the merge is a free relayout) — the bin width
    FLOP accounting must use, because the hardware computes the pad."""
    return max(64, -(-int(num_bins) // 64) * 64)


# ---------------------------------------------------------------------------
# Formula functions — the ONE definition each call site and the driver
# ledger share.  All return (flops, hbm_bytes) ints.
# ---------------------------------------------------------------------------

def hist_flops_bytes(n_rows: int, n_cols: int, num_bins: int,
                     channels: int = 3,
                     binned_itemsize: int = 1,
                     vals_itemsize: int = 4,
                     slotted: bool = None) -> Tuple[int, int]:
    """One full-N one-hot-contraction histogram pass over ``n_cols``
    binned columns (features, or EFB groups): ``hist[c, f*Bp] +=
    vals[c, n] @ onehot[n, f*Bp]`` — 2 FLOPs per MAC.  ``channels`` is
    the accumulated channel count (3 strict; 3K for the split_batch
    multi-leaf contraction).  Bytes: binned matrix read + the
    (grad, hess, weight) vals read AT THEIR STORED WIDTH
    (``vals_itemsize``: 4 for f32, 1/2 for the int8/int16 quantized
    packing — the per-dtype accounting the quant_train acceptance
    instrument reads) + the [N] int32 slot vector when the TRUE
    multi-slot expansion is active (``slotted``: num_slots > 1, the
    kernel passes it explicitly; defaults to ``channels > 3``) +
    histogram write (f32 and int32 are both 4-byte lanes); the one-hot
    is generated in-registers (measured fused, ops/histogram.py).

    Accounting convention for the strict hist_overlap path: its 1-slot
    mask is the in-graph ENCODING of the masked pass it is
    byte-identical to — like the ``vals * mask`` temp it replaces
    (which this model never counted under the perfect-fusion rule),
    the [N] mask carries no operand bytes here.  Only a real K-way
    slot expansion (num_slots > 1) adds the slot read, which keeps the
    quantized-training byte-cut instrument (docs/Quantized-Training.md
    ≥2x pin) calibrated identically across overlap on/off.

    ``channels`` is the USEFUL (logical) width: the MXU lane padding
    wide widths take (C = 3K > 48 buckets to 128 multiples,
    utils/shapes.bucket_channels) is NOT useful work, so its MACs are
    excluded here and accounted separately by
    :func:`hist_pad_flops_bytes` under the MFU-excluded ``pad`` phase
    — MFU from this site stays an honest useful-work fraction.  The
    histogram WRITE does cross HBM at the padded width (the padded
    accumulator is materialized before the in-kernel slice), so the
    write term uses the padded channel count."""
    from ..utils.shapes import bucket_channels
    if slotted is None:
        slotted = int(channels) > 3
    bp = padded_bins(num_bins)
    flops = 2 * int(channels) * int(n_rows) * int(n_cols) * bp
    hbm = (int(n_rows) * int(n_cols) * int(binned_itemsize)
           + int(n_rows) * 3 * int(vals_itemsize)
           + (int(n_rows) * 4 if slotted else 0)
           + bucket_channels(int(channels)) * int(n_cols) * bp * 4)
    return flops, hbm


def hist_pad_flops_bytes(n_rows: int, n_cols: int, num_bins: int,
                         channels: int = 3) -> Tuple[int, int]:
    """The lane-pad MACs of one wide histogram pass: the hardware
    multiplies the padded ``bucket_channels(C) - C`` zero columns too
    (ops/histogram.py), but they produce no useful result — recorded
    as the ``hist_pad`` site under ``phase="pad"``, which
    ``obs/attrib.perf_summary`` reports per-site but EXCLUDES from
    phase/total aggregation so MFU never counts padding as achieved
    work.  Zero bytes: the pad's operand columns are generated
    in-registers and its write share is already in the ``hist`` site's
    padded write term."""
    from ..utils.shapes import bucket_channels
    c = int(channels)
    pad = bucket_channels(c) - c
    bp = padded_bins(num_bins)
    return 2 * pad * int(n_rows) * int(n_cols) * bp, 0


# elementwise ops per (direction, feature, bin) cell of the numerical
# split scan: cumsum add, left/right sums (6), two leaf gains (~2x8),
# gain shift + subtract (3), six validity masks + where (~12), argmax
# compare (1) — a documented estimate, stable across runs
SPLIT_SCAN_OPS_PER_CELL = 40
# bytes per (feature, bin) cell: hist read [3] f32 + the two-direction
# gain tensor write+read [2 x 2] f32
SPLIT_SCAN_BYTES_PER_CELL = 4 * (3 + 4)


def split_scan_flops_bytes(n_feat: int, num_bins: int,
                           n_leaves: int = 1) -> Tuple[int, int]:
    """Best-split scan over ``n_leaves`` candidate leaves: the two
    directional scans over the ``[2, F, B]`` gain tensor
    (ops/split.py find_best_split), VPU elementwise work."""
    cells = 2 * int(n_feat) * int(num_bins) * int(n_leaves)
    return (SPLIT_SCAN_OPS_PER_CELL * cells,
            SPLIT_SCAN_BYTES_PER_CELL
            * int(n_feat) * int(num_bins) * int(n_leaves))


# per-row ops of one partition pass: feature-column gather, NaN test,
# rank gather, threshold compare, leaf-id select
PARTITION_OPS_PER_ROW = 5


def partition_flops_bytes(n_rows: int,
                          binned_itemsize: int = 1) -> Tuple[int, int]:
    """One row-partition pass (grower do_split / super_step): gather
    the winning feature's column, compare, rewrite ``leaf_of_row``.
    Bytes: column read + leaf_of_row read+write (int32)."""
    n = int(n_rows)
    return (PARTITION_OPS_PER_ROW * n,
            n * int(binned_itemsize) + 2 * n * 4)


# ops per quantized value: divide by scale, hash-uniform draw (~2 mixes
# amortized), add, floor, clip — a documented estimate (ops/quantize.py)
QUANTIZE_OPS_PER_VAL = 5


def quantize_flops_bytes(n_rows: int,
                         out_itemsize: int = 1) -> Tuple[int, int]:
    """One per-iteration grad/hess/weight packing pass (quant_train,
    ops/quantize.py): the [N, 3] f32 stack read + the int8/int16 stack
    written; the scale reduction's [N, 3] read fuses with it."""
    n3 = 3 * int(n_rows)
    return (QUANTIZE_OPS_PER_VAL * n3,
            n3 * 4 + n3 * int(out_itemsize))


def dequant_flops_bytes(n_cols: int, num_bins: int,
                        n_leaves: int = 1) -> Tuple[int, int]:
    """Split-scan-time dequantization (ops/split.py dequantize_hist):
    one int32->f32 widening multiply per (leaf, column, bin, channel)
    cell; int32 read + f32 write, both 4-byte lanes."""
    cells = 3 * int(n_cols) * int(num_bins) * int(n_leaves)
    return cells, 2 * 4 * cells


def score_update_flops_bytes(n_rows: int) -> Tuple[int, int]:
    """Per-iteration score update: ``score += leaf_value[leaf_of_row]``
    — one gather + one add per row; leaf_of_row read, score
    read-modify-write."""
    n = int(n_rows)
    return 2 * n, n * 4 + 2 * n * 4


def eval_flops_bytes(n_rows: int, n_entries: int) -> Tuple[int, int]:
    """Traced in-scan metric evaluation (metrics.traced_metric_fn,
    models/gbdt.py train_superepoch): ~8 ops per (valid row, metric
    entry) — transform, clip, weight, pad-mask, reduce — charged against
    the TRAIN row count as a conservative stand-in (valid sets are
    usually smaller).  Bytes: score/label/weight reads per entry."""
    n = int(n_rows) * max(int(n_entries), 1)
    return 8 * n, 3 * 4 * n


# per (row, tree, level) ops of the binned traversal: node gather,
# feature gather, bin gather, NaN test, rank gather, compare,
# child select, finished-row select
TRAVERSE_OPS_PER_STEP = 8
# bytes per (row, tree, level): ~6 gathered int32 words
TRAVERSE_BYTES_PER_STEP = 6 * 4


def traverse_flops_bytes(n_rows: int, n_trees: int, steps: int,
                         n_feat: int,
                         binned_itemsize: int = 1) -> Tuple[int, int]:
    """Fixed-depth binned traversal (predict_device.py): every row
    walks ``n_trees`` trees one level per step for ``steps`` levels.
    Bytes add one read of the binned matrix."""
    per_level = int(n_rows) * int(n_trees) * int(steps)
    return (TRAVERSE_OPS_PER_STEP * per_level,
            TRAVERSE_BYTES_PER_STEP * per_level
            + int(n_rows) * int(n_feat) * int(binned_itemsize))


def device_bin_flops_bytes(n_rows: int, n_feat: int,
                           thr_bins: int) -> Tuple[int, int]:
    """On-device model-derived binning (predict_device
    ``bin_rows_device*``): one compare+accumulate per (row, feature,
    threshold-table slot) — the searchsorted-as-comparison-sum.
    Bytes: raw f32 rows read + threshold tables read + binned write
    (the binned tensor stays in registers when fused ahead of the
    traversal, but the write is counted as the op's result)."""
    n, f, b = int(n_rows), int(n_feat), int(thr_bins)
    flops = 2 * n * f * b
    hbm = n * f * 4 + f * b * 4 + n * f * 4
    return flops, hbm


def fused_forest_flops_bytes(n_rows: int, n_trees: int, steps: int,
                             n_feat: int, thr_bins: int,
                             num_class: int = 1,
                             table_itemsize: int = 4) -> Tuple[int, int]:
    """One fused serve batch (predict_device.fused_forest_predict):
    on-device binning + whole-forest traversal + tree-order leaf-value
    accumulation (gather + multiply + add per (row, tree)) + objective
    transform (~4 elementwise ops per output).  ``table_itemsize`` is
    the PACKED node-table element width (serve_packed_tables), which
    scales the traversal's gather bytes; the final ``[rows, out]``
    score is the only tensor that crosses back to the host."""
    n, t, k = int(n_rows), int(n_trees), max(1, int(num_class))
    bf, bb = device_bin_flops_bytes(n, n_feat, thr_bins)
    per_level = n * t * int(steps)
    tf = TRAVERSE_OPS_PER_STEP * per_level
    tb = (TRAVERSE_BYTES_PER_STEP * per_level
          * int(table_itemsize)) // 4
    af = 3 * n * t + 4 * n * k
    ab = n * t * 4 + 2 * n * k * 4
    return bf + tf + af, bb + tb + ab


def train_hist_flops_per_iter(n_rows: int, n_feat: int, num_bins: int,
                              num_leaves: int) -> float:
    """Useful histogram FLOPs per boosting iteration: one C=3 full-N
    contraction per smaller-child pass, (num_leaves - 1) passes/tree —
    the headline number bench.py reports (its former private
    ``_hist_flops_per_iter``, now derived from the shared formula)."""
    f, _ = hist_flops_bytes(n_rows, n_feat, num_bins, channels=3)
    return float(f) * (int(num_leaves) - 1)


# ---------------------------------------------------------------------------
# Channel 1: trace-time site notes (process-global, like trace_event)
# ---------------------------------------------------------------------------

_TRACED_LOCK = threading.Lock()
_TRACED: Dict[str, FlopSite] = {}

# ambient member-axis multiplier (fleet/trainer.py): while a fleet
# program traces, every site note fires ONCE (vmap traces the body once)
# but the compiled program executes it N times per dispatch — scale the
# note so perf.* / MFU stay truthful for the whole fleet.  A contextvar
# (not a global) so a concurrent solo trace in another thread is not
# contaminated.
_MEMBER_AXIS: "contextvars.ContextVar[int]" = contextvars.ContextVar(
    "lgbtpu_member_axis", default=1)


def _member_scale() -> int:
    return _MEMBER_AXIS.get()


@contextlib.contextmanager
def member_axis(n: int):
    """Scale every ``note_traced`` fired inside the context by ``n`` —
    wrap the fleet program's trace/dispatch so the process-wide traced
    ledger accounts all N members' work, not one lane's."""
    tok = _MEMBER_AXIS.set(max(1, int(n)))
    try:
        yield
    finally:
        _MEMBER_AXIS.reset(tok)


def note_traced(site: str, flops: int, hbm_bytes: int,
                phase: str = "", cadence: str = "step") -> None:
    """Record a site's static accounting from TRACED shapes.  Called
    inside jitted function bodies, so it fires once per fresh trace and
    overwrites idempotently on retrace — the latest traced shapes win
    (the process-wide view; per-model attribution goes through the
    driver's FlopLedger, which never depends on jit-cache state).
    Under :func:`member_axis` the note is scaled by the fleet's member
    count — vmap traces the body once but runs it N-wide."""
    scale = _member_scale()
    with _TRACED_LOCK:
        _TRACED[site] = FlopSite(site=site, phase=phase,
                                 flops=int(flops) * scale,
                                 hbm_bytes=int(hbm_bytes) * scale,
                                 cadence=cadence)


def traced_sites() -> Dict[str, FlopSite]:
    """Process-wide snapshot of the trace-time site notes."""
    with _TRACED_LOCK:
        return dict(_TRACED)


# ---------------------------------------------------------------------------
# Channel 2: the per-model ledger
# ---------------------------------------------------------------------------

class FlopLedger:
    """Per-model static compute ledger, the compute sibling of
    ``obs/comm.CommLedger``: a table of (site, phase, flops, hbm_bytes,
    cadence) built from LOGICAL GLOBAL shapes so serial and
    ``tree_learner=data`` produce byte-identical accounting."""

    def __init__(self):
        self._sites: Dict[str, FlopSite] = {}

    def add(self, site: str, phase: str, flops: int, hbm_bytes: int,
            cadence: str = "step") -> None:
        self._sites[site] = FlopSite(site=site, phase=phase,
                                     flops=int(flops),
                                     hbm_bytes=int(hbm_bytes),
                                     cadence=cadence)

    def sites(self) -> Tuple[FlopSite, ...]:
        return tuple(self._sites[k] for k in sorted(self._sites))

    def per_iteration(self, n_steps: int) -> Tuple[int, int]:
        """(flops, hbm_bytes) for one boosting iteration that ran
        ``n_steps`` grower loop steps."""
        f = b = 0
        for s in self.sites():
            mult = n_steps if s.cadence == "step" else 1
            f += s.flops * mult
            b += s.hbm_bytes * mult
        return f, b

    def flop_share(self, n_steps: int) -> Dict[str, float]:
        """Static per-site share of one iteration's FLOPs — the
        "where would the nanoseconds go on ideal hardware" split every
        bench point records alongside the measured rate."""
        total, _ = self.per_iteration(n_steps)
        if total <= 0:
            return {}
        return {s.site: round(s.flops
                              * (n_steps if s.cadence == "step" else 1)
                              / total, 4)
                for s in self.sites()}

    @classmethod
    def for_training(cls, n_rows: int, n_feat: int, num_bins: int,
                     split_batch: int = 1, hist_cols: int = None,
                     hist_bins: int = None, binned_itemsize: int = 1,
                     num_class: int = 1,
                     vals_itemsize: int = 4,
                     quant: bool = False) -> "FlopLedger":
        """The training-loop site table for the masked grower family.

        ``hist_cols``/``hist_bins``: the histogram pass's column/bin
        axes when they differ from the scan space (EFB bundles build
        G-column histograms at the max group-bin width, then expand to
        F features for the scan); default to ``n_feat``/``num_bins``.
        ``num_class``: trees grown per iteration — iter-cadence sites
        run once PER CLASS, so their per-iteration values carry the
        factor (step-cadence sites get it through the summed
        across-class step count the driver records).
        ``vals_itemsize``/``quant``: quantized training (quant_train)
        — the histogram passes read int8/int16 accumulands instead of
        f32, and the quantize/dequant sites appear so ``perf.hist.*``
        intensity/bound keys show the bound actually moving.  (The
        strict hist_overlap path's 1-slot mask is accounted as the
        masked pass it is byte-identical to — see
        :func:`hist_flops_bytes`.)  Sites:

        - ``hist``       smaller-child contraction, C=3K, per step
        - ``hist_pad``   MXU lane-pad MACs of the wide contraction
                         (C=3K > 48 buckets to 128 multiples), per
                         step — phase="pad", excluded from MFU
        - ``hist_root``  root contraction, C=3, per class per iter
        - ``split_scan`` 2K candidate leaves per step
        - ``split_root`` root scan, per class per iteration
        - ``partition``  one row pass per step
        - ``score``      leaf-gather score update, per class per iter
        - ``quantize``   grad/hess int packing, per class per iter
        - ``dequant``    scan-time int32->f32 widen, per step
        """
        k = max(1, int(split_batch))
        nc = max(1, int(num_class))
        hc = int(hist_cols) if hist_cols else int(n_feat)
        hb = int(hist_bins) if hist_bins else int(num_bins)
        led = cls()
        f, b = hist_flops_bytes(n_rows, hc, hb, channels=3 * k,
                                binned_itemsize=binned_itemsize,
                                vals_itemsize=vals_itemsize,
                                slotted=k > 1)
        led.add("hist", "grow", f, b, "step")
        f, b = hist_pad_flops_bytes(n_rows, hc, hb, channels=3 * k)
        if f:
            led.add("hist_pad", "pad", f, b, "step")
        f, b = hist_flops_bytes(n_rows, hc, hb, channels=3,
                                binned_itemsize=binned_itemsize,
                                vals_itemsize=vals_itemsize,
                                slotted=False)
        led.add("hist_root", "grow", f * nc, b * nc, "iter")
        f, b = split_scan_flops_bytes(n_feat, num_bins, n_leaves=2 * k)
        led.add("split_scan", "grow", f, b, "step")
        f, b = split_scan_flops_bytes(n_feat, num_bins, n_leaves=1)
        led.add("split_root", "grow", f * nc, b * nc, "iter")
        f, b = partition_flops_bytes(n_rows, binned_itemsize)
        led.add("partition", "grow", f, b, "step")
        f, b = score_update_flops_bytes(n_rows)
        led.add("score", "score", f * nc, b * nc, "iter")
        if quant:
            f, b = quantize_flops_bytes(n_rows, vals_itemsize)
            led.add("quantize", "grow", f * nc, b * nc, "iter")
            f, b = dequant_flops_bytes(n_feat, num_bins, n_leaves=2 * k)
            led.add("dequant", "grow", f, b, "step")
        return led
