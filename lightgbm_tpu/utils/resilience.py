"""Fault-tolerance primitives: retry/backoff, watchdogs, atomic writes.

Round-5 VERDICT.md recorded the failure mode this module exists for: the
exclusive TPU tunnel wedged for ~10 hours and every probe died in ``claim
hung`` or backend setup/compile errors — with no retry, no traceback from
the hung call, and snapshots that were written non-atomically and never
read back.  The reference hardens the same surface piecemeal (network
retry in the socket learner, ``snapshot_freq`` in gbdt.cpp, continued
training via ``init_model``); here it is one layer:

- :class:`RetryPolicy` / :func:`retry_call` / :func:`retry` — jittered
  exponential backoff with a hard deadline and an exception CLASSIFIER
  (:func:`is_retryable_device_error`): transient device-claim /
  backend-bring-up errors are retried, programming errors are not.
- :class:`CircuitBreaker` — CLOSED/OPEN/HALF_OPEN state machine with
  exponentially backed-off half-open probes: where retry protects one
  call, the breaker protects the caller population from queuing onto a
  dependency that is down (serve/breaker.py maps it to admission-time
  rejects).
- :class:`Watchdog` — arms ``faulthandler`` stack dumps while a blocking
  device call (claim, compile, collective bring-up) is in flight, so a
  wedge produces a traceback instead of silence.
- :func:`atomic_write` — temp file in the target directory +
  ``os.replace``, so a crash mid-write can never leave a truncated model
  or binary cache behind.  Hosts the ``snapshot_write`` /
  ``snapshot_kill`` fault-injection sites (utils/faultinject.py).

Consumers: ``parallel/launch.py`` / ``parallel/mesh.py`` /
``models/gbdt.py`` device bring-up, ``booster.py`` / ``dataset.py`` /
``snapshot.py`` persistence, ``tools/tpu_watch.py`` claim probes.
"""

from __future__ import annotations

import dataclasses
import faulthandler
import functools
import os
import random
import sys
import tempfile
import threading
import time
from typing import Callable, Optional


# ---------------------------------------------------------------------------
# Exception classification
# ---------------------------------------------------------------------------

# Message fragments of transient device-claim / backend-init / network
# failures (the axon relay's "claim hung", jax.distributed heartbeats,
# gRPC status strings).  Matched case-insensitively against str(exc).
_RETRYABLE_PATTERNS = (
    "unavailable",
    "deadline exceeded",
    "deadline_exceeded",
    "timed out",
    "timeout",
    "connection refused",
    "connection reset",
    "connection closed",
    "failed to connect",
    "socket closed",
    "stream removed",
    "resource exhausted",
    "aborted",
    "claim",
    "heartbeat",
    "coordination service",
    "barrier",
    "backend setup",
    "initialization failed",
)

# Never retried regardless of message: programming / environment errors a
# second attempt cannot fix, and control-flow exceptions.
_FATAL_TYPES = (KeyboardInterrupt, SystemExit, GeneratorExit, MemoryError,
                NotImplementedError, AssertionError, TypeError,
                AttributeError, KeyError, IndexError, ImportError,
                SyntaxError)


def is_retryable_device_error(exc: BaseException) -> bool:
    """Default classifier: True for transient device-claim / backend-init
    shaped failures, False for programming errors.  ValueError is fatal
    (bad arguments don't become good by waiting) EXCEPT LightGBMError
    subclasses are still checked by message — they wrap device errors."""
    if isinstance(exc, _FATAL_TYPES):
        return False
    if type(exc) is ValueError:
        return False
    msg = str(exc).lower()
    return any(p in msg for p in _RETRYABLE_PATTERNS)


# ---------------------------------------------------------------------------
# Retry with jittered exponential backoff + hard deadline
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RetryPolicy:
    """Backoff schedule for :func:`retry_call`.

    max_attempts: total tries (1 = no retry).
    base_delay_s: backoff before the 2nd attempt; doubles per attempt.
    max_delay_s:  backoff cap.
    deadline_s:   hard wall-clock budget across ALL attempts (0 = none);
                  a retry that could not even START before the deadline
                  re-raises instead of sleeping.
    jitter:       fraction of each delay randomized (0..1): the slept
                  delay is uniform in [d*(1-jitter/2), d*(1+jitter/2)],
                  de-synchronizing a fleet of workers hammering one relay.
    """
    max_attempts: int = 3
    base_delay_s: float = 1.0
    max_delay_s: float = 30.0
    deadline_s: float = 0.0
    jitter: float = 0.5

    @classmethod
    def for_bringup(cls, retries: int, timeout_s: float) -> "RetryPolicy":
        """The device/distributed bring-up schedule shared by
        ``gbdt._resolve_mesh``, ``launch.init`` and
        ``mesh.init_distributed``: ``retries`` re-attempts after the
        first, a base delay scaled to 1% of the deadline (capped at
        1 s), and the deadline itself as the hard budget."""
        return cls(
            max_attempts=max(1, int(retries) + 1),
            base_delay_s=min(1.0, timeout_s / 100.0) if timeout_s > 0
            else 1.0,
            deadline_s=timeout_s)


def retry_call(fn: Callable, *args, policy: Optional[RetryPolicy] = None,
               classify: Optional[Callable[[BaseException], bool]] = None,
               on_retry: Optional[Callable[[int, float, BaseException],
                                           None]] = None,
               label: str = "", **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying classified-transient
    failures under ``policy``.  ``on_retry(attempt, delay_s, exc)`` is
    invoked before each backoff sleep (tools/tpu_watch.py logs these).
    The final failure is re-raised unmodified."""
    policy = policy or RetryPolicy()
    classify = classify or is_retryable_device_error
    name = label or getattr(fn, "__name__", "call")
    t0 = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn(*args, **kwargs)
        except BaseException as e:
            if attempt >= max(1, policy.max_attempts) or not classify(e):
                raise
            delay = min(policy.max_delay_s,
                        policy.base_delay_s * (2.0 ** (attempt - 1)))
            if policy.jitter > 0:
                delay *= 1.0 + policy.jitter * (random.random() - 0.5)
            if policy.deadline_s > 0 and \
                    time.monotonic() - t0 + delay > policy.deadline_s:
                from .log import Log
                Log.warning(
                    f"{name}: retry deadline ({policy.deadline_s:g}s) "
                    f"exhausted after attempt {attempt}; giving up")
                raise
            from .log import Log
            Log.warning(
                f"{name}: attempt {attempt}/{policy.max_attempts} failed "
                f"({e}); retrying in {delay:.1f}s")
            if on_retry is not None:
                on_retry(attempt, delay, e)
            time.sleep(delay)


def retry(policy: Optional[RetryPolicy] = None, **retry_kwargs):
    """Decorator form of :func:`retry_call`::

        @retry(RetryPolicy(max_attempts=4))
        def claim(): ...
    """
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(fn, *args, policy=policy, **retry_kwargs,
                              **kwargs)
        return wrapper
    return deco


# ---------------------------------------------------------------------------
# Circuit breaker: stop hammering a failing dependency
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Thread-safe CLOSED -> OPEN -> HALF_OPEN breaker.

    Retry/backoff (above) protects one CALL; the breaker protects the
    CALLER POPULATION: once ``failure_threshold`` consecutive failures
    are recorded the circuit opens and :meth:`allow` answers False —
    work is rejected up front instead of queuing onto a dependency that
    is down (the serve batcher maps this to an immediate 503, keeping
    the bounded queue free for traffic that can succeed).  After
    ``cooldown_s`` the circuit half-opens: :meth:`allow` admits ONE
    probe (further callers stay rejected — a burst arriving right at
    the cooldown boundary must not pile onto the still-unproven
    dependency; an abandoned probe expires after the current cooldown
    so a lost outcome cannot wedge the breaker); the probe's recorded
    outcome decides — success closes the circuit, failure re-opens it
    with the cooldown DOUBLED (capped at ``cooldown_max_s``), so a
    dependency that stays down is probed at a decaying rate rather
    than every cooldown.

    ``failure_threshold <= 0`` disables the breaker entirely (always
    allows, records nothing).  ``clock`` is injectable for tests.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold: int = 5, cooldown_s: float = 1.0,
                 cooldown_max_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = int(failure_threshold)
        # floored above zero: with cooldown 0 a tripped circuit is
        # instantly HALF_OPEN and the probe-expiry test always passes,
        # so EVERY caller becomes the probe and nothing is ever
        # rejected — the breaker would silently not exist
        self.cooldown_s = max(1e-3, float(cooldown_s))
        self.cooldown_max_s = max(self.cooldown_s, float(cooldown_max_s))
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0            # consecutive, while CLOSED
        self._open_until = 0.0
        self._cur_cooldown = self.cooldown_s
        self._probe_t: Optional[float] = None   # outstanding probe start
        self.opens = 0                # lifetime open transitions

    @property
    def enabled(self) -> bool:
        return self.failure_threshold > 0

    def state(self) -> str:
        """Current state, with the OPEN -> HALF_OPEN clock transition
        applied (reading the state can move it, like :meth:`allow`)."""
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if self._state == self.OPEN \
                and self._clock() >= self._open_until:
            self._state = self.HALF_OPEN
            self._probe_t = None
        return self._state

    def allow(self) -> bool:
        """Whether new work may proceed right now.  False while OPEN
        with the cooldown running, and in HALF_OPEN for everyone but
        the single probe (the first caller after the cooldown; a probe
        whose outcome never lands expires after the current cooldown)."""
        return self.try_acquire()[0]

    def try_acquire(self) -> "tuple[bool, bool]":
        """``(admitted, claimed_probe)`` — :meth:`allow`, additionally
        reporting whether THIS call claimed the half-open probe slot.
        A caller whose admitted work can leave the system without a
        recorded outcome (dropped, shed) must :meth:`release_probe`
        when that happens, or the breaker stays shut for the full
        abandoned-probe expiry on a possibly healthy dependency."""
        if not self.enabled:
            return True, False
        with self._lock:
            st = self._state_locked()
            if st == self.OPEN:
                return False, False
            if st == self.HALF_OPEN:
                now = self._clock()
                if self._probe_t is not None \
                        and now - self._probe_t < self._cur_cooldown:
                    return False, False
                self._probe_t = now
                return True, True
            return True, False

    def release_probe(self) -> None:
        """Give back a probe slot claimed by :meth:`try_acquire` whose
        work will never record an outcome (deadline-shed before
        dispatch, request-scoped failure): the next caller probes
        immediately instead of every caller waiting out the
        abandoned-probe expiry."""
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._probe_t = None

    def retry_after_s(self) -> float:
        """The Retry-After hint for rejected work: the remaining
        cooldown while OPEN, the remaining probe window while HALF_OPEN
        with a probe outstanding (callers rejected then must NOT retry
        immediately — that is exactly when traffic is being held back),
        0 otherwise."""
        with self._lock:
            return self._retry_after_locked()

    def _retry_after_locked(self) -> float:
        # the ONE computation of the hint: describe() must report the
        # same number CircuitOpen carries, or /healthz and the 503
        # body disagree about when to come back
        st = self._state_locked()
        now = self._clock()
        if st == self.OPEN:
            return max(0.0, self._open_until - now)
        if st == self.HALF_OPEN and self._probe_t is not None:
            return max(0.0, self._probe_t + self._cur_cooldown - now)
        return 0.0

    def record_success(self) -> None:
        if not self.enabled:
            return
        with self._lock:
            st = self._state_locked()
            if st == self.HALF_OPEN:
                # probe succeeded: full reset, cooldown back to base
                self._state = self.CLOSED
                self._cur_cooldown = self.cooldown_s
                self._probe_t = None
            self._failures = 0

    def record_failure(self) -> None:
        if not self.enabled:
            return
        with self._lock:
            st = self._state_locked()
            if st == self.HALF_OPEN:
                # failed probe: re-open with a doubled cooldown
                self._cur_cooldown = min(self.cooldown_max_s,
                                         self._cur_cooldown * 2.0)
                self._trip_locked()
            elif st == self.CLOSED:
                self._failures += 1
                if self._failures >= self.failure_threshold:
                    self._trip_locked()
            # already OPEN: late failures from in-flight work don't
            # extend the cooldown (they predate the trip)

    def _trip_locked(self) -> None:
        self._state = self.OPEN
        self._failures = 0
        self._open_until = self._clock() + self._cur_cooldown
        self._probe_t = None
        self.opens += 1

    def describe(self) -> dict:
        with self._lock:
            retry_after = self._retry_after_locked()
            return {"state": self._state,
                    "consecutive_failures": self._failures,
                    "opens": self.opens,
                    "cooldown_s": self._cur_cooldown,
                    "retry_after_s": retry_after}


# ---------------------------------------------------------------------------
# Watchdog: faulthandler stack dumps for wedged blocking calls
# ---------------------------------------------------------------------------

class WatchdogTimeout(RuntimeError):
    """A blocking call guarded by :meth:`Watchdog.run` exceeded its
    deadline.  The message deliberately matches the resilience
    classifier's retryable patterns (``deadline exceeded``) so a hung
    collective/claim is retried — or handed to the elastic recovery
    ladder — like any other transient device failure."""

    def __init__(self, label: str, timeout_s: float):
        self.label = label
        self.timeout_s = timeout_s
        super().__init__(
            f"deadline exceeded: {label or 'blocking call'} still "
            f"running after {timeout_s:g}s (abandoned by watchdog)")


class Watchdog:
    """Context manager arming periodic ``faulthandler`` stack dumps while
    a blocking device call is in flight::

        with Watchdog(cfg.dist_init_timeout_s, label="device claim"):
            devs = jax.devices()

    If the call exceeds ``timeout_s`` the interpreter dumps every
    thread's stack to stderr (repeating each ``timeout_s``) — the
    round-5 wedge produced NO traceback for 10 hours; this makes the
    hang loud and attributable.  ``timeout_s <= 0`` disables.

    **Cancel-and-raise mode** (``on_timeout="raise"``): :meth:`run`
    executes the guarded call in a daemon worker thread and, at the
    deadline, raises :class:`WatchdogTimeout` in the WAITING thread —
    the hung C call itself cannot be interrupted (a wedged collective
    blocks in the runtime), so the worker is abandoned and the caller
    gets a classified, retryable exception instead of a silent hang.
    The all-thread stack dump and flight-recorder dump fire
    synchronously at the deadline, so the post-mortem survives the
    abandonment.  The default (``on_timeout="dump"``) keeps the
    historical dump-only behavior: :meth:`run` calls the function
    inline under the context manager and never raises on its own
    (tests/test_zelastic.py pins this regression contract).

    ``faulthandler``'s later-dump timer is process-global: nesting
    dump-mode Watchdogs (or combining with pytest's per-test dump)
    leaves the innermost exit having cancelled the outer timer.
    Acceptable for the bring-up call sites the CONTEXT MANAGER guards —
    they do not nest.  Raise-mode :meth:`run` deliberately never
    touches that timer (it dumps synchronously at the deadline
    instead): its callers — the per-iteration elastic collective
    deadline above all — would otherwise cancel any ambient hang dump
    (e.g. the conftest per-test watchdog) on every single fetch.
    """

    def __init__(self, timeout_s: float, label: str = "",
                 file=None, on_timeout: str = "dump") -> None:
        if on_timeout not in ("dump", "raise"):
            raise ValueError(
                f"on_timeout must be 'dump' or 'raise', got {on_timeout!r}")
        self.timeout_s = float(timeout_s)
        self.label = label
        self.file = file
        self.on_timeout = on_timeout
        self._bb_timer = None

    def __enter__(self) -> "Watchdog":
        if self.timeout_s > 0:
            faulthandler.dump_traceback_later(
                self.timeout_s, repeat=True,
                file=self.file if self.file is not None else sys.stderr)
            # a wedge is also a flight-recorder trigger: alongside the
            # faulthandler stack dump, dump every live blackbox ring
            # (obs/blackbox.py) so the post-mortem carries the last K
            # iteration records, not just stacks.  No-op (None) when no
            # recorder is live — the telemetry_blackbox=false fast path.
            from ..obs.blackbox import watchdog_timer
            self._bb_timer = watchdog_timer(self.timeout_s, self.label)
            from .log import Log
            Log.debug(f"watchdog armed ({self.timeout_s:g}s) around "
                      f"{self.label or 'blocking call'}")
        return self

    def __exit__(self, *exc) -> None:
        if self.timeout_s > 0:
            faulthandler.cancel_dump_traceback_later()
            if self._bb_timer is not None:
                self._bb_timer.cancel()
                self._bb_timer = None

    def run(self, fn: Callable, *args, **kwargs):
        """Call ``fn(*args, **kwargs)`` under this watchdog.

        ``on_timeout="dump"`` (default): inline call inside the context
        manager — stack dumps at the deadline, no exception, identical
        to ``with Watchdog(...): fn()``.

        ``on_timeout="raise"``: the call runs in a daemon worker
        thread; if it has not finished after ``timeout_s`` the waiting
        thread dumps every thread's stack + the live flight recorders
        synchronously, raises :class:`WatchdogTimeout`, and the worker
        is abandoned — it keeps whatever it was wedged on, like a real
        hung collective, and its eventual result (or exception) is
        discarded.  ``timeout_s <= 0`` always runs inline (no
        deadline)."""
        if self.timeout_s <= 0 or self.on_timeout == "dump":
            with self:
                return fn(*args, **kwargs)
        box: dict = {}
        done = threading.Event()

        def _worker():
            try:
                box["value"] = fn(*args, **kwargs)
            except BaseException as e:      # noqa: BLE001 — relayed below
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=_worker, daemon=True,
                             name=f"watchdog:{self.label or 'call'}")
        t.start()
        if not done.wait(self.timeout_s):
            # deadline: post-mortem NOW (all-thread stacks + every live
            # blackbox ring), synchronously in this thread — NOT via the
            # process-global dump_traceback_later timer, which per-call
            # arm/cancel would silently disable any ambient hang dump
            # (conftest's per-test watchdog) for raise-mode callers that
            # run once per training iteration
            faulthandler.dump_traceback(
                file=self.file if self.file is not None else sys.stderr,
                all_threads=True)
            from ..obs.blackbox import dump_all
            dump_all(f"watchdog:{self.label}" if self.label
                     else "watchdog")
            from .log import Log
            Log.warning(f"watchdog: {self.label or 'blocking call'} "
                        f"abandoned after {self.timeout_s:g}s deadline")
            raise WatchdogTimeout(self.label, self.timeout_s)
        if "error" in box:
            raise box["error"]
        return box["value"]


# ---------------------------------------------------------------------------
# Atomic file writes (temp + os.replace)
# ---------------------------------------------------------------------------

def atomic_write(path, data, binary: bool = False) -> None:
    """Write ``data`` to ``path`` atomically: temp file in the TARGET
    directory (``os.replace`` requires same-filesystem), fsync, rename.
    A crash at any point leaves either the old file or the new file —
    never a truncated hybrid.  Creates missing parent directories (a
    relative ``output_model`` in a fresh working dir used to make every
    snapshot write raise).

    Fault-injection sites (utils/faultinject.py): ``snapshot_write``
    fires before anything is written; ``snapshot_kill`` fires after the
    temp file is durable but BEFORE the rename — the kill-before-rename
    crash window.  An injected kill deliberately leaves the temp file
    behind, like a real crash would."""
    from . import faultinject
    faultinject.check("snapshot_write")
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d,
                               prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        # text mode pins utf-8: readers (Booster model load, manifest
        # json) decode utf-8, and a locale-dependent write encoding
        # would break the byte checksums recorded over these files
        with os.fdopen(fd, "wb" if binary else "w",
                       encoding=None if binary else "utf-8") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    # kill-before-rename window: InjectedKill is a BaseException and the
    # cleanup above only catches Exception, so the temp file survives —
    # exactly the debris a real crash leaves (readers must ignore *.tmp)
    faultinject.check("snapshot_kill")
    os.replace(tmp, path)
