"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh, the same way the reference
simulates multi-machine training with localhost sockets
(/root/reference/tests/distributed/_test_distributed.py) — see SURVEY.md §4.

NOTE on platform forcing: the environment's sitecustomize imports jax and
registers the TPU (axon) PJRT plugin at interpreter start, freezing
``jax_platforms``; setting the JAX_PLATFORMS env var here is too late.
``jax.config.update`` below is the supported override and prevents the TPU
backend from initializing during tests (the TPU tunnel is exclusive and
slow to claim).
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite's wall clock is dominated by
# XLA compiles (hundreds of jit variants across growers / shapes), and
# every run used to pay them from scratch.  min_compile_time 0.5 s keeps
# tiny kernels out of it.  The cache lives in the MACHINE-LOCAL temp dir,
# not the repo, AND is keyed by the host's CPU feature set: XLA:CPU AOT
# entries are machine-feature-specific, and this environment can migrate
# between heterogeneous hosts mid-session — a cache populated on one
# host then read on another makes EVERY load fail ("Target machine
# feature ... is not supported on the host machine"), paying both the
# failed loads and the full recompiles (measured: a poisoned cache run
# took 25 min where a fresh one compiles in far less).
import getpass  # noqa: E402
import hashlib  # noqa: E402
import tempfile  # noqa: E402


def _machine_tag() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.sha256(line.encode()).hexdigest()[:10]
    except OSError:
        pass
    import platform
    return hashlib.sha256(platform.processor().encode()).hexdigest()[:10]


jax.config.update("jax_compilation_cache_dir",
                  os.path.join(tempfile.gettempdir(),
                               f"lgbtpu_jax_cache_{getpass.getuser()}_"
                               f"{_machine_tag()}"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Skip budget (VERDICT r2: a regressing guard skipped instead of failing
# and nobody noticed).  On the standard harness — virtual 8-device CPU
# mesh, full toolchain — exactly two skips are expected: the
# graphviz-executable plotting skip and the R-binding smoke test
# (test_r_binding.py, needs Rscript; its shim-compile/link guard still
# RUNS without R).  Every new skip must either be fixed or the budget
# consciously raised here with a comment.
SKIP_BUDGET = 2
_skips: list = []


def pytest_runtest_logreport(report):
    if report.skipped:
        _skips.append(f"{report.nodeid}: {report.longrepr[2] if isinstance(report.longrepr, tuple) else report.longrepr}")


def pytest_sessionfinish(session, exitstatus):
    # only enforce on the standard full-suite harness (virtual CPU mesh);
    # single-chip TPU runs legitimately skip the 8-device tests
    if jax.default_backend() != "cpu" or len(jax.devices()) < 8:
        return
    if session.config.args and any("::" in a for a in session.config.args):
        return                       # targeted runs, not the full suite
    if len(_skips) > SKIP_BUDGET and exitstatus == 0:
        lines = "\n  ".join(_skips)
        print(f"\nERROR: {len(_skips)} skipped tests exceed the skip "
              f"budget ({SKIP_BUDGET}):\n  {lines}", flush=True)
        session.exitstatus = 1


@pytest.fixture(scope="session")
def rng():
    return np.random.RandomState(42)


@pytest.fixture(scope="session")
def binary_data():
    """Synthetic binary classification set (sklearn-style, utils.py analog)."""
    rs = np.random.RandomState(0)
    n, f = 4000, 20
    x = rs.randn(n, f)
    logit = x[:, 0] * 1.5 - x[:, 1] + 0.5 * x[:, 2] * x[:, 3] + 0.3 * rs.randn(n)
    y = (logit > 0).astype(np.float32)
    return x, y


@pytest.fixture(scope="session")
def regression_data():
    rs = np.random.RandomState(1)
    n, f = 4000, 15
    x = rs.randn(n, f)
    y = (2.0 * x[:, 0] + x[:, 1] ** 2 - 1.5 * x[:, 2] + 0.1 * rs.randn(n)).astype(np.float32)
    return x, y
