"""Foreign-language FFI smoke test (VERDICT r4 task 8): prove the
"any FFI language binds libcapi_train.so" claim with a REAL R binding —
the reference ships an R package whose glue is exactly this pattern
(R-package/src/lightgbm_R.cpp: C shim + dynamic load).

The test compiles bindings/R/lgbtpu_shim.c against libcapi_train.so,
runs bindings/R/smoke.R under Rscript (dataset create, 5 training
iterations, SaveModel, predict), and asserts the R-side predictions and
saved model match the Python API trained on identical data.  Skips when
R is absent (this is the one environment-dependent skip besides
graphviz — see conftest SKIP_BUDGET); the shim still gets compiled and
its symbols checked, so the binding surface itself is guarded even
without R.
"""

import os
import shutil
import subprocess
import sysconfig

import numpy as np
import pytest

import lightgbm_tpu as lgb
from test_capi_train import SO, _ensure_built

HERE = os.path.dirname(os.path.abspath(__file__))
SHIM_SRC = os.path.join(os.path.dirname(HERE), "bindings", "R",
                        "lgbtpu_shim.c")
SMOKE_R = os.path.join(os.path.dirname(HERE), "bindings", "R", "smoke.R")

_BUILD_ERR = _ensure_built()
pytestmark = pytest.mark.skipif(bool(_BUILD_ERR), reason=_BUILD_ERR)


def _data(n=1500, f=6, seed=4):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, f)
    y = (x[:, 0] - 0.5 * x[:, 1] > 0).astype(np.float32)
    return x, y


def _build_shim(tmp_path) -> str:
    shim = str(tmp_path / "lgbtpu_shim.so")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    subprocess.run(
        ["cc", "-O2", "-shared", "-fPIC", SHIM_SRC, "-o", shim, SO,
         f"-Wl,-rpath,{os.path.dirname(SO)}", f"-Wl,-rpath,{libdir}"],
        check=True, capture_output=True, text=True)
    return shim


def test_shim_compiles_and_links(tmp_path):
    """The R shim builds and resolves every LGBM_Train* symbol it uses —
    guarded even on machines without R."""
    shim = _build_shim(tmp_path)
    nm = subprocess.run(["nm", "-D", "--undefined-only", shim],
                        capture_output=True, text=True, check=True).stdout
    # ld resolved the LGBM symbols against libcapi_train.so at link
    # time (they appear as undefined in the shim, satisfied by the
    # NEEDED entry); ldd proves the dependency edge exists
    ldd = subprocess.run(["ldd", shim], capture_output=True, text=True,
                         check=True).stdout
    assert "libcapi_train.so" in ldd
    assert "lgbtpu_smoke" in subprocess.run(
        ["nm", "-D", shim], capture_output=True, text=True,
        check=True).stdout


def test_shim_lifecycle_as_r_would_call_it(tmp_path):
    """Drive lgbtpu_smoke through ctypes with EXACTLY R's .C calling
    convention — column-major doubles, every argument a pointer, strings
    as char** — so the shim's transpose/narrowing/lifecycle logic is
    behavior-tested even on machines without R."""
    import ctypes
    shim = _build_shim(tmp_path)
    lib = ctypes.CDLL(shim)
    x, y = _data()
    n, f = x.shape
    x_col = np.asfortranarray(x).ravel(order="F")   # R memory layout
    y_d = y.astype(np.float64)
    pred = np.zeros(n, np.float64)
    status = ctypes.c_int(-1)
    n_c, f_c, rounds = ctypes.c_int(n), ctypes.c_int(f), ctypes.c_int(5)
    model = str(tmp_path / "model.txt").encode()

    def charpp(s):
        return (ctypes.c_char_p * 1)(s)

    lib.lgbtpu_smoke(
        x_col.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.byref(n_c), ctypes.byref(f_c),
        y_d.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        charpp(b"max_bin=63 verbosity=-1"),
        charpp(b"objective=binary num_leaves=15 learning_rate=0.1 "
               b"verbosity=-1"),
        ctypes.byref(rounds), charpp(model),
        pred.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.byref(status))
    assert status.value == 0

    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.1, "max_bin": 63, "verbosity": -1}
    bst = lgb.train(params, lgb.Dataset(x, label=y), num_boost_round=5)
    np.testing.assert_allclose(pred, bst.predict(x), rtol=1e-6, atol=1e-8)
    from_c = lgb.Booster(model_file=model.decode()).predict(x)
    np.testing.assert_allclose(from_c, pred, rtol=1e-6, atol=1e-8)


def test_r_smoke_matches_python(tmp_path):
    """dyn.load + .C from a real R process: train 5 iters, predict,
    compare predictions and the saved model to the Python API."""
    if shutil.which("Rscript") is None:
        pytest.skip("R (Rscript) not installed on this machine")
    shim = _build_shim(tmp_path)
    x, y = _data()
    xcsv = tmp_path / "x.csv"
    ycsv = tmp_path / "y.csv"
    model = tmp_path / "model.txt"
    predcsv = tmp_path / "pred.csv"
    np.savetxt(xcsv, x, delimiter=",", fmt="%.17g")
    np.savetxt(ycsv, y, fmt="%g")

    env = dict(os.environ,
               PYTHONPATH=os.path.dirname(HERE),
               LGBM_TPU_FORCE_CPU="1",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    r = subprocess.run(
        ["Rscript", SMOKE_R, shim, str(xcsv), str(ycsv), str(model),
         str(predcsv)],
        env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "R smoke ok" in r.stdout

    # Python API on identical data/params — same trees, same predictions
    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.1, "max_bin": 63, "verbosity": -1}
    bst = lgb.train(params, lgb.Dataset(x, label=y), num_boost_round=5)
    ref = bst.predict(x)
    r_pred = np.loadtxt(predcsv)
    # CSV round-trips x at %.17g (exact for float64); binning and
    # training are deterministic, so parity is tight
    np.testing.assert_allclose(r_pred, ref, rtol=1e-6, atol=1e-8)
    # the R-saved model loads in Python and predicts identically
    from_r = lgb.Booster(model_file=str(model)).predict(x)
    np.testing.assert_allclose(from_r, r_pred, rtol=1e-6, atol=1e-8)
