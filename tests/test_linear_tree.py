"""Linear-tree tests (test_engine.py linear trees analog)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


class TestLinearTree:
    def test_beats_constant_leaves_on_linear_data(self):
        rs = np.random.RandomState(0)
        n = 3000
        x = rs.randn(n, 4)
        y = (2.0 * x[:, 0] + 1.0 * x[:, 1] + 0.05 * rs.randn(n)) \
            .astype(np.float32)
        base = {"objective": "regression", "num_leaves": 7, "max_bin": 31,
                "min_data_in_leaf": 20}
        bst_const = lgb.train(base, lgb.Dataset(x, label=y),
                              num_boost_round=40)
        bst_lin = lgb.train(dict(base, linear_tree=True),
                            lgb.Dataset(x, label=y), num_boost_round=40)
        mse_const = float(np.mean((bst_const.predict(x) - y) ** 2))
        mse_lin = float(np.mean((bst_lin.predict(x) - y) ** 2))
        assert mse_lin < 0.5 * mse_const, (mse_lin, mse_const)

    def test_model_roundtrip(self, tmp_path):
        rs = np.random.RandomState(1)
        x = rs.randn(1000, 3)
        y = (x[:, 0] + 0.5 * x[:, 1]).astype(np.float32)
        p = {"objective": "regression", "num_leaves": 5, "max_bin": 31,
             "linear_tree": True}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=3)
        path = str(tmp_path / "lin.txt")
        bst.save_model(path)
        assert "is_linear=1" in open(path).read()
        bst2 = lgb.Booster(model_file=path)
        np.testing.assert_allclose(bst.predict(x[:100]), bst2.predict(x[:100]),
                                   rtol=1e-5, atol=1e-8)

    def test_nan_rows_fall_back_to_constant(self):
        rs = np.random.RandomState(2)
        x = rs.randn(1500, 3)
        y = (x[:, 0] * 1.5).astype(np.float32)
        p = {"objective": "regression", "num_leaves": 5, "max_bin": 31,
             "linear_tree": True}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=3)
        xt = x[:20].copy()
        xt[:, 0] = np.nan
        assert np.isfinite(bst.predict(xt)).all()

    def test_valid_eval_with_linear(self):
        rs = np.random.RandomState(3)
        x = rs.randn(2000, 3)
        y = (x[:, 0] + 0.2 * rs.randn(2000)).astype(np.float32)
        ds = lgb.Dataset(x[:1500], label=y[:1500])
        vds = lgb.Dataset(x[1500:], label=y[1500:], reference=ds)
        rec = {}
        lgb.train({"objective": "regression", "num_leaves": 5, "max_bin": 31,
                   "linear_tree": True, "metric": ["l2"]},
                  ds, num_boost_round=10, valid_sets=[vds],
                  callbacks=[lgb.record_evaluation(rec)])
        l2 = rec["valid_0"]["l2"]
        assert l2[-1] < l2[0]
