from .mesh import (make_mesh, default_mesh, init_distributed,
                   OwnerShardPlan, owner_shard_plan)
from .data_parallel import (make_dp_grower, shard_rows, pad_to_multiple,
                            owner_hist_reduce)
from .feature_parallel import make_fp_grower
from .voting_parallel import make_voting_grower
