"""Continual boosting pipeline (ISSUE 11): the freshness-guaranteed
train -> publish -> serve loop with shadow-parity gating and automatic
rollback (lightgbm_tpu/pipeline/continual.py), plus its satellites —
snapshot-prune TOCTOU pinning, registry in-flight guards, absolute
``best_iteration`` for continued runs, and the kill -9 stage-boundary
matrix proving restart converges byte-identically.
"""

import glob
import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.pipeline.continual import (ContinualTrainer,
                                             gate_metric_value,
                                             lineage_gate_reason,
                                             score_gate_reason,
                                             shadow_parity_probe)
from lightgbm_tpu.utils import faultinject

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_rs = np.random.RandomState(11)


def _chunk(n, seed=None, n_feat=6):
    rs = np.random.RandomState(seed) if seed is not None else _rs
    x = rs.randn(n, n_feat)
    return x, x[:, 0] + 0.5 * x[:, 1] + 0.05 * rs.randn(n)


BASE = {"objective": "regression", "num_leaves": 7, "max_bin": 31,
        "min_data_in_leaf": 5, "verbosity": -1, "continual_rounds": 3}


@pytest.fixture(autouse=True)
def _clear_faults():
    faultinject.clear()
    yield
    faultinject.clear()


def _params(tmp_path, **kw):
    p = dict(BASE, output_model=str(tmp_path / "m.txt"))
    p.update(kw)
    return p


# ---------------------------------------------------------------------------
# gate primitives
# ---------------------------------------------------------------------------

class TestGatePrimitives:
    def test_probability_drift_is_absolute(self):
        a = np.array([0.5, 0.6])
        assert score_gate_reason("binary", a, a + 0.05, 0.1) is None
        r = score_gate_reason("binary", a, a + 0.2, 0.1)
        assert r is not None and "probability drift" in r

    def test_regression_drift_is_relative(self):
        inc = np.array([100.0, 200.0])
        # 5 absolute on a scale of 200 = 2.5% relative: inside 10%
        assert score_gate_reason("regression", inc + 5.0, inc, 0.1) is None
        r = score_gate_reason("regression", inc + 50.0, inc, 0.1)
        assert r is not None and "relative score drift" in r

    def test_non_finite_and_shape_refused(self):
        inc = np.array([1.0, 2.0])
        assert "non-finite" in score_gate_reason(
            "regression", np.array([1.0, np.nan]), inc, 10.0)
        assert "shape" in score_gate_reason(
            "regression", np.array([1.0]), inc, 10.0)

    def test_degraded_incumbent_does_not_blind_the_gate(self):
        # NaN in the INCUMBENT poisons max(): every NaN comparison is
        # False, which used to pass ANY candidate exactly when serving
        # was already sick — the gate must judge on the finite entries
        inc = np.array([np.nan, 1.0, 2.0])
        cand = np.array([5.0, 1.0, 500.0])
        r = score_gate_reason("regression", cand, inc, 0.5)
        assert r is not None and "drift" in r
        # all-NaN incumbent: nothing sane to compare against — pass
        assert score_gate_reason(
            "regression", cand, np.full(3, np.nan), 0.5) is None

    def test_gate_metric_values(self):
        y = np.array([0.0, 1.0])
        name, v, hib = gate_metric_value("binary",
                                         np.array([0.1, 0.9]), y)
        assert name == "binary_logloss" and not hib
        assert v == pytest.approx(-np.mean([np.log(0.9), np.log(0.9)]))
        name, v, _ = gate_metric_value("regression",
                                       np.array([1.0, 3.0]),
                                       np.array([1.0, 1.0]))
        assert name == "l2" and v == pytest.approx(2.0)

    def test_lineage_gate_catches_tampered_prefix(self):
        x, y = _chunk(300, seed=1)
        m1 = lgb.train(dict(BASE), lgb.Dataset(x, label=y),
                       num_boost_round=3)
        m2 = lgb.train(dict(BASE), lgb.Dataset(x, label=y),
                       num_boost_round=3, init_model=m1)
        rows = x[:32]
        assert lineage_gate_reason(m2, m1, rows, 1.0, 1e-9) is None
        # corrupt one leading tree (on a text round-trip copy — the
        # merged booster SHARES tree objects with m1): the continuation
        # claim is now false
        m2 = lgb.Booster(model_str=m2.model_to_string())
        m2.trees[0].leaf_value = m2.trees[0].leaf_value + 0.5
        m2._drop_predict_cache()
        r = lineage_gate_reason(m2, m1, rows, 1.0, 1e-9)
        assert r is not None and "lineage parity violated" in r

    def test_lineage_gate_respects_decay(self):
        x, y = _chunk(300, seed=2)
        m1 = lgb.train(dict(BASE), lgb.Dataset(x, label=y),
                       num_boost_round=3)
        m2 = lgb.Booster(model_str=m1.model_to_string())
        for t in m2.trees:
            t.shrink(0.5)
        m2._drop_predict_cache()
        rows = x[:16]
        assert lineage_gate_reason(m2, m1, rows, 0.5, 1e-9) is None
        assert lineage_gate_reason(m2, m1, rows, 1.0, 1e-9) is not None

    def test_probe_timeout_is_a_failure(self):
        class Slow:
            trees = []

            def predict(self, rows):
                time.sleep(5.0)
                return np.zeros(len(rows))

        cfg = lgb.Config(dict(BASE))
        out = shadow_parity_probe(Slow(), Slow(),
                                  [np.zeros((4, 6))], cfg,
                                  timeout_s=0.2)
        assert not out["ok"] and "continual_timeout_s" in out["reason"]


# ---------------------------------------------------------------------------
# standalone trainer loop
# ---------------------------------------------------------------------------

class TestContinualStandalone:
    def test_generations_publish_and_freshen(self, tmp_path):
        p = _params(tmp_path)
        tr = ContinualTrainer(p, *_chunk(300, seed=3))
        reports = [tr.run_generation()]
        for s in (4, 5):
            reports.append(tr.run_generation(*_chunk(120, seed=s)))
        assert [r["status"] for r in reports] == ["published"] * 3
        assert [r["iteration"] for r in reports] == [3, 6, 9]
        assert tr.generation == 3
        # the newest complete snapshot is the freshest generation
        from lightgbm_tpu.snapshot import find_latest_complete_snapshot
        it, path = find_latest_complete_snapshot(p["output_model"])
        assert it == 9
        snap = tr.metrics.snapshot()
        assert snap["continual.published"]["value"] == 3
        assert snap["continual.rollbacks"]["value"] == 0
        assert snap["continual.freshness_lag_s"]["value"] > 0
        assert reports[-1]["freshness_lag_s"] > 0
        assert tr.freshness_lag_s() == pytest.approx(
            reports[-1]["freshness_lag_s"], abs=1e-6)

    def test_decay_shrinks_carried_trees(self, tmp_path):
        p = _params(tmp_path, continual_decay=0.5)
        tr = ContinualTrainer(p, *_chunk(300, seed=6))
        tr.run_generation()
        gen1 = lgb.Booster(model_file=p["output_model"]
                           + ".snapshot_iter_3")
        tr.run_generation(*_chunk(100, seed=7))
        gen2 = lgb.Booster(model_file=p["output_model"]
                           + ".snapshot_iter_6")
        # the carried trees' leaf values decayed by exactly 0.5
        for t1, t2 in zip(gen1.trees, gen2.trees[:3]):
            np.testing.assert_allclose(np.asarray(t2.leaf_value),
                                       0.5 * np.asarray(t1.leaf_value),
                                       rtol=1e-12)

    def test_decay_refused_for_linear_trees(self, tmp_path):
        p = _params(tmp_path, continual_decay=0.5, linear_tree=True)
        tr = ContinualTrainer(p, *_chunk(300, seed=8))
        tr.run_generation()
        rep = tr.run_generation(*_chunk(100, seed=9))
        assert rep["status"] == "rolled_back"
        assert "linear-tree" in rep["reason"]

    def test_gate_failure_rolls_back_and_quarantines(self, tmp_path):
        p = _params(tmp_path)
        tr = ContinualTrainer(p, *_chunk(300, seed=10))
        assert tr.run_generation()["status"] == "published"
        incumbent_text = tr._incumbent.model_to_string()
        faultinject.configure("shadow_probe:1-")
        rep = tr.run_generation(*_chunk(100, seed=11))
        faultinject.clear()
        assert rep["status"] == "rolled_back"
        assert rep["stage"] == "shadow_probe"
        # the incumbent is untouched and still the newest snapshot
        assert tr._incumbent.model_to_string() == incumbent_text
        from lightgbm_tpu.snapshot import find_latest_complete_snapshot
        assert find_latest_complete_snapshot(p["output_model"])[0] == 3
        # the candidate is quarantined with a blackbox dump
        q = tr.quarantine_dir
        names = os.listdir(q)
        assert "m.txt.snapshot_iter_6" in names
        assert "m.txt.snapshot_iter_6.manifest.json" in names
        bb = json.load(open(os.path.join(
            q, "m.txt.snapshot_iter_6.blackbox.json")))
        assert bb["stage"] == "shadow_probe"
        assert "shadow_probe" in bb["reason"] or "injected" in bb["reason"]
        snap = tr.metrics.snapshot()
        assert snap["continual.rollbacks"]["value"] == 1
        assert snap["continual.quarantined"]["value"] == 1
        # ...and the NEXT generation recovers from the incumbent
        rep2 = tr.run_generation(*_chunk(100, seed=12))
        assert rep2["status"] == "published"
        assert rep2["iteration"] == 6      # boosted from iter 3, not 6

    def test_transient_stage_faults_retried(self, tmp_path):
        # one trainer, one site per generation: each stage's retry must
        # carry its generation through a single transient fault
        p = _params(tmp_path, continual_retries=2)
        tr = ContinualTrainer(p, *_chunk(260, seed=13))
        assert tr.run_generation()["status"] == "published"
        for i, site in enumerate(["continual_append", "continual_boost",
                                  "continual_publish",
                                  "continual_promote"]):
            # arm AFTER the previous generation (configure resets hit
            # counters): the next occurrence of the site is hit 1
            faultinject.configure(f"{site}:1")
            rep = tr.run_generation(*_chunk(90, seed=14 + i))
            assert rep["status"] == "published", (site, rep)
            assert faultinject.hits(site) >= 2   # fault + retry
        assert tr.metrics.snapshot()["continual.rollbacks"]["value"] == 0

    def test_exhausted_retries_roll_back(self, tmp_path):
        p = _params(tmp_path, continual_retries=1)
        tr = ContinualTrainer(p, *_chunk(260, seed=15))
        assert tr.run_generation()["status"] == "published"
        faultinject.configure("continual_boost:1-")
        rep = tr.run_generation(*_chunk(90, seed=16))
        faultinject.clear()
        assert rep["status"] == "rolled_back"
        assert rep["stage"] == "boost"
        from lightgbm_tpu.snapshot import find_latest_complete_snapshot
        assert find_latest_complete_snapshot(p["output_model"])[0] == 3

    def test_probe_fault_is_gate_failure_not_retry(self, tmp_path):
        # a fault INSIDE the probe is conservative: never promote on an
        # unproven probe — rollback, even though retries remain
        p = _params(tmp_path, continual_retries=3)
        tr = ContinualTrainer(p, *_chunk(260, seed=17))
        assert tr.run_generation()["status"] == "published"
        faultinject.configure("shadow_probe:1")
        rep = tr.run_generation(*_chunk(90, seed=18))
        assert rep["status"] == "rolled_back"
        assert rep["stage"] == "shadow_probe"

    def test_snapshot_keep_clamped_above_one(self, tmp_path):
        tr = ContinualTrainer(_params(tmp_path, snapshot_keep=1),
                              *_chunk(100, seed=19))
        assert tr.config.snapshot_keep == 2


# ---------------------------------------------------------------------------
# serving integration: registry gate, /promote, /freshness
# ---------------------------------------------------------------------------

class TestServeIntegration:
    def _server(self, tmp_path, **kw):
        from lightgbm_tpu.serve.server import Server
        return Server(_params(tmp_path, serve_max_wait_ms=0.5, **kw))

    def test_pipeline_promotes_into_registry(self, tmp_path):
        srv = self._server(tmp_path)
        try:
            tr = ContinualTrainer(srv.config, *_chunk(300, seed=20),
                                  server=srv)
            r0 = tr.run_generation()
            assert r0["status"] == "published"
            assert srv.registry.current().version == r0["version"]
            # live traffic fills the shadow ring; the next gate replays it
            for _ in range(4):
                srv.predict(_rs.randn(8, 6))
            assert len(srv.shadow_batches()) == 4
            r1 = tr.run_generation(*_chunk(140, seed=21))
            assert r1["status"] == "published"
            assert srv.registry.current().version == r1["version"]
            assert r1["gate"]["probe"]["batches"] == 4
            fresh = srv.freshness()
            assert fresh["model_version"] == r1["version"]
            assert fresh["generation"] == 2
            assert fresh["generations_published"] == 2
            assert fresh["freshness_lag_s"] > 0
            # residency hygiene: with no serve_max_resident cap the
            # displaced incumbent is unloaded after the swap — a
            # long-running pipeline must not accumulate generations
            versions = [v["version"] for v in srv.registry.versions()]
            assert versions == [r1["version"]]
        finally:
            srv.close()

    def test_gate_failure_keeps_incumbent_serving(self, tmp_path):
        srv = self._server(tmp_path)
        try:
            tr = ContinualTrainer(srv.config, *_chunk(300, seed=22),
                                  server=srv)
            r0 = tr.run_generation()
            before = srv.predict(np.zeros((2, 6)))
            faultinject.configure("shadow_probe:1-")
            rep = tr.run_generation(*_chunk(100, seed=23))
            faultinject.clear()
            assert rep["status"] == "rolled_back"
            # the refused candidate is gone from the registry and the
            # incumbent answers byte-identically
            versions = [v["version"] for v in srv.registry.versions()]
            assert rep.get("version_refused") not in versions
            assert srv.registry.current().version == r0["version"]
            np.testing.assert_array_equal(
                srv.predict(np.zeros((2, 6))), before)
            assert srv.freshness()["generations_rolled_back"] == 1
        finally:
            srv.close()

    def test_http_promote_and_freshness(self, tmp_path):
        from lightgbm_tpu.serve.server import start_http
        srv = self._server(tmp_path)
        fe = start_http(srv, port=0)
        base = f"http://127.0.0.1:{fe.port}"

        def post(path, body):
            req = urllib.request.Request(
                base + path, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            return json.loads(urllib.request.urlopen(req).read())

        try:
            tr = ContinualTrainer(srv.config, *_chunk(300, seed=24),
                                  server=srv)
            tr.run_generation()
            tr.run_generation(*_chunk(120, seed=25))
            out = str(tmp_path / "m.txt")
            # GET /freshness
            f = json.loads(urllib.request.urlopen(
                base + "/freshness").read())
            assert f["generation"] == 2 and f["freshness_lag_s"] > 0
            assert f["generations_published"] == 2
            # POST /promote of the newest artifact: gate passes
            ok = post("/promote", {"snapshot": out})
            assert ok["model_version"]
            assert ok["gate"]["probe"]["ok"] is True
            # POST /promote with a wrong pin: 409, reason + incumbent
            cur = srv.registry.current().version
            with pytest.raises(urllib.error.HTTPError) as ei:
                post("/promote", {"snapshot": out, "sha256": "0" * 64})
            assert ei.value.code == 409
            body = json.loads(ei.value.read())
            assert "checksum mismatch" in body["reason"]
            assert body["current_version"] == cur
            assert srv.registry.current().version == cur
        finally:
            fe.close()
            srv.close()

    def test_http_reload_409_carries_reason(self, tmp_path):
        from lightgbm_tpu.serve.server import start_http
        x, y = _chunk(200, seed=26)
        bst = lgb.train(dict(BASE), lgb.Dataset(x, label=y),
                        num_boost_round=2)
        mf = str(tmp_path / "m1.txt")
        bst.save_model(mf)
        from lightgbm_tpu.serve.server import Server
        srv = Server({"verbosity": -1}, booster=bst)
        fe = start_http(srv, port=0)
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{fe.port}/reload",
                data=json.dumps({"model_file": mf,
                                 "sha256": "f" * 64}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 409
            body = json.loads(ei.value.read())
            # satellite: the 409 BODY carries the verification failure
            # reason and the version still serving, not a bare status
            assert "checksum mismatch" in body["reason"]
            assert body["verification"] == "failed"
            assert body["current_version"] == "v1"
        finally:
            fe.close()
            srv.close()

    def test_unrelated_incumbent_skips_lineage_not_wedged(self, tmp_path):
        # an operator hot-swaps an UNRELATED hotfix model in: the next
        # generation is a continuation of the SNAPSHOT lineage, not of
        # the incumbent — the lineage gate must stand down (checksum
        # mismatch) instead of quarantining every generation forever.
        # (metric tolerance loosened: whether the candidate BEATS the
        # hotfix is the metric gate's call, not lineage's)
        srv = self._server(tmp_path, shadow_probe_metric_tolerance=10.0)
        try:
            tr = ContinualTrainer(srv.config, *_chunk(300, seed=29),
                                  server=srv)
            assert tr.run_generation()["status"] == "published"
            x, y = _chunk(300, seed=29)
            hotfix = lgb.train(dict(BASE, num_leaves=12),
                               lgb.Dataset(x, label=y),
                               num_boost_round=7)
            srv.reload(booster=hotfix)            # unpinned, unrelated
            rep = tr.run_generation(*_chunk(140, seed=30))
            assert rep["status"] == "published", rep
        finally:
            srv.close()

    def test_probe_batches_zero_disables_replay(self, tmp_path):
        srv = self._server(tmp_path, shadow_probe_batches=0)
        try:
            tr = ContinualTrainer(srv.config, *_chunk(300, seed=31),
                                  server=srv)
            assert tr.run_generation()["status"] == "published"
            srv.predict(_chunk(8, seed=31)[0])
            assert srv.shadow_batches() == []     # ring stays empty
            rep = tr.run_generation(*_chunk(120, seed=32))
            assert rep["status"] == "published"
            assert rep["gate"]["probe"]["batches"] == 0
        finally:
            srv.close()

    def test_self_check_failure_refuses_promotion(self, tmp_path):
        # serve_self_check fault: plain serving demotes to the host
        # walk; the continual gate REFUSES the candidate instead
        srv = self._server(tmp_path)
        try:
            tr = ContinualTrainer(srv.config, *_chunk(300, seed=27),
                                  server=srv)
            r0 = tr.run_generation()
            assert r0["status"] == "published"
            faultinject.configure("serve_self_check:1-")
            rep = tr.run_generation(*_chunk(100, seed=28))
            faultinject.clear()
            assert rep["status"] == "rolled_back"
            assert rep["stage"] == "self_check"
            assert srv.registry.current().version == r0["version"]
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# satellite: registry in-flight guards
# ---------------------------------------------------------------------------

class TestRegistryInflight:
    def _boosters(self, n=3):
        x, y = _chunk(200, seed=30)
        return [lgb.train(dict(BASE), lgb.Dataset(x, label=y),
                          num_boost_round=r) for r in range(2, 2 + n)]

    def test_unload_current_refused_force_allowed(self):
        from lightgbm_tpu.serve.registry import ModelRegistry, NoModelError
        reg = ModelRegistry(build_engine=False)
        b = self._boosters(1)[0]
        v = reg.load(booster=b)
        with pytest.raises(ValueError, match="current"):
            reg.unload(v)
        reg.unload(v, force=True)
        with pytest.raises(NoModelError):
            reg.current()

    def test_shadow_load_into_empty_registry_takes_no_traffic(self):
        # a gate candidate shadow-loaded into a model-less registry
        # must NOT auto-activate: the gated-promotion invariant is that
        # a refused candidate served ZERO requests, including during
        # the gate window before refusal
        from lightgbm_tpu.serve.registry import ModelRegistry, NoModelError
        reg = ModelRegistry(build_engine=False)
        v = reg.load(booster=self._boosters(1)[0], activate=False)
        with pytest.raises(NoModelError):
            reg.current()
        reg.activate(v)
        assert reg.current().version == v

    def test_eviction_skips_inflight_versions(self):
        from lightgbm_tpu.serve.registry import ModelRegistry
        b1, b2, b3 = self._boosters(3)
        reg = ModelRegistry(build_engine=False, max_resident=2)
        v1 = reg.load(booster=b1)                     # current
        v2 = reg.load(booster=b2, activate=False)     # shadow
        # a batch is mid-flight on the shadow version: the next load
        # would evict it (oldest non-current) — it must be skipped
        reg.get(v2).begin_request()
        v3 = reg.load(booster=b3, activate=False)
        versions = {v["version"] for v in reg.versions()}
        assert v2 in versions and v1 in versions and v3 in versions
        # batch finished: the NEXT load may evict it again
        reg.get(v2).end_request()
        b4 = self._boosters(1)[0]
        reg.load(booster=b4, activate=False)
        versions = {v["version"] for v in reg.versions()}
        assert v2 not in versions

    def test_inflight_counter_brackets_serving(self, tmp_path):
        from lightgbm_tpu.serve.server import Server
        x, y = _chunk(150, seed=31)
        bst = lgb.train(dict(BASE), lgb.Dataset(x, label=y),
                        num_boost_round=2)
        srv = Server({"verbosity": -1, "serve_max_wait_ms": 0.5},
                     booster=bst)
        try:
            srv.predict(x[:4])
            served = srv.registry.current()
            assert served.inflight == 0          # bracketed, not leaked
            assert served.describe()["inflight"] == 0
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# satellite: snapshot prune TOCTOU
# ---------------------------------------------------------------------------

class TestSnapshotPinning:
    def _make_snapshots(self, tmp_path, rounds=(2, 4, 6)):
        out = str(tmp_path / "m.txt")
        x, y = _chunk(200, seed=32)
        lgb.train(dict(BASE, snapshot_freq=2, snapshot_keep=0,
                       output_model=out),
                  lgb.Dataset(x, label=y), num_boost_round=max(rounds))
        return out

    def test_pinned_generation_survives_prune(self, tmp_path):
        from lightgbm_tpu.snapshot import pin_snapshot, prune_snapshots
        out = self._make_snapshots(tmp_path)
        oldest = out + ".snapshot_iter_2"
        with pin_snapshot(oldest):
            prune_snapshots(out, 1)
            assert os.path.exists(oldest)            # pinned: held
            assert not os.path.exists(out + ".snapshot_iter_4")
        prune_snapshots(out, 1)                      # unpinned: goes
        assert not os.path.exists(oldest)
        assert os.path.exists(out + ".snapshot_iter_6")

    def test_registry_rescans_once_on_pruned_snapshot(self, tmp_path,
                                                      monkeypatch):
        from lightgbm_tpu import snapshot as snap_mod
        from lightgbm_tpu.serve.registry import ModelRegistry
        out = self._make_snapshots(tmp_path)
        real = snap_mod.find_latest_complete_snapshot
        stale_path = out + ".snapshot_iter_9"        # never existed
        calls = []

        def finder(output_model, verify=True):
            calls.append(1)
            if len(calls) == 1:
                # the TOCTOU: the finder located a generation that a
                # concurrent prune deletes before the reader opens it
                return 9, stale_path
            return real(output_model, verify)

        monkeypatch.setattr(snap_mod, "find_latest_complete_snapshot",
                            finder)
        reg = ModelRegistry(build_engine=False)
        v = reg.load_snapshot(out)
        assert len(calls) == 2                       # re-scanned ONCE
        assert "snapshot_iter_6" in reg.get(v).source

    def test_resume_rescans_once_on_pruned_snapshot(self, tmp_path,
                                                    monkeypatch):
        out = str(tmp_path / "m.txt")
        x, y = _chunk(200, seed=33)
        p = dict(BASE, snapshot_freq=2, snapshot_keep=0,
                 output_model=out)
        straight = lgb.train(dict(p), lgb.Dataset(x, label=y),
                             num_boost_round=6)
        from lightgbm_tpu import snapshot as snap_mod
        real = snap_mod.find_latest_snapshot
        calls = []

        def finder(output_model, signature, train_set):
            calls.append(1)
            found = real(output_model, signature, train_set)
            if len(calls) == 1 and found is not None:
                it, path, score = found
                return it, str(tmp_path / "vanished.snapshot"), score
            return found

        monkeypatch.setattr(snap_mod, "find_latest_snapshot", finder)
        resumed = lgb.train(dict(p, resume=True),
                            lgb.Dataset(x, label=y), num_boost_round=6)
        assert len(calls) == 2
        assert resumed.model_to_string() == straight.model_to_string()


# ---------------------------------------------------------------------------
# satellite: best_iteration is absolute for continued runs
# ---------------------------------------------------------------------------

class TestBestIterationContinuation:
    def _stopping_feval(self, best_at):
        """Deterministic custom metric: improves until ``best_at`` calls,
        then worsens — early stopping fires with a known best."""
        calls = []

        def feval(preds, ds):
            it = len(calls)
            calls.append(it)
            return ("gate", abs(it - best_at) + 1.0, False)

        return feval

    def test_best_iteration_includes_init_model_trees(self, tmp_path):
        x, y = _chunk(400, seed=34)
        ds = lgb.Dataset(x, label=y, free_raw_data=False)
        m1 = lgb.train(dict(BASE), ds, num_boost_round=5)
        vs = lgb.Dataset(x[:100], label=y[:100])
        m2 = lgb.train(dict(BASE, metric="custom"),
                       lgb.Dataset(x, label=y, free_raw_data=False),
                       num_boost_round=10, valid_sets=[vs],
                       valid_names=["v"],
                       feval=self._stopping_feval(2), init_model=m1,
                       callbacks=[lgb.early_stopping(2, verbose=False)])
        # best is the continued run's 3rd iteration == absolute 5 + 3
        assert m2.best_iteration == 8
        # predict's best-iteration default slices the merged forest:
        # identical to an explicit absolute slice, and NOT to the
        # run-relative (wrong) slice
        np.testing.assert_array_equal(
            m2.predict(x[:50]), m2.predict(x[:50], num_iteration=8))
        assert not np.array_equal(
            m2.predict(x[:50]), m2.predict(x[:50], num_iteration=3))

    def test_save_continue_save_roundtrip_consistent(self, tmp_path):
        x, y = _chunk(400, seed=35)
        m1 = lgb.train(dict(BASE),
                       lgb.Dataset(x, label=y, free_raw_data=False),
                       num_boost_round=4)
        p1 = str(tmp_path / "m1.txt")
        m1.save_model(p1)
        vs = lgb.Dataset(x[:100], label=y[:100])
        m2 = lgb.train(dict(BASE, metric="custom"),
                       lgb.Dataset(x, label=y, free_raw_data=False),
                       num_boost_round=8, valid_sets=[vs],
                       valid_names=["v"],
                       feval=self._stopping_feval(1), init_model=p1,
                       callbacks=[lgb.early_stopping(2, verbose=False)])
        assert m2.best_iteration == 4 + 2
        # save at best -> reload -> predictions match the live booster's
        # best-sliced predictions (the round-trip the satellite pins)
        p2 = str(tmp_path / "m2.txt")
        m2.save_model(p2, num_iteration=m2.best_iteration)
        reloaded = lgb.Booster(model_file=p2)
        np.testing.assert_array_equal(reloaded.predict(x[:64]),
                                      m2.predict(x[:64]))

    def test_resume_best_iteration_unchanged(self, tmp_path):
        # a RESUMED run's loop index is already absolute — the offset
        # must not double-count (regression guard for the fix)
        out = str(tmp_path / "m.txt")
        x, y = _chunk(300, seed=36)
        # metric in BOTH runs' params: the resume's params signature
        # must match the snapshot writer's or nothing resumes
        p = dict(BASE, snapshot_freq=2, output_model=out,
                 metric="custom")
        lgb.train(dict(p), lgb.Dataset(x, label=y), num_boost_round=4)
        vs = lgb.Dataset(x[:80], label=y[:80])
        m = lgb.train(dict(p, resume=True),
                      lgb.Dataset(x, label=y), num_boost_round=10,
                      valid_sets=[vs], valid_names=["v"],
                      feval=self._stopping_feval(1),
                      callbacks=[lgb.early_stopping(2, verbose=False)])
        # resume continues at iteration 4; the feval's first call is
        # iteration 5 (env.iteration 4), best at its 2nd call -> abs 6
        assert m.best_iteration == 6


# ---------------------------------------------------------------------------
# satellite: kill -9 matrix at every stage boundary
# ---------------------------------------------------------------------------

class TestKillMatrix:
    N_CHUNKS = 1    # two generations: incumbent + the one under fire
    WORKER = os.path.join(REPO, "tests", "continual_worker.py")

    def _spawn(self, outdir, faults=None):
        env = dict(os.environ)
        env.pop("LGBM_TPU_FAULTS", None)
        if faults:
            env["LGBM_TPU_FAULTS"] = faults
        return subprocess.Popen(
            [sys.executable, self.WORKER, str(outdir),
             str(self.N_CHUNKS)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)

    @staticmethod
    def _wait(procs, timeout=240):
        """{name: (returncode, output)} for a batch of concurrent
        workers (the matrix runs its independent dirs in parallel to
        stay inside the tier-1 wall-clock budget)."""
        out = {}
        for name, p in procs.items():
            try:
                stdout, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                stdout, _ = p.communicate()
                stdout = (stdout or "") + "\n<worker timed out>"
            out[name] = (p.returncode, stdout)
        return out

    def _run_worker(self, outdir, faults=None, timeout=240):
        p = self._spawn(outdir, faults=faults)
        rc, stdout = self._wait({"one": p}, timeout=timeout)["one"]

        class R:
            returncode, output = rc, stdout

        return R

    def _audit_disk(self, outdir):
        """After a kill, every COMPLETE snapshot must verify and a
        serving bring-up from disk must succeed — the dead pipeline
        never leaves serving without a verified incumbent."""
        from lightgbm_tpu.serve.registry import ModelRegistry
        from lightgbm_tpu.snapshot import (find_latest_complete_snapshot,
                                           verify_snapshot_artifacts)
        out = os.path.join(str(outdir), "m.txt")
        for man in glob.glob(out + ".snapshot_iter_*.manifest.json"):
            path = man[:-len(".manifest.json")]
            with open(man, encoding="utf-8") as f:
                assert verify_snapshot_artifacts(
                    path, json.load(f), state=True) is None, path
        found = find_latest_complete_snapshot(out)
        if found is not None:
            reg = ModelRegistry(build_engine=False)
            reg.load_snapshot(out)
            assert reg.current() is not None

    @staticmethod
    def _normalize(text):
        """The one legitimately path-dependent byte of a published
        model: its own output_model parameter line."""
        return "\n".join(ln for ln in text.splitlines()
                         if not ln.startswith("[output_model:"))

    def test_kill_exit_matrix_converges_byte_identical(self, tmp_path):
        # the clean reference run goes first, alone — it also warms the
        # persistent compile cache for the concurrent batches below
        clean = tmp_path / "clean"
        clean.mkdir()
        r = self._run_worker(clean)
        assert r.returncode == 0, r.output
        final_clean = self._normalize(
            open(clean / "final.txt", encoding="utf-8").read())
        # fault spec per stage boundary: hit indices target the SECOND
        # generation (the base generation must land so there is an
        # incumbent to protect); snapshot_kill:5 dies mid-publish
        # between the model and manifest writes — the torn-write window
        matrix = {
            "continual_append": "continual_append:1:exit",
            "continual_boost": "continual_boost:2:exit",
            "continual_publish": "continual_publish:2:exit",
            "continual_promote": "continual_promote:2:exit",
            "shadow_probe": "shadow_probe:1:exit",
            "publish_torn_write": "snapshot_kill:5:exit",
        }
        for name in matrix:
            (tmp_path / name).mkdir()
        # batch 1: every stage-boundary kill, concurrently (independent
        # dirs; serializing 12 jax subprocesses would not fit tier-1)
        killed = self._wait({name: self._spawn(tmp_path / name,
                                               faults=spec)
                             for name, spec in matrix.items()})
        for name, (rc, output) in killed.items():
            assert rc == 23, (f"{name}: expected injected exit(23), "
                              f"got {rc}\n{output}")
            # serving invariant while the pipeline is dead
            self._audit_disk(tmp_path / name)
        # batch 2: restart every dir with no faults — byte-identical
        # convergence with the uninterrupted run
        resumed = self._wait({name: self._spawn(tmp_path / name)
                              for name in matrix})
        for name, (rc, output) in resumed.items():
            assert rc == 0, f"{name}: restart failed\n{output}"
            final = self._normalize(
                open(tmp_path / name / "final.txt",
                     encoding="utf-8").read())
            assert final == final_clean, \
                f"{name}: restart did not converge byte-identically"


# ---------------------------------------------------------------------------
# chaos soak (tools/soak_serve.py --continual) — short tier-1 run
# ---------------------------------------------------------------------------

class TestContinualSoak:
    def test_short_continual_soak_with_gate_failure(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import soak_serve
        report = soak_serve.run_continual_soak(
            duration_s=1.5, clients=2, generations=2, seed=0,
            gate_failure=True)
        assert report["violations"] == [], report
        gens = report["generations"]
        assert gens[0]["status"] == "published"      # base incumbent
        assert gens[1]["status"] == "rolled_back"    # injected gate fail
        assert gens[2]["status"] == "published"      # recovery
        assert report["metrics"]["continual.rollbacks"]["value"] == 1
        assert report["freshness"]["generations_published"] == 2
        assert report["counts"].get("hung", 0) == 0
        assert report["counts"]["ok"] > 0


# ---------------------------------------------------------------------------
# CLI task=continual
# ---------------------------------------------------------------------------

class TestContinualCLI:
    def test_task_continual_end_to_end(self, tmp_path, capsys):
        from lightgbm_tpu.cli import run as cli_run

        def write_csv(path, n, seed):
            x, y = _chunk(n, seed=seed, n_feat=4)
            np.savetxt(path, np.column_stack([y, x]), delimiter=",",
                       fmt="%.8g")

        base = str(tmp_path / "base.csv")
        c1 = str(tmp_path / "c1.csv")
        c2 = str(tmp_path / "c2.csv")
        write_csv(base, 200, 40)
        write_csv(c1, 80, 41)
        write_csv(c2, 80, 42)
        out = str(tmp_path / "m.txt")
        rc = cli_run(["task=continual", f"data={base}",
                      f"continual_data={c1},{c2}", f"output_model={out}",
                      "continual_rounds=2", "num_leaves=6",
                      "min_data_in_leaf=5", "verbosity=-1"])
        assert rc == 0
        lines = capsys.readouterr().out.strip().splitlines()
        reports = [json.loads(ln) for ln in lines
                   if ln.startswith("{")]
        assert len(reports) == 3
        assert all(r["status"] == "published" for r in reports)
        assert [r["iteration"] for r in reports] == [2, 4, 6]
        from lightgbm_tpu.snapshot import find_latest_complete_snapshot
        assert find_latest_complete_snapshot(out)[0] == 6

    def test_bare_continual_token(self, tmp_path):
        from lightgbm_tpu.cli import _load_params
        p = _load_params(["continual", "data=x.csv"])
        assert p["task"] == "continual"
