"""Embedding bridge for the native training C API.

The reference exposes its full training surface through C
(/root/reference/src/c_api.cpp: LGBM_DatasetCreateFromMat :~900,
LGBM_BoosterCreate :1600, LGBM_BoosterUpdateOneIter :1686,
LGBM_BoosterSaveModel...).  In the TPU rebuild the training core is a JAX
program, so the native shim (native/capi_train.cpp) embeds CPython and
calls these thin adapters; zero-copy views of the caller's buffers come in
as memoryviews.

Functions here must stay exception-safe-by-contract: the C++ caller
converts any raised exception into LGBM_GetLastError().
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

if os.environ.get("LGBM_TPU_FORCE_CPU"):
    # embedded hosts (pure-C callers) can't run the test conftest; honor an
    # env switch so they avoid claiming the exclusive TPU tunnel
    import jax
    jax.config.update("jax_platforms", "cpu")

from .booster import Booster
from .config import kv2map
from .dataset import Dataset

_F32, _F64, _I32, _I64 = 0, 1, 2, 3
_NP_OF = {_F32: np.float32, _F64: np.float64, _I32: np.int32, _I64: np.int64}


def _params(s: str) -> dict:
    return kv2map((s or "").replace("\n", " ").split())


def dataset_create_from_mat(mv, nrow: int, ncol: int, params: str,
                            reference: Optional[Dataset] = None) -> Dataset:
    arr = np.frombuffer(mv, np.float64).reshape(int(nrow), int(ncol)).copy()
    return Dataset(arr, params=_params(params), reference=reference)


def dataset_create_from_file(path: str, params: str,
                             reference: Optional[Dataset] = None) -> Dataset:
    from .data_io import load_text
    p = _params(params)
    x, y = load_text(path, has_header=str(p.get("header", "")).lower()
                     in ("true", "1"),
                     label_column=str(p.get("label_column", "")))
    return Dataset(x, label=y, params=p, reference=reference)


def dataset_set_field(ds: Dataset, name: str, mv, n: int, dtype: int) -> None:
    arr = np.frombuffer(mv, _NP_OF[int(dtype)])[:int(n)].copy()
    if name == "label":
        ds.set_label(arr)
    elif name == "weight":
        ds.set_weight(arr)
    elif name in ("group", "query"):
        ds.set_group(arr)
    elif name == "init_score":
        ds.set_init_score(arr)
    else:
        raise ValueError(f"unknown field {name!r}")


def dataset_num_data(ds: Dataset) -> int:
    ds.construct()
    return int(ds.num_data)


def dataset_num_feature(ds: Dataset) -> int:
    ds.construct()
    return int(ds.num_total_features)


def booster_create(ds: Dataset, params: str) -> Booster:
    return Booster(params=_params(params), train_set=ds)


def booster_create_from_model_string(s: str) -> Booster:
    return Booster(model_str=s)


def booster_add_valid(bst: Booster, ds: Dataset, name: str) -> None:
    bst.add_valid(ds, name)


def booster_update(bst: Booster) -> int:
    return 1 if bst.update() else 0


def booster_rollback(bst: Booster) -> None:
    bst.rollback_one_iter()


def booster_current_iteration(bst: Booster) -> int:
    return int(bst.current_iteration)


def booster_num_classes(bst: Booster) -> int:
    return int(bst._num_class)


def booster_save_model_to_string(bst: Booster, start_iteration: int,
                                 num_iteration: int) -> str:
    num = num_iteration if num_iteration > 0 else None
    return bst.model_to_string(num_iteration=num,
                               start_iteration=int(start_iteration))


def booster_save_model(bst: Booster, start_iteration: int,
                       num_iteration: int, filename: str) -> None:
    with open(filename, "w") as f:
        f.write(booster_save_model_to_string(bst, start_iteration,
                                             num_iteration))


def booster_get_eval(bst: Booster) -> str:
    """One eval sweep, rendered as 'name metric value' lines."""
    rows = bst.eval_valid() + bst.eval_train()
    return "\n".join(f"{dn}\t{mn}\t{val!r}" for dn, mn, val, _ in rows)


def booster_predict_mat(bst: Booster, mv, nrow: int, ncol: int,
                        predict_type: int, start_iteration: int,
                        num_iteration: int, out_mv) -> int:
    """predict_type: 0 normal, 1 raw, 2 leaf index, 3 contrib
    (C_API_PREDICT_* values, c_api.h:527-535)."""
    x = np.frombuffer(mv, np.float64).reshape(int(nrow), int(ncol))
    num = num_iteration if num_iteration > 0 else None
    kw = dict(start_iteration=int(start_iteration), num_iteration=num)
    if predict_type == 2:
        res = bst.predict(x, pred_leaf=True, **kw).astype(np.float64)
    elif predict_type == 3:
        res = bst.predict(x, pred_contrib=True, **kw).astype(np.float64)
    else:
        res = bst.predict(x, raw_score=(predict_type == 1),
                          **kw).astype(np.float64)
    flat = np.ascontiguousarray(res).reshape(-1)
    out = np.frombuffer(out_mv, np.float64)
    if len(flat) > len(out):
        raise ValueError(f"output buffer too small: need {len(flat)}, "
                         f"have {len(out)}")
    out[:len(flat)] = flat
    return int(len(flat))
