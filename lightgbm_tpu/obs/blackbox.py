"""Flight recorder: a bounded ring of per-iteration records, dumped as
JSONL when something goes wrong.

The post-mortem counterpart of the live telemetry: with
``telemetry_blackbox=true`` the training driver (and the serve batch
path) append one small host-side record per iteration/batch — phase
seconds, train/valid metric, finite-guard flags, static comm/flop
counters — into a ``deque(maxlen=K)``.  On an exception, a watchdog
fire (utils/resilience.Watchdog), or a ``finite_check_policy``
trigger, the last K records are written as JSONL
(:func:`~lightgbm_tpu.obs.trace.read_jsonl`-parseable: one header
line with the dump reason, then one line per record, oldest first).

Zero-cost when disabled: :func:`maybe_recorder` returns None (no ring
allocation, no file is ever created) and every wiring point is a
single ``is None`` branch.  Recording NEVER touches the device — all
fields are values the driver already holds host-side, so the sync
lint stays green with the recorder on.

``dump_all(reason)`` dumps every live recorder in the process — the
hook the resilience watchdog and the train-loop exception path use so
one registration point serves every surface.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, Optional

# live recorders (weak: a dropped Booster must not pin its ring)
_LIVE: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()
_LIVE_LOCK = threading.Lock()

# dump throttle, PER REASON: a flapping trigger (e.g. a finite guard
# tripping every iteration) stops re-writing the file after this many
# dumps — but only for ITS reason, so the one dump that matters most
# (the eventual train_exception / watchdog) always still lands.  All
# dumps os.replace one path, so disk fill is not the concern; repeated
# fsync on the hot path is.
MAX_DUMPS_PER_REASON = 8


class FlightRecorder:
    """Bounded per-iteration record ring with crash-dump semantics."""

    def __init__(self, path: str, last_k: int = 64,
                 meta: Optional[Dict[str, Any]] = None):
        self.path = os.fspath(path)
        self.capacity = max(1, int(last_k))
        self.meta = dict(meta or {})
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._dumps: Dict[str, int] = {}
        with _LIVE_LOCK:
            _LIVE.add(self)

    # -- recording (hot path: one dict build + deque append) --------------
    def record(self, **fields: Any) -> None:
        rec = {"t": round(time.time(), 3)}
        rec.update(fields)
        with self._lock:
            self._ring.append(rec)

    def annotate_last(self, **fields: Any) -> None:
        """Merge fields into the newest record (the engine loop adds
        eval results computed after the iteration record landed)."""
        with self._lock:
            if self._ring:
                self._ring[-1].update(fields)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- dumping -----------------------------------------------------------
    def dump(self, reason: str) -> Optional[str]:
        """Write the ring as JSONL (header line + one record per line,
        oldest first); returns the path, or None when this reason's
        dump budget is exhausted or the write failed (a failing
        recorder must never mask the error that triggered it)."""
        with self._lock:
            if self._dumps.get(reason, 0) >= MAX_DUMPS_PER_REASON:
                return None
            self._dumps[reason] = self._dumps.get(reason, 0) + 1
            records = list(self._ring)
        header = {"blackbox": True, "reason": reason,
                  "t": round(time.time(), 3), "pid": os.getpid(),
                  "n_records": len(records), "meta": self.meta}
        try:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            # plain write-then-replace (not resilience.atomic_write: its
            # fault-injection sites must not fire inside a crash dump)
            tmp = f"{self.path}.{os.getpid()}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(json.dumps(header) + "\n")
                for rec in records:
                    f.write(json.dumps(rec, default=str) + "\n")
                f.flush()
                try:
                    os.fsync(f.fileno())
                except OSError:
                    pass
            os.replace(tmp, self.path)
        except OSError:
            return None
        from ..utils.log import Log
        Log.warning(f"flight recorder: dumped last {len(records)} "
                    f"record(s) to {self.path} (reason: {reason})")
        return self.path

    def close(self) -> None:
        with _LIVE_LOCK:
            _LIVE.discard(self)


def maybe_recorder(config, default_path: str = "lgbtpu_blackbox.jsonl",
                   meta: Optional[Dict[str, Any]] = None
                   ) -> Optional[FlightRecorder]:
    """Build a FlightRecorder from Config params, or None when
    ``telemetry_blackbox=false`` (the default) — the only thing the
    hot path ever does with the recorder off is test this None."""
    if not getattr(config, "telemetry_blackbox", False):
        return None
    path = getattr(config, "telemetry_blackbox_path", "") or default_path
    return FlightRecorder(
        path, last_k=getattr(config, "telemetry_blackbox_last_k", 64),
        meta=meta)


def any_live() -> bool:
    with _LIVE_LOCK:
        return len(_LIVE) > 0


def dump_all(reason: str) -> int:
    """Dump every live recorder; returns how many dumped.  Cheap when
    none are registered (the disabled-recorder fast path)."""
    with _LIVE_LOCK:
        recs = list(_LIVE)
    n = 0
    for r in recs:
        if r.dump(reason) is not None:
            n += 1
    return n


def watchdog_timer(timeout_s: float, label: str = ""
                   ) -> Optional[threading.Timer]:
    """A started daemon timer that dumps every live recorder if a
    blocking call outlives ``timeout_s`` (armed by
    utils/resilience.Watchdog next to its faulthandler dump).  Returns
    None — and costs nothing — when no recorder is live."""
    if timeout_s <= 0 or not any_live():
        return None
    t = threading.Timer(
        timeout_s, dump_all,
        args=(f"watchdog:{label}" if label else "watchdog",))
    t.daemon = True
    t.start()
    return t
