"""Benchmark: HIGGS-shaped binary classification training throughput.

Mirrors the reference's headline experiment (docs/Experiments.rst: HIGGS,
500 iterations, num_leaves=255 -> 130.094 s on 2x E5-2690v4, i.e. 3.843
iters/s; GPU docs recommend 63 bins for accelerator runs,
docs/GPU-Performance.rst:108-124).  This benches a 1M-row slice of that
shape; ``vs_baseline`` is our steady-state iters/s over the reference's
full-size 3.843 iters/s.

Robustness (round-1 postmortem: one TPU-claim hiccup lost the round's perf
signal): the measurement runs in a CHILD process; the parent retries with
backoff on failure, falls back to a reduced CPU run as a last resort, and
ALWAYS prints exactly one JSON line
{"metric", "value", "unit", "vs_baseline"[, "error"]}.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

BASELINE_IPS = 500.0 / 130.094  # reference HIGGS CPU (Experiments.rst:113)
METRIC = "higgs1m_binary_train_iters_per_sec"
N_ROWS, N_FEAT = 1_000_000, 28
ITERS = 100

# bf16/f32 MXU peak per chip for MFU estimate (How-to-Scale-Your-Model
# hardware tables); unknown kinds report FLOP/s only.
PEAK_FLOPS = {
    # device_kind strings normalize like "tpuv5lite" / "tpuv4" etc.
    "v5lite": 197e12, "v5e": 197e12, "v5p": 459e12,
    "v4": 275e12, "v6e": 918e12, "v6lite": 918e12,
}


def make_higgs_like(n: int, f: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float32)
    logit = (1.2 * x[:, 0] - 0.8 * x[:, 1] + 0.6 * x[:, 2] * x[:, 3]
             + 0.4 * np.abs(x[:, 4]) + 0.5 * rng.randn(n))
    y = (logit > 0).astype(np.float32)
    return x, y


def child(iters: int) -> None:
    """The actual measurement; prints the JSON line on success."""
    x, y = make_higgs_like(N_ROWS, N_FEAT)

    print("[bench] data ready; importing jax / claiming device...",
          file=sys.stderr, flush=True)
    t_dev = time.time()
    import jax
    devs = jax.devices()
    print(f"[bench] devices={devs} ({time.time() - t_dev:.1f}s)",
          file=sys.stderr, flush=True)
    import lightgbm_tpu as lgb
    from lightgbm_tpu.metrics import _auc

    num_leaves, max_bin = 31, 63
    params = {
        "objective": "binary",
        "num_leaves": num_leaves,
        "learning_rate": 0.1,
        "max_bin": max_bin,
        "min_data_in_leaf": 20,
        "verbosity": 0,
    }
    t_bin0 = time.time()
    ds = lgb.Dataset(x, label=y)
    ds.construct()
    t_bin = time.time() - t_bin0

    bst = lgb.Booster(params=params, train_set=ds)
    # warmup: first iteration includes XLA compilation
    t0 = time.time()
    bst.update()
    t_compile = time.time() - t0

    t1 = time.time()
    for i in range(iters - 1):
        bst.update()
        if (i + 1) % 20 == 0:
            print(f"[bench] iter {i + 1}/{iters - 1} "
                  f"({(i + 1) / (time.time() - t1):.2f} iters/s)",
                  file=sys.stderr, flush=True)
    # force device sync
    np.asarray(bst._model.score)
    dt = time.time() - t1
    ips = (iters - 1) / dt

    # observability: achieved histogram FLOP/s + MFU estimate.  Dominant
    # work per iteration is the one-hot-matmul histogram pass per split:
    # [3, N] @ [N, F*B] = 2*3*N*F*B FLOPs, (num_leaves-1) splits/tree
    # (subtraction trick already halves what a naive build would do).
    hist_flops_per_iter = 2.0 * 3 * N_ROWS * N_FEAT * max_bin * (num_leaves - 1)
    achieved = hist_flops_per_iter * ips
    kind = devs[0].device_kind.lower().replace(" ", "")
    peak = next((v for k, v in PEAK_FLOPS.items() if k in kind), None)
    mfu = f"{achieved / peak:.1%}" if peak else "n/a"
    auc = _auc(y, np.asarray(bst._model.train_score())[:, 0], None)
    print(f"[bench] bin={t_bin:.1f}s compile+iter1={t_compile:.1f}s "
          f"steady={dt:.1f}s for {iters - 1} iters -> {ips:.2f} iters/s "
          f"train-AUC={auc:.4f} hist~{achieved / 1e12:.2f} TFLOP/s "
          f"(MFU~{mfu} of {devs[0].device_kind})", file=sys.stderr)

    print(json.dumps({
        "metric": METRIC,
        "value": round(ips, 3),
        "unit": "iters/s (1M rows x 28 feat, 31 leaves, 63 bins)",
        "vs_baseline": round(ips / BASELINE_IPS, 3),
    }), flush=True)


def run_child(extra_env, iters: int, timeout: int):
    env = dict(os.environ, _BENCH_CHILD="1", _BENCH_ITERS=str(iters))
    env.update(extra_env)
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, capture_output=True, text=True,
                           timeout=timeout)
    except subprocess.TimeoutExpired as e:
        # TimeoutExpired.stderr is bytes even under text=True
        err_txt = (e.stderr.decode(errors="replace")
                   if isinstance(e.stderr, bytes) else (e.stderr or ""))
        sys.stderr.write(err_txt[-2000:])
        return None, f"timeout after {timeout}s"
    sys.stderr.write(r.stderr[-4000:] if r.stderr else "")
    for line in (r.stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{") and METRIC in line:
            return line, None
    return None, f"rc={r.returncode}, no JSON line"


def main():
    if os.environ.get("_BENCH_CHILD"):
        child(int(os.environ.get("_BENCH_ITERS", ITERS)))
        return

    errors = []
    # attempt 1-3: the default backend (TPU when available), with backoff —
    # transient tunnel/claim failures were the round-1 failure mode
    for attempt, backoff in enumerate((0, 20, 60)):
        if backoff:
            print(f"[bench] retrying in {backoff}s...", file=sys.stderr,
                  flush=True)
            time.sleep(backoff)
        line, err = run_child({}, ITERS, timeout=2400)
        if line:
            print(line, flush=True)
            return
        errors.append(f"attempt{attempt + 1}: {err}")
        print(f"[bench] attempt {attempt + 1} failed: {err}", file=sys.stderr,
              flush=True)

    # last resort: reduced-iteration CPU run — an honest degraded number
    # beats no number
    line, err = run_child({"JAX_PLATFORMS": "cpu"}, 12, timeout=2400)
    if line:
        rec = json.loads(line)
        rec["error"] = ("degraded: accelerator unavailable, CPU fallback; "
                        + "; ".join(errors))
        print(json.dumps(rec), flush=True)
        return
    errors.append(f"cpu-fallback: {err}")
    print(json.dumps({
        "metric": METRIC, "value": 0.0, "unit": "iters/s",
        "vs_baseline": 0.0, "error": "; ".join(errors)}), flush=True)


if __name__ == "__main__":
    main()
