"""Configuration system for the TPU-native GBDT framework.

Mirrors the reference's single Config-struct-of-record design
(/root/reference/include/LightGBM/config.h:34-1234, src/io/config.cpp:195
``Config::Set`` pipeline: KV2Map -> alias resolution -> member parse ->
``CheckParamConflict``), rebuilt as a Python dataclass-of-record with the
same parameter names, aliases and defaults.  Docs and alias tables are
derived from the single ``_PARAMS`` table below (the reference generates
them from header comments via helpers/parameter_generator.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Union

# ---------------------------------------------------------------------------
# Parameter table: name -> (type, default, aliases)
# Names/defaults follow the reference parameter list
# (/root/reference/include/LightGBM/config.h and docs/Parameters.rst).
# ---------------------------------------------------------------------------

_PARAMS: Dict[str, tuple] = {
    # ---- core ----
    "objective": (str, "regression", ["objective_type", "app", "application", "loss"]),
    "boosting": (str, "gbdt", ["boosting_type", "boost"]),
    "data_sample_strategy": (str, "bagging", []),
    "num_iterations": (int, 100, ["num_iteration", "n_iter", "num_tree", "num_trees",
                                  "num_round", "num_rounds", "nrounds", "num_boost_round",
                                  "n_estimators", "max_iter"]),
    "learning_rate": (float, 0.1, ["shrinkage_rate", "eta"]),
    "num_leaves": (int, 31, ["num_leaf", "max_leaves", "max_leaf", "max_leaf_nodes"]),
    "tree_learner": (str, "serial", ["tree", "tree_type", "tree_learner_type"]),
    "num_threads": (int, 0, ["num_thread", "nthread", "nthreads", "n_jobs"]),
    "device_type": (str, "tpu", ["device"]),
    "seed": (int, 0, ["random_seed", "random_state"]),
    # Honored by design: the functional JAX training path is
    # deterministic for a fixed config+data+device regardless of this
    # flag (host RNGs are seeded; XLA reduction order is fixed per
    # compiled program) — unlike the reference, where it forces
    # col/row-wise choice to tame OpenMP ordering (config.h:233).
    # Tested by tests/test_extra_params.py::test_deterministic_by_design.
    "deterministic": (bool, False, []),
    # ---- learning control ----
    "force_col_wise": (bool, False, []),
    "force_row_wise": (bool, False, []),
    "histogram_pool_size": (float, -1.0, ["hist_pool_size"]),
    "max_depth": (int, -1, []),
    "min_data_in_leaf": (int, 20, ["min_data_per_leaf", "min_data", "min_child_samples",
                                   "min_samples_leaf"]),
    "min_sum_hessian_in_leaf": (float, 1e-3, ["min_sum_hessian_per_leaf", "min_sum_hessian",
                                              "min_hessian", "min_child_weight"]),
    "bagging_fraction": (float, 1.0, ["sub_row", "subsample", "bagging"]),
    "pos_bagging_fraction": (float, 1.0, ["pos_sub_row", "pos_subsample", "pos_bagging"]),
    "neg_bagging_fraction": (float, 1.0, ["neg_sub_row", "neg_subsample", "neg_bagging"]),
    "bagging_freq": (int, 0, ["subsample_freq"]),
    "bagging_seed": (int, 3, ["bagging_fraction_seed"]),
    "feature_fraction": (float, 1.0, ["sub_feature", "colsample_bytree"]),
    "feature_fraction_bynode": (float, 1.0, ["sub_feature_bynode", "colsample_bynode"]),
    "feature_fraction_seed": (int, 2, []),
    "extra_trees": (bool, False, ["extra_tree"]),
    "extra_seed": (int, 6, []),
    "early_stopping_round": (int, 0, ["early_stopping_rounds", "early_stopping",
                                      "n_iter_no_change"]),
    "first_metric_only": (bool, False, []),
    "max_delta_step": (float, 0.0, ["max_tree_output", "max_leaf_output"]),
    "lambda_l1": (float, 0.0, ["reg_alpha", "l1_regularization"]),
    "lambda_l2": (float, 0.0, ["reg_lambda", "lambda", "l2_regularization"]),
    "linear_lambda": (float, 0.0, []),
    "min_gain_to_split": (float, 0.0, ["min_split_gain"]),
    "drop_rate": (float, 0.1, ["rate_drop"]),
    "max_drop": (int, 50, []),
    "skip_drop": (float, 0.5, []),
    "xgboost_dart_mode": (bool, False, []),
    "uniform_drop": (bool, False, []),
    "drop_seed": (int, 4, []),
    "top_rate": (float, 0.2, []),
    "other_rate": (float, 0.1, []),
    "min_data_per_group": (int, 100, []),
    "max_cat_threshold": (int, 32, []),
    "cat_l2": (float, 10.0, []),
    "cat_smooth": (float, 10.0, []),
    "max_cat_to_onehot": (int, 4, []),
    "top_k": (int, 20, ["topk"]),
    "monotone_constraints": (list, None, ["mc", "monotone_constraint", "monotonic_cst"]),
    "monotone_constraints_method": (str, "basic", ["monotone_constraining_method", "mc_method"]),
    "monotone_penalty": (float, 0.0, ["monotone_splits_penalty", "ms_penalty", "mc_penalty"]),
    "feature_contri": (list, None, ["feature_contrib", "fc", "fp", "feature_penalty"]),
    "forcedsplits_filename": (str, "", ["fs", "forced_splits_filename", "forced_splits_file",
                                        "forced_splits"]),
    "refit_decay_rate": (float, 0.9, []),
    "cegb_tradeoff": (float, 1.0, []),
    "cegb_penalty_split": (float, 0.0, []),
    "cegb_penalty_feature_lazy": (list, None, []),
    "cegb_penalty_feature_coupled": (list, None, []),
    "path_smooth": (float, 0.0, []),
    "interaction_constraints": (str, "", []),
    "verbosity": (int, 1, ["verbose"]),
    "linear_tree": (bool, False, ["linear_trees"]),
    # ---- dataset ----
    "max_bin": (int, 255, ["max_bins"]),
    "max_bin_by_feature": (list, None, []),
    "min_data_in_bin": (int, 3, []),
    "bin_construct_sample_cnt": (int, 200000, ["subsample_for_bin"]),
    "data_random_seed": (int, 1, ["data_seed"]),
    "is_enable_sparse": (bool, True, ["is_sparse", "enable_sparse", "sparse"]),
    "enable_bundle": (bool, True, ["is_enable_bundle", "bundle"]),
    "max_conflict_rate": (float, 0.0, []),
    "use_missing": (bool, True, []),
    "zero_as_missing": (bool, False, []),
    "feature_pre_filter": (bool, True, []),
    "pre_partition": (bool, False, ["is_pre_partition"]),
    "two_round": (bool, False, ["two_round_loading", "use_two_round_loading"]),
    "header": (bool, False, ["has_header"]),
    "label_column": (str, "", ["label"]),
    "weight_column": (str, "", ["weight"]),
    "group_column": (str, "", ["group", "group_id", "query_column", "query", "query_id"]),
    "ignore_column": (str, "", ["ignore_feature", "blacklist"]),
    "categorical_feature": (str, "", ["cat_feature", "categorical_column", "cat_column",
                                      "categorical_features"]),
    "forcedbins_filename": (str, "", []),
    "save_binary": (bool, False, ["is_save_binary", "is_save_binary_file"]),
    "precise_float_parser": (bool, False, []),
    # ---- predict ----
    "start_iteration_predict": (int, 0, []),
    "num_iteration_predict": (int, -1, []),
    "predict_raw_score": (bool, False, ["is_predict_raw_score", "predict_rawscore", "raw_score"]),
    "predict_leaf_index": (bool, False, ["is_predict_leaf_index", "leaf_index"]),
    "predict_contrib": (bool, False, ["is_predict_contrib", "contrib"]),
    "predict_disable_shape_check": (bool, False, []),
    # route Booster.predict through the bucketed SoA predictor engine
    # (serve/engine.py): batch sizes round up to power-of-two buckets so
    # repeated predicts with varying row counts stay within a bounded
    # compile cache.  auto = engine when rows x trees is large enough to
    # repay the trace (or when serving already built one); true =
    # always; false = legacy host-tree walk.  Results are byte-identical
    # on every path
    "predict_bucketed": (str, "auto", []),
    "pred_early_stop": (bool, False, []),
    "pred_early_stop_freq": (int, 10, []),
    "pred_early_stop_margin": (float, 10.0, []),
    # ---- objective ----
    "num_class": (int, 1, ["num_classes"]),
    "is_unbalance": (bool, False, ["unbalance", "unbalanced_sets"]),
    "scale_pos_weight": (float, 1.0, []),
    "sigmoid": (float, 1.0, []),
    "boost_from_average": (bool, True, []),
    "reg_sqrt": (bool, False, []),
    "alpha": (float, 0.9, []),
    "fair_c": (float, 1.0, []),
    "poisson_max_delta_step": (float, 0.7, []),
    "tweedie_variance_power": (float, 1.5, []),
    "lambdarank_truncation_level": (int, 30, []),
    "lambdarank_norm": (bool, True, []),
    "label_gain": (list, None, []),
    "objective_seed": (int, 5, []),
    # ---- metric ----
    # CLI conf-file pointer (config.h:99 ``config``): consumed by the
    # CLI layer (cli.py loads the file and merges); inert as a library
    # param, mirroring the reference where only main.cpp reads it
    "config": (str, "", ["config_file"]),
    # external parser spec (config.h parser_config_file): the reference
    # feeds it to its pluggable Parser factory; this framework covers the
    # same extension point with the Python-side registry
    # (data_io.py register_parser), so the path is accepted for CLI/conf
    # compatibility and custom formats are registered in Python instead
    "parser_config_file": (str, "", []),
    "metric": (list, None, ["metrics", "metric_types"]),
    "metric_freq": (int, 1, ["output_freq"]),
    "is_provide_training_metric": (bool, False, ["training_metric", "is_training_metric",
                                                 "train_metric"]),
    "eval_at": (list, None, ["ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at"]),
    "multi_error_top_k": (int, 1, []),
    "auc_mu_weights": (list, None, []),
    # ---- network ----
    "num_machines": (int, 1, ["num_machine"]),
    "local_listen_port": (int, 12400, ["local_port", "port"]),
    "time_out": (int, 120, []),
    "machine_list_filename": (str, "", ["machine_list_file", "machine_list", "mlist"]),
    "machines": (str, "", ["workers", "nodes"]),
    # ---- GPU/device (kept for API parity; TPU uses mesh_* below) ----
    "gpu_platform_id": (int, -1, []),
    "gpu_device_id": (int, -1, []),
    "gpu_use_dp": (bool, False, []),
    "num_gpu": (int, 1, []),
    # ---- TPU-specific (new axis, cf. SURVEY.md §1 device dimension) ----
    "mesh_shape": (list, None, []),          # one axis, e.g. [8]
    "mesh_axis_names": (list, None, []),     # one axis, e.g. ["data"]
    # tree_learner=data histogram reduction: true = reduce-scatter the
    # feature-chunked histograms so each shard carries only [L, F/n, B, 3]
    # of GLOBAL histograms (the reference's ReduceScatter owner shape,
    # data_parallel_tree_learner.cpp:174-186); false = legacy full psum
    # (every shard holds all global histograms) — A/B escape hatch
    "dp_owner_shard": (bool, True, []),
    "hist_dtype": (str, "float32", []),      # histogram accumulation dtype
    # auto: partitioned on CPU, masked (one jitted program per tree) on
    # accelerators where per-split host round-trips dominate
    "tpu_learner": (str, "auto", []),  # auto | partitioned | masked
    "rows_per_block": (int, 0, []),          # 0 = auto-tune histogram row blocking
    # iterations fused into one on-device program (lax.scan) when the
    # objective/bagging config allows it — amortizes the host<->device
    # round-trip (measured ~67 ms on a tunneled chip) over the chunk.
    # 0/1 disables fusion.
    "fused_chunk": (int, 25, []),
    # super-epoch trainer (docs/Fused-Training.md): lax.scan over k FULL
    # boosting iterations — grow + score update + traced metric eval
    # over the bucketed validation sets + an early-stop vote carried as
    # a traced flag — with exactly ONE host sync per epoch.  0 = auto
    # (engine picks k from fused_chunk / early_stopping_round when the
    # config qualifies), >0 = explicit epoch size, -1 = disable (always
    # per-iteration eval)
    "superepoch": (int, 0, []),
    # ---- fleet training (lightgbm_tpu/fleet/, docs/Fleet.md) ----
    # number of fleet members when no explicit sweep is given: N seed
    # replicas of the base params — member j trains with seed+j,
    # bagging_seed+j, feature_fraction_seed+j, byte-identical to a solo
    # run with those seeds.  0 disables (fleet_train needs members from
    # one of fleet_members / fleet_sweep / the members= argument)
    "fleet_members": (int, 0, []),
    # sweep spec: "param=v1|v2;param2=v3|v4" — the cartesian grid of
    # the listed member-axis params (learning_rate, seed, bagging_seed,
    # feature_fraction_seed, num_leaves) becomes the fleet roster.  All
    # members grow inside ONE vmapped super-epoch program; num_leaves
    # variation requires padded_leaves bucketing (the same one-trace
    # rule the solo path pins)
    "fleet_sweep": (str, "", []),
    # traced on-device metric evaluation (metrics.traced_metric_fn):
    # "auto" uses traced (f32) eval wherever the super-epoch engages and
    # host (f64) eval elsewhere; "true" forces traced eval in the
    # per-iteration loop too (the byte-identity partner of the scan
    # path); "false" disables traced eval AND the super-epoch whenever
    # validation sets are attached
    "fused_eval": (str, "auto", []),
    # quantized training (docs/Quantized-Training.md, ROADMAP item 3):
    # pack per-row gradients/hessians to int8/int16 with one shared
    # per-channel scale per iteration and stochastic rounding, and
    # accumulate EXACT int32 histograms through the one-hot contraction
    # — 2-4x less HBM traffic per histogram pass and a step toward the
    # MXU's low-precision throughput.  Gains/leaf values are computed
    # from dequantized totals at split-scan time only; an AUC/metric
    # parity harness (tests/test_quant.py) pins quant-vs-f32 quality on
    # regression/binary/multiclass/lambdarank.  false (default) is
    # byte-identical to pre-quantization training
    "quant_train": (bool, False, ["use_quantized_grad"]),
    # packed gradient/hessian width: 8 (int8 lanes, the full HBM win)
    # or 16 (int16, tighter parity at half the bandwidth saving)
    "quant_bits": (int, 8, []),
    # stochastic (unbiased, iteration-keyed counter RNG — resume stays
    # byte-identical) | nearest (deterministic, biased).  No alias to
    # the reference's bool `stochastic_rounding` on purpose: a bool
    # value would coerce to a nonsense mode string
    "quant_round": (str, "stochastic", []),
    # leaves split per grower super-step (masked learner).  1 = exact
    # strict leaf-wise growth (reference semantics).  K>1 splits the top-K
    # leaves by cached gain per step and builds all K child histograms in
    # ONE C=3K one-hot contraction — ~K× more MXU sublane utilization and
    # 1/K the one-hot passes (PROFILE.md), at the cost of a slightly
    # different (still best-first) growth order.  0 = auto: 1 below 64
    # leaves, then 8.
    "split_batch": (int, 0, []),
    # on-device (K, block_rows) autotuner for the histogram contraction
    # (ops/hist_tune.py; docs/Contraction-Width.md): "on" runs a
    # one-shot measured sweep over the shipped split_batch widths and a
    # block_rows neighborhood at FIRST fit per (platform, shape
    # bucket), persists the choice next to the persistent compile
    # cache, and applies it ONLY when split_batch=0 (auto; an explicit
    # width is the user's choice and skips the sweep entirely), with
    # the paired block_rows filling rows_per_block=0.  The tuned K
    # changes the (equally valid) growth order, so "on" trades
    # cross-platform model determinism for measured throughput; "off"
    # (default) reproduces today's exact shapes, traces and models
    "hist_tune": (str, "off", []),
    # strict (split_batch=1) grower: build the per-split smaller-child
    # histogram through the batched path's slot mechanism (one [N]
    # int32 slot vector as the scan operand) instead of materializing
    # a fresh masked [N, 3] vals temp per split.  BYTE-IDENTICAL
    # models by construction (the 0/1 multiply happens inside the
    # row-block scan on the same values; pinned by
    # tests/test_hist_width.py) — false restores the serialized
    # masked-operand baseline for A/B
    "hist_overlap": (bool, True, []),
    # ---- compile cache / trace buckets ----
    # compile-time management (ROADMAP item 4; docs/Compile-Cache.md)
    # persistent XLA compilation cache across processes (train -> serve
    # warm start): enabled by default; the directory precedence is
    # compile_cache_dir > a pre-set JAX_COMPILATION_CACHE_DIR (the
    # user's choice is respected, never clobbered) > a per-user,
    # per-host-fingerprint tmp path (utils/compile_cache.py)
    "compile_cache": (bool, True, ["persistent_compile_cache"]),
    "compile_cache_dir": (str, "", []),
    # persistence thresholds (previously hardwired): only compiles at
    # least this long / this large are written to the cache
    "compile_cache_min_compile_s": (float, 0.5, []),
    "compile_cache_min_entry_bytes": (int, 0, []),
    # bucket the trace-relevant static dims (utils/shapes.py): the
    # grower's leaf budget pads to a pow2 bucket (num_leaves 31/40/63
    # share ONE L=64 trace with bit-identical trees — the while_loop
    # exits on the actual budget), explicit split_batch snaps to the
    # shipped {1, 8, 16, 32, 64} widths (fitted under the leaf
    # budget), and DENSE validation sets row-bucket
    # so early stopping over differently-sized valid sets stops
    # re-tracing (sparse-binned valid sets keep exact shapes).
    # false = exact per-shape traces (A/B escape hatch);
    # tools/check_retraces.py pins the bucketed trace budget
    "trace_buckets": (bool, True, []),
    # ---- telemetry / observability ----
    # master switch for the obs subsystem (lightgbm_tpu/obs/): per-phase
    # spans + metrics registry + comm-bytes counters on the training
    # loop.  false (default) keeps the hot path byte-identical: zero
    # extra host syncs, no per-iteration allocation beyond a branch
    "telemetry": (bool, False, []),
    # JSONL span sink path; convert with obs.trace.jsonl_to_chrome for
    # Perfetto / chrome://tracing.  Empty = in-memory events only
    "telemetry_trace_file": (str, "", []),
    # [k, n] — capture iterations [k, k+n) with jax.profiler (best
    # effort; requires telemetry=true).  [k] captures one iteration
    "telemetry_profile_iters": (list, None, []),
    # flight recorder (obs/blackbox.py): keep a bounded ring of
    # per-iteration records (phase seconds, eval results, finite-guard
    # flags, static comm/flop counters) and dump the last K as JSONL on
    # exception, watchdog fire, or a finite_check_policy trigger.
    # false (default) allocates nothing and never touches disk
    "telemetry_blackbox": (bool, False, []),
    # dump path; empty derives <output_model>.blackbox.jsonl (train)
    # or lgbtpu_serve_blackbox.jsonl (serve)
    "telemetry_blackbox_path": (str, "", []),
    # ring capacity: how many trailing iteration records a dump holds
    "telemetry_blackbox_last_k": (int, 64, []),
    # roofline peak overrides for device kinds obs/attrib.py's table
    # does not know (0 = auto-detect from the device kind): MXU peak
    # FLOP/s and HBM bandwidth in GB/s — the denominators of the
    # perf.* mfu / bound keys
    "telemetry_peak_flops": (float, 0.0, []),
    "telemetry_peak_hbm_gbs": (float, 0.0, []),
    # ---- fault tolerance ----
    # retries after the first failed device-claim / jax.distributed
    # bring-up attempt (jittered exponential backoff, utils/resilience.py)
    "dist_init_retries": (int, 2, []),
    # watchdog + retry deadline (seconds) for device/distributed bring-up:
    # a blocking claim exceeding this dumps all-thread stacks via
    # faulthandler (the round-5 wedge was silent for 10 h); 0 disables
    "dist_init_timeout_s": (float, 300.0, []),
    # when multi-chip bring-up exhausts its retries, degrade to the
    # serial learner with a logged warning instead of raising
    "dist_fallback_serial": (bool, False, []),
    # ---- elastic training (lightgbm_tpu/parallel/elastic.py) ----
    # master switch for the elastic liveness + recovery layer: the
    # training loop's host fetch runs under the collective deadline,
    # the device claim under a cancel-and-raise watchdog, peers are
    # liveness-checked per iteration, and snapshot params-signatures
    # treat the topology (tree_learner=data|serial, mesh_shape,
    # num_machines) as volatile so a shrunk mesh can resume the same
    # run.  false (default) keeps every path byte-identical to before
    "elastic_enable": (bool, False, []),
    # per-iteration collective deadline (seconds): the training loop's
    # one host fetch — where every queued collective actually blocks —
    # is abandoned past this and classified as
    # ElasticFailure("collective_timeout"); 0 disables the deadline
    "elastic_collective_timeout_s": (float, 300.0, []),
    # heartbeat cadence of the per-process liveness thread (elastic
    # ladder runs only; requires elastic_heartbeat_dir)
    "elastic_heartbeat_interval_s": (float, 1.0, []),
    # a peer whose heartbeat file is staler than this is declared lost
    # (ElasticFailure("host_loss"))
    "elastic_heartbeat_timeout_s": (float, 10.0, []),
    # shared directory for heartbeat files (one hb_<process>.json per
    # process); empty disables the heartbeat layer
    "elastic_heartbeat_dir": (str, "", []),
    # wall-clock budget (seconds) for one recovery episode: from the
    # first classified failure until training runs again, across all
    # retry/shrink attempts; past it the ladder re-raises.  0 = no
    # budget
    "elastic_recover_timeout_s": (float, 600.0, []),
    # same-rung retries (jittered backoff) before the ladder shrinks
    # the mesh; host_loss always shrinks immediately
    "elastic_retries": (int, 1, []),
    # check grad/hess and new-tree leaf outputs for non-finite values
    # every k iterations (one amortized scalar sync; fused-chunk
    # compatible); 0 disables
    "finite_check_freq": (int, 0, []),
    # what to do when the finite check trips: raise | skip_iter (the
    # iteration contributes a zero stump) | clamp (nan_to_num gradients
    # and leaf outputs, applied every iteration — it is sync-free)
    "finite_check_policy": (str, "raise", []),
    # ---- computation integrity (lightgbm_tpu/integrity.py) ----
    # silent-data-corruption detection: every k iterations re-execute
    # the iteration's grow (histogram contraction + split scan) through
    # an independently-jitted shadow program and compare — bitwise on
    # int32 fields, ulp-bounded on f32 — plus cheap in-graph invariants
    # riding the existing consolidated fetch every iteration.  0
    # disables the layer entirely (byte-identical to pre-integrity
    # behavior, zero extra host syncs).  Forces the per-iteration
    # training path (fused_chunk/super-epoch fall back; see
    # GBDTModel.fused_reasons)
    "integrity_check_freq": (int, 0, []),
    # what a STICKY mismatch (fails the one re-check) does: raise
    # (IntegrityFailure, kind "sdc") | rewind (engine.train re-enters
    # from the newest integrity-verified snapshot, up to
    # integrity.MAX_REWINDS times) | quarantine (additionally marks the
    # suspect devices so the elastic ladder's next mesh excludes them)
    "integrity_policy": (str, "raise", []),
    # float32 comparison slack for the shadow compare, in ulps (units
    # in the last place); int32 fields are always compared bitwise.
    # 0 = exact; the default absorbs benign reassociation between the
    # two traces
    "integrity_ulp_tol": (int, 2, []),
    # newest snapshots kept on disk (model + manifest + state pruned
    # together); <= 0 keeps all
    "snapshot_keep": (int, 3, []),
    # auto-resume: locate the latest VALID snapshot of output_model
    # (manifest params-signature + data fingerprint match) and continue
    # through the init_model path (engine.py); never recorded in the
    # saved model's parameters section
    "resume": (bool, False, ["auto_resume"]),
    # ---- continual training (lightgbm_tpu/pipeline/continual.py) ----
    # boosting iterations per continual generation: each generation
    # appends a data chunk and boosts this many more rounds from the
    # newest complete snapshot via the init_model path
    "continual_rounds": (int, 10, []),
    # shrink the contribution of the trees carried over from previous
    # generations by this factor each generation (Tree::Shrinkage over
    # the loaded model before the init score is computed); 1.0 = no
    # decay.  Refused for linear-tree models (only the constant leaf
    # values would decay, like refit)
    "continual_decay": (float, 1.0, []),
    # retries per pipeline stage (append/boost/publish/promote) for
    # transient failures, on top of the first attempt; gate refusals
    # (GateFailure) are never retried — they roll back
    "continual_retries": (int, 1, []),
    # promotion-gate budget (seconds): a shadow-parity probe that has
    # not finished within it is a gate FAILURE (automatic rollback), not
    # a wait.  0 = no timeout
    "continual_timeout_s": (float, 30.0, []),
    # where gate-failed candidates are moved (model + sidecars + a
    # blackbox reason dump) so the next generation can never boost from
    # them; empty derives <output_model>.quarantine
    "continual_quarantine_dir": (str, "", []),
    # CLI task=continual chunk sources: files appended one generation
    # each, after the base generation trained from ``data``
    "continual_data": (list, None, ["continual_chunks"]),
    # shadow-traffic parity probe: how many of the last live serve
    # batches are replayed through a promotion candidate (the serve
    # server keeps a ring of this many batches; without live traffic
    # the probe replays slices of the newest data chunk).  0 disables
    # the replay entirely — the metric-regression gate still applies
    "shadow_probe_batches": (int, 8, []),
    # objective-aware score-DRIFT bound of the probe: probability-like
    # outputs (binary/multiclass/xentropy) compare absolutely, unbounded
    # outputs relative to the incumbent's scale.  This is the
    # freshness-vs-stability budget — how far a candidate may move live
    # scores — not a corruption check (that is the lineage gate below);
    # the permissive default only rejects insanity.  NOTE: probability
    # drift is bounded by 1.0, so at the default the probability leg
    # enforces only finiteness/shape — set an explicit tolerance to
    # bound how far a candidate may move classification scores
    "shadow_probe_tolerance": (float, 1.0, []),
    # lineage-parity tolerance (relative): the candidate's raw-score
    # prefix over the incumbent's iteration count must reproduce the
    # (decayed) incumbent's raw scores to float rounding — the
    # convergence-independent corruption catcher.  Applied only when the
    # candidate is a continuation of the serving incumbent (the
    # trainer's own promotions; POST /promote of an unrelated retrain
    # skips it)
    "shadow_probe_lineage_tolerance": (float, 1e-9, []),
    # allowed eval-metric regression of the candidate vs the incumbent
    # on the gate set (the newest chunk): worse by more than this and
    # the promotion rolls back
    "shadow_probe_metric_tolerance": (float, 0.0, []),
    # ---- serving (lightgbm_tpu/serve/, docs/Serving.md) ----
    # micro-batch cap in rows: the batcher dispatches a batch as soon as
    # this many rows are queued; also the engine's bucket cap, bounding
    # XLA compiles per model to ~log2(serve_max_batch)
    "serve_max_batch": (int, 1024, []),
    # how long the first queued request holds the coalescing window open
    # before the batch dispatches short of serve_max_batch
    "serve_max_wait_ms": (float, 2.0, []),
    # bounded queue size in ROWS: beyond it, submissions are rejected
    # with an explicit retry-after (HTTP 429) instead of growing the
    # backlog without bound
    "serve_queue_rows": (int, 8192, ["serve_queue_size"]),
    # smallest padded-batch bucket: tiny requests all share one compiled
    # shape instead of one per power of two below it
    "serve_min_bucket": (int, 16, []),
    # retries for TRANSIENT device errors during a serve batch
    # (utils/resilience.py classifier; programming errors never retry)
    "serve_retries": (int, 2, []),
    # opt-in device-resident fast path: bin + traverse + accumulate +
    # objective transform run as ONE jitted program per (model,
    # row-bucket) — the only host<->device sync per batch is the final
    # score fetch.  Approximate vs the exact host path: rows tying a
    # split threshold within f32 rounding may bin differently, and leaf
    # values accumulate in f32 (tree order).  The engine self-check
    # gates the path; a parity failure demotes the model to the host
    # walk (serve.host_fallback_batches) instead of refusing traffic
    "serve_device_binning": (bool, False, []),
    # pack the serve engine's flattened node tables to the narrowest
    # dtypes the model allows (thresholds uint8/uint16 by bin count,
    # children/features by node/feature count): ~4x smaller HBM/VMEM
    # footprint per resident model — the headroom multi-model
    # co-hosting spends.  Decisions are identical either way
    "serve_packed_tables": (bool, True, []),
    # co-hosting cap: max model versions kept device-resident in the
    # serving registry; loading past it evicts the oldest non-current
    # version (hot-swap/shadow versions below the cap serve without
    # re-upload or re-trace).  The current version and the incoming
    # load are never evicted, so a shadow load may exceed the cap by
    # one until the next load/swap.  0 = unlimited
    "serve_max_resident": (int, 0, []),
    "serve_host": (str, "127.0.0.1", []),
    "serve_port": (int, 7070, []),
    # default per-request deadline (ms): requests are failed-fast at
    # admission when the queue's estimated wait already exceeds it, and
    # shed before dispatch when it lapsed while queued — device time is
    # never spent on a request the client has abandoned.  0 = none;
    # per-request deadline_ms overrides
    "serve_deadline_ms": (float, 0.0, ["serve_default_deadline_ms"]),
    # consecutive FAILED batches (infrastructure errors, after
    # serve_retries) that open the serving circuit breaker: while open,
    # submissions are rejected up front (HTTP 503 + Retry-After)
    # instead of queuing onto a failing device; after the cooldown a
    # probe batch decides close vs re-open (cooldown doubles, capped at
    # 16x).  0 disables the breaker
    "serve_breaker_failures": (int, 5, []),
    "serve_breaker_cooldown_ms": (float, 1000.0, []),
    # graceful-drain budget (seconds) on shutdown (SIGTERM / POST
    # /drain / Server.drain): new work is refused, queued work finishes
    # within the budget, leftovers fail with BatcherClosed
    "serve_drain_s": (float, 5.0, []),
    # per-request segment routing (fleet serving, docs/Fleet.md):
    # requests carrying segment=<key> are routed to the model version
    # the SegmentRouter maps that key to; unknown keys fall back to the
    # default segment's version (or the registry's current model when
    # the default is unassigned)
    "serve_default_segment": (str, "default", []),
    # cardinality bound for per-version / per-segment serve metric
    # labels: beyond this many distinct label values, further ones
    # aggregate into one "__other__" bucket so a 500-segment fleet
    # cannot bloat the /metrics exposition.  0 = unlimited
    "serve_metrics_max_versions": (int, 32, []),
    # verify artifacts before activation: SHA-256 of model files
    # against the snapshot manifest's recorded checksum, plus the
    # engine's byte-parity self-check probe (fall back to the host walk
    # on mismatch).  Disable only to shave load latency
    "serve_verify_artifacts": (bool, True, []),
    # ---- out-of-core ingest (lightgbm_tpu/ingest.py) ----
    # stream text data through bounded-memory chunks with a per-chunk
    # spool + manifest (sha256, row span) so a killed loader resumes
    # from the last complete chunk, and fit bin mappers from mergeable
    # quantile sketches (binning.QuantileSketch) instead of a full
    # in-memory sample.  Implied by passing a directory as ``data``
    "ingest_enable": (bool, False, ["streaming_ingest"]),
    # rows per chunk when splitting a single text file (directory
    # sources use one chunk per file)
    "ingest_chunk_rows": (int, 65536, []),
    # spool/manifest directory; empty -> "<data>.ingest" next to the
    # source
    "ingest_dir": (str, "", ["ingest_spool_dir"]),
    # resume from spooled chunks whose manifest verifies (byte-identical
    # to the uninterrupted run); false re-ingests from scratch
    "ingest_resume": (bool, True, []),
    # persistently corrupt chunk (sha mismatch, parse failure, row-count
    # drift) policy: "raise" fails the run, "skip" quarantines the chunk
    # and keeps an accounting of the dropped rows
    "ingest_bad_chunk": (str, "raise", []),
    # transient read-error retries per chunk (attempts = retries + 1)
    # and the base of their jittered exponential backoff
    "ingest_retries": (int, 2, []),
    "ingest_retry_backoff_s": (float, 0.1, []),
    # per-chunk read+parse deadline: a reader wedged on a dead
    # filesystem is abandoned (resilience.Watchdog raise mode) and the
    # timeout classifies as retryable.  0 disables
    "ingest_read_timeout_s": (float, 60.0, []),
    # per-feature quantile-sketch capacity: distinct (value, count)
    # pairs kept exactly; past this the sketch compacts with rank error
    # ~2*rows/capacity per compaction generation (docs/Ingest.md)
    "ingest_sketch_size": (int, 2048, []),
    # ---- IO / task ----
    "task": (str, "train", ["task_type"]),
    "data": (str, "", ["train", "train_data", "train_data_file", "data_filename"]),
    "valid": (list, None, ["test", "valid_data", "valid_data_file", "test_data",
                           "test_data_file", "valid_filenames"]),
    "input_model": (str, "", ["model_input", "model_in"]),
    "output_model": (str, "LightGBM_model.txt", ["model_output", "model_out"]),
    "convert_model": (str, "gbdt_prediction.c", ["convert_model_file"]),
    "convert_model_language": (str, "c", []),
    "saved_feature_importance_type": (int, 0, []),
    "snapshot_freq": (int, -1, ["save_period"]),
    "output_result": (str, "LightGBM_predict_result.txt",
                      ["predict_result", "prediction_result", "predict_name",
                       "prediction_name", "pred_name", "name_pred"]),
}

# alias -> canonical name
_ALIASES: Dict[str, str] = {}
for _name, (_t, _d, _al) in _PARAMS.items():
    for _a in _al:
        _ALIASES[_a] = _name


def canonical_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Keys alias-resolved to canonical names (first writer wins among
    aliases within one dict, matching _set's alias priority).  Use when
    MERGING two param dicts — a raw {**a, **b} lets an alias in one dict
    silently coexist with the canonical name in the other, and _set's
    first-writer rule would then pick the wrong source."""
    out: Dict[str, Any] = {}
    for k, v in params.items():
        name = _ALIASES.get(k, k)
        if name not in out:
            out[name] = v
    return out

# Objective aliases (config_auto.cpp ParseObjectiveAlias analog)
_OBJECTIVE_ALIASES = {
    "regression": "regression", "regression_l2": "regression", "l2": "regression",
    "mean_squared_error": "regression", "mse": "regression", "l2_root": "regression",
    "root_mean_squared_error": "regression", "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile", "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary", "binary_logloss": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank", "rank_xendcg": "rank_xendcg",
    "xendcg": "rank_xendcg", "xe_ndcg": "rank_xendcg", "xe_ndcg_mart": "rank_xendcg",
    "xendcg_mart": "rank_xendcg",
    "none": "custom", "null": "custom", "custom": "custom", "na": "custom",
}

_METRIC_ALIASES = {
    "l1": "l1", "mean_absolute_error": "l1", "mae": "l1", "regression_l1": "l1",
    "l2": "l2", "mean_squared_error": "l2", "mse": "l2", "regression_l2": "l2",
    "regression": "l2",
    "rmse": "rmse", "root_mean_squared_error": "rmse", "l2_root": "rmse",
    "quantile": "quantile", "mape": "mape", "mean_absolute_percentage_error": "mape",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "gamma": "gamma", "gamma_deviance": "gamma_deviance", "tweedie": "tweedie",
    "ndcg": "ndcg", "lambdarank": "ndcg", "rank_xendcg": "ndcg", "xendcg": "ndcg",
    "xe_ndcg": "ndcg", "xe_ndcg_mart": "ndcg", "xendcg_mart": "ndcg",
    "map": "map", "mean_average_precision": "map",
    "auc": "auc", "average_precision": "average_precision",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "binary_error": "binary_error",
    "auc_mu": "auc_mu",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multiclass_ova": "multi_logloss", "ova": "multi_logloss", "ovr": "multi_logloss",
    "multi_error": "multi_error",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "kullback_leibler": "kldiv", "kldiv": "kldiv",
    "none": "custom", "null": "custom", "custom": "custom", "na": "custom",
}

_RANKING_OBJECTIVES = {"lambdarank", "rank_xendcg"}
_MULTICLASS_OBJECTIVES = {"multiclass", "multiclassova"}


def _coerce(name: str, typ: type, value: Any) -> Any:
    """Coerce a raw (possibly string) parameter value to its declared type."""
    if value is None:
        return None
    if typ is bool:
        if isinstance(value, str):
            v = value.strip().lower()
            if v in ("true", "1", "+", "yes", "on"):
                return True
            if v in ("false", "0", "-", "no", "off"):
                return False
            raise ValueError(f"Cannot parse bool parameter {name}={value!r}")
        return bool(value)
    if typ is int:
        if isinstance(value, bool):
            return int(value)
        return int(float(value)) if isinstance(value, str) else int(value)
    if typ is float:
        return float(value)
    if typ is list:
        if isinstance(value, str):
            if not value:
                return None
            return [_auto_num(tok) for tok in value.replace(";", ",").split(",") if tok != ""]
        if isinstance(value, (list, tuple)):
            return list(value)
        if hasattr(value, "tolist"):      # ndarray / pandas
            v = value.tolist()
            return v if isinstance(v, list) else [v]
        return [value]
    if typ is str:
        return str(value)
    return value


def _auto_num(tok: str) -> Union[int, float, str]:
    tok = tok.strip()
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        return tok


# unknown parameter names already warned about (once per process)
_warned_unknown: set = set()


class Config:
    """Dataclass-of-record holding every hyperparameter.

    ``Config(params_dict)`` replicates ``Config::Set``
    (/root/reference/src/io/config.cpp:195-259): alias resolution, value
    parsing, then conflict checking/auto-promotion (``CheckParamConflict``
    config.cpp:261).
    """

    def __init__(self, params: Optional[Dict[str, Any]] = None, **kw):
        for name, (typ, default, _aliases) in _PARAMS.items():
            setattr(self, name, default)
        merged: Dict[str, Any] = {}
        if params:
            merged.update(params)
        merged.update(kw)
        self.raw_params: Dict[str, Any] = dict(merged)
        # apply the requested (or default) verbosity BEFORE parsing, so
        # parse-time warnings (unknown parameters) honor THIS
        # construction's level rather than a previous Config's — the
        # level is process-global, like the reference's Log state
        from .utils.log import Log
        v = merged.get("verbosity", merged.get("verbose", self.verbosity))
        try:
            Log.set_verbosity(_coerce("verbosity", int, v))
        except (TypeError, ValueError):
            pass            # bad value: surfaced by _set's typed coerce
        self._set(merged)
        self._check_param_conflict()

    def _set(self, params: Dict[str, Any]) -> None:
        seen: Dict[str, str] = {}
        for key, value in params.items():
            name = _ALIASES.get(key, key)
            if name not in _PARAMS:
                # Unknown keys are kept (callbacks / custom use) but not
                # typed — and warned ONCE per key per process, like the
                # reference's "Unknown parameter" message (config.cpp Set
                # tail); one train() call constructs several Configs
                # (engine/booster/dataset), so an unconditional warning
                # would repeat 2-4x per call
                if key not in _warned_unknown:
                    _warned_unknown.add(key)
                    from .utils.log import Log
                    Log.warning(f"Unknown parameter: {key}")
                setattr(self, name, value)
                continue
            if name in seen:
                # First writer wins for a canonical name through distinct
                # aliases, matching the reference alias-priority behavior.
                continue
            seen[name] = key
            typ = _PARAMS[name][0]
            setattr(self, name, _coerce(name, typ, value))

        if "objective" in seen or "objective" in params:
            obj = str(self.objective).lower()
            self.objective = _OBJECTIVE_ALIASES.get(obj, obj)
        if self.metric is not None:
            norm = []
            for m in self.metric:
                m = str(m).strip().lower()
                norm.append(_METRIC_ALIASES.get(m, m))
            self.metric = norm

    def _check_param_conflict(self) -> None:
        # Mirrors CheckParamConflict (config.cpp:261+): auto-select parallel
        # learner, clamp fractions, task-implied settings.
        if self.num_machines > 1 and self.tree_learner == "serial":
            self.tree_learner = "data"
        self.is_parallel = self.tree_learner in ("data", "feature", "voting")
        self.is_data_based_parallel = self.tree_learner in ("data", "voting")
        if self.objective in _RANKING_OBJECTIVES and self.metric is None:
            self.metric = ["ndcg"]
        if self.objective in _MULTICLASS_OBJECTIVES and self.num_class <= 1:
            raise ValueError("num_class must be >1 for multiclass objectives")
        if self.objective not in _MULTICLASS_OBJECTIVES \
                and self.num_class != 1 and self.objective != "custom":
            # custom-objective training (objective=none) legitimately
            # carries num_class>1: the caller's fobj produces per-class
            # gradients (basic.py __boost F-ravels [n, num_class])
            raise ValueError("num_class can only be used with multiclass objectives")
        if self.bagging_freq > 0 and (self.bagging_fraction >= 1.0 and
                                      self.pos_bagging_fraction >= 1.0 and
                                      self.neg_bagging_fraction >= 1.0):
            self.bagging_freq = 0
        if self.boosting == "goss":  # legacy alias: boosting=goss
            self.boosting = "gbdt"
            self.data_sample_strategy = "goss"
        if self.boosting == "rf":
            if self.bagging_freq <= 0 or self.bagging_fraction >= 1.0 or self.bagging_fraction <= 0.0:
                raise ValueError("Random forest needs bagging_freq>0 and 0<bagging_fraction<1")
        if self.max_bin < 2:
            raise ValueError("max_bin must be >= 2")
        if self.num_leaves < 2:
            raise ValueError("num_leaves must be >= 2")
        if self.quant_bits not in (8, 16):
            raise ValueError(f"quant_bits={self.quant_bits} must be 8 "
                             "or 16")
        if self.quant_round not in ("stochastic", "nearest"):
            raise ValueError(
                f"quant_round={self.quant_round!r} must be one of: "
                "stochastic, nearest")
        if self.hist_tune not in ("off", "on"):
            raise ValueError(
                f"hist_tune={self.hist_tune!r} must be one of: off, on")
        if self.finite_check_policy not in ("raise", "skip_iter", "clamp"):
            raise ValueError(
                f"finite_check_policy={self.finite_check_policy!r} must be "
                "one of: raise, skip_iter, clamp")
        if self.integrity_check_freq < 0:
            raise ValueError("integrity_check_freq must be >= 0")
        if self.integrity_policy not in ("raise", "rewind", "quarantine"):
            raise ValueError(
                f"integrity_policy={self.integrity_policy!r} must be "
                "one of: raise, rewind, quarantine")
        if self.integrity_ulp_tol < 0:
            raise ValueError("integrity_ulp_tol must be >= 0")
        if self.compile_cache_min_compile_s < 0:
            raise ValueError("compile_cache_min_compile_s must be >= 0")
        if self.compile_cache_min_entry_bytes < 0:
            raise ValueError("compile_cache_min_entry_bytes must be >= 0")
        if self.telemetry_profile_iters is not None \
                and len(self.telemetry_profile_iters) not in (1, 2):
            raise ValueError(
                "telemetry_profile_iters must be [start] or [start, count]")
        if self.telemetry_blackbox_last_k < 1:
            raise ValueError("telemetry_blackbox_last_k must be >= 1")
        for knob in ("telemetry_peak_flops", "telemetry_peak_hbm_gbs"):
            if getattr(self, knob) < 0:
                raise ValueError(f"{knob} must be >= 0 (0 = auto-detect)")
        pb = str(self.predict_bucketed).strip().lower()
        if pb in ("true", "1", "+", "yes", "on"):
            self.predict_bucketed = "true"
        elif pb in ("false", "0", "-", "no", "off"):
            self.predict_bucketed = "false"
        elif pb == "auto":
            self.predict_bucketed = "auto"
        else:
            raise ValueError(
                f"predict_bucketed={self.predict_bucketed!r} must be "
                "auto, true or false")
        if self.serve_max_batch < 1:
            raise ValueError("serve_max_batch must be >= 1")
        if self.serve_max_wait_ms < 0:
            raise ValueError("serve_max_wait_ms must be >= 0")
        # the bucket floor can never exceed the batch cap, and the queue
        # must hold at least one full batch (clamped, not rejected: both
        # are derived sizing knobs)
        self.serve_min_bucket = max(1, min(self.serve_min_bucket,
                                           self.serve_max_batch))
        self.serve_queue_rows = max(self.serve_queue_rows,
                                    self.serve_max_batch)
        for knob in ("serve_deadline_ms", "serve_drain_s"):
            if getattr(self, knob) < 0:
                raise ValueError(f"{knob} must be >= 0")
        if self.serve_breaker_cooldown_ms <= 0:
            # 0 is not "retry immediately": a zero cooldown makes every
            # caller the half-open probe, so an open circuit would
            # never reject anything — the breaker would silently not
            # exist (disable it via serve_breaker_failures=0 instead)
            raise ValueError("serve_breaker_cooldown_ms must be > 0 "
                             "(set serve_breaker_failures=0 to disable "
                             "the breaker)")
        if self.serve_max_resident < 0:
            raise ValueError("serve_max_resident must be >= 0 "
                             "(0 = unlimited resident versions)")
        if self.serve_metrics_max_versions < 0:
            raise ValueError("serve_metrics_max_versions must be >= 0 "
                             "(0 = unlimited metric label values)")
        if self.fleet_members < 0:
            raise ValueError("fleet_members must be >= 0 "
                             "(0 = no implicit seed-replica roster)")
        if self.serve_breaker_failures < 0:
            raise ValueError("serve_breaker_failures must be >= 0 "
                             "(0 disables the breaker)")
        if self.continual_rounds < 1:
            raise ValueError("continual_rounds must be >= 1")
        if not (0.0 < self.continual_decay <= 1.0):
            raise ValueError("continual_decay must be in (0, 1] "
                             "(1 = no decay)")
        if self.continual_retries < 0:
            raise ValueError("continual_retries must be >= 0")
        if self.continual_timeout_s < 0:
            raise ValueError("continual_timeout_s must be >= 0 "
                             "(0 = no gate timeout)")
        if self.shadow_probe_batches < 0:
            raise ValueError("shadow_probe_batches must be >= 0")
        for knob in ("elastic_collective_timeout_s",
                     "elastic_recover_timeout_s"):
            if getattr(self, knob) < 0:
                raise ValueError(f"{knob} must be >= 0 (0 disables)")
        if self.elastic_heartbeat_interval_s <= 0:
            raise ValueError("elastic_heartbeat_interval_s must be > 0")
        if self.elastic_heartbeat_timeout_s \
                <= self.elastic_heartbeat_interval_s:
            # a deadline at or under the write cadence declares every
            # healthy peer dead on scheduler jitter alone
            raise ValueError(
                "elastic_heartbeat_timeout_s must exceed "
                "elastic_heartbeat_interval_s")
        if self.elastic_retries < 0:
            raise ValueError("elastic_retries must be >= 0")
        if self.ingest_bad_chunk not in ("raise", "skip"):
            raise ValueError(
                f"ingest_bad_chunk={self.ingest_bad_chunk!r} must be one "
                "of: raise, skip")
        if self.ingest_chunk_rows < 1:
            raise ValueError("ingest_chunk_rows must be >= 1")
        if self.ingest_retries < 0:
            raise ValueError("ingest_retries must be >= 0")
        for knob in ("ingest_retry_backoff_s", "ingest_read_timeout_s"):
            if getattr(self, knob) < 0:
                raise ValueError(f"{knob} must be >= 0 (0 disables)")
        if self.ingest_sketch_size < 16:
            raise ValueError("ingest_sketch_size must be >= 16")
        for knob in ("shadow_probe_tolerance",
                     "shadow_probe_metric_tolerance",
                     "shadow_probe_lineage_tolerance"):
            if getattr(self, knob) < 0:
                raise ValueError(f"{knob} must be >= 0")
        # verbosity drives the global log level with reference semantics
        # (config.h: <0 fatal-only, 0 warnings, 1 info, >=2 debug; the
        # reference's Config::Set calls Log::ResetLogLevel the same way)
        from .utils.log import Log
        Log.set_verbosity(self.verbosity)
        if self.eval_at is None:
            self.eval_at = [1, 2, 3, 4, 5]

    # -- helpers -----------------------------------------------------------
    @property
    def num_model_per_iteration(self) -> int:
        if self.objective in _MULTICLASS_OBJECTIVES \
                or (self.objective == "custom" and self.num_class > 1):
            # custom-objective multiclass: num_class models per iter,
            # gradients class-major from the caller (boosting.h
            # num_model_per_iteration via num_class)
            return self.num_class
        return 1

    def default_metric(self) -> List[str]:
        if self.metric is not None and len(self.metric) > 0:
            return list(self.metric)
        obj = self.objective
        table = {
            "regression": ["l2"], "regression_l1": ["l1"], "huber": ["huber"],
            "fair": ["fair"], "poisson": ["poisson"], "quantile": ["quantile"],
            "mape": ["mape"], "gamma": ["gamma"], "tweedie": ["tweedie"],
            "binary": ["binary_logloss"], "multiclass": ["multi_logloss"],
            "multiclassova": ["multi_logloss"], "cross_entropy": ["cross_entropy"],
            "cross_entropy_lambda": ["cross_entropy_lambda"],
            "lambdarank": ["ndcg"], "rank_xendcg": ["ndcg"],
        }
        return table.get(obj, [])

    def to_dict(self) -> Dict[str, Any]:
        return {name: getattr(self, name) for name in _PARAMS}

    def copy(self, **updates) -> "Config":
        d = self.to_dict()
        d.update(updates)
        d.pop("eval_at", None) if updates.get("objective") else None
        return Config(d)

    def __repr__(self) -> str:
        changed = {k: getattr(self, k) for k, (t, d, a) in _PARAMS.items()
                   if getattr(self, k) != d}
        return f"Config({changed})"


def kv2map(argv: List[str]) -> Dict[str, str]:
    """Parse ``key=value`` CLI tokens (config.h:81 ``KV2Map`` analog)."""
    out: Dict[str, str] = {}
    for tok in argv:
        tok = tok.strip()
        if not tok or tok.startswith("#"):
            continue
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k.strip()] = v.split("#")[0].strip()
    return out


def load_config_file(path: str) -> Dict[str, str]:
    """Parse a LightGBM-style ``key = value`` config file (application.cpp:50)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out
