"""Worker for the multi-process GOSS equality test
(tests/test_multiprocess.py::test_two_process_goss_matches_single).

Each process: launch.init -> deterministic global data -> bin mappers
fitted on the FULL global data (identically on every process, so binning
is topology-invariant and any tree difference is attributable to GOSS
semantics) -> local row shard -> GBDT training with
data_sample_strategy=goss over the 2-process mesh -> rank 0 dumps the
trees.  The host test trains single-process on the same mappers and
requires tree-for-tree equality — the contract that the GOSS top-rate
threshold and Bernoulli draws are GLOBAL (goss.hpp samples over the full
data; models/gbdt.py _goss_vals multi-process branch)."""

import json
import os
import sys


def main():
    rank = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    out = sys.argv[4]

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from lightgbm_tpu.utils.compile_cache import enable_persistent_cache
    enable_persistent_cache()   # pods re-pay every compile without it
    from lightgbm_tpu.parallel import launch

    launch.init(coordinator_address=f"127.0.0.1:{port}",
                num_processes=nproc, process_id=rank)

    import numpy as np
    from lightgbm_tpu import Dataset, train
    from tests_goss_shared import GOSS_PARAMS, ROUNDS, global_data, \
        full_data_mappers, tree_records, synthetic_grads, shard_bounds

    import jax.numpy as jnp
    import numpy as np

    x, y = global_data()
    mappers = full_data_mappers(x)

    shard = launch.row_shard(x, y)
    params = dict(GOSS_PARAMS, num_machines=nproc, tree_learner="data")
    ds = Dataset(shard.x, label=shard.y, bin_mappers=mappers,
                 params=params)
    bst = train(params, ds, num_boost_round=ROUNDS)

    # the semantic contract, tested EXACTLY: the GOSS weight vector for
    # this process's rows must be the corresponding slice of the
    # single-process weight vector (same synthetic gradients)
    m = bst._model
    g_full, h_full = synthetic_grads(len(y))
    lo, hi = shard_bounds(len(y), nproc)[rank]
    w0 = np.asarray(m._goss_vals(jnp.asarray(g_full[lo:hi]),
                                 jnp.asarray(h_full[lo:hi]), it=0))
    import jax
    dbg = {
        "pc": int(jax.process_count()),
        "counts": [int(c) for c in m._global_counts],
        "u8": [float(v) for v in np.asarray(jax.random.uniform(
            jax.random.PRNGKey(m.config.bagging_seed), (4096,)))[:8]],
        "seed": int(m.config.bagging_seed),
    }

    if rank == 0:
        with open(out, "w") as f:
            json.dump({"trees": tree_records(bst),
                       "w0_rank0": w0.tolist(), "dbg": dbg,
                       "pred_head": bst.predict(x[:256]).tolist()}, f)


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    main()
