"""Machine-keyed persistent XLA compilation cache.

One call makes every jit compile in this process reusable by later
processes on the SAME host: the cache directory is keyed by the host's
CPU feature fingerprint because XLA:CPU AOT entries are
machine-specific and this can run in environments that migrate between
heterogeneous hosts — a cache written on one host fails every load on
another ("Target machine feature ... is not supported"), costing the
failed loads on top of the recompiles (measured: 25 cold minutes for
the test suite).  Used by tests/conftest.py, the spawned multi-process
pod workers, and ``lightgbm_tpu.distributed`` worker bootstrap — pod
tests pay dozens of fresh-process compiles per run without it.
"""

from __future__ import annotations

import getpass
import hashlib
import os
import tempfile


def machine_tag() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.sha256(line.encode()).hexdigest()[:10]
    except OSError:
        pass
    import platform
    return hashlib.sha256(platform.processor().encode()).hexdigest()[:10]


def enable_persistent_cache(min_compile_secs: float = 0.5) -> str:
    """Point jax at the per-host cache dir; returns the path."""
    import jax
    path = os.path.join(
        tempfile.gettempdir(),
        f"lgbtpu_jax_cache_{getpass.getuser()}_{machine_tag()}")
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return path


def watch_compiles(metrics, tracer=None) -> bool:
    """Feed XLA compile / compilation-cache events into an obs
    MetricsRegistry (+ optional Tracer instants): compile durations as
    a ``jax.compile_seconds`` histogram, cache hits/misses and other
    compile-adjacent counters as ``jax.events{event=...}``.

    Uses ``jax.monitoring``'s public listener hooks; listeners are
    process-global and cannot be unregistered, so the registered
    closures forward to whatever registry/tracer was CURRENT at
    registration — callers register once per session (obs.ObsSession).
    Returns False when the monitoring surface is unavailable."""
    try:
        from jax import monitoring
    except Exception:
        return False

    def _on_duration(event: str, duration: float, **kw) -> None:
        if "compil" not in event:
            return
        metrics.histogram("jax.compile_seconds",
                          event=event).observe(duration)
        if tracer is not None:
            tracer.instant("jax_compile", event=event, seconds=duration)

    def _on_event(event: str, **kw) -> None:
        if "compil" not in event and "cache" not in event:
            return
        metrics.counter("jax.events", event=event).inc()
        if tracer is not None and "cache" in event:
            tracer.instant("jax_cache", event=event)

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
    except Exception:
        return False
    return True
