"""Per-phase wall-clock attribution of one training iteration on real TPU.

VERDICT r2 task 1: the bench's own FLOP arithmetic says the histogram matmul
is tens of ms, but steady state was 850 ms/iter.  This script replicates
``GBDTModel.train_one_iter``'s phases with explicit ``block_until_ready``
fences so every millisecond is attributed to a named phase:

  grad      objective get_gradients (device)
  vals      stack g/h/w (device)
  grow      the jitted tree grower (device, includes all splits)
  fetch     jax.device_get of the small tree arrays (host round trip)
  hosttree  Tree.from_arrays + leaf-value numpy work (host)
  score     leaf-gather score update (device)

It also measures the raw tunnel round-trip latency (tiny-op device_get) to
separate dispatch/transfer latency from compute.  Output: a table on stderr,
reproduced in PROFILE.md (the reference's global_timer discipline,
/root/reference/include/LightGBM/utils/common.h:978).

Run: python tools/profile_iter.py [n_rows] [num_leaves]
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")


def bench_phase(fn, iters=10):
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts), sum(ts) / len(ts)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    num_leaves = int(sys.argv[2]) if len(sys.argv) > 2 else 31

    rng = np.random.RandomState(0)
    f = 28
    x = rng.randn(n, f).astype(np.float32)
    logit = (1.2 * x[:, 0] - 0.8 * x[:, 1] + 0.6 * x[:, 2] * x[:, 3]
             + 0.4 * np.abs(x[:, 4]) + 0.5 * rng.randn(n))
    y = (logit > 0).astype(np.float32)

    import jax
    import jax.numpy as jnp
    devs = jax.devices()
    print(f"devices={devs}", file=sys.stderr)

    # raw tunnel round-trip: dispatch + fetch of a 4-byte scalar
    one = jnp.float32(1.0) + 0.0
    jax.block_until_ready(one)
    t_rt_min, t_rt_avg = bench_phase(
        lambda: jax.device_get(jnp.float32(1.0) + one), iters=20)
    print(f"tunnel round-trip (scalar op + device_get): "
          f"min {t_rt_min*1e3:.1f} ms avg {t_rt_avg*1e3:.1f} ms",
          file=sys.stderr)

    # dispatch-only latency (async, no fetch)
    t_d_min, t_d_avg = bench_phase(
        lambda: (jnp.float32(1.0) + one).block_until_ready(), iters=20)
    print(f"blocking tiny dispatch: min {t_d_min*1e3:.1f} ms "
          f"avg {t_d_avg*1e3:.1f} ms", file=sys.stderr)

    import lightgbm_tpu as lgb

    params = {"objective": "binary", "num_leaves": num_leaves,
              "learning_rate": 0.1, "max_bin": 63, "min_data_in_leaf": 20,
              "verbosity": 0}
    ds = lgb.Dataset(x, label=y, params=params)   # bin at the CLAIMED max_bin
    ds.construct()
    bst = lgb.Booster(params=params, train_set=ds)
    m = bst._model

    # one full update to compile everything
    t0 = time.perf_counter()
    bst.update()
    print(f"compile+iter1: {time.perf_counter()-t0:.1f} s", file=sys.stderr)

    # now phase-by-phase, repeated
    from lightgbm_tpu.tree_model import Tree
    from lightgbm_tpu.predict_device import round_up_pow2

    phases = {k: [] for k in ("grad", "vals", "grow", "fetch", "hosttree",
                              "score", "total")}
    reps = 8
    for _ in range(reps):
        t_all0 = time.perf_counter()

        t0 = time.perf_counter()
        g, h = m.objective.get_gradients(m.score[:, 0])
        jax.block_until_ready((g, h))
        phases["grad"].append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        w = jnp.ones(m.num_data, jnp.float32)
        vals = jnp.stack([g * w, h * w, w], axis=1)
        jax.block_until_ready(vals)
        phases["vals"].append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        gkw = {}
        if m._ic_grow is not None:
            gkw["is_cat"] = m._ic_grow
        fmask = jnp.asarray(m._feature_mask())
        arrays = m.grower(m.binned_dev, vals, fmask, m._nb_grow,
                          m._na_grow, **gkw)
        jax.block_until_ready(arrays)
        phases["grow"].append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        small = arrays._replace(leaf_of_row=arrays.num_leaves)
        host = jax.device_get(small)._replace(leaf_of_row=arrays.leaf_of_row)
        phases["fetch"].append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        nl = int(host.num_leaves)
        leaf_values = np.asarray(host.leaf_value, np.float64).copy()
        leaf_values *= m.learning_rate
        ht = Tree.from_arrays(host, m.train_set.used_features,
                              m.train_set.bin_mappers)
        ht.leaf_value = leaf_values[:max(nl, 1)].copy()
        steps = round_up_pow2(max(ht.max_depth(), 1))
        phases["hosttree"].append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        lv_dev = jnp.asarray(leaf_values, jnp.float32)
        delta = jnp.take(lv_dev, arrays.leaf_of_row)
        score = m.score.at[:, 0].add(delta)
        jax.block_until_ready(score)
        phases["score"].append(time.perf_counter() - t0)
        m.score = score

        phases["total"].append(time.perf_counter() - t_all0)

    print(f"\nper-phase (over {reps} reps), n={n} leaves={num_leaves}:",
          file=sys.stderr)
    total_min = sum(min(v) for k, v in phases.items() if k != "total")
    for k, v in phases.items():
        print(f"  {k:9s} min {min(v)*1e3:8.1f} ms   avg "
              f"{np.mean(v)*1e3:8.1f} ms", file=sys.stderr)
    print(f"  (sum of phase mins: {total_min*1e3:.1f} ms)", file=sys.stderr)

    # contrast: plain bst.update() loop (what bench.py measures)
    t0 = time.perf_counter()
    k = 5
    for _ in range(k):
        bst.update()
    np.asarray(m.score)
    print(f"\nplain bst.update() x{k}: {(time.perf_counter()-t0)/k*1e3:.1f} "
          f"ms/iter", file=sys.stderr)


if __name__ == "__main__":
    main()
