"""Worker entry for tests/test_distributed_module.py — imported by the
``lightgbm_tpu.distributed`` launcher in each spawned process
(``--entry dist_worker:worker``)."""

import numpy as np


def _global_data(n=4096, f=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float64)
    y = (x[:, 0] - 0.7 * x[:, 1] + 0.2 * rng.randn(n) > 0) \
        .astype(np.float32)
    return x, y


def worker(ctx, args):
    from lightgbm_tpu import distributed
    x, y = _global_data()
    # global weights: distributed.train must shard them with the rows
    w = np.full(len(y), 1.0, np.float32) if args.get("weighted") else None
    bst = distributed.train(args["params"], x, y, weight=w,
                            num_boost_round=args["rounds"])
    # every rank must hold the same replicated model
    return {"rank": ctx.rank, "machines": ctx.machines,
            "model": bst.model_to_string(),
            "pred_head": bst.predict(x[:64], raw_score=True).tolist()}
