"""Native C API inference runtime vs Python predictor parity
(c_api.h prediction surface analog; tests/c_api_test/test_.py pattern)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.capi import NativeBooster, native_available

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native toolchain unavailable")


def _train(params, x, y, rounds=10, **ds_kw):
    ds = lgb.Dataset(x, label=y, **ds_kw)
    return lgb.train(dict(params, verbosity=-1), ds, num_boost_round=rounds)


def _roundtrip(bst, tmp_path):
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    return NativeBooster(model_file=path)


def test_binary_parity(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.randn(500, 8)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
    bst = _train({"objective": "binary", "num_leaves": 15}, x, y)
    nb = _roundtrip(bst, tmp_path)
    xt = rng.randn(100, 8)
    np.testing.assert_allclose(nb.predict(xt), bst.predict(xt), rtol=2e-5, atol=1e-7)
    np.testing.assert_allclose(nb.predict(xt, raw_score=True),
                               bst.predict(xt, raw_score=True), rtol=2e-5, atol=1e-7)
    assert nb.num_classes == 1
    assert nb.num_feature == 8
    assert nb.current_iteration() == 10


def test_multiclass_parity(tmp_path):
    rng = np.random.RandomState(1)
    x = rng.randn(600, 6)
    y = (np.abs(x[:, 0]) + x[:, 1] > 1).astype(int) + (x[:, 2] > 0)
    bst = _train({"objective": "multiclass", "num_class": 3,
                  "num_leaves": 7}, x, y.astype(np.float64))
    nb = _roundtrip(bst, tmp_path)
    xt = rng.randn(50, 6)
    np.testing.assert_allclose(nb.predict(xt), bst.predict(xt), rtol=2e-5, atol=1e-7)
    got = nb.predict(xt)
    np.testing.assert_allclose(got.sum(axis=1), 1.0, rtol=2e-5, atol=1e-7)  # softmax


def test_missing_and_categorical_parity(tmp_path):
    rng = np.random.RandomState(2)
    n = 800
    x = rng.randn(n, 5)
    x[rng.rand(n, 5) < 0.2] = np.nan
    cat = rng.randint(0, 12, size=n).astype(np.float64)
    x = np.column_stack([x, cat])
    y = (np.nan_to_num(x[:, 0]) + (cat % 3 == 0)).astype(np.float64)
    bst = _train({"objective": "regression", "num_leaves": 15}, x, y,
                 categorical_feature=[5])
    nb = _roundtrip(bst, tmp_path)
    xt = x[:200]
    np.testing.assert_allclose(nb.predict(xt), bst.predict(xt), rtol=2e-5, atol=1e-7)


def test_leaf_index_parity(tmp_path):
    rng = np.random.RandomState(3)
    x = rng.randn(400, 4)
    y = x[:, 0] * x[:, 1]
    bst = _train({"objective": "regression", "num_leaves": 8}, x, y, rounds=5)
    nb = _roundtrip(bst, tmp_path)
    xt = rng.randn(30, 4)
    np.testing.assert_array_equal(nb.predict(xt, pred_leaf=True),
                                  bst.predict(xt, pred_leaf=True))


def test_model_string_and_iter_range(tmp_path):
    rng = np.random.RandomState(4)
    x = rng.randn(300, 4)
    y = x[:, 0] + rng.randn(300) * 0.1
    bst = _train({"objective": "regression", "num_leaves": 8}, x, y, rounds=8)
    nb = NativeBooster(model_str=bst.model_to_string())
    xt = rng.randn(20, 4)
    np.testing.assert_allclose(
        nb.predict(xt, num_iteration=3),
        bst.predict(xt, num_iteration=3), rtol=2e-5, atol=1e-7)
    np.testing.assert_allclose(
        nb.predict(xt, start_iteration=2, num_iteration=4),
        bst.predict(xt, start_iteration=2, num_iteration=4), rtol=2e-5, atol=1e-7)


def test_linear_tree_parity(tmp_path):
    rng = np.random.RandomState(5)
    x = rng.randn(500, 3)
    y = 2.0 * x[:, 0] - x[:, 1] + 0.1 * rng.randn(500)
    bst = _train({"objective": "regression", "num_leaves": 6,
                  "linear_tree": True}, x, y, rounds=5)
    nb = _roundtrip(bst, tmp_path)
    xt = rng.randn(40, 3)
    np.testing.assert_allclose(nb.predict(xt), bst.predict(xt), rtol=2e-5, atol=1e-7)


def test_error_on_bad_model():
    with pytest.raises(RuntimeError):
        NativeBooster(model_str="this is not a model")


def test_error_on_corrupt_numeric_field():
    """std::stoi failures must surface as errors, not abort the process
    (exception must not escape the C ABI)."""
    bad = ("num_class=1\nnum_tree_per_iteration=1\nmax_feature_idx=0\n"
           "Tree=0\nnum_leaves=abc\n")
    with pytest.raises(RuntimeError):
        NativeBooster(model_str=bad)


def test_csr_predict_parity(tmp_path):
    from scipy.sparse import csr_matrix
    rng = np.random.RandomState(2)
    x = rng.randn(400, 8)
    x[rng.rand(*x.shape) < 0.7] = 0.0
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
    bst = _train({"objective": "binary", "num_leaves": 15}, x, y)
    nb = _roundtrip(bst, tmp_path)
    xs = csr_matrix(x)
    np.testing.assert_allclose(nb.predict(xs), nb.predict(x),
                               rtol=1e-12, atol=0)
    np.testing.assert_allclose(nb.predict(xs), bst.predict(x),
                               rtol=2e-5, atol=1e-7)
    # leaf indices via CSR too
    np.testing.assert_array_equal(nb.predict(xs, pred_leaf=True),
                                  nb.predict(x, pred_leaf=True))


def test_predict_file_csv_and_libsvm(tmp_path):
    rng = np.random.RandomState(3)
    x = rng.randn(120, 5)
    y = (x[:, 0] > 0).astype(np.float64)
    bst = _train({"objective": "binary", "num_leaves": 7}, x, y, rounds=5)
    nb = _roundtrip(bst, tmp_path)
    ref = nb.predict(x)

    # CSV with leading label column (the reference predict-task layout)
    csv = tmp_path / "data.csv"
    with open(csv, "w") as f:
        for i in range(x.shape[0]):
            f.write(",".join([str(y[i])] + [f"{v:.17g}" for v in x[i]]) + "\n")
    out_csv = tmp_path / "pred_csv.txt"
    nb.predict_file(str(csv), str(out_csv))
    got = np.loadtxt(out_csv)
    np.testing.assert_allclose(got, ref, rtol=1e-9)

    # LibSVM (zero-based feature ids)
    svm = tmp_path / "data.svm"
    with open(svm, "w") as f:
        for i in range(x.shape[0]):
            pairs = " ".join(f"{j}:{x[i, j]:.17g}" for j in range(x.shape[1]))
            f.write(f"{y[i]} {pairs}\n")
    out_svm = tmp_path / "pred_svm.txt"
    nb.predict_file(str(svm), str(out_svm))
    np.testing.assert_allclose(np.loadtxt(out_svm), ref, rtol=1e-9)


def test_predict_file_na_tokens_and_short_rows(tmp_path):
    # ADVICE r3: "NA"/text fields map to missing (NaN) instead of aborting
    # the file, and rows shorter than ncol leave trailing features missing
    # rather than 0.0 (reference parser missing-value semantics)
    rng = np.random.RandomState(7)
    x = rng.randn(300, 4)
    y = (x[:, 0] - x[:, 3] > 0).astype(np.float64)
    bst = _train({"objective": "binary", "num_leaves": 15,
                  "use_missing": True}, x, y)
    nb = _roundtrip(bst, tmp_path)

    xt = rng.randn(3, 4)
    csv = tmp_path / "na.csv"
    lines = []
    for i, r in enumerate(xt):
        cells = ["0"] + [f"{v:.8f}" for v in r]
        if i == 0:
            cells[2] = "NA"            # text token -> NaN
        if i == 1:
            cells = cells[:3]          # short row -> trailing NaN
        lines.append(",".join(cells))
    csv.write_text("\n".join(lines) + "\n")
    out = tmp_path / "na_out.txt"
    nb.predict_file(str(csv), str(out))
    got = np.loadtxt(str(out))

    xt_expect = xt.copy()
    xt_expect[0, 1] = np.nan
    xt_expect[1, 2:] = np.nan
    want = bst.predict(xt_expect)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-7)


def test_csr_out_of_range_indices_ignored(tmp_path):
    # malformed CSR entries (index < 0 or >= ncol) are dropped, not an
    # out-of-bounds heap write
    import ctypes
    rng = np.random.RandomState(8)
    x = rng.randn(200, 5)
    y = (x[:, 0] > 0).astype(np.float64)
    bst = _train({"objective": "binary", "num_leaves": 7}, x, y)
    nb = _roundtrip(bst, tmp_path)

    row = np.array([0.5, -1.2], dtype=np.float64)
    indptr = np.array([0, 2], dtype=np.int32)
    bad_indices = np.array([0, 99], dtype=np.int32)   # 99 >= ncol=5
    out = np.zeros((1, 1), dtype=np.float64)
    out_len = ctypes.c_int64(0)
    rc = nb._lib.LGBM_BoosterPredictForCSR(
        nb._handle, indptr, 2, bad_indices, row, 2, 5, 0, 0, -1,
        ctypes.byref(out_len), out)
    assert rc == 0
    out = out[:, 0]
    dense = np.zeros((1, 5))
    dense[0, 0] = 0.5                  # the bad entry contributes nothing
    np.testing.assert_allclose(out, bst.predict(dense), rtol=2e-5, atol=1e-7)
