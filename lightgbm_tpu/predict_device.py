"""Device-side tree traversal over binned data.

Used for validation-set score updates each iteration (the reference's
``ScoreUpdater::AddScore(tree)`` path, score_updater.hpp:21-128) and for
batched leaf prediction.  The traversal is a fixed-depth ``fori_loop`` of
vectorized gathers: every row walks one level per step; finished rows carry
their (negative-encoded) leaf id unchanged — static shapes, no divergence.

Numerical and categorical decisions share one predicate: per-node
``cat_rank`` maps bin -> decision rank (identity for numerical nodes), go
left iff rank <= threshold (see ops/split.py SplitResult).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.jit, static_argnames=("steps",))
def traverse_tree_binned(binned, split_feature, threshold_bin, default_left,
                         left_child, right_child, na_bin, is_cat_node,
                         cat_rank, efb_maps=None, *, steps: int):
    """Return the leaf index for every row of ``binned`` [N, F].

    ``efb_maps``: optional (group_of_feat, off_of_feat, nbm1_of_feat) device
    arrays when ``binned`` is the EFB-grouped matrix [N, G] (efb.py) — the
    gathered group bin is unmapped to the feature's own bin space."""
    n = binned.shape[0]
    node = jnp.zeros(n, jnp.int32)

    def body(_, node):
        internal = node >= 0
        nid = jnp.maximum(node, 0)
        f = split_feature[nid]
        if efb_maps is None:
            col = f
        else:
            col = efb_maps[0][f]
        v = jnp.take_along_axis(binned, col[:, None].astype(jnp.int32),
                                axis=1)[:, 0].astype(jnp.int32)
        if efb_maps is not None:
            off, nbm1 = efb_maps[1][f], efb_maps[2][f]
            v = jnp.where(off < 0, v,
                          jnp.where((v >= off) & (v < off + nbm1),
                                    v - off + 1, 0))
        nb = na_bin[f]
        is_na = (nb >= 0) & (v == nb) & (~is_cat_node[nid])
        rank = cat_rank[nid, v]
        go_left = jnp.where(is_na, default_left[nid], rank <= threshold_bin[nid])
        nxt = jnp.where(go_left, left_child[nid], right_child[nid])
        return jnp.where(internal, nxt, node)

    node = lax.fori_loop(0, steps, body, node)
    return (~node).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("steps",))
def add_tree_score(score, binned, split_feature, threshold_bin, default_left,
                   left_child, right_child, na_bin, is_cat_node, cat_rank,
                   leaf_value, weight, efb_maps=None, *, steps: int):
    """score += weight * tree(binned) — incremental ScoreUpdater step."""
    leaf = traverse_tree_binned(binned, split_feature, threshold_bin,
                                default_left, left_child, right_child,
                                na_bin, is_cat_node, cat_rank, efb_maps,
                                steps=steps)
    return score + weight * jnp.take(leaf_value, leaf)


def round_up_pow2(x: int) -> int:
    """Bucket traversal depth to limit jit-cache entries."""
    p = 1
    while p < x:
        p *= 2
    return p
