"""Embedding bridge for the native training C API.

The reference exposes its full training surface through C
(/root/reference/src/c_api.cpp: LGBM_DatasetCreateFromMat :~900,
LGBM_BoosterCreate :1600, LGBM_BoosterUpdateOneIter :1686,
LGBM_BoosterSaveModel...).  In the TPU rebuild the training core is a JAX
program, so the native shim (native/capi_train.cpp) embeds CPython and
calls these thin adapters; zero-copy views of the caller's buffers come in
as memoryviews.

Functions here must stay exception-safe-by-contract: the C++ caller
converts any raised exception into LGBM_GetLastError().
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

if os.environ.get("LGBM_TPU_FORCE_CPU"):
    # embedded hosts (pure-C callers) can't run the test conftest; honor an
    # env switch so they avoid claiming the exclusive TPU tunnel
    import jax
    jax.config.update("jax_platforms", "cpu")

from .booster import Booster
from .config import kv2map
from .dataset import Dataset

_F32, _F64, _I32, _I64 = 0, 1, 2, 3
_NP_OF = {_F32: np.float32, _F64: np.float64, _I32: np.int32, _I64: np.int64}


def _params(s: str) -> dict:
    return kv2map((s or "").replace("\n", " ").split())


def dataset_create_from_mat(mv, nrow: int, ncol: int, params: str,
                            reference: Optional[Dataset] = None) -> Dataset:
    arr = np.frombuffer(mv, np.float64).reshape(int(nrow), int(ncol)).copy()
    return Dataset(arr, params=_params(params), reference=reference)


def dataset_create_from_file(path: str, params: str,
                             reference: Optional[Dataset] = None) -> Dataset:
    from .data_io import load_text
    p = _params(params)
    # binary dataset cache (the reference detects its binary magic the
    # same way, dataset_loader.cpp LoadFromBinFile): the npz container
    # starts with the zip magic
    real = path if os.path.exists(path) else (
        path + ".npz" if os.path.exists(path + ".npz") else path)
    try:
        with open(real, "rb") as f:
            if f.read(2) == b"PK":
                return Dataset.load_binary(real)
    except OSError:
        pass
    x, y = load_text(path, has_header=str(p.get("header", "")).lower()
                     in ("true", "1"),
                     label_column=str(p.get("label_column", "")))
    return Dataset(x, label=y, params=p, reference=reference)


def dataset_set_field(ds, name: str, mv, n: int, dtype: int) -> None:
    arr = np.frombuffer(mv, _NP_OF[int(dtype)])[:int(n)].copy()
    if isinstance(ds, _StreamingDataset) and ds.ds is None:
        # SetField is valid at any point of the streaming protocol in the
        # reference C API; it must not finalize the dataset mid-stream
        ds.pending_fields[name] = arr
        return
    ds = _as_dataset(ds)
    if name == "label":
        ds.set_label(arr)
    elif name == "weight":
        ds.set_weight(arr)
    elif name in ("group", "query"):
        ds.set_group(arr)
    elif name == "init_score":
        nrows = ds.num_data if getattr(ds, "num_data", 0) else (
            ds._raw_input.shape[0]
            if getattr(ds, "_raw_input", None) is not None
            and hasattr(ds._raw_input, "shape") else len(arr))
        if nrows and len(arr) > nrows:
            # multiclass: the C API ships class-major blocks
            # ([all rows class 0, all rows class 1, ...], c_api.h);
            # internal storage is [rows, classes]
            arr = np.ascontiguousarray(arr.reshape((-1, nrows)).T)
        ds.set_init_score(arr)
    else:
        raise ValueError(f"unknown field {name!r}")


def dataset_num_data(ds) -> int:
    ds = _as_dataset(ds)
    ds.construct()
    return int(ds.num_data)


def dataset_num_feature(ds) -> int:
    ds = _as_dataset(ds)
    ds.construct()
    return int(ds.num_total_features)


def booster_create(ds, params: str) -> Booster:
    return Booster(params=_params(params), train_set=_as_dataset(ds))


def booster_create_from_model_string(s: str) -> Booster:
    return Booster(model_str=s)


def booster_add_valid(bst: Booster, ds, name: str) -> None:
    bst.add_valid(_as_dataset(ds), name)


def booster_update(bst: Booster) -> int:
    return 1 if bst.update() else 0


def booster_rollback(bst: Booster) -> None:
    bst.rollback_one_iter()


def booster_current_iteration(bst: Booster) -> int:
    return int(bst.current_iteration)


def booster_num_classes(bst: Booster) -> int:
    return int(bst._num_class)


def booster_save_model_to_string(bst: Booster, start_iteration: int,
                                 num_iteration: int) -> str:
    num = num_iteration if num_iteration > 0 else None
    return bst.model_to_string(num_iteration=num,
                               start_iteration=int(start_iteration))


def booster_save_model(bst: Booster, start_iteration: int,
                       num_iteration: int, filename: str) -> None:
    # utf-8 to match Booster's load side and the artifact-checksum
    # convention (snapshot manifests hash utf-8 bytes); the locale
    # default would break the round-trip on non-utf-8 hosts
    with open(filename, "w", encoding="utf-8") as f:
        f.write(booster_save_model_to_string(bst, start_iteration,
                                             num_iteration))


def booster_get_eval(bst: Booster) -> str:
    """One eval sweep, rendered as 'name metric value' lines."""
    rows = bst.eval_valid() + bst.eval_train()
    return "\n".join(f"{dn}\t{mn}\t{val!r}" for dn, mn, val, _ in rows)


def _predict_dispatch(bst: Booster, x, predict_type: int,
                      start_iteration: int, num_iteration: int) -> np.ndarray:
    """predict_type: 0 normal, 1 raw, 2 leaf index, 3 contrib
    (C_API_PREDICT_* values, c_api.h:527-535) — the single dispatch used
    by every C prediction entry point."""
    num = int(num_iteration) if int(num_iteration) > 0 else None
    kw = dict(start_iteration=int(start_iteration), num_iteration=num)
    predict_type = int(predict_type)
    if predict_type == 2:
        res = bst.predict(x, pred_leaf=True, **kw)
    elif predict_type == 3:
        res = bst.predict(x, pred_contrib=True, **kw)
    else:
        res = bst.predict(x, raw_score=(predict_type == 1), **kw)
    return np.asarray(res, np.float64)


def _predict_out(bst: Booster, x, predict_type: int, start_iteration: int,
                 num_iteration: int, out_mv) -> int:
    res = _predict_dispatch(bst, x, predict_type, start_iteration,
                            num_iteration)
    flat = np.ascontiguousarray(res).reshape(-1)
    out = np.frombuffer(out_mv, np.float64)
    if len(flat) > len(out):
        raise ValueError(f"output buffer too small: need {len(flat)}, "
                         f"have {len(out)}")
    out[:len(flat)] = flat
    return int(len(flat))


def booster_predict_mat(bst: Booster, mv, nrow: int, ncol: int,
                        predict_type: int, start_iteration: int,
                        num_iteration: int, out_mv) -> int:
    """predict_type: 0 normal, 1 raw, 2 leaf index, 3 contrib
    (C_API_PREDICT_* values, c_api.h:527-535)."""
    x = np.frombuffer(mv, np.float64).reshape(int(nrow), int(ncol))
    return _predict_out(bst, x, predict_type, start_iteration,
                        num_iteration, out_mv)


# ---------------------------------------------------------------------------
# CSR / CSC dataset construction + prediction
# (LGBM_DatasetCreateFromCSR/CSC c_api.h:200-268;
#  LGBM_BoosterPredictForCSR c_api.h:815)
# ---------------------------------------------------------------------------

def _sparse_parts(indptr_mv, n_indptr, indices_mv, data_mv, nelem):
    indptr = np.frombuffer(indptr_mv, np.int32)[:int(n_indptr)].copy()
    indices = np.frombuffer(indices_mv, np.int32)[:int(nelem)].copy()
    data = np.frombuffer(data_mv, np.float64)[:int(nelem)].copy()
    return indptr, indices, data


def _csr(indptr_mv, n_indptr, indices_mv, data_mv, nelem, ncol):
    from scipy.sparse import csr_matrix
    indptr, indices, data = _sparse_parts(indptr_mv, n_indptr, indices_mv,
                                          data_mv, nelem)
    return csr_matrix((data, indices, indptr),
                      shape=(int(n_indptr) - 1, int(ncol)))


def dataset_create_from_csr(indptr_mv, n_indptr, indices_mv, data_mv,
                            nelem, ncol, params: str,
                            reference: Optional[Dataset] = None) -> Dataset:
    return Dataset(_csr(indptr_mv, n_indptr, indices_mv, data_mv, nelem,
                        ncol), params=_params(params), reference=reference)


def dataset_create_from_csc(indptr_mv, n_indptr, indices_mv, data_mv,
                            nelem, nrow, params: str,
                            reference: Optional[Dataset] = None) -> Dataset:
    from scipy.sparse import csc_matrix
    indptr, indices, data = _sparse_parts(indptr_mv, n_indptr, indices_mv,
                                          data_mv, nelem)
    mat = csc_matrix((data, indices, indptr),
                     shape=(int(nrow), int(n_indptr) - 1))
    return Dataset(mat, params=_params(params), reference=reference)


def booster_predict_csr(bst: Booster, indptr_mv, n_indptr, indices_mv,
                        data_mv, nelem, ncol, predict_type: int,
                        start_iteration: int, num_iteration: int,
                        out_mv) -> int:
    x = _csr(indptr_mv, n_indptr, indices_mv, data_mv, nelem, ncol)
    return _predict_out(bst, x, predict_type, start_iteration,
                        num_iteration, out_mv)


# ---------------------------------------------------------------------------
# Streaming dataset construction
# (LGBM_DatasetCreateFromSampledColumn + LGBM_DatasetPushRows[ByCSR],
#  c_api.h:109-313).  The reference pre-builds bin mappers from the sample
#  and bins rows as they are pushed; here rows are accumulated and binned
#  at finalize — same API contract and final Dataset, with peak memory one
#  float64 copy of the raw matrix (the TPU learner keeps a dense binned
#  matrix in HBM anyway, so sampled-column binning would not change the
#  steady-state footprint).
# ---------------------------------------------------------------------------

class _StreamingDataset:
    def __init__(self, nrow: int, ncol: int, params: str):
        self.buf = np.full((int(nrow), int(ncol)), np.nan, np.float64)
        self.filled = 0
        self.params = _params(params)
        self.pending_fields: dict = {}
        self.ds: Optional[Dataset] = None
        self.mappers = None            # CreateFromSampledColumn pre-fit
        self.reference = None          # CreateByReference alignment

    def finish(self) -> Dataset:
        if self.ds is None:
            self.ds = Dataset(self.buf[:self.filled], params=self.params,
                              bin_mappers=self.mappers,
                              reference=self.reference)
            for name, arr in self.pending_fields.items():
                dataset_set_field(self.ds, name, memoryview(arr.tobytes()),
                                  len(arr),
                                  {np.dtype(np.float32): _F32,
                                   np.dtype(np.float64): _F64,
                                   np.dtype(np.int32): _I32,
                                   np.dtype(np.int64): _I64}[arr.dtype])
        return self.ds


def dataset_create_streaming(nrow: int, ncol: int,
                             params: str) -> _StreamingDataset:
    return _StreamingDataset(nrow, ncol, params)


def dataset_push_rows(sd: _StreamingDataset, mv, nrow: int, ncol: int,
                      start_row: int) -> None:
    if sd.ds is not None:
        raise ValueError("dataset already finalized")
    arr = np.frombuffer(mv, np.float64).reshape(int(nrow), int(ncol))
    sd.buf[int(start_row):int(start_row) + int(nrow), :int(ncol)] = arr
    sd.filled = max(sd.filled, int(start_row) + int(nrow))


def dataset_push_rows_by_csr(sd: _StreamingDataset, indptr_mv, n_indptr,
                             indices_mv, data_mv, nelem,
                             start_row: int) -> None:
    if sd.ds is not None:
        raise ValueError("dataset already finalized")
    x = _csr(indptr_mv, n_indptr, indices_mv, data_mv, nelem,
             sd.buf.shape[1]).toarray()
    sd.buf[int(start_row):int(start_row) + x.shape[0]] = x
    sd.filled = max(sd.filled, int(start_row) + x.shape[0])


def _as_dataset(ds):
    """Streaming handles are accepted anywhere a Dataset is (finalized on
    first use, like the reference's mark-finished semantics)."""
    return ds.finish() if isinstance(ds, _StreamingDataset) else ds


# ---------------------------------------------------------------------------
# Booster getters / reset (c_api.h booster introspection surface)
# ---------------------------------------------------------------------------

def booster_num_feature(bst: Booster) -> int:
    return int(bst.num_feature())


def booster_get_eval_names(bst: Booster) -> str:
    """Metadata-only (the reference's GetEvalNames does not evaluate)."""
    names = []
    for m in bst._train_metrics:
        if m.name not in names:
            names.append(m.name)
    return "\t".join(names)


def booster_feature_importance(bst: Booster, importance_type: int,
                               out_mv) -> int:
    """importance_type: 0 split, 1 gain (C_API_FEATURE_IMPORTANCE_*)."""
    imp = bst.feature_importance(
        importance_type="gain" if importance_type == 1 else "split")
    out = np.frombuffer(out_mv, np.float64)
    if len(imp) > len(out):
        raise ValueError("output buffer too small")
    out[:len(imp)] = imp.astype(np.float64)
    return int(len(imp))


def booster_reset_parameter(bst: Booster, params: str) -> None:
    bst.reset_parameter(_params(params))


def booster_dump_model(bst: Booster, start_iteration: int,
                       num_iteration: int) -> str:
    """JSON model dump (LGBM_BoosterDumpModel, c_api.h; DumpModel)."""
    import json
    num = num_iteration if num_iteration > 0 else None
    return json.dumps(bst.dump_model(num_iteration=num,
                                     start_iteration=int(start_iteration)))


def booster_refit(bst: Booster, mv, nrow: int, ncol: int, label_mv,
                  decay_rate: float) -> Booster:
    """Refit existing tree structures on new data
    (LGBM_BoosterRefit, c_api.h; GBDT::RefitTree gbdt.cpp:287)."""
    x = np.frombuffer(mv, np.float64).reshape(int(nrow), int(ncol)).copy()
    label = np.frombuffer(label_mv, np.float32)[:int(nrow)].copy()
    return bst.refit(x, label, decay_rate=float(decay_rate))


def dataset_get_field(ds, name: str):
    """(address, length, type_code) of a metadata field, or length 0 when
    unset (LGBM_DatasetGetField, c_api.h).  'group' returns the QUERY
    BOUNDARIES array [num_queries+1] like the reference.  The backing
    array is pinned on the Dataset so the pointer stays valid until the
    next GetField call on the same handle."""
    ds = _as_dataset(ds)
    ds.construct()
    md = ds.metadata
    if name == "label":
        arr, code = md.label, _F32
    elif name == "weight":
        arr, code = md.weight, _F32
    elif name in ("group", "query"):
        arr, code = md.query_boundaries, _I32
    elif name == "init_score":
        arr, code = md.init_score, _F64
    else:
        raise ValueError(f"unknown field {name!r}")
    if arr is None:
        # empty field: valid dtype code + null pointer, like the reference
        return (0, 0, code)
    arr = np.asarray(arr, _NP_OF[code])
    if arr.ndim == 2:
        # multiclass init_score: the C API contract is CLASS-MAJOR
        # ([all rows class 0, all rows class 1, ...], c_api.h GetField)
        arr = arr.flatten(order="F")
    arr = np.ascontiguousarray(arr)
    ds._field_out = arr            # keep the buffer alive for the caller
    return (int(arr.ctypes.data), int(arr.size), code)


def dataset_save_binary(ds, filename: str) -> None:
    """Binary dataset cache (LGBM_DatasetSaveBinary, c_api.h;
    Dataset::SaveBinaryFile)."""
    ds = _as_dataset(ds)
    ds.construct()
    ds.save_binary(filename)


def dataset_get_feature_names(ds) -> str:
    ds = _as_dataset(ds)
    ds.construct()
    names = ds.feature_names or [
        f"Column_{i}" for i in range(ds.num_total_features)]
    return "\t".join(names)


def dataset_set_feature_names(ds, names: str) -> None:
    ds = _as_dataset(ds)
    lst = names.split("\t")
    nf = getattr(ds, "num_total_features", 0)
    if not nf:
        # pre-construct: the raw input's width is already known
        raw = getattr(ds, "_raw_input", None)
        nf = raw.shape[1] if raw is not None \
            and hasattr(raw, "shape") and len(raw.shape) == 2 else 0
    if nf and len(lst) != nf:
        # fail at the API call, not later inside dump_model/save
        raise ValueError(f"{len(lst)} feature names for {nf} features")
    # set the constructor-style input too: construct()'s _resolve_names
    # would otherwise overwrite the assignment with Column_N defaults
    ds._feature_name_in = lst
    ds.feature_names = lst


# ---------------------------------------------------------------------------
# Network init (LGBM_NetworkInit, c_api.h:1350).  The reference builds its
# socket-collective mesh from a machine list; the TPU framework's
# collectives are XLA's, so this maps onto the jax.distributed runtime:
# coordinator = first machine, rank = position of the entry whose port
# matches local_listen_port (the reference derives rank by matching local
# addresses the same way, src/network/linkers_socket.cpp).
# ---------------------------------------------------------------------------

def network_init(machines: str, local_listen_port: int, listen_time_out: int,
                 num_machines: int) -> None:
    if num_machines <= 1:
        return
    entries = [m.strip() for m in machines.replace("\n", ",").split(",")
               if m.strip()]
    if len(entries) != num_machines:
        raise ValueError(
            f"machines lists {len(entries)} entries, num_machines="
            f"{num_machines}")
    from .parallel import launch
    # multi-process-per-host (the reference's distributed test topology,
    # tests/distributed/_test_distributed.py): every entry is the same
    # host with a DISTINCT port, so the port identifies the rank.  Only
    # safe when exactly one entry matches — the canonical multi-host
    # layout reuses one port on every machine, where the port would match
    # entry 0 everywhere; that case goes to launch.init's local-address
    # matching instead.
    matches = [i for i, e in enumerate(entries)
               if e.endswith(f":{local_listen_port}")]
    # the reference's listen_time_out is MINUTES (config.h time_out);
    # it bounds the resilience layer's bring-up watchdog + retry deadline
    timeout_s = max(0.0, float(listen_time_out)) * 60.0
    if len(matches) == 1:
        launch.init(coordinator_address=entries[0],
                    num_processes=num_machines, process_id=matches[0],
                    timeout_s=timeout_s)
    else:
        launch.init(machines=",".join(entries),
                    local_listen_port=local_listen_port,
                    timeout_s=timeout_s)


def network_free() -> None:
    import jax
    try:
        jax.distributed.shutdown()
    except RuntimeError:
        pass  # never initialized


# ---------------------------------------------------------------------------
# Reference-exact ABI adapters (VERDICT r3 task 5): the typed/positional
# variants the reference's own c_api.h prototypes use (c_api.h:109,203,
# 248,272,472,567,701,749,1072,1141-1199,1220), driven by the LGBM_*-named
# exports in native/capi_train.cpp so reference bindings and apps link
# against libcapi_train.so unmodified.
# ---------------------------------------------------------------------------

def _typed_matrix(mv, data_type: int, nrow: int, ncol: int,
                  is_row_major: int) -> np.ndarray:
    dt = _NP_OF[int(data_type)]
    arr = np.frombuffer(mv, dt)[:int(nrow) * int(ncol)]
    if int(is_row_major):
        arr = arr.reshape(int(nrow), int(ncol))
    else:
        arr = arr.reshape(int(ncol), int(nrow)).T
    return np.array(arr, np.float64, copy=True, order="C")


def dataset_create_from_mat2(mv, data_type: int, nrow: int, ncol: int,
                             is_row_major: int, params: str,
                             reference=None) -> Dataset:
    return Dataset(_typed_matrix(mv, data_type, nrow, ncol, is_row_major),
                   params=_params(params), reference=_as_dataset(reference)
                   if reference is not None else None)


def _typed_sparse_parts(indptr_mv, indptr_type, n_indptr, indices_mv,
                        data_mv, data_type, nelem):
    indptr = np.frombuffer(indptr_mv,
                           _NP_OF[int(indptr_type)])[:int(n_indptr)]
    indices = np.frombuffer(indices_mv, np.int32)[:int(nelem)]
    data = np.frombuffer(data_mv, _NP_OF[int(data_type)])[:int(nelem)]
    return (indptr.astype(np.int64), indices.copy(),
            data.astype(np.float64))


def dataset_create_from_csr2(indptr_mv, indptr_type, indices_mv, data_mv,
                             data_type, n_indptr, nelem, ncol, params: str,
                             reference=None) -> Dataset:
    from scipy.sparse import csr_matrix
    indptr, indices, data = _typed_sparse_parts(
        indptr_mv, indptr_type, n_indptr, indices_mv, data_mv, data_type,
        nelem)
    mat = csr_matrix((data, indices, indptr),
                     shape=(int(n_indptr) - 1, int(ncol)))
    return Dataset(mat, params=_params(params),
                   reference=_as_dataset(reference)
                   if reference is not None else None)


def dataset_create_from_csc2(colptr_mv, colptr_type, indices_mv, data_mv,
                             data_type, ncol_ptr, nelem, nrow, params: str,
                             reference=None) -> Dataset:
    from scipy.sparse import csc_matrix
    colptr, indices, data = _typed_sparse_parts(
        colptr_mv, colptr_type, ncol_ptr, indices_mv, data_mv, data_type,
        nelem)
    mat = csc_matrix((data, indices, colptr),
                     shape=(int(nrow), int(ncol_ptr) - 1))
    return Dataset(mat, params=_params(params),
                   reference=_as_dataset(reference)
                   if reference is not None else None)


def booster_num_total_model(bst: Booster) -> int:
    return int(len(bst.trees))


def booster_num_model_per_iteration(bst: Booster) -> int:
    return int(bst._num_tree_per_iteration)


def booster_get_eval_counts(bst: Booster) -> int:
    return len(booster_get_eval_names(bst).split("\t")) \
        if booster_get_eval_names(bst) else 0


def booster_get_eval_values(bst: Booster, data_idx: int, out_mv) -> int:
    """LGBM_BoosterGetEval (c_api.h:701): data_idx 0 = training data,
    i >= 1 = (i-1)-th validation set; one double per eval metric."""
    if int(data_idx) == 0:
        rows = bst.eval_train()
    else:
        names = bst._valid_names
        i = int(data_idx) - 1
        if i >= len(names):
            raise ValueError(f"data_idx {data_idx} out of range "
                             f"({len(names)} validation sets)")
        rows = [r for r in bst.eval_valid() if r[0] == names[i]]
    vals = np.asarray([v for _, _, v, _ in rows], np.float64)
    out = np.frombuffer(out_mv, np.float64)
    if len(vals) > len(out):
        raise ValueError("output buffer too small")
    out[:len(vals)] = vals
    return int(len(vals))


def booster_predict_mat2(bst: Booster, mv, data_type: int, nrow: int,
                         ncol: int, is_row_major: int, predict_type: int,
                         start_iteration: int, num_iteration: int,
                         out_mv) -> int:
    x = _typed_matrix(mv, data_type, nrow, ncol, is_row_major)
    return _predict_out(bst, x, predict_type, start_iteration,
                        num_iteration, out_mv)


def booster_predict_csr2(bst: Booster, indptr_mv, indptr_type, indices_mv,
                         data_mv, data_type, n_indptr, nelem, ncol,
                         predict_type: int, start_iteration: int,
                         num_iteration: int, out_mv) -> int:
    from scipy.sparse import csr_matrix
    indptr, indices, data = _typed_sparse_parts(
        indptr_mv, indptr_type, n_indptr, indices_mv, data_mv, data_type,
        nelem)
    x = csr_matrix((data, indices, indptr),
                   shape=(int(n_indptr) - 1, int(ncol)))
    return _predict_out(bst, x, predict_type, start_iteration,
                        num_iteration, out_mv)


def booster_predict_for_file(bst: Booster, data_filename: str,
                             has_header: int, predict_type: int,
                             start_iteration: int, num_iteration: int,
                             result_filename: str) -> None:
    """LGBM_BoosterPredictForFile (c_api.h:749): text rows follow the
    training convention (label in the first column unless the width
    already matches the model)."""
    from .data_io import load_text
    x, y = load_text(data_filename, has_header=bool(int(has_header)))
    nf = bst.num_feature()
    if x.shape[1] == nf - 1 and y is not None:
        # the file had NO label column: load_text treated feature 0 as
        # the label — put it back
        x = np.column_stack([y, x])
    res = np.atleast_1d(_predict_dispatch(bst, x, predict_type,
                                          start_iteration, num_iteration))
    with open(result_filename, "w") as f:
        if res.ndim == 1:
            for v in res:
                f.write(f"{v:.17g}\n")
        else:
            for row in res:
                f.write("\t".join(f"{v:.17g}" for v in row) + "\n")


def booster_add_valid_auto(bst: Booster, ds) -> None:
    booster_add_valid(bst, ds, f"valid_{len(bst._valid_names)}")


def booster_update_custom(bst: Booster, grad_mv, hess_mv, n: int) -> int:
    g = np.frombuffer(grad_mv, np.float32)[:int(n)].copy()
    h = np.frombuffer(hess_mv, np.float32)[:int(n)].copy()
    nc = int(bst._model.num_class)
    if nc > 1:
        # the C contract ships class-major blocks ([all rows class 0,
        # all rows class 1, ...], c_api.h:589); internal layout is
        # [rows, classes]
        nd = int(bst._model.num_data)
        g = np.ascontiguousarray(g.reshape(nc, nd).T)
        h = np.ascontiguousarray(h.reshape(nc, nd).T)
    return 1 if bst.update(fobj=lambda preds, ds: (g, h)) else 0


def booster_train_num_data(bst: Booster) -> int:
    """Gradient buffer length for LGBM_BoosterUpdateOneIterCustom:
    num_data * num_class (c_api.h:589-595 contract)."""
    return int(bst._model.num_data * bst._model.num_class)


# ---------------------------------------------------------------------------
# The remaining reference entry points (c_api.h full-surface closure):
# sampled-column/by-reference construction, subset, feature merge, text
# dump, per-feature bin counts, model surgery (merge/shuffle/leaf get-set),
# leaf-pred refit, reset-training-data, bound values, sparse-output
# predict, param-alias dump, log forwarding.
# ---------------------------------------------------------------------------

def dump_param_aliases() -> str:
    """LGBM_DumpParamAliases (c_api.h:62): JSON param -> [aliases]."""
    import json
    from .config import _PARAMS
    return json.dumps({name: list(spec[2]) if len(spec) > 2 else []
                       for name, spec in _PARAMS.items()})


def sample_count(num_total_row: int, params: str) -> int:
    """LGBM_GetSampleCount: min(bin_construct_sample_cnt, total)."""
    p = _params(params)
    cnt = int(p.get("bin_construct_sample_cnt", 200000))
    return int(min(cnt, int(num_total_row)))


def sample_indices(num_total_row: int, params: str, out_mv) -> int:
    """LGBM_SampleIndices: the binning sample row ids (sorted, like the
    reference's Random::Sample)."""
    p = _params(params)
    n = sample_count(num_total_row, params)
    seed = int(p.get("data_random_seed", 1))
    rng = np.random.RandomState(seed)
    idx = np.sort(rng.choice(int(num_total_row), size=n, replace=False)
                  .astype(np.int32))
    out = np.frombuffer(out_mv, np.int32)
    out[:n] = idx
    return n


def register_log_forward(addr: int) -> None:
    """Route Log output to a C callback (LGBM_RegisterLogCallback)."""
    import ctypes
    from .utils import log as log_mod
    if addr == 0:
        log_mod._callback = None
        return
    cb = ctypes.CFUNCTYPE(None, ctypes.c_char_p)(int(addr))
    log_mod._callback = lambda msg: cb(msg.encode())


def dataset_create_from_sampled_column(cols, num_sample_row: int,
                                       num_total_row: int,
                                       params: str) -> "_StreamingDataset":
    """LGBM_DatasetCreateFromSampledColumn (c_api.h:126): pre-size the
    dataset and fit the bin mappers NOW from the per-column samples, so
    pushed rows bin against a fixed layout (the reference streams the
    same way); ``cols`` is a list of per-column sampled value arrays.
    find_bin's total count is the SAMPLE size (zeros are inferred as
    num_sample_row - len(col), not against the full dataset)."""
    from .binning import BinMapper
    from .config import Config
    p = _params(params)
    cfg = Config(p)
    mappers = []
    for vals in cols:
        m = BinMapper()
        m.find_bin(np.asarray(vals, np.float64), int(num_sample_row),
                   cfg.max_bin, cfg.min_data_in_bin,
                   use_missing=cfg.use_missing,
                   zero_as_missing=cfg.zero_as_missing)
        mappers.append(m)
    sd = _StreamingDataset(num_total_row, len(cols), params)
    sd.mappers = mappers
    return sd


def dataset_create_by_reference(ref, num_total_row: int) -> "_StreamingDataset":
    """LGBM_DatasetCreateByReference (c_api.h:142): pre-sized streaming
    dataset aligned to the reference's bin mappers."""
    ref = _as_dataset(ref)
    ref.construct()
    sd = _StreamingDataset(num_total_row, ref.num_total_features, "")
    sd.reference = ref
    return sd


def dataset_push_rows2(sd, mv, data_type: int, nrow: int, ncol: int,
                       start_row: int) -> None:
    """Typed LGBM_DatasetPushRows (c_api.h:156)."""
    arr = _typed_matrix(mv, data_type, nrow, ncol, 1)
    if sd.ds is not None:
        raise ValueError("dataset already finalized")
    sd.buf[int(start_row):int(start_row) + int(nrow), :int(ncol)] = arr
    sd.filled = max(sd.filled, int(start_row) + int(nrow))


def dataset_push_rows_by_csr2(sd, indptr_mv, indptr_type, indices_mv,
                              data_mv, data_type, nindptr, nelem,
                              start_row: int) -> None:
    """Typed LGBM_DatasetPushRowsByCSR (c_api.h:177)."""
    from scipy.sparse import csr_matrix
    indptr, indices, data = _typed_sparse_parts(
        indptr_mv, indptr_type, nindptr, indices_mv, data_mv, data_type,
        nelem)
    x = csr_matrix((data, indices, indptr),
                   shape=(int(nindptr) - 1, sd.buf.shape[1])).toarray()
    if sd.ds is not None:
        raise ValueError("dataset already finalized")
    sd.buf[int(start_row):int(start_row) + x.shape[0]] = x
    sd.filled = max(sd.filled, int(start_row) + x.shape[0])


def dataset_get_subset(ds, idx_mv, num: int, params: str):
    """LGBM_DatasetGetSubset (c_api.h:313)."""
    ds = _as_dataset(ds)
    ds.construct()
    idx = np.frombuffer(idx_mv, np.int32)[:int(num)].copy()
    return ds.subset(idx)


def dataset_add_features_from(target, source) -> None:
    """LGBM_DatasetAddFeaturesFrom (c_api.h:452): append source's
    feature columns to target (Dataset.add_features_from).  A C-API
    dataset handle is semantically always constructed (the reference's
    LGBM_DatasetCreateFromMat bins eagerly); only the PYTHON Dataset is
    lazy, so construct before delegating — the lazy-API strictness
    check is for python callers."""
    _as_dataset(target).construct()
    _as_dataset(source).construct()
    _as_dataset(target).add_features_from(_as_dataset(source))


def dataset_dump_text(ds, filename: str) -> None:
    """LGBM_DatasetDumpText (c_api.h:371): binned values, one row per
    line (the reference's debugging dump).  The header lists only the
    USED features — feature_binned() has no columns for trivial ones."""
    ds = _as_dataset(ds)
    ds.construct()
    binned = ds.feature_binned()
    names = ds.feature_names or [
        f"Column_{i}" for i in range(ds.num_total_features)]
    used_names = [names[f] for f in ds.used_features]
    with open(filename, "w") as f:
        f.write("\t".join(used_names) + "\n")
        for row in binned:
            f.write("\t".join(str(int(v)) for v in row) + "\n")


def dataset_update_param_checking(old_params: str, new_params: str) -> None:
    """LGBM_DatasetUpdateParamChecking (c_api.h:414): raise when a
    dataset-affecting parameter changed (config.cpp dataset param set).
    Compared on RESOLVED Config values (aliases applied, absent keys at
    their defaults) like the reference — an explicit value equal to the
    default is not a change."""
    from .config import Config
    dataset_keys = (
        "max_bin", "min_data_in_bin", "bin_construct_sample_cnt",
        "use_missing", "zero_as_missing", "categorical_feature",
        "feature_pre_filter", "enable_bundle", "data_random_seed",
        "is_enable_sparse", "header", "two_round", "label_column",
        "weight_column", "group_column", "ignore_column",
        "forcedbins_filename", "precise_float_parser",
        "max_conflict_rate", "linear_tree")
    o, n = Config(_params(old_params)), Config(_params(new_params))
    changed = [k for k in dataset_keys
               if getattr(o, k, None) != getattr(n, k, None)]
    if changed:
        raise ValueError(
            "cannot change dataset parameters after construction: "
            + ", ".join(changed))


def dataset_feature_num_bin(ds, feature: int) -> int:
    """LGBM_DatasetGetFeatureNumBin (c_api.h:442).  ``bin_mappers`` is
    indexed by TOTAL feature id (trivial features keep their single-bin
    mapper), not by used-feature slot."""
    ds = _as_dataset(ds)
    ds.construct()
    f = int(feature)
    if not 0 <= f < len(ds.bin_mappers):
        raise ValueError(f"feature index {f} out of range "
                         f"({len(ds.bin_mappers)} features)")
    return int(ds.bin_mappers[f].num_bin)


def booster_get_linear(bst: Booster) -> int:
    return 1 if getattr(bst.config, "linear_tree", False) else 0


def booster_get_leaf_value(bst: Booster, tree_idx: int,
                           leaf_idx: int) -> float:
    return float(bst.trees[int(tree_idx)].leaf_value[int(leaf_idx)])


def booster_set_leaf_value(bst: Booster, tree_idx: int, leaf_idx: int,
                           val: float) -> None:
    """LGBM_BoosterSetLeafValue (Tree::SetLeafOutput): updates the host
    tree and, when the booster is mid-training, its device copy (train/
    valid score caches are NOT retro-adjusted — same as the reference,
    which applies the new value from the next AddScore on)."""
    bst.trees[int(tree_idx)].leaf_value[int(leaf_idx)] = float(val)
    m = getattr(bst, "_model", None)
    if m is not None and int(tree_idx) < len(getattr(m, "device_trees", [])):
        import jax.numpy as jnp
        dt = m.device_trees[int(tree_idx)]
        lv = np.asarray(dt.leaf_value).copy()
        lv[int(leaf_idx)] = float(val)
        dt.leaf_value = jnp.asarray(lv, jnp.float32)


def booster_merge(bst: Booster, other: Booster) -> None:
    """LGBM_BoosterMerge (c_api.h:522): append other's models."""
    bst._merge_from(other)


def booster_shuffle_models(bst: Booster, start_iter: int,
                           end_iter: int) -> None:
    bst._shuffle_models(int(start_iter), int(end_iter))


def booster_num_predict(bst: Booster, data_idx: int) -> int:
    m = bst._model
    if int(data_idx) == 0:
        n = m.num_data
    else:
        i = int(data_idx) - 1
        if i >= len(m.valid_sets):
            raise ValueError(f"data_idx {data_idx} out of range")
        n = m.valid_sets[i][0].num_data
    return int(n * m.num_class)


def booster_get_predict(bst: Booster, data_idx: int, out_mv) -> int:
    """LGBM_BoosterGetPredict (c_api.h:728): transformed scores for the
    train (0) / valid (i>=1) data."""
    import jax.numpy as jnp
    m = bst._model
    if int(data_idx) == 0:
        score = m.train_score()
    else:
        score = m.valid_score(int(data_idx) - 1)
    score = np.asarray(score)
    if m.objective is not None:
        s = score[:, 0] if m.num_class == 1 else score
        score = np.asarray(m.objective.convert_output(jnp.asarray(s)))
        score = score.reshape(len(score), -1)
    flat = np.ascontiguousarray(score.astype(np.float64)).reshape(-1)
    out = np.frombuffer(out_mv, np.float64)
    if len(flat) > len(out):
        raise ValueError("output buffer too small")
    out[:len(flat)] = flat
    return int(len(flat))


def booster_reset_training_data(bst: Booster, ds) -> None:
    bst.reset_training_data(_as_dataset(ds))


def booster_refit_leaf_preds(bst: Booster, leaf_mv, nrow: int,
                             ncol: int) -> None:
    """LGBM_BoosterRefit (c_api.h:578): re-fit leaf values from given
    per-tree leaf assignments (GBDT::RefitTree, gbdt.cpp:287-323) using
    the booster's training data labels."""
    leaves = np.frombuffer(leaf_mv, np.int32)[:int(nrow) * int(ncol)] \
        .reshape(int(nrow), int(ncol)).copy()
    bst.refit_with_leaves(leaves)


def booster_upper_bound(bst: Booster) -> float:
    return bst._bounds()[1]


def booster_lower_bound(bst: Booster) -> float:
    return bst._bounds()[0]


def booster_predict_csc2(bst: Booster, colptr_mv, colptr_type, indices_mv,
                         data_mv, data_type, ncol_ptr, nelem, nrow,
                         predict_type: int, start_iteration: int,
                         num_iteration: int, out_mv) -> int:
    from scipy.sparse import csc_matrix
    colptr, indices, data = _typed_sparse_parts(
        colptr_mv, colptr_type, ncol_ptr, indices_mv, data_mv, data_type,
        nelem)
    x = csc_matrix((data, indices, colptr),
                   shape=(int(nrow), int(ncol_ptr) - 1)).tocsr()
    return _predict_out(bst, x, predict_type, start_iteration,
                        num_iteration, out_mv)


def booster_predict_sparse(bst: Booster, indptr_mv, indptr_type,
                           indices_mv, data_mv, data_type, nindptr, nelem,
                           num_col_or_row, predict_type: int,
                           start_iteration: int, num_iteration: int,
                           matrix_type: int):
    """LGBM_BoosterPredictSparseOutput (c_api.h:859): contrib
    predictions as sparse CSR (matrix_type 0) / CSC (1) triples.
    Returns (indptr int64 array, indices int32 array, data float64
    array) pinned on the booster until the next call."""
    from scipy.sparse import csr_matrix, csc_matrix
    indptr, indices, data = _typed_sparse_parts(
        indptr_mv, indptr_type, nindptr, indices_mv, data_mv, data_type,
        nelem)
    x = csr_matrix((data, indices, indptr),
                   shape=(int(nindptr) - 1, int(num_col_or_row)))
    dense = _predict_dispatch(bst, x, predict_type, start_iteration,
                              num_iteration)
    dense = dense.reshape(x.shape[0], -1)
    out = csc_matrix(dense) if int(matrix_type) == 1 else csr_matrix(dense)
    # output buffers TYPED to the caller's input types, like the
    # reference (c_api.cpp:504-507): int32/int64 indptr, f32/f64 data
    trip = (np.ascontiguousarray(out.indptr, _NP_OF[int(indptr_type)]),
            np.ascontiguousarray(out.indices, np.int32),
            np.ascontiguousarray(out.data, _NP_OF[int(data_type)]))
    bst._sparse_out = trip             # keep buffers alive for the caller
    return (int(trip[0].ctypes.data), int(trip[0].size),
            int(trip[1].ctypes.data),
            int(trip[2].ctypes.data), int(trip[2].size))


def booster_get_feature_names(bst: Booster) -> str:
    return "\t".join(bst.feature_names or [])
