"""split_batch (K-way super-step grower, grower.py grow_tree_batched).

The batched grower splits the top-K leaves per step and builds all K child
histograms in one C=3K one-hot contraction (PROFILE.md: the histogram
matmul is sublane-bound at M=3, so batching is the only way past that
ceiling).  K=1 keeps exact strict leaf-wise reference semantics; K>1 is a
best-first variant between LightGBM's leaf-wise and XGBoost's depth-wise
growth.  These tests pin: model validity, near-parity of quality, exact
fused==per-iteration equality, and serial==distributed agreement.
"""

import pytest

pytestmark = pytest.mark.slow   # exhaustive sweep tier (docs/Testing.md)


import numpy as np
from sklearn.metrics import roc_auc_score

import lightgbm_tpu as lgb


def _assert_same_model(bst_a, bst_b):
    assert len(bst_a.trees) == len(bst_b.trees)
    for ts, td in zip(bst_a.trees, bst_b.trees):
        np.testing.assert_array_equal(ts.split_feature, td.split_feature)
        np.testing.assert_array_equal(ts.left_child, td.left_child)
        np.testing.assert_allclose(ts.leaf_value, td.leaf_value,
                                   rtol=1e-4, atol=1e-6)


def _params(sb, **kw):
    p = {"objective": "binary", "num_leaves": 31, "min_data_in_leaf": 5,
         "learning_rate": 0.1, "verbose": -1, "split_batch": sb,
         "tpu_learner": "masked", "fused_chunk": 0}
    p.update(kw)
    return p


@pytest.fixture(scope="module")
def data():
    rs = np.random.RandomState(7)
    n, f = 4000, 20
    x = rs.randn(n, f)
    x[rs.rand(n, f) < 0.05] = np.nan
    logit = (np.nan_to_num(x[:, 0]) * 1.5 - np.nan_to_num(x[:, 1])
             + 0.5 * np.nan_to_num(x[:, 2] * x[:, 3]) + 0.3 * rs.randn(n))
    y = (logit > 0).astype(np.float32)
    return x, y


def _train(x, y, params, rounds=20, max_bin=63):
    ds = lgb.Dataset(x, label=y, params={"max_bin": max_bin})
    return lgb.train(params, ds, num_boost_round=rounds)


def test_batched_auc_near_strict(data):
    """K>1 changes growth order, not model quality."""
    x, y = data
    auc = {}
    for sb in (1, 4, 8):
        bst = _train(x, y, _params(sb))
        auc[sb] = roc_auc_score(y, bst.predict(x))
    assert auc[4] > auc[1] - 0.01
    assert auc[8] > auc[1] - 0.02


def test_batched_model_roundtrip(data):
    x, y = data
    bst = _train(x, y, _params(4))
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(bst2.predict(x), bst.predict(x), rtol=1e-6)


def test_batched_fused_equals_per_iter(data):
    """The fused lax.scan chunk path must be bit-identical to the
    per-iteration path under batching (same RNG/semantics)."""
    x, y = data
    b_it = _train(x, y, _params(4))
    b_fu = _train(x, y, _params(4, fused_chunk=10))
    np.testing.assert_array_equal(b_it.predict(x), b_fu.predict(x))


def test_batched_exhausts_splits_like_strict(data):
    """Batched growth must still stop cleanly and FILL up to num_leaves
    when gains allow: the super-step count accounts for the exponential
    ramp-up (step s can split at most min(K, leaves) leaves), so K=8
    cannot silently cap a 15-leaf tree at 2 steps = 3 nodes."""
    x, y = data
    bst = _train(x, y, _params(8, num_leaves=15, min_data_in_leaf=2))
    assert max(t.num_leaves for t in bst.trees) == 15
    for t in bst.trees:
        assert t.num_leaves <= 15
        # children pointers well-formed: every internal node referenced once
        lc, rc = np.asarray(t.left_child), np.asarray(t.right_child)
        nn = t.num_leaves - 1
        refs = [c for c in list(lc[:nn]) + list(rc[:nn]) if c >= 0]
        assert sorted(refs) == list(range(1, nn))


def test_reset_parameter_invalidates_fused_chunk(data):
    """reset_parameter must retrace the fused chunk program — the old
    jitted closure has the previous learning rate baked in."""
    x, y = data
    ds = lgb.Dataset(x, label=y, params={"max_bin": 63})
    bst = lgb.train(_params(4, fused_chunk=5), ds, num_boost_round=5)
    bst.reset_parameter({"learning_rate": 0.77})
    bst.update_chunk(5)          # must NOT reuse the lr=0.1 jitted chunk
    shr = {t.shrinkage for t in bst.trees}
    assert 0.77 in shr and 0.1 in shr
    # device score must agree with the host trees' raw predictions
    raw = bst.predict(x, raw_score=True)
    dev = np.asarray(bst._model.train_score())[:, 0]
    np.testing.assert_allclose(raw, dev, rtol=1e-4, atol=1e-5)


def test_batched_feature_fraction_and_goss(data):
    x, y = data
    bst = _train(x, y, _params(4, feature_fraction=0.7,
                               data_sample_strategy="goss",
                               top_rate=0.3, other_rate=0.2))
    assert roc_auc_score(y, bst.predict(x)) > 0.85


def test_batched_efb(data):
    """EFB bundled layout under the batched grower (bundle-column decode in
    the one-pass partition)."""
    x, y = data
    rs = np.random.RandomState(3)
    # append sparse mutually-exclusive features so EFB actually bundles
    extra = np.zeros((x.shape[0], 6))
    for j in range(6):
        rows = rs.choice(x.shape[0], 200, replace=False)
        extra[rows, j] = rs.randn(200)
    xw = np.column_stack([np.nan_to_num(x), extra])
    b1 = _train(xw, y, _params(1, enable_bundle=True))
    b4 = _train(xw, y, _params(4, enable_bundle=True))
    assert roc_auc_score(y, b4.predict(xw)) > \
        roc_auc_score(y, b1.predict(xw)) - 0.02


def test_batched_categorical(data):
    x, y = data
    rs = np.random.RandomState(5)
    xc = np.nan_to_num(x).copy()
    cat = rs.randint(0, 8, x.shape[0]).astype(float)
    y2 = ((cat >= 4) ^ (np.nan_to_num(x[:, 0]) > 0)).astype(np.float32)
    xc[:, 5] = cat
    ds = lgb.Dataset(xc, label=y2, params={"max_bin": 63},
                     categorical_feature=[5])
    bst = lgb.train(_params(4, min_data_per_group=5), ds, num_boost_round=20)
    assert roc_auc_score(y2, bst.predict(xc)) > 0.9


@pytest.mark.skipif(
    __import__("jax").device_count() < 8,
    reason="needs the 8-device virtual mesh")
class TestDistributedBatched:
    def test_data_parallel_matches_serial(self, data):
        x, y = data
        b_s = _train(x, y, _params(2, num_leaves=15), rounds=8)
        p = _params(2, num_leaves=15)
        p.pop("tpu_learner")
        p["tree_learner"] = "data"
        b_d = _train(x, y, p, rounds=8)
        assert b_d._model._dist == "data"
        _assert_same_model(b_s, b_d)
        np.testing.assert_allclose(b_s.predict(x), b_d.predict(x),
                                   rtol=1e-4, atol=1e-6)

    def test_feature_parallel_matches_serial(self, data):
        x, y = data
        b_s = _train(x, y, _params(2, num_leaves=15), rounds=8)
        p = _params(2, num_leaves=15)
        p.pop("tpu_learner")
        p["tree_learner"] = "feature"
        b_f = _train(x, y, p, rounds=8)
        assert b_f._model._dist == "feature"
        _assert_same_model(b_s, b_f)

    def test_auto_split_batch_above_64_leaves(self, data):
        x, y = data
        bst = _train(x, y, _params(0, num_leaves=64,
                                   min_data_in_leaf=2), rounds=3)
        assert bst._model._split_batch == 8
        bst2 = _train(x, y, _params(0, num_leaves=31), rounds=3)
        assert bst2._model._split_batch == 1
