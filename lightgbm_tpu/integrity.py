"""Computation integrity: silent-data-corruption (SDC) detection,
last-good rewind, and suspect-device quarantine.

Every other robustness layer (elastic recovery, survivable ingest,
hardened serving) defends against failures that announce themselves —
hangs, crashes, torn files.  This layer defends against the marginal
chip that keeps running but computes wrong NUMBERS: one flipped bit in
a histogram silently diverges the forest and poisons every downstream
snapshot, fleet member, and served prediction.  Two existing contracts
make that detectable and recoverable:

- the quantized int32 histogram/reduce path is bitwise deterministic
  (dp == serial, docs/Determinism.md), so a redundant recompute is an
  EXACT oracle on the quant path and an ulp-bounded one on f32;
- snapshots are byte-identical kill+resume points, so the newest
  integrity-verified snapshot is a sound rewind target.

Mechanics (wired in ``models/gbdt.GBDTModel.train_one_iter`` and
``engine.train``; docs/Fault-Tolerance.md layer 7):

**Detection.**  Every ``integrity_check_freq`` iterations (and at every
snapshot boundary) the iteration's grow — histogram contraction + split
scan — is re-executed through an INDEPENDENTLY-jitted shadow program:
``jax.jit`` over the unjitted grower builds a second trace of the same
logical math, so a wrong answer must reproduce across two distinct
compiled programs to evade the compare (bitwise on int32/bool fields,
``integrity_ulp_tol``-bounded on f32).  Additionally, cheap in-graph
invariants ride the existing consolidated ``_eget`` fetch EVERY
iteration as one traced boolean — parent/child count conservation down
the tree (the subtraction trick makes it exact), leaf-total == root
count, split-gain finiteness — so steady state gains ZERO extra host
syncs.  The row->leaf partition itself stays on device (fetching [N]
ints would defeat the consolidated-fetch design); corruption there
surfaces through the score-path check (``verify_score``) instead.

**Transient vs sticky.**  A mismatch is re-run ONCE (fresh primary +
fresh shadow).  A clean re-run is a transient — absorbed: the re-run's
arrays become the iteration's result, so the final model is
byte-identical to an uninjected run.  A second mismatch is sticky:
blackbox-dump the divergent fields, attribute suspect devices, record
an ``elastic.*`` failure event, and raise :class:`IntegrityFailure`
(``ElasticFailure`` kind ``"sdc"``).

**Recovery.**  Policy ``rewind``: ``engine.train`` catches the failure
and re-enters itself with ``resume=True`` — snapshot manifests carry an
``integrity`` stamp, and ``snapshot.find_latest_snapshot`` prefers the
newest VERIFIED snapshot over a newer unverified one — up to
:data:`MAX_REWINDS` times.  Policy ``quarantine``: additionally mark
the suspect devices (``parallel/elastic.mark_suspect``) so the elastic
ladder's next rung runs mesh-minus-suspects instead of halving, and
``GBDTModel._resolve_mesh`` excludes them from the claim.

Fault injection: sites ``hist_sdc`` / ``score_sdc`` with the
``bitflip`` action (``utils/faultinject.maybe_bitflip``) are the chaos
substrate; ``tools/soak_train.py --chaos sdc`` drives the full
transient + sticky + rewind + quarantine ladder in one run.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .obs.metrics import MetricsRegistry
from .parallel.elastic import ElasticFailure, _on_failure, mark_suspect
from .utils.log import Log

# sticky-SDC rewind budget per training entry: past this, engine.train
# stops re-entering and re-raises (a chip that corrupts three rewinds
# in a row is not transient — quarantine or die loudly)
MAX_REWINDS = 3

# integrity.* metrics: host-side counter bumps on check/mismatch paths
# only — nothing per-iteration in steady state (same always-on contract
# as the elastic.* registry)
_REGISTRY = MetricsRegistry()
_REGISTRY_LOCK = threading.Lock()


def metrics_snapshot() -> dict:
    """Deterministic dict snapshot of the ``integrity.*`` metrics."""
    return _REGISTRY.snapshot()


def reset_metrics() -> None:
    """Test hook: drop all ``integrity.*`` metric state."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        _REGISTRY = MetricsRegistry()


def _metrics() -> MetricsRegistry:
    with _REGISTRY_LOCK:
        return _REGISTRY


class IntegrityFailure(ElasticFailure):
    """A STICKY computation-integrity mismatch (survived the one
    re-check), classified as ``ElasticFailure`` kind ``"sdc"`` so the
    recovery ladder and ``failure_kind`` treat it like any other
    classified failure.  Carries the 1-based iteration it fired on,
    the attributed suspect device ids, and the divergent-field summary
    (also blackbox-dumped)."""

    def __init__(self, detail: str = "", iteration: Optional[int] = None,
                 devices: Tuple[int, ...] = (),
                 divergences: Tuple[Dict[str, Any], ...] = ()):
        self.iteration = iteration
        self.devices = tuple(devices)
        self.divergences = tuple(divergences)
        super().__init__("sdc", detail)


# ---------------------------------------------------------------------------
# Comparison primitives (host-side numpy; operands come off the one fetch)
# ---------------------------------------------------------------------------

def _float_ord(x: np.ndarray) -> np.ndarray:
    """Monotone-within-sign int64 key of f32 bit patterns: the distance
    between two same-sign keys is their ulp distance.  Cross-sign pairs
    map far apart, which is the right answer for a compare (a sign flip
    IS a divergence; numerically-equal ±0.0 is short-circuited by the
    equality check before this runs)."""
    i = np.ascontiguousarray(x, np.float32).view(np.int32).astype(np.int64)
    return np.where(i >= 0, i, (np.int64(1) << 31) - 1 - i)


def ulp_delta(a, b) -> np.ndarray:
    """Elementwise ulp distance between two f32 arrays (0 where equal,
    including NaN==NaN and -0.0==+0.0)."""
    av = np.asarray(a, np.float32)
    bv = np.asarray(b, np.float32)
    same = (av == bv) | (np.isnan(av) & np.isnan(bv))
    d = np.abs(_float_ord(av) - _float_ord(bv))
    return np.where(same, 0, d)


def compare_tree_arrays(a, b, ulp_tol: int = 0) -> List[Dict[str, Any]]:
    """Field-by-field compare of two host ``TreeArrays``: bitwise on
    int/bool fields, ``ulp_tol``-bounded on floats.  Returns one record
    per divergent field — ``{"field", "count", "index", "got",
    "want", "ulp"}`` with the first divergent element as the sample —
    empty list == match.  ``leaf_of_row`` is skipped: the consolidated
    fetch replaces it with a scalar placeholder (and the [N] partition
    deliberately never leaves the device)."""
    out: List[Dict[str, Any]] = []
    for name, av, bv in zip(type(a)._fields, a, b):
        if name == "leaf_of_row":
            continue
        av = np.asarray(av)
        bv = np.asarray(bv)
        if av.shape != bv.shape:
            out.append({"field": name, "count": -1,
                        "got": list(av.shape), "want": list(bv.shape),
                        "index": -1, "ulp": -1})
            continue
        if np.issubdtype(av.dtype, np.floating):
            d = ulp_delta(av, bv)
            bad = d > ulp_tol
        else:
            bad = np.asarray(av != bv)
            d = bad.astype(np.int64)
        if not bad.any():
            continue
        idx = int(np.argmax(bad.ravel()))
        out.append({
            "field": name,
            "count": int(bad.sum()),
            "index": idx,
            "got": float(np.ravel(av)[idx]) if av.ndim else float(av),
            "want": float(np.ravel(bv)[idx]) if bv.ndim else float(bv),
            "ulp": int(np.ravel(d)[idx]),
        })
    return out


def invariant_flags(arrays):
    """ONE traced boolean: cheap in-graph invariants of a freshly grown
    tree, evaluated on device and fetched as part of the existing
    consolidated ``_eget`` — zero extra host syncs.

    - **count conservation** (subtraction trick): every live internal
      node's count equals the sum of its children's counts;
    - **total conservation**: the live leaf counts sum to the root's
      count (for an unweighted, unbagged run the root count is the row
      count; under bagging/GOSS it is the weight total, which the same
      identity still pins);
    - **gain finiteness** over live internal nodes.

    Counts are f32 weight sums, so conservation uses a relative slack
    of 1e-3 (+0.5 absolute) — loose enough never to false-positive on
    rounding, tight enough that any injected bit flip above the bottom
    few mantissa bits trips it.
    """
    import jax.numpy as jnp
    lc = arrays.leaf_count
    ic = arrays.internal_count
    nl = arrays.num_leaves
    L = lc.shape[0]
    nnode = ic.shape[0]
    leaf_live = jnp.arange(L, dtype=jnp.int32) < nl
    node_live = jnp.arange(nnode, dtype=jnp.int32) < (nl - 1)

    def _child_count(c):
        is_leaf = c < 0
        li = jnp.where(is_leaf, ~c, 0)
        ni = jnp.where(is_leaf, 0, c)
        return jnp.where(is_leaf, jnp.take(lc, li, mode="clip"),
                         jnp.take(ic, ni, mode="clip"))

    kid = _child_count(arrays.left_child) + _child_count(arrays.right_child)
    slack = 0.5 + 1e-3 * jnp.abs(ic)
    conserve_ok = jnp.where(node_live,
                            jnp.abs(ic - kid) <= slack, True).all()
    tot = jnp.sum(jnp.where(leaf_live, lc, 0.0))
    root = jnp.where(nl > 1, ic[0], lc[0])
    total_ok = jnp.abs(tot - root) <= (0.5 + 1e-3 * jnp.abs(root))
    gain_ok = jnp.isfinite(
        jnp.where(node_live, arrays.split_gain, 0.0)).all()
    return conserve_ok & total_ok & gain_ok


def attribute_devices(x) -> List[int]:
    """Coarse suspect attribution from a divergent array's placement.
    A single-device (serial-rung) result names that chip exactly; a
    replicated/sharded result cannot localize WHICH participant flipped
    the bit, so the highest device id is picked deterministically — a
    documented heuristic that keeps quarantine monotone (repeat sticky
    failures walk the mesh down one suspect at a time) rather than
    precise."""
    try:
        ids = sorted(int(d.id) for d in x.devices())
    except Exception:   # noqa: BLE001 — host array / deleted buffer
        return []
    if not ids:
        return []
    return [ids[-1]] if len(ids) > 1 else ids


class IntegrityChecker:
    """Per-model driver of the integrity layer (``GBDTModel._integrity``
    — constructed only when ``integrity_check_freq > 0``).  Owned by the
    one training thread; no locking.

    ``shadow_fn`` is the independently-jitted twin of the model's
    grower (``grower.make_shadow_grower``); for redundancy-only
    learners (dp/voting/feature, whose growers are built per-topology)
    it may be the primary grower itself — still a full recompute, just
    not a second trace — flagged by ``independent=False`` and recorded
    in the manifest."""

    def __init__(self, config, shadow_fn: Callable, independent: bool):
        self.freq = int(config.integrity_check_freq)
        self.policy = str(config.integrity_policy)
        self.ulp_tol = int(config.integrity_ulp_tol)
        self.shadow_fn = shadow_fn
        self.independent = bool(independent)
        self.checks = 0
        self.transients = 0
        # newest 1-based iteration whose grow passed a shadow compare
        self.verified_iteration = 0
        # retained state for the snapshot-boundary check:
        # (it_global, host_small, run_shadow_closure)
        self._pending: Optional[Tuple[int, Any, Callable]] = None
        self._take = None     # lazily-jitted independent score gather

    def should_check(self, it_global: int) -> bool:
        """Whether iteration ``it_global`` (0-based) is a shadow-compare
        iteration."""
        return self.freq > 0 and (it_global + 1) % self.freq == 0

    # -- grow-path verification ------------------------------------------

    def verify_grow(self, model, it_global: int, grow: Callable,
                    run_shadow: Callable, arrays, host_small,
                    inv_ok: bool, shadow_host):
        """Called right after the consolidated fetch with the traced
        invariant flag and (on check iterations) the fetched shadow
        tree.  Returns the ``(arrays, host_small)`` to commit — the
        originals on a clean check, the re-run's on an absorbed
        transient.  Raises :class:`IntegrityFailure` on sticky."""
        div: List[Dict[str, Any]] = []
        if shadow_host is not None:
            self.checks += 1
            _metrics().counter("integrity.checks", path="grow").inc()
            div = compare_tree_arrays(host_small, shadow_host, self.ulp_tol)
        if inv_ok and not div:
            if shadow_host is not None:
                self.verified_iteration = it_global + 1
            self._pending = (it_global, host_small, run_shadow)
            return arrays, host_small
        self._mismatch(model, it_global, inv_ok, div)
        # re-check once, fresh primary + fresh shadow (the injection
        # counters advance, so a single-hit transient is clean here)
        a2 = grow()
        inv2 = invariant_flags(a2)
        s2 = run_shadow(self.shadow_fn)
        small2 = a2._replace(leaf_of_row=a2.num_leaves)
        h2, inv2_ok, sh2 = model._eget(
            (small2, inv2, s2._replace(leaf_of_row=s2.num_leaves)),
            "integrity_recheck")
        div2 = compare_tree_arrays(h2, sh2, self.ulp_tol)
        if bool(inv2_ok) and not div2:
            self._absorb(it_global)
            self._pending = (it_global, h2, run_shadow)
            return a2, h2
        self._sticky(model, it_global, div2 or div, a2.num_leaves)

    def _mismatch(self, model, it_global: int, inv_ok: bool,
                  div: List[Dict[str, Any]]) -> None:
        _metrics().counter("integrity.mismatches", path="grow").inc()
        Log.warning(
            f"integrity: mismatch at iteration {it_global + 1} "
            f"(invariants {'ok' if inv_ok else 'TRIPPED'}, "
            f"{len(div)} divergent field(s): "
            f"{[d['field'] for d in div]}); re-checking once")
        bbox = getattr(model, "_bbox", None)
        if bbox is not None:
            bbox.record(event="integrity_mismatch",
                        iteration=it_global + 1,
                        invariants_ok=bool(inv_ok),
                        divergences=div[:8])
            bbox.dump("integrity_mismatch")

    def _absorb(self, it_global: int) -> None:
        self.transients += 1
        _metrics().counter("integrity.transient_absorbed").inc()
        self.verified_iteration = it_global + 1
        Log.warning(
            f"integrity: iteration {it_global + 1} re-check clean — "
            "transient SDC absorbed (re-run result committed)")

    def _sticky(self, model, it_global: int, div: List[Dict[str, Any]],
                placed) -> None:
        """Terminal: record, attribute, (maybe) quarantine, raise."""
        _metrics().counter("integrity.sticky").inc()
        ids = attribute_devices(placed)
        bbox = getattr(model, "_bbox", None)
        if bbox is not None:
            bbox.record(event="integrity_sticky",
                        iteration=it_global + 1,
                        devices=ids, divergences=div[:8])
        fail = IntegrityFailure(
            detail=f"sticky SDC at iteration {it_global + 1}: "
                   f"{len(div)} divergent field(s) "
                   f"{[d['field'] for d in div][:4]}, "
                   f"suspect devices {ids}",
            iteration=it_global + 1, devices=tuple(ids),
            divergences=tuple(div[:8]))
        _on_failure(fail, site="integrity")
        if self.policy == "quarantine" and ids:
            mark_suspect(ids)
            _metrics().counter("integrity.quarantined").inc()
            Log.warning(f"integrity: quarantined device(s) {ids}")
        raise fail

    # -- score-path verification -----------------------------------------

    def verify_score(self, model, lv_dev, leaf_of_row, delta,
                     it_global: int):
        """Shadow-verify the score-update gather on check iterations:
        recompute ``take(leaf_values, leaf_of_row)`` through an
        independently-jitted gather and compare ON DEVICE — the fetch
        is one scalar, and only on check iterations (steady state stays
        sync-free).  Same transient/sticky ladder as the grow path."""
        import jax
        import jax.numpy as jnp
        if self._take is None:
            self._take = jax.jit(lambda lv, r: jnp.take(lv, r))
        self.checks += 1
        _metrics().counter("integrity.checks", path="score").inc()
        bad = model._eget(jnp.any(self._take(lv_dev, leaf_of_row)
                                  != delta), "integrity_score")
        if not bool(bad):
            return delta
        _metrics().counter("integrity.mismatches", path="score").inc()
        Log.warning(
            f"integrity: score-update mismatch at iteration "
            f"{it_global + 1}; re-checking once")
        from .utils import faultinject
        d2 = jnp.take(lv_dev, leaf_of_row)
        if faultinject.enabled():
            d2 = faultinject.maybe_bitflip("score_sdc", d2)
        bad2 = model._eget(jnp.any(self._take(lv_dev, leaf_of_row)
                                   != d2), "integrity_recheck")
        if not bool(bad2):
            self.transients += 1
            _metrics().counter("integrity.transient_absorbed").inc()
            Log.warning(
                f"integrity: score re-check at iteration "
                f"{it_global + 1} clean — transient SDC absorbed")
            return d2
        self._sticky(model, it_global,
                     [{"field": "score_delta", "count": -1, "index": -1,
                       "got": 0.0, "want": 0.0, "ulp": -1}], delta)

    # -- snapshot-boundary check + manifest stamp ------------------------

    def boundary_check(self, model) -> None:
        """Shadow-verify the newest committed grow right before a
        snapshot is written, so the manifest's ``integrity`` stamp
        means 'last check clean AT this snapshot'.  Re-runs ONLY the
        shadow against the retained fetched primary — it consumes no
        injection hits, and a boundary that lands on a just-checked
        iteration is free.  A mismatch here is sticky by construction
        (the primary's tree is already committed): one shadow re-run
        separates a shadow-side transient, then :class:`IntegrityFailure`.
        """
        if self._pending is None:
            return
        it_g, host_small, run_shadow = self._pending
        if self.verified_iteration >= it_g + 1:
            return
        self.checks += 1
        _metrics().counter("integrity.checks", path="boundary").inc()
        for attempt in range(2):
            s = run_shadow(self.shadow_fn)
            sh = model._eget(s._replace(leaf_of_row=s.num_leaves),
                             "integrity_boundary")
            div = compare_tree_arrays(host_small, sh, self.ulp_tol)
            if not div:
                self.verified_iteration = it_g + 1
                return
            if attempt == 0:
                self._mismatch(model, it_g, True, div)
        self._sticky(model, it_g, div, host_small.num_leaves)

    def manifest(self, iteration: int) -> Dict[str, Any]:
        """The snapshot manifest's ``integrity`` stamp.  ``verified``
        means the snapshot's newest tree passed a shadow compare (the
        boundary check runs first, so this is normally True; False
        survives only if the boundary check could not run, e.g. no
        retained state after resume)."""
        return {
            "verified": bool(self.verified_iteration >= int(iteration)),
            "checked_iteration": int(self.verified_iteration),
            "checks": int(self.checks),
            "transients": int(self.transients),
            "check_freq": int(self.freq),
            "independent_trace": bool(self.independent),
        }
