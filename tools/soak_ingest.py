"""Chaos-injection soak harness for the STREAMING INGEST pipeline
(the ``tools/soak_train.py`` analog for the data path).

Runs one streaming ingest + training job (``lightgbm_tpu/ingest.py``)
while ``utils/faultinject`` injects all three failure kinds the
pipeline promises to survive (docs/Fault-Tolerance.md "Out-of-core
ingest", docs/Ingest.md "Failure taxonomy"):

- **Transient read errors** (``ingest_read``): must be retried with
  backoff and succeed — zero dropped rows, retry metrics present.
- **Corrupt chunks** (``ingest_checksum``): must be quarantined with a
  blackbox dump and an exact dropped-row accounting under
  ``ingest_bad_chunk=skip``; the degraded run still trains.
- **Reader hangs** (``ingest_hang``): the per-chunk deadline
  (``ingest_read_timeout_s``) must abandon the wedge and classify it —
  the soak only finishes inside its wall budget if no hang ever ran to
  its full sleep.

Plus **resume parity**: a second ingest over the same spool must resume
every committed chunk and train a model byte-identical to the chaos
run's (the chaos run's spool IS the checkpoint).

Run standalone (prints one JSON report, exit 1 on violations)::

    python tools/soak_ingest.py rows=4000 chunk_rows=250

Importable: ``run_soak_ingest(...)`` returns the report dict —
``tests/test_ingest_soak.py`` runs a short deterministic soak in
tier-1.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, Optional

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

N_FEAT = 5


def _write_csv(path: str, n_rows: int, seed: int = 0) -> None:
    rs = np.random.RandomState(seed)
    x = np.round(rs.randn(n_rows, N_FEAT), 1)
    y = (x[:, 0] + 0.25 * rs.randn(n_rows) > 0).astype(np.float64)
    with open(path, "w", encoding="utf-8") as f:
        for i in range(n_rows):
            f.write(",".join([f"{y[i]:g}"]
                             + [f"{v:.1f}" for v in x[i]]) + "\n")


def run_soak_ingest(n_rows: int = 4000, chunk_rows: int = 250,
                    rounds: int = 6, seed: int = 0, chaos: bool = True,
                    chaos_spec: Optional[str] = None,
                    hang_s: float = 6.0,
                    read_timeout_s: float = 0.5,
                    budget_s: float = 120.0,
                    workdir: Optional[str] = None,
                    params: Optional[Dict] = None) -> Dict:
    """One ingest soak; returns the report dict (module docstring).
    ``chaos=False`` is the control arm: same config, no faults — must
    complete with zero retries, zero quarantines, zero drops."""
    import tempfile

    import lightgbm_tpu as lgb
    from lightgbm_tpu import ingest as ing
    from lightgbm_tpu.utils import faultinject

    workdir = workdir or tempfile.mkdtemp(prefix="lgbm_soak_ingest_")
    os.makedirs(workdir, exist_ok=True)
    src = os.path.join(workdir, "train.csv")
    _write_csv(src, n_rows, seed)
    spool = os.path.join(workdir, "spool")
    n_chunks = (n_rows + chunk_rows - 1) // chunk_rows

    p = {"objective": "binary", "num_leaves": 8, "max_bin": 31,
         "min_data_in_leaf": 5, "verbosity": -1,
         "ingest_chunk_rows": int(chunk_rows),
         "ingest_bad_chunk": "skip",
         "ingest_retries": 2, "ingest_retry_backoff_s": 0.05,
         "ingest_read_timeout_s": float(read_timeout_s),
         "telemetry_blackbox": True}
    p.update(params or {})

    # mid-run chaos: chunk 2 hits a transient read error (retried),
    # chunk 4 is corrupt (quarantined), chunk 6's reader wedges once
    # (deadline abandons it, retry succeeds)
    spec = chaos_spec or ("ingest_read:2,ingest_checksum:4,"
                          "ingest_hang:6" if chaos else None)
    prev_hang = os.environ.get(faultinject.HANG_ENV_VAR)
    os.environ[faultinject.HANG_ENV_VAR] = str(hang_s)
    ing.reset_metrics()
    violations = []
    t0 = time.monotonic()
    try:
        faultinject.configure(spec)
        ds = lgb.ingest_dataset(src, dict(p), spool_dir=spool)
        bst = lgb.train(dict(p), ds, num_boost_round=rounds)
    finally:
        faultinject.clear()
        if prev_hang is None:
            os.environ.pop(faultinject.HANG_ENV_VAR, None)
        else:
            os.environ[faultinject.HANG_ENV_VAR] = prev_hang
    wall_s = time.monotonic() - t0
    report = dict(ds.ingest_report)
    metrics = ing.metrics_snapshot()
    if bst.num_trees() < rounds:
        violations.append(
            f"degraded run under-trained: {bst.num_trees()} < {rounds}")

    # -- invariants --------------------------------------------------------
    if wall_s > budget_s:
        violations.append(
            f"soak exceeded its wall budget ({wall_s:.1f}s > "
            f"{budget_s}s): a hang was NOT bounded by the deadline")
    if chaos:
        # hang must classify via the deadline, not run its full sleep:
        # generous margin, but far below hang_s stacking onto the run
        if wall_s > hang_s:
            violations.append(
                f"wall {wall_s:.1f}s exceeds the injected hang "
                f"({hang_s}s): the read deadline never fired")
        if metrics.get("ingest.retries", {}).get("value", 0) < 2:
            violations.append(
                "expected >=2 retries (transient read error + abandoned "
                f"hang), metrics say {metrics.get('ingest.retries')}")
        if len(report["quarantined"]) != 1:
            violations.append(
                f"expected exactly 1 quarantined chunk, got "
                f"{len(report['quarantined'])}")
        if report["dropped_rows"] != chunk_rows:
            violations.append(
                f"dropped-row accounting wrong: {report['dropped_rows']}"
                f" != {chunk_rows} (one quarantined chunk)")
        if report["num_rows"] != n_rows - chunk_rows:
            violations.append(
                f"surviving rows {report['num_rows']} != "
                f"{n_rows - chunk_rows}")
        qdir = os.path.join(spool, "quarantine")
        if not (os.path.isdir(qdir) and os.listdir(qdir)):
            violations.append("quarantine directory missing/empty")
    else:
        if report["dropped_rows"] or report["quarantined"]:
            violations.append("control run dropped/quarantined chunks")
        if metrics.get("ingest.retries", {}).get("value", 0):
            violations.append("control run recorded retries")

    # -- resume parity: the chaos spool is the checkpoint ------------------
    # a quarantined chunk commits no manifest, so the resume run re-reads
    # it fault-free and HEALS — the resumed model must therefore match a
    # clean fresh-spool run over the full data, byte for byte
    ing.reset_metrics()
    ds2 = lgb.ingest_dataset(src, dict(p), spool_dir=spool)
    if ds2.ingest_report["resumed_chunks"] != \
            n_chunks - len(report["quarantined"]):
        violations.append(
            f"resume replayed chunks: {ds2.ingest_report['resumed_chunks']}"
            f" resumed of {n_chunks - len(report['quarantined'])} "
            "committed")
    if ds2.ingest_report["dropped_rows"] or \
            ds2.ingest_report["num_rows"] != n_rows:
        violations.append(
            "resume run did not heal the quarantined chunk: "
            f"{ds2.ingest_report['num_rows']} rows, "
            f"{ds2.ingest_report['dropped_rows']} dropped")
    bst2 = lgb.train(dict(p), ds2, num_boost_round=rounds)
    ds3 = lgb.ingest_dataset(src, dict(p),
                             spool_dir=os.path.join(workdir, "spool_clean"))
    bst3 = lgb.train(dict(p), ds3, num_boost_round=rounds)
    if bst2.model_to_string().split("parameters:")[0] != \
            bst3.model_to_string().split("parameters:")[0]:
        violations.append(
            "resume parity failed: resumed-spool model differs from a "
            "clean fresh-spool run")

    return {"violations": violations, "wall_s": round(wall_s, 2),
            "n_chunks": n_chunks, "report": report,
            "resumed_chunks": ds2.ingest_report["resumed_chunks"],
            "ingest_metrics": {k: v.get("value")
                               for k, v in metrics.items()
                               if v.get("type") != "histogram"},
            "workdir": workdir}


def main(argv) -> int:
    kv = dict(a.split("=", 1) for a in argv if "=" in a)
    # force CPU the supported way (the axon sitecustomize freezes
    # jax_platforms at interpreter start; same pattern as soak_train.py)
    import jax
    jax.config.update("jax_platforms", "cpu")
    rep = run_soak_ingest(
        n_rows=int(kv.get("rows", 4000)),
        chunk_rows=int(kv.get("chunk_rows", 250)),
        rounds=int(kv.get("rounds", 6)),
        chaos=kv.get("chaos", "1") not in ("0", "false"),
        hang_s=float(kv.get("hang_s", 6.0)),
        budget_s=float(kv.get("budget_s", 120.0)))
    print(json.dumps(rep, indent=1, sort_keys=True))
    return 1 if rep["violations"] else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
