"""Continual boosting pipeline: train -> publish -> serve as ONE loop.

ROADMAP item 6 closed: byte-identical resume (snapshot.py), ``init_model``
continuation (engine.py), SHA-verified artifacts + engine self-check and
hot-swap serving (serve/) all exist — this module connects them into a
production continual-training system with freshness guarantees:

- :class:`ContinualTrainer` runs GENERATIONS.  Each generation (a)
  appends a new data chunk, (b) boosts ``continual_rounds`` more
  iterations from the newest COMPLETE snapshot through the existing
  ``engine.train`` init_model path (``continual_decay`` optionally
  shrinks the carried-over trees' contributions), (c) publishes a
  SHA-pinned snapshot artifact atomically (manifest written last — the
  completeness marker crash-safe training already relies on), and (d)
  promotes it into the serving :class:`~..serve.registry.ModelRegistry`
  only after the TWO-STAGE gate below.
- The gate (:func:`gated_promote`): stage 1 is the SHA-verified shadow
  load — manifest checksum enforced end to end plus the engine's
  byte-parity ``self_check``, whose FAILURE here is a gate refusal (plain
  serving merely demotes to the host walk; a continual promotion never
  ships an unproven engine).  Stage 2 is the SHADOW-TRAFFIC PARITY
  PROBE: the last K live serve batches replay through the candidate in a
  background thread; it must score within an objective-aware tolerance
  of the incumbent (``shadow_probe_tolerance`` — probabilities compare
  absolutely, unbounded outputs relative to the incumbent's scale) and
  must not regress the eval metric on the newest chunk by more than
  ``shadow_probe_metric_tolerance``.  Only then does the registry
  pointer swap — the PV-Tree discipline (arXiv:1611.01276) applied to
  model promotion: an explicit vote, never optimism.
- On ANY gate failure, probe timeout (``continual_timeout_s``) or
  in-process crash the generation ROLLS BACK automatically: the
  incumbent keeps serving (the registry was never activated), the
  candidate artifact is QUARANTINED (moved under
  ``continual_quarantine_dir`` with a blackbox reason dump, manifest
  first so a crash mid-quarantine can never leave it looking complete)
  and ``continual.rollbacks`` counts it.  A process death mid-generation
  is handled by the publish discipline instead: restart boosts from the
  newest complete snapshot and converges byte-identically with the
  uninterrupted run (tests/test_zcontinual.py kill matrix).

Every stage runs under ``utils/resilience.RetryPolicy`` with its own
fault-injection site (``continual_append`` / ``continual_boost`` /
``continual_publish`` / ``continual_promote`` / ``shadow_probe``) and
emits ``continual.*`` metrics (freshness lag seconds, generations
published / rolled back, gate latency) plus spans.  Drivable via
``cli task=continual`` and the serve server's ``POST /promote`` +
``GET /freshness`` surface; chaos-proven by
``tools/soak_serve.py --continual``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..config import Config
from ..utils import faultinject
from ..utils.log import Log
from ..utils.resilience import (RetryPolicy, atomic_write,
                                is_retryable_device_error, retry_call)

# probability-valued objective outputs: the parity probe compares these
# absolutely (the scores live in [0, 1]); everything else compares
# relative to the incumbent's scale
_PROBABILITY_OBJECTIVES = {"binary", "multiclass", "multiclassova",
                           "cross_entropy", "cross_entropy_lambda"}


class GateFailure(RuntimeError):
    """A promotion gate refused the candidate (verification, engine
    self-check, shadow parity, metric regression, or probe timeout).
    The incumbent keeps serving; the caller quarantines the candidate.
    Never retried — a refusal is a verdict, not a transient."""

    def __init__(self, stage: str, reason: str,
                 version: Optional[str] = None):
        self.stage = stage
        self.reason = reason
        # the refused candidate's registry version id (when it got as
        # far as a shadow load) — soak/ops tooling asserts it never
        # served a request
        self.version = version
        super().__init__(f"continual gate failed at {stage}: {reason}")


# ---------------------------------------------------------------------------
# gate primitives
# ---------------------------------------------------------------------------

def score_gate_reason(objective: str, cand: np.ndarray, inc: np.ndarray,
                      tol: float) -> Optional[str]:
    """Objective-aware shadow-parity check of one replayed batch:
    None when the candidate's scores are acceptably close to the
    incumbent's, else a reason string.  This bounds score MOVEMENT, not
    byte parity — a continual candidate legitimately differs from the
    incumbent by its fresh trees; a corrupt or insane one differs by
    orders of magnitude."""
    cand = np.asarray(cand, np.float64)
    inc = np.asarray(inc, np.float64)
    if cand.shape != inc.shape:
        return (f"output shape {cand.shape} != incumbent's {inc.shape}")
    if not np.all(np.isfinite(cand)):
        return "candidate produced non-finite scores"
    if cand.size == 0:
        return None
    # a degraded INCUMBENT (non-finite scores) must not blind the gate:
    # NaN poisons max() and every NaN comparison is False, which would
    # pass ANY candidate exactly when serving is already sick.  Compare
    # on the incumbent's finite entries only
    finite = np.isfinite(inc)
    if not np.any(finite):
        return None     # nothing sane to compare against
    worst = float(np.max(np.abs(cand[finite] - inc[finite])))
    if objective in _PROBABILITY_OBJECTIVES:
        if worst > tol:
            return (f"probability drift {worst:.6g} > "
                    f"shadow_probe_tolerance {tol:g}")
        return None
    # unbounded outputs (regression/ranking/raw): relative to the
    # incumbent's scale, floored at 1 so near-zero scores don't demand
    # absolute agreement tighter than the tolerance itself
    scale = max(1.0, float(np.max(np.abs(inc[finite]))))
    if worst / scale > tol:
        return (f"relative score drift {worst / scale:.6g} > "
                f"shadow_probe_tolerance {tol:g} "
                f"(|delta| {worst:.6g} at scale {scale:.6g})")
    return None


def lineage_gate_reason(candidate, incumbent, rows: np.ndarray,
                        decay: float, rtol: float) -> Optional[str]:
    """The SHARP parity invariant of a continual candidate: its leading
    trees ARE the incumbent's (scaled by ``continual_decay``), so its
    raw-score prefix prediction must reproduce the incumbent's raw
    scores to float rounding — independent of how far training has
    converged, which the drift check cannot be.  A corrupt, truncated
    or wrong-lineage candidate fails HERE even when its outputs look
    plausible.  None = parity holds; only meaningful when the candidate
    was boosted from the serving incumbent (the trainer's case — an
    operator promoting an unrelated retrain skips it)."""
    k = max(1, incumbent._num_tree_per_iteration)
    n_prev = len(incumbent.trees) // k
    if len(candidate.trees) < len(incumbent.trees):
        return (f"candidate carries {len(candidate.trees)} trees, fewer "
                f"than the incumbent's {len(incumbent.trees)} — not a "
                "continuation")
    if n_prev == 0 or not len(rows):
        return None
    prefix = np.asarray(candidate.predict(rows, num_iteration=n_prev,
                                          raw_score=True), np.float64)
    base = np.asarray(incumbent.predict(rows, raw_score=True),
                      np.float64) * decay
    if prefix.shape != base.shape:
        return (f"prefix output shape {prefix.shape} != incumbent's "
                f"{base.shape}")
    if not np.all(np.isfinite(prefix)):
        return "candidate prefix produced non-finite scores"
    # non-finite incumbent entries are the incumbent's degradation, not
    # lineage evidence either way — compare on the finite ones (NaN
    # comparisons are always False and would silently PASS corruption)
    finite = np.isfinite(base)
    if not np.any(finite):
        return None
    scale = np.maximum(1.0, np.abs(base[finite]))
    worst = float(np.max(np.abs(prefix[finite] - base[finite]) / scale))
    if worst > rtol:
        return (f"lineage parity violated: candidate's first {n_prev} "
                f"iterations diverge from the incumbent by "
                f"{worst:.3g} relative (allowed {rtol:g}, decay "
                f"{decay:g}) — the candidate is not the incumbent "
                "plus new trees")
    return None


def gate_metric_value(objective: str, pred: np.ndarray,
                      y: np.ndarray) -> Tuple[str, float, bool]:
    """Self-contained ``(name, value, higher_better)`` eval of
    predictions on the gate set — the metric-regression leg of the
    probe.  Deliberately tiny: logloss for the classification families,
    L2 for everything else (a loaded candidate has no Dataset to drive
    the full metric registry with)."""
    pred = np.asarray(pred, np.float64)
    y = np.asarray(y, np.float64).reshape(-1)
    eps = 1e-15
    if objective == "binary":
        p = np.clip(pred.reshape(-1), eps, 1.0 - eps)
        return ("binary_logloss",
                float(-np.mean(y * np.log(p)
                               + (1.0 - y) * np.log(1.0 - p))), False)
    if objective in ("multiclass", "multiclassova"):
        p = np.clip(pred.reshape(len(y), -1), eps, 1.0)
        idx = y.astype(np.int64)
        return ("multi_logloss",
                float(-np.mean(np.log(p[np.arange(len(y)), idx]))), False)
    return ("l2", float(np.mean((pred.reshape(len(y), -1)[:, 0] - y)
                                ** 2)), False)


def shadow_parity_probe(candidate, incumbent, batches: List[np.ndarray],
                        cfg: Config,
                        eval_set: Optional[Tuple[np.ndarray, np.ndarray]]
                        = None,
                        timeout_s: Optional[float] = None,
                        lineage_decay: Optional[float] = None) -> Dict:
    """Replay ``batches`` (the last K live serve batches, or chunk
    slices when there is no traffic yet) through the candidate AND the
    incumbent in a BACKGROUND thread; the serving hot path never waits
    on it.  Returns a report dict — ``ok`` True only when every batch
    scored within the objective-aware tolerance and the eval metric did
    not regress past ``shadow_probe_metric_tolerance``.  A probe that
    exceeds ``timeout_s`` (``continual_timeout_s``) is a FAILURE, not a
    wait — a wedged candidate must roll back, not stall freshness."""
    result: Dict[str, Any] = {}

    def _run() -> None:
        try:
            faultinject.check("shadow_probe")
            checked = 0
            for rows in batches:
                c = candidate.predict(rows)
                i = incumbent.predict(rows)
                reason = score_gate_reason(cfg.objective, c, i,
                                           cfg.shadow_probe_tolerance)
                if reason is not None:
                    result["reason"] = f"batch {checked}: {reason}"
                    return
                checked += 1
            if lineage_decay is not None and batches:
                # batch-independent invariant: ONE raw-prefix replay
                # (the first batch) proves it — running it per batch
                # would triple the probe's forest-traversal cost for
                # no added coverage
                reason = lineage_gate_reason(
                    candidate, incumbent, batches[0], lineage_decay,
                    cfg.shadow_probe_lineage_tolerance)
                if reason is not None:
                    result["reason"] = reason
                    return
            result["batches"] = checked
            if eval_set is not None and len(eval_set[0]):
                x, y = eval_set
                name, cv, hib = gate_metric_value(
                    cfg.objective, candidate.predict(x), y)
                _n, iv, _h = gate_metric_value(
                    cfg.objective, incumbent.predict(x), y)
                worse = (iv - cv) if hib else (cv - iv)
                result["metric"] = {"name": name,
                                    "candidate": round(cv, 8),
                                    "incumbent": round(iv, 8)}
                if worse > cfg.shadow_probe_metric_tolerance:
                    result["reason"] = (
                        f"eval metric {name} regressed: candidate "
                        f"{cv:.6g} vs incumbent {iv:.6g} (allowed "
                        f"{cfg.shadow_probe_metric_tolerance:g})")
                    return
            result["ok"] = True
        except BaseException as e:      # noqa: BLE001 — the probe thread
            # must report, never kill the pipeline
            result["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=_run, daemon=True,
                         name="lgbtpu-shadow-probe")
    t0 = time.perf_counter()
    t.start()
    t.join(timeout_s if timeout_s and timeout_s > 0 else None)
    if t.is_alive():
        return {"ok": False,
                "reason": f"shadow probe exceeded continual_timeout_s "
                          f"({timeout_s:g}s)"}
    out = {"ok": bool(result.get("ok")),
           "probe_s": round(time.perf_counter() - t0, 6)}
    for k in ("batches", "metric"):
        if k in result:
            out[k] = result[k]
    if not out["ok"]:
        out["reason"] = result.get("error") \
            or result.get("reason", "probe aborted")
    return out


def gated_promote(registry, *, snapshot: Optional[str] = None,
                  model_file: Optional[str] = None,
                  expected_sha256: Optional[str] = None,
                  cfg: Optional[Config] = None,
                  batches: Optional[List[np.ndarray]] = None,
                  eval_set: Optional[Tuple[np.ndarray, np.ndarray]] = None,
                  metrics=None, version: Optional[str] = None,
                  lineage_decay: Optional[float] = None,
                  activate: bool = True) -> Tuple[str, Dict]:
    """Two-stage gated promotion into a ``ModelRegistry`` — the ONLY
    sanctioned way a continual candidate starts serving.

    Stage 1: SHA-verified SHADOW load (``activate=False`` — the
    candidate is resident but takes no traffic).  The registry enforces
    the checksum pin and runs the engine ``self_check``; a self-check
    that FAILED is a gate refusal here (``ServedModel
    .self_check_failed``), not the host-walk demotion plain serving
    settles for.  Stage 2: the shadow-traffic parity probe against the
    incumbent.  Both pass -> ``registry.activate`` flips the pointer
    (in-flight requests finish on the incumbent, the hot-swap
    contract).  Anything fails -> the candidate is unloaded (it never
    served a request) and :class:`GateFailure` raises for the caller to
    quarantine.  Returns ``(version, gate_report)``.

    ``activate=False`` runs the FULL gate but leaves the passed
    candidate resident without flipping the registry's current pointer
    — the per-segment promote (fleet serving): the caller routes a
    segment at the returned version instead of making it the
    default."""
    cfg = cfg if cfg is not None else Config({})
    faultinject.check("continual_promote")
    from ..serve.registry import NoModelError
    t0 = time.perf_counter()
    had_incumbent = True
    try:
        registry.current()
    except NoModelError:
        had_incumbent = False
    if snapshot is not None:
        version = registry.load_snapshot(snapshot, version=version,
                                         activate=False,
                                         expected_sha256=expected_sha256)
    else:
        version = registry.load(model_file=model_file, version=version,
                                activate=False,
                                expected_sha256=expected_sha256)
    report: Dict[str, Any] = {"version": version}
    try:
        cand = registry.get(version)
        if cand.self_check_failed:
            raise GateFailure(
                "self_check",
                "engine byte-parity self-check failed (plain serving "
                "would demote to the host walk; a continual promotion "
                "refuses the candidate)")
        inc = None
        if had_incumbent:
            inc = registry.current()
        if inc is not None and inc.version != version:
            probe = shadow_parity_probe(
                cand.booster, inc.booster, batches or [], cfg,
                eval_set=eval_set, timeout_s=cfg.continual_timeout_s,
                lineage_decay=lineage_decay)
            report["probe"] = probe
            if not probe["ok"]:
                raise GateFailure("shadow_probe", probe["reason"])
        if activate:
            registry.activate(version)
        report["gate_s"] = round(time.perf_counter() - t0, 6)
        if metrics is not None:
            metrics.histogram("continual.gate_seconds").observe(
                report["gate_s"])
        return version, report
    except BaseException as e:
        # the candidate never served (a shadow load takes no traffic,
        # even into an empty registry): expel it.  force is belt and
        # braces for the no-incumbent case
        try:
            registry.unload(version, force=not had_incumbent)
        except Exception:       # noqa: BLE001 — rollback is best-effort
            pass
        if isinstance(e, GateFailure):
            e.version = version
        raise


# ---------------------------------------------------------------------------
# the trainer loop
# ---------------------------------------------------------------------------

class ContinualTrainer:
    """Freshness-guaranteed continual boosting loop (module docstring).

    Construct with the training params and (optionally) the base data;
    each :meth:`run_generation` call appends a chunk and runs
    append -> boost -> publish -> promote, returning a report dict with
    ``status`` ``"published"`` or ``"rolled_back"``.  Attach a live
    ``serve.Server`` to promote into its registry (sharing its metrics
    registry and shadow-traffic ring) or run standalone — the gates run
    either way, against an in-memory incumbent.

    Thread topology: the generation loop runs on ONE trainer thread
    (stages never overlap), but a live server's HTTP threads read the
    freshness surface (``generation`` / :meth:`freshness_lag_s` /
    ``last_publish`` via ``GET /freshness``) while a generation is in
    flight — that cross-thread state is lock-guarded; the bulk data
    (``_x``/``_chunk_x`` …) is trainer-thread-only and stays lock-free.

    Lock contract (tools/analyze/check_races.py):
        _lock guards: generation, _chunk_t, _last_promote_t
        _lock guards: last_publish
        registry type: lightgbm_tpu/serve/registry.py:ModelRegistry
        server type: lightgbm_tpu/serve/server.py:Server
    """

    def __init__(self, params, x=None, y=None, *, server=None,
                 registry=None):
        self.config = params if isinstance(params, Config) \
            else Config(params or {})
        self.params: Dict[str, Any] = dict(
            self.config.raw_params if isinstance(params, Config)
            else (params or {}))
        if not self.config.output_model:
            raise ValueError("continual training needs output_model "
                             "(the published-snapshot base path)")
        if 0 < self.config.snapshot_keep < 2:
            # publish prunes to snapshot_keep; with keep=1 a gate
            # failure would quarantine the ONLY snapshot and strand the
            # next generation with nothing to boost from
            Log.warning("continual: snapshot_keep=1 cannot hold the "
                        "incumbent through a rollback; using 2")
            self.config.snapshot_keep = 2
        self.server = server
        self.registry = registry if registry is not None \
            else (server.registry if server is not None else None)
        if server is not None:
            self.metrics = server.metrics
            self.tracer = server.tracer
            server.continual = self
        else:
            from ..obs import MetricsRegistry
            self.metrics = MetricsRegistry()
            self.tracer = None
        # pre-register the counter family: a dashboard (or test) reading
        # the snapshot sees explicit zeros, not missing keys
        for c in ("continual.generations", "continual.published",
                  "continual.rollbacks", "continual.quarantined"):
            self.metrics.counter(c)
        self._retry = RetryPolicy(
            max_attempts=max(1, self.config.continual_retries + 1),
            base_delay_s=0.05, max_delay_s=1.0)
        # guards the freshness surface served to HTTP threads (class
        # docstring lock contract)
        self._lock = threading.Lock()
        self.generation = 0             # completed (promoted) generations
        self.last_publish: Dict[str, Any] = {}
        self._incumbent = None          # standalone-mode gate anchor
        self._incumbent_sha: Optional[str] = None
        self._boost_base_sha: Optional[str] = None
        self._x: Optional[np.ndarray] = None
        self._y: Optional[np.ndarray] = None
        self._chunk_x: Optional[np.ndarray] = None
        self._chunk_y: Optional[np.ndarray] = None
        self._chunk_t: Optional[float] = None
        self._last_promote_t: Optional[float] = None
        if x is not None:
            self._x = np.asarray(x, np.float64)
            self._y = np.asarray(y)
            self._chunk_x, self._chunk_y = self._x, self._y

    # -- stage plumbing ----------------------------------------------------
    def _stage(self, name: str, fn):
        """Run one pipeline stage under the retry policy + a span.
        Gate refusals are never retried (a verdict, not a transient);
        injected faults match the resilience classifier's patterns so a
        ``site:1`` spec exercises the REAL retry path."""
        span = (self.tracer.span(f"continual.{name}")
                if self.tracer is not None else None)
        try:
            return retry_call(
                fn, policy=self._retry,
                classify=lambda e: not isinstance(e, GateFailure)
                and is_retryable_device_error(e),
                label=f"continual.{name}")
        finally:
            if span is not None:
                span.end()

    @property
    def quarantine_dir(self) -> str:
        return self.config.continual_quarantine_dir \
            or self.config.output_model + ".quarantine"

    def freshness_lag_s(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds between the newest chunk's arrival and its model
        serving — the headline freshness number while a generation is
        in flight, frozen at the promoted lag after it lands."""
        with self._lock:         # HTTP threads vs the trainer loop
            chunk_t = self._chunk_t
            promote_t = self._last_promote_t
        return self._lag(chunk_t, promote_t, now)

    @staticmethod
    def _lag(chunk_t, promote_t, now=None) -> Optional[float]:
        if chunk_t is None:
            return None
        now = time.time() if now is None else now
        if promote_t is not None and promote_t >= chunk_t:
            return round(promote_t - chunk_t, 6)
        return round(now - chunk_t, 6)

    def freshness_snapshot(self, now: Optional[float] = None) -> Dict:
        """One-lock snapshot of the freshness surface — the form
        ``GET /freshness`` consumes.  Composing the same fields from
        separate ``generation`` / :meth:`freshness_lag_s` /
        ``last_publish`` reads would let a promote land between them
        and serve a torn pair (generation N next to gen-N+1's publish
        record)."""
        with self._lock:
            return {"generation": self.generation,
                    "freshness_lag_s": self._lag(
                        self._chunk_t, self._last_promote_t, now),
                    "last_publish": dict(self.last_publish) or None}

    # -- stages ------------------------------------------------------------
    def append_chunk(self, x, y) -> None:
        """(a) ingest one new data chunk."""
        x = np.asarray(x, np.float64)
        y = np.asarray(y)

        def _do():
            faultinject.check("continual_append")
            if self._x is None:
                self._x, self._y = x, y
            else:
                self._x = np.concatenate([self._x, x], axis=0)
                self._y = np.concatenate([self._y, y], axis=0)
            self._chunk_x, self._chunk_y = x, y
            with self._lock:     # /freshness reads the arrival stamp
                self._chunk_t = time.time()

        self._stage("append", _do)

    def boost(self):
        """(b) boost ``continual_rounds`` more iterations from the
        newest complete snapshot through the init_model path; returns
        ``(booster, dataset)`` with the snapshot's trees merged in."""
        if self._x is None:
            raise ValueError("no data: append a chunk (or construct "
                             "with base x/y) before boosting")

        def _do():
            faultinject.check("continual_boost")
            from ..booster import Booster
            from ..dataset import Dataset
            from ..engine import train as train_fn
            from ..snapshot import find_latest_complete_snapshot
            prev = None
            self._boost_base_sha = None
            found = find_latest_complete_snapshot(
                self.config.output_model,
                verify=self.config.serve_verify_artifacts)
            if found is not None:
                prev = Booster(model_file=found[1])
                try:
                    # the base artifact's checksum: the promote gate
                    # applies the lineage-parity check only when the
                    # serving incumbent IS this snapshot (an operator
                    # may have hot-swapped an unrelated model in — a
                    # continuation of THIS base is then legitimately
                    # not a continuation of the incumbent)
                    with open(found[1] + ".manifest.json",
                              encoding="utf-8") as f:
                        self._boost_base_sha = json.load(f).get(
                            "model_sha256")
                except (OSError, ValueError):
                    pass
                decay = self.config.continual_decay
                if decay < 1.0:
                    if any(t.is_linear for t in prev.trees):
                        raise ValueError(
                            "continual_decay is not supported for "
                            "linear-tree models: only the constant "
                            "leaf values would decay, leaving the "
                            "leaf linear models at full weight")
                    for t in prev.trees:
                        t.shrink(decay)
                    prev._drop_predict_cache()
            ds = Dataset(self._x, label=self._y,
                         params=dict(self.params),
                         free_raw_data=False)
            p = dict(self.params)
            # run-control knobs stripped: the GENERATION is the unit of
            # redo (publish is the only snapshot writer; a restart
            # re-runs the whole generation deterministically), and the
            # inner round count is continual_rounds, never the params'
            from ..config import _ALIASES
            for k in list(p):
                if _ALIASES.get(k, k) in ("resume", "snapshot_freq",
                                          "num_iterations", "task",
                                          "continual_data"):
                    p.pop(k)
            return train_fn(p, ds,
                            num_boost_round=self.config.continual_rounds,
                            init_model=prev), ds

        return self._stage("boost", _do)

    def publish(self, booster, ds) -> Tuple[str, str, int]:
        """(c) write the candidate as a SHA-pinned snapshot artifact
        (atomic, manifest last) and prune to ``snapshot_keep``; returns
        ``(path, model_sha256, iteration)``."""

        def _do():
            faultinject.check("continual_publish")
            from ..snapshot import params_signature, write_snapshot
            # the FULL forest's iteration count (prev snapshot's trees
            # merged in), not current_iteration — that counts only this
            # generation's boosting
            it = len(booster.trees) // max(
                1, booster._num_tree_per_iteration)
            write_snapshot(booster, None, self.config, it,
                           params_signature(self.params), ds)
            path = f"{self.config.output_model}.snapshot_iter_{it}"
            with open(path + ".manifest.json", encoding="utf-8") as f:
                sha = json.load(f)["model_sha256"]
            return path, sha, it

        return self._stage("publish", _do)

    def promote(self, path: str, sha: str) -> Tuple[str, Dict]:
        """(d) two-stage gated promotion of the published artifact —
        into the attached registry, or against the in-memory incumbent
        when running standalone."""

        def _do():
            if self.registry is not None:
                prev = None
                try:
                    prev = self.registry.current().version
                except Exception:   # noqa: BLE001 — no incumbent yet
                    pass
                out = gated_promote(
                    self.registry, snapshot=self.config.output_model,
                    expected_sha256=sha, cfg=self.config,
                    batches=self._probe_batches(),
                    eval_set=self._eval_set(), metrics=self.metrics,
                    lineage_decay=self._lineage_decay(
                        self._registry_incumbent_sha()))
                # residency hygiene: with no serve_max_resident cap a
                # generation-every-few-minutes pipeline would keep
                # every superseded incumbent (booster + device tables)
                # resident forever — drop the displaced one after a
                # successful swap; in-flight batches finish on their
                # own references.  Under a cap, eviction owns this
                if prev is not None and prev != out[0] \
                        and self.registry.max_resident == 0:
                    try:
                        self.registry.unload(prev)
                    except Exception:   # noqa: BLE001 — best-effort
                        pass
                return out
            return self._promote_standalone(path, sha)

        return self._stage("promote", _do)

    def _promote_standalone(self, path: str, sha: str) -> Tuple[str, Dict]:
        """The registry-less gate: same two stages, in-memory incumbent."""
        faultinject.check("continual_promote")
        t0 = time.perf_counter()
        with self._lock:
            gen_next = self.generation + 1
        from ..booster import Booster
        from ..snapshot import file_sha256
        got = file_sha256(path)
        if got != sha:
            raise GateFailure("verify",
                              f"artifact checksum mismatch (file "
                              f"{got[:12]}…, pinned {sha[:12]}…)")
        cand = Booster(model_file=path)
        report: Dict[str, Any] = {}
        if self.config.serve_verify_artifacts:
            from ..serve.engine import EngineUnsupported, PredictorEngine
            try:
                eng = PredictorEngine.from_booster(cand, max_batch=256)
                if not eng.self_check():
                    raise GateFailure(
                        "self_check",
                        "engine byte-parity self-check failed")
            except EngineUnsupported:
                # an engine-unsupported model serves via the host walk
                # everywhere — nothing to prove here
                pass
        if self._incumbent is not None:
            probe = shadow_parity_probe(
                cand, self._incumbent, self._probe_batches(),
                self.config, eval_set=self._eval_set(),
                timeout_s=self.config.continual_timeout_s,
                lineage_decay=self._lineage_decay(self._incumbent_sha))
            report["probe"] = probe
            if not probe["ok"]:
                raise GateFailure("shadow_probe", probe["reason"])
        self._incumbent = cand
        self._incumbent_sha = sha
        version = f"gen{gen_next}"
        report["version"] = version
        report["gate_s"] = round(time.perf_counter() - t0, 6)
        self.metrics.histogram("continual.gate_seconds").observe(
            report["gate_s"])
        return version, report

    def _registry_incumbent_sha(self) -> Optional[str]:
        try:
            return self.registry.current().sha256
        except Exception:       # noqa: BLE001 — no incumbent yet
            return None

    def _lineage_decay(self, incumbent_sha: Optional[str]
                       ) -> Optional[float]:
        """The lineage-parity check applies ONLY when the serving
        incumbent is provably the snapshot this candidate boosted from
        (checksums match).  After an operator hot-swaps an unrelated
        model (POST /reload of a hotfix), a legitimate continuation of
        the SNAPSHOT lineage is not a continuation of the INCUMBENT —
        gating on lineage then would quarantine every generation
        forever.  The drift and metric gates still apply."""
        if self._boost_base_sha is not None \
                and incumbent_sha == self._boost_base_sha:
            return self.config.continual_decay
        return None

    # -- probe inputs ------------------------------------------------------
    def _probe_batches(self) -> List[np.ndarray]:
        """The last K live serve batches when a server is attached and
        has traffic; otherwise slices of the newest chunk (the gate
        must always have SOMETHING representative to replay)."""
        k = self.config.shadow_probe_batches
        if k <= 0:
            return []       # replay probe disabled (metric gate remains)
        if self.server is not None:
            ring = self.server.shadow_batches()
            if ring:
                return ring
        if self._chunk_x is None or not len(self._chunk_x):
            return []
        rows = self._chunk_x[-min(len(self._chunk_x), 256 * k):]
        return [b for b in np.array_split(rows, min(k, len(rows)))
                if len(b)]

    def _eval_set(self):
        if self._chunk_x is None or self._chunk_y is None \
                or not len(self._chunk_x):
            return None
        return self._chunk_x, self._chunk_y

    # -- rollback / quarantine --------------------------------------------
    def _quarantine(self, path: str, sha: str, stage: str,
                    reason: str) -> None:
        """Move a refused candidate's files out of the snapshot lineage
        (manifest FIRST: a crash mid-quarantine must never leave the
        candidate looking complete) and drop a blackbox dump beside
        them — next generation boosts from the incumbent again."""
        import shutil
        qdir = self.quarantine_dir
        os.makedirs(qdir, exist_ok=True)
        base = os.path.basename(path)
        moved = []
        for suffix in (".manifest.json", ".state.npz", ""):
            src = path + suffix
            if not os.path.exists(src):
                continue
            dst = os.path.join(qdir, base + suffix)
            try:
                os.replace(src, dst)
            except OSError:
                # cross-filesystem quarantine dir: copy, then unlink.
                # What matters is that the SOURCE goes away — above
                # all the manifest, the completeness marker: were it
                # left behind, the next generation would boost from
                # the refused candidate
                try:
                    shutil.copy2(src, dst)
                except OSError:
                    pass
                try:
                    os.unlink(src)
                except OSError as e:
                    Log.warning(f"continual: could not remove "
                                f"quarantined {src} ({e})")
                    continue
            moved.append(base + suffix)
        with self._lock:
            gen_next = self.generation + 1
        dump = {"reason": reason, "stage": stage, "model_sha256": sha,
                "generation": gen_next,
                "quarantined_at": time.time(), "files": moved}
        try:
            atomic_write(os.path.join(qdir, base + ".blackbox.json"),
                         json.dumps(dump, indent=1, sort_keys=True))
        except Exception as e:      # noqa: BLE001 — the dump is evidence,
            # not a gate: a full disk must not mask the rollback itself
            Log.warning(f"continual: quarantine blackbox dump failed "
                        f"({e})")
        from ..obs import blackbox
        blackbox.dump_all(f"continual_{stage}")
        self.metrics.counter("continual.quarantined").inc()
        Log.warning(f"continual: candidate {base} quarantined to "
                    f"{qdir} ({stage}: {reason})")

    # -- the generation ----------------------------------------------------
    def run_generation(self, x=None, y=None) -> Dict:
        """One full generation; returns the report dict.  In-process
        failures (gate refusals, exhausted retries, probe timeouts) roll
        back automatically — the incumbent keeps serving and the report
        says ``rolled_back``; process-death exceptions (InjectedKill /
        KeyboardInterrupt / SystemExit) propagate, the on-disk publish
        discipline makes the RESTART converge instead."""
        t_start = time.time()
        with self._lock:
            gen_next = self.generation + 1
        report: Dict[str, Any] = {"generation": gen_next,
                                  "status": "published"}
        published: Optional[Tuple[str, str]] = None
        stage = "append"
        try:
            if x is not None:
                self.append_chunk(x, y)
            stage = "boost"
            booster, ds = self.boost()
            stage = "publish"
            path, sha, it = self.publish(booster, ds)
            published = (path, sha)
            stage = "promote"
            version, gate = self.promote(path, sha)
            with self._lock:
                # one atomic publish of the freshness surface: an HTTP
                # reader never sees the new generation number with the
                # old promote stamp (a transiently negative/huge lag)
                self.generation += 1
                gen_done = self.generation
                promote_t = self._last_promote_t = time.time()
                lag = promote_t - (self._chunk_t or t_start)
                self.last_publish = {"version": version, "path": path,
                                     "sha256": sha, "iteration": it,
                                     "at": promote_t}
            self.metrics.counter("continual.published").inc()
            self.metrics.gauge("continual.freshness_lag_s").set(lag)
            report.update(version=version, sha256=sha, iteration=it,
                          gate=gate, freshness_lag_s=round(lag, 6))
            Log.info(f"continual: generation {gen_done} "
                     f"published as {version} (iter {it}, freshness "
                     f"lag {lag:.3f}s)")
        except Exception as e:          # noqa: BLE001 — ANY in-process
            # failure is a rollback; BaseException (kill/exit) means the
            # process is dying and restart-convergence takes over
            reason = f"{type(e).__name__}: {e}"
            stage_name = e.stage if isinstance(e, GateFailure) else stage
            self.metrics.counter("continual.rollbacks").inc()
            if published is not None:
                self._quarantine(published[0], published[1], stage_name,
                                 reason)
            report.update(status="rolled_back", stage=stage_name,
                          reason=reason)
            if getattr(e, "version", None):
                report["version_refused"] = e.version
            Log.warning(f"continual: generation "
                        f"{report['generation']} ROLLED BACK at "
                        f"{stage_name} ({reason}); incumbent keeps "
                        "serving")
        finally:
            self.metrics.counter("continual.generations").inc()
            self.metrics.histogram("continual.generation_seconds") \
                .observe(time.time() - t_start)
        return report

    def run(self, chunks) -> List[Dict]:
        """Run one generation per ``(x, y)`` chunk; returns the reports."""
        return [self.run_generation(cx, cy) for cx, cy in chunks]
