"""Reference-parity tier (VERDICT r2 task 4).

Two claims are tested against the ACTUAL reference implementation
(/root/reference, LightGBM v3.3.x fork), the way its own
tests/python_package_test/test_consistency.py does:

1. **Model-format compatibility**: models trained by the reference CLI
   (committed fixtures, see tests/fixtures/reference/README.md) load in
   this framework and predict the reference's own `*.test` files to within
   float tolerance of the reference's own predictions; re-serializing with
   our writer round-trips exactly.
2. **Training quality on the reference's example datasets + conf files**:
   training with each example's train.conf parameters reaches golden
   metric thresholds derived from the reference's 20-iteration results.
"""

import os
from pathlib import Path

import numpy as np
import pytest

import lightgbm_tpu as lgb

EXAMPLES = Path("/root/reference/examples")
FIXTURES = Path(__file__).parent / "fixtures" / "reference"

pytestmark = pytest.mark.skipif(
    not EXAMPLES.exists(), reason="reference examples not available")


def load_conf(path: Path) -> dict:
    """Parse a reference train.conf (test_consistency.py FileLoader)."""
    params = {}
    for line in path.read_text().splitlines():
        line = line.strip()
        if line and not line.startswith("#") and "=" in line:
            k, v = (t.strip() for t in line.split("=", 1))
            params[k] = v
    return params


def load_svm(path: Path, n_features=None):
    """label + dense matrix; auto-detects the dense TSV files (binary,
    regression, multiclass) vs the sparse LibSVM ranking files."""
    with open(path) as f:
        first = f.readline()
    if ":" not in first:
        mat = np.loadtxt(str(path), dtype=np.float64)
        return mat[:, 1:], mat[:, 0]
    from sklearn.datasets import load_svmlight_file
    x, y = load_svmlight_file(str(path), dtype=np.float64, zero_based=True,
                              n_features=n_features)
    return np.asarray(x.todense()), y


def _train_params(conf: dict, extra=None) -> dict:
    drop = {"task", "data", "valid_data", "output_model", "input_model",
            "output_result", "machine_list_file", "num_machines",
            "local_listen_port", "tree_learner", "is_training_metric",
            "label_column", "query_column", "metric_freq",
            "is_enable_sparse", "use_two_round_loading",
            "is_save_binary_file"}
    p = {k: v for k, v in conf.items() if k not in drop}
    p["verbosity"] = -1
    p["num_trees"] = 20
    if extra:
        p.update(extra)
    return p


CASES = {
    # task: (example dir, prefix, fixture stem)
    "binary": ("binary_classification", "binary", "binary"),
    "regression": ("regression", "regression", "regression"),
    "multiclass": ("multiclass_classification", "multiclass", "multiclass"),
    "lambdarank": ("lambdarank", "rank", "lambdarank"),
    "xendcg": ("xendcg", "rank", "xendcg"),
}


@pytest.mark.parametrize("task", sorted(CASES))
def test_load_reference_model_predict_parity(task):
    """A reference-trained model.txt must load and reproduce the
    reference's own predictions on its own test file."""
    ex_dir, prefix, stem = CASES[task]
    model_txt = (FIXTURES / f"{stem}_model.txt").read_text()
    n_feat = next((int(l.split("=")[1]) + 1
                   for l in model_txt.splitlines()
                   if l.startswith("max_feature_idx=")), None)
    x_test, _ = load_svm(EXAMPLES / ex_dir / f"{prefix}.test",
                         n_features=n_feat)
    bst = lgb.Booster(model_file=str(FIXTURES / f"{stem}_model.txt"))
    pred = np.asarray(bst.predict(x_test))
    ref = np.loadtxt(str(FIXTURES / f"{stem}_pred.txt"))
    assert pred.shape == ref.shape
    np.testing.assert_allclose(pred, ref, rtol=1e-5, atol=1e-7)

    # round-trip through OUR writer must preserve predictions exactly
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(np.asarray(bst2.predict(x_test)), pred,
                               rtol=1e-9, atol=0)


# golden thresholds: reference 20-iter valid metrics with slack for
# binning/bagging RNG differences (fixtures README records the exact values)
def _ndcg5(bst, x, y, qs):
    from lightgbm_tpu.metrics import NDCGMetric
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import Metadata

    raw = np.asarray(bst.predict(x, raw_score=True))
    md = Metadata(len(y))
    md.label = np.asarray(y)
    md.set_group(qs)
    m = NDCGMetric(Config({"eval_at": [5]}))
    m.init(md, len(y))
    return m.eval(raw)[0][1]


def test_train_binary_reference_conf():
    conf = load_conf(EXAMPLES / "binary_classification" / "train.conf")
    x, y = load_svm(EXAMPLES / "binary_classification" / "binary.train")
    w = np.loadtxt(str(EXAMPLES / "binary_classification"
                       / "binary.train.weight"))
    xt, yt = load_svm(EXAMPLES / "binary_classification" / "binary.test")
    params = _train_params(conf)
    bst = lgb.train(params, lgb.Dataset(x, label=y, weight=w, params=params),
                    num_boost_round=20)
    from lightgbm_tpu.metrics import _auc
    auc = _auc(yt, np.asarray(bst.predict(xt, raw_score=True)), None)
    # measured r4: 0.8234 — ABOVE the reference's 0.8014; the gate sits
    # between them so it fails on a 0.01 drop while still requiring
    # reference-level quality (VERDICT r3 task 10)
    assert auc > 0.815, f"valid AUC {auc} (ours 0.8234, reference 0.8014)"


def test_train_regression_reference_conf():
    conf = load_conf(EXAMPLES / "regression" / "train.conf")
    x, y = load_svm(EXAMPLES / "regression" / "regression.train")
    xt, yt = load_svm(EXAMPLES / "regression" / "regression.test")
    params = _train_params(conf)
    bst = lgb.train(params, lgb.Dataset(x, label=y, params=params),
                    num_boost_round=20)
    l2 = float(np.mean((np.asarray(bst.predict(xt)) - yt) ** 2))
    # measured r4: 0.1981, reference 0.1989 — gate at +2.5% of ours
    assert l2 < 0.203, f"valid l2 {l2} (ours 0.1981, reference 0.1989)"


def test_train_multiclass_reference_conf():
    conf = load_conf(EXAMPLES / "multiclass_classification" / "train.conf")
    x, y = load_svm(EXAMPLES / "multiclass_classification"
                    / "multiclass.train")
    xt, yt = load_svm(EXAMPLES / "multiclass_classification"
                      / "multiclass.test")
    params = _train_params(conf)
    bst = lgb.train(params, lgb.Dataset(x, label=y, params=params),
                    num_boost_round=20)
    p = np.clip(np.asarray(bst.predict(xt)), 1e-15, 1.0)
    ll = float(np.mean(-np.log(p[np.arange(len(yt)), yt.astype(int)])))
    # measured r4: 1.5114 vs reference 1.4663 (+3.1%, the one example
    # task we don't beat; binning/one-vs-rest ordering differences) —
    # gate tracks OUR value with ~1.5% slack so regressions fail
    assert ll < 1.535, f"valid multi_logloss {ll} (ours 1.5114, " \
                       f"reference 1.4663)"


# measured r4: lambdarank 0.6589, xendcg 0.6579 — both above the
# reference's ~0.63-0.65; floors fail on a 0.015 drop
@pytest.mark.parametrize("task,floor", [("lambdarank", 0.645),
                                        ("xendcg", 0.645)])
def test_train_ranking_reference_conf(task, floor):
    ex_dir, prefix, _ = CASES[task]
    conf = load_conf(EXAMPLES / ex_dir / "train.conf")
    x, y = load_svm(EXAMPLES / ex_dir / f"{prefix}.train")
    qs = np.loadtxt(str(EXAMPLES / ex_dir / f"{prefix}.train.query"),
                    dtype=np.int64)
    xt, yt = load_svm(EXAMPLES / ex_dir / f"{prefix}.test",
                      n_features=x.shape[1])
    qt = np.loadtxt(str(EXAMPLES / ex_dir / f"{prefix}.test.query"),
                    dtype=np.int64)
    params = _train_params(conf)
    bst = lgb.train(params,
                    lgb.Dataset(x, label=y, group=qs, params=params),
                    num_boost_round=20)
    ndcg = _ndcg5(bst, xt, yt, qt)
    assert ndcg > floor, f"{task} valid ndcg@5 {ndcg} (ours ~0.658, reference ~0.63-0.65)"
