"""Chaos-injection soak harness for ELASTIC TRAINING (the
``tools/soak_serve.py`` analog for the training side).

Runs one boosting job under the elastic recovery ladder
(``lightgbm_tpu/parallel/elastic.elastic_train``) while
``utils/faultinject`` windows wedge its collectives
(``collective_hang``), wedge its device claim (``claim_wedge``) and
kill a simulated peer (``host_loss``) mid-run, then checks the
invariants the elastic layer promises (docs/Fault-Tolerance.md
"Elastic training"):

- **Zero hangs**: every collective is bounded by
  ``elastic_collective_timeout_s`` — the injected wedges sleep far
  longer than the deadline, so the run only completes inside the
  wall-clock budget if the deadline actually fired and classified
  every one of them.
- **Shrink-to-survive**: the run completes WITH at least one mesh
  shrink (full mesh -> shrunk mesh -> serial as the chaos demands),
  resuming each rung from the newest COMPLETE snapshot — no lost
  iterations beyond the snapshot gap, counted via the final model's
  tree count.
- **Determinism**: the final model passes the metric-parity harness
  against an uninterrupted SERIAL run over the same data — bitwise
  tree text on the int32 quantized-histogram path (the default here),
  metric-epsilon on f32.
- **Observability**: ``elastic.*`` recovery metrics are present
  (failures by kind, shrinks, recoveries, mesh gauge), the
  per-failure JSONL event log exists next to the model, and the
  flight recorder (``telemetry_blackbox``) dumped on the classified
  failures.

Run standalone (prints one JSON report, exit 1 on violations)::

    python tools/soak_train.py rounds=16 mesh=4 chaos=1

Importable: ``run_soak_train(...)`` returns the report dict —
``tests/test_zelastic.py`` runs a short deterministic soak in tier-1.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time
from typing import Dict, Optional

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

N_FEAT = 6


def _data(n_rows: int, seed: int = 0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n_rows, N_FEAT)
    y = (x[:, 0] - 0.7 * x[:, 1] + 0.25 * rs.randn(n_rows) > 0) \
        .astype("float32")
    return x, y


def run_soak_train(rounds: int = 12, n_rows: int = 400, mesh: int = 4,
                   seed: int = 0, chaos: bool = True,
                   chaos_spec: Optional[str] = None,
                   quant: bool = True, workdir: Optional[str] = None,
                   hang_s: float = 6.0,
                   collective_timeout_s: float = 1.0,
                   budget_s: float = 300.0,
                   params: Optional[Dict] = None) -> Dict:
    """One elastic-training soak; returns the report dict (module
    docstring).  ``chaos=False`` is the control arm: same config, no
    faults — must complete with zero shrinks and the same final model.
    """
    import tempfile

    from lightgbm_tpu import Dataset, train as engine_train
    from lightgbm_tpu.metrics import _auc
    from lightgbm_tpu.parallel import elastic
    from lightgbm_tpu.utils import faultinject

    workdir = workdir or tempfile.mkdtemp(prefix="lgbm_soak_train_")
    os.makedirs(workdir, exist_ok=True)
    out_model = os.path.join(workdir, "soak_model.txt")
    x, y = _data(n_rows, seed)

    p = {"objective": "binary", "num_leaves": 8, "max_bin": 31,
         "min_data_in_leaf": 5, "verbosity": -1,
         "tree_learner": "data", "mesh_shape": [int(mesh)],
         "quant_train": bool(quant),
         "output_model": out_model,
         "snapshot_freq": 2, "snapshot_keep": 0,
         "elastic_enable": True,
         "elastic_collective_timeout_s": float(collective_timeout_s),
         "elastic_retries": 1,
         "elastic_recover_timeout_s": float(budget_s),
         "dist_init_timeout_s": float(collective_timeout_s),
         "dist_init_retries": 0,
         "telemetry_blackbox": True}
    p.update(params or {})

    # uninterrupted SERIAL oracle over the same data — the parity
    # anchor the shrunk/ recovered run must reproduce
    ref_params = {k: v for k, v in p.items()
                  if not k.startswith(("elastic_", "dist_init",
                                       "telemetry", "snapshot",
                                       "mesh_shape", "output_model"))}
    ref_params["tree_learner"] = "serial"
    ref = engine_train(dict(ref_params), Dataset(x, label=y),
                       num_boost_round=rounds)

    violations = []
    spec = chaos_spec or ("collective_hang:4,claim_wedge:2,host_loss:8"
                          if chaos else None)
    prev_hang = os.environ.get(faultinject.HANG_ENV_VAR)
    os.environ[faultinject.HANG_ENV_VAR] = str(hang_s)
    elastic.reset_metrics()
    t0 = time.monotonic()
    try:
        faultinject.configure(spec)
        bst = elastic.elastic_train(dict(p), x, y,
                                    num_boost_round=rounds)
    finally:
        faultinject.clear()
        if prev_hang is None:
            os.environ.pop(faultinject.HANG_ENV_VAR, None)
        else:
            os.environ[faultinject.HANG_ENV_VAR] = prev_hang
    wall_s = time.monotonic() - t0
    report = dict(bst.elastic_report)
    metrics = elastic.metrics_snapshot()

    # -- invariants --------------------------------------------------------
    if wall_s > budget_s:
        violations.append(
            f"run exceeded its wall budget ({wall_s:.1f}s > {budget_s}s):"
            " a collective was NOT bounded by the deadline")
    n_trees = len(bst.trees)
    if n_trees != rounds:
        violations.append(
            f"lost iterations: {n_trees} trees != {rounds} requested "
            "(recovery must lose nothing beyond the snapshot gap, which "
            "is retrained on resume)")
    trees_of = (lambda b:
                b.model_to_string().split("parameters:")[0]
                .split("feature_infos")[1])
    if quant:
        if trees_of(bst) != trees_of(ref):
            violations.append(
                "final model is not bitwise-identical to the "
                "uninterrupted serial run (int32 quantized path)")
    auc_ref = _auc(y, ref.predict(x, raw_score=True), None)
    auc_got = _auc(y, bst.predict(x, raw_score=True), None)
    if abs(float(auc_ref) - float(auc_got)) > 1e-6:
        violations.append(
            f"metric parity failed: soak auc {auc_got:.6f} vs "
            f"serial {auc_ref:.6f}")
    if chaos:
        if report.get("shrinks", 0) < 1:
            violations.append("chaos run finished without a mesh shrink")
        if report.get("recoveries", 0) < 1:
            violations.append("no automatic recovery recorded")
        kinds = {f["kind"] for f in report.get("failures", ())}
        if not kinds:
            violations.append("no classified failures recorded")
        if not any(k.startswith("elastic.failures")
                   for k in metrics):
            violations.append("elastic.failures metrics missing")
        if "elastic.shrinks" not in metrics:
            violations.append("elastic.shrinks metric missing")
        if not os.path.exists(out_model + ".elastic.jsonl"):
            violations.append("elastic failure event log missing")
        bb = glob.glob(os.path.join(workdir, "*.blackbox.jsonl*"))
        if not bb:
            violations.append("no flight-recorder (blackbox) dump found")
    else:
        if report.get("shrinks", 0) != 0:
            violations.append("control run shrank without chaos")

    return {"violations": violations, "wall_s": round(wall_s, 2),
            "rounds": rounds, "n_trees": n_trees,
            "report": report,
            "auc": round(float(auc_got), 6),
            "elastic_metrics": {k: v.get("value")
                                for k, v in metrics.items()
                                if v.get("type") != "histogram"},
            "workdir": workdir}


def main(argv) -> int:
    kv = dict(a.split("=", 1) for a in argv if "=" in a)
    # force CPU + a virtual multi-device topology the supported way
    # (the axon sitecustomize freezes jax_platforms at interpreter
    # start; same pattern as bench.py / tools/check_retraces.py)
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_"
                                     "device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
    rep = run_soak_train(
        rounds=int(kv.get("rounds", 12)),
        n_rows=int(kv.get("rows", 400)),
        mesh=int(kv.get("mesh", 4)),
        chaos=kv.get("chaos", "1") not in ("0", "false"),
        quant=kv.get("quant", "1") not in ("0", "false"),
        hang_s=float(kv.get("hang_s", 6.0)),
        budget_s=float(kv.get("budget_s", 300.0)))
    print(json.dumps(rep, indent=1, sort_keys=True))
    return 1 if rep["violations"] else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
