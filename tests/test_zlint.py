"""Static-analysis suite (tools/lint.py + tools/analyze/ —
docs/Static-Analysis.md).

- the UNIFIED tier-1 invocation: ``python tools/lint.py`` green over
  all four passes (races, purity, syncs, retraces) — this run replaces
  the separate sync/retrace invocations;
- a tamper negative control per pass (injected unguarded write,
  injected ``np.sum`` in a traced body, injected raw sync,
  budget-exceeding retrace), subprocess-driven like the retrace tests;
- lock-order cycle detection, stale-pin detection, mandatory-rationale
  enforcement, ``--update`` re-pin round-trip;
- in-process lintlib/guard-inference units;
- regression tests for the concrete races the lint surfaced and this
  PR fixed (registry in-flight counter, server version counter,
  continual freshness state);
- a marker-gated concurrency stress test hammering registry hot-swap +
  batcher drain from N threads to dynamically corroborate the
  statically-fixed races.
"""

import os
import shutil
import subprocess
import sys
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "tools", "lint.py")
PKG = os.path.join(REPO, "lightgbm_tpu")

sys.path.insert(0, os.path.join(REPO, "tools"))


def _run_lint(*args, timeout=120):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, LINT, *args],
                          capture_output=True, text=True,
                          timeout=timeout, env=env, cwd=REPO)


def _copy_pkg(tmp_path) -> str:
    """Copy the package under a dir of the SAME name so the real
    allowlists (keyed ``lightgbm_tpu/...``) keep matching."""
    dst = str(tmp_path / "lightgbm_tpu")
    shutil.copytree(PKG, dst, ignore=shutil.ignore_patterns(
        "__pycache__"))
    return dst


def _train_tiny(seed=0, rounds=2, **over):
    rs = np.random.RandomState(seed)
    x = rs.randn(300, 6)
    y = (x[:, 0] - x[:, 1] + 0.2 * rs.randn(300) > 0).astype("float32")
    p = {"objective": "binary", "num_leaves": 7, "verbosity": 0,
         "min_data_in_leaf": 5, "max_bin": 15, "fused_chunk": 0}
    p.update(over)
    ds = lgb.Dataset(x, label=y, params=p)
    return lgb.train(p, ds, num_boost_round=rounds), x


# -- the tier-1 invocation --------------------------------------------------

class TestUnifiedDriver:
    def test_all_four_passes_green(self):
        """THE tier-1 lint run: one driver, one exit code, all four
        passes against the pinned allowlists/budget (the retrace
        matrix rides a warm compile cache, ~15 s)."""
        out = _run_lint(timeout=600)
        assert out.returncode == 0, out.stdout + out.stderr
        for name in ("races", "purity", "syncs", "retraces"):
            assert f"[{name}] clean" in out.stdout, out.stdout
        assert "all passes clean" in out.stdout

    def test_unknown_pass_rejected(self):
        out = _run_lint("--only", "nonsense")
        assert out.returncode == 2
        assert "unknown pass" in out.stderr


# -- race lint: tampers + mechanisms ----------------------------------------

class TestRaceLintTamper:
    def test_injected_unguarded_write_fails(self, tmp_path):
        """Negative control: a method writing a lock-guarded attribute
        without the lock must fail the driver."""
        root = _copy_pkg(tmp_path)
        p = os.path.join(root, "serve", "batcher.py")
        src = open(p).read()
        assert "def max_wait_ms_effective" in src
        src = src.replace(
            "    def max_wait_ms_effective(self) -> float:",
            "    def poke(self) -> None:\n"
            "        self._depth_rows += 1\n\n"
            "    def max_wait_ms_effective(self) -> float:")
        open(p, "w").write(src)
        out = _run_lint("--only", "races", "--package-root", root)
        assert out.returncode == 1, out.stdout + out.stderr
        assert "MicroBatcher.poke" in out.stderr
        assert "_depth_rows" in out.stderr
        assert "outside its guard" in out.stderr

    def test_lock_order_cycle_detected(self, tmp_path):
        """Static deadlock detection: two classes acquiring each
        other's locks through declared attr types form a cycle."""
        root = _copy_pkg(tmp_path)
        with open(os.path.join(root, "serve", "cycletamper.py"),
                  "w") as f:
            f.write('''\
"""Synthetic lock-order cycle."""
import threading


class Alpha:
    """A.

    Lock contract (tools/analyze/check_races.py):
        _lock guards: _a
        peer type: lightgbm_tpu/serve/cycletamper.py:Beta
    """

    def __init__(self, peer):
        self._lock = threading.Lock()
        self._a = 0
        self.peer = peer

    def tick(self):
        with self._lock:
            self._a += 1
            self.peer.tock()


class Beta:
    """B.

    Lock contract (tools/analyze/check_races.py):
        _lock guards: _b
        peer type: lightgbm_tpu/serve/cycletamper.py:Alpha
    """

    def __init__(self, peer):
        self._lock = threading.Lock()
        self._b = 0
        self.peer = peer

    def tock(self):
        with self._lock:
            self._b += 1

    def kick(self):
        with self._lock:
            self.peer.tick()
''')
        out = _run_lint("--only", "races", "--package-root", root)
        assert out.returncode == 1
        assert "lock-order cycle" in out.stderr
        assert "Alpha._lock" in out.stderr and "Beta._lock" \
            in out.stderr

    def test_stale_race_pin_rejected(self, tmp_path):
        allow = tmp_path / "races.txt"
        allow.write_text("lightgbm_tpu/serve/batcher.py | "
                         "MicroBatcher.ghost | _queue | no such site\n")
        out = _run_lint("--only", "races",
                        "--race-allowlist", str(allow))
        assert out.returncode == 1
        assert "stale race allowlist entry" in out.stderr

    def test_rationale_is_mandatory(self, tmp_path):
        allow = tmp_path / "races.txt"
        allow.write_text("lightgbm_tpu/serve/batcher.py | "
                         "MicroBatcher.submit | _queue |\n")
        out = _run_lint("--only", "races",
                        "--race-allowlist", str(allow))
        assert out.returncode == 1
        assert "malformed pin" in out.stderr


class TestRaceLintInference:
    """In-process units over synthetic packages: the inference
    mechanics the real-tree green run exercises only implicitly."""

    def _run_on(self, tmp_path, source: str, allow: str = ""):
        from analyze import check_races
        root = tmp_path / "lightgbm_tpu"
        root.mkdir()
        (root / "threaded.py").write_text(source)
        allowf = tmp_path / "allow.txt"
        allowf.write_text(allow)
        return check_races.run(str(root), str(allowf), modules=[])

    def test_locked_helper_contexts_propagate(self, tmp_path):
        """A private helper only ever called with the lock held is NOT
        flagged (the `_trip_locked` pattern), and the same helper
        reachable from a public method without the lock IS."""
        findings = self._run_on(tmp_path, '''\
import threading


class Good:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._bump_locked()

    def _bump_locked(self):
        self._n += 1


class Bad(Good):
    def __init__(self):
        self._lock = threading.Lock()
        self._m = 0

    def locked_write(self):
        with self._lock:
            self._m = 1

    def sneaky(self):
        self._helper()

    def _helper(self):
        self._m = 2
''')
        joined = "\n".join(findings)
        assert "Good" not in joined, joined
        assert "_helper" in joined and "_m" in joined, joined

    def test_condition_aliases_its_lock(self, tmp_path):
        """threading.Condition(self._lock) is the SAME mutex: holding
        the condition's with-block satisfies the lock's guard."""
        findings = self._run_on(tmp_path, '''\
import threading


class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._q = []

    def put(self, x):
        with self._cv:
            self._q.append(x)
            self._cv.notify()

    def take(self):
        with self._lock:
            return self._q.pop(0)
''')
        assert findings == [], "\n".join(findings)

    def test_docstring_contract_and_staleness(self, tmp_path):
        """A declared guard flags lock-free accesses inference alone
        would miss; a contract line naming a never-accessed attribute
        is stale and fails."""
        findings = self._run_on(tmp_path, '''\
import threading


class D:
    """Doc.

    Lock contract (tools/analyze/check_races.py):
        _lock guards: _flag, _ghost
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._flag = False

    def set(self):
        self._flag = True
''')
        joined = "\n".join(findings)
        assert "_flag" in joined and "outside its guard" in joined
        assert "stale lock contract" in joined and "_ghost" in joined

    def test_stale_type_line_flagged(self, tmp_path):
        """A `type:` contract line that resolves to no analyzed class
        silently drops deadlock-graph edges — it must be reported
        stale, like every other rotten pin."""
        findings = self._run_on(tmp_path, '''\
import threading


class T:
    """Doc.

    Lock contract (tools/analyze/check_races.py):
        _lock guards: _n
        peer type: lightgbm_tpu/gone.py:Ghost
    """

    def __init__(self, peer):
        self._lock = threading.Lock()
        self._n = 0
        self.peer = peer

    def tick(self):
        with self._lock:
            self._n += 1
            self.peer.tock()
''')
        joined = "\n".join(findings)
        assert "stale lock contract" in joined and "Ghost" in joined

    def test_multi_writer_without_lock_flagged(self, tmp_path):
        findings = self._run_on(tmp_path, '''\
import threading


class M:
    def __init__(self):
        self._lock = threading.Lock()   # owns a lock -> reported on
        self._count = 0

    def a(self):
        self._count += 1

    def b(self):
        self._count -= 1
''')
        joined = "\n".join(findings)
        assert "_count" in joined and "2 methods with no lock" \
            in joined


# -- purity lint ------------------------------------------------------------

class TestPurityLintTamper:
    def test_injected_np_sum_fails(self, tmp_path):
        """Negative control: np.* compute on a traced value inside the
        forest-walk body must fail the driver."""
        root = _copy_pkg(tmp_path)
        p = os.path.join(root, "predict_device.py")
        src = open(p).read()
        probe = ("    n = binned.shape[0]\n"
                 "    t = split_feature.shape[0]\n"
                 "    node = jnp.zeros((n, t), jnp.int32)")
        assert probe in src
        src = src.replace(probe,
                          "    n = binned.shape[0]\n"
                          "    t = split_feature.shape[0]\n"
                          "    import numpy as np\n"
                          "    _bad = np.sum(binned)\n"
                          "    node = jnp.zeros((n, t), jnp.int32)")
        open(p, "w").write(src)
        out = _run_lint("--only", "purity", "--package-root", root)
        assert out.returncode == 1, out.stdout + out.stderr
        assert "np.sum" in out.stderr
        assert "_forest_walk" in out.stderr

    def test_stale_purity_pin_rejected(self, tmp_path):
        allow = tmp_path / "purity.txt"
        allow.write_text("lightgbm_tpu/predict_device.py | ghost | "
                         "np.sum | gone\n")
        out = _run_lint("--only", "purity",
                        "--purity-allowlist", str(allow))
        assert out.returncode == 1
        assert "stale purity allowlist entry" in out.stderr

    def test_traced_reachability_covers_the_hot_paths(self):
        """The reachable-function inference must cover the grower, the
        fused chunk, the forest walk and the fused serve program — the
        bodies the issue names; an indexing regression that loses them
        would green-wash the whole pass."""
        from analyze import check_purity
        reach = set(check_purity.reachable_functions())
        for needle in (
                "lightgbm_tpu/grower.py:make_grower.grow_tree",
                "lightgbm_tpu/models/gbdt.py:"
                "GBDTModel._fused_chunk_fn.chunk",
                "lightgbm_tpu/predict_device.py:_forest_walk",
                "lightgbm_tpu/predict_device.py:fused_forest_predict",
                "lightgbm_tpu/ops/histogram.py:compute_histogram",
        ):
            assert any(r.startswith(needle) for r in reach), \
                (needle, sorted(reach)[:40])


# -- sync lint through the driver -------------------------------------------

class TestSyncLintTamper:
    def test_injected_raw_sync_fails(self, tmp_path):
        root = _copy_pkg(tmp_path)
        p = os.path.join(root, "serve", "registry.py")
        src = open(p).read()
        src = src.replace(
            "import threading\nimport time",
            "import threading\nimport time\n\n\n"
            "def _bad_sync(x):\n"
            "    import jax\n"
            "    return jax.device_get(x)")
        open(p, "w").write(src)
        out = _run_lint("--only", "syncs", "--package-root", root)
        assert out.returncode == 1, out.stdout + out.stderr
        assert "device_get" in out.stderr


# -- retrace pass through the driver ----------------------------------------

class TestRetraceViaDriver:
    """The expensive pass: each test re-runs the canonical matrix in a
    subprocess (warm compile cache ~15 s), so the sensitivity checks
    are slow-marked like the existing test_zretrace tampers; the green
    run is already covered by TestUnifiedDriver."""

    @pytest.mark.slow
    def test_budget_breach_fails(self, tmp_path):
        import re
        budget = os.path.join(REPO, "tools", "retrace_budget.txt")
        tampered = tmp_path / "budget.txt"
        text = open(budget).read()
        text = re.sub(r"leaf_sweep.grower = \d+",
                      "leaf_sweep.grower = 0", text)
        tampered.write_text(text + "ghost.scenario = 9\n")
        out = _run_lint("--only", "retraces", "--budget",
                        str(tampered), timeout=600)
        assert out.returncode == 1
        assert "trace budget violated: leaf_sweep.grower" in out.stderr
        assert "stale budget entry" in out.stderr

    @pytest.mark.slow
    def test_update_repin_round_trip(self, tmp_path):
        """--update writes a budget the very next run is green
        against."""
        budget = tmp_path / "budget.txt"
        up = _run_lint("--only", "retraces", "--update",
                       "--budget", str(budget), timeout=600)
        assert up.returncode == 0, up.stdout + up.stderr
        assert budget.exists() and "leaf_sweep.grower" \
            in budget.read_text()
        green = _run_lint("--only", "retraces", "--budget",
                          str(budget), timeout=600)
        assert green.returncode == 0, green.stdout + green.stderr


# -- lintlib units ----------------------------------------------------------

class TestLintlib:
    def test_parse_pins_rationale_enforced(self, tmp_path):
        from analyze import lintlib
        f = tmp_path / "pins.txt"
        f.write_text("# comment\na.py | X.y | attr | because\n")
        [(key, why)] = lintlib.parse_pins(str(f), 3,
                                          require_rationale=True)
        assert key == ("a.py", "X.y", "attr") and why == "because"
        f.write_text("a.py | X.y | attr |\n")
        with pytest.raises(ValueError, match="malformed pin"):
            lintlib.parse_pins(str(f), 3, require_rationale=True)

    def test_stale_pins_and_kv_round_trip(self, tmp_path):
        from analyze import lintlib
        stale = lintlib.stale_pins({("a",), ("b",)}, {("a",)}, "zzz")
        assert stale == ["stale zzz entry (no matching finding): b"]
        p = str(tmp_path / "kv.txt")
        lintlib.write_kv_int({"x.y": 3, "a.b": 1}, p, ["# hdr"])
        assert lintlib.load_kv_int(p) == {"x.y": 3, "a.b": 1}

    def test_rel_to_root_is_copy_stable(self, tmp_path):
        """The path convention that makes tamper copies match the real
        allowlists: rel is computed against the PARENT of the scanned
        root, so <tmp>/lightgbm_tpu/serve/x.py pins identically to the
        real tree."""
        from analyze import lintlib
        root = tmp_path / "lightgbm_tpu"
        (root / "serve").mkdir(parents=True)
        f = root / "serve" / "x.py"
        f.write_text("pass\n")
        assert lintlib.rel_to_root(str(f), str(root)) == \
            os.path.join("lightgbm_tpu", "serve", "x.py")


# -- regression tests for the races this PR fixed ---------------------------

class TestRaceFixRegressions:
    def test_served_model_inflight_is_consistent_under_threads(self):
        """registry.py fix: the in-flight counter's reads take _iflock;
        N threads bracketing begin/end must land on exactly zero, and
        concurrent describe() must never crash or report < 0."""
        from lightgbm_tpu.serve.registry import ModelRegistry
        bst, _x = _train_tiny()
        reg = ModelRegistry(build_engine=False)
        v = reg.load(booster=bst)
        served = reg.get(v)
        errs = []

        def worker():
            try:
                for _ in range(300):
                    served.begin_request()
                    assert served.inflight >= 1
                    d = served.describe()
                    assert d["inflight"] >= 0
                    served.end_request()
            except BaseException as e:   # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=worker) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert not errs, errs
        assert served.inflight == 0

    def test_server_version_counter_survives_concurrent_reloads(self):
        """server.py fix: _versions_loaded += 1 races from HTTP handler
        threads were lost updates; under the lock the count is exact."""
        from lightgbm_tpu.serve.server import Server
        bst, _x = _train_tiny()
        srv = Server({"verbosity": -1, "serve_max_wait_ms": 0.0},
                     booster=bst)
        try:
            per, n = 25, 6
            errs = []

            def reloader():
                try:
                    for _ in range(per):
                        srv.reload(booster=bst)
                except BaseException as e:   # noqa: BLE001
                    errs.append(e)

            ts = [threading.Thread(target=reloader) for _ in range(n)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(60)
            assert not errs, errs
            with srv._lock:
                got = srv._versions_loaded
            assert got == 1 + per * n
        finally:
            srv.close()

    def test_continual_freshness_readable_during_generation(
            self, tmp_path):
        """continual.py fix: the freshness surface (generation, chunk
        stamp, promote stamp) is lock-guarded, so an HTTP-style reader
        polling freshness_lag_s()/generation during a generation never
        sees a torn pair (a negative lag) and never crashes."""
        from lightgbm_tpu.pipeline.continual import ContinualTrainer
        rs = np.random.RandomState(1)
        x = rs.randn(400, 6)
        y = (x[:, 0] - x[:, 1] + 0.2 * rs.randn(400) > 0) \
            .astype("float64")
        out_model = str(tmp_path / "m.txt")
        params = {"objective": "binary", "num_leaves": 7,
                  "verbosity": -1, "min_data_in_leaf": 5,
                  "max_bin": 15, "output_model": out_model,
                  "continual_rounds": 2, "shadow_probe_batches": 2}
        ct = ContinualTrainer(params, x[:200], y[:200])
        stop = threading.Event()
        errs = []

        def reader():
            try:
                while not stop.is_set():
                    lag = ct.freshness_lag_s()
                    assert lag is None or lag >= 0, lag
                    assert ct.generation >= 0
                    # the /freshness surface: ONE-lock snapshot means
                    # the publish record can never be torn against the
                    # generation counter (standalone versions are
                    # genN with N == generation)
                    snap = ct.freshness_snapshot()
                    lp = snap["last_publish"]
                    if lp is not None:
                        assert lp["version"] == \
                            f"gen{snap['generation']}", snap
            except BaseException as e:   # noqa: BLE001
                errs.append(e)

        t = threading.Thread(target=reader)
        t.start()
        try:
            r1 = ct.run_generation(x[200:300], y[200:300])
            r2 = ct.run_generation(x[300:], y[300:])
        finally:
            stop.set()
            t.join(30)
        assert not errs, errs
        assert r1["status"] == "published", r1
        assert r2["status"] == "published", r2
        assert ct.generation == 2


# -- dynamic corroboration: hot-swap + drain storm --------------------------

@pytest.mark.stress
class TestConcurrencyStress:
    def test_hot_swap_drain_storm(self):
        """Hammer a live Server from N client threads while a reloader
        thread hot-swaps versions, then drain: every accepted request
        is answered (correct row count), refusals are the typed drain/
        closed errors only, and the drain leaves nothing queued — the
        dynamic counterpart of the statically-checked lock discipline
        in batcher/registry/server."""
        from lightgbm_tpu.serve.batcher import (BatcherClosed,
                                                BatcherDraining)
        from lightgbm_tpu.serve.server import Server
        bst_a, x = _train_tiny(seed=0)
        bst_b, _ = _train_tiny(seed=1, learning_rate=0.2)
        srv = Server({"verbosity": -1, "serve_max_batch": 64,
                      "serve_max_wait_ms": 0.5}, booster=bst_a)
        stop = threading.Event()
        errs: list = []
        answered = [0]
        refused = [0]

        def client(i):
            rs = np.random.RandomState(i)
            try:
                while not stop.is_set():
                    n = int(rs.randint(1, 9))
                    rows = x[rs.randint(0, len(x), n)]
                    try:
                        out = srv.predict(rows, timeout=30)
                    except (BatcherDraining, BatcherClosed):
                        refused[0] += 1
                        continue
                    assert len(np.atleast_1d(out)) == n
                    answered[0] += 1
            except BaseException as e:   # noqa: BLE001
                errs.append(e)

        def reloader():
            try:
                k = 0
                while not stop.is_set():
                    srv.reload(booster=[bst_a, bst_b][k % 2])
                    k += 1
            except BaseException as e:   # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        threads.append(threading.Thread(target=reloader))
        for t in threads:
            t.start()
        import time
        time.sleep(1.5)
        # drain while the storm is still submitting: late submissions
        # must refuse with BatcherDraining, accepted work must finish
        report = srv.drain(timeout_s=20)
        stop.set()
        for t in threads:
            t.join(30)
        try:
            assert not errs, errs
            assert answered[0] > 0
            assert report["drained"] is True, report
            assert report["leftover_rows"] == 0, report
            health = srv.health()
            assert health["status"] == "draining"
        finally:
            srv.close()
