"""Unified static-analysis driver: one entry point, one exit code.

Runs the whole lint family (docs/Static-Analysis.md) over the tree:

- **races**    — lock-discipline race lint for the threaded
  serve/continual stack (tools/analyze/check_races.py;
  tools/race_allowlist.txt)
- **purity**   — jit-purity lint over every function reachable inside
  a traced body (tools/analyze/check_purity.py;
  tools/purity_allowlist.txt)
- **syncs**    — raw host-sync lint (tools/check_syncs.py;
  tools/sync_allowlist.txt)
- **faultsites** — fault-injection-site coverage lint: every declared
  ``utils/faultinject.KNOWN_SITES`` entry is wired in the package and
  exercised by a test/soak (tools/analyze/check_faultsites.py;
  tools/faultsite_allowlist.txt)
- **retraces** — retrace-budget lint; runs the canonical training/serve
  matrix on CPU, so it costs ~15 s warm (tools/check_retraces.py;
  tools/retrace_budget.txt, the one pass ``--update`` re-pins)

Tier-1 invokes ``python tools/lint.py`` once (tests/test_zlint.py)
instead of separate sync/retrace invocations.  Exit code 0 only when
EVERY selected pass is clean; every pass shares the lintlib pin-file
conventions — mandatory rationales on race/purity allowlists, stale
entries are errors everywhere.

Usage::

    python tools/lint.py                     # all four passes
    python tools/lint.py --only races,purity # static passes only
    python tools/lint.py --only retraces --update   # re-pin budget
    python tools/lint.py --package-root /tmp/copy/lightgbm_tpu \
        --only races                         # tamper tests
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Callable, Dict, List, Tuple

TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, TOOLS)
from analyze import (check_faultsites, check_purity,     # noqa: E402
                     check_races, lintlib)

PASSES = ("races", "purity", "syncs", "faultsites", "retraces")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--only", default=",".join(PASSES),
                    help="comma-separated subset of: "
                         + ", ".join(PASSES))
    ap.add_argument("--package-root", default=lintlib.PACKAGE,
                    help="package tree to lint (tamper tests point "
                         "this at a modified copy)")
    ap.add_argument("--race-allowlist", default=check_races.ALLOWLIST)
    ap.add_argument("--purity-allowlist",
                    default=check_purity.ALLOWLIST)
    ap.add_argument("--sync-allowlist", default=None)
    ap.add_argument("--budget", default=None,
                    help="retrace budget file override")
    ap.add_argument("--update", action="store_true",
                    help="re-pin the retrace budget from this run "
                         "(the only pass with measured pins; "
                         "allowlists are hand-edited, rationale "
                         "required)")
    args = ap.parse_args(argv)

    selected = [p.strip() for p in args.only.split(",") if p.strip()]
    unknown = sorted(set(selected) - set(PASSES))
    if unknown:
        print(f"unknown pass(es): {', '.join(unknown)} "
              f"(valid: {', '.join(PASSES)})", file=sys.stderr)
        return 2

    root = args.package_root
    if "retraces" in selected and os.path.abspath(root) \
            != os.path.abspath(lintlib.PACKAGE):
        # the retrace pass imports and MEASURES the installed package;
        # silently linting the real tree while the AST passes lint the
        # copy would green-wash a planted retrace regression
        print("--package-root does not apply to the retraces pass "
              "(it measures the installed package); use "
              "--only races,purity,syncs with a package copy",
              file=sys.stderr)
        return 2

    def run_syncs() -> List[str]:
        import check_syncs
        return check_syncs.find_raw_syncs(
            root, args.sync_allowlist or check_syncs.ALLOWLIST)

    def run_retraces() -> List[str]:
        import check_retraces
        return check_retraces.run_lint(
            args.budget or check_retraces.BUDGET, update=args.update)

    runners: Dict[str, Tuple[Callable[[], List[str]], str]] = {
        "races": (lambda: check_races.run(root, args.race_allowlist),
                  "take the lock, declare the class lock contract, or "
                  "pin in tools/race_allowlist.txt"),
        "purity": (lambda: check_purity.run(root,
                                            args.purity_allowlist),
                   "move the effect out of the traced body, or pin in "
                   "tools/purity_allowlist.txt"),
        "syncs": (run_syncs,
                  "route fences through obs.trace.fence, or pin in "
                  "tools/sync_allowlist.txt"),
        "faultsites": (lambda: check_faultsites.run(root),
                       "exercise the site from a test/soak, drop it "
                       "from KNOWN_SITES, or pin with a rationale in "
                       "tools/faultsite_allowlist.txt"),
        "retraces": (run_retraces,
                     "if intentional, re-pin with `python tools/lint.py"
                     " --only retraces --update`"),
    }

    # cheap AST passes first; the retrace pass (trains the canonical
    # matrix) last so a red static pass fails fast
    order = [p for p in PASSES if p in selected]
    failed: List[str] = []
    for name in order:
        fn, hint = runners[name]
        try:
            findings = fn()
        except Exception as e:      # noqa: BLE001 — a crashed pass is
            # a finding, not a free pass; carry the traceback so the
            # failing construct is locatable from the tier-1 log alone
            import traceback
            findings = [f"lint pass crashed: {type(e).__name__}: {e}",
                        *traceback.format_exc().rstrip().splitlines()]
        if findings:
            failed.append(name)
            print(f"[{name}] {len(findings)} finding(s):",
                  file=sys.stderr)
            for f in findings:
                print(f"  {f}", file=sys.stderr)
            print(f"[{name}] hint: {hint}", file=sys.stderr)
        else:
            print(f"[{name}] clean")
    if failed:
        print(f"\nlint: FAILED ({', '.join(failed)})", file=sys.stderr)
        return 1
    print(f"lint: all passes clean ({', '.join(order)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
