"""Cluster orchestration: the reference's Dask-layer analog, TPU-shaped.

The reference orchestrates multi-machine training from Python with
dask.py (/root/reference/python-package/lightgbm/dask.py:393-810
``_train``: find each worker's data parts, allocate one port per worker
machine, build the ``machines=ip1:port1,ip2:port2`` parameter, then run
one trainer per worker wired through ``LGBM_NetworkInit``).  A TPU
cluster's unit of scheduling is a process per host over a device mesh,
so the analog here has two halves:

- :func:`run` — the *launcher* (dask._train's port-allocation and
  process bring-up role, shaped like torchrun): spawns N coordinated
  worker processes on this machine (or emits the per-host command lines
  for a real multi-host cluster), each bootstrapped through
  ``parallel.launch.init`` with the machines-parameter conventions.
- :func:`train` — the *per-worker trainer* (dask._train_part's role):
  an SPMD entry every process calls identically; it shards rows, fits
  globally-consistent bin mappers (sharded FindBin + allgather,
  parallel/dist_data.py), constructs the local Dataset and trains with
  ``tree_learner=data`` over the global mesh.  On a TPU pod slice, call
  :func:`train` directly from your per-host script — the JAX runtime is
  the launcher there.

Worker functions are addressed as ``"module:function"`` (the launcher
re-imports them in each spawned process), receive a
:class:`WorkerContext` and may return any picklable result;
:func:`run` returns the per-rank results rank-ordered.
"""

from __future__ import annotations

import os
import pickle
import socket
import subprocess
import sys
import tempfile
import time
from typing import Any, List, NamedTuple, Optional

import numpy as np

from .sklearn import (LGBMClassifier as _SkClassifier,
                      LGBMRanker as _SkRanker,
                      LGBMRegressor as _SkRegressor)


class WorkerContext(NamedTuple):
    """What every spawned worker receives (dask.py passes the same facts
    through its closure: rank via worker address, machines string,
    listen port)."""
    rank: int
    num_workers: int
    machines: str            # "host1:port1,host2:port2" (config.h machines)
    local_listen_port: int


def _free_ports(n: int) -> List[int]:
    """Allocate n distinct free localhost ports (dask.py:_find_n_open_ports
    role)."""
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


def build_machines(hosts: List[str], ports: List[int]) -> str:
    """The reference ``machines`` parameter (config.h; dask.py:700)."""
    return ",".join(f"{h}:{p}" for h, p in zip(hosts, ports))


def run(entry: str, num_workers: int = 2, *,
        hosts: Optional[List[str]] = None,
        base_port: Optional[int] = None,
        backend: str = "cpu",
        args: Any = None,
        rank_args: Optional[List[Any]] = None,
        timeout: int = 600,
        extra_pythonpath: Optional[List[str]] = None) -> List[Any]:
    """Spawn ``num_workers`` coordinated training processes on this
    machine and return their results rank-ordered.

    entry: ``"module:function"`` — imported in each worker; called as
      ``function(ctx)``, ``function(ctx, args)`` when ``args`` given, or
      ``function(ctx, args, rank_args[rank])`` when ``rank_args`` given.
    rank_args: one value PER RANK, serialized separately so each worker
      unpickles only its own (a worker's data partition must not be
      shipped to — or held by — every other worker).
    hosts: one entry per worker for a REAL cluster (the function then
      only prints the per-host command lines — a cluster scheduler, not
      this process, must start them); default localhost spawning.
    backend: "cpu" pins workers to the CPU backend with gloo collectives
      (the test topology; also what the reference's distributed tests
      do over localhost sockets); "" leaves device selection to JAX
      (TPU pod workers).
    """
    if hosts is not None and set(hosts) - {"127.0.0.1", "localhost"}:
        ports = [base_port or 12400] * len(hosts)
        machines = build_machines(hosts, ports)
        lines = [
            f"{sys.executable} -m lightgbm_tpu.distributed "
            f"--entry {entry} --rank {i} --num-workers {len(hosts)} "
            f"--machines {machines}" for i in range(len(hosts))]
        raise SystemExit(
            "multi-host cluster: start one process per host:\n  "
            + "\n  ".join(lines))

    ports = _free_ports(num_workers)
    machines = build_machines(["127.0.0.1"] * num_workers, ports)
    tmp = tempfile.mkdtemp(prefix="lgbm_tpu_dist_")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)           # worker sets its own device count
    if extra_pythonpath:
        env["PYTHONPATH"] = os.pathsep.join(
            list(extra_pythonpath) + [env.get("PYTHONPATH", "")])
    args_path = ""
    if args is not None:
        args_path = os.path.join(tmp, "args.pkl")
        with open(args_path, "wb") as f:
            pickle.dump(args, f)
    rank_args_paths = [""] * num_workers
    if rank_args is not None:
        if len(rank_args) != num_workers:
            raise ValueError(f"rank_args has {len(rank_args)} entries "
                             f"for {num_workers} workers")
        for rank, ra in enumerate(rank_args):
            rank_args_paths[rank] = os.path.join(tmp, f"rank{rank}.pkl")
            with open(rank_args_paths[rank], "wb") as f:
                pickle.dump(ra, f)

    # worker output goes to FILES, not pipes: the workers run coordinated
    # collectives, so blocking on one worker's full pipe buffer would
    # stall its collectives and deadlock the whole cluster
    procs, logs = [], []
    for rank in range(num_workers):
        cmd = [sys.executable, "-m", "lightgbm_tpu.distributed",
               "--entry", entry, "--rank", str(rank),
               "--num-workers", str(num_workers),
               "--machines", machines,
               "--result", os.path.join(tmp, f"r{rank}.pkl"),
               "--backend", backend]
        if args_path:
            cmd += ["--args", args_path]
        if rank_args_paths[rank]:
            cmd += ["--rank-args", rank_args_paths[rank]]
        log = open(os.path.join(tmp, f"r{rank}.log"), "w+")
        logs.append(log)
        procs.append(subprocess.Popen(cmd, env=env, stdout=log,
                                      stderr=subprocess.STDOUT, text=True))
    deadline = time.monotonic() + timeout
    try:
        for p in procs:
            p.wait(timeout=max(deadline - time.monotonic(), 1.0))
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise
    outs = []
    for log in logs:
        log.flush()
        log.seek(0)
        outs.append(log.read())
        log.close()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(
                f"worker {rank} failed (rc={p.returncode}):\n{out[-3000:]}")
    results = []
    for rank in range(num_workers):
        with open(os.path.join(tmp, f"r{rank}.pkl"), "rb") as f:
            results.append(pickle.load(f))
    return results


def train(params: dict, x: np.ndarray, y: Optional[np.ndarray] = None, *,
          weight: Optional[np.ndarray] = None,
          num_boost_round: int = 100,
          shard_rows: bool = True,
          sample_count: int = 200_000,
          valid: Optional[tuple] = None):
    """SPMD per-worker trainer (dask.py:_train_part analog): every
    process calls this identically; returns the (replicated) Booster.

    params may carry the reference's network parameters — ``machines`` +
    ``local_listen_port`` (config.h) — in which case the network is
    initialized here exactly like ``LGBM_NetworkInit``; under :func:`run`
    or on an already-initialized pod that step is a no-op.

    shard_rows: x/y are the GLOBAL arrays and each process keeps its
    contiguous shard (dataset_loader.cpp:203-298 per-rank partition);
    pass False when each process loaded only its own rows already.
    """
    from . import Dataset, train as _engine_train
    from .config import Config
    from .parallel import launch

    p = dict(params)
    machines = str(p.pop("machines", "") or "")
    port = int(p.pop("local_listen_port", 12400) or 12400)
    if machines and not getattr(launch.init, "_done", False):
        # honor the fault-tolerance bring-up params (config.py) here the
        # same way GBDTModel._resolve_mesh does for the mesh claim
        launch.init(machines=machines, local_listen_port=port,
                    retries=int(p.get("dist_init_retries", 2)),
                    timeout_s=float(p.get("dist_init_timeout_s", 300.0)))

    import jax
    pc = jax.process_count()
    if pc > 1:
        p.setdefault("num_machines", pc)
        p.setdefault("tree_learner", "data")
        if shard_rows:
            sh = launch.row_shard(x, y)
            if weight is not None:
                # same deterministic contiguous partition as row_shard
                parts = np.array_split(np.arange(len(x)), pc)
                weight = np.asarray(weight)[parts[sh.process_index]]
        else:
            sh = launch.RowShard(x=x, y=y,
                                 process_index=jax.process_index(),
                                 process_count=pc)
        cfg = Config(dict(p, num_iterations=num_boost_round))
        cat_spec = str(getattr(cfg, "categorical_feature", "") or "")
        cat = {int(t) for t in cat_spec.split(",") if t.strip().isdigit()} \
            or None
        mappers = launch.global_bin_mappers(sh.sample(sample_count), cfg,
                                            cat_idx=cat)
        ds = Dataset(sh.x, label=sh.y, weight=weight, params=p,
                     bin_mappers=mappers)
    else:
        ds = Dataset(x, label=y, weight=weight, params=p)
    kw = {}
    if valid is not None:
        vx, vy = valid
        kw["valid_sets"] = [Dataset(vx, label=vy, params=p, reference=ds)]
    return _engine_train(p, ds, num_boost_round=num_boost_round, **kw)


# ---------------------------------------------------------------------------
# Estimator layer (dask.py:1092-1417 DaskLGBMClassifier/Regressor/Ranker
# analog, minus Dask itself): sklearn-style estimators whose fit() runs
# over a pod of coordinated worker processes via :func:`run`, training
# directly on PRE-PARTITIONED per-worker data (the dask-collection
# partition model) or partitioning a global array for you.

def _fit_worker(ctx: WorkerContext, args: dict, part: tuple):
    """Per-worker fit body (dask.py:_train_part analog): spawned by
    :func:`run` inside an initialized pod with ONLY this rank's data
    part (run's rank_args — no worker ever holds another's partition);
    trains with globally-consistent bin mappers and returns the
    (replicated) model plus fit-result attributes."""
    from . import Dataset, train as _engine_train
    from .callback import record_evaluation
    from .config import Config
    from .parallel import launch
    import jax

    pc = jax.process_count()
    x, y, w, g = part
    p = dict(args["params"])
    p.setdefault("num_machines", pc)
    rounds = args["rounds"]

    cfg = Config(dict(p, num_iterations=rounds))
    # categorical columns participate in the distributed FindBin as
    # categories, mirroring the single-process sklearn path (and
    # distributed.train's cat_idx handling)
    cat_spec = str(getattr(cfg, "categorical_feature", "") or "")
    cat = {int(t) for t in cat_spec.split(",") if t.strip().isdigit()} \
        or None
    k_sample = int(p.get("bin_construct_sample_cnt", 200000))
    if _is_sparse(x):
        x = x.tocsr()
        # densifying the sample is bounded by an ELEMENT budget — the
        # floor is 1 row, not a fixed row count, or the budget would be
        # defeated exactly on the very-wide input it exists for
        # (256 rows x 5M columns is already ~10 GB dense)
        k_sample = min(k_sample,
                       max(1, 50_000_000 // max(1, x.shape[1])))
        sample = x[:k_sample].toarray()
    else:
        sample = np.asarray(x)[:k_sample]
    mappers = launch.global_bin_mappers(sample, cfg, cat_idx=cat)
    ds = Dataset(x, label=y, weight=w, group=g, params=p,
                 bin_mappers=mappers)

    valid_sets, valid_names, evals = [], [], {}
    for i, (vx, vy, vw, vg) in enumerate(args.get("eval_set") or []):
        valid_sets.append(Dataset(vx, label=vy, weight=vw, group=vg,
                                  reference=ds))
        names = args.get("eval_names")
        valid_names.append(names[i] if names else f"valid_{i}")
    cbs = [record_evaluation(evals)] if valid_sets else None
    bst = _engine_train(p, ds, num_boost_round=rounds,
                        valid_sets=valid_sets or None,
                        valid_names=valid_names or None, callbacks=cbs)
    return {"model": bst.model_to_string(),
            "evals": evals,
            "best_iteration": bst.best_iteration,
            "best_score": dict(bst.best_score),
            "n_features": int(x.shape[1])}


def _is_sparse(a) -> bool:
    try:
        import scipy.sparse as sp
        return sp.issparse(a)
    except ImportError:
        return False


def _split_parts(arr, n: int, row_splits: Optional[List[np.ndarray]]):
    """Contiguous per-worker row parts; scipy-sparse matrices pass
    through row-sliced (the Dataset consumes CSR/CSC natively — see
    sparse_data.py — so densifying here would defeat the k-hot binned
    storage on exactly the wide inputs that need it)."""
    if arr is None:
        return [None] * n
    if isinstance(arr, (list, tuple)):
        if len(arr) != n:
            raise ValueError(
                f"pre-partitioned input has {len(arr)} parts for "
                f"{n} workers — one part per worker")
        return [a.tocsr() if _is_sparse(a) else np.asarray(a)
                for a in arr]
    # CSR row-slices/indexes like an ndarray; COO/DOK/BSR do not
    arr = arr.tocsr() if _is_sparse(arr) else np.asarray(arr)
    if row_splits is not None:
        return [arr[idx] for idx in row_splits]
    bounds = np.linspace(0, arr.shape[0], n + 1).astype(int)
    return [arr[bounds[i]:bounds[i + 1]] for i in range(n)]


class _DistLGBMModel:
    """Mixin carrying the distributed fit (dask.py:_DaskLGBMModel role:
    the launcher knobs ride the estimator, fit fans out, the fitted
    state loads back into the plain sklearn estimator)."""

    def _set_dist(self, n_workers: int, backend: str, timeout: int):
        self.n_workers = int(n_workers)
        self._dist_backend = backend
        self._dist_timeout = int(timeout)

    def _encode_eval_label(self, y: np.ndarray) -> np.ndarray:
        """eval_set labels through the same transform as the training
        labels (classifier overrides with the fitted class encoding)."""
        return self._process_label(y)

    def _dist_fit(self, X, y, sample_weight=None, group=None,
                  eval_set=None, eval_names=None):
        params = self._lgb_params()
        tl = params.setdefault("tree_learner", "data")
        if tl == "feature":
            raise ValueError(
                "the estimator layer partitions ROWS across workers; "
                "tree_learner=feature replicates rows and shards "
                "features — use lightgbm_tpu.distributed.train directly "
                "for that topology, or tree_learner=data|voting here")
        n = self.n_workers
        pre_partitioned = isinstance(X, (list, tuple))
        row_splits = None
        if not pre_partitioned and group is not None:
            # partition at query boundaries (dask requires group-aligned
            # partitions the same way, dask.py _train group handling)
            sizes = np.asarray(group, np.int64)
            if len(sizes) < n:
                raise ValueError(
                    f"cannot partition {len(sizes)} query groups across "
                    f"{n} workers — every worker needs at least one "
                    "whole group (reduce n_workers)")
            bounds = np.concatenate([[0], np.cumsum(sizes)])
            gsplil = np.array_split(np.arange(len(sizes)), n)
            row_splits = [np.arange(bounds[gi[0]], bounds[gi[-1] + 1])
                          for gi in gsplil]
            group = [sizes[gi] for gi in gsplil]
        xp = _split_parts(X, n, row_splits)
        yp = _split_parts(y, n, row_splits)
        wp = _split_parts(sample_weight, n, row_splits)
        gp = _split_parts(group, n, None) if group is not None \
            else [None] * n
        evs = None
        if eval_set:
            evs = []
            for tup in eval_set:
                vx, vy = tup[0], tup[1]
                vx = vx.tocsr() if _is_sparse(vx) else np.asarray(vx)
                evs.append((vx,
                            self._encode_eval_label(np.asarray(vy)), None,
                            None))
        args = {"params": params, "rounds": self.n_estimators,
                "eval_set": evs, "eval_names": eval_names}
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        results = run("lightgbm_tpu.distributed:_fit_worker",
                      num_workers=n, backend=self._dist_backend,
                      args=args,
                      rank_args=[(xp[i], yp[i], wp[i], gp[i])
                                 for i in range(n)],
                      timeout=self._dist_timeout,
                      extra_pythonpath=[repo_root])
        r0 = results[0]
        from .booster import Booster
        self._Booster = Booster(model_str=r0["model"])
        self._n_features = r0["n_features"]
        self.best_iteration_ = r0["best_iteration"]
        self.best_score_ = r0["best_score"]
        self._evals_result = r0["evals"]
        self.fitted_ = True
        self.n_iter_ = (self.best_iteration_
                        if self.best_iteration_ and self.best_iteration_ > 0
                        else self._Booster.current_iteration)
        self.objective_ = params.get("objective")
        return self

    def to_local(self):
        """The plain single-process estimator carrying the fitted model
        (dask.py to_local analog)."""
        from . import sklearn as _sk
        cls = getattr(_sk, type(self).__name__.replace("Distributed", ""))
        local = cls(**self.get_params())
        for attr in ("_Booster", "_n_features", "_classes", "_n_classes",
                     "best_iteration_", "best_score_", "_evals_result",
                     "fitted_", "n_iter_", "objective_"):
            if hasattr(self, attr):
                setattr(local, attr, getattr(self, attr))
        return local


class DistributedLGBMRegressor(_DistLGBMModel, _SkRegressor):
    """Distributed version of LGBMRegressor (dask.py:1268
    DaskLGBMRegressor analog): ``fit(X, y)`` trains over ``n_workers``
    coordinated processes; ``X``/``y`` may be global arrays (partitioned
    for you) or lists of per-worker parts (pre-distributed data)."""

    def __init__(self, *args, n_workers: int = 2, backend: str = "cpu",
                 timeout: int = 600, **kwargs):
        super().__init__(*args, **kwargs)
        self._set_dist(n_workers, backend, timeout)

    def fit(self, X, y, sample_weight=None, eval_set=None,
            eval_names=None, **_):
        y = [np.asarray(p, np.float32) for p in y] \
            if isinstance(y, (list, tuple)) \
            else np.asarray(y, np.float32)
        return self._dist_fit(X, y, sample_weight=sample_weight,
                              eval_set=eval_set, eval_names=eval_names)


class DistributedLGBMClassifier(_DistLGBMModel, _SkClassifier):
    """Distributed version of LGBMClassifier (dask.py:1092 analog)."""

    def __init__(self, *args, n_workers: int = 2, backend: str = "cpu",
                 timeout: int = 600, **kwargs):
        super().__init__(*args, **kwargs)
        self._set_dist(n_workers, backend, timeout)

    def fit(self, X, y, sample_weight=None, eval_set=None,
            eval_names=None, **_):
        parts = isinstance(y, (list, tuple))
        sizes = [len(p) for p in y] if parts else None
        y_all = np.concatenate([np.asarray(p) for p in y]) if parts \
            else np.asarray(y)
        self._classes, y_enc = np.unique(y_all, return_inverse=True)
        self._n_classes = len(self._classes)
        if self._n_classes > 2:
            self._other_params.setdefault("num_class", self._n_classes)
        if isinstance(sample_weight, (list, tuple)):
            # per-part weights concatenate for the (global) class-weight
            # multiply, then re-split with the labels below
            sample_weight = np.concatenate(
                [np.asarray(p) for p in sample_weight])
        w = self._class_weights(sample_weight, y_enc)
        y_enc = y_enc.astype(np.float32)
        if parts:
            cuts = np.cumsum(sizes)[:-1]
            y_enc = list(np.split(y_enc, cuts))
            if w is not None:
                w = list(np.split(np.asarray(w), cuts))
        return self._dist_fit(X, y_enc, sample_weight=w,
                              eval_set=eval_set, eval_names=eval_names)

    def _encode_eval_label(self, y: np.ndarray) -> np.ndarray:
        idx = np.searchsorted(self._classes, y)
        idx = np.clip(idx, 0, len(self._classes) - 1)
        if not np.array_equal(self._classes[idx], y):
            raise ValueError(
                "eval_set contains labels not present in the training "
                f"classes {list(self._classes)}")
        return idx.astype(np.float32)


class DistributedLGBMRanker(_DistLGBMModel, _SkRanker):
    """Distributed version of LGBMRanker (dask.py:1417 analog): global
    input is partitioned at query-group boundaries; pre-partitioned
    input takes one ``group`` array per part."""

    def __init__(self, *args, n_workers: int = 2, backend: str = "cpu",
                 timeout: int = 600, **kwargs):
        super().__init__(*args, **kwargs)
        self._set_dist(n_workers, backend, timeout)

    def fit(self, X, y, group=None, sample_weight=None, eval_set=None,
            eval_names=None, **_):
        if group is None:
            raise ValueError("DistributedLGBMRanker requires group")
        y = [np.asarray(p, np.float32) for p in y] \
            if isinstance(y, (list, tuple)) \
            else np.asarray(y, np.float32)
        return self._dist_fit(X, y, sample_weight=sample_weight,
                              group=group, eval_set=eval_set,
                              eval_names=eval_names)


def _main(argv: List[str]) -> None:
    """Worker bootstrap (what ``run`` spawns): init the collective
    runtime BEFORE any backend exists, then hand control to the entry."""
    import argparse
    ap = argparse.ArgumentParser(prog="python -m lightgbm_tpu.distributed")
    ap.add_argument("--entry", required=True)
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--num-workers", type=int, required=True)
    ap.add_argument("--machines", required=True)
    ap.add_argument("--result", default="")
    ap.add_argument("--args", default="")
    ap.add_argument("--rank-args", default="")
    ap.add_argument("--backend", default="cpu")
    ns = ap.parse_args(argv)

    if ns.backend == "cpu":
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        from .utils.compile_cache import enable_persistent_cache
        enable_persistent_cache()

    from .parallel import launch
    entries = [m for m in ns.machines.split(",") if m]
    launch.init(coordinator_address=entries[0],
                num_processes=ns.num_workers, process_id=ns.rank)

    mod_name, fn_name = ns.entry.split(":")
    import importlib
    fn = getattr(importlib.import_module(mod_name), fn_name)
    ctx = WorkerContext(rank=ns.rank, num_workers=ns.num_workers,
                        machines=ns.machines,
                        local_listen_port=int(
                            entries[ns.rank].rsplit(":", 1)[1]))
    shared = None
    if ns.args:
        with open(ns.args, "rb") as f:
            shared = pickle.load(f)
    if ns.rank_args:
        with open(ns.rank_args, "rb") as f:
            result = fn(ctx, shared, pickle.load(f))
    elif ns.args:
        result = fn(ctx, shared)
    else:
        result = fn(ctx)
    if ns.result:
        with open(ns.result, "wb") as f:
            pickle.dump(result, f)


if __name__ == "__main__":
    _main(sys.argv[1:])
