"""Fleet subsystem: vmapped multi-forest training and segment-routed
fleet serving (docs/Fleet.md).

Training (``fleet/trainer.py``): N same-shape boosters — segments, seed
replicas, or a hyperparameter grid — grow inside ONE jitted program by
``jax.vmap``-ping the super-epoch scan (models/gbdt.py PR 16) over a
member axis.  Per-member RNG streams ride as traced arguments, so every
member trains BYTE-IDENTICAL to a solo ``train()`` run with that
member's params; one host fetch per epoch serves all members.

Serving (``fleet/router.py``): per-request ``segment`` keys map to
model versions co-resident in the serve registry; same-family segments
share every serve trace through the existing pow2 SoA padding, so a
hundred-segment fleet adds ZERO new compiled programs.
"""

from .router import SegmentRouter
from .trainer import FleetResult, expand_members, fleet_train, parse_sweep

__all__ = ["FleetResult", "SegmentRouter", "expand_members",
           "fleet_train", "parse_sweep"]
