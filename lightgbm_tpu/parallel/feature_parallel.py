"""Feature-parallel tree learner: split search sharded over features.

TPU-native redesign of the reference FeatureParallelTreeLearner
(/root/reference/src/treelearner/feature_parallel_tree_learner.cpp:13-83):
data is REPLICATED on every shard; each shard builds histograms and scans
thresholds only for its own feature slice; the winning split is agreed via
an all-gather + argmax (the reference's 2-SplitInfo ``SyncUpGlobalBestSplit``
allreduce, parallel_tree_learner.h:191); every shard then applies the split
locally — no row data ever moves.

Implemented as hooks into the shared grower program (grower.py):
``hist_view`` slices this shard's columns, ``select_best`` globalizes the
feature index and reduces candidates across the mesh axis.

Quantized training (``quant``) threads straight through: rows are
replicated, so every shard computes the IDENTICAL per-iteration scale
and rounding stream with no extra collective (global row id == local
row id, ops/quantize.py).

Leaf-budget trace sharing (ROADMAP item 1 remainder): ``padded_leaves``
+ per-call traced ``max_leaves`` + a process-level memo of the jitted
shard_map program, so a ``num_leaves`` sweep inside one bucket runs ONE
feature-parallel grower trace (pinned by tools/check_retraces.py).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..grower import TreeArrays, make_grower
from ..obs.comm import CommLedger
from ..ops.split import SplitParams, SplitResult, gather_best
from ..utils.jax_compat import shard_map
from ..utils.memo import memo_get_or_build

# process-level memo of jitted feature-parallel growers (the voting
# learner's pattern; see parallel/voting_parallel.py)
_SHARED: "OrderedDict[tuple, tuple]" = OrderedDict()
_SHARED_MAX = 16
_SHARED_LOCK = threading.Lock()


def make_fp_grower(mesh: Mesh, *, num_features: int, num_leaves: int,
                   num_bins: int, params: SplitParams, max_depth: int = -1,
                   block_rows: int = 0, axis: str = "feature",
                   split_batch: int = 1, hist_overlap: bool = False,
                   padded_leaves=None, quant=None):
    """Jitted feature-parallel ``grow_tree``.

    Inputs: binned [N, F] and vals replicated; feature metadata arrays
    (feature_mask, num_bin, na_bin) sharded over the feature axis by
    shard_map; ``na_bin_part`` replicated for row partitioning.
    ``num_features`` must be a multiple of the axis size (pad with masked
    dummy features).
    """
    n_shards = mesh.shape[axis]
    if num_features % n_shards != 0:
        raise ValueError(f"num_features {num_features} must divide over "
                         f"{n_shards} shards (pad with masked features)")
    key = (tuple(int(d.id) for d in np.ravel(mesh.devices)), axis,
           int(num_features),
           int(padded_leaves) if padded_leaves else None,
           None if padded_leaves else int(num_leaves),
           int(num_bins), params, int(max_depth), int(block_rows),
           int(split_batch), bool(hist_overlap), quant)
    jitted, ledger = memo_get_or_build(
        _SHARED, _SHARED_LOCK, _SHARED_MAX, key,
        lambda: _build(mesh, num_features=num_features,
                       num_leaves=num_leaves, num_bins=num_bins,
                       params=params, max_depth=max_depth,
                       block_rows=block_rows, axis=axis,
                       split_batch=split_batch,
                       hist_overlap=hist_overlap,
                       padded_leaves=padded_leaves, quant=quant))

    def grow(binned, vals, feature_mask, num_bin, na_bin, na_bin_part=None,
             is_cat=None, max_leaves=None, rng_iter=None):
        if na_bin_part is None:
            na_bin_part = na_bin
        if is_cat is None:
            is_cat = jnp.zeros(num_bin.shape[0], bool)
        ml = jnp.int32(num_leaves if max_leaves is None else max_leaves)
        ri = jnp.int32(0 if rng_iter is None else rng_iter)
        return jitted(binned, vals, feature_mask, num_bin, na_bin,
                      na_bin_part, is_cat, ml, ri)

    grow.comm = ledger
    return grow


def _build(mesh: Mesh, *, num_features, num_leaves, num_bins, params,
           max_depth, block_rows, axis, split_batch, hist_overlap=False,
           padded_leaves=None,
           quant=None):
    n_shards = mesh.shape[axis]
    f_local = num_features // n_shards
    ledger = CommLedger(n_shards)     # static comm-bytes sites (obs/comm)

    def hist_view(binned):
        idx = lax.axis_index(axis)
        return lax.dynamic_slice_in_dim(binned, idx * f_local, f_local,
                                        axis=1)

    def select_best(res: SplitResult) -> SplitResult:
        # contiguous slices globalize by offset; the winner sync is the
        # shared SyncUpGlobalBestSplit allgather (ops/split.gather_best)
        idx = lax.axis_index(axis)
        res = res._replace(feature=res.feature + idx * f_local)
        ledger.note_all_gather(res, site="fp.best_split")
        return gather_best(res, axis)

    inner = make_grower(
        num_leaves=num_leaves, num_bins=num_bins, params=params,
        max_depth=max_depth, block_rows=block_rows,
        hist_view=hist_view, select_best=select_best,
        split_batch=split_batch, hist_overlap=hist_overlap,
        padded_leaves=padded_leaves,
        # rows replicated: identical scales/rounding on every shard —
        # no scale pmax or row offset needed (module docstring)
        quant=quant, jit=False)

    out_specs = jax.tree.map(lambda _: P(), TreeArrays(
        *(0,) * len(TreeArrays._fields)))

    def wrapped(binned, vals, fm, nb, na, nabp, ic, ml, ri):
        return inner(binned, vals, fm, nb, na, nabp, ic, rng_iter=ri,
                     max_leaves=ml)

    f = shard_map(
        wrapped, mesh=mesh,
        in_specs=(P(None, None), P(None, None), P(axis), P(axis), P(axis),
                  P(None), P(axis), P(), P()),
        out_specs=out_specs, check_vma=False)

    return jax.jit(f), ledger
