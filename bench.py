"""Benchmark: HIGGS-shaped binary classification training throughput.

Mirrors the reference's headline experiment (docs/Experiments.rst: HIGGS,
500 iterations, num_leaves=255 -> 130.094 s on 2x E5-2690v4, i.e. 3.843
iters/s; GPU docs recommend 63 bins for accelerator runs,
docs/GPU-Performance.rst:108-124).

Primary metric (round-over-round comparable): steady-state iters/s on a
1M-row slice at 31 leaves / 63 bins; ``vs_baseline`` is against the
reference's full-size 3.843 iters/s.  ``extra`` carries the
baseline-shaped points: strict leaf-wise growth, a 255-leaf run (the
baseline's own tree shape), a 10M-row scaling point, and an
Epsilon-shaped wide point (400k x 2000 dense, GPU-Performance.rst:63).

Capture discipline (VERDICT r3 task 1 — a perf round whose number can't
be captured is a failed perf round):

- The parent first PROBES the TPU claim in a disposable child (the axon
  tunnel is exclusive and can wedge: a killed mid-claim process blocks
  every later ``jax.devices()`` for hours).  A hung probe is diagnosed
  as a wedge and the parent goes STRAIGHT to the CPU fallback instead of
  burning the round's budget on retries that cannot succeed.
- The primary point runs in a child with a HARD 600 s budget; one quick
  retry (300 s) and then the CPU fallback.  Extras run in a SEPARATE
  child afterwards that can die without losing the primary.
- Every measured point is appended to ``BENCH_POINTS.jsonl`` (next to
  this file) the moment it lands, and the primary metric line is printed
  to stdout immediately — a timeout kill loses at most the point in
  flight.  The parent merges file + partial stdout and always emits
  exactly ONE final JSON line {"metric", "value", "unit",
  "vs_baseline"[, "extra"][, "error"]}.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

BASELINE_IPS = 500.0 / 130.094  # reference HIGGS CPU (Experiments.rst:113)
METRIC = "higgs1m_binary_train_iters_per_sec"
N_ROWS, N_FEAT = 1_000_000, 28
PRIMARY_LEAVES, PRIMARY_MAX_BIN = 31, 63
PRIMARY_PADDED_BIN = 64          # ops/histogram.py pads the bin axis to 64
_DIR = os.path.dirname(os.path.abspath(__file__))
# per-run points file (superseded each run); children honor the override
# so the opportunistic capture (tools/tpu_watch.py) can redirect points
# to the durable capture file below
POINTS_FILE = os.environ.get("_BENCH_POINTS_FILE") \
    or os.path.join(_DIR, "BENCH_POINTS.jsonl")
# durable across runs: TPU points captured mid-round by tools/tpu_watch.py
# the moment the tunnel grants a claim.  The end-of-round bench PREFERS a
# point from here over a CPU fallback (VERDICT r4 task 1: one clean TPU
# measurement beats any number of degraded ones).
CAPTURE_FILE = os.environ.get("_BENCH_CAPTURE_FILE") \
    or os.path.join(_DIR, "BENCH_TPU_CAPTURE.jsonl")

PROBE_TIMEOUT = 150              # healthy claims take ~0.1 s (BENCH_r02)
PRIMARY_TIMEOUT = 600            # hard cap, VERDICT r3 task 1
QUICK_TIMEOUT = 300
EXTRAS_TIMEOUT = 900
CPU_TIMEOUT = 420

# FLOP accounting and the per-device peak table now live in the library
# (obs/flops.py formulas + obs/attrib.py PEAKS — the measurement
# substrate telemetry, serving and this bench all share); the private
# _hist_flops_per_iter / PEAK_FLOPS copies this file used to carry are
# gone.  Children import them lazily (the parent must never touch jax).

_PROVENANCE = None


def _provenance():
    """Self-describing point metadata (device, library versions, host,
    git sha) so BENCH_*.json files can be compared across rounds by
    tools/bench_diff.py without external context.  Device fields are
    included only when jax is ALREADY imported — the parent process
    must never trigger a TPU claim for bookkeeping."""
    global _PROVENANCE
    if _PROVENANCE is None:
        import platform
        prov = {"hostname": platform.node(), "py": platform.python_version()}
        try:
            out = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                                 capture_output=True, text=True, cwd=_DIR,
                                 timeout=10)
            if out.returncode == 0:
                prov["git_sha"] = out.stdout.strip()
        except (OSError, subprocess.SubprocessError):
            pass
        _PROVENANCE = prov
    prov = dict(_PROVENANCE)
    if "jax" in sys.modules:          # imported by a measurement child
        jax = sys.modules["jax"]
        prov["jax"] = getattr(jax, "__version__", "?")
        try:
            import jaxlib
            prov["jaxlib"] = getattr(jaxlib, "__version__", "?")
        except ImportError:
            pass
        try:
            from importlib import metadata as _md
            for dist in ("libtpu", "libtpu-nightly"):
                try:
                    prov["libtpu"] = _md.version(dist)
                    break
                except _md.PackageNotFoundError:
                    continue
        except Exception:
            pass
        try:
            devs = jax.devices()      # already claimed by this child
            prov["device_kind"] = devs[0].device_kind
            prov["device_count"] = len(devs)
        except Exception:
            pass
    return prov


def _record_point(name, **kv):
    """Append one measured point to the results file IMMEDIATELY (crash /
    timeout safe) and mirror it to stderr for the log tail.  Every
    point carries its provenance (device + versions + git sha) so the
    file is self-describing for tools/bench_diff.py."""
    rec = {"point": name, "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
           "prov": _provenance(), **kv}
    try:
        with open(POINTS_FILE, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError as e:
        print(f"[bench] point-file write failed: {e}", file=sys.stderr)
    print(f"[bench] point {rec}", file=sys.stderr, flush=True)


def make_higgs_like(n: int, f: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float32)
    logit = (1.2 * x[:, 0] - 0.8 * x[:, 1] + 0.6 * x[:, 2] * x[:, 3]
             + 0.4 * np.abs(x[:, 4]) + 0.5 * rng.randn(n))
    y = (logit > 0).astype(np.float32)
    return x, y


def make_epsilon_like(n: int, f: int, seed: int = 3):
    """Epsilon-shaped wide dense data (400k x 2000), generated in f32
    row-chunks so the host never holds an f64 copy (~6.4 GB)."""
    rng = np.random.RandomState(seed)
    x = np.empty((n, f), dtype=np.float32)
    chunk = max(1, 50_000_000 // f)
    for lo in range(0, n, chunk):
        hi = min(n, lo + chunk)
        x[lo:hi] = rng.standard_normal((hi - lo, f)).astype(np.float32)
    w = rng.standard_normal(16).astype(np.float32)
    logit = x[:, :16] @ w + 0.5 * rng.standard_normal(n).astype(np.float32)
    y = (logit > 0).astype(np.float32)
    return x, y


def _train_point(lgb, x, y, num_leaves, chunk, n_chunks, tag, ds=None,
                 split_batch=0, max_bin=PRIMARY_MAX_BIN, learner=None):
    """Train one config; returns (ips, auc, ds, steps) steady-state over
    n_chunks fused chunks (or per-iter updates when fusion is
    unavailable).  ``steps`` is the per-tree grower loop count
    (super-steps for split_batch>1) from the last chunk.  Pass ``ds`` to
    reuse an already-binned dataset (num_leaves is a Booster param;
    binning is identical across points on the same data).
    split_batch: 0 = config auto (strict below 64 leaves, batched above),
    explicit K pins the grower's super-step width (grower.py).
    learner: pin tpu_learner (CPU fallback auto-selects the partitioned
    host-driven learner, which never batches splits — pass "masked" to
    measure the super-step path on CPU).

    The returned ``stats`` dict carries the first-class compile
    metrics (ROADMAP item 4): ``compile_s`` — wall time of the first
    chunk/iteration including XLA trace+compile (warm-started by the
    persistent cache when enabled), ``trace_count`` — library jit
    traces this point added, and the process compile/cache counters
    delta (utils/compile_cache.py)."""
    from lightgbm_tpu.utils.compile_cache import compile_stats, trace_total
    params = {
        "objective": "binary", "num_leaves": num_leaves,
        "learning_rate": 0.1, "max_bin": max_bin,
        "min_data_in_leaf": 20, "verbosity": 0,
        "split_batch": split_batch,
    }
    if learner:
        params["tpu_learner"] = learner
    t0 = time.time()
    if ds is None:
        ds = lgb.Dataset(x, label=y, params=params)
        ds.construct()
    t_bin = time.time() - t0

    traces0, cs0 = trace_total(), compile_stats()
    bst = lgb.Booster(params=dict(params, fused_chunk=chunk),
                      train_set=ds)
    m = bst._model
    fused = m.supports_fused() and chunk > 1

    t0 = time.time()
    if fused:
        m.train_chunk(chunk)          # includes XLA compile
    else:
        bst.update()
    np.asarray(m.score)
    t_compile = time.time() - t0

    t0 = time.time()
    start_iter = m.iter_
    if fused:
        for _ in range(n_chunks):
            if m.train_chunk(chunk):
                break                 # no-split stop: count only real iters
    else:
        for _ in range(n_chunks * chunk):
            if bst.update():
                break
    np.asarray(m.score)               # hard sync
    dt = time.time() - t0
    iters = m.iter_ - start_iter
    ips = iters / max(dt, 1e-9)
    cs1 = compile_stats()
    stats = {
        "compile_s": round(t_compile, 2),
        "trace_count": trace_total() - traces0,
        "backend_compiles": cs1["count"] - cs0["count"],
        "compile_cache_hits": cs1["cache_hits"] - cs0["cache_hits"],
    }
    if not fused:
        # provenance: WHY this point measured the per-iteration path
        # (GBDTModel.fused_reasons — specific blockers, never a guess)
        stats["fused_reasons"] = "; ".join(m.fused_reasons())[:200]

    from lightgbm_tpu.metrics import _auc
    auc = _auc(y, np.asarray(m.train_score())[:, 0], None)
    steps = m.step_counts[-min(len(m.step_counts), 8):]
    print(f"[bench] {tag}: bin={t_bin:.1f}s compile+warm={t_compile:.1f}s "
          f"(traces={stats['trace_count']}, "
          f"cache_hits={stats['compile_cache_hits']}) "
          f"steady={dt:.1f}s/{iters} iters -> {ips:.3f} iters/s "
          f"(train-AUC={auc:.4f}, fused={fused}, steps/tree={steps[-1] if steps else '?'})",
          file=sys.stderr, flush=True)
    return ips, auc, ds, steps, stats


def _claim_device(cpu: bool):
    print("[bench] importing jax / claiming device...", file=sys.stderr,
          flush=True)
    t_dev = time.time()
    import jax
    if cpu:
        # in-process override, NOT the JAX_PLATFORMS env var: the axon
        # sitecustomize pins the platform config at interpreter start, so
        # the env var is ignored and jax.devices() would still try to
        # claim the (possibly wedged) TPU tunnel; jax.config.update is
        # the supported escape (same pattern as tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    print(f"[bench] devices={devs} ({time.time() - t_dev:.1f}s)",
          file=sys.stderr, flush=True)
    return devs


def child_probe() -> None:
    """Disposable TPU-claim probe: prints a marker line on success."""
    devs = _claim_device(cpu=False)
    print(f"PROBE_OK {devs[0].device_kind}", flush=True)


def child_primary() -> None:
    """The primary measurement; prints the JSON metric line ASAP."""
    quick = os.environ.get("_BENCH_QUICK") == "1"
    cpu = os.environ.get("_BENCH_CPU") == "1"
    devs = _claim_device(cpu=cpu)
    import lightgbm_tpu as lgb

    n = N_ROWS if not cpu else N_ROWS // 10
    x, y = make_higgs_like(n, N_FEAT)

    # primary: 1M x 28, 31 leaves, 8-way batched super-steps (the
    # framework's fast growth mode; AUC reported alongside so quality is
    # auditable against the strict point below)
    ips1, auc1, ds1, steps1, stats1 = _train_point(
        lgb, x, y, num_leaves=PRIMARY_LEAVES,
        chunk=4 if quick else 25, n_chunks=1 if quick else 4,
        tag="1M/31leaf/sb8", split_batch=8)
    rec = {
        "metric": METRIC,
        "value": round(ips1, 3),
        "unit": ("iters/s (1M rows x 28 feat, 31 leaves, 63 bins, "
                 "split_batch=8)"),
        # vs_baseline is only meaningful at the baseline's own data size;
        # the reduced CPU-fallback shape nulls it instead of reporting a
        # misleading ratio (VERDICT r4 weak #5)
        "vs_baseline": round(ips1 / BASELINE_IPS, 3) if not cpu else None,
    }
    if cpu:
        rec["unit"] += f" [CPU fallback, {n} rows]"
    # roofline attribution from the library ledger (obs/flops.py /
    # obs/attrib.py — the same formulas telemetry_snapshot uses):
    # achieved histogram FLOP/s, MFU against the claimed device's peak,
    # and the static per-phase FLOP share — first-class in the point
    from lightgbm_tpu.obs.attrib import device_peaks
    from lightgbm_tpu.obs.flops import (FlopLedger,
                                        train_hist_flops_per_iter)
    achieved = train_hist_flops_per_iter(
        n, N_FEAT, PRIMARY_MAX_BIN, PRIMARY_LEAVES) * ips1
    peak, _bw = device_peaks(devs)
    mfu = round(achieved / peak, 4) if peak else None
    share = FlopLedger.for_training(
        n, N_FEAT, PRIMARY_MAX_BIN, split_batch=8).flop_share(
        steps1[-1] if steps1 else PRIMARY_LEAVES - 1)
    # persist + emit the primary record NOW: a later timeout kill (or a
    # hang in the strict point) must not discard it
    _record_point("primary", auc=round(float(auc1), 4), cpu=cpu,
                  steps_per_tree=steps1[-1] if steps1 else None,
                  hist_tflops=round(achieved / 1e12, 3), mfu=mfu,
                  flop_share=share, **stats1, **rec)
    print(json.dumps(rec), flush=True)
    print(f"[bench] primary {ips1:.2f} iters/s train-AUC={auc1:.4f} "
          f"hist~{achieved / 1e12:.2f} TFLOP/s "
          f"(MFU~{f'{mfu:.1%}' if mfu is not None else 'n/a'} of "
          f"{devs[0].device_kind})", file=sys.stderr, flush=True)

    if not quick and not cpu:
        # strict leaf-wise growth (split_batch=1): round-over-round
        # comparable with BENCH_r02/r03 history + the AUC quality anchor
        try:
            ips0, auc0, _, _, st0 = _train_point(lgb, x, y,
                                                 num_leaves=PRIMARY_LEAVES,
                                                 chunk=25, n_chunks=2,
                                                 tag="1M/31leaf/strict",
                                                 ds=ds1, split_batch=1)
            _record_point("higgs1m_31leaf_strict", value=round(ips0, 3),
                          auc=round(float(auc0), 4), **st0)
        except Exception as e:
            _record_point("higgs1m_31leaf_strict",
                          error=f"{type(e).__name__}: {e}"[:200])


def child_extras() -> None:
    """The non-primary points, each persisted as it lands.  Runs in its
    own child AFTER the primary is safe; a wedge/timeout here costs only
    the points not yet reached.  On the CPU fallback the shapes shrink
    10x and vs_baseline is omitted (shape mismatch), but the points
    still run (VERDICT r4 weak #1: round 4's structural changes had no
    empirical record anywhere) — with tpu_learner=masked pinned, since
    CPU auto-selects the partitioned learner which never batches."""
    cpu = os.environ.get("_BENCH_CPU") == "1"
    devs = _claim_device(cpu=cpu)
    import lightgbm_tpu as lgb

    n = N_ROWS if not cpu else N_ROWS // 10
    learner = "masked" if cpu else None
    x, y = make_higgs_like(n, N_FEAT)

    # the baseline's own 255-leaf tree shape (VERDICT r2 task 3a; the
    # vs_baseline that matters most — 3.843 iters/s IS this shape).
    # auto split_batch=16 -> M=3K=48 of the MXU's 128 rows; the achieved
    # histogram FLOP/s double as the MFU evidence for VERDICT r3 task 3.
    # steps_per_tree is the while-loop super-step count: ~16-20 for a
    # balanced 255-leaf tree at K=16 (vs 254 for the old static loop).
    ds2 = ips2 = None
    try:
        ips2, auc2, ds2, st2, cst2 = _train_point(
            lgb, x, y, num_leaves=255, chunk=4,
            n_chunks=2, tag=f"{n//1000}k/255leaf", learner=learner)
        from lightgbm_tpu.obs.attrib import device_peaks
        from lightgbm_tpu.obs.flops import (FlopLedger,
                                            train_hist_flops_per_iter)
        flops = train_hist_flops_per_iter(
            n, N_FEAT, PRIMARY_MAX_BIN, 255) * ips2
        peak, _bw = device_peaks(devs)
        share255 = FlopLedger.for_training(
            n, N_FEAT, PRIMARY_MAX_BIN, split_batch=16).flop_share(
            st2[-1] if st2 else 254)
        _record_point("higgs1m_255leaf", value=round(ips2, 3),
                      auc=round(float(auc2), 4), cpu=cpu,
                      steps_per_tree=st2[-1] if st2 else None,
                      vs_baseline=(round(ips2 / BASELINE_IPS, 3)
                                   if not cpu else None),
                      hist_tflops=round(flops / 1e12, 2),
                      mfu=round(flops / peak, 4) if peak else None,
                      flop_share=share255,
                      **cst2)
    except Exception as e:
        _record_point("higgs1m_255leaf",
                      error=f"{type(e).__name__}: {e}"[:200])

    # Epsilon-shaped wide point (VERDICT r3 task 6: 400k x 2000 dense).
    # Runs BEFORE the slow strict point below so a timeout starves the
    # least important measurement, not this one.
    try:
        ne, fe = (400_000, 2000) if not cpu else (40_000, 500)
        xe, ye = make_epsilon_like(ne, fe)
        ipse, auce, _, _, cste = _train_point(
            lgb, xe, ye, num_leaves=PRIMARY_LEAVES, chunk=4, n_chunks=2,
            tag=f"{ne//1000}k/{fe}f/31leaf", split_batch=8,
            learner=learner)
        _record_point("epsilon400k_2000f", value=round(ipse, 3), cpu=cpu,
                      shape=f"{ne}x{fe}", auc=round(float(auce), 4),
                      **cste)
        del xe, ye
    except Exception as e:
        _record_point("epsilon400k_2000f",
                      error=f"{type(e).__name__}: {e}"[:200])

    # strict (split_batch=1) 255-leaf on the same data: the measured
    # K=16-vs-1 super-step ratio — the empirical record for round 4's
    # two structural claims (while-loop growers + auto split_batch).
    # ~254 passes/tree makes this the slowest point; it runs last.
    if ds2 is not None:
        try:
            ips2s, _, _, st2s, cst2s = _train_point(
                lgb, x, y, num_leaves=255, chunk=2, n_chunks=1,
                tag=f"{n//1000}k/255leaf/strict", ds=ds2, split_batch=1,
                learner=learner)
            _record_point("higgs1m_255leaf_strict", value=round(ips2s, 3),
                          cpu=cpu,
                          steps_per_tree=st2s[-1] if st2s else None,
                          batched_over_strict=round(
                              ips2 / max(ips2s, 1e-9), 2), **cst2s)
        except Exception as e:
            _record_point("higgs1m_255leaf_strict",
                          error=f"{type(e).__name__}: {e}"[:200])

    # owner-shard dp histogram state (ISSUE 1 / VERDICT #63): per-shard
    # histogram bytes per leaf after the psum_scatter, vs the full-psum
    # replication — the memory shape tools/bench_hist.py --sharded times
    try:
        from lightgbm_tpu.parallel.mesh import owner_shard_plan
        pts = {}
        for wname, f in (("higgs28", 28), ("bosch968", 968),
                         ("allstate4228", 4228)):
            for s in (8, 16):
                plan = owner_shard_plan(np.arange(f), s)
                pts[f"{wname}_x{s}"] = plan.hist_bytes(1, 64)
            pts[f"{wname}_full"] = f * 64 * 3 * 4
        _record_point("dp_owner_shard_hist_bytes_per_leaf", cpu=cpu, **pts)
    except Exception as e:
        _record_point("dp_owner_shard_hist_bytes_per_leaf",
                      error=f"{type(e).__name__}: {e}"[:200])

    # serving microbench (ISSUE 4 / tools/bench_serve.py): in-process
    # serve stack (micro-batcher + bucketed predictor engine) driven by
    # concurrent clients — rows/s and client-observed p50/p99 latency.
    # Keyed-payload point: the keys fold into extras as serve_rows_per_s
    # / serve_p99_ms etc.
    try:
        sys.path.insert(0, os.path.join(_DIR, "tools"))
        import bench_serve
        sp = bench_serve.run_bench(
            duration_s=2.0 if cpu else 4.0, clients=4,
            rows_per_request=64,
            n_train=5_000 if cpu else 50_000)
        _record_point("serve", cpu=cpu,
                      **{k: v for k, v in sp.items()
                         if k in ("rows_per_s", "p50_ms", "p99_ms",
                                  "requests", "batch_occupancy_mean",
                                  "compile_bound")})
    except Exception as e:
        _record_point("serve", error=f"{type(e).__name__}: {e}"[:200])

    # fused device-resident serve path (ISSUE 10): the same drive with
    # serve_device_binning — one jitted bin/traverse/accumulate program,
    # one sync per batch.  Folds into extras as serve_device_rows_per_s
    # / serve_device_p99_ms, gated by tools/bench_diff.py next to the
    # host-accumulation numbers above
    try:
        import bench_serve
        spd = bench_serve.run_bench(
            duration_s=2.0 if cpu else 4.0, clients=4,
            rows_per_request=64,
            n_train=5_000 if cpu else 50_000, device_binning=True)
        _record_point("serve_device", cpu=cpu,
                      **{k: v for k, v in spd.items()
                         if k in ("rows_per_s", "p50_ms", "p99_ms",
                                  "requests", "batch_occupancy_mean",
                                  "compile_bound", "fused_batches",
                                  "host_fallback_batches",
                                  "table_bytes")})
    except Exception as e:
        _record_point("serve_device",
                      error=f"{type(e).__name__}: {e}"[:200])

    # continual-pipeline microbench (ISSUE 11, pipeline/continual.py):
    # two fault-free generations of the train->publish->gate->promote
    # loop against a live in-process serving registry under client
    # traffic.  The gated numbers are chunk-arrival-to-serving lag
    # (continual_freshness_lag_s) and mean wall time per generation
    # (continual_gen_s) — the freshness guarantee as a perf metric
    try:
        import soak_serve
        cr = soak_serve.run_continual_soak(
            duration_s=2.0 if cpu else 4.0, clients=2, generations=2,
            gate_failure=False)
        _record_point(
            "continual", cpu=cpu,
            freshness_lag_s=cr.get("freshness_lag_s"),
            gen_s=cr.get("gen_s"),
            published=(cr.get("freshness") or {}).get(
                "generations_published"),
            violations=len(cr.get("violations") or []))
    except Exception as e:
        _record_point("continual", error=f"{type(e).__name__}: {e}"[:200])

    # quantized-training histogram sweep (ISSUE 13, ops/quantize.py):
    # f32 vs int8/int16 packed accumulands through the SHIPPED
    # contraction across split_batch slot widths K in {16,32,64}
    # (tools/bench_hist.run_quant_bench — ms/pass AND ms/leaf-slot per
    # width, plus the autotuner's chosen (K, block_rows) as
    # provenance), folded into extras as hist_quant_*.  Gated keys
    # (tools/perf_budget.txt): hist_hbm_bytes_per_iter — the static
    # ledger's histogram HBM bytes for ONE canonical 255-leaf K=16
    # iteration under quant_bits=8 (lower-better, the ledger-proven
    # cut of ISSUE 13) — and hist_ms_per_pass / hist_ms_per_leaf_wide
    # — the measured shipped-shape pass cost and the best wide-width
    # per-leaf cost (the MXU-widening win of ISSUE 15)
    try:
        sys.path.insert(0, os.path.join(_DIR, "tools"))
        import bench_hist
        qp = bench_hist.run_quant_bench(
            n_rows=50_000 if cpu else 500_000, reps=3 if cpu else 10)
        _record_point("hist_quant", cpu=cpu, **qp)
        from lightgbm_tpu.obs.flops import FlopLedger
        steps = -(-254 // 16)        # canonical 255-leaf K=16 iteration
        led_q8 = FlopLedger.for_training(
            n, N_FEAT, PRIMARY_MAX_BIN, split_batch=16,
            vals_itemsize=1, quant=True)
        led_f32 = FlopLedger.for_training(
            n, N_FEAT, PRIMARY_MAX_BIN, split_batch=16)
        site_q8 = {s.site: s for s in led_q8.sites()}
        site_f32 = {s.site: s for s in led_f32.sites()}
        wide = [v for k, v in qp.items()
                if k.startswith("qoff_k") and k.endswith("_ms_per_leaf")
                and not k.startswith("qoff_k16")]
        _record_point(
            "hist", cpu=cpu,
            hbm_bytes_per_iter=site_q8["hist"].hbm_bytes * steps
            + site_q8["hist_root"].hbm_bytes,
            hbm_bytes_per_iter_f32=site_f32["hist"].hbm_bytes * steps
            + site_f32["hist_root"].hbm_bytes,
            ms_per_pass=qp.get("qoff_k16_ms_per_pass"),
            ms_per_leaf_k16=qp.get("qoff_k16_ms_per_leaf"),
            ms_per_leaf_wide=min(wide) if wide else None,
            tuned_k=qp.get("tuned_k"),
            tuned_block_rows=qp.get("tuned_block_rows"))
    except Exception as e:
        _record_point("hist_quant", error=f"{type(e).__name__}: {e}"[:200])

    # super-epoch sweep (ISSUE 16, tools/bench_fused.sweep): k in
    # {1, 8, 32} x {valid, novalid} end-to-end lgb.train runs — k=1 is
    # the per-iteration baseline — counting jax.device_get syncs during
    # the timed run.  Headline keys fold as superepoch_iters_per_s /
    # superepoch_sync_count_per_iter (the k=32 + one-valid + ES
    # acceptance shape, pinned in tools/perf_budget.txt: the sync count
    # is structural, 1/k, near-zero tolerance)
    try:
        sys.path.insert(0, os.path.join(_DIR, "tools"))
        import bench_fused
        # CPU shape is deliberately small: at 20k rows x 31 leaves one
        # 32-round train is ~70 s on CPU, and the sweep runs each
        # (k, valid) cell twice (warmup + timed)
        sp = bench_fused.sweep(
            n_rows=10_000 if cpu else 400_000,
            ks=(1, 32) if cpu else (1, 8, 32),
            rounds=32 if cpu else None)
        _record_point("superepoch", cpu=cpu, **sp)
    except Exception as e:
        _record_point("superepoch", error=f"{type(e).__name__}: {e}"[:200])

    # fleet sweep (ISSUE 19, tools/bench_fleet.run_bench): warm
    # aggregate iters/s of ONE vmapped N-member fleet_train vs N warm
    # sequential solo runs, N in {1, 4, 8, 16}.  The shape is the
    # fleet's home regime — a small-data hyperparameter sweep, where
    # per-epoch dispatch dominates and batching members into one
    # program wins.  Headline keys fold as fleet_agg_iters_per_s (the
    # N=8 vmapped aggregate, pinned in tools/perf_budget.txt) and
    # fleet_speedup_x8 (the >=2x acceptance ratio vs 8 solos)
    try:
        sys.path.insert(0, os.path.join(_DIR, "tools"))
        import bench_fleet
        fp = bench_fleet.run_bench(
            n_rows=500, rounds=32,
            sizes=(1, 4, 8) if cpu else (1, 4, 8, 16))
        _record_point("fleet", cpu=cpu, **fp)
    except Exception as e:
        _record_point("fleet", error=f"{type(e).__name__}: {e}"[:200])

    # out-of-core ingest microbench (ISSUE 17, lightgbm_tpu/ingest.py):
    # streaming rows/s through the chunked reader + quantile sketcher,
    # peak RSS of a SUBPROCESS ingesting a many-chunk file (the
    # bounded-memory claim: one chunk in flight regardless of chunk
    # count — gated lower-better in tools/perf_budget.txt), and the
    # serialized-sketch allgather wire bytes from parallel/dist_data.py
    # (what crosses the fleet instead of raw sample rows).  Keyed
    # points: fold as ingest_rows_per_s / ingest_peak_rss_mb /
    # binning_wire_bytes
    try:
        import tempfile
        n_i, f_i = (40_000, 8) if cpu else (200_000, 8)
        tmpd = tempfile.mkdtemp(prefix="bench_ingest_")
        src = os.path.join(tmpd, "train.csv")
        rng = np.random.RandomState(11)
        xi = np.round(rng.randn(n_i, f_i), 3)
        yi = (xi[:, 0] > 0).astype(np.float64)
        np.savetxt(src, np.column_stack([yi, xi]), fmt="%.3f",
                   delimiter=",")
        child = (
            "import sys,json,time,resource;"
            f"sys.path.insert(0,{_DIR!r});"
            "import jax; jax.config.update('jax_platforms','cpu');"
            "import lightgbm_tpu as lgb;"
            f"p={{'verbosity':-1,'ingest_chunk_rows':{max(n_i // 64, 1)}}};"
            "t0=time.time();"
            f"ds=lgb.ingest_dataset({src!r},p,"
            f"spool_dir={os.path.join(tmpd, 'spool')!r});"
            "dt=time.time()-t0;"
            "print(json.dumps({"
            "'rows_per_s':ds.ingest_report['num_rows']/max(dt,1e-9),"
            "'peak_rss_mb':resource.getrusage("
            "resource.RUSAGE_SELF).ru_maxrss/1024.0}))")
        out = subprocess.run([sys.executable, "-c", child],
                             capture_output=True, text=True, timeout=600)
        ip = json.loads(out.stdout.strip().splitlines()[-1])
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.parallel import dist_data
        cfg_i = Config({"max_bin": PRIMARY_MAX_BIN, "verbosity": -1})
        dist_data.reset_wire_bytes()
        dist_data.distributed_bin_mappers(
            xi[:20_000], cfg_i, process_index=0, process_count=1,
            allgather=lambda b: [b])
        _record_point("ingest", cpu=cpu,
                      rows_per_s=round(ip["rows_per_s"], 1),
                      peak_rss_mb=round(ip["peak_rss_mb"], 1),
                      chunk_rows=max(n_i // 64, 1))
        _record_point("binning", cpu=cpu,
                      wire_bytes=dist_data.wire_bytes_sent())
    except Exception as e:
        _record_point("ingest", error=f"{type(e).__name__}: {e}"[:200])

    # integrity-layer overhead (ISSUE 20, lightgbm_tpu/integrity.py):
    # checked (integrity_check_freq=16: shadow re-execution every 16th
    # iteration + traced invariants riding the consolidated fetch) vs
    # unchecked iters/s on the per-iteration masked path, same binned
    # data.  Folds into extras as integrity_overhead_pct — pinned
    # lower-better in tools/perf_budget.txt: the "pay only on check
    # iterations" contract, measured
    try:
        # CPU fallback shrinks harder than the other points: the
        # masked one-program grower this measures runs ~8 s/iter at
        # the 20k/255-bin shape on a CPU host, and the point needs
        # ~80 iterations (warm-up past the first shadow compile +
        # 32 timed at each freq)
        n_g = 5_000 if cpu else 200_000
        xg, yg = make_higgs_like(n_g, N_FEAT, seed=5)
        pg = {"objective": "binary", "num_leaves": 31,
              "max_bin": 63 if cpu else PRIMARY_MAX_BIN,
              "min_data_in_leaf": 20,
              "verbosity": -1, "tpu_learner": "masked"}
        dsg = lgb.Dataset(xg, label=yg, params=pg)
        dsg.construct()

        def _ips_at(freq):
            bst = lgb.Booster(params=dict(pg, integrity_check_freq=freq),
                              train_set=dsg)
            m = bst._model
            for _ in range(max(freq, 1) + 1):   # warm: compile primary
                bst.update()                    # AND the shadow's first
            np.asarray(m.score)                 # check iteration
            t0 = time.time()
            n0 = m.iter_
            for _ in range(32):
                bst.update()
            np.asarray(m.score)
            return (m.iter_ - n0) / max(time.time() - t0, 1e-9)

        ips_off = _ips_at(0)
        ips_on = _ips_at(16)
        overhead = max(0.0, (ips_off / max(ips_on, 1e-9) - 1.0) * 100.0)
        _record_point("integrity", cpu=cpu, check_freq=16,
                      unchecked_ips=round(ips_off, 3),
                      checked_ips=round(ips_on, 3),
                      overhead_pct=round(overhead, 1))
    except Exception as e:
        _record_point("integrity", error=f"{type(e).__name__}: {e}"[:200])

    # comm wire bytes per boosting iteration (obs/comm.py static model,
    # same math the telemetry counters use at train time): the in-flight
    # number arXiv:1706.08359 instruments to validate scaling — one
    # reduce-scattered hist pass per split, (leaves-1) splits/tree
    try:
        from lightgbm_tpu.obs.comm import dp_hist_bytes_per_iter
        from lightgbm_tpu.parallel.mesh import owner_shard_plan
        pts = {}
        for wname, f in (("higgs28", 28), ("bosch968", 968),
                         ("allstate4228", 4228)):
            for s in (8, 16):
                plan = owner_shard_plan(np.arange(f), s)
                pts[f"{wname}_x{s}"] = dp_hist_bytes_per_iter(
                    s, plan.chunk, PRIMARY_PADDED_BIN,
                    n_steps=PRIMARY_LEAVES - 1)
        _record_point("comm_bytes_per_iter", cpu=cpu,
                      leaves=PRIMARY_LEAVES, **pts)
    except Exception as e:
        _record_point("comm_bytes_per_iter",
                      error=f"{type(e).__name__}: {e}"[:200])

    if cpu:
        return                       # 10M-row point is TPU-only
    # 10M-row scaling point (VERDICT r2 task 3b)
    try:
        x10 = np.concatenate([x] * 10, axis=0)
        rng = np.random.RandomState(7)
        for i in range(10):     # chunked f32 noise: no 2 GB f64 spike
            sl = slice(i * N_ROWS, (i + 1) * N_ROWS)
            x10[sl] += (rng.standard_normal(
                (N_ROWS, N_FEAT)).astype(np.float32) * 1e-3)
        y10 = np.concatenate([y] * 10)
        ips3, auc3, _, _, cst3 = _train_point(lgb, x10, y10, num_leaves=31,
                                              chunk=8, n_chunks=2,
                                              tag="10M/31leaf/sb8",
                                              split_batch=8)
        _record_point("higgs10m", value=round(ips3, 3),
                      auc=round(float(auc3), 4), **cst3)
    except Exception as e:
        _record_point("higgs10m", error=f"{type(e).__name__}: {e}"[:200])


def _metric_line(stdout: str):
    """Last JSON metric line in a (possibly partial) stdout, or None."""
    found = None
    for line in (stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{") and METRIC in line:
            found = line
    return found


def run_child(mode: str, timeout: int, extra_env=None, orphan=False):
    """Run one child; returns (stdout_text, err_summary).

    orphan=True (the probe): on timeout the child is LEFT RUNNING, not
    killed — SIGKILLing a client mid-TPU-claim is exactly what wedges
    the axon relay ('grant unclaimed past timeout'); an orphan that
    eventually gets the grant exits cleanly a moment later and releases
    it, merely delaying (not breaking) the next claimer."""
    env = dict(os.environ, _BENCH_CHILD=mode)
    env.update(extra_env or {})
    out_f = open(POINTS_FILE + f".{mode}.out", "w+")
    err_f = open(POINTS_FILE + f".{mode}.err", "w+")
    p = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                         env=env, stdout=out_f, stderr=err_f, text=True)
    try:
        p.wait(timeout=timeout)
        timed_out = False
    except subprocess.TimeoutExpired:
        timed_out = True
        if not orphan:
            p.kill()
            p.wait()

    def _read(f):
        f.flush()
        f.seek(0)
        return f.read()
    out, err_txt = _read(out_f), _read(err_f)
    out_f.close()
    err_f.close()
    sys.stderr.write(err_txt[-4000:])
    if timed_out:
        return out, f"timeout after {timeout}s" + \
            (" (left running, not killed mid-claim)" if orphan else "")
    err = None if p.returncode == 0 else f"rc={p.returncode}"
    return out, err


def _read_points(path=None):
    pts = []
    try:
        with open(path or POINTS_FILE) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        pts.append(json.loads(line))
                    except ValueError:
                        pass
    except OSError:
        pass
    return pts


def main():
    mode = os.environ.get("_BENCH_CHILD")
    if mode:
        {"probe": child_probe, "primary": child_primary,
         "extras": child_extras}[mode]()
        return

    # fresh points file per run; the old one is superseded
    try:
        os.replace(POINTS_FILE, POINTS_FILE + ".prev")
    except OSError:
        pass
    _record_point("run_start", t=time.strftime("%Y-%m-%dT%H:%M:%S"))

    errors = []
    # --- 1. probe the TPU claim (wedge detection, see module docstring) --
    # claim_reason classifies the TPU-loss story machine-readably
    # (ISSUE 14 satellite — BENCH_r03-r05 lost the claim with only
    # free-text diagnoses): "wedge" = claim hung past the probe budget,
    # "no_claim" = fast refusal (Unavailable/backend error), "preempt"
    # = claim granted but the measurement child lost it mid-run
    tpu_ok = False
    claim_reason = None
    for i in range(2):
        t0 = time.time()
        out, err = run_child("probe", timeout=PROBE_TIMEOUT, orphan=True)
        if "PROBE_OK" in (out or ""):
            tpu_ok = True
            claim_reason = None
            break
        claim_reason = "wedge" if (err and "timeout" in err) \
            else "no_claim"
        diag = ("wedged: claim hung (timeout-killed client holds the "
                "relay grant)" if err and "timeout" in err
                else f"claim failed fast ({err}) after "
                     f"{time.time() - t0:.0f}s")
        errors.append(f"probe{i + 1}: {diag}")
        print(f"[bench] TPU probe {i + 1} failed: {diag}", file=sys.stderr,
              flush=True)
        if err and "timeout" in err:
            break                    # a wedge does not clear in 30 s
        time.sleep(30)               # fast Unavailable may be transient
    _record_point("probe", tpu_ok=tpu_ok, reason=claim_reason,
                  errors=errors[:])

    # --- 2. primary point (hard-capped) ---------------------------------
    line = None
    if tpu_ok:
        out, err = run_child("primary", timeout=PRIMARY_TIMEOUT)
        line = _metric_line(out)
        if not line:
            errors.append(f"primary: {err or 'no JSON line'}")
            print("[bench] primary failed; quick retry...", file=sys.stderr,
                  flush=True)
            out, err = run_child("primary", timeout=QUICK_TIMEOUT,
                                 extra_env={"_BENCH_QUICK": "1"})
            line = _metric_line(out)
            if not line:
                errors.append(f"primary-quick: {err or 'no JSON line'}")
                # the probe was granted but the measurement lost the
                # device mid-run — the preemption story, distinct from
                # never having claimed at all
                claim_reason = "preempt"

    # --- 2b. prefer a TPU point captured mid-round over any fallback ----
    # tools/tpu_watch.py waits out the tunnel wedge all round and runs
    # the same measurement children the moment a claim lands, appending
    # to CAPTURE_FILE.  A real-hardware number measured hours ago beats
    # a degraded CPU number measured now (VERDICT r4 task 1).
    captured = None
    points_src = POINTS_FILE
    if not line:
        # staleness guard: only trust captures from the last 12 h (one
        # round) — the watcher also truncates the file at round start,
        # but if no watcher ran this round an old point must not be
        # attributed to current code
        def _fresh(p):
            try:
                age = time.time() - time.mktime(
                    time.strptime(p["t"], "%Y-%m-%dT%H:%M:%S"))
                return age < 12 * 3600
            except (KeyError, ValueError):
                return False
        cap = [p for p in _read_points(CAPTURE_FILE)
               if p.get("point") == "primary" and not p.get("cpu")
               and "value" in p and _fresh(p)]
        if cap:
            captured = cap[-1]
            points_src = CAPTURE_FILE
            line = json.dumps({k: captured[k] for k in
                               ("metric", "value", "unit", "vs_baseline")
                               if k in captured})
            print(f"[bench] using mid-round TPU capture "
                  f"({captured.get('t', 'no timestamp')})",
                  file=sys.stderr, flush=True)

    # --- 2c. ONE elastic re-acquire attempt before degrading to CPU ----
    # (ISSUE 14 satellite): a preempted claim or fast refusal may have
    # cleared by now — one cheap re-probe + quick primary salvages the
    # hardware number.  A WEDGE is excluded: it does not clear on this
    # timescale (r03-r05), and tools/tpu_watch.py already owns the
    # wait-out-the-wedge strategy; re-probing would only burn the
    # probe budget twice.
    reacquired = None
    if not line and claim_reason in ("preempt", "no_claim"):
        print(f"[bench] elastic re-acquire after {claim_reason}...",
              file=sys.stderr, flush=True)
        out, err = run_child("probe", timeout=PROBE_TIMEOUT, orphan=True)
        if "PROBE_OK" in (out or ""):
            out, err = run_child("primary", timeout=QUICK_TIMEOUT,
                                 extra_env={"_BENCH_QUICK": "1"})
            line = _metric_line(out)
            reacquired = bool(line)
            if not line:
                errors.append(f"reacquire-primary: {err or 'no JSON line'}")
        else:
            reacquired = False
            errors.append("reacquire: no claim")
        _record_point("reacquire", ok=reacquired, reason=claim_reason)

    degraded = None
    cpu_fallback = False
    if not line:
        # last resort: reduced CPU run — an honest degraded number beats
        # none (and records the wedge diagnosis machine-readably)
        cpu_fallback = True
        out, err = run_child("primary", timeout=CPU_TIMEOUT,
                             extra_env={"_BENCH_CPU": "1",
                                        "_BENCH_QUICK": "1"})
        line = _metric_line(out)
        if line:
            degraded = ("degraded: accelerator unavailable, CPU fallback; "
                        + "; ".join(errors))
        else:
            errors.append(f"cpu-fallback: {err or 'no JSON line'}")

    # --- 3. extras in their own killable child --------------------------
    # TPU extras only when the TPU primary itself succeeded; on CPU
    # fallback run the reduced-shape extras anyway so structural changes
    # (super-step counts, batched-vs-strict ratio) always leave an
    # empirical record (VERDICT r4 weak #1)
    if line and tpu_ok and not degraded and not captured:
        run_child("extras", timeout=EXTRAS_TIMEOUT)
    elif line and cpu_fallback:
        run_child("extras", timeout=EXTRAS_TIMEOUT,
                  extra_env={"_BENCH_CPU": "1"})

    # --- 4. merge + emit exactly one line -------------------------------
    if not line:
        rec = {"metric": METRIC, "value": 0.0, "unit": "iters/s",
               "vs_baseline": 0.0, "error": "; ".join(errors)}
        if claim_reason:
            rec["claim"] = {"reason": claim_reason,
                            "reacquired": reacquired}
        _record_point("final", **rec)
        print(json.dumps(rec), flush=True)
        return
    rec = json.loads(line)
    if claim_reason:
        # the TPU-loss story rides the final record's provenance: WHY
        # this round's number is degraded/captured, machine-readably
        rec["claim"] = {"reason": claim_reason, "reacquired": reacquired}
    extra = {}
    for p in _read_points(points_src):
        name = p.get("point")
        if name in (None, "run_start", "probe", "final", "primary"):
            if name == "primary" and "auc" in p:
                extra["higgs1m_31leaf_sb8_auc"] = p["auc"]
                if p.get("steps_per_tree") is not None:
                    extra["higgs1m_31leaf_sb8_steps"] = p["steps_per_tree"]
                for k_src in ("compile_s", "trace_count", "hist_tflops",
                              "mfu", "flop_share"):
                    if p.get(k_src) is not None:
                        extra[f"higgs1m_31leaf_sb8_{k_src}"] = p[k_src]
                if p.get("prov"):
                    rec["prov"] = p["prov"]
            continue
        if "value" not in p and "error" not in p:
            # keyed payload points (hist-bytes shapes, comm_bytes_per_iter
            # from the obs/comm static model): fold every data key
            for k_src, v in p.items():
                if k_src not in ("point", "t", "cpu", "prov"):
                    extra[f"{name}_{k_src}"] = v
            continue
        if "value" in p:
            extra[name + "_iters_per_sec"] = p["value"]
            for k_src, k_dst in (("auc", "_auc"),
                                 ("vs_baseline", "_vs_baseline"),
                                 ("steps_per_tree", "_steps"),
                                 ("batched_over_strict", "_speedup"),
                                 ("hist_tflops", "_hist_tflops"),
                                 ("mfu", "_mfu"),
                                 ("flop_share", "_flop_share"),
                                 # compile wall metrics (ROADMAP item 4):
                                 # first-class in every train point
                                 ("compile_s", "_compile_s"),
                                 ("trace_count", "_trace_count"),
                                 ("compile_cache_hits", "_cache_hits"),
                                 # reduced-shape CPU points must stay
                                 # distinguishable from full-size TPU
                                 # ones in the merged record
                                 ("cpu", "_cpu"),
                                 ("shape", "_shape")):
                if p.get(k_src) is not None:
                    extra[name + k_dst] = p[k_src]
        elif "error" in p:
            extra[name + "_error"] = p["error"]
    if extra:
        rec["extra"] = extra
    if captured:
        rec["note"] = ("primary + extras captured opportunistically "
                       "mid-round by tools/tpu_watch.py at "
                       f"{captured.get('t', '?')}; tunnel wedged at "
                       "bench time")
    if degraded:
        rec["error"] = degraded
    _record_point("final", **rec)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
