"""Sparse binned storage: the TPU-native analog of the reference's
delta-encoded sparse bins (sparse_bin.hpp:73 SparseBin, row-wise
multi_val_sparse_bin.hpp MultiValSparseBin).

The dense binned matrix is ``[N, G]`` bytes of HBM; for wide-sparse data
(Allstate-class: 13.2M x 4228 dummy-encoded columns, docs/Experiments.rst:32)
that is 51.9 GB — infeasible on a 16 GB chip unless EFB compresses it.  The
reference's answer is per-feature delta-encoded (row, bin) streams; the
TPU-native answer here is a **padded k-hot row layout**:

    flat[n, k] = f * stride + b        for the k-th stored entry of row n
    flat[n, k] = -1                    padding

where an entry is stored only when its bin differs from the feature's
*default bin* (the bin that the absent value 0.0 maps to — the reference's
most_freq_bin discipline, bin.h).  K = max stored entries per row, so the
array is ``[N, K] int32``: static shapes for XLA, rows shard over a mesh
axis exactly like the dense matrix, and memory is ``4K`` bytes/row instead
of ``G`` — for Allstate-shaped data K ~= the number of original categorical
columns (~35), i.e. ~1.9 GB.

Histogram construction cannot ride the one-hot MXU contraction (its FLOP
cost is slot-count x output-size, independent of sparsity), so the sparse
path uses the formulation whose work IS O(nnz): a per-row-block
``segment_sum`` scatter-add keyed by ``flat`` (+ a slot offset for the
split_batch multi-histogram), followed by the reference's FixHistogram
subtraction (dataset.cpp:1292) to reconstruct the default bin from the
leaf totals.  Column access (row partitioning, traversal) is a K-wide
vectorized compare — O(N*K) VPU work, no gather.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


# row-block size for the segment_sum scan is chosen so a block carries
# ~ENTRY_BLOCK entries; bounds the [R*K, C] gathered-values buffer
ENTRY_BLOCK = 512 * 1024


@jax.tree_util.register_pytree_node_class
class SparseBinned:
    """Device-side padded k-hot binned matrix (pytree: jit-traceable).

    flat:        [N, K] int32, ``f * stride + b`` or -1 padding
    default_bin: [F] int32 — bin of the absent value, per used feature
    stride:      static bin-axis stride (>= every feature's num_bin)
    num_features: static F
    """

    def __init__(self, flat, default_bin, stride: int, num_features: int):
        self.flat = flat
        self.default_bin = default_bin
        self.stride = int(stride)
        self.num_features = int(num_features)

    @property
    def shape(self):
        """(N, F) — matches the dense binned matrix's shape contract."""
        return (self.flat.shape[0], self.num_features)

    @property
    def k(self) -> int:
        return self.flat.shape[1]

    def tree_flatten(self):
        return (self.flat, self.default_bin), (self.stride, self.num_features)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])

    def take_rows(self, idx) -> "SparseBinned":
        """Row gather (the child-histogram tier path's axis-0 take)."""
        return SparseBinned(jnp.take(self.flat, idx, axis=0),
                            self.default_bin, self.stride, self.num_features)


def column(sp: SparseBinned, feat) -> jax.Array:
    """Bin of feature ``feat`` (traced scalar) for every row — the sparse
    analog of ``jnp.take(binned, feat, axis=1)``."""
    lo = feat.astype(jnp.int32) * sp.stride if hasattr(feat, "astype") \
        else jnp.int32(feat) * sp.stride
    m = (sp.flat >= lo) & (sp.flat < lo + sp.stride)
    binv = jnp.sum(jnp.where(m, sp.flat - lo, 0), axis=1)
    return jnp.where(m.any(axis=1), binv, sp.default_bin[feat]) \
        .astype(jnp.int32)


def column_per_row(sp: SparseBinned, feat_r) -> jax.Array:
    """Per-row feature lookup: row n reads feature ``feat_r[n]`` — the
    sparse analog of ``take_along_axis(binned, feat_r[:, None], 1)``
    (batched-grower partitioning, tree traversal)."""
    lo = feat_r.astype(jnp.int32)[:, None] * sp.stride
    m = (sp.flat >= lo) & (sp.flat < lo + sp.stride)
    binv = jnp.sum(jnp.where(m, sp.flat - lo, 0), axis=1)
    return jnp.where(m.any(axis=1), binv, sp.default_bin[feat_r]) \
        .astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_bins", "num_slots"))
def histogram(sp: SparseBinned, vals: jax.Array, *, num_bins: int,
              slot: Optional[jax.Array] = None,
              num_slots: int = 1) -> jax.Array:
    """hist[f, b, c] over the sparse layout — same output contract as
    ops/histogram.compute_histogram: [F, num_bins, C] with C = cv*num_slots
    and channel index ``c * num_slots + s``.

    O(nnz) work: stored entries scatter-add per row-block; the default bin
    gets ``leaf_total - stored_mass`` per feature afterwards (FixHistogram,
    dataset.cpp:1292), which assigns every absent row in one subtraction.
    """
    n, k = sp.flat.shape
    f = sp.num_features
    cv = vals.shape[1]
    s = num_slots if slot is not None else 1
    nseg = s * f * sp.stride

    block_rows = max(8, min(n, ENTRY_BLOCK // max(k, 1)) // 8 * 8)
    pad = (-n) % block_rows
    flat_p, vals_p, slot_p = sp.flat, vals, slot
    if pad:
        flat_p = jnp.pad(flat_p, ((0, pad), (0, 0)), constant_values=-1)
        vals_p = jnp.pad(vals_p, ((0, pad), (0, 0)))
        if slot is not None:
            slot_p = jnp.pad(slot_p, (0, pad), constant_values=-1)
    nblocks = (n + pad) // block_rows

    xs = (flat_p.reshape(nblocks, block_rows, k),
          vals_p.reshape(nblocks, block_rows, cv))
    if slot is not None:
        xs = xs + (slot_p.reshape(nblocks, block_rows),)

    def body(acc, chunk):
        fl, vb = chunk[0], chunk[1]
        sid = fl.astype(jnp.int32)                       # [R, K]
        ok = sid >= 0
        if slot is not None:
            sb = chunk[2].astype(jnp.int32)              # [R]
            ok = ok & (sb >= 0)[:, None]
            sid = sid + jnp.maximum(sb, 0)[:, None] * (f * sp.stride)
        # invalid entries land in the overflow segment nseg (dropped)
        sid = jnp.where(ok, sid, nseg).reshape(-1)
        data = jnp.broadcast_to(vb[:, None, :], (block_rows, k, cv)) \
            .reshape(-1, cv)
        return acc + jax.ops.segment_sum(data, sid, num_segments=nseg + 1), \
            None

    acc0 = jnp.zeros((nseg + 1, cv), jnp.float32)
    acc, _ = lax.scan(body, acc0, xs)
    # [S, F, stride, cv] -> [F, stride, cv, S] -> [F, stride, cv*S]
    hist = acc[:nseg].reshape(s, f, sp.stride, cv).transpose(1, 2, 3, 0) \
        .reshape(f, sp.stride, cv * s)

    # FixHistogram: absent mass = per-slot totals - stored mass, added at
    # each feature's default bin.  Totals via an MXU contraction (onehot
    # fused into the dot) when slotted, a plain sum otherwise.
    if slot is not None:
        oh = (slot[:, None] == jnp.arange(num_slots, dtype=jnp.int32)) \
            .astype(jnp.float32)
        tot = lax.dot_general(vals, oh, (((0,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)  # [cv, S]
        tot = tot.reshape(cv * s)
    else:
        tot = vals.sum(axis=0)
    absent = tot[None, :] - hist.sum(axis=1)             # [F, cv*S]
    hist = hist.at[jnp.arange(f), sp.default_bin].add(absent)
    return hist[:, :num_bins, :]


@functools.partial(jax.jit, static_argnames=("steps",))
def traverse_tree_sparse(sp: SparseBinned, split_feature, threshold_bin,
                         default_left, left_child, right_child, na_bin,
                         is_cat_node, cat_rank, *, steps: int):
    """Leaf index per row over the sparse layout — predict_device
    traverse_tree_binned with the gather replaced by column_per_row."""
    n = sp.flat.shape[0]
    node = jnp.zeros(n, jnp.int32)

    def body(_, node):
        internal = node >= 0
        nid = jnp.maximum(node, 0)
        fcol = split_feature[nid]
        v = column_per_row(sp, fcol)
        nb = na_bin[fcol]
        is_na = (nb >= 0) & (v == nb) & (~is_cat_node[nid])
        rank = cat_rank[nid, v]
        go_left = jnp.where(is_na, default_left[nid],
                            rank <= threshold_bin[nid])
        nxt = jnp.where(go_left, left_child[nid], right_child[nid])
        return jnp.where(internal, nxt, node)

    node = lax.fori_loop(0, steps, body, node)
    return (~node).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("steps",))
def add_tree_score_sparse(score, sp: SparseBinned, split_feature,
                          threshold_bin, default_left, left_child,
                          right_child, na_bin, is_cat_node, cat_rank,
                          leaf_value, weight, *, steps: int):
    """score += weight * tree(sparse rows)."""
    leaf = traverse_tree_sparse(sp, split_feature, threshold_bin,
                                default_left, left_child, right_child,
                                na_bin, is_cat_node, cat_rank, steps=steps)
    return score + weight * jnp.take(leaf_value, leaf)


# ----------------------------------------------------------------------
# host-side construction
# ----------------------------------------------------------------------

class SparseBinnedHost:
    """Construction product kept on the Dataset (numpy; device copies are
    made by the model)."""

    def __init__(self, flat: np.ndarray, default_bin: np.ndarray,
                 stride: int, num_features: int):
        self.flat = flat                    # [N, K] int32
        self.default_bin = default_bin      # [F] int32
        self.stride = int(stride)
        self.num_features = int(num_features)

    @property
    def k(self) -> int:
        return self.flat.shape[1]

    def nbytes(self) -> int:
        return self.flat.nbytes

    def to_device(self) -> SparseBinned:
        return SparseBinned(jnp.asarray(self.flat),
                            jnp.asarray(self.default_bin),
                            self.stride, self.num_features)

    def subset_rows(self, idx: np.ndarray) -> "SparseBinnedHost":
        return SparseBinnedHost(self.flat[idx], self.default_bin,
                                self.stride, self.num_features)

    def densify(self) -> np.ndarray:
        """[N, F] dense bins — for paths that need the flat layout
        (add_features_from, partitioned learner).  O(N*F) memory: callers
        guard on size."""
        n, _ = self.flat.shape
        dtype = np.uint8 if self.stride <= 256 else np.uint16
        out = np.broadcast_to(self.default_bin.astype(dtype),
                              (n, self.num_features)).copy()
        rows, ks = np.nonzero(self.flat >= 0)
        fl = self.flat[rows, ks]
        out[rows, fl // self.stride] = (fl % self.stride).astype(dtype)
        return out


def collect_entries_csc(csc, mappers, used_features, stride: int):
    """collect_entries straight off a scipy CSC layout — O(nnz_col) per
    column, no N-length dense intermediate (the LGBM_DatasetCreateFromCSC
    discipline, c_api.h:281)."""
    rows_l, flat_l = [], []
    default_bin = np.zeros(len(used_features), np.int32)
    for j, f in enumerate(used_features):
        m = mappers[f]
        db = int(m.value_to_bin(np.zeros(1))[0])
        default_bin[j] = db
        lo, hi = csc.indptr[f], csc.indptr[f + 1]
        idx, dat = csc.indices[lo:hi], np.asarray(csc.data[lo:hi],
                                                  np.float64)
        b = m.value_to_bin(dat).astype(np.int32)
        keep = np.nonzero(b != db)[0]
        if len(keep):
            rows_l.append(idx[keep].astype(np.int64))
            flat_l.append(j * stride + b[keep])
    if rows_l:
        rows = np.concatenate(rows_l)
        flat = np.concatenate(flat_l)
    else:
        rows = np.zeros(0, np.int64)
        flat = np.zeros(0, np.int32)
    return rows, flat, default_bin


def build_khot(rows: np.ndarray, flat: np.ndarray, default_bin: np.ndarray,
               num_data: int, stride: int, num_features: int,
               counts: Optional[np.ndarray] = None) -> SparseBinnedHost:
    """Assemble the padded [N, K] layout from entry streams.  ``counts``
    (per-row entry counts) may be passed by a caller that already
    bincounted the stream for the layout decision."""
    if counts is None:
        counts = np.bincount(rows, minlength=num_data) if len(rows) \
            else np.zeros(num_data, np.int64)
    k = int(max(counts.max() if num_data else 0, 1))
    out = np.full((num_data, k), -1, np.int32)
    if len(rows):
        order = np.argsort(rows, kind="stable")
        r_s, f_s = rows[order], flat[order]
        offs = np.zeros(num_data + 1, np.int64)
        np.cumsum(counts, out=offs[1:])
        pos = np.arange(len(r_s)) - offs[r_s]
        out[r_s, pos] = f_s
    return SparseBinnedHost(out, default_bin, stride, num_features)
